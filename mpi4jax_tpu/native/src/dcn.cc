// DCN bridge implementation: see dcn.h.

#include "dcn.h"
#include "shm.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <cerrno>

#include <atomic>
#include <chrono>
#include <complex>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace t4j {

namespace {

// ---------------------------------------------------------------- logging

bool g_logging = false;
int g_rank = -1;
int g_size = -1;
bool g_initialized = false;
std::atomic<bool> g_shutting_down{false};

std::string call_id() {
  // 8-char random id, matching the reference's debug-log wire format
  // (mpi_xla_bridge.pyx:35-60).
  static thread_local std::mt19937_64 rng(
      std::random_device{}() ^
      std::hash<std::thread::id>{}(std::this_thread::get_id()));
  static const char alnum[] =
      "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789";
  std::string s(8, 'x');
  for (auto& c : s) c = alnum[rng() % (sizeof(alnum) - 1)];
  return s;
}

struct LogScope {
  std::string id;
  std::string op;
  std::chrono::steady_clock::time_point start;
  bool active;

  // Wire format follows the reference's bridge
  // (mpi_xla_bridge.pyx:47-52, 95-450): stdout, "r{rank} | {8-char id} |
  // MPI_<Op> <detail>" then "... | MPI_<Op> done with code 0 (1.23e-04s)".
  // Detail quantities are in bytes where this layer works on bytes (the
  // reference's Cython layer sees item counts; the FFI handlers here
  // only carry counts for reductions).
  LogScope(const char* op_, const std::string& detail) : op(op_),
                                                         active(g_logging) {
    if (!active) return;
    id = call_id();
    start = std::chrono::steady_clock::now();
    if (detail.empty())
      std::fprintf(stdout, "r%d | %s | %s\n", g_rank, id.c_str(), op.c_str());
    else
      std::fprintf(stdout, "r%d | %s | %s %s\n", g_rank, id.c_str(),
                   op.c_str(), detail.c_str());
    std::fflush(stdout);
  }
  ~LogScope() {
    if (!active) return;
    double secs = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - start)
                      .count();
    std::fprintf(stdout, "r%d | %s | %s done with code 0 (%.2es)\n", g_rank,
                 id.c_str(), op.c_str(), secs);
    std::fflush(stdout);
  }
};

[[noreturn]] void die(const char* what) {
  std::fprintf(stderr, "r%d | t4j DCN bridge: %s returned error; aborting job\n",
               g_rank, what);
  std::fflush(stderr);
  _exit(13);
}

// ------------------------------------------------------------- transport

// Frame payload: default-initialised allocation (new[] without parens
// does not zero) — a std::vector resize() value-initialises, which for
// large frames adds a full memset pass per hop.
struct Buf {
  std::unique_ptr<uint8_t[]> p;
  size_t n = 0;

  Buf() = default;
  explicit Buf(size_t nbytes)
      : p(nbytes ? new uint8_t[nbytes] : nullptr), n(nbytes) {}

  uint8_t* data() { return p.get(); }
  const uint8_t* data() const { return p.get(); }
  size_t size() const { return n; }
};

struct Frame {
  int src;
  int ctx;
  int tag;
  Buf data;
};

struct PeerSock {
  int fd = -1;
  std::mutex send_mu;
};

std::vector<PeerSock> g_peers;  // world_size entries; [g_rank] unused
std::vector<std::thread> g_readers;

// Same-host p2p fast path: frames to same-host peers ride SPSC shm
// byte pipes in the same wire format as the sockets (shm.h), drained
// by one reader thread per source into the same mailbox — matching
// semantics and per-pair ordering are exactly the TCP tier's.  ALL
// frames for a pair use one transport, so ordering can never split.
shm::PipeSeg* g_my_pipes = nullptr;
std::vector<shm::Pipe*> g_tx_pipes;   // world-indexed; nullptr = TCP
std::vector<std::thread> g_pipe_readers;

std::mutex g_mail_mu;
std::condition_variable g_mail_cv;
std::deque<Frame> g_mailbox;

constexpr uint32_t kMagic = 0x7446a001;

struct WireHeader {
  uint32_t magic;
  uint32_t src;
  uint32_t ctx;
  uint32_t tag;  // tag + 1 so ANY(-1) never travels
  uint64_t nbytes;
};

void write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w < 0 && errno == EINTR) continue;  // signal without SA_RESTART
    if (w <= 0) die("socket write");
    p += w;
    n -= static_cast<size_t>(w);
  }
}

bool read_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r == 0) return false;  // peer closed
    if (r < 0 && errno == EINTR) continue;  // signal without SA_RESTART
    if (r < 0) {
      // a local shutdown() wakes blocked readers with an error; that is
      // the clean teardown path, not a transport failure
      if (g_shutting_down.load()) return false;
      die("socket read");
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void reader_loop(int peer, int fd) {
  (void)peer;
  for (;;) {
    WireHeader h;
    if (!read_all(fd, &h, sizeof(h))) return;  // clean shutdown
    if (h.magic != kMagic) die("frame magic check");
    Frame f;
    f.src = static_cast<int>(h.src);
    f.ctx = static_cast<int>(h.ctx);
    f.tag = static_cast<int>(h.tag) - 1;
    f.data = Buf(h.nbytes);
    if (h.nbytes && !read_all(fd, f.data.data(), h.nbytes))
      die("frame body read");
    {
      std::lock_guard<std::mutex> lk(g_mail_mu);
      g_mailbox.push_back(std::move(f));
    }
    g_mail_cv.notify_all();
  }
}

int enc_ctx(int ctx, bool coll) { return ctx * 2 + (coll ? 1 : 0); }

void raw_send(int world_dest, int ctx, int tag, const void* buf,
              size_t nbytes) {
  if (world_dest == g_rank) {
    Frame f;
    f.src = g_rank;
    f.ctx = ctx;
    f.tag = tag;
    f.data = Buf(nbytes);
    if (nbytes) std::memcpy(f.data.data(), buf, nbytes);
    {
      std::lock_guard<std::mutex> lk(g_mail_mu);
      g_mailbox.push_back(std::move(f));
    }
    g_mail_cv.notify_all();
    return;
  }
  WireHeader h{kMagic, static_cast<uint32_t>(g_rank),
               static_cast<uint32_t>(ctx), static_cast<uint32_t>(tag + 1),
               static_cast<uint64_t>(nbytes)};
  if (world_dest < static_cast<int>(g_tx_pipes.size()) &&
      g_tx_pipes[world_dest]) {
    shm::Pipe* pipe = g_tx_pipes[world_dest];
    PeerSock& pp = g_peers[world_dest];
    std::lock_guard<std::mutex> lk(pp.send_mu);  // one producer per pipe
    if (!shm::pipe_write(pipe, &h, sizeof(h), g_shutting_down) ||
        (nbytes && !shm::pipe_write(pipe, buf, nbytes, g_shutting_down)))
      die("shm pipe write during shutdown");
    return;
  }
  PeerSock& p = g_peers[world_dest];
  if (p.fd < 0) die("send to unconnected peer");
  std::lock_guard<std::mutex> lk(p.send_mu);
  // header + body in one syscall (one TCP segment for small frames)
  iovec iov[2] = {{&h, sizeof(h)}, {const_cast<void*>(buf), nbytes}};
  ssize_t w;
  do {
    w = ::writev(p.fd, iov, nbytes ? 2 : 1);
  } while (w < 0 && errno == EINTR);  // signal without SA_RESTART
  if (w < 0) die("socket writev");
  size_t done = static_cast<size_t>(w);
  if (done < sizeof(h)) {
    write_all(p.fd, reinterpret_cast<const char*>(&h) + done,
              sizeof(h) - done);
    done = sizeof(h);
  }
  size_t body_done = done - sizeof(h);
  if (nbytes > body_done)
    write_all(p.fd, static_cast<const char*>(buf) + body_done,
              nbytes - body_done);
}

// Blocking matched receive from the mailbox (MPI matching semantics:
// FIFO per (source, ctx, tag) with wildcards).
Frame raw_recv(int world_source, int ctx, int tag) {
  std::unique_lock<std::mutex> lk(g_mail_mu);
  for (;;) {
    for (auto it = g_mailbox.begin(); it != g_mailbox.end(); ++it) {
      if (it->ctx != ctx) continue;
      if (world_source != kAnySource && it->src != world_source) continue;
      if (tag != kAnyTag && it->tag != tag) continue;
      Frame f = std::move(*it);
      g_mailbox.erase(it);
      return f;
    }
    g_mail_cv.wait(lk);
  }
}

// ------------------------------------------------------------- bootstrap

// Explicit SO_*BUF disables kernel receive auto-tuning and is clamped
// by net.core.{r,w}mem_max — on stock sysctls the clamp (~416KB) would
// be WORSE than auto-tuning. Probe once whether the kernel honours a
// large request; only then pin buffers (before connect/listen, so the
// TCP window scale is negotiated with the enlarged buffer in place).
constexpr int kWantBuf = 8 << 20;

bool buf_honoured(int optname) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return false;
  int bufsz = kWantBuf;
  ::setsockopt(fd, SOL_SOCKET, optname, &bufsz, sizeof(bufsz));
  int got = 0;
  socklen_t len = sizeof(got);
  ::getsockopt(fd, SOL_SOCKET, optname, &got, &len);
  ::close(fd);
  return got >= kWantBuf;  // kernel reports doubled value when honoured
}

void presize_buffers(int fd) {
  // each direction is governed by its own sysctl (wmem_max / rmem_max):
  // pin only the side the kernel honours, keep auto-tuning on the other
  static const bool snd_ok = buf_honoured(SO_SNDBUF);
  static const bool rcv_ok = buf_honoured(SO_RCVBUF);
  int bufsz = kWantBuf;
  if (snd_ok) ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
  if (rcv_ok) ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
}

void tune_socket(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

int tcp_listen(uint16_t* port_out) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) die("socket");
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  presize_buffers(fd);  // accepted sockets inherit
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(*port_out);
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
    die("bind");
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  *port_out = ntohs(addr.sin_port);
  if (::listen(fd, 128) < 0) die("listen");
  return fd;
}


int tcp_connect(const std::string& host, uint16_t port) {
  for (int attempt = 0; attempt < 600; ++attempt) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) die("socket");
    presize_buffers(fd);  // before connect: window scale negotiation
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
      die("inet_pton (coordinator must be an IPv4 literal)");
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      tune_socket(fd);
      return fd;
    }
    ::close(fd);
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  die("connect (timeout)");
}

struct PeerAddr {
  uint32_t ip;
  uint16_t port;
  uint16_t pad;
  uint64_t host_fp;  // same value <=> same host (shm-transport eligible)
};
static_assert(sizeof(PeerAddr) == 16, "PeerAddr wire layout");

std::vector<uint64_t> g_host_fps;  // world_size entries
std::string g_job;                 // unique job id (shm segment namespace)

uint64_t host_fingerprint() {
  // FNV-1a over the boot uuid (unique per host+boot), the hostname,
  // and the IPC + mount namespace identities: two ranks only count as
  // "same host" for the shm transport when they share the kernel AND
  // can actually see one another's /dev/shm — containers on one node
  // share boot_id but have distinct ns inodes.
  uint64_t h = 1469598103934665603ULL;
  auto mix = [&h](const char* s, size_t n) {
    for (size_t i = 0; i < n; ++i) {
      h ^= static_cast<uint8_t>(s[i]);
      h *= 1099511628211ULL;
    }
  };
  FILE* f = std::fopen("/proc/sys/kernel/random/boot_id", "r");
  if (f) {
    char buf[64] = {0};
    size_t n = std::fread(buf, 1, sizeof(buf) - 1, f);
    std::fclose(f);
    mix(buf, n);
  }
  char host[256] = {0};
  ::gethostname(host, sizeof(host) - 1);
  mix(host, std::strlen(host));
  for (const char* ns : {"/proc/self/ns/ipc", "/proc/self/ns/mnt"}) {
    char link[128] = {0};
    ssize_t n = ::readlink(ns, link, sizeof(link) - 1);
    if (n > 0) mix(link, static_cast<size_t>(n));
  }
  // T4J_NO_SHM rides the fingerprint: a rank with shm disabled must
  // never be classified same-host by ENABLED peers, or a divergent env
  // (hand-launched ranks) would split the transport — member 0 falling
  // straight to TCP while the others block in the agreement rounds.
  // Mixed-in (not zeroed) so an all-disabled job still agrees among
  // itself and falls back together through the ok=0 round.
  if (shm::disabled()) mix("t4j-no-shm", 10);
  return h ? h : 1;
}

void pipe_reader_loop(int peer, shm::Pipe* pipe) {
  (void)peer;
  for (;;) {
    WireHeader h;
    if (!shm::pipe_read(pipe, &h, sizeof(h), g_shutting_down))
      return;  // shutdown
    if (h.magic != kMagic) die("pipe frame magic check");
    Frame f;
    f.src = static_cast<int>(h.src);
    f.ctx = static_cast<int>(h.ctx);
    f.tag = static_cast<int>(h.tag) - 1;
    f.data = Buf(h.nbytes);
    if (h.nbytes &&
        !shm::pipe_read(pipe, f.data.data(), h.nbytes, g_shutting_down))
      return;
    {
      std::lock_guard<std::mutex> lk(g_mail_mu);
      g_mailbox.push_back(std::move(f));
    }
    g_mail_cv.notify_all();
  }
}

// Wire up the same-host pipe transport after the bootstrap table (and
// host fingerprints) exist.  Like the collective arena, the transport
// choice is AGREED over TCP so a partial failure can never split a
// pair across transports or aim a pipe at a reader-less segment:
//   round 1: every rank creates its own inbound segment, then the
//     group leader gathers "created" bytes and broadcasts the AND —
//     only after that does anyone attach (so a stale leaked segment
//     from a crashed prior run can never be attached: every name was
//     just unlinked+recreated by its owner);
//   round 2: attach results are gathered/broadcast the same way, and
//     pipes go live (g_tx_pipes published, readers started) only when
//     EVERY member succeeded — otherwise everyone drops to TCP.
// The agreement frames ride raw TCP (g_tx_pipes is still empty while
// the rounds run, so raw_send cannot route them through a pipe).
constexpr int kPipeTagCreated = (1 << 24) + 12;
constexpr int kPipeTagFinal = (1 << 24) + 13;

void setup_pipes() {
  g_tx_pipes.assign(g_size, nullptr);
  if (g_size < 2 || static_cast<int>(g_host_fps.size()) != g_size) return;
  std::vector<int> local;  // same-host world ranks, ascending (incl. me)
  for (int r = 0; r < g_size; ++r)
    if (g_host_fps[r] == g_host_fps[g_rank]) local.push_back(r);
  if (local.size() < 2) return;
  int leader = local[0];
  int wctx = enc_ctx(0, /*coll=*/true);  // world comm's collective channel

  auto agree = [&](uint8_t mine, int tag) -> uint8_t {
    uint8_t ok = mine;
    if (g_rank == leader) {
      for (int r : local) {
        if (r == leader) continue;
        Frame f = raw_recv(r, wctx, tag);
        ok &= f.data.size() == 1 ? f.data.data()[0] : 0;
      }
      for (int r : local) {
        if (r == leader) continue;
        raw_send(r, wctx, tag, &ok, 1);
      }
    } else {
      raw_send(leader, wctx, tag, &mine, 1);
      Frame f = raw_recv(leader, wctx, tag);
      ok = f.data.size() == 1 ? f.data.data()[0] : 0;
    }
    return ok;
  };

  auto slot_of = [&](int dest, int src) {
    // source slot within dest's inbound segment: index of src in the
    // ascending same-host list with dest itself excluded
    int slot = 0;
    for (int r : local) {
      if (r == dest) continue;
      if (r == src) return slot;
      ++slot;
    }
    return -1;
  };
  int n_sources = static_cast<int>(local.size()) - 1;

  g_my_pipes = shm::pipes_create(g_job.c_str(), g_rank, n_sources);
  if (!agree(g_my_pipes != nullptr, kPipeTagCreated)) {
    if (g_my_pipes) {
      shm::pipes_destroy(g_my_pipes);
      g_my_pipes = nullptr;
    }
    return;
  }

  std::vector<shm::Pipe*> tx(g_size, nullptr);
  bool all_ok = true;
  for (int r : local) {
    if (r == g_rank) continue;
    tx[r] = shm::pipe_attach(g_job.c_str(), r, slot_of(r, g_rank),
                             n_sources);
    if (!tx[r]) {
      all_ok = false;
      break;
    }
  }
  if (!agree(all_ok, kPipeTagFinal)) {
    for (auto*& t : tx)
      if (t) {
        shm::pipe_close(t);
        t = nullptr;
      }
    shm::pipes_destroy(g_my_pipes);
    g_my_pipes = nullptr;
    return;
  }
  // every peer holds its attached mapping now (the round-2 agreement
  // proves it): drop the segment NAME immediately, shrinking the crash
  // window that could leak /dev/shm to the few ms of setup itself
  shm::pipes_unlink(g_my_pipes);
  g_tx_pipes = std::move(tx);  // publish: raw_send may now route pipes
  for (int r : local) {
    if (r == g_rank) continue;
    g_pipe_readers.emplace_back(
        pipe_reader_loop, r,
        shm::pipe_of(g_my_pipes, slot_of(g_rank, r)));
  }
}

void bootstrap(const std::string& coord_host, uint16_t coord_port) {
  // Every rank opens a listener for the full-mesh phase.
  uint16_t my_port = 0;
  int listen_fd = tcp_listen(&my_port);

  std::vector<PeerAddr> table(g_size);

  uint64_t my_fp = host_fingerprint();

  if (g_rank == 0) {
    // phase 1: collect every rank's (ip, port, host_fp) on the
    // coordinator socket
    uint16_t cport = coord_port;
    int coord_fd = tcp_listen(&cport);
    table[0] = PeerAddr{htonl(INADDR_LOOPBACK), my_port, 0, my_fp};
    std::vector<int> fds(g_size, -1);
    for (int i = 1; i < g_size; ++i) {
      sockaddr_in peer{};
      socklen_t len = sizeof(peer);
      int fd = ::accept(coord_fd, reinterpret_cast<sockaddr*>(&peer), &len);
      if (fd < 0) die("accept (coordinator)");
      uint32_t rank_and_port[2];
      if (!read_all(fd, rank_and_port, sizeof(rank_and_port)))
        die("coordinator handshake");
      uint64_t fp = 0;
      if (!read_all(fd, &fp, sizeof(fp))) die("coordinator fp handshake");
      int r = static_cast<int>(rank_and_port[0]);
      if (r < 1 || r >= g_size) die("coordinator rank check");
      table[r] = PeerAddr{peer.sin_addr.s_addr,
                          static_cast<uint16_t>(rank_and_port[1]), 0, fp};
      fds[r] = fd;
    }
    // phase 2: broadcast the table
    for (int i = 1; i < g_size; ++i) {
      write_all(fds[i], table.data(), sizeof(PeerAddr) * g_size);
      ::close(fds[i]);
    }
    ::close(coord_fd);
  } else {
    int fd = tcp_connect(coord_host, coord_port);
    uint32_t rank_and_port[2] = {static_cast<uint32_t>(g_rank), my_port};
    write_all(fd, rank_and_port, sizeof(rank_and_port));
    write_all(fd, &my_fp, sizeof(my_fp));
    if (!read_all(fd, table.data(), sizeof(PeerAddr) * g_size))
      die("coordinator table read");
    ::close(fd);
  }

  g_host_fps.resize(g_size);
  for (int i = 0; i < g_size; ++i) g_host_fps[i] = table[i].host_fp;

  // phase 3: full mesh -- rank i accepts from ranks > i, connects to < i.
  g_peers = std::vector<PeerSock>(g_size);
  for (int lower = 0; lower < g_rank; ++lower) {
    char ip[INET_ADDRSTRLEN];
    in_addr a{table[lower].ip};
    ::inet_ntop(AF_INET, &a, ip, sizeof(ip));
    std::string host = (lower == 0) ? coord_host : std::string(ip);
    int fd = tcp_connect(host, table[lower].port);
    uint32_t me = static_cast<uint32_t>(g_rank);
    write_all(fd, &me, sizeof(me));
    g_peers[lower].fd = fd;
  }
  for (int higher = g_rank + 1; higher < g_size; ++higher) {
    sockaddr_in peer{};
    socklen_t len = sizeof(peer);
    int fd = ::accept(listen_fd, reinterpret_cast<sockaddr*>(&peer), &len);
    if (fd < 0) die("accept (mesh)");
    tune_socket(fd);
    uint32_t who = 0;
    if (!read_all(fd, &who, sizeof(who))) die("mesh handshake");
    if (static_cast<int>(who) <= g_rank || static_cast<int>(who) >= g_size)
      die("mesh handshake rank check");
    g_peers[who].fd = fd;
  }
  ::close(listen_fd);

  for (int p = 0; p < g_size; ++p) {
    if (p == g_rank || g_peers[p].fd < 0) continue;
    g_readers.emplace_back(reader_loop, p, g_peers[p].fd);
  }
  setup_pipes();
}

// --------------------------------------------------------- communicators

struct Comm {
  std::vector<int> ranks;  // world ranks, ascending caller order
  int ctx;
  int my_index;  // index of g_rank in ranks, or -1
  // same-host shm collective arena (lazy; nullptr = use TCP algorithms)
  shm::Arena* arena = nullptr;
  bool arena_checked = false;
};

std::mutex g_comm_mu;
// deque: push_back never invalidates references to existing elements,
// so in-flight collectives can hold Comm& across concurrent comm_create
std::deque<Comm> g_comms;

// Collective traffic uses the upper tag space so it can never collide
// with user p2p tags (which are >= 0 and modest).
constexpr int kCollTagBase = 1 << 24;

Comm& get_comm(int handle) {
  std::lock_guard<std::mutex> lk(g_comm_mu);
  if (handle < 0 || handle >= static_cast<int>(g_comms.size()))
    die("invalid communicator handle");
  return g_comms[handle];
}

// Arena negotiation runs over the TCP collective channel with reserved
// tags, so it can never collide with user traffic or collectives.
constexpr int kArenaTagCreated = kCollTagBase + 9;
constexpr int kArenaTagAttach = kCollTagBase + 10;
constexpr int kArenaTagFinal = kCollTagBase + 11;

void csend(Comm& c, int dest_idx, int tag, const void* buf, size_t n,
           bool coll);
Frame crecv(Comm& c, int src_idx, int tag, bool coll);

// Same-host shm arena for a communicator (lazy).  Eligible when every
// member's bootstrap host fingerprint matches ours — then collectives
// move through shared memory instead of TCP frames (the role libmpi's
// shm BTL plays for the reference, mpi_xla_bridge.pyx:149-167).
//
// Setup is an explicit agreement protocol so the transport choice can
// never split the communicator (a rank silently falling back to TCP
// while its peers wait in shm would deadlock the job):
//   1. member 0 creates + fully initialises the segment, then tells
//      everyone whether that worked;
//   2. the others attach (no polling: the segment provably exists) and
//      report success back to member 0;
//   3. member 0 broadcasts the AND of every report — the arena is used
//      only when every member attached, else every member drops it and
//      the whole comm stays on TCP.
// The three rounds ride the TCP collective channel, which is always up.
shm::Arena* negotiate_arena(Comm& c) {
  int n = static_cast<int>(c.ranks.size());
  // fingerprints come from one bootstrap table, so this predicate is
  // identical on every member: either all enter the rounds or none do
  bool same_host = n > 1 && c.my_index >= 0 && !shm::disabled() &&
                   static_cast<int>(g_host_fps.size()) == g_size;
  if (same_host) {
    for (int r : c.ranks)
      if (g_host_fps[r] != g_host_fps[g_rank]) {
        same_host = false;
        break;
      }
  }
  if (!same_host) return nullptr;

  shm::Arena* a = nullptr;
  uint8_t ok = 0;
  if (c.my_index == 0) {
    a = shm::create(g_job.c_str(), c.ctx, n);
    ok = a != nullptr;
    for (int i = 1; i < n; ++i)
      csend(c, i, kArenaTagCreated, &ok, 1, true);
  } else {
    Frame f = crecv(c, 0, kArenaTagCreated, true);
    ok = f.data.size() == 1 ? f.data.data()[0] : 0;
    if (ok) {
      a = shm::attach(g_job.c_str(), c.ctx, n, c.my_index);
      ok = a != nullptr;
    }
  }
  if (c.my_index == 0) {
    for (int i = 1; i < n; ++i) {
      Frame f = crecv(c, i, kArenaTagAttach, true);
      ok &= f.data.size() == 1 ? f.data.data()[0] : 0;
    }
    for (int i = 1; i < n; ++i)
      csend(c, i, kArenaTagFinal, &ok, 1, true);
  } else {
    csend(c, 0, kArenaTagAttach, &ok, 1, true);
    Frame f = crecv(c, 0, kArenaTagFinal, true);
    ok = f.data.size() == 1 ? f.data.data()[0] : 0;
  }
  if (!ok && a) {
    shm::destroy(a);
    a = nullptr;
  }
  // every member holds a mapping now, so drop the NAME immediately: an
  // abnormal exit (die/_exit/SIGKILL) can then never leak the segment —
  // the kernel frees the tmpfs pages with the last mapping
  if (a) shm::unlink_name(a);
  return a;
}

shm::Arena* comm_arena(Comm& c) {
  {
    std::lock_guard<std::mutex> lk(g_comm_mu);
    if (c.arena_checked) return c.arena;
  }
  // Negotiation happens OUTSIDE the registry mutex: it blocks on TCP
  // rounds, and holding g_comm_mu there would stall every unrelated
  // bridge call in the process.  Concurrent first-collectives on the
  // SAME comm cannot happen (MPI serialises collectives per comm).
  shm::Arena* a = negotiate_arena(c);
  std::lock_guard<std::mutex> lk(g_comm_mu);
  c.arena = a;
  c.arena_checked = true;
  return c.arena;
}

// ------------------------------------------------------------ reductions

template <typename T>
void combine_typed(ReduceOp op, const T* a, T* acc, size_t n) {
  switch (op) {
    case ReduceOp::kSum:
      for (size_t i = 0; i < n; ++i) acc[i] = acc[i] + a[i];
      return;
    case ReduceOp::kProd:
      for (size_t i = 0; i < n; ++i) acc[i] = acc[i] * a[i];
      return;
    case ReduceOp::kMin:
      if constexpr (!std::is_same_v<T, std::complex<float>> &&
                    !std::is_same_v<T, std::complex<double>>) {
        for (size_t i = 0; i < n; ++i) acc[i] = a[i] < acc[i] ? a[i] : acc[i];
        return;
      }
      die("MIN on complex dtype");
    case ReduceOp::kMax:
      if constexpr (!std::is_same_v<T, std::complex<float>> &&
                    !std::is_same_v<T, std::complex<double>>) {
        for (size_t i = 0; i < n; ++i) acc[i] = acc[i] < a[i] ? a[i] : acc[i];
        return;
      }
      die("MAX on complex dtype");
    default:
      break;
  }
  if constexpr (std::is_integral_v<T>) {
    switch (op) {
      case ReduceOp::kLand:
        for (size_t i = 0; i < n; ++i) acc[i] = (acc[i] && a[i]) ? 1 : 0;
        return;
      case ReduceOp::kLor:
        for (size_t i = 0; i < n; ++i) acc[i] = (acc[i] || a[i]) ? 1 : 0;
        return;
      case ReduceOp::kLxor:
        for (size_t i = 0; i < n; ++i)
          acc[i] = ((acc[i] != 0) != (a[i] != 0)) ? 1 : 0;
        return;
      case ReduceOp::kBand:
        for (size_t i = 0; i < n; ++i) acc[i] = acc[i] & a[i];
        return;
      case ReduceOp::kBor:
        for (size_t i = 0; i < n; ++i) acc[i] = acc[i] | a[i];
        return;
      case ReduceOp::kBxor:
        for (size_t i = 0; i < n; ++i) acc[i] = acc[i] ^ a[i];
        return;
      default:
        break;
    }
  }
  die("unsupported reduce op for dtype");
}

// half-precision types travel as uint16 and reduce via float
float half_to_float(uint16_t h, bool bf16) {
  if (bf16) {
    uint32_t bits = static_cast<uint32_t>(h) << 16;
    float f;
    std::memcpy(&f, &bits, 4);
    return f;
  }
  // IEEE f16 -> f32
  uint32_t sign = (h >> 15) & 1, exp = (h >> 10) & 0x1f, frac = h & 0x3ff;
  uint32_t bits;
  if (exp == 0) {
    if (frac == 0) {
      bits = sign << 31;
    } else {
      exp = 127 - 15 + 1;
      while (!(frac & 0x400)) {
        frac <<= 1;
        --exp;
      }
      frac &= 0x3ff;
      bits = (sign << 31) | (exp << 23) | (frac << 13);
    }
  } else if (exp == 0x1f) {
    bits = (sign << 31) | 0x7f800000u | (frac << 13);
  } else {
    bits = (sign << 31) | ((exp - 15 + 127) << 23) | (frac << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

uint16_t float_to_half(float f, bool bf16) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  if (bf16) {
    // round-to-nearest-even
    uint32_t rounding = ((bits >> 16) & 1) + 0x7fff;
    return static_cast<uint16_t>((bits + rounding) >> 16);
  }
  uint32_t sign = (bits >> 31) & 1, exp = (bits >> 23) & 0xff,
           frac = bits & 0x7fffff;
  uint16_t h;
  if (exp >= 0xff) {
    h = static_cast<uint16_t>((sign << 15) | 0x7c00 | (frac ? 0x200 : 0));
  } else if (exp > 127 + 15) {
    h = static_cast<uint16_t>((sign << 15) | 0x7c00);
  } else if (exp < 127 - 14) {
    h = static_cast<uint16_t>(sign << 15);  // flush tiny to zero
  } else {
    h = static_cast<uint16_t>((sign << 15) | ((exp - 127 + 15) << 10) |
                              (frac >> 13));
  }
  return h;
}

void combine_half(ReduceOp op, const uint16_t* a, uint16_t* acc, size_t n,
                  bool bf16) {
  for (size_t i = 0; i < n; ++i) {
    float x = half_to_float(a[i], bf16), y = half_to_float(acc[i], bf16);
    float r;
    switch (op) {
      case ReduceOp::kSum:
        r = y + x;
        break;
      case ReduceOp::kProd:
        r = y * x;
        break;
      case ReduceOp::kMin:
        r = x < y ? x : y;
        break;
      case ReduceOp::kMax:
        r = y < x ? x : y;
        break;
      default:
        die("unsupported reduce op for half dtype");
    }
    acc[i] = float_to_half(r, bf16);
  }
}

}  // namespace (reopened below: combine is linked from shm.cc)

namespace detail {
void combine(ReduceOp op, DType dt, const void* contrib, void* acc,
             size_t count) {
  switch (dt) {
    case DType::kF32:
      return combine_typed(op, static_cast<const float*>(contrib),
                           static_cast<float*>(acc), count);
    case DType::kF64:
      return combine_typed(op, static_cast<const double*>(contrib),
                           static_cast<double*>(acc), count);
    case DType::kI8:
      return combine_typed(op, static_cast<const int8_t*>(contrib),
                           static_cast<int8_t*>(acc), count);
    case DType::kI16:
      return combine_typed(op, static_cast<const int16_t*>(contrib),
                           static_cast<int16_t*>(acc), count);
    case DType::kI32:
      return combine_typed(op, static_cast<const int32_t*>(contrib),
                           static_cast<int32_t*>(acc), count);
    case DType::kI64:
      return combine_typed(op, static_cast<const int64_t*>(contrib),
                           static_cast<int64_t*>(acc), count);
    case DType::kU8:
    case DType::kBool:
      return combine_typed(op, static_cast<const uint8_t*>(contrib),
                           static_cast<uint8_t*>(acc), count);
    case DType::kU16:
      return combine_typed(op, static_cast<const uint16_t*>(contrib),
                           static_cast<uint16_t*>(acc), count);
    case DType::kU32:
      return combine_typed(op, static_cast<const uint32_t*>(contrib),
                           static_cast<uint32_t*>(acc), count);
    case DType::kU64:
      return combine_typed(op, static_cast<const uint64_t*>(contrib),
                           static_cast<uint64_t*>(acc), count);
    case DType::kC64:
      return combine_typed(op, static_cast<const std::complex<float>*>(contrib),
                           static_cast<std::complex<float>*>(acc), count);
    case DType::kC128:
      return combine_typed(op,
                           static_cast<const std::complex<double>*>(contrib),
                           static_cast<std::complex<double>*>(acc), count);
    case DType::kF16:
      return combine_half(op, static_cast<const uint16_t*>(contrib),
                          static_cast<uint16_t*>(acc), count, false);
    case DType::kBF16:
      return combine_half(op, static_cast<const uint16_t*>(contrib),
                          static_cast<uint16_t*>(acc), count, true);
  }
  die("unknown dtype");
}
}  // namespace detail

namespace {

using detail::combine;

// comm-relative send/recv; coll=true routes through the internal
// collective channel (separate wire ctx), so user-facing ANY_SOURCE /
// ANY_TAG receives can never capture collective frames
void csend(Comm& c, int dest_idx, int tag, const void* buf, size_t n,
           bool coll = true) {
  raw_send(c.ranks[dest_idx], enc_ctx(c.ctx, coll), tag, buf, n);
}

Frame crecv(Comm& c, int src_idx, int tag, bool coll = true) {
  int world_src = src_idx == kAnySource ? kAnySource : c.ranks[src_idx];
  return raw_recv(world_src, enc_ctx(c.ctx, coll), tag);
}

}  // namespace

// ---------------------------------------------------------------- public

size_t dtype_size(DType dt) {
  switch (dt) {
    case DType::kI8:
    case DType::kU8:
    case DType::kBool:
      return 1;
    case DType::kI16:
    case DType::kU16:
    case DType::kF16:
    case DType::kBF16:
      return 2;
    case DType::kF32:
    case DType::kI32:
    case DType::kU32:
      return 4;
    case DType::kF64:
    case DType::kI64:
    case DType::kU64:
    case DType::kC64:
      return 8;
    case DType::kC128:
      return 16;
  }
  die("unknown dtype");
}

bool initialized() { return g_initialized; }
int world_rank() { return g_rank; }
int world_size() { return g_size; }
void set_logging(bool enabled) { g_logging = enabled; }

void abort_job(int code, const char* why) {
  std::fprintf(stderr, "r%d | t4j abort: %s\n", g_rank, why);
  std::fflush(stderr);
  _exit(code);
}

int init_from_env() {
  if (g_initialized) return 0;
  const char* rank_s = std::getenv("T4J_RANK");
  const char* size_s = std::getenv("T4J_SIZE");
  const char* coord_s = std::getenv("T4J_COORD");
  if (!rank_s || !size_s) return 1;  // not a multi-process job
  g_rank = std::atoi(rank_s);
  g_size = std::atoi(size_s);
  if (g_size < 1 || g_rank < 0 || g_rank >= g_size) die("T4J_RANK/T4J_SIZE");
  // The native LogScope has its own switch, separate from the Python
  // layer's MPI4JAX_TPU_DEBUG: with both keyed to one var every MPI
  // call would log two begin/done pairs with different call ids.
  const char* dbg = std::getenv("MPI4JAX_TPU_NATIVE_DEBUG");
  if (dbg && dbg[0] && std::strcmp(dbg, "0") != 0) g_logging = true;

  // unique job id namespaces the shm segments (launcher sets T4J_JOB;
  // fall back to a sanitised coordinator address + uid)
  const char* job_s = std::getenv("T4J_JOB");
  if (job_s && job_s[0]) {
    g_job = job_s;
  } else {
    g_job = coord_s ? coord_s : "local";
    g_job += "_u" + std::to_string(::getuid());
  }
  for (auto& ch : g_job)
    if (!std::isalnum(static_cast<unsigned char>(ch))) ch = '_';
  if (g_job.size() > 80) g_job.resize(80);

  if (g_size > 1) {
    std::string coord = coord_s ? coord_s : "127.0.0.1:45677";
    auto colon = coord.rfind(':');
    if (colon == std::string::npos) die("T4J_COORD format (host:port)");
    std::string host = coord.substr(0, colon);
    uint16_t port = static_cast<uint16_t>(std::atoi(coord.c_str() + colon + 1));
    bootstrap(host, port);
  }

  {
    std::lock_guard<std::mutex> lk(g_comm_mu);
    Comm world;
    for (int i = 0; i < g_size; ++i) world.ranks.push_back(i);
    world.ctx = 0;
    world.my_index = g_rank;
    g_comms.push_back(world);
  }
  g_initialized = true;
  barrier(0);
  return 0;
}

void finalize() {
  if (!g_initialized) return;
  barrier(0);
  {
    std::lock_guard<std::mutex> lk(g_comm_mu);
    for (auto& c : g_comms) {
      if (c.arena) shm::destroy(c.arena);
      c.arena = nullptr;
      c.arena_checked = true;
    }
  }
  g_shutting_down.store(true);
  // wake every pipe waiter (readers blocked on empty, writers on full):
  // they observe g_shutting_down and exit
  if (g_my_pipes)
    for (int i = 0;; ++i) {
      shm::Pipe* p = shm::pipe_of(g_my_pipes, i);
      if (!p) break;
      shm::pipe_wake(p);
    }
  for (auto* tx : g_tx_pipes)
    if (tx) shm::pipe_wake(tx);
  for (auto& t : g_pipe_readers) t.join();
  g_pipe_readers.clear();
  for (auto*& tx : g_tx_pipes) {
    if (tx) shm::pipe_close(tx);
    tx = nullptr;
  }
  if (g_my_pipes) {
    shm::pipes_destroy(g_my_pipes);
    g_my_pipes = nullptr;
  }
  // shutdown first (wakes blocked readers with EOF/error), close only
  // after every reader has exited — closing a fd a thread is blocked on
  // is undefined behaviour and produced spurious EBADF aborts
  for (auto& p : g_peers) {
    if (p.fd >= 0) ::shutdown(p.fd, SHUT_RDWR);
  }
  for (auto& t : g_readers) t.join();
  g_readers.clear();
  for (auto& p : g_peers) {
    if (p.fd >= 0) {
      ::close(p.fd);
      p.fd = -1;
    }
  }
  g_initialized = false;
}

int comm_create(const int* world_ranks, int n, int ctx) {
  std::lock_guard<std::mutex> lk(g_comm_mu);
  Comm c;
  c.my_index = -1;
  for (int i = 0; i < n; ++i) {
    int r = world_ranks[i];
    if (r < 0 || r >= g_size) die("comm_create rank range");
    if (r == g_rank) c.my_index = i;
    c.ranks.push_back(r);
  }
  // ctx is supplied by the caller as a deterministic function of
  // (ranks, clone-generation) so every member derives the same channel
  // id regardless of local comm-creation order (per-process counters
  // would desynchronise under MPMD control flow)
  c.ctx = ctx;
  g_comms.push_back(c);
  return static_cast<int>(g_comms.size()) - 1;
}

int comm_rank(int comm) { return get_comm(comm).my_index; }
int comm_size(int comm) {
  return static_cast<int>(get_comm(comm).ranks.size());
}

void send(int comm, const void* buf, size_t nbytes, int dest, int tag) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Send", "-> " + std::to_string(dest) + " with tag " +
                             std::to_string(tag) + " and " +
                             std::to_string(nbytes) + " bytes");
  if (dest < 0 || dest >= static_cast<int>(c.ranks.size()))
    die("send dest rank (MPI_Send)");
  csend(c, dest, tag, buf, nbytes, /*coll=*/false);
}

void recv(int comm, void* buf, size_t nbytes, int source, int tag,
          int* src_out, int* tag_out) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Recv", "<- " + std::to_string(source) + " with tag " +
                             std::to_string(tag) + " and " +
                             std::to_string(nbytes) + " bytes");
  if (source != kAnySource &&
      (source < 0 || source >= static_cast<int>(c.ranks.size())))
    die("recv source rank (MPI_Recv)");
  Frame f = crecv(c, source, tag, /*coll=*/false);
  if (f.data.size() != nbytes) die("recv size mismatch");
  std::memcpy(buf, f.data.data(), nbytes);
  if (src_out) {
    *src_out = 0;
    for (size_t i = 0; i < c.ranks.size(); ++i)
      if (c.ranks[i] == f.src) *src_out = static_cast<int>(i);
  }
  if (tag_out) *tag_out = f.tag;
}

void sendrecv(int comm, const void* sendbuf, size_t send_nbytes,
              void* recvbuf, size_t recv_nbytes, int source, int dest,
              int sendtag, int recvtag, int* src_out, int* tag_out) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Sendrecv", "<- " + std::to_string(source) +
                                 " (tag " + std::to_string(recvtag) +
                                 ") / -> " + std::to_string(dest) +
                                 " (tag " + std::to_string(sendtag) + ")");
  // eager sends cannot block: send first, then receive (the pattern the
  // reference's deadlock test guards, test_send_and_recv.py:104-117).
  // Send and recv sizes are independent (MPI_Sendrecv semantics).
  csend(c, dest, sendtag, sendbuf, send_nbytes, /*coll=*/false);
  Frame f = crecv(c, source, recvtag, /*coll=*/false);
  if (f.data.size() != recv_nbytes) die("sendrecv size mismatch");
  std::memcpy(recvbuf, f.data.data(), recv_nbytes);
  if (src_out) {
    *src_out = 0;
    for (size_t i = 0; i < c.ranks.size(); ++i)
      if (c.ranks[i] == f.src) *src_out = static_cast<int>(i);
  }
  if (tag_out) *tag_out = f.tag;
}

void barrier(int comm) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Barrier", "");
  int n = static_cast<int>(c.ranks.size());
  if (n == 1) return;
  if (shm::Arena* a = comm_arena(c)) return shm::barrier(a);
  int me = c.my_index;
  // dissemination barrier
  for (int k = 1; k < n; k <<= 1) {
    uint8_t b = 1;
    csend(c, (me + k) % n, kCollTagBase + 1, &b, 1);
    crecv(c, ((me - k) % n + n) % n, kCollTagBase + 1);
  }
}

void bcast(int comm, void* buf, size_t nbytes, int root) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Bcast", "-> " + std::to_string(root) + " with " +
                              std::to_string(nbytes) + " bytes");
  int n = static_cast<int>(c.ranks.size());
  if (n == 1) return;
  if (shm::Arena* a = comm_arena(c)) return shm::bcast(a, buf, nbytes, root);
  // binomial tree rooted at `root` (rotate indices so root -> 0)
  int me = (c.my_index - root % n + n) % n;
  for (int k = 1; k < n; k <<= 1) {
    if (me < k) {
      int partner = me + k;
      if (partner < n)
        csend(c, (partner + root) % n, kCollTagBase + 2, buf, nbytes);
    } else if (me < 2 * k) {
      Frame f = crecv(c, ((me - k) + root) % n, kCollTagBase + 2);
      if (f.data.size() != nbytes) die("bcast size mismatch");
      std::memcpy(buf, f.data.data(), nbytes);
    }
  }
}

void reduce(int comm, const void* in, void* out, size_t count, DType dt,
            ReduceOp op, int root) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Reduce", "-> " + std::to_string(root) + " with " +
                               std::to_string(count) + " items");
  int n = static_cast<int>(c.ranks.size());
  if (shm::Arena* a = comm_arena(c))
    return shm::reduce(a, in, out, count, dt, op, root);
  size_t nbytes = count * dtype_size(dt);
  std::vector<uint8_t> acc(static_cast<const uint8_t*>(in),
                           static_cast<const uint8_t*>(in) + nbytes);
  // binomial tree towards root (rotated)
  int me = (c.my_index - root % n + n) % n;
  int k = 1;
  while (k < n) k <<= 1;
  for (k >>= 1; k >= 1; k >>= 1) {
    if (me < k) {
      int partner = me + k;
      if (partner < n) {
        Frame f = crecv(c, (partner + root) % n, kCollTagBase + 3);
        if (f.data.size() != nbytes) die("reduce size mismatch");
        combine(op, dt, f.data.data(), acc.data(), count);
      }
    } else if (me < 2 * k) {
      csend(c, ((me - k) + root) % n, kCollTagBase + 3, acc.data(), nbytes);
      break;
    }
  }
  if (c.my_index == root) std::memcpy(out, acc.data(), nbytes);
}

void allreduce(int comm, const void* in, void* out, size_t count, DType dt,
               ReduceOp op) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Allreduce", "with " + std::to_string(count) + " items");
  if (shm::Arena* a = comm_arena(c))
    return shm::allreduce(a, in, out, count, dt, op);
  size_t nbytes = count * dtype_size(dt);
  reduce(comm, in, out, count, dt, op, 0);
  if (c.my_index != 0) std::memcpy(out, in, nbytes);  // placate valgrind
  bcast(comm, out, nbytes, 0);
}

void scan(int comm, const void* in, void* out, size_t count, DType dt,
          ReduceOp op) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Scan", "with " + std::to_string(count) + " items");
  if (shm::Arena* a = comm_arena(c))
    return shm::scan(a, in, out, count, dt, op);
  int n = static_cast<int>(c.ranks.size());
  size_t nbytes = count * dtype_size(dt);
  std::memcpy(out, in, nbytes);
  // linear inclusive prefix chain (MPI_Scan semantics)
  if (c.my_index > 0) {
    Frame f = crecv(c, c.my_index - 1, kCollTagBase + 4);
    if (f.data.size() != nbytes) die("scan size mismatch");
    combine(op, dt, in, f.data.data(), count);
    std::memcpy(out, f.data.data(), nbytes);
  }
  if (c.my_index + 1 < n) csend(c, c.my_index + 1, kCollTagBase + 4, out, nbytes);
}

void allgather(int comm, const void* in, void* out, size_t nbytes_each) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Allgather", "sending " + std::to_string(nbytes_each) +
                                  " bytes each");
  if (shm::Arena* a = comm_arena(c))
    return shm::allgather(a, in, out, nbytes_each);
  gather(comm, in, out, nbytes_each, 0);
  bcast(comm, out, nbytes_each * c.ranks.size(), 0);
}

void gather(int comm, const void* in, void* out, size_t nbytes_each,
            int root) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Gather", "-> " + std::to_string(root) + " sending " +
                               std::to_string(nbytes_each) + " bytes each");
  if (shm::Arena* a = comm_arena(c))
    return shm::gather(a, in, out, nbytes_each, root);
  int n = static_cast<int>(c.ranks.size());
  if (c.my_index == root) {
    uint8_t* o = static_cast<uint8_t*>(out);
    std::memcpy(o + nbytes_each * root, in, nbytes_each);
    for (int i = 0; i < n; ++i) {
      if (i == root) continue;
      Frame f = crecv(c, i, kCollTagBase + 5);
      if (f.data.size() != nbytes_each) die("gather size mismatch");
      std::memcpy(o + nbytes_each * i, f.data.data(), nbytes_each);
    }
  } else {
    csend(c, root, kCollTagBase + 5, in, nbytes_each);
  }
}

void scatter(int comm, const void* in, void* out, size_t nbytes_each,
             int root) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Scatter", "-> " + std::to_string(root) + " sending " +
                                std::to_string(nbytes_each) + " bytes each");
  if (shm::Arena* a = comm_arena(c))
    return shm::scatter(a, in, out, nbytes_each, root);
  int n = static_cast<int>(c.ranks.size());
  if (c.my_index == root) {
    const uint8_t* i8 = static_cast<const uint8_t*>(in);
    for (int i = 0; i < n; ++i) {
      if (i == root) continue;
      csend(c, i, kCollTagBase + 6, i8 + nbytes_each * i, nbytes_each);
    }
    std::memcpy(out, i8 + nbytes_each * root, nbytes_each);
  } else {
    Frame f = crecv(c, root, kCollTagBase + 6);
    if (f.data.size() != nbytes_each) die("scatter size mismatch");
    std::memcpy(out, f.data.data(), nbytes_each);
  }
}

void alltoall(int comm, const void* in, void* out, size_t nbytes_each) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Alltoall", "sending " + std::to_string(nbytes_each) +
                                 " bytes each");
  if (shm::Arena* a = comm_arena(c))
    return shm::alltoall(a, in, out, nbytes_each);
  int n = static_cast<int>(c.ranks.size());
  int me = c.my_index;
  const uint8_t* i8 = static_cast<const uint8_t*>(in);
  uint8_t* o8 = static_cast<uint8_t*>(out);
  std::memcpy(o8 + nbytes_each * me, i8 + nbytes_each * me, nbytes_each);
  // staggered pairwise exchange
  for (int off = 1; off < n; ++off) {
    int to = (me + off) % n;
    int from = ((me - off) % n + n) % n;
    csend(c, to, kCollTagBase + 7, i8 + nbytes_each * to, nbytes_each);
    Frame f = crecv(c, from, kCollTagBase + 7);
    if (f.data.size() != nbytes_each) die("alltoall size mismatch");
    std::memcpy(o8 + nbytes_each * from, f.data.data(), nbytes_each);
  }
}

}  // namespace t4j
