// DCN bridge implementation: see dcn.h.

#include "dcn.h"
#include "shm.h"
#include "telemetry.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#ifdef __linux__
#include <linux/errqueue.h>  // MSG_ZEROCOPY completion records
#endif

// io_uring wire backend (docs/performance.md "io_uring wire
// backend"): raw syscalls against the uapi header — the container
// toolchain carries no liburing, and the three syscalls plus two
// mmaps are all the backend needs.  Compile-gated on the header,
// runtime-gated on an io_uring_setup probe (kernels without io_uring,
// or with it seccomp-filtered, degrade loudly to the sendmsg
// backend).
#if defined(__linux__) && defined(__has_include)
#if __has_include(<linux/io_uring.h>)
#define T4J_HAVE_URING 1
#include <linux/io_uring.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#endif
#endif
#ifndef T4J_HAVE_URING
#define T4J_HAVE_URING 0
#endif

#include <csignal>

#include <cerrno>

#ifdef __SSE2__
#include <emmintrin.h>
#endif

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <complex>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <limits>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace t4j {

namespace {

// ---------------------------------------------------------------- logging

bool g_logging = false;
int g_rank = -1;
int g_size = -1;
bool g_initialized = false;
std::atomic<bool> g_shutting_down{false};
// g_stop = "no bridge call can make progress any more": set on clean
// shutdown AND on the first posted fault.  Blocked pipe/socket/mailbox
// waiters key off this single flag so one wake path covers both.
std::atomic<bool> g_stop{false};

// ------------------------------------------------------- fault surface

// Globals shared with DETACHED threads (readers, repair dialers) are
// leaked on purpose: an abnormal exit (a fault raised through user
// code that never reaches finalize) runs static destructors while
// those threads may still be mid-access, and destroying a mutex or a
// deque under a live thread is use-after-free.  The process is exiting
// either way — leaking is the correct lifetime for these.
std::atomic<bool> g_faulted{false};
std::mutex& g_fault_mu = *new std::mutex;
std::string& g_fault_msg = *new std::string;  // guarded by g_fault_mu
// Set at finalize entry, BEFORE the exit barrier: peers that finish
// teardown first close their sockets while we are still leaving, and
// that expected EOF must not print a scary fault line (it still posts
// quietly, so a genuinely dead peer cannot hang our exit barrier).
std::atomic<bool> g_finalizing{false};

// current op name for error context ("MPI_Recv", ...), maintained by
// the LogScope RAII every public entry point already constructs
thread_local const char* tls_op = nullptr;

const char* cur_op() { return tls_op ? tls_op : "bridge call"; }

std::string err_prefix() {
  return "r" + std::to_string(g_rank) + " | t4j: ";
}

// ------------------------------------------------------------ deadlines

// Python (native/runtime.py) validates via utils/config.py and calls
// set_timeouts before init; the env parse is the fallback for hand-run
// processes.  -1 = "not set yet".
std::atomic<double> g_op_timeout_s{-1.0};
std::atomic<double> g_connect_timeout_s{-1.0};

double env_seconds(const char* name, double dflt) {
  const char* s = std::getenv(name);
  if (!s || !s[0]) return dflt;
  char* end = nullptr;
  double v = std::strtod(s, &end);
  if (end == s || v < 0) return dflt;  // Python layer rejects loudly
  return v;
}

double op_timeout() {
  double v = g_op_timeout_s.load(std::memory_order_relaxed);
  if (v < 0) {
    v = env_seconds("T4J_OP_TIMEOUT", 0.0);  // 0 = wait forever (MPI)
    g_op_timeout_s.store(v, std::memory_order_relaxed);
  }
  return v;
}

double connect_timeout() {
  double v = g_connect_timeout_s.load(std::memory_order_relaxed);
  if (v < 0) {
    v = env_seconds("T4J_CONNECT_TIMEOUT", 30.0);
    if (v <= 0) v = 30.0;
    g_connect_timeout_s.store(v, std::memory_order_relaxed);
  }
  return v;
}

// ------------------------------------------------- data-plane tuning
//
// Ring-vs-tree switchover and segment size for the TCP-tier
// collectives (docs/performance.md "TCP-tier algorithm selection").
// Python (native/runtime.py) validates via utils/config.py and calls
// set_tuning before init; the env parse is the fallback for hand-run
// processes.  -1 = "not set yet".

std::atomic<long long> g_ring_min_bytes{-1};
std::atomic<long long> g_seg_bytes{-1};

// Measured crossover on the 8-proc loopback sweep (docs/performance.md
// "TCP-tier algorithm selection"): trees win below ~256 KB (the ring
// pays 2(n-1) serialized step latencies), ring wins 2-3x from 1 MB up.
constexpr long long kDefaultRingMinBytes = 256 << 10;  // 256 KiB
constexpr long long kDefaultSegBytes = 1 << 20;       // 1 MiB

long long env_bytes(const char* name, long long dflt) {
  const char* s = std::getenv(name);
  if (!s || !s[0]) return dflt;
  char* end = nullptr;
  long long v = std::strtoll(s, &end, 10);
  if (end == s || v < 0) return dflt;  // Python layer rejects loudly
  // optional K/M/G suffix; anything else trailing ("0x40", "256KB")
  // falls back to the default rather than misparsing — the Python
  // layer (utils/config.py byte_count) is the loud validator
  while (*end == ' ') ++end;
  if (*end == 'k' || *end == 'K') { v <<= 10; ++end; }
  else if (*end == 'm' || *end == 'M') { v <<= 20; ++end; }
  else if (*end == 'g' || *end == 'G') { v <<= 30; ++end; }
  while (*end == ' ') ++end;
  if (*end != '\0') return dflt;
  return v;
}

long long ring_min_bytes() {
  long long v = g_ring_min_bytes.load(std::memory_order_relaxed);
  if (v < 0) {
    v = env_bytes("T4J_RING_MIN_BYTES", kDefaultRingMinBytes);
    g_ring_min_bytes.store(v, std::memory_order_relaxed);
  }
  return v;
}

long long seg_bytes() {
  long long v = g_seg_bytes.load(std::memory_order_relaxed);
  if (v < 1) {
    v = env_bytes("T4J_SEG_BYTES", kDefaultSegBytes);
    if (v < 1) v = kDefaultSegBytes;
    g_seg_bytes.store(v, std::memory_order_relaxed);
  }
  return v;
}

// ------------------------------------------------ coalescing tuning
//
// Small-message coalescing threshold (docs/performance.md
// "small-message coalescing"): the Python op layer fuses runs of
// small same-peer messages into one wire frame when their combined
// payload is at or below this many bytes.  The knob is mirrored here
// so standalone ctypes harnesses and introspection read the same
// effective value; 0 disables fusion entirely (exact pre-coalescing
// wire behaviour).  -1 = "not set yet"; Python validates via
// utils/config.py and calls set_coalesce, the env parse is the
// fallback for hand-run processes.

std::atomic<long long> g_coalesce_bytes{-1};

constexpr long long kDefaultCoalesceBytes = 16 << 10;  // 16 KiB

long long coalesce_bytes() {
  long long v = g_coalesce_bytes.load(std::memory_order_relaxed);
  if (v < 0) {
    v = env_bytes("T4J_COALESCE_BYTES", kDefaultCoalesceBytes);
    g_coalesce_bytes.store(v, std::memory_order_relaxed);
  }
  return v;
}

// ----------------------------------------------- wire-path tuning
//
// Striped multi-connection links + syscall batching + MSG_ZEROCOPY
// (docs/performance.md "striped links and the zero-copy path").  The
// BUILT stripe count is fixed at bootstrap (connections are dialed
// then); the DEALING width can be changed at runtime up to the built
// width (the trace-guided calibrator A/Bs widths inside one world).
// -1 = "not set yet"; Python validates via utils/config.py and calls
// set_wire, the env parse is the fallback for hand-run processes.

constexpr int kMaxStripes = 16;
constexpr int kDefaultSendmsgBatch = 8;

long long env_int(const char* name, long long dflt);  // defined below

std::atomic<int> g_wire_stripes{-1};       // requested dealing width
std::atomic<long long> g_zc_min_bytes{-1};  // 0 = zerocopy off
std::atomic<int> g_sendmsg_batch{-1};
std::atomic<long long> g_emu_flow_bps{-1};  // 0 = no throttle
// Fixed at init (single-threaded): connections bootstrap built per
// link, and whether the kernel honoured SO_ZEROCOPY when requested.
int g_built_stripes = 1;
bool g_zc_supported = false;

int requested_stripes() {
  int v = g_wire_stripes.load(std::memory_order_relaxed);
  if (v < 1) {
    const char* s = std::getenv("T4J_STRIPES");
    v = 1;  // auto resolves to 1 until the calibrator learns better
    if (s && s[0] && std::strcmp(s, "auto") != 0) {
      long p = std::atol(s);
      if (p >= 1) v = static_cast<int>(p);
    }
    if (v > kMaxStripes) v = kMaxStripes;
    g_wire_stripes.store(v, std::memory_order_relaxed);
  }
  return v;
}

// Current dealing width: never wider than what bootstrap built.
int active_stripes() {
  int v = requested_stripes();
  if (g_initialized && v > g_built_stripes) v = g_built_stripes;
  return v < 1 ? 1 : v;
}

long long zc_min_bytes() {
  long long v = g_zc_min_bytes.load(std::memory_order_relaxed);
  if (v < 0) {
    v = env_bytes("T4J_ZEROCOPY_MIN_BYTES", 0);
    g_zc_min_bytes.store(v, std::memory_order_relaxed);
  }
  return v;
}

int sendmsg_batch() {
  int v = g_sendmsg_batch.load(std::memory_order_relaxed);
  if (v < 1) {
    v = static_cast<int>(
        env_int("T4J_SENDMSG_BATCH", kDefaultSendmsgBatch));
    if (v < 1) v = 1;
    g_sendmsg_batch.store(v, std::memory_order_relaxed);
  }
  return v;
}

long long emu_flow_bps() {
  long long v = g_emu_flow_bps.load(std::memory_order_relaxed);
  if (v < 0) {
    v = env_bytes("T4J_EMU_FLOW_BPS", 0);
    g_emu_flow_bps.store(v, std::memory_order_relaxed);
  }
  return v;
}

// ------------------------------------------------- wire backend
//
// Pluggable data plane (docs/performance.md "io_uring wire backend").
// The sendmsg backend is the classic gather-write/recv loop, byte-
// stable against every prior release; the uring backend submits whole
// segment runs as one io_uring_enter (SENDMSG chains for small
// frames, header + WRITE_FIXED over the registered replay arena for
// large ones) and replaces the reader's recv+poll pair with a single
// completion wait.  Frame bytes on the wire are identical across
// backends — only the syscall shape changes — so mixed backends
// interoperate and every fault/replay/elastic/compression contract is
// backend-independent.  "auto" resolves to sendmsg until the
// calibrator learns better (mirroring T4J_STRIPES); an explicit
// "uring" on a kernel without usable io_uring degrades LOUDLY to
// sendmsg at init.

constexpr int kBackendSendmsg = 0, kBackendUring = 1, kBackendAuto = 2;

std::atomic<int> g_wire_backend{-1};
std::atomic<int> g_uring_supported{-1};  // -1 = not probed yet
std::atomic<bool> g_uring_degrade_logged{false};

// Per-thread destination for data-plane syscall counters
// (Stripe::tx_syscalls / rx_syscalls): stripe_write points it at the
// stripe's tx counter, reader_loop at its rx counter, and every
// kernel crossing on the hot paths (sendmsg/recv/read/poll/
// io_uring_enter) bumps through it.  Never hand-derived — this is the
// syscalls-per-frame evidence t4j-top and the acceptance gate read.
thread_local std::atomic<uint64_t>* tls_syscall_ctr = nullptr;

inline void count_syscall() {
  if (tls_syscall_ctr)
    tls_syscall_ctr->fetch_add(1, std::memory_order_relaxed);
}

struct TlsSyscallScope {
  std::atomic<uint64_t>* prev;
  explicit TlsSyscallScope(std::atomic<uint64_t>* c) : prev(tls_syscall_ctr) {
    tls_syscall_ctr = c;
  }
  ~TlsSyscallScope() { tls_syscall_ctr = prev; }
};

// Adaptive io poll tick (the historical hard 100 ms floor inflated
// small-frame latency under light load): a global gauge of frames
// actively being sent or received picks between a tight bound while
// work is in flight and a lazy one when the rank is idle — idle ranks
// must not spin (asserted by tests via the syscall counters), and the
// idle tick stays far under telemetry/postmortem.py's 5 s heartbeat
// staleness threshold so a parked rank still reads as alive.
constexpr int kIoTickBusyMs = 5;
constexpr int kIoTickIdleMs = 250;

std::atomic<int> g_inflight_frames{0};

inline int io_tick_ms() {
  return g_inflight_frames.load(std::memory_order_relaxed) > 0
             ? kIoTickBusyMs
             : kIoTickIdleMs;
}

struct InflightScope {
  InflightScope() { g_inflight_frames.fetch_add(1, std::memory_order_relaxed); }
  ~InflightScope() {
    g_inflight_frames.fetch_sub(1, std::memory_order_relaxed);
  }
};

#if T4J_HAVE_URING

inline int sys_uring_setup(unsigned entries, io_uring_params* p) {
  return static_cast<int>(::syscall(__NR_io_uring_setup, entries, p));
}
inline int sys_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                           unsigned flags, const void* arg, size_t argsz) {
  return static_cast<int>(::syscall(__NR_io_uring_enter, fd, to_submit,
                                    min_complete, flags, arg, argsz));
}
inline int sys_uring_register(int fd, unsigned opcode, const void* arg,
                              unsigned nr_args) {
  return static_cast<int>(
      ::syscall(__NR_io_uring_register, fd, opcode, arg, nr_args));
}

// Minimal SQ/CQ ring pair over the raw mmap layout.  Single submitter
// thread per ring (each Stripe's send path is serialised by send_mu,
// each reader owns its recv ring, the engine owns its wait ring), so
// the only cross-party ordering is against the kernel: acquire on the
// kernel-written tail/head words, release on ours.
struct UringRing {
  int fd = -1;
  unsigned entries = 0;
  bool ext_arg = false;
  void* sq_mem = nullptr;
  void* cq_mem = nullptr;
  size_t sq_len = 0, cq_len = 0;
  io_uring_sqe* sqes = nullptr;
  size_t sqes_len = 0;
  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned* sq_mask = nullptr;
  unsigned* sq_array = nullptr;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned* cq_mask = nullptr;
  io_uring_cqe* cqes = nullptr;
  // Registered-buffer state (IORING_REGISTER_BUFFERS over the replay
  // arena / reader buffer).  Re-registration only happens with no
  // SQEs in flight — stripe_write's invariant is that it never
  // returns while the kernel still references caller memory.
  bool bufs_registered = false;
  const void* reg_base = nullptr;
  size_t reg_len = 0;

  bool open_ring(unsigned want) {
    io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    fd = sys_uring_setup(want, &p);
    if (fd < 0) return false;
    if (!(p.features & IORING_FEAT_NODROP) ||
        !(p.features & IORING_FEAT_EXT_ARG)) {
      // Pre-5.11 semantics (droppable CQEs, no timed enter): not
      // worth a second code path — the probe rejects these kernels
      // too, this is just belt and braces.
      ::close(fd);
      fd = -1;
      return false;
    }
    ext_arg = true;
    bool single = (p.features & IORING_FEAT_SINGLE_MMAP) != 0;
    sq_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cq_len = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    if (single) sq_len = cq_len = (sq_len > cq_len ? sq_len : cq_len);
    sq_mem = ::mmap(nullptr, sq_len, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
    if (sq_mem == MAP_FAILED) {
      sq_mem = nullptr;
      close_ring();
      return false;
    }
    if (single) {
      cq_mem = sq_mem;
    } else {
      cq_mem = ::mmap(nullptr, cq_len, PROT_READ | PROT_WRITE,
                      MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_CQ_RING);
      if (cq_mem == MAP_FAILED) {
        cq_mem = nullptr;
        close_ring();
        return false;
      }
    }
    sqes_len = p.sq_entries * sizeof(io_uring_sqe);
    sqes = static_cast<io_uring_sqe*>(
        ::mmap(nullptr, sqes_len, PROT_READ | PROT_WRITE,
               MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQES));
    if (sqes == MAP_FAILED) {
      sqes = nullptr;
      close_ring();
      return false;
    }
    auto* sq = static_cast<uint8_t*>(sq_mem);
    sq_head = reinterpret_cast<unsigned*>(sq + p.sq_off.head);
    sq_tail = reinterpret_cast<unsigned*>(sq + p.sq_off.tail);
    sq_mask = reinterpret_cast<unsigned*>(sq + p.sq_off.ring_mask);
    sq_array = reinterpret_cast<unsigned*>(sq + p.sq_off.array);
    auto* cq = static_cast<uint8_t*>(cq_mem);
    cq_head = reinterpret_cast<unsigned*>(cq + p.cq_off.head);
    cq_tail = reinterpret_cast<unsigned*>(cq + p.cq_off.tail);
    cq_mask = reinterpret_cast<unsigned*>(cq + p.cq_off.ring_mask);
    cqes = reinterpret_cast<io_uring_cqe*>(cq + p.cq_off.cqes);
    entries = p.sq_entries;
    return true;
  }

  void close_ring() {
    if (sqes) ::munmap(sqes, sqes_len);
    if (cq_mem && cq_mem != sq_mem) ::munmap(cq_mem, cq_len);
    if (sq_mem) ::munmap(sq_mem, sq_len);
    sqes = nullptr;
    sq_mem = cq_mem = nullptr;
    if (fd >= 0) ::close(fd);
    fd = -1;
    bufs_registered = false;
  }

  ~UringRing() { close_ring(); }

  io_uring_sqe* get_sqe() {
    unsigned tail = *sq_tail;
    unsigned idx = tail & *sq_mask;
    io_uring_sqe* e = &sqes[idx];
    std::memset(e, 0, sizeof(*e));
    sq_array[idx] = idx;
    __atomic_store_n(sq_tail, tail + 1, __ATOMIC_RELEASE);
    return e;
  }

  bool pop_cqe(io_uring_cqe* out) {
    unsigned head = *cq_head;
    if (head == __atomic_load_n(cq_tail, __ATOMIC_ACQUIRE)) return false;
    *out = cqes[head & *cq_mask];
    __atomic_store_n(cq_head, head + 1, __ATOMIC_RELEASE);
    return true;
  }

  // (Re-)register one buffer as fixed index 0.  Caller guarantees no
  // SQE referencing the old registration is in flight.
  bool register_buffer(const void* base, size_t len) {
    if (fd < 0 || !base || !len) return false;
    if (bufs_registered && base == reg_base && len == reg_len) return true;
    if (bufs_registered) {
      if (sys_uring_register(fd, IORING_UNREGISTER_BUFFERS, nullptr, 0) < 0)
        return false;
      bufs_registered = false;
    }
    iovec iov;
    iov.iov_base = const_cast<void*>(base);
    iov.iov_len = len;
    if (sys_uring_register(fd, IORING_REGISTER_BUFFERS, &iov, 1) < 0)
      return false;
    bufs_registered = true;
    reg_base = base;
    reg_len = len;
    return true;
  }
};

// One kernel crossing: submit whatever is queued and/or wait for
// completions, bounded by wait_ms (-1 = no wait, just submit/peek).
// Returns the enter() result (submitted count or -1/errno); -ETIME
// and EINTR are normal and surface as 0 with errno preserved for the
// caller's tick loop.
int uring_enter(UringRing& r, unsigned to_submit, unsigned min_complete,
                int wait_ms) {
  count_syscall();
  if (wait_ms >= 0) {
    __kernel_timespec ts;
    ts.tv_sec = wait_ms / 1000;
    ts.tv_nsec = static_cast<long long>(wait_ms % 1000) * 1000000LL;
    io_uring_getevents_arg arg;
    std::memset(&arg, 0, sizeof(arg));
    arg.ts = reinterpret_cast<uint64_t>(&ts);
    return sys_uring_enter(
        r.fd, to_submit, min_complete,
        IORING_ENTER_GETEVENTS | IORING_ENTER_EXT_ARG, &arg, sizeof(arg));
  }
  return sys_uring_enter(r.fd, to_submit, min_complete, 0, nullptr, 0);
}

#endif  // T4J_HAVE_URING

// Kernel support probe: one tiny io_uring_setup (also catches seccomp
// filters that ENOSYS the syscall).  T4J_URING_FORCE_UNSUPPORTED=1
// lets tests exercise the no-io_uring degrade path on any kernel.
bool probe_uring_support() {
#if T4J_HAVE_URING
  const char* force = std::getenv("T4J_URING_FORCE_UNSUPPORTED");
  if (force && force[0] && std::strcmp(force, "0") != 0) return false;
  io_uring_params p;
  std::memset(&p, 0, sizeof(p));
  int fd = sys_uring_setup(4, &p);
  if (fd < 0) return false;
  ::close(fd);
  return (p.features & IORING_FEAT_NODROP) &&
         (p.features & IORING_FEAT_EXT_ARG);
#else
  return false;
#endif
}

bool uring_supported() {
  int v = g_uring_supported.load(std::memory_order_acquire);
  if (v < 0) {
    v = probe_uring_support() ? 1 : 0;
    g_uring_supported.store(v, std::memory_order_release);
  }
  return v == 1;
}

// Requested mode; env parse is the fallback for hand-run processes
// (utils/config.py validates and calls set_wire_backend first in the
// normal bridge path).
int wire_backend_mode() {
  int v = g_wire_backend.load(std::memory_order_relaxed);
  if (v < 0) {
    const char* s = std::getenv("T4J_WIRE_BACKEND");
    v = kBackendAuto;
    if (s && s[0]) {
      if (!std::strcmp(s, "sendmsg")) v = kBackendSendmsg;
      else if (!std::strcmp(s, "uring")) v = kBackendUring;
      // anything else (incl. "auto") stays auto: utils/config.py
      // already failed loudly on invalid spellings at bridge init
    }
    g_wire_backend.store(v, std::memory_order_relaxed);
  }
  return v;
}

// ACTIVE backend after resolution: uring only when explicitly
// requested (directly or by the calibrator writing the fitted arm
// through set_wire_backend) AND the kernel probe passed.  The loud
// degrade for an explicit-but-unsupported request prints once.
bool uring_active() {
  int m = wire_backend_mode();
  if (m != kBackendUring) return false;
  if (uring_supported()) return true;
  if (!g_uring_degrade_logged.exchange(true)) {
    std::fprintf(stderr,
                 "r%d | t4j: T4J_WIRE_BACKEND=uring requested but this "
                 "kernel has no usable io_uring — degrading to the sendmsg "
                 "backend (docs/performance.md \"io_uring wire backend\")\n",
                 g_rank);
    std::fflush(stderr);
  }
  return false;
}

#if T4J_HAVE_URING
// Engine-thread completion-driven idle wait: when the uring backend
// is active the engine's idle cv.wait_for becomes an io_uring_enter
// wait on a persistent POLL_ADD over this eventfd, and every notifier
// that would have signalled the engine's condvars also pokes it.  The
// wait is tick-bounded, so a poke lost to the (tiny) park-flag race
// costs one tick, never a hang — the same bound the condvar ticks
// gave.  The evfd is deliberately leaked at engine exit: closing it
// while a racing poker holds the fd number could hit a recycled fd.
std::atomic<int> g_engine_evfd{-1};
std::atomic<bool> g_engine_parked{false};
#endif

void poke_engine() {
#if T4J_HAVE_URING
  if (!g_engine_parked.load(std::memory_order_relaxed)) return;
  int fd = g_engine_evfd.load(std::memory_order_relaxed);
  if (fd < 0) return;
  uint64_t one = 1;
  (void)!::write(fd, &one, sizeof(one));
#endif
}

// ---------------------------------------------- compressed wire dtype
//
// Low-precision wire dtypes for the segmented ring / hier-leader
// collectives (docs/performance.md "Compressed collectives"): f32 SUM
// payloads travel as bf16 or fp8(e4m3) on cross-host hops while the
// accumulation and the user-visible result stay f32.  The downcast
// lands in the wire staging buffer the send engine uses directly as
// the frame payload (so with healing the replay arena copies — and
// replays — the already-compressed bytes), and the upcast is fused
// into the recv-combine fold: one pass either side, and compressed
// segments are just smaller frames to the striping / self-heal /
// telemetry machinery.  -1 = "not set yet"; Python validates via
// utils/config.py and calls set_wire_dtype, the env parse is the
// fallback for hand-run processes.

constexpr int kWireOff = 0, kWireBf16 = 1, kWireFp8 = 2;

std::atomic<int> g_wire_dtype{-1};
// Cumulative logical (f32) vs wire (compressed) bytes over the
// compressed send path: the provable byte saving for t4j-top /
// t4j-diagnose.  Stay 0 while the mode is off.
std::atomic<unsigned long long> g_wire_logical_bytes{0};
std::atomic<unsigned long long> g_wire_comp_bytes{0};

int wire_dtype_mode() {
  int v = g_wire_dtype.load(std::memory_order_relaxed);
  if (v < 0) {
    const char* s = std::getenv("T4J_WIRE_DTYPE");
    v = kWireOff;
    if (s && s[0]) {
      if (!std::strcmp(s, "bf16")) v = kWireBf16;
      else if (!std::strcmp(s, "fp8")) v = kWireFp8;
      // anything else (incl. "off") stays off: utils/config.py
      // already failed loudly on invalid spellings at bridge init
    }
    g_wire_dtype.store(v, std::memory_order_relaxed);
  }
  return v;
}

// Bytes per wire element (logical element is always 4-byte f32).
inline size_t wire_elem_size(int wdt) { return wdt == kWireBf16 ? 2 : 1; }

// f32 -> bf16, round-to-nearest-even, NaN quieted (the standard
// truncation-with-rounding trick: add 0x7fff plus the LSB of the
// result mantissa, then take the high half).
inline uint16_t f32_to_bf16(float f) {
  uint32_t u;
  std::memcpy(&u, &f, 4);
  if ((u & 0x7fffffffu) > 0x7f800000u)  // NaN: quiet, keep sign
    return static_cast<uint16_t>((u >> 16) | 0x0040);
  u += 0x7fffu + ((u >> 16) & 1u);
  return static_cast<uint16_t>(u >> 16);
}

inline float bf16_to_f32(uint16_t h) {
  uint32_t u = static_cast<uint32_t>(h) << 16;
  float f;
  std::memcpy(&f, &u, 4);
  return f;
}

// f32 -> fp8 e4m3 (OCP E4M3: bias 7, no infinities, 0x7f mantissa
// pattern is NaN, max finite 448).  Saturating: |x| > 448 (incl. inf)
// clamps to +-448.  Subnormal quantum is 2^-9; round-to-nearest-even
// throughout.
inline uint8_t f32_to_fp8(float f) {
  uint32_t u;
  std::memcpy(&u, &f, 4);
  uint8_t sign = static_cast<uint8_t>((u >> 24) & 0x80u);
  uint32_t abs = u & 0x7fffffffu;
  if (abs > 0x7f800000u) return static_cast<uint8_t>(sign | 0x7f);  // NaN
  float af;
  std::memcpy(&af, &abs, 4);
  if (af > 448.0f) return static_cast<uint8_t>(sign | 0x7e);  // saturate
  int e = static_cast<int>(abs >> 23) - 127;
  if (e < -6) {
    // subnormal range [0, 2^-6): quantize to multiples of 2^-9; a
    // value rounding up to 2^-6 rolls naturally into code 8, the
    // first normal
    int q = static_cast<int>(lrintf(af * 512.0f));
    return static_cast<uint8_t>(sign | static_cast<uint8_t>(q));
  }
  // normal: RNE into the 3-bit mantissa, re-derive the exponent (the
  // round can carry into it), then pack biased-by-7
  uint32_t r = abs + 0x7ffffu + ((abs >> 20) & 1u);
  e = static_cast<int>(r >> 23) - 127;
  uint32_t m = (r >> 20) & 7u;
  return static_cast<uint8_t>(sign |
                              static_cast<uint8_t>(((e + 7) << 3) | m));
}

// fp8 e4m3 -> f32 through a 256-entry LUT (magic-static init).
inline const float* fp8_lut() {
  static const float* table = [] {
    static float t[256];
    for (int b = 0; b < 256; ++b) {
      int e = (b >> 3) & 0xf;
      int m = b & 7;
      float v;
      if (e == 0)
        v = static_cast<float>(m) * 0x1p-9f;  // subnormals (and +-0)
      else if (e == 15 && m == 7)
        v = std::numeric_limits<float>::quiet_NaN();
      else
        v = ldexpf(static_cast<float>(8 + m), e - 10);  // (8+m)*2^(e-7-3)
    t[b] = (b & 0x80) ? -v : v;
    }
    return t;
  }();
  return table;
}

inline float fp8_to_f32(uint8_t b) { return fp8_lut()[b]; }

// One-pass batch downcast into the wire staging buffer.
void downcast_wire(int wdt, const float* in, uint8_t* out, size_t n) {
  if (wdt == kWireBf16) {
    uint16_t* o = reinterpret_cast<uint16_t*>(out);
    for (size_t i = 0; i < n; ++i) o[i] = f32_to_bf16(in[i]);
  } else {
    for (size_t i = 0; i < n; ++i) out[i] = f32_to_fp8(in[i]);
  }
}

// Fused upcast+combine: acc[i] = local[i] + upcast(wire[i]).  acc may
// alias local (the in-place hier leader ring).
void upcast_add_wire(int wdt, const float* local, const uint8_t* wire,
                     float* acc, size_t n) {
  if (wdt == kWireBf16) {
    const uint16_t* w = reinterpret_cast<const uint16_t*>(wire);
    for (size_t i = 0; i < n; ++i) acc[i] = local[i] + bf16_to_f32(w[i]);
  } else {
    const float* lut = fp8_lut();
    for (size_t i = 0; i < n; ++i) acc[i] = local[i] + lut[wire[i]];
  }
}

// Upcast-while-copying (the allgather phase of a compressed ring).
void upcast_copy_wire(int wdt, const uint8_t* wire, float* dst,
                      size_t n) {
  if (wdt == kWireBf16) {
    const uint16_t* w = reinterpret_cast<const uint16_t*>(wire);
    for (size_t i = 0; i < n; ++i) dst[i] = bf16_to_f32(w[i]);
  } else {
    const float* lut = fp8_lut();
    for (size_t i = 0; i < n; ++i) dst[i] = lut[wire[i]];
  }
}

// ---------------------------------------------- hierarchical tuning
//
// Selection knobs for the two-tier (shm leaf + leader ring) path
// (docs/performance.md "hierarchical collectives").  -1 = "not set
// yet"; Python validates via utils/config.py and calls set_hier
// before init, the env parse is the fallback for hand-run processes.

constexpr int kHierAuto = 0, kHierOn = 1, kHierOff = 2;

std::atomic<int> g_hier_mode{-1};
std::atomic<long long> g_leader_ring_min_bytes{-1};

constexpr long long kDefaultLeaderRingMinBytes = 256 << 10;  // 256 KiB

int hier_mode() {
  int v = g_hier_mode.load(std::memory_order_relaxed);
  if (v < 0) {
    const char* s = std::getenv("T4J_HIER");
    v = kHierAuto;
    if (s && s[0]) {
      if (!std::strcmp(s, "on")) v = kHierOn;
      else if (!std::strcmp(s, "off")) v = kHierOff;
      // anything else keeps auto; utils/config.py rejects loudly
    }
    g_hier_mode.store(v, std::memory_order_relaxed);
  }
  return v;
}

long long leader_ring_min_bytes() {
  long long v = g_leader_ring_min_bytes.load(std::memory_order_relaxed);
  if (v < 0) {
    v = env_bytes("T4J_LEADER_RING_MIN_BYTES", kDefaultLeaderRingMinBytes);
    g_leader_ring_min_bytes.store(v, std::memory_order_relaxed);
  }
  return v;
}

// ------------------------------------------------- resilience tuning
//
// Self-healing DCN transport (docs/failure-semantics.md "self-healing
// transport"): every TCP peer link carries sequence-numbered frames
// backed by a bounded replay ring, and a broken connection is re-dialed
// with exponential backoff + jitter instead of faulting the job.  The
// escalation ladder is retry -> reconnect+replay -> abort; abort (the
// PR-1 fail-stop path, unchanged) remains the backstop for genuinely
// dead peers.  -1 = "not set yet"; Python validates via utils/config.py
// and calls set_resilience before init, the env parse is the fallback
// for hand-run processes.

std::atomic<int> g_retry_max{-1};
std::atomic<double> g_backoff_base_s{-1.0};
std::atomic<double> g_backoff_max_s{-1.0};
std::atomic<long long> g_replay_bytes{-1};

constexpr int kDefaultRetryMax = 3;
constexpr double kDefaultBackoffBase = 0.05;
constexpr double kDefaultBackoffMax = 2.0;
// Large enough that the bytes lost in flight on a drop (bounded by the
// two kernel socket buffers, ~8 MB each when pinned) always fit the
// ring; docs/performance.md covers the per-peer memory cost.
constexpr long long kDefaultReplayBytes = 32ll << 20;

long long env_bytes(const char* name, long long dflt);

long long env_int(const char* name, long long dflt) {
  const char* s = std::getenv(name);
  if (!s || !s[0]) return dflt;
  char* end = nullptr;
  long long v = std::strtoll(s, &end, 10);
  if (end == s || *end != '\0' || v < 0) return dflt;  // Python is loud
  return v;
}

int retry_max() {
  int v = g_retry_max.load(std::memory_order_relaxed);
  if (v < 0) {
    v = static_cast<int>(env_int("T4J_RETRY_MAX", kDefaultRetryMax));
    g_retry_max.store(v, std::memory_order_relaxed);
  }
  return v;
}

double backoff_base_s() {
  double v = g_backoff_base_s.load(std::memory_order_relaxed);
  if (v < 0) {
    v = env_seconds("T4J_BACKOFF_BASE", kDefaultBackoffBase);
    if (v <= 0) v = kDefaultBackoffBase;
    g_backoff_base_s.store(v, std::memory_order_relaxed);
  }
  return v;
}

double backoff_max_s() {
  double v = g_backoff_max_s.load(std::memory_order_relaxed);
  if (v < 0) {
    v = env_seconds("T4J_BACKOFF_MAX", kDefaultBackoffMax);
    if (v <= 0) v = kDefaultBackoffMax;
    g_backoff_max_s.store(v, std::memory_order_relaxed);
  }
  return v;
}

long long replay_bytes() {
  long long v = g_replay_bytes.load(std::memory_order_relaxed);
  if (v < 0) {
    v = env_bytes("T4J_REPLAY_BYTES", kDefaultReplayBytes);
    g_replay_bytes.store(v, std::memory_order_relaxed);
  }
  return v;
}

bool resilience_on() { return retry_max() > 0 && g_size > 1; }

// --------------------------------------------- elastic membership knobs
//
// T4J_ELASTIC=off|shrink|rejoin (docs/failure-semantics.md "elastic
// membership"): what happens when a rank is declared unrecoverable.
// off (the default) keeps today's exact abort path; shrink lets the
// survivors agree on a reduced world and continue; rejoin additionally
// keeps the bootstrap coordinator port open so a relaunched
// replacement can re-bootstrap into the mesh at the next epoch fence.
// T4J_MIN_WORLD floors the shrink (below it the legacy abort fires);
// T4J_RESIZE_TIMEOUT bounds each agreement/rebuild phase.  -1 = "not
// set yet"; Python validates via utils/config.py and calls
// set_elastic before init, the env parse is the fallback for hand-run
// processes.

constexpr int kElasticOff = 0, kElasticShrink = 1, kElasticRejoin = 2;

std::atomic<int> g_elastic_mode{-1};
std::atomic<int> g_min_world{-1};
std::atomic<double> g_resize_timeout_s{-1.0};

int elastic_mode() {
  int v = g_elastic_mode.load(std::memory_order_relaxed);
  if (v < 0) {
    const char* s = std::getenv("T4J_ELASTIC");
    v = kElasticOff;
    if (s && s[0]) {
      if (!std::strcmp(s, "shrink")) v = kElasticShrink;
      else if (!std::strcmp(s, "rejoin")) v = kElasticRejoin;
      // anything else keeps off; utils/config.py rejects loudly
    }
    g_elastic_mode.store(v, std::memory_order_relaxed);
  }
  return v;
}

int min_world() {
  int v = g_min_world.load(std::memory_order_relaxed);
  if (v < 1) {
    v = static_cast<int>(env_int("T4J_MIN_WORLD", 1));
    if (v < 1) v = 1;
    g_min_world.store(v, std::memory_order_relaxed);
  }
  return v;
}

double resize_timeout() {
  double v = g_resize_timeout_s.load(std::memory_order_relaxed);
  if (v <= 0) {
    v = env_seconds("T4J_RESIZE_TIMEOUT", 30.0);
    if (v <= 0) v = 30.0;
    g_resize_timeout_s.store(v, std::memory_order_relaxed);
  }
  return v;
}

// -------------------------------------------- elastic membership state
//
// The world's rank-id space stays the BOOTSTRAP space for the whole
// job (g_rank/g_size never change; g_peers/g_endpoints keep their
// indexing).  Membership is the alive mask: a resize flips bits off
// (shrink) or back on (rejoin) and bumps the world epoch.  The epoch
// is stamped into every wire frame so traffic from a previous
// membership can never be delivered into the resized world.

std::atomic<uint32_t> g_world_epoch{0};
std::atomic<uint64_t> g_alive_mask{0};
std::atomic<bool> g_resizing{false};
// wire context of the (rebuilt) world communicator: 0 at bootstrap, a
// per-epoch derived id after a resize so old-world collective frames
// can never match new-world receives even before the epoch check
int g_world_ctx = 0;

int popcount64(uint64_t v) {
  int n = 0;
  while (v) {
    v &= v - 1;
    ++n;
  }
  return n;
}

int alive_count() {
  if (g_size > 64) return g_size;  // elastic disabled: nobody leaves
  return popcount64(g_alive_mask.load(std::memory_order_relaxed));
}

bool rank_alive(int r) {
  if (r < 0 || r >= g_size) return false;
  if (r >= 64) return true;  // beyond the mask, elastic is disabled
  return (g_alive_mask.load(std::memory_order_relaxed) >> r) & 1;
}

// Elastic escalation is reachable at all only when the self-healing
// layer is on (escalation IS its last rung) and the membership fits
// the u64 agreement mask.
bool elastic_usable() {
  return elastic_mode() != kElasticOff && g_size > 1 && g_size <= 64 &&
         resilience_on() &&
         !g_shutting_down.load(std::memory_order_acquire) &&
         !g_finalizing.load(std::memory_order_acquire) &&
         !g_faulted.load(std::memory_order_acquire);
}

// Exponential backoff with +/-25% jitter for reconnect attempt
// `attempt` (0-based), capped at T4J_BACKOFF_MAX.  Jitter keeps the
// two ends of a broken link (and many links broken by one NIC blip)
// from re-dialing in lockstep.
double backoff_delay_s(int attempt) {
  double d = backoff_base_s() * std::ldexp(1.0, attempt);
  double cap = backoff_max_s();
  if (d > cap) d = cap;
  static thread_local std::mt19937_64 rng(
      std::random_device{}() ^
      std::hash<std::thread::id>{}(std::this_thread::get_id()));
  std::uniform_real_distribution<double> jitter(0.75, 1.25);
  return d * jitter(rng);
}

// Worst-case wall time of the dialer's full retry ladder: the passive
// (accepting) side of a broken link waits this long for the peer's
// re-dial before escalating, so an idle acceptor can never sit broken
// forever.  In-flight ops additionally enforce their own
// T4J_OP_TIMEOUT, whichever fires first.
double repair_budget_s() {
  double s = 0;
  int n = retry_max();
  for (int i = 0; i < n; ++i) {
    double d = backoff_base_s() * std::ldexp(1.0, i);
    double cap = backoff_max_s();
    s += (d > cap ? cap : d) * 1.25;  // jitter headroom
  }
  // every attempt can spend TWO connect windows — the dial itself and
  // a fresh hello/reply handshake deadline — so budget both, plus one
  // spare: the watchdog must never expire while a legitimate
  // last-attempt repair is still making progress (replay needs no
  // extra term: the state flips to kUp before replay starts, which
  // ends the watchdog's wait)
  return s + (2 * n + 1) * connect_timeout() + 5.0;
}

// Init-phase ops (the bootstrap barrier, the shm-pipe agreement rounds)
// are bounded by the CONNECT deadline, not the per-op one: rank startup
// skew (python imports, jit warmup) legitimately exceeds a sub-second
// T4J_OP_TIMEOUT, and tripping there would make tight deadlines unusable.
std::atomic<bool> g_in_init{false};

double effective_op_timeout() {
  double v = op_timeout();
  if (v > 0 && g_in_init.load(std::memory_order_relaxed)) {
    double c = connect_timeout();
    if (v < c) v = c;
  }
  return v;
}

// Name the knob that set the enforced deadline, so error messages
// report the limit that actually fired (during init the op deadline is
// widened to the connect one).
const char* deadline_knob() {
  if (g_in_init.load(std::memory_order_relaxed) &&
      connect_timeout() > op_timeout())
    return "T4J_CONNECT_TIMEOUT, init phase";
  return "T4J_OP_TIMEOUT";
}

using Clock = std::chrono::steady_clock;

// Absolute deadline; limit_s <= 0 means unbounded.
struct Deadline {
  bool bounded = false;
  Clock::time_point at{};

  static Deadline after(double limit_s) {
    Deadline d;
    if (limit_s > 0) {
      d.bounded = true;
      d.at = Clock::now() + std::chrono::duration_cast<Clock::duration>(
                                std::chrono::duration<double>(limit_s));
    }
    return d;
  }
  bool expired() const { return bounded && Clock::now() >= at; }
  int remaining_ms(int tick_ms) const {
    if (!bounded) return tick_ms;
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    at - Clock::now())
                    .count();
    if (left <= 0) return 0;
    return left < tick_ms ? static_cast<int>(left) : tick_ms;
  }
};

// Sleep `s` seconds in 50ms ticks, bailing early when the bridge
// stops.  Returns false when stopped.
bool backoff_sleep(double s) {
  Deadline dl = Deadline::after(s);
  while (!dl.expired()) {
    if (g_stop.load(std::memory_order_acquire)) return false;
    std::this_thread::sleep_for(
        std::chrono::milliseconds(dl.remaining_ms(50)));
  }
  return !g_stop.load(std::memory_order_acquire);
}

std::string call_id() {
  // 8-char random id, matching the reference's debug-log wire format
  // (mpi_xla_bridge.pyx:35-60).
  static thread_local std::mt19937_64 rng(
      std::random_device{}() ^
      std::hash<std::thread::id>{}(std::this_thread::get_id()));
  static const char alnum[] =
      "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789";
  std::string s(8, 'x');
  for (auto& c : s) c = alnum[rng() % (sizeof(alnum) - 1)];
  return s;
}

struct LogScope {
  std::string id;
  std::string op;
  std::chrono::steady_clock::time_point start;
  bool active;
  const char* prev_op;  // restored on exit (ops can nest, e.g.
                        // allreduce -> reduce + bcast)

  // Wire format follows the reference's bridge
  // (mpi_xla_bridge.pyx:47-52, 95-450): stdout, "r{rank} | {8-char id} |
  // MPI_<Op> <detail>" then "... | MPI_<Op> done with code 0 (1.23e-04s)".
  // Detail quantities are in bytes where this layer works on bytes (the
  // reference's Cython layer sees item counts; the FFI handlers here
  // only carry counts for reductions).
  LogScope(const char* op_, const std::string& detail) : op(op_),
                                                         active(g_logging) {
    prev_op = tls_op;
    tls_op = op.c_str();  // error-message context even when not logging
    if (!active) return;
    id = call_id();
    start = std::chrono::steady_clock::now();
    if (detail.empty())
      std::fprintf(stdout, "r%d | %s | %s\n", g_rank, id.c_str(), op.c_str());
    else
      std::fprintf(stdout, "r%d | %s | %s %s\n", g_rank, id.c_str(),
                   op.c_str(), detail.c_str());
    std::fflush(stdout);
  }
  ~LogScope() {
    tls_op = prev_op;
    if (!active) return;
    double secs = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - start)
                      .count();
    std::fprintf(stdout, "r%d | %s | %s done with code 0 (%.2es)\n", g_rank,
                 id.c_str(), op.c_str(), secs);
    std::fflush(stdout);
  }
};

void wake_all_pipes();  // defined after the pipe globals
void wake_async_engine();  // defined with the async progress engine

// Record the first failure, print it once, and wake every blocked
// waiter (mailbox condvar, shm pipes) so they observe g_stop and bail.
// Reader threads call this when they detect a dead/garbled peer; op
// threads call it (via fail_op) just before throwing.
void post_fault(const std::string& msg) {
  bool first = false;
  {
    std::lock_guard<std::mutex> lk(g_fault_mu);
    if (!g_faulted.load(std::memory_order_acquire)) {
      g_fault_msg = msg;
      g_faulted.store(true, std::memory_order_release);
      first = true;
    }
  }
  if (first) tel::control_event(tel::kFault, -1, 0);
  g_stop.store(true, std::memory_order_release);
  if (first && !g_finalizing.load(std::memory_order_acquire)) {
    std::fprintf(stderr, "%s\n", msg.c_str());
    std::fflush(stderr);
  }
  wake_all_pipes();
  // the progress thread and any async waiters must observe the stop
  // and drain their queued/parked requests as failed
  wake_async_engine();
}

std::string posted_fault_msg() {
  std::lock_guard<std::mutex> lk(g_fault_mu);
  return g_fault_msg;
}

// Contextual message for an op interrupted by an elastic resize: the
// marker string "ResizeInterrupted" is the contract the Python tier
// (native/runtime.py) keys on to convert the failure into
// WorldResized instead of a fatal BridgeError.
std::string resize_interrupted_msg() {
  return err_prefix() + std::string(cur_op()) +
         ": interrupted by elastic world resize (epoch " +
         std::to_string(g_world_epoch.load(std::memory_order_relaxed) + 1) +
         " forming) — ResizeInterrupted: the op did not complete and "
         "must be reissued on the resized world "
         "(docs/failure-semantics.md \"elastic membership\")";
}

// The bridge stopped under us (fault posted elsewhere, a resize in
// progress, or finalize): throw the recorded context so Python sees
// WHY, not just "stuck".
[[noreturn]] void raise_stopped() {
  if (g_resizing.load(std::memory_order_acquire) &&
      !g_faulted.load(std::memory_order_acquire))
    throw BridgeError(resize_interrupted_msg());
  std::string m = posted_fault_msg();
  if (m.empty())
    m = err_prefix() + std::string(cur_op()) +
        ": bridge already shut down";
  throw BridgeError(m);
}

void broadcast_abort(const std::string& why);  // after transport globals

// Op-context failure on THIS rank: tell the peers (so their blocked
// collectives raise instead of hanging), record the fault, throw.
[[noreturn]] void fail_op(const std::string& what) {
  std::string msg = err_prefix() + std::string(cur_op()) + ": " + what;
  broadcast_abort(msg);
  post_fault(msg);
  throw BridgeError(msg);
}

// Invariant/argument errors (bad handle, unknown dtype, rank range):
// no abort broadcast — the job state is fine, only this call is wrong.
[[noreturn]] void fail_arg(const std::string& what) {
  throw BridgeError(err_prefix() + std::string(cur_op()) + ": " + what);
}

// ------------------------------------------------------------- transport

// Frame payload: default-initialised allocation (new[] without parens
// does not zero) — a std::vector resize() value-initialises, which for
// large frames adds a full memset pass per hop.
struct Buf {
  std::unique_ptr<uint8_t[]> p;
  size_t n = 0;

  Buf() = default;
  explicit Buf(size_t nbytes)
      : p(nbytes ? new uint8_t[nbytes] : nullptr), n(nbytes) {}

  uint8_t* data() { return p.get(); }
  const uint8_t* data() const { return p.get(); }
  size_t size() const { return n; }
};

struct Frame {
  int src;
  int ctx;
  int tag;
  Buf data;
};

constexpr uint32_t kMagic = 0x7446a003;  // bumped: header gained epoch

struct WireHeader {
  uint32_t magic;
  uint32_t src;
  uint32_t ctx;
  uint32_t tag;  // tag + 1 so ANY(-1) never travels
  uint64_t nbytes;
  // Per-link data-frame sequence number (1-based; 0 = unsequenced:
  // control frames, shm-pipe frames, self-delivery).  Receivers drop
  // seq <= last-delivered, which is what makes the reconnect replay
  // idempotent (docs/failure-semantics.md "self-healing transport").
  uint64_t seq;
  // World epoch the frame was built in (docs/failure-semantics.md
  // "elastic membership"): receivers drop data frames whose epoch is
  // not the current one, so traffic interrupted by a resize can never
  // be delivered into the resized world.  Abort control frames pass
  // regardless (a rank aborting mid-resize must still be heard).
  uint32_t epoch;
  uint32_t pad;
};
static_assert(sizeof(WireHeader) == 40, "wire header layout");

uint32_t cur_epoch() {
  return g_world_epoch.load(std::memory_order_relaxed);
}

// Frames a resize dropped for carrying a stale world epoch (pure
// diagnostic; the drop itself is the correctness mechanism).
std::atomic<uint64_t> g_stale_frames{0};

// Reserved wire ctx for abort control frames.  Real channels are
// enc_ctx(ctx30bit) <= 2^31, so this value can never collide.
constexpr uint32_t kAbortCtx = 0xFFFFFFFFu;

// Reconnect handshake (first bytes on a re-dialed connection; the
// bootstrap mesh phase sends a bare rank u32, and the two can never be
// confused because reconnects only arrive after bootstrap completed).
constexpr uint32_t kReconMagic = 0x7446b001;

struct ReconHello {
  uint32_t magic;
  uint32_t rank;        // dialer's world rank
  uint64_t boot_token;  // dialer's bootstrap incarnation token
  uint32_t epoch;       // dialer's view of the STRIPE epoch
  uint32_t pad;         // stripe index being re-dialed
  uint64_t last_recv_seq;  // link-level received watermark
                           // (link_recv_watermark)
};
static_assert(sizeof(ReconHello) == 32, "recon hello layout");

struct ReconReply {
  uint32_t magic;
  uint32_t ok;          // 1 accept, 0 reject (identity/epoch mismatch)
  uint64_t boot_token;  // acceptor's incarnation token
  uint32_t epoch;
  uint32_t pad;
  uint64_t last_recv_seq;
};
static_assert(sizeof(ReconReply) == 32, "recon reply layout");

// Elastic-membership control messages (docs/failure-semantics.md
// "elastic membership"): out-of-band 32-byte frames on FRESH dials to
// a peer's mesh listener (or, for kRejoinHello, to rank 0's kept-open
// bootstrap coordinator port), so the agreement never depends on the
// possibly-torn data-plane byte streams.  Same first-4-bytes-magic
// discipline as ReconHello — the reconnect acceptor branches on it.
constexpr uint32_t kResizeMagic = 0x7446d001;

enum ResizeMsgType : uint32_t {
  kResizeReport = 1,   // mask = sender's suspected-dead set
  kResizeVerdict = 2,  // mask = final ALIVE set (0 = abort the job)
  kResizeDial = 3,     // link-rebuild handshake at `epoch`
  kResizeAck = 4,      // dial reply; mask = 1 accept, 0 reject
  kRejoinHello = 5,    // replacement process -> coordinator; +PeerAddr
  kResizeGrow = 6,     // verdict adding `rank` back; +PeerAddr payload
};

struct ResizeMsg {
  uint32_t magic;
  uint32_t type;   // ResizeMsgType
  uint32_t rank;   // sender's world rank (kResizeGrow: the rejoiner)
  uint32_t epoch;  // epoch the message proposes / targets
  uint64_t mask;   // see ResizeMsgType
  uint64_t token;  // sender's bootstrap incarnation token
};
static_assert(sizeof(ResizeMsg) == 32, "resize msg layout");

// Defined with the resize engine (end of this namespace): the
// reconnect acceptor and the link-escalation path call into them.
bool try_begin_resize(int peer, const std::string& why);
void enter_resize(uint64_t dead_delta, const std::string& why);
void handle_resize_msg(int fd, const ResizeMsg& m);

// A sent frame retained for replay-after-reconnect: the payload lives
// at `off` inside the stripe's circular replay arena (never split
// across the wrap point).  zc_id: nonzero when the frame was sent
// with MSG_ZEROCOPY — the kernel may still be reading the arena bytes
// until completion id zc_id-1 is reaped, so eviction/overwrite must
// wait for it (docs/sharp-bits.md "MSG_ZEROCOPY pins pages").
struct Replay {
  WireHeader h;
  size_t off;
  uint32_t zc_id = 0;  // kernel zerocopy completion id + 1; 0 = none
};

// One TCP connection of a (possibly striped) peer link, with its own
// self-healing state (docs/failure-semantics.md "self-healing
// transport", docs/performance.md "striped links").  Lock order:
// send_mu before mu; never the reverse.
#if T4J_HAVE_URING
// Per-stripe io_uring send context, guarded by the stripe's send_mu
// (one submitter).  The msghdr/iovec arrays are stable storage for
// SQEs between submit and completion — stripe_write never returns
// with SQEs in flight, so their lifetime is one stripe_write call.
struct UringSendCtx {
  UringRing ring;
  bool ok = false;        // ring opened
  bool fixed_ok = true;   // WRITE_FIXED/registered-arena path usable
  std::vector<msghdr> mhs;
  std::vector<iovec> iovs;
};
#endif

struct Stripe {
  int fd = -1;
  std::mutex send_mu;  // serialises writers on fd (and fd swaps)

  // --- connection state, guarded by mu --------------------------------
  std::mutex mu;
  std::condition_variable cv;  // signalled on every state change
  enum State { kUp = 0, kBroken = 1, kDead = 2 };
  State state = kUp;
  uint32_t epoch = 0;     // bumped on every successful reconnect
  bool repairing = false; // a dial/watchdog thread owns the break

  // Current reader thread for this stripe's fd.  join_mu serialises
  // join/assign of `reader` between a repair handler and finalize;
  // accept_busy serialises concurrent reconnect dials for the same
  // stripe (handlers run on their own threads).
  std::thread reader;
  std::mutex join_mu;
  std::atomic<bool> accept_busy{false};

  // --- send side, guarded by send_mu ----------------------------------
  // The replay ring is a single preallocated circular byte arena plus
  // an entry deque — per-frame heap Bufs would pay an mmap + kernel
  // zero-fill + munmap cycle per large frame, which measured ~30%
  // busbw on the loopback box.  Seqs are the LINK's namespace (this
  // stripe holds a round-robin subset); after a stripe migration the
  // deque is no longer seq-sorted, so eviction-loss detection tracks
  // the max seq ever evicted instead of a contiguous floor.
  std::deque<Replay> ring;
  std::unique_ptr<uint8_t[]> ring_buf;
  size_t ring_cap = 0;
  size_t ring_head = 0;          // next write offset into ring_buf
  uint64_t max_evicted_seq = 0;  // highest seq evicted from the ring

  // Set (under send_mu) when escalate_stripe migrated this dead
  // stripe's ring onto a sibling: anything appended AFTER that has no
  // redelivery path here — senders must redeal instead of buffering.
  bool migrated = false;

  // --- MSG_ZEROCOPY accounting, guarded by send_mu ---------------------
  bool zc_enabled = false;  // SO_ZEROCOPY accepted on this fd
  uint32_t zc_sent = 0;     // completion ids issued (next id == zc_sent)
  uint32_t zc_done = 0;     // ids [0, zc_done) reaped from the errqueue

  // --- emulated per-flow throttle, guarded by send_mu ------------------
  double tb_tokens = 0;
  Clock::time_point tb_last{};

  // --- recv side: highest link seq seen on this stripe (diagnostics;
  // delivery order lives on the link's reorder stage) ------------------
  std::atomic<uint64_t> seen_seq{0};

  // --- stats (t4j_link_stats / t4j_link_stripe_stats) ------------------
  std::atomic<uint64_t> reconnects{0};
  std::atomic<uint64_t> replayed_frames{0};
  std::atomic<uint64_t> replayed_bytes{0};
  // Data-plane kernel crossings on this stripe (see tls_syscall_ctr):
  // the numerator of the syscalls-per-frame metric, both backends.
  std::atomic<uint64_t> tx_syscalls{0};
  std::atomic<uint64_t> rx_syscalls{0};

#if T4J_HAVE_URING
  // io_uring send context, lazily built under send_mu on the first
  // uring-backend write on this stripe (and after every fd swap the
  // registered arena simply re-registers — registration is per-ring,
  // not per-fd, so reconnects need no special casing).
  std::unique_ptr<UringSendCtx> uring;
#endif

  // A process exiting WITHOUT finalize (a fault raised through user
  // code that never reaches the atexit hook) must not std::terminate
  // in the joinable-thread destructor and mask the real exit code.
  ~Stripe() {
    if (reader.joinable()) reader.detach();
  }
};

// Per-peer link: N stripes plus the link-level dealing and delivery
// state that keeps striping invisible to MPI matching.  Frames get a
// link-global sequence number under deal_mu and are dealt round-robin
// over the non-dead stripes; the receive side restores per-link order
// under ro_mu (frames from a fast stripe park in `reorder` until the
// gap fills).  Lock order: deal_mu / ro_mu are leaf locks relative to
// stripe locks EXCEPT ro_mu -> g_mail_mu (delivery).
struct PeerLink {
  std::unique_ptr<Stripe[]> s;  // built stripes (TCP peers; empty for self)
  int nstripes = 0;
  std::mutex pipe_mu;  // one producer per same-host shm pipe

  // --- send dealing, guarded by deal_mu --------------------------------
  std::mutex deal_mu;
  uint64_t send_seq = 0;  // last assigned outbound link seq
  uint64_t dealt = 0;     // round-robin cursor over live stripes
  // relaxed mirror of the stripes' kDead verdicts so dealing can skip
  // dead stripes without taking their mutexes
  std::atomic<uint32_t> dead_mask{0};

  // --- delivery order, guarded by ro_mu --------------------------------
  std::mutex ro_mu;
  uint64_t delivered = 0;            // last contiguous seq delivered
  std::map<uint64_t, Frame> reorder; // early frames from fast stripes

  void alloc_stripes(int n) {
    nstripes = n < 1 ? 1 : n;
    s.reset(new Stripe[nstripes]);
    dead_mask.store(0, std::memory_order_relaxed);
  }
  bool link_dead() const {
    uint32_t m = dead_mask.load(std::memory_order_relaxed);
    return nstripes > 0 &&
           m == ((nstripes >= 32 ? ~0u : ((1u << nstripes) - 1)));
  }
};

// leaked: see the g_fault_mu comment (detached readers/repair threads)
std::vector<PeerLink>& g_peers = *new std::vector<PeerLink>;

// Re-dial targets: every rank's mesh-listener address plus its
// bootstrap incarnation token (a fresh random id per process, carried
// in the coordinator table).  A peer that re-dials with a token other
// than the one bootstrap recorded is a RESTARTED process — its mailbox
// and comm state are gone, so recovery is impossible and the handshake
// escalates to abort.
struct PeerEndpoint {
  std::string host;
  uint16_t port = 0;
  uint64_t boot_token = 0;
};

// leaked: repair dialers read it from detached threads
std::vector<PeerEndpoint>& g_endpoints = *new std::vector<PeerEndpoint>;
uint64_t g_my_boot_token = 0;
int g_listen_fd = -1;  // mesh listener, kept open for reconnects
// Bootstrap coordinator listener: rank 0 keeps it open for the job's
// lifetime when T4J_ELASTIC=rejoin, so a relaunched replacement
// process can re-bootstrap into the mesh (docs/failure-semantics.md
// "elastic membership").  -1 everywhere else.
int g_coord_listen_fd = -1;
void coord_accept_loop();  // defined with the resize engine

// Reader threads are joined in finalize(); detach-on-destruction for
// the same abnormal-exit reason as PeerLink::reader.
struct ThreadList {
  std::vector<std::thread> v;
  ~ThreadList() {
    for (auto& t : v)
      if (t.joinable()) t.detach();
  }
  void join_all() {
    for (auto& t : v)
      if (t.joinable()) t.join();
    v.clear();
  }
};

ThreadList g_accept_thread;  // 0 or 1 entries: the reconnect acceptor

// Same-host p2p fast path: frames to same-host peers ride SPSC shm
// byte pipes in the same wire format as the sockets (shm.h), drained
// by one reader thread per source into the same mailbox — matching
// semantics and per-pair ordering are exactly the TCP tier's.  ALL
// frames for a pair use one transport, so ordering can never split.
shm::PipeSeg* g_my_pipes = nullptr;
// leaked: wake_all_pipes runs from post_fault on detached threads
std::vector<shm::Pipe*>& g_tx_pipes = *new std::vector<shm::Pipe*>;
ThreadList g_pipe_readers;

// leaked: reader threads push frames until the instant they exit
std::mutex& g_mail_mu = *new std::mutex;
std::condition_variable& g_mail_cv = *new std::condition_variable;
std::deque<Frame>& g_mailbox = *new std::deque<Frame>;

// Guards PUBLICATION and TEARDOWN of g_my_pipes/g_tx_pipes against
// wake_all_pipes: a reader thread can post a fault (and wake pipes)
// while setup_pipes is still move-assigning the vectors, or while
// finalize is nulling them.  The raw_send hot path still reads
// g_tx_pipes unlocked — publication happens on the only thread that
// sends during bootstrap, so that read is single-threaded until the
// vector is stable.  Leaked, like every global wake_all_pipes touches.
std::mutex& g_pipe_pub_mu = *new std::mutex;

// Wake every shm-pipe waiter AND the mailbox waiters: called when a
// fault is posted so waiters re-check g_stop instead of sleeping
// through the failure.
void wake_all_pipes() {
  {
    std::lock_guard<std::mutex> lk(g_pipe_pub_mu);
    if (g_my_pipes)
      for (int i = 0;; ++i) {
        shm::Pipe* p = shm::pipe_of(g_my_pipes, i);
        if (!p) break;
        shm::pipe_wake(p);
      }
    for (auto* tx : g_tx_pipes)
      if (tx) shm::pipe_wake(tx);
  }
  // take the mailbox lock so a recv that just scanned and is about to
  // wait cannot miss the notification (classic lost-wakeup window)
  { std::lock_guard<std::mutex> lk(g_mail_mu); }
  g_mail_cv.notify_all();
  poke_engine();
}

// ------------------------------------------------- deterministic faults
//
// Env-driven fault injection compiled into the bridge so the failure
// paths are testable end-to-end (tests/proc/test_fault_injection.py):
//   T4J_FAULT_RANK      rank the fault applies to (-1 = nobody)
//   T4J_FAULT_MODE      refuse      — never join the bootstrap (park,
//                                     then exit 41): connect-failure
//                       close_after — abruptly close every transport
//                                     and exit 42 after N sent frames:
//                                     dead peer mid-collective
//                       delay       — sleep T4J_FAULT_DELAY_MS before
//                                     every frame send after the first
//                                     N: slow peer / deadline trips
//                       die_after   — _exit(42) T4J_FAULT_DELAY_MS
//                                     after init completes: a rank
//                                     whose data plane is frameless
//                                     (shm arena — e.g. a non-leader
//                                     local in a hierarchical
//                                     collective) still dies
//                                     deterministically mid-op
//                       flaky       — drop every TCP connection
//                                     (shutdown, process stays alive)
//                                     each time another N frames went
//                                     out, T4J_FAULT_COUNT times in
//                                     total, then behave: the
//                                     self-healing reconnect+replay
//                                     path end-to-end
//                       drop_conn   — flaky with exactly one drop
//   T4J_FAULT_AFTER     N frames before the fault arms (default 0)
//   T4J_FAULT_DELAY_MS  delay mode's per-frame stall / die_after's
//                       countdown (default 1000)
//   T4J_FAULT_COUNT     flaky's total number of drops (default 2)
//   T4J_FAULT_STRIPE    flaky/drop_conn: drop only this stripe index
//                       of every link (default -1 = every stripe) —
//                       the per-stripe self-heal matrix's handle
//                       (docs/performance.md "striped links")

struct FaultPlan {
  enum Mode { kNone, kRefuse, kCloseAfter, kDelay, kDieAfter, kFlaky };
  Mode mode = kNone;
  int rank = -1;
  long after = 0;
  long delay_ms = 1000;
  long count = 2;
  int stripe = -1;  // flaky: -1 = all stripes, else just this one
};

FaultPlan g_fault_plan;
std::atomic<long> g_frames_sent{0};
std::atomic<long> g_drops_done{0};

void parse_fault_plan() {
  const char* mode = std::getenv("T4J_FAULT_MODE");
  if (!mode || !mode[0]) return;
  FaultPlan p;
  if (!std::strcmp(mode, "refuse")) p.mode = FaultPlan::kRefuse;
  else if (!std::strcmp(mode, "close_after")) p.mode = FaultPlan::kCloseAfter;
  else if (!std::strcmp(mode, "delay")) p.mode = FaultPlan::kDelay;
  else if (!std::strcmp(mode, "die_after")) p.mode = FaultPlan::kDieAfter;
  else if (!std::strcmp(mode, "flaky")) p.mode = FaultPlan::kFlaky;
  else if (!std::strcmp(mode, "drop_conn")) {
    p.mode = FaultPlan::kFlaky;
    p.count = 1;
  } else {
    std::fprintf(stderr,
                 "r%d | t4j: unknown T4J_FAULT_MODE=%s (want refuse|"
                 "close_after|delay|die_after|flaky|drop_conn); fault "
                 "injection disabled\n",
                 g_rank, mode);
    return;
  }
  const char* r = std::getenv("T4J_FAULT_RANK");
  p.rank = r ? std::atoi(r) : -1;
  const char* a = std::getenv("T4J_FAULT_AFTER");
  if (a) p.after = std::atol(a);
  const char* d = std::getenv("T4J_FAULT_DELAY_MS");
  if (d) p.delay_ms = std::atol(d);
  const char* c = std::getenv("T4J_FAULT_COUNT");
  if (c && p.mode == FaultPlan::kFlaky &&
      std::strcmp(mode, "drop_conn") != 0)
    p.count = std::atol(c);
  const char* sidx = std::getenv("T4J_FAULT_STRIPE");
  if (sidx && sidx[0]) p.stripe = std::atoi(sidx);
  g_fault_plan = p;
}

bool fault_armed(FaultPlan::Mode mode) {
  return g_fault_plan.mode == mode && g_fault_plan.rank == g_rank;
}

// Called once per outbound frame (both transports).  close_after,
// delay and flaky key off the frame counter so tests land the fault
// mid-stream.
void maybe_inject_send_fault() {
  if (g_fault_plan.mode == FaultPlan::kNone ||
      g_fault_plan.rank != g_rank)
    return;
  long n = g_frames_sent.fetch_add(1, std::memory_order_relaxed) + 1;
  if (n <= g_fault_plan.after) return;
  if (g_fault_plan.mode == FaultPlan::kCloseAfter) {
    std::fprintf(stderr,
                 "r%d | t4j fault-injection: closing all transports and "
                 "dying after %ld frames\n",
                 g_rank, n - 1);
    std::fflush(stderr);
    for (auto& p : g_peers)
      for (int si = 0; si < p.nstripes; ++si) {
        Stripe& st = p.s[si];
        if (st.fd >= 0) {
          ::shutdown(st.fd, SHUT_RDWR);
          ::close(st.fd);
        }
      }
    _exit(42);
  }
  if (g_fault_plan.mode == FaultPlan::kFlaky) {
    // drop (shutdown, not close: the fds stay owned by the stripes and
    // the repair machinery swaps them) every TCP connection — or just
    // stripe T4J_FAULT_STRIPE of every link — once per additional
    // T4J_FAULT_AFTER frames, T4J_FAULT_COUNT times total: the process
    // stays alive and the job must self-heal (per stripe)
    long done = g_drops_done.load(std::memory_order_relaxed);
    long after = g_fault_plan.after > 0 ? g_fault_plan.after : 1;
    if (done < g_fault_plan.count && n > after * (done + 1) &&
        g_drops_done.compare_exchange_strong(done, done + 1,
                                             std::memory_order_relaxed)) {
      std::fprintf(stderr,
                   "r%d | t4j fault-injection: dropping %s after %ld "
                   "frames (drop %ld/%ld)\n",
                   g_rank,
                   g_fault_plan.stripe < 0
                       ? "every TCP connection"
                       : "one stripe of every TCP link",
                   n - 1, done + 1, g_fault_plan.count);
      std::fflush(stderr);
      for (auto& p : g_peers)
        for (int si = 0; si < p.nstripes; ++si) {
          if (g_fault_plan.stripe >= 0 && si != g_fault_plan.stripe)
            continue;
          Stripe& st = p.s[si];
          // fd is only stable under send_mu (finish_repair swaps/
          // closes it there); try_lock so a stripe busy in a long
          // write or a repair is skipped rather than raced.  Callers
          // never hold any send_mu here (the injection checks run
          // before locks are acquired), so this is never a
          // self-try_lock.
          std::unique_lock<std::mutex> lk(st.send_mu, std::try_to_lock);
          if (lk.owns_lock() && st.fd >= 0) ::shutdown(st.fd, SHUT_RDWR);
        }
    }
    return;
  }
  if (g_fault_plan.mode == FaultPlan::kDelay)
    std::this_thread::sleep_for(
        std::chrono::milliseconds(g_fault_plan.delay_ms));
}

// --------------------------------------------------------- socket I/O
//
// Every managed fd is O_NONBLOCK; progress is driven by poll() with a
// 100ms tick (so blocked I/O observes g_stop promptly) bounded by the
// caller's deadline.  This is what turns "peer died / peer stalled"
// from an indefinite hang into a contextual error within the deadline.

enum class IoStatus { kOk, kEof, kTimeout, kStopped, kError };

void set_nonblock(int fd) {
  int fl = ::fcntl(fd, F_GETFL, 0);
  if (fl >= 0) ::fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

// 1 = ready, 0 = deadline expired, -1 = bridge stopped under us.
// ignore_stop: the elastic-resize control plane runs WHILE the bridge
// is soft-stopped (g_stop is exactly what interrupts the data plane
// during a resize), so its I/O opts out of the stop check — the
// deadline still bounds it.
int io_wait(int fd, short events, const Deadline& dl,
            bool ignore_stop = false) {
  for (;;) {
    // flight-recorder liveness: every blocked I/O path ticks through
    // here at least every 100ms, so a fresh heartbeat means "alive
    // (possibly wedged on a peer)" while a frozen one means the
    // process itself is gone — the dead-vs-wedged distinction
    // t4j-postmortem and t4j-top key on
    tel::flight_heartbeat();
    if (!ignore_stop && g_stop.load(std::memory_order_acquire)) return -1;
    // adaptive tick: tight while frames are in flight (small-frame
    // latency), lazy when idle (idle ranks must not spin)
    int tick = dl.remaining_ms(io_tick_ms());
    if (dl.bounded && tick == 0) return 0;
    pollfd pfd{fd, events, 0};
    count_syscall();
    int rc = ::poll(&pfd, 1, tick);
    if (rc < 0 && errno != EINTR && errno != EAGAIN) return -1;
    if (rc > 0) return 1;
  }
}

IoStatus nb_read_all(int fd, void* buf, size_t n, const Deadline& dl,
                     bool ignore_stop = false) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    count_syscall();
    ssize_t r = ::read(fd, p, n);
    if (r > 0) {
      p += r;
      n -= static_cast<size_t>(r);
      continue;
    }
    if (r == 0) return IoStatus::kEof;
    if (errno == EINTR) continue;
    if (errno == EAGAIN || errno == EWOULDBLOCK) {
      int w = io_wait(fd, POLLIN, dl, ignore_stop);
      if (w == 1) continue;
      return w == 0 ? IoStatus::kTimeout : IoStatus::kStopped;
    }
    return IoStatus::kError;
  }
  return IoStatus::kOk;
}

// Gathered write via sendmsg(MSG_NOSIGNAL): a dead peer surfaces as
// EPIPE (-> contextual error) instead of a process-killing SIGPIPE.
// extra_flags: MSG_MORE for a header whose payload follows in the
// next call (keeps TCP_NODELAY from emitting a 40-byte segment).
IoStatus nb_write_all(int fd, iovec* iov, int iovcnt, const Deadline& dl,
                      bool ignore_stop = false, int extra_flags = 0) {
  msghdr mh{};
  while (iovcnt > 0) {
    mh.msg_iov = iov;
    mh.msg_iovlen = iovcnt;
    count_syscall();
    ssize_t w = ::sendmsg(fd, &mh, MSG_NOSIGNAL | extra_flags);
    if (w < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int rc = io_wait(fd, POLLOUT, dl, ignore_stop);
        if (rc == 1) continue;
        return rc == 0 ? IoStatus::kTimeout : IoStatus::kStopped;
      }
      return IoStatus::kError;
    }
    size_t done = static_cast<size_t>(w);
    while (iovcnt > 0 && done >= iov[0].iov_len) {
      done -= iov[0].iov_len;
      ++iov;
      --iovcnt;
    }
    if (iovcnt > 0 && done > 0) {
      iov[0].iov_base = static_cast<char*>(iov[0].iov_base) + done;
      iov[0].iov_len -= done;
    }
  }
  return IoStatus::kOk;
}

// Best-effort MPI_Abort propagation: one short-deadline abort frame to
// every TCP peer.  Runs at most once per process; must never recurse
// into the failure paths (hence raw sendmsg, try_lock, swallowed
// errors).  Same-host peers get it over their still-open TCP socket —
// frames never ride the shm pipes here, so a wedged pipe cannot block
// the broadcast.
std::atomic<bool> g_abort_sent{false};

void broadcast_abort(const std::string& why) {
  if (!g_initialized || g_abort_sent.exchange(true)) return;
  std::string msg = why.size() > 512 ? why.substr(0, 512) : why;
  WireHeader h{kMagic, static_cast<uint32_t>(g_rank), kAbortCtx, 1,
               static_cast<uint64_t>(msg.size()), 0, cur_epoch(), 0};
  Deadline dl = Deadline::after(1.0);  // do not let goodbye block us
  for (int peer = 0; peer < static_cast<int>(g_peers.size()); ++peer) {
    if (peer == g_rank) continue;
    PeerLink& p = g_peers[peer];
    // first stripe whose socket is free takes the goodbye; a sender
    // wedged on a stripe holds its send_mu — skip it (that peer will
    // observe our EOF or its own deadline instead)
    for (int si = 0; si < p.nstripes; ++si) {
      Stripe& st = p.s[si];
      if (st.fd < 0) continue;
      std::unique_lock<std::mutex> lk(st.send_mu, std::try_to_lock);
      if (!lk.owns_lock()) continue;
      iovec iov[2] = {{&h, sizeof(h)},
                      {const_cast<char*>(msg.data()), msg.size()}};
      (void)nb_write_all(st.fd, iov, msg.empty() ? 1 : 2, dl);
      break;
    }
  }
}

// Self-healing entry point: a stripe-level transport failure (EOF,
// write error, reset) lands here.  With resilience enabled the stripe
// is marked broken and a repair cycle starts (higher rank re-dials,
// lower rank accepts) while sibling stripes keep moving; without it —
// or during teardown — the legacy PR-1 fail-stop path runs unchanged.
// Defined with the rest of the repair machinery after the bootstrap
// helpers (it dials).
void mark_stripe_broken(int peer, int stripe, const std::string& why);

// The legacy reader-side failure: post the fault unless we are already
// tearing down (finalize-order EOF from a peer that left first is the
// clean path and must stay quiet).
void reader_post_fault(const std::string& msg) {
  if (!g_shutting_down.load() && !g_stop.load()) post_fault(msg);
}

// Mailbox insertion + the frame_rx record (the event's comm field
// carries the stripe index — schema v2, telemetry/schema.py
// event_stripe).  Caller may hold ro_mu (ro_mu -> g_mail_mu is the
// one sanctioned order; mailbox consumers never take ro_mu).
void mailbox_push(Frame&& f, int peer, int stripe, tel::Plane plane) {
  uint64_t nbytes = f.data.size();
  {
    std::lock_guard<std::mutex> lk(g_mail_mu);
    g_mailbox.push_back(std::move(f));
  }
  g_mail_cv.notify_all();
  poke_engine();
  tel::trace_event(tel::kFrameRx, tel::kInstant, plane, stripe, peer,
                   nbytes);
}

// Deliver a received frame in LINK order (docs/performance.md
// "striped links"): frames carry a link-global seq, stripes present
// them out of order, and MPI matching needs per-(src, ctx, tag) FIFO
// — so early frames park in the link's reorder map until the gap
// fills, duplicates (reconnect replay, stripe migration) drop, and
// the contiguous prefix goes to the mailbox under ro_mu so no two
// readers can interleave their pushes out of order.  Returns false
// only for a gap on an UNSTRIPED link — TCP is in-order and the
// replay starts at the acked tail, so that is stream corruption, the
// caller posts the fault.
bool deliver_frame(int peer, int stripe, uint64_t seq, Frame&& f) {
  if (seq == 0) {  // unsequenced legacy frame: straight through
    mailbox_push(std::move(f), peer, stripe, tel::kPlaneNone);
    return true;
  }
  PeerLink& p = g_peers[peer];
  std::lock_guard<std::mutex> lk(p.ro_mu);
  if (seq <= p.delivered || p.reorder.count(seq))
    return true;  // replay/migration duplicate: already have it
  if (seq != p.delivered + 1) {
    if (p.nstripes <= 1) return false;  // single flow: gap = corruption
    // a sibling stripe still owes the gap frame; park this one.  The
    // buffer is bounded by the sender side: frames for the lagging
    // stripe blind-buffer into its bounded replay ring and then block,
    // so at most (nstripes-1) x T4J_REPLAY_BYTES can ever park here.
    p.reorder.emplace(seq, std::move(f));
    return true;
  }
  ++p.delivered;
  mailbox_push(std::move(f), peer, stripe, tel::kPlaneNone);
  for (auto it = p.reorder.find(p.delivered + 1);
       it != p.reorder.end(); it = p.reorder.find(p.delivered + 1)) {
    Frame g = std::move(it->second);
    p.reorder.erase(it);
    ++p.delivered;
    mailbox_push(std::move(g), peer, stripe, tel::kPlaneNone);
  }
  return true;
}

// The reconnect handshake's ack: the largest W such that EVERY frame
// with seq <= W was received — the contiguous delivery cursor
// extended through the contiguous prefix of the reorder map.  Frames
// parked in reorder (received on a fast stripe while a sibling owes
// the gap) count as received: acking only the delivery cursor made a
// healthy stripe's normal ring eviction look like data loss whenever
// a sibling lagged, and finish_repair would then kill a repairable
// stripe with "grow T4J_REPLAY_BYTES".  Unstriped links have an empty
// reorder map, so W == delivered == the legacy ack exactly.
uint64_t link_recv_watermark(PeerLink& p) {
  std::lock_guard<std::mutex> lk(p.ro_mu);
  uint64_t w = p.delivered;
  for (auto it = p.reorder.begin();
       it != p.reorder.end() && it->first == w + 1; ++it)
    w = it->first;
  return w;
}

// Buffered stripe reader: one recv() pulls as many small frames as
// the kernel has ready (the scatter half of the syscall batching —
// the gather half is the sendmsg iovec builder in stripe_write), and
// large bodies are read straight into the frame buffer with no
// double copy.
constexpr size_t kRecvBufBytes = 64 << 10;

// One bounded read appending to rb[len..cap): kOk after >= 1 byte.
IoStatus fill_some(int fd, uint8_t* rb, size_t& len, size_t cap,
                   const Deadline& dl) {
  for (;;) {
    count_syscall();
    ssize_t r = ::recv(fd, rb + len, cap - len, 0);
    if (r > 0) {
      len += static_cast<size_t>(r);
      return IoStatus::kOk;
    }
    if (r == 0) return IoStatus::kEof;
    if (errno == EINTR) continue;
    if (errno == EAGAIN || errno == EWOULDBLOCK) {
      int w = io_wait(fd, POLLIN, dl);
      if (w == 1) continue;
      return w == 0 ? IoStatus::kTimeout : IoStatus::kStopped;
    }
    return IoStatus::kError;
  }
}

#if T4J_HAVE_URING

constexpr uint64_t kCancelUd = ~0ull;

// Cancel-and-drain in-flight SQEs (identified by user_data) so no SQE
// ever outlives the memory it points at.  Returns false if the ring
// wedged — the caller must then LEAK the referenced buffer rather
// than hand memory the kernel may still write back to the allocator.
bool uring_cancel_drain(UringRing& r, unsigned inflight,
                        const uint64_t* uds, unsigned nuds) {
  if (r.fd < 0 || inflight == 0) return true;
  unsigned to_submit = 0;
  for (unsigned i = 0; i < nuds; ++i) {
    io_uring_sqe* e = r.get_sqe();
    e->opcode = IORING_OP_ASYNC_CANCEL;
    e->fd = -1;
    e->addr = uds[i];
    e->user_data = kCancelUd;
    ++to_submit;
  }
  for (int round = 0; round < 100 && inflight; ++round) {
    tel::flight_heartbeat();
    int rc = uring_enter(r, to_submit, 1, 100);
    if (rc >= 0) to_submit = 0;
    else if (errno != ETIME && errno != EINTR && errno != EAGAIN &&
             errno != EBUSY)
      break;
    io_uring_cqe cqe;
    while (r.pop_cqe(&cqe))
      if (cqe.user_data != kCancelUd && inflight) --inflight;
  }
  return inflight == 0;
}

// Per-reader io_uring recv context: its own ring (rings are single-
// submitter) with the 64 KiB reader buffer registered as fixed
// index 0.
struct UringRecvCtx {
  UringRing ring;
  bool fixed_ok = false;

  bool open_for(uint8_t* rb, size_t cap) {
    if (!ring.open_ring(8)) return false;
    fixed_ok = ring.register_buffer(rb, cap);
    return true;
  }
};

// uring variant of fill_some: one READ_FIXED (over the registered
// reader buffer) or RECV completion wait replaces the classic
// recv+poll syscall pair — a quiet reader parks inside
// io_uring_enter and wakes with the bytes already landed.  Never
// returns with the recv SQE still in flight (stop/timeout edges
// cancel-and-drain; *wedged reports a drain failure).
IoStatus fill_some_uring(UringRecvCtx& c, int fd, uint8_t* rb, size_t& len,
                         size_t cap, const Deadline& dl, bool* wedged) {
  // Opportunistic drain first: bytes that accumulated while the
  // caller processed the previous batch are claimed with ONE plain
  // recv — the completion path below is only paid when the socket is
  // genuinely empty, where its single enter replaces the classic
  // recv(EAGAIN)+poll+recv round trip.  Without this, the kernel-side
  // retry completes the armed RECV the instant the FIRST bytes land,
  // so an eager reader wakes per TCP chunk instead of per accumulated
  // run and spends MORE syscalls than the classic path, not fewer.
  {
    count_syscall();
    ssize_t r = ::recv(fd, rb + len, cap - len, MSG_DONTWAIT);
    if (r > 0) {
      len += static_cast<size_t>(r);
      return IoStatus::kOk;
    }
    if (r == 0) return IoStatus::kEof;
    if (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
        errno != EINTR)
      return IoStatus::kError;
  }
  bool submitted = false;
  auto abort_inflight = [&]() {
    if (!submitted) return;
    const uint64_t ud = 1;
    if (!uring_cancel_drain(c.ring, 1, &ud, 1)) *wedged = true;
    submitted = false;
  };
  for (;;) {
    tel::flight_heartbeat();
    bool stopping = g_stop.load(std::memory_order_acquire);
    int tick = dl.remaining_ms(io_tick_ms());
    if (stopping || (dl.bounded && tick == 0)) {
      abort_inflight();
      return stopping ? IoStatus::kStopped : IoStatus::kTimeout;
    }
    int rc;
    if (!submitted) {
      io_uring_sqe* e = c.ring.get_sqe();
      if (c.fixed_ok) {
        e->opcode = IORING_OP_READ_FIXED;
        e->buf_index = 0;
      } else {
        e->opcode = IORING_OP_RECV;
      }
      e->fd = fd;
      e->addr = reinterpret_cast<uint64_t>(rb + len);
      e->len = static_cast<unsigned>(cap - len);
      e->user_data = 1;
      rc = uring_enter(c.ring, 1, 1, tick);
      if (rc >= 1) submitted = true;
    } else {
      rc = uring_enter(c.ring, 0, 1, tick);
    }
    if (rc < 0 && errno != ETIME && errno != EINTR && errno != EAGAIN &&
        errno != EBUSY) {
      abort_inflight();
      return IoStatus::kError;
    }
    io_uring_cqe cqe;
    while (c.ring.pop_cqe(&cqe)) {
      if (cqe.user_data == kCancelUd) continue;  // stale drain residue
      submitted = false;
      int res = cqe.res;
      if (res > 0) {
        len += static_cast<size_t>(res);
        return IoStatus::kOk;
      }
      if (res == 0) return IoStatus::kEof;
      if (res == -EINTR || res == -EAGAIN) break;  // resubmit
      if (c.fixed_ok &&
          (res == -EINVAL || res == -EOPNOTSUPP || res == -EFAULT)) {
        // registered-buffer path not honoured here: quiet sticky
        // fallback to plain RECV on this reader
        c.fixed_ok = false;
        break;
      }
      errno = -res;
      return IoStatus::kError;
    }
  }
}

#endif  // T4J_HAVE_URING

void reader_loop(int peer, int stripe, int fd) {
  Deadline forever;  // idle between frames is legal — wait unbounded
  // every kernel crossing this thread makes lands on the stripe's rx
  // counter (syscalls-per-frame observability)
  TlsSyscallScope sysc_scope(&g_peers[peer].s[stripe].rx_syscalls);
  std::unique_ptr<uint8_t[]> rb(new uint8_t[kRecvBufBytes]);
#if T4J_HAVE_URING
  bool ring_wedged = false;
  // if the recv ring wedges on teardown the kernel may still own a
  // READ_FIXED into rb: leak the 64 KiB rather than free it under an
  // in-flight DMA-style write.  Guard destroys before rb, after uctx.
  struct RbGuard {
    std::unique_ptr<uint8_t[]>* rb;
    bool* wedged;
    ~RbGuard() {
      if (*wedged) (void)rb->release();
    }
  } rb_guard{&rb, &ring_wedged};
  UringRecvCtx uctx;
  const bool use_uring =
      uring_active() && uctx.open_for(rb.get(), kRecvBufBytes);
#endif
  size_t off = 0, len = 0;  // rb[off, off+len) holds undelivered bytes

  // Shared failure handling: mid = true when the stream died inside a
  // frame (repairable loss: the sender's replay redelivers it whole).
  auto stream_down = [&](IoStatus st, bool mid,
                         uint64_t body_pending) -> void {
    if (st == IoStatus::kStopped || g_shutting_down.load() ||
        g_stop.load())
      return;
    if (resilience_on() && !g_finalizing.load(std::memory_order_acquire)) {
      mark_stripe_broken(
          peer, stripe,
          mid ? (st == IoStatus::kTimeout
                     ? "recv stalled mid-frame (T4J_OP_TIMEOUT)"
                     : "recv connection lost mid-frame")
              : "recv connection lost");
      return;
    }
    if (mid)
      post_fault(err_prefix() + "lost peer r" + std::to_string(peer) +
                 " mid-frame (" +
                 (st == IoStatus::kTimeout
                      ? "stalled beyond T4J_OP_TIMEOUT"
                      : "connection dropped") +
                 " with " + std::to_string(body_pending) +
                 "-byte body pending)");
    else
      reader_post_fault(err_prefix() + "peer r" + std::to_string(peer) +
                        " closed the connection unexpectedly (process "
                        "died or exited without finalize)");
  };

  for (;;) {
    // ensure a whole header is buffered.  A clean teardown lands
    // exactly on a frame boundary (off == len == 0); EOF with partial
    // bytes buffered is a mid-frame loss.
    while (len < sizeof(WireHeader)) {
      if (off && len) std::memmove(rb.get(), rb.get() + off, len);
      off = 0;
      IoStatus st;
#if T4J_HAVE_URING
      if (use_uring)
        st = fill_some_uring(uctx, fd, rb.get(), len, kRecvBufBytes,
                             forever, &ring_wedged);
      else
#endif
        st = fill_some(fd, rb.get(), len, kRecvBufBytes, forever);
      if (st != IoStatus::kOk) {
        stream_down(st, len > 0, 0);
        return;
      }
    }
    WireHeader h;
    std::memcpy(&h, rb.get() + off, sizeof(h));
    off += sizeof(h);
    len -= sizeof(h);
    if (h.magic != kMagic) {
      // stream corruption is not a transient: no replay can fix a
      // desynchronised byte stream, so this stays fail-stop
      post_fault(err_prefix() + "garbled frame from peer r" +
                 std::to_string(peer) +
                 " (magic check failed — torn abort frame or stream "
                 "corruption)");
      return;
    }
    if (h.ctx == kAbortCtx && h.nbytes > 4096) {
      // broadcast_abort caps the payload at 512 bytes, so anything
      // larger is stream corruption, not a real abort reason
      post_fault(err_prefix() + "garbled abort frame from peer r" +
                 std::to_string(peer));
      return;
    }
    Frame f;
    f.src = static_cast<int>(h.src);
    f.ctx = static_cast<int>(h.ctx);
    f.tag = static_cast<int>(h.tag) - 1;
    f.data = Buf(h.nbytes);
    size_t have = len < h.nbytes ? len : static_cast<size_t>(h.nbytes);
    if (have) {
      std::memcpy(f.data.data(), rb.get() + off, have);
      off += have;
      len -= have;
    }
    if (have < h.nbytes) {
      // mid-frame the peer is actively sending: a stall here is a
      // real fault, so the per-op deadline applies (when configured),
      // and the poll tick tightens while the body is in flight
      InflightScope busy;
      Deadline body = Deadline::after(effective_op_timeout());
      IoStatus bst = nb_read_all(fd, f.data.data() + have,
                                 h.nbytes - have, body);
      if (bst != IoStatus::kOk) {
        // the partial frame is discarded (delivery cursor not
        // advanced), so the reconnect replay redelivers it whole
        stream_down(bst, true, h.nbytes);
        return;
      }
    }
    if (h.ctx == kAbortCtx) {
      // MPI_Abort analog from a peer: record and wake everyone
      std::string why(reinterpret_cast<const char*>(f.data.data()),
                      f.data.size());
      if (why.empty()) why = "(abort reason lost in transit)";
      post_fault(err_prefix() + "abort broadcast from rank " +
                 std::to_string(h.src) + ": " + why);
      return;
    }
    if (h.epoch != cur_epoch()) {
      // stale-epoch traffic (a frame built before a world resize):
      // the op it belonged to was already interrupted with
      // ResizeInterrupted, so delivering it into the resized world
      // would corrupt matching.  Post-resize links are fresh
      // connections, so this is belt-and-braces, not the mechanism.
      g_stale_frames.fetch_add(1, std::memory_order_relaxed);
      continue;
    }
    if (h.seq) {
      Stripe& st = g_peers[peer].s[stripe];
      uint64_t seen = st.seen_seq.load(std::memory_order_relaxed);
      if (h.seq > seen)
        st.seen_seq.store(h.seq, std::memory_order_relaxed);
    }
    if (!deliver_frame(peer, stripe, h.seq, std::move(f))) {
      post_fault(err_prefix() + "sequence gap from peer r" +
                 std::to_string(peer) + " (got frame " +
                 std::to_string(h.seq) + " after " +
                 std::to_string(g_peers[peer].delivered) +
                 " — stream corruption)");
      return;
    }
  }
}

int enc_ctx(int ctx, bool coll) { return ctx * 2 + (coll ? 1 : 0); }

// Copy into the replay arena with non-temporal stores where the ISA
// offers them: the arena is written once and read back only on the
// (rare) reconnect replay, so streaming past the cache halves the
// copy's memory traffic (no read-for-ownership) and keeps the
// many-MB arena from evicting the hot TCP path — the difference
// between a ~20% and a ~5% busbw tax on the loopback box.
void replay_copy(uint8_t* dst, const void* src, size_t n) {
#ifdef __SSE2__
  const uint8_t* s = static_cast<const uint8_t*>(src);
  // small frames stay on plain memcpy: they are cache-friendly and not
  // worth a store fence
  if (n >= 1024 && (reinterpret_cast<uintptr_t>(dst) & 15) == 0) {
    size_t vecs = n / 16;
    if ((reinterpret_cast<uintptr_t>(s) & 15) == 0) {
      for (size_t i = 0; i < vecs; ++i)
        _mm_stream_si128(reinterpret_cast<__m128i*>(dst) + i,
                         _mm_load_si128(
                             reinterpret_cast<const __m128i*>(s) + i));
    } else {
      for (size_t i = 0; i < vecs; ++i)
        _mm_stream_si128(reinterpret_cast<__m128i*>(dst) + i,
                         _mm_loadu_si128(
                             reinterpret_cast<const __m128i*>(s) + i));
    }
    _mm_sfence();  // streamed stores must be visible to the replayer
    size_t done = vecs * 16;
    if (n - done) std::memcpy(dst + done, s + done, n - done);
    return;
  }
#endif
  std::memcpy(dst, src, n);
}

// ------------------------------------------------ MSG_ZEROCOPY plumbing
//
// Large frames opt into MSG_ZEROCOPY (T4J_ZEROCOPY_MIN_BYTES): the
// kernel transmits straight from the caller's pages instead of copying
// into the socket buffer, and posts a completion record on the
// socket's error queue once it is done with them.  Until that
// completion is reaped the pages are pinned — overwriting them would
// corrupt in-flight data — so replay-arena reuse (eviction/grow) and,
// on the no-ring T4J_RETRY_MAX=0 path, returning to the caller both
// gate on the reap (docs/sharp-bits.md "MSG_ZEROCOPY pins pages").

#if defined(__linux__) && defined(MSG_ZEROCOPY) && defined(SO_ZEROCOPY)
#define T4J_HAVE_ZEROCOPY 1
#else
#define T4J_HAVE_ZEROCOPY 0
#endif

// Zerocopy completion diagnostics: total completions reaped, and how
// many the kernel reported as COPIED anyway (SO_EE_CODE_ZEROCOPY_
// COPIED — loopback always does; a real NIC path should not).
std::atomic<uint64_t> g_zc_completions{0};
std::atomic<uint64_t> g_zc_copied{0};

bool probe_zerocopy_support() {
#if T4J_HAVE_ZEROCOPY
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return false;
  int one = 1;
  bool ok = ::setsockopt(fd, SOL_SOCKET, SO_ZEROCOPY, &one,
                         sizeof(one)) == 0;
  ::close(fd);
  return ok;
#else
  return false;
#endif
}

// Enable SO_ZEROCOPY on a freshly installed stripe socket (caller
// holds send_mu or is single-threaded during bootstrap).
void stripe_enable_zc(Stripe& st) {
#if T4J_HAVE_ZEROCOPY
  st.zc_enabled = false;
  if (!g_zc_supported || zc_min_bytes() <= 0 || st.fd < 0) return;
  int one = 1;
  st.zc_enabled = ::setsockopt(st.fd, SOL_SOCKET, SO_ZEROCOPY, &one,
                               sizeof(one)) == 0;
  st.zc_sent = 0;
  st.zc_done = 0;
#else
  (void)st;
#endif
}

// Drain the socket error queue, advancing zc_done (caller holds
// send_mu).  Nonblocking; safe to call on any stripe.
void reap_zc(Stripe& st) {
#if T4J_HAVE_ZEROCOPY
  if (!st.zc_enabled || st.fd < 0 || st.zc_done == st.zc_sent) return;
  for (;;) {
    char ctrl[256];
    msghdr mh{};
    mh.msg_control = ctrl;
    mh.msg_controllen = sizeof(ctrl);
    count_syscall();
    ssize_t r = ::recvmsg(st.fd, &mh, MSG_ERRQUEUE | MSG_DONTWAIT);
    if (r < 0) return;  // EAGAIN: nothing pending right now
    for (cmsghdr* c = CMSG_FIRSTHDR(&mh); c; c = CMSG_NXTHDR(&mh, c)) {
      if (!((c->cmsg_level == SOL_IP && c->cmsg_type == IP_RECVERR) ||
            (c->cmsg_level == SOL_IPV6 && c->cmsg_type == IPV6_RECVERR)))
        continue;
      auto* ee = reinterpret_cast<sock_extended_err*>(CMSG_DATA(c));
      if (ee->ee_errno != 0 || ee->ee_origin != SO_EE_ORIGIN_ZEROCOPY)
        continue;
      // ids [ee_info, ee_data] completed (u32, sequential from 0).
      // SO_EE_CODE_ZEROCOPY_COPIED = the kernel fell back to copying
      // for this range (loopback does; some NIC paths do) — count it
      // so introspection can tell a real zero-copy fabric from one
      // paying pin overhead for nothing (docs/performance.md).
      uint32_t lo = ee->ee_info, hi = ee->ee_data + 1;
      g_zc_completions.fetch_add(hi - lo, std::memory_order_relaxed);
#ifdef SO_EE_CODE_ZEROCOPY_COPIED
      if (ee->ee_code & SO_EE_CODE_ZEROCOPY_COPIED)
        g_zc_copied.fetch_add(hi - lo, std::memory_order_relaxed);
#endif
      if (hi > st.zc_done) st.zc_done = hi;
    }
  }
#else
  (void)st;
#endif
}

// Block (bounded) until completion ids [0, upto) are reaped — the
// arena-reuse / caller-buffer-release gate.  Returns false on the
// deadline (the caller escalates: overwriting pinned pages is
// corruption, not a recoverable slow path).
bool zc_wait(Stripe& st, uint32_t upto, const Deadline& dl) {
#if T4J_HAVE_ZEROCOPY
  while (st.zc_done < upto) {
    reap_zc(st);
    if (st.zc_done >= upto) break;
    if (g_stop.load(std::memory_order_acquire)) return false;
    if (dl.expired()) return false;
    // completions arrive promptly (loopback: as soon as the reader
    // consumed the bytes) — a 1ms tick keeps the eviction gate from
    // serialising the pipeline on the poll granularity (a 20ms tick
    // measured 2.5x busbw loss on the eviction-heavy 64MB path)
    pollfd pfd{st.fd, POLLERR, 0};
    count_syscall();
    ::poll(&pfd, 1, dl.remaining_ms(1));
  }
  return true;
#else
  (void)st;
  (void)upto;
  (void)dl;
  return true;
#endif
}

// ---------------------------------------------- emulated flow throttle
//
// T4J_EMU_FLOW_BPS: per-connection token bucket applied in the write
// path (caller holds send_mu, so the sleep paces exactly one flow —
// sibling stripes keep writing).  This is what lets a loopback box
// show the multi-flow busbw step real fabrics get from multiple NIC
// queues: one throttled flow caps at the knob, N stripes at N x knob.
void throttle_stripe(Stripe& st, size_t nbytes) {
  long long rate = emu_flow_bps();
  if (rate <= 0 || nbytes == 0) return;
  Clock::time_point now = Clock::now();
  if (st.tb_last.time_since_epoch().count() == 0) st.tb_last = now;
  double dt = std::chrono::duration<double>(now - st.tb_last).count();
  st.tb_last = now;
  st.tb_tokens += dt * static_cast<double>(rate);
  double burst = static_cast<double>(rate) * 0.05;  // 50ms of burst
  if (st.tb_tokens > burst) st.tb_tokens = burst;
  st.tb_tokens -= static_cast<double>(nbytes);
  while (st.tb_tokens < 0 && !g_stop.load(std::memory_order_acquire)) {
    double wait_s = -st.tb_tokens / static_cast<double>(rate);
    if (wait_s > 0.05) wait_s = 0.05;
    std::this_thread::sleep_for(
        std::chrono::duration<double>(wait_s));
    now = Clock::now();
    dt = std::chrono::duration<double>(now - st.tb_last).count();
    st.tb_last = now;
    st.tb_tokens += dt * static_cast<double>(rate);
  }
}

// ------------------------------------------------ per-stripe replay ring

// True when a contiguous region of `nbytes` is available WITHOUT
// evicting (the blind-buffer space check for sends on a broken
// stripe; caller holds send_mu).
bool ring_has_space(const Stripe& st, size_t nbytes) {
  if (!st.ring_buf) return nbytes <= static_cast<size_t>(replay_bytes());
  if (st.ring.empty()) return nbytes <= st.ring_cap;
  size_t tail = st.ring.front().off;
  if (st.ring_head > tail)
    return st.ring_cap - st.ring_head >= nbytes || tail >= nbytes;
  if (st.ring_head < tail) return tail - st.ring_head >= nbytes;
  return false;
}

// Append a just-built frame to the stripe's circular replay arena
// (caller holds send_mu), evicting the oldest frames when space runs
// out.  The newest frame is always retained even when it alone
// exceeds T4J_REPLAY_BYTES — an empty ring could replay nothing.
// Eviction of a MSG_ZEROCOPY-sent entry first waits for its kernel
// completion: the pages are pinned until then, and overwriting them
// would corrupt data still on the wire.  Returns the appended entry
// (for the zerocopy send path, which points its iovec at the arena
// copy and stamps the completion id back in).
Replay& ring_append(Stripe& st, const WireHeader& h, const void* buf,
                    size_t nbytes) {
  size_t cap = static_cast<size_t>(replay_bytes());
  if (cap < nbytes) cap = nbytes;  // an oversized frame always fits
  auto note_evicted = [&st](const Replay& r) {
    if (r.h.seq > st.max_evicted_seq) st.max_evicted_seq = r.h.seq;
  };
  if (!st.ring_buf || st.ring_cap < cap) {
    // first use, or an oversized frame forces a grow: retained history
    // is dropped (identical to evicting everything) — but the old
    // arena may still be pinned by in-flight zerocopy sends, so reap
    // those first (freeing pinned pages is the one unrecoverable bug)
    if (st.zc_sent != st.zc_done)
      (void)zc_wait(st, st.zc_sent,
                    Deadline::after(effective_op_timeout() > 0
                                        ? effective_op_timeout()
                                        : 30.0));
    for (const Replay& r : st.ring) note_evicted(r);
    st.ring.clear();
    st.ring_head = 0;
    st.ring_buf.reset(new uint8_t[cap]);
    st.ring_cap = cap;
  }
  auto evict = [&] {
    Replay& victim = st.ring.front();
    if (victim.zc_id && victim.zc_id > st.zc_done)
      (void)zc_wait(st, victim.zc_id,
                    Deadline::after(effective_op_timeout() > 0
                                        ? effective_op_timeout()
                                        : 30.0));
    note_evicted(victim);
    st.ring.pop_front();
    if (st.ring.empty()) st.ring_head = 0;
  };
  // carve a contiguous [off, off+nbytes) region: frames never wrap, so
  // the gap between the last entry's end and the arena end is wasted
  // until the wrapped-past entries are evicted (standard ring layout)
  size_t off;
  for (;;) {
    if (st.ring.empty()) {
      off = 0;
      break;
    }
    size_t tail = st.ring.front().off;  // oldest resident payload
    if (st.ring_head > tail) {
      if (st.ring_cap - st.ring_head >= nbytes) {
        off = st.ring_head;
        break;
      }
      if (tail >= nbytes) {
        off = 0;  // wrap
        break;
      }
    } else if (st.ring_head < tail && tail - st.ring_head >= nbytes) {
      off = st.ring_head;
      break;
    }
    evict();
  }
  if (nbytes) replay_copy(st.ring_buf.get() + off, buf, nbytes);
  // keep every frame 16-aligned so replay_copy's streaming path stays
  // eligible (off 0 is aligned; aligning the head aligns the rest)
  st.ring_head = (off + nbytes + 15) & ~static_cast<size_t>(15);
  if (st.ring_head > st.ring_cap) st.ring_head = st.ring_cap;
  st.ring.push_back(Replay{h, off, 0});
  return st.ring.back();
}

// Wait (bounded by `dl`) until the stripe to `world_dest` is up (or
// back up) — used both before a send on a broken stripe whose ring is
// full and after a failed write whose frame now sits in the replay
// ring (the repair redelivers it under send_mu).  Returns normally on
// kUp; throws on stop/death (raise_stopped — a dead STRIPE with live
// siblings never lands here: dealing skips it) or deadline expiry.
void wait_stripe_up(int world_dest, int stripe, const Deadline& dl,
                    size_t nbytes, int tag, double limit_s) {
  Stripe& st = g_peers[world_dest].s[stripe];
  std::unique_lock<std::mutex> lk(st.mu);
  for (;;) {
    if (g_stop.load(std::memory_order_acquire) ||
        st.state == Stripe::kDead) {
      lk.unlock();
      if (g_peers[world_dest].link_dead() ||
          g_stop.load(std::memory_order_acquire))
        raise_stopped();
      return;  // stripe died but siblings live: migration redeals it
    }
    if (st.state == Stripe::kUp) return;
    if (dl.expired()) {
      lk.unlock();
      fail_op("send of " + std::to_string(nbytes) + " bytes to peer r" +
              std::to_string(world_dest) + " (tag " + std::to_string(tag) +
              ", stripe " + std::to_string(stripe) +
              ") made no progress for " + std::to_string(limit_s) + "s (" +
              deadline_knob() + ") — link down, reconnect still pending");
    }
    st.cv.wait_for(lk, std::chrono::milliseconds(dl.remaining_ms(100)));
  }
}

// ------------------------------------------------ striped send engine
//
// One frame headed for one link (seq/stripe assigned by deal_frames).
struct WirePart {
  const void* buf;
  size_t nbytes;
  WireHeader h;
  int stripe = 0;
};

// Round-robin pick over the live stripes (caller holds deal_mu): scan
// the active dealing width first, then — so a dead stripe can never
// strand traffic when live siblings exist OUTSIDE the active width —
// fall back to any live built stripe.  Returns -1 only when every
// stripe of the link is dead (the link-level verdict owns that).
int pick_live_stripe(PeerLink& p) {
  uint32_t dead = p.dead_mask.load(std::memory_order_relaxed);
  int width = active_stripes();
  if (width > p.nstripes) width = p.nstripes;
  for (int t = 0; t < width; ++t) {
    int si = static_cast<int>(p.dealt++ % width);
    if (!((dead >> si) & 1)) return si;
  }
  for (int si = 0; si < p.nstripes; ++si)
    if (!((dead >> si) & 1)) return si;
  return -1;
}

// Assign link seqs + round-robin stripes under deal_mu.  Frames are
// sequenced whenever self-healing is on (replay dedup needs it) OR
// more than one stripe is dealing (delivery order needs it); the
// single-flow no-healing path keeps seq 0 — the exact pre-striping
// wire bytes.
void deal_frames(PeerLink& p, int ctx, int tag, WirePart* parts,
                 size_t nparts, bool healing) {
  int width = active_stripes();
  if (width > p.nstripes) width = p.nstripes;
  bool sequenced = healing || width > 1;
  std::lock_guard<std::mutex> lk(p.deal_mu);
  for (size_t i = 0; i < nparts; ++i) {
    WirePart& w = parts[i];
    uint64_t seq = sequenced ? ++p.send_seq : 0;
    w.h = WireHeader{kMagic, static_cast<uint32_t>(g_rank),
                     static_cast<uint32_t>(ctx),
                     static_cast<uint32_t>(tag + 1),
                     static_cast<uint64_t>(w.nbytes), seq, cur_epoch(), 0};
    int si = pick_live_stripe(p);
    w.stripe = si < 0 ? 0 : si;  // all-dead: stripe 0's dead state
                                 // surfaces the link verdict to the
                                 // sender promptly
  }
}

#if T4J_HAVE_URING

// Payloads at or above this (and already resident in the replay
// arena, i.e. healing on) take the registered-buffer WRITE_FIXED
// path; below it one SENDMSG SQE per frame is cheaper than splitting
// header and payload across two SQEs.
constexpr size_t kUringFixedMinBytes = 64 << 10;

// io_uring variant of stripe_write (caller holds st.send_mu, tls
// syscall counter already points at tx).  A whole segment run is
// queued as one SENDMSG SQE per frame, IOSQE_IO_LINK-chained to
// preserve stream order, and submitted with ONE io_uring_enter that
// also waits for the batch's completions.  Large arena-resident
// frames go out as a MSG_MORE header SEND linked to a WRITE_FIXED
// over the registered replay arena — fixed-buffer I/O, the arena is
// the only memory the kernel ever sees.  The WRITE_FIXED SQE always
// TERMINATES its chain: io_uring only breaks links on res < 0, not
// on short success, and socket writes may legitimately complete
// short under backpressure — a linked successor after a short write
// would silently desynchronise the stream.  Short remainders are
// resubmitted explicitly instead.
//
// INVARIANT: never returns with SQEs in flight (stop/timeout edges
// cancel-and-drain after shutting the socket down), so every iovec /
// msghdr / arena pointer an SQE references strictly outlives it.
// Any failed or short SQE kills the socket: the peer then observes a
// clean mid-frame EOF (repairable via replay) rather than garbled
// framing (fail-stop).  Wire bytes are identical to the sendmsg
// backend — only the syscall shape differs.
IoStatus stripe_write_uring(Stripe& st, WirePart** run, size_t n,
                            bool healing, const Deadline& dl) {
  UringSendCtx& c = *st.uring;
  constexpr long long kPending = INT64_MIN;
  auto bail = [&](IoStatus s, size_t next) {
    if (healing)
      for (size_t k = next; k < n; ++k)
        ring_append(st, run[k]->h, run[k]->buf, run[k]->nbytes);
    return s;
  };
  auto kill_stream = [&]() {
    if (st.fd >= 0) ::shutdown(st.fd, SHUT_RDWR);
  };
  std::vector<long long> res;     // per-user_data completion results
  std::vector<uint64_t> expect;   // bytes each SQE must move
  // Submit `queued` SQEs and wait until every slot in res[] has a
  // completion.  One enter both submits and waits on the happy path.
  auto wait_all = [&](unsigned queued) -> IoStatus {
    unsigned count = static_cast<unsigned>(res.size());
    unsigned done = 0;
    unsigned to_submit = queued;
    for (;;) {
      io_uring_cqe cqe;
      while (c.ring.pop_cqe(&cqe)) {
        if (cqe.user_data == kCancelUd) continue;
        if (cqe.user_data < count && res[cqe.user_data] == kPending) {
          res[cqe.user_data] = cqe.res;
          ++done;
        }
      }
      if (done >= count && !to_submit) return IoStatus::kOk;
      tel::flight_heartbeat();
      bool stopping = g_stop.load(std::memory_order_acquire);
      int tick = dl.remaining_ms(io_tick_ms());
      bool timed_out = dl.bounded && tick == 0;
      int hard_errno = 0;
      if (!stopping && !timed_out) {
        int rc = uring_enter(c.ring, to_submit, count - done, tick);
        if (rc >= 0) {
          unsigned sub = static_cast<unsigned>(rc);
          to_submit -= sub < to_submit ? sub : to_submit;
          continue;
        }
        if (errno == ETIME || errno == EINTR || errno == EAGAIN ||
            errno == EBUSY)
          continue;
        hard_errno = errno;
      }
      // stop / deadline / broken ring: SQEs already queued cannot be
      // un-queued — force the submit, kill the stream so blocked
      // sends resolve promptly, then cancel-and-drain
      if (to_submit) {
        int rc = uring_enter(c.ring, to_submit, 0, -1);
        if (rc > 0)
          to_submit -= static_cast<unsigned>(rc) < to_submit
                           ? static_cast<unsigned>(rc)
                           : to_submit;
      }
      kill_stream();
      std::vector<uint64_t> uds;
      for (unsigned k = 0; k < count; ++k)
        if (res[k] == kPending) uds.push_back(k);
      if (!uring_cancel_drain(c.ring, count - done, uds.data(),
                              static_cast<unsigned>(uds.size())))
        c.ok = false;  // wedged ring: never reuse it (keeps arena pin)
      errno = hard_errno ? hard_errno : EPIPE;
      return stopping
                 ? IoStatus::kStopped
                 : (timed_out ? IoStatus::kTimeout : IoStatus::kError);
    }
  };
  size_t batch_cap = c.ring.entries > 2 ? c.ring.entries - 2 : 1;
  if (batch_cap > 256) batch_cap = 256;
  bool pre_appended = false;  // run[i] already in the ring (see below)
  size_t i = 0;
  while (i < n) {
    WirePart& w = *run[i];
    if (healing && c.fixed_ok && !pre_appended &&
        w.nbytes >= kUringFixedMinBytes) {
      // ---- registered-arena WRITE_FIXED path (its own submission:
      // nothing pending — fixed frames always start a fresh batch)
      Replay& rep = ring_append(st, w.h, w.buf, w.nbytes);
      if (!c.ring.register_buffer(st.ring_buf.get(), st.ring_cap)) {
        // registration refused (pin limits): sticky fallback — the
        // frame is already appended, let the SENDMSG batch below
        // send it from the arena without re-appending
        c.fixed_ok = false;
        pre_appended = true;
      } else {
        throttle_stripe(st, sizeof(WireHeader) + w.nbytes);
        uint8_t* base = st.ring_buf.get() + rep.off;
        res.assign(2, kPending);
        expect.assign(2, 0);
        expect[0] = sizeof(WireHeader);
        expect[1] = w.nbytes;
        io_uring_sqe* eh = c.ring.get_sqe();
        eh->opcode = IORING_OP_SEND;
        eh->fd = st.fd;
        eh->addr = reinterpret_cast<uint64_t>(&rep.h);
        eh->len = sizeof(WireHeader);
        eh->msg_flags = MSG_NOSIGNAL | MSG_WAITALL | MSG_MORE;
        eh->flags = IOSQE_IO_LINK;
        eh->user_data = 0;
        io_uring_sqe* ep = c.ring.get_sqe();
        ep->opcode = IORING_OP_WRITE_FIXED;
        ep->fd = st.fd;
        ep->addr = reinterpret_cast<uint64_t>(base);
        ep->len = static_cast<unsigned>(w.nbytes);
        ep->off = 0;
        ep->buf_index = 0;
        ep->user_data = 1;
        IoStatus s = wait_all(2);
        if (s != IoStatus::kOk) return bail(s, i + 1);
        if (res[0] != static_cast<long long>(sizeof(WireHeader))) {
          kill_stream();
          errno = res[0] < 0 ? static_cast<int>(-res[0]) : EPIPE;
          if (res[0] == -ECANCELED) errno = EPIPE;
          return bail(IoStatus::kError, i + 1);
        }
        long long sent = res[1];
        if (sent < 0 && (sent == -EINVAL || sent == -EOPNOTSUPP ||
                         sent == -EFAULT)) {
          // fixed-buffer op not honoured here: header is already on
          // the wire, finish the payload classically and stop trying
          c.fixed_ok = false;
          iovec pv{base, w.nbytes};
          IoStatus s2 = nb_write_all(st.fd, &pv, 1, dl);
          if (s2 != IoStatus::kOk) return bail(s2, i + 1);
          ++i;
          continue;
        }
        size_t done_b = sent > 0 ? static_cast<size_t>(sent) : 0;
        if (sent < 0 && sent != -EINTR && sent != -EAGAIN) {
          kill_stream();
          errno = sent == -ECANCELED ? EPIPE : static_cast<int>(-sent);
          return bail(IoStatus::kError, i + 1);
        }
        // short (or retryable) completion: resubmit the remainder —
        // each remainder SQE again terminates its own submission
        while (done_b < w.nbytes) {
          res.assign(1, kPending);
          io_uring_sqe* er = c.ring.get_sqe();
          er->opcode = IORING_OP_WRITE_FIXED;
          er->fd = st.fd;
          er->addr = reinterpret_cast<uint64_t>(base + done_b);
          er->len = static_cast<unsigned>(w.nbytes - done_b);
          er->off = 0;
          er->buf_index = 0;
          er->user_data = 0;
          IoStatus s2 = wait_all(1);
          if (s2 != IoStatus::kOk) return bail(s2, i + 1);
          long long r2 = res[0];
          if (r2 == -EINTR || r2 == -EAGAIN) continue;
          if (r2 <= 0) {
            kill_stream();
            errno = r2 < 0 ? static_cast<int>(-r2) : EPIPE;
            if (r2 == -ECANCELED) errno = EPIPE;
            return bail(IoStatus::kError, i + 1);
          }
          done_b += static_cast<size_t>(r2);
        }
        ++i;
        continue;
      }
    }
    // ---- gather batch: the whole run segment as ONE SENDMSG SQE —
    // header + payload iovec pairs, the exact classic gather-write
    // shape, so a single submission and a single completion cover the
    // run.  (An earlier shape queued one linked SENDMSG per frame;
    // the link-by-link task-work between chained SQEs cost more
    // small-frame latency than the batched submit saved.)  Same arena
    // flush discipline as the classic path: an append that would
    // evict breaks the batch so no queued iovec ever points at arena
    // bytes an eviction could hand to a later frame.
    size_t maxb = (n - i) < batch_cap ? (n - i) : batch_cap;
    c.mhs.clear();
    c.iovs.clear();
    c.mhs.reserve(1);
    c.iovs.reserve(2 * maxb);
    size_t total = 0;
    size_t j = i;
    while (j < n && (j - i) < maxb) {
      WirePart& b = *run[j];
      if (j != i && healing && c.fixed_ok &&
          b.nbytes >= kUringFixedMinBytes)
        break;  // the fixed frame starts its own submission
      if (healing) {
        bool pre = (j == i) && pre_appended;
        if (!pre && j != i && !ring_has_space(st, b.nbytes))
          break;  // would evict under the pending batch: flush first
        Replay& r2 =
            pre ? st.ring.back() : ring_append(st, b.h, b.buf, b.nbytes);
        c.iovs.push_back({&r2.h, sizeof(WireHeader)});
        if (r2.h.nbytes)
          c.iovs.push_back({st.ring_buf.get() + r2.off,
                            static_cast<size_t>(r2.h.nbytes)});
      } else {
        c.iovs.push_back({&b.h, sizeof(WireHeader)});
        if (b.nbytes)
          c.iovs.push_back({const_cast<void*>(b.buf), b.nbytes});
      }
      total += sizeof(WireHeader) + b.nbytes;
      ++j;
    }
    size_t batched = j - i;
    throttle_stripe(st, total);
    size_t sent_total = 0;
    size_t iov_pos = 0;  // first iovec not yet fully on the wire
    while (sent_total < total) {
      c.mhs.assign(1, msghdr{});
      msghdr& mh = c.mhs[0];
      mh.msg_iov = c.iovs.data() + iov_pos;
      mh.msg_iovlen = c.iovs.size() - iov_pos;
      res.assign(1, kPending);
      io_uring_sqe* e = c.ring.get_sqe();
      e->opcode = IORING_OP_SENDMSG;
      e->fd = st.fd;
      e->addr = reinterpret_cast<uint64_t>(&mh);
      e->len = 1;
      e->msg_flags = MSG_NOSIGNAL | MSG_WAITALL;
      e->user_data = 0;
      IoStatus s = wait_all(1);
      if (s != IoStatus::kOk) return bail(s, i + batched);
      long long r = res[0];
      if (r == -EINTR || r == -EAGAIN) continue;
      if (r <= 0) {
        // error or -ECANCELED from a broken link: the stream byte
        // position is indeterminate — kill it and let replay
        // (healing) or fail-stop (not) own recovery
        kill_stream();
        errno = r < 0 ? (r == -ECANCELED ? EPIPE : static_cast<int>(-r))
                      : EPIPE;
        return bail(IoStatus::kError, i + batched);
      }
      sent_total += static_cast<size_t>(r);
      // short completion (pre-WAITALL-retry kernels or a signal
      // race): advance the iovec cursor and resubmit the tail —
      // never kill a healthy stream for a short write
      size_t adv = static_cast<size_t>(r);
      while (iov_pos < c.iovs.size() && adv >= c.iovs[iov_pos].iov_len) {
        adv -= c.iovs[iov_pos].iov_len;
        ++iov_pos;
      }
      if (adv && iov_pos < c.iovs.size()) {
        c.iovs[iov_pos].iov_base =
            static_cast<uint8_t*>(c.iovs[iov_pos].iov_base) + adv;
        c.iovs[iov_pos].iov_len -= adv;
      }
    }
    i += batched;
    pre_appended = false;
  }
  return IoStatus::kOk;
}

#endif  // T4J_HAVE_URING

// Write a run of frames for ONE stripe (caller holds st.send_mu).
// Small frames gather into sendmsg iovec batches (header + payload
// pairs, up to T4J_SENDMSG_BATCH frames / one syscall); frames at or
// above T4J_ZEROCOPY_MIN_BYTES go out individually with MSG_ZEROCOPY.
// With healing on, payloads are already in the replay arena and the
// iovecs point THERE (the arena copy is the only copy; the kernel
// reads the pinned arena pages) — with healing off, iovecs point at
// the caller's buffers and zerocopy sends are reaped before return.
// Returns kOk, or the first failure (frames up to it are either on
// the wire or in the ring).
IoStatus stripe_write(Stripe& st, WirePart** run, size_t n, bool healing,
                      const Deadline& dl, size_t* zc_out) {
  // every kernel crossing below lands on this stripe's tx counter
  TlsSyscallScope sysc_scope(&st.tx_syscalls);
#if T4J_HAVE_URING
  if (uring_active()) {
    if (!st.uring) {
      st.uring.reset(new UringSendCtx);
      st.uring->ok = st.uring->ring.open_ring(512);
    }
    if (st.uring->ok) return stripe_write_uring(st, run, n, healing, dl);
    // ring setup failed (fd limits, seccomp): quiet sticky fallback
    // to the classic path on this stripe — wire bytes are identical
  }
#endif
  long long zc_min = zc_min_bytes();
  int batch_cap = sendmsg_batch();
  if (batch_cap > 256) batch_cap = 256;  // IOV_MAX safety (2 iov/frame)
  std::vector<iovec> iov;
  iov.reserve(2 * static_cast<size_t>(batch_cap));
  // On a failure mid-run (healing), every frame from `next` on must
  // still land in the replay ring — the repair cycle is the only
  // redelivery path, and a frame that is neither on the wire nor in
  // the ring would be silently lost.  Over-capacity eviction here is
  // DETECTED loss (the repair handshake escalates when the peer needs
  // an evicted seq), matching the documented "grow T4J_REPLAY_BYTES"
  // contract.
  auto bail = [&](IoStatus s, size_t next) {
    if (healing)
      for (size_t k = next; k < n; ++k)
        ring_append(st, run[k]->h, run[k]->buf, run[k]->nbytes);
    return s;
  };
  size_t i = 0;
  while (i < n) {
    WirePart& w = *run[i];
    const uint8_t* payload = static_cast<const uint8_t*>(w.buf);
    Replay* rep = nullptr;
    if (healing) {
      rep = &ring_append(st, w.h, w.buf, w.nbytes);
      payload = st.ring_buf.get() + rep->off;
    }
    bool zc = st.zc_enabled && zc_min > 0 && w.nbytes &&
              static_cast<long long>(w.nbytes) >= zc_min;
    if (zc) {
#if T4J_HAVE_ZEROCOPY
      throttle_stripe(st, sizeof(WireHeader) + w.nbytes);
      // header rides a plain MSG_MORE write (40 B — not worth pinning,
      // and pinning it would outlive the caller's stack frame); the
      // payload goes zerocopy and uncorks it.  Each successful
      // sendmsg(MSG_ZEROCOPY) call issues one completion id.
      iovec hi[1] = {{&w.h, sizeof(w.h)}};
#ifdef MSG_MORE
      IoStatus s1 = nb_write_all(st.fd, hi, 1, dl, false, MSG_MORE);
#else
      IoStatus s1 = nb_write_all(st.fd, hi, 1, dl);
#endif
      if (s1 != IoStatus::kOk) return bail(s1, i + 1);
      size_t left = w.nbytes;
      const uint8_t* ptr = payload;
      while (left > 0) {
        iovec pv{const_cast<uint8_t*>(ptr), left};
        msghdr mh{};
        mh.msg_iov = &pv;
        mh.msg_iovlen = 1;
        count_syscall();
        ssize_t wr = ::sendmsg(st.fd, &mh, MSG_NOSIGNAL | MSG_ZEROCOPY);
        if (wr < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) {
            int rc = io_wait(st.fd, POLLOUT, dl);
            if (rc == 1) continue;
            return bail(rc == 0 ? IoStatus::kTimeout : IoStatus::kStopped,
                        i + 1);
          }
          if (errno == ENOBUFS) {
            // optmem exhausted: reap and fall back to the copy path
            // for the remainder of this frame
            reap_zc(st);
            iovec cv{const_cast<uint8_t*>(ptr), left};
            IoStatus s2 = nb_write_all(st.fd, &cv, 1, dl);
            if (s2 != IoStatus::kOk) return bail(s2, i + 1);
            left = 0;
            break;
          }
          return bail(IoStatus::kError, i + 1);
        }
        ++st.zc_sent;
        if (rep) rep->zc_id = st.zc_sent;  // pins the arena entry
        if (zc_out) *zc_out += 1;
        ptr += wr;
        left -= static_cast<size_t>(wr);
      }
      reap_zc(st);  // opportunistic: keep the errqueue short
      if (!healing) {
        // no arena copy exists: the caller's buffer is the pinned
        // storage and may be reused the moment we return — block on
        // the completion (prompt on loopback; bounded by the deadline)
        if (!zc_wait(st, st.zc_sent, dl))
          return g_stop.load(std::memory_order_acquire)
                     ? IoStatus::kStopped
                     : IoStatus::kTimeout;
      }
      ++i;
      continue;
#endif
    }
    // Gather batch: this frame plus following non-zerocopy frames,
    // one sendmsg per batch.  Under healing every frame is appended
    // to the replay ring and its iovecs point at the ARENA copy; an
    // append can evict older entries or grow (replace) the arena, so
    // the pending batch is FLUSHED first whenever the next append
    // could not be satisfied without evicting — the iovec list never
    // holds a pointer into arena space an eviction could hand to a
    // later frame, and a same-batch frame can never be evicted before
    // it hits the wire.  (Deque references themselves survive
    // push_back/pop_front of other elements; only the arena bytes
    // need the flush discipline.)
    iov.clear();
    size_t batched = 0;
    auto flush = [&]() -> IoStatus {
      if (iov.empty()) return IoStatus::kOk;
      size_t total = 0;
      for (const iovec& v : iov) total += v.iov_len;
      throttle_stripe(st, total);
      IoStatus s = nb_write_all(st.fd, iov.data(),
                                static_cast<int>(iov.size()), dl);
      iov.clear();
      return s;
    };
    size_t j = i;
    while (j < n && batched < static_cast<size_t>(batch_cap)) {
      WirePart& b = *run[j];
      if (st.zc_enabled && zc_min > 0 && b.nbytes &&
          static_cast<long long>(b.nbytes) >= zc_min && j != i)
        break;  // the zerocopy frame starts its own write
      if (healing) {
        if (!ring_has_space(st, b.nbytes)) {
          // the append would evict: put the pending batch on the wire
          // first (its arena bytes must not be reused under it)
          IoStatus s = flush();
          if (s != IoStatus::kOk) return bail(s, j);
        }
        Replay& r2 = ring_append(st, b.h, b.buf, b.nbytes);
        iov.push_back({&r2.h, sizeof(r2.h)});
        if (r2.h.nbytes)
          iov.push_back({st.ring_buf.get() + r2.off,
                         static_cast<size_t>(r2.h.nbytes)});
      } else {
        iov.push_back({&b.h, sizeof(b.h)});
        if (b.nbytes)
          iov.push_back({const_cast<void*>(b.buf), b.nbytes});
      }
      ++batched;
      ++j;
    }
    IoStatus s = flush();
    if (s != IoStatus::kOk) return bail(s, i + batched);
    i += batched;
  }
  return IoStatus::kOk;
}

void mark_stripe_broken(int peer, int stripe, const std::string& why);

// Send `nparts` frames to one TCP peer through the striped wire path:
// deal (seq + stripe), group per stripe, and write each stripe's run
// with gather batching / zerocopy / the emulated flow throttle.  A
// broken stripe blind-buffers into its replay ring (bounded) instead
// of stalling the caller while siblings flow — the repair cycle
// redelivers; only a FULL ring blocks, and only on that stripe.
void link_send(int world_dest, int ctx, int tag, const void** bufs,
               const size_t* sizes, size_t nparts) {
  PeerLink& p = g_peers[world_dest];
  bool healing = resilience_on() &&
                 !g_finalizing.load(std::memory_order_acquire);
  if (p.nstripes == 0 || (p.s[0].fd < 0 && !healing && p.nstripes == 1))
    fail_arg("send to unconnected peer r" + std::to_string(world_dest));
  double limit_s = effective_op_timeout();
  Deadline dl = Deadline::after(limit_s);
  InflightScope busy;  // tighten the io poll tick while we send
  for (size_t i = 0; i < nparts; ++i) maybe_inject_send_fault();
  std::vector<WirePart> parts(nparts);
  for (size_t i = 0; i < nparts; ++i) {
    parts[i].buf = bufs[i];
    parts[i].nbytes = sizes[i];
  }
  deal_frames(p, ctx, tag, parts.data(), nparts, healing);
  // group per stripe, preserving per-stripe order
  std::vector<std::vector<WirePart*>> runs(p.nstripes);
  for (WirePart& w : parts) runs[w.stripe].push_back(&w);
  // Runs drain stripe by stripe; a migration/redeal can move frames
  // onto an already-visited stripe, so sweep until every run is empty.
  bool pending = true;
  while (pending) {
    pending = false;
    for (int si = 0; si < p.nstripes; ++si) {
      if (runs[si].empty()) continue;
      Stripe& st = p.s[si];
      if (g_stop.load(std::memory_order_acquire)) raise_stopped();
      bool blind = false;
      bool stripe_dead =
          ((p.dead_mask.load(std::memory_order_relaxed) >> si) & 1) != 0;
      if (!stripe_dead) {
        std::unique_lock<std::mutex> slk(st.mu);
        if (st.state != Stripe::kUp) {
          if (!healing) {
            slk.unlock();
            raise_stopped();
          }
          blind = st.state == Stripe::kBroken;
          stripe_dead = st.state == Stripe::kDead;
        }
      }
      if (stripe_dead) {
        // a dead stripe's ring migrated (one-shot): frames must NOT
        // buffer here — redeal onto a live sibling.  No live sibling
        // means the link is (about to be) dead: surface the stop.
        {
          std::lock_guard<std::mutex> dlk(p.deal_mu);
          for (WirePart* w : runs[si]) {
            int cand = pick_live_stripe(p);
            if (cand < 0 || cand == si) {
              raise_stopped();
            }
            w->stripe = cand;
            runs[cand].push_back(w);
          }
        }
        runs[si].clear();
        pending = true;  // re-sweep: the new homes still hold the run
        continue;
      }
      if (blind) {
        // broken stripe: buffer the run into the replay ring so
        // siblings never stall; the repair redelivers it.  The state
        // is re-checked under send_mu — a stripe that died (and
        // migrated its ring) between our peek and the lock must not
        // swallow frames.  Single-flow links keep the legacy
        // behaviour (block for the verdict): T4J_STRIPES=1 must stay
        // byte- and timing-stable vs HEAD.
        if (p.nstripes > 1) {
          bool buffered = false;
          bool died = false;
          {
            std::lock_guard<std::mutex> slk(st.send_mu);
            if (st.migrated) {
              died = true;
            } else {
              bool fits = true;
              for (WirePart* w : runs[si])
                if (!ring_has_space(st, w->nbytes)) {
                  fits = false;
                  break;
                }
              if (fits) {
                for (WirePart* w : runs[si])
                  ring_append(st, w->h, w->buf, w->nbytes);
                buffered = true;
              }
            }
          }
          if (died) {
            pending = true;  // redealt by the stripe_dead branch above
            continue;        // (next sweep sees the dead_mask bit)
          }
          if (buffered) {
            runs[si].clear();
            continue;
          }
        }
        wait_stripe_up(world_dest, si, dl, runs[si].front()->nbytes,
                       tag, limit_s);
        pending = true;  // re-sweep: up again, or dead and redealt
        continue;
      }
      IoStatus wst;
      int saved_errno = 0;
      size_t zc_frames = 0;
      {
        // failure handling happens OUTSIDE this scope: fail_op
        // broadcasts the abort, and broadcast_abort try_locks every
        // stripe's send_mu — including this one
        std::lock_guard<std::mutex> slk(st.send_mu);
        wst = stripe_write(st, runs[si].data(), runs[si].size(),
                           healing, dl, &zc_frames);
        saved_errno = errno;
      }
      switch (wst) {
        case IoStatus::kOk:
          for (WirePart* w : runs[si])
            tel::trace_event(tel::kFrameTx, tel::kInstant,
                             tel::kPlaneNone, si, world_dest,
                             w->nbytes);
          runs[si].clear();
          continue;
        case IoStatus::kTimeout:
          fail_op("send of " +
                  std::to_string(runs[si].front()->nbytes) +
                  " bytes to peer r" + std::to_string(world_dest) +
                  " (tag " + std::to_string(tag) + ", stripe " +
                  std::to_string(si) + ") made no progress for " +
                  std::to_string(limit_s) + "s (" + deadline_knob() +
                  ") — peer stalled or not draining");
        case IoStatus::kStopped:
          raise_stopped();
        default:
          if (healing) {
            // every frame of this run is in the stripe's replay ring
            // (stripe_write appends before writing): hand delivery to
            // the repair cycle.  Siblings' runs continue; single-flow
            // links additionally wait for the verdict (legacy
            // semantics).
            mark_stripe_broken(world_dest, si,
                               std::string("send failed: ") +
                                   std::strerror(saved_errno));
            if (p.nstripes == 1)
              wait_stripe_up(world_dest, si, dl,
                             runs[si].front()->nbytes, tag, limit_s);
            runs[si].clear();
            continue;
          }
          fail_op("send to peer r" + std::to_string(world_dest) +
                  " failed: " + std::strerror(saved_errno) +
                  " (peer process likely dead)");
      }
    }
  }
}

void raw_send(int world_dest, int ctx, int tag, const void* buf,
              size_t nbytes) {
  if (g_stop.load(std::memory_order_acquire)) raise_stopped();
  if (world_dest == g_rank) {
    Frame f;
    f.src = g_rank;
    f.ctx = ctx;
    f.tag = tag;
    f.data = Buf(nbytes);
    if (nbytes) std::memcpy(f.data.data(), buf, nbytes);
    {
      std::lock_guard<std::mutex> lk(g_mail_mu);
      g_mailbox.push_back(std::move(f));
    }
    g_mail_cv.notify_all();
    poke_engine();
    tel::trace_event(tel::kFrameTx, tel::kInstant, tel::kPlaneNone, -1,
                     world_dest, nbytes);
    return;
  }
  if (world_dest < static_cast<int>(g_tx_pipes.size()) &&
      g_tx_pipes[world_dest]) {
    maybe_inject_send_fault();
    WireHeader h{kMagic, static_cast<uint32_t>(g_rank),
                 static_cast<uint32_t>(ctx),
                 static_cast<uint32_t>(tag + 1),
                 static_cast<uint64_t>(nbytes), 0, cur_epoch(), 0};
    shm::Pipe* pipe = g_tx_pipes[world_dest];
    PeerLink& pp = g_peers[world_dest];
    std::lock_guard<std::mutex> lk(pp.pipe_mu);  // one producer per pipe
    // g_stop (not just the shutdown flag): a fault posted while we are
    // blocked on a full pipe with a dead consumer must unblock us
    if (!shm::pipe_write(pipe, &h, sizeof(h), g_stop) ||
        (nbytes && !shm::pipe_write(pipe, buf, nbytes, g_stop))) {
      if (g_shutting_down.load())
        throw BridgeError(err_prefix() + std::string(cur_op()) +
                          ": shm pipe write during shutdown");
      raise_stopped();
    }
    tel::trace_event(tel::kFrameTx, tel::kInstant, tel::kPlaneShm, -1,
                     world_dest, nbytes);
    return;
  }
  const void* bufs[1] = {buf};
  size_t sizes[1] = {nbytes};
  link_send(world_dest, ctx, tag, bufs, sizes, 1);
}

// The one envelope-matching rule (MPI matching semantics: exact ctx,
// source/tag exact or wildcard).  Every mailbox scan — blocking
// raw_recv, the engine's parked-irecv poll, and its pre-sleep ready
// check — must go through this so the paths can never disagree.
inline bool frame_matches(const Frame& f, int ctx, int world_source,
                          int tag) {
  if (f.ctx != ctx) return false;
  if (world_source != kAnySource && f.src != world_source) return false;
  if (tag != kAnyTag && f.tag != tag) return false;
  return true;
}

// Blocking matched receive from the mailbox (MPI matching semantics:
// FIFO per (source, ctx, tag) with wildcards), bounded by the per-op
// progress deadline when one is configured.
Frame raw_recv(int world_source, int ctx, int tag) {
  double limit_s = effective_op_timeout();
  Deadline dl = Deadline::after(limit_s);
  std::unique_lock<std::mutex> lk(g_mail_mu);
  for (;;) {
    for (auto it = g_mailbox.begin(); it != g_mailbox.end(); ++it) {
      if (!frame_matches(*it, ctx, world_source, tag)) continue;
      Frame f = std::move(*it);
      g_mailbox.erase(it);
      return f;
    }
    if (g_stop.load(std::memory_order_acquire)) {
      lk.unlock();
      raise_stopped();
    }
    if (dl.expired()) {
      lk.unlock();
      std::string src = world_source == kAnySource
                            ? std::string("ANY_SOURCE")
                            : "r" + std::to_string(world_source);
      std::string tg = tag == kAnyTag ? std::string("ANY_TAG")
                                      : std::to_string(tag);
      fail_op("no matching message from " + src + " (tag " + tg +
              ") within " + std::to_string(limit_s) + "s (" +
              deadline_knob() +
              ") — mismatched send/recv, dead peer, or a peer running "
              "behind");
    }
    if (dl.bounded)
      // adaptive tick (io_tick_ms): tight while frames are moving so
      // a notify raced against the deadline check costs ~5ms, lazy
      // when the rank is idle so bounded recvs don't spin
      g_mail_cv.wait_for(
          lk, std::chrono::milliseconds(dl.remaining_ms(io_tick_ms())));
    else
      // unbounded (the default): sleep until notified — post_fault and
      // raw_send both notify under g_mail_mu, so no wakeup can be lost
      g_mail_cv.wait(lk);
  }
}

// ------------------------------------------------------------- bootstrap

// Explicit SO_*BUF disables kernel receive auto-tuning and is clamped
// by net.core.{r,w}mem_max — on stock sysctls the clamp (~416KB) would
// be WORSE than auto-tuning. Probe once whether the kernel honours a
// large request; only then pin buffers (before connect/listen, so the
// TCP window scale is negotiated with the enlarged buffer in place).
constexpr int kWantBuf = 8 << 20;

bool buf_honoured(int optname) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return false;
  int bufsz = kWantBuf;
  ::setsockopt(fd, SOL_SOCKET, optname, &bufsz, sizeof(bufsz));
  int got = 0;
  socklen_t len = sizeof(got);
  ::getsockopt(fd, SOL_SOCKET, optname, &got, &len);
  ::close(fd);
  return got >= kWantBuf;  // kernel reports doubled value when honoured
}

void presize_buffers(int fd) {
  // each direction is governed by its own sysctl (wmem_max / rmem_max):
  // pin only the side the kernel honours, keep auto-tuning on the other
  static const bool snd_ok = buf_honoured(SO_SNDBUF);
  static const bool rcv_ok = buf_honoured(SO_RCVBUF);
  int bufsz = kWantBuf;
  if (snd_ok) ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
  if (rcv_ok) ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
}

void tune_socket(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// Bootstrap-context failure: main-thread, nothing to broadcast yet
// (the mesh may not exist) — just throw with rank context.
[[noreturn]] void fail_boot(const std::string& what) {
  throw BridgeError(err_prefix() + "bootstrap: " + what);
}

int tcp_listen(uint16_t* port_out) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) fail_boot(std::string("socket: ") + std::strerror(errno));
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  presize_buffers(fd);  // accepted sockets inherit
  set_nonblock(fd);     // accept goes through the poll/deadline path
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(*port_out);
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
    fail_boot("bind to port " + std::to_string(*port_out) + ": " +
              std::strerror(errno));
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  *port_out = ntohs(addr.sin_port);
  if (::listen(fd, 128) < 0)
    fail_boot(std::string("listen: ") + std::strerror(errno));
  return fd;
}

// Deadline-bounded accept with attributable context: `who` names what
// we are waiting for ("rank check-ins at the coordinator", ...).
int tcp_accept(int listen_fd, const Deadline& dl, const std::string& who) {
  for (;;) {
    sockaddr_in peer{};
    socklen_t len = sizeof(peer);
    int fd = ::accept(listen_fd, reinterpret_cast<sockaddr*>(&peer), &len);
    if (fd >= 0) return fd;
    if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR &&
        errno != ECONNABORTED)
      fail_boot("accept (" + who + "): " + std::strerror(errno));
    int w = io_wait(listen_fd, POLLIN, dl);
    if (w == 0)
      fail_boot("timed out after " + std::to_string(connect_timeout()) +
                "s (T4J_CONNECT_TIMEOUT) waiting for " + who +
                " — a rank failed to start, died during startup, or is "
                "unreachable");
    if (w < 0) raise_stopped();
  }
}

// Single bounded connect attempt (no retry loop, never throws): the
// callers' loops — bootstrap's tcp_connect and the reconnect dialer —
// own the retry policy.  `dl` bounds the in-progress wait; *stopped is
// set when the bridge stopped mid-wait.
int dial_once(const std::string& host, uint16_t port, const Deadline& dl,
              std::string* why, bool* stopped = nullptr,
              bool ignore_stop = false) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    *why = std::string("socket: ") + std::strerror(errno);
    return -1;
  }
  presize_buffers(fd);  // before connect: window scale negotiation
  set_nonblock(fd);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    *why = "bad address " + host;
    return -1;
  }
  int rc = ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  if (rc != 0 && errno == EINPROGRESS) {
    int w = io_wait(fd, POLLOUT, dl, ignore_stop);
    if (w == 1) {
      int soerr = 0;
      socklen_t slen = sizeof(soerr);
      ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
      if (soerr == 0) rc = 0;
      else *why = std::strerror(soerr);
    } else if (w < 0) {
      if (stopped) *stopped = true;
      *why = "bridge stopped";
    } else {
      *why = "timed out";
    }
  } else if (rc != 0) {
    *why = std::strerror(errno);
  }
  if (rc == 0) {
    tune_socket(fd);
    return fd;
  }
  ::close(fd);
  return -1;
}

// Bounded retrying connect.  `who` names the target for the failure
// message, and the retry cadence is the same exponential-backoff-with-
// jitter policy the reconnect path uses (T4J_BACKOFF_BASE/MAX) — one
// policy for bootstrap and recovery, instead of the old fixed 50ms
// spin.  The overall budget stays T4J_CONNECT_TIMEOUT.
int tcp_connect(const std::string& host, uint16_t port,
                const std::string& who) {
  {
    // a bad address is a config error, not a transient: fail now
    in_addr probe;
    if (::inet_pton(AF_INET, host.c_str(), &probe) != 1)
      fail_boot("bad address " + host +
                " (coordinator must be an IPv4 literal)");
  }
  Deadline dl = Deadline::after(connect_timeout());
  std::string why = "timed out";
  int attempt = 0;
  for (;;) {
    bool stopped = false;
    int fd = dial_once(host, port, dl, &why, &stopped);
    if (fd >= 0) return fd;
    if (stopped) raise_stopped();
    if (dl.expired())
      fail_boot("connect to " + who + " at " + host + ":" +
                std::to_string(port) + " failed after " +
                std::to_string(connect_timeout()) +
                "s (T4J_CONNECT_TIMEOUT): " + why);
    double delay = backoff_delay_s(attempt++);
    int left = dl.remaining_ms(static_cast<int>(delay * 1000));
    std::this_thread::sleep_for(std::chrono::milliseconds(left));
  }
}

// ----------------------------------------------------- link self-healing
//
// The repair cycle for a broken TCP link (docs/failure-semantics.md
// "self-healing transport"):
//
//   1. Any transport error (reader EOF/reset, writer EPIPE) calls
//      mark_broken: the link flips kUp -> kBroken, its fd is shut down
//      (waking both directions), blocked senders park on the link cv.
//   2. The HIGHER rank of the pair re-dials the lower rank's mesh
//      listener (the same orientation bootstrap used) with exponential
//      backoff + jitter, at most T4J_RETRY_MAX attempts.  The lower
//      rank's accept thread answers; a watchdog bounds its wait so an
//      idle acceptor cannot sit broken forever.
//   3. The two sides handshake (bootstrap incarnation token, link
//      epoch, last contiguous seq received) and each replays its
//      unacked tail out of the bounded replay ring.  Receivers drop
//      duplicate seqs, so replay is idempotent; in-flight collectives
//      just see their next segment arrive late and resume from the
//      last completed one.
//   4. Exhausted retries, a replay ring that no longer holds the
//      needed tail, or a handshake from a RESTARTED process (stale
//      incarnation token) escalate to the PR-1 fail-stop path: abort
//      broadcast + posted fault, job over.

// Terminal link verdict: no stripe can carry traffic any more.
// Outside teardown this is exactly today's fail-stop path — abort
// broadcast + posted fault.  The fault is posted BEFORE the states
// flip to kDead: a sender parked on a stripe cv must find the repair
// diagnostic in the fault slot when it wakes, not an empty "bridge
// already shut down".
void escalate_link(int peer, const std::string& why) {
  tel::control_event(tel::kLinkDead, peer, 0);
  // Elastic membership (docs/failure-semantics.md "elastic
  // membership"): an unrecoverable LINK to a peer is the signal that
  // the RANK is gone — with T4J_ELASTIC=shrink|rejoin the survivors
  // agree on a reduced world instead of aborting the whole job.  off
  // keeps the exact abort path below, byte for byte.
  std::string extra;
  if (elastic_usable()) {
    if (try_begin_resize(peer, why)) return;
    // the shrink was refused (world would fall below the floor, or
    // the peer is already accounted dead by an active resize): name
    // the reason next to the legacy escalation
    extra = " (T4J_ELASTIC: surviving world would fall below "
            "T4J_MIN_WORLD=" + std::to_string(min_world()) + ")";
  }
  PeerLink& p = g_peers[peer];
  if (!g_shutting_down.load() &&
      !g_stop.load(std::memory_order_acquire) &&
      !g_finalizing.load(std::memory_order_acquire)) {
    std::string msg = err_prefix() + "link to peer r" +
                      std::to_string(peer) + " could not be repaired (" +
                      why + ") — escalating to abort" + extra;
    broadcast_abort(msg);
    post_fault(msg);
  }
  for (int si = 0; si < p.nstripes; ++si) {
    Stripe& st = p.s[si];
    {
      std::lock_guard<std::mutex> lk(st.mu);
      st.state = Stripe::kDead;
      st.repairing = false;
    }
    st.cv.notify_all();
  }
  p.dead_mask.store(
      p.nstripes >= 32 ? ~0u : ((1u << p.nstripes) - 1),
      std::memory_order_relaxed);
}

// Move a dead stripe's replay tail onto the lowest live sibling: the
// frames are appended to the sibling's ring (its own future repairs
// must cover them too) and written out on its socket.  The receiver
// dedups by link seq, so frames the peer already had are harmless.
// Returns false when no live sibling exists.
bool migrate_stripe(int peer, int dead_si) {
  PeerLink& p = g_peers[peer];
  uint32_t dead = p.dead_mask.load(std::memory_order_relaxed);
  int tgt = -1;
  for (int si = 0; si < p.nstripes; ++si)
    if (si != dead_si && !((dead >> si) & 1)) {
      tgt = si;
      break;
    }
  if (tgt < 0) return false;
  Stripe& src = p.s[dead_si];
  Stripe& dst = p.s[tgt];
  // two-stripe lock order: lower index first (the only code path that
  // ever holds two stripe send_mus)
  Stripe& first = dead_si < tgt ? src : dst;
  Stripe& second = dead_si < tgt ? dst : src;
  std::lock_guard<std::mutex> lk1(first.send_mu);
  std::lock_guard<std::mutex> lk2(second.send_mu);
  uint64_t frames = 0, bytes = 0;
  IoStatus wst = IoStatus::kOk;
  for (Replay& r : src.ring) {
    size_t len = static_cast<size_t>(r.h.nbytes);
    Replay& nr = ring_append(dst, r.h, src.ring_buf.get() + r.off, len);
    if (wst == IoStatus::kOk && dst.fd >= 0) {
      iovec iov[2] = {{&nr.h, sizeof(nr.h)},
                      {dst.ring_buf.get() + nr.off, len}};
      wst = nb_write_all(dst.fd, iov, len ? 2 : 1,
                         Deadline::after(connect_timeout()));
    }
    ++frames;
    bytes += len;
  }
  src.ring.clear();
  src.ring_head = 0;
  // one-shot: anything a racing sender appends to src AFTER this has
  // no redelivery path — the flag (checked under send_mu) makes such
  // senders redeal onto a live sibling instead of buffering here
  src.migrated = true;
  std::fprintf(stderr,
               "r%d | t4j: stripe %d of link r%d is dead — migrated "
               "%llu frame(s) / %llu bytes onto stripe %d "
               "(siblings keep the link alive)\n",
               g_rank, dead_si, peer,
               static_cast<unsigned long long>(frames),
               static_cast<unsigned long long>(bytes), tgt);
  std::fflush(stderr);
  // a write failure mid-migration is fine: everything is in dst's
  // ring, and dst's own repair cycle redelivers (triggered by its
  // reader/writer noticing the break)
  return true;
}

void watchdog_repair(int peer, int stripe);

// Terminal STRIPE verdict.  With live siblings the link survives: the
// dead stripe's tail migrates and dealing skips it from now on — the
// link is dead only when every stripe is
// (docs/failure-semantics.md "per-stripe replay and escalation").
void escalate_stripe(int peer, int si, const std::string& why) {
  PeerLink& p = g_peers[peer];
  if (p.nstripes <= 1) {
    escalate_link(peer, why);
    return;
  }
  Stripe& st = p.s[si];
  {
    std::lock_guard<std::mutex> lk(st.mu);
    st.state = Stripe::kDead;
    st.repairing = false;
  }
  p.dead_mask.fetch_or(1u << si, std::memory_order_relaxed);
  st.cv.notify_all();
  tel::control_event(tel::kLinkDead, peer, 0, si);
  if (p.link_dead() || g_stop.load(std::memory_order_acquire)) {
    escalate_link(peer, why + " (no live stripe remains)");
    return;
  }
  std::fprintf(stderr,
               "r%d | t4j: stripe %d of link r%d could not be repaired "
               "(%s) — continuing on the surviving stripe(s)\n",
               g_rank, si, peer, why.c_str());
  std::fflush(stderr);
  if (!migrate_stripe(peer, si))
    escalate_link(peer, why + " (no live stripe remains)");
}

// Install the fresh connection on the stripe and replay its unacked
// tail.  `peer_has` is the LINK-level received watermark the peer
// reported in the handshake (frames at or below it were received —
// delivered or parked in its reorder stage; frames above it that
// arrived on other stripes dedup at the receiver).
// Returns false (with *why set) when this stripe's replay ring
// evicted a frame the peer may still need — the caller escalates.
// The caller must already have joined the stripe's old reader thread.
bool finish_repair(int peer, int si, int fd, uint64_t peer_has,
                   std::string* why) {
  PeerLink& p = g_peers[peer];
  Stripe& st = p.s[si];
  std::unique_lock<std::mutex> slk(st.send_mu);
  {
    std::lock_guard<std::mutex> lk(st.mu);
    if (st.state == Stripe::kDead ||
        g_stop.load(std::memory_order_acquire)) {
      ::close(fd);
      return true;  // verdict already reached elsewhere
    }
  }
  if (st.max_evicted_seq > peer_has) {
    *why = "peer is missing frame(s) up to seq " +
           std::to_string(st.max_evicted_seq) +
           " already evicted from this stripe's replay ring — grow "
           "T4J_REPLAY_BYTES";
    ::close(fd);
    return false;
  }
  int old = st.fd;
  st.fd = fd;
  if (old >= 0) ::close(old);
  stripe_enable_zc(st);
  uint32_t ep;
  {
    std::lock_guard<std::mutex> lk(st.mu);
    st.state = Stripe::kUp;
    ep = ++st.epoch;
    st.repairing = false;
    st.reconnects.fetch_add(1, std::memory_order_relaxed);
  }
  // reader first, replay second: the peer replays its own tail
  // concurrently, and a reader consuming it keeps two large opposing
  // tails from deadlocking against full kernel buffers
  {
    std::lock_guard<std::mutex> jk(st.join_mu);
    st.reader = std::thread(reader_loop, peer, si, fd);
  }
  st.cv.notify_all();
  uint64_t frames = 0, bytes = 0;
  IoStatus wst = IoStatus::kOk;
  for (Replay& r : st.ring) {
    if (r.h.seq <= peer_has) continue;
    size_t len = static_cast<size_t>(r.h.nbytes);
    iovec iov[2] = {{&r.h, sizeof(r.h)},
                    {st.ring_buf.get() + r.off, len}};
    wst = nb_write_all(st.fd, iov, len ? 2 : 1,
                       Deadline::after(connect_timeout()));
    if (wst != IoStatus::kOk) break;
    ++frames;
    bytes += len;
  }
  st.replayed_frames.fetch_add(frames, std::memory_order_relaxed);
  st.replayed_bytes.fetch_add(bytes, std::memory_order_relaxed);
  tel::control_event(tel::kReconnect, peer, bytes, si);
  if (frames) tel::control_event(tel::kReplay, peer, bytes, si);
  std::fprintf(stderr,
               "r%d | t4j: link to peer r%d reconnected (stripe %d, "
               "epoch %u, replayed %llu frame(s) / %llu bytes)\n",
               g_rank, peer, si, ep,
               static_cast<unsigned long long>(frames),
               static_cast<unsigned long long>(bytes));
  std::fflush(stderr);
  if (wst != IoStatus::kOk && !g_stop.load(std::memory_order_acquire)) {
    // the fresh connection broke again mid-replay: the un-replayed
    // tail is still in the ring, so start another cycle
    slk.unlock();
    mark_stripe_broken(peer, si, "link dropped again during replay");
  }
  return true;
}

// Active (dialer-side) repair: the higher rank of the pair re-dials
// the lower rank's mesh listener with backoff, handshakes, replays —
// one cycle per STRIPE, so one dropped flow repairs while its
// siblings keep carrying traffic.
void dial_repair(int peer, int si) {
  PeerLink& p = g_peers[peer];
  Stripe& st = p.s[si];
  {
    std::lock_guard<std::mutex> jk(st.join_mu);
    if (st.reader.joinable()) st.reader.join();
  }
  std::string why = "connection lost";
  int attempts = retry_max();
  for (int a = 0; a < attempts; ++a) {
    if (a > 0 && !backoff_sleep(backoff_delay_s(a - 1))) return;
    if (g_stop.load(std::memory_order_acquire)) return;
    int fd = dial_once(g_endpoints[peer].host, g_endpoints[peer].port,
                       Deadline::after(connect_timeout()), &why);
    if (fd < 0) continue;
    Deadline dl = Deadline::after(connect_timeout());
    ReconHello hello{kReconMagic, static_cast<uint32_t>(g_rank),
                     g_my_boot_token, st.epoch,
                     static_cast<uint32_t>(si),
                     link_recv_watermark(p)};
    iovec hi[1] = {{&hello, sizeof(hello)}};
    if (nb_write_all(fd, hi, 1, dl) != IoStatus::kOk) {
      ::close(fd);
      why = "reconnect hello stalled";
      continue;
    }
    ReconReply rep{};
    if (nb_read_all(fd, &rep, sizeof(rep), dl) != IoStatus::kOk) {
      ::close(fd);
      why = "no reconnect reply";
      continue;
    }
    if (rep.magic != kReconMagic) {
      ::close(fd);
      why = "garbled reconnect reply";
      continue;
    }
    if (rep.boot_token != g_endpoints[peer].boot_token) {
      ::close(fd);
      escalate_link(peer,
                    "the listener answered with an unknown bootstrap "
                    "fingerprint — peer restarted, its in-flight state "
                    "is unrecoverable");
      return;
    }
    if (!rep.ok) {
      ::close(fd);
      escalate_stripe(peer, si, "peer rejected the reconnect handshake");
      return;
    }
    {
      // adopt the acceptor's epoch: ours may have fallen behind if a
      // previous repair's reply was lost to a second drop, and both
      // sides must enter finish_repair's bump in sync
      std::lock_guard<std::mutex> lk(st.mu);
      if (rep.epoch > st.epoch) st.epoch = rep.epoch;
    }
    if (!finish_repair(peer, si, fd, rep.last_recv_seq, &why))
      escalate_stripe(peer, si, why);
    return;
  }
  escalate_stripe(peer, si,
                  why + " after " + std::to_string(attempts) +
                      " reconnect attempt(s) (T4J_RETRY_MAX)");
}

// Passive (acceptor-side) bound: the lower rank waits for the peer's
// re-dial; past the dialer's PER-STRIPE worst-case retry budget the
// stripe is declared dead so an idle acceptor cannot sit broken
// forever (sibling stripes keep their own budgets and their own
// traffic).
void watchdog_repair(int peer, int si) {
  PeerLink& p = g_peers[peer];
  Stripe& st = p.s[si];
  Deadline dl = Deadline::after(repair_budget_s());
  // Elastic mode probes the peer's mesh listener while waiting: the
  // listener is open for the peer PROCESS's whole lifetime, so a
  // refused dial means the process is gone and the resize can start
  // now instead of after the full repair budget (which is sized for a
  // live-but-redialing peer).  Off-mode behaviour is untouched — the
  // probe only runs when an escalation could go elastic.
  Deadline next_probe = Deadline::after(0.5);
  int refused = 0;
  std::unique_lock<std::mutex> lk(st.mu);
  while (st.state == Stripe::kBroken) {
    if (g_stop.load(std::memory_order_acquire)) return;
    if (dl.expired()) {
      lk.unlock();
      escalate_stripe(peer, si,
                      "no reconnect from the peer within the retry "
                      "budget — peer dead or unreachable");
      return;
    }
    if (elastic_mode() != kElasticOff && next_probe.expired()) {
      lk.unlock();
      std::string why;
      int fd = dial_once(g_endpoints[peer].host, g_endpoints[peer].port,
                         Deadline::after(1.0), &why);
      if (fd >= 0) {
        ::close(fd);
        refused = 0;  // listener up: the peer lives, keep waiting
      } else if (why == std::strerror(ECONNREFUSED)) {
        if (++refused >= 3) {
          escalate_link(peer,
                        "peer's mesh listener refuses connections — "
                        "process dead");
          return;
        }
      }
      next_probe = Deadline::after(0.5);
      lk.lock();
      continue;
    }
    st.cv.wait_for(lk, std::chrono::milliseconds(100));
  }
}

void mark_stripe_broken(int peer, int si, const std::string& why) {
  if (peer < 0 || peer >= g_size || peer == g_rank) return;
  PeerLink& p = g_peers[peer];
  if (si < 0 || si >= p.nstripes) return;
  Stripe& st = p.s[si];
  if (g_resizing.load(std::memory_order_acquire)) {
    // an elastic resize owns every link right now: the rebuild
    // replaces them wholesale, so per-stripe repair cycles would only
    // race it (and noisily re-establish old-epoch connections)
    std::lock_guard<std::mutex> lk(st.mu);
    if (st.state == Stripe::kUp) st.state = Stripe::kBroken;
    st.cv.notify_all();
    return;
  }
  bool spawn = false;
  {
    std::lock_guard<std::mutex> lk(st.mu);
    if (st.state != Stripe::kUp) return;  // a cycle is already running
    tel::control_event(tel::kLinkBreak, peer, 0, si);
    st.state = Stripe::kBroken;
    if (!st.repairing) {
      st.repairing = true;
      spawn = true;
    }
  }
  // wake both directions: the blocked writer fails over to the cv
  // wait, the reader drains out and exits.  fd is only stable under
  // send_mu (finish_repair swaps it there, finalize closes it there);
  // no caller of mark_stripe_broken holds this stripe's send_mu, so a
  // blocking acquire is safe and bounded (writers on a dead fd error
  // out fast).
  {
    std::lock_guard<std::mutex> lk(st.send_mu);
    if (st.fd >= 0) ::shutdown(st.fd, SHUT_RDWR);
  }
  st.cv.notify_all();
  std::fprintf(stderr,
               "r%d | t4j: link to peer r%d broke (stripe %d: %s) — "
               "reconnecting (T4J_RETRY_MAX=%d)\n",
               g_rank, peer, si, why.c_str(), retry_max());
  std::fflush(stderr);
  if (spawn) {
    // bootstrap orientation: the higher rank dialed, so it re-dials;
    // the lower rank's accept thread answers and a watchdog bounds it
    if (g_rank > peer)
      std::thread(dial_repair, peer, si).detach();
    else
      std::thread(watchdog_repair, peer, si).detach();
  }
}

// One reconnect dial, handled on its own detached thread so several
// broken links to this rank repair concurrently (a NIC blip breaks
// them all at once, and a serial acceptor would let later dialers
// exhaust their retry budget waiting in the backlog).
void handle_reconnect(int fd) {
  Deadline dl = Deadline::after(connect_timeout());
  ReconHello hello{};
  // ignore_stop: during an elastic resize g_stop is set, but THIS
  // listener carries the membership agreement — the read must
  // proceed (the deadline still bounds it)
  if (nb_read_all(fd, &hello, sizeof(hello), dl,
                  /*ignore_stop=*/true) != IoStatus::kOk) {
    ::close(fd);
    return;
  }
  if (hello.magic == kResizeMagic) {
    // elastic-membership control dial (same 32-byte first read as the
    // reconnect hello; the magic disambiguates)
    ResizeMsg m{};
    std::memcpy(&m, &hello, sizeof(m));
    handle_resize_msg(fd, m);
    return;
  }
  if (hello.magic != kReconMagic) {
    ::close(fd);  // not a reconnect dial: stray/garbled connection
    return;
  }
  int r = static_cast<int>(hello.rank);
  int si = static_cast<int>(hello.pad);  // dialing stripe index
  auto reject = [&]() {
    ReconReply rep{kReconMagic, 0, g_my_boot_token, 0, 0, 0};
    iovec iov[1] = {{&rep, sizeof(rep)}};
    (void)nb_write_all(fd, iov, 1, dl);
    ::close(fd);
  };
  if (r <= g_rank || r >= g_size || !resilience_on()) {
    reject();
    return;
  }
  PeerLink& p = g_peers[r];
  if (si < 0 || si >= p.nstripes) {
    reject();
    return;
  }
  Stripe& st = p.s[si];
  if (hello.boot_token != g_endpoints[r].boot_token) {
    // a RESTARTED process re-dialing under an old identity: its
    // mailbox and comm state are gone, recovery is impossible
    reject();
    escalate_link(r,
                  "reconnect dial carried a stale bootstrap "
                  "fingerprint — peer restarted, its in-flight state "
                  "is unrecoverable");
    return;
  }
  if (st.accept_busy.exchange(true)) {
    ::close(fd);  // a handler for this stripe is mid-handshake already;
    return;       // the dialer's next attempt restarts the dance
  }
  struct ClearBusy {
    std::atomic<bool>& f;
    ~ClearBusy() { f.store(false); }
  } clear_busy{st.accept_busy};
  uint32_t ep_now;
  {
    std::lock_guard<std::mutex> lk(st.mu);
    if (st.state == Stripe::kDead) {
      reject();
      return;
    }
    // Any authentic (token-verified) dial is honoured, even against a
    // stripe we consider healthy or with a lagging epoch: the peer
    // runs at most ONE serial dialer per stripe and only dials when
    // ITS side broke, so "stale dial against a healthy stripe" cannot
    // occur — but a dialer whose previous reply was lost to a second
    // drop (the flaky regime) legitimately arrives with an older
    // epoch and must not be bounced into the abort path.  Epochs stay
    // a monotonic diagnostic: adopt the newer of the two (the reply
    // hands ours back, which the dialer adopts) so both sides
    // re-enter finish_repair's bump in sync.
    if (hello.epoch > st.epoch) st.epoch = hello.epoch;
    ep_now = st.epoch;
  }
  // force-break if we had not noticed the drop yet (one-sided breaks
  // are normal: the side that wrote sees the error first);
  // mark_stripe_broken also spawns the watchdog bounding the handshake
  mark_stripe_broken(r, si, "peer re-dialed");
  {
    std::lock_guard<std::mutex> jk(st.join_mu);
    if (st.reader.joinable()) st.reader.join();
  }
  ReconReply rep{kReconMagic, 1, g_my_boot_token, ep_now, 0,
                 link_recv_watermark(p)};
  iovec iov[1] = {{&rep, sizeof(rep)}};
  if (nb_write_all(fd, iov, 1, dl) != IoStatus::kOk) {
    ::close(fd);  // dialer gave up: its next attempt restarts the dance
    return;
  }
  std::string why;
  if (!finish_repair(r, si, fd, hello.last_recv_seq, &why))
    escalate_stripe(r, si, why);
}

// Reconnect acceptor: owns the mesh listener after bootstrap and
// hands each dial to its own handler thread.
void accept_loop() {
  // g_stop alone must not end the acceptor: an elastic resize sets it
  // while the membership agreement is still flowing through THIS
  // listener.  The acceptor ends on teardown, or on a terminal stop
  // (fault/finalize) with no resize in progress.
  while (!g_shutting_down.load(std::memory_order_acquire) &&
         (!g_stop.load(std::memory_order_acquire) ||
          g_resizing.load(std::memory_order_acquire))) {
    pollfd pfd{g_listen_fd, POLLIN, 0};
    int rc = ::poll(&pfd, 1, 100);
    if (rc <= 0) continue;
    sockaddr_in peer{};
    socklen_t len = sizeof(peer);
    int fd = ::accept(g_listen_fd,
                      reinterpret_cast<sockaddr*>(&peer), &len);
    if (fd < 0) continue;
    set_nonblock(fd);
    tune_socket(fd);
    std::thread(handle_reconnect, fd).detach();
  }
}

struct PeerAddr {
  uint32_t ip;
  uint16_t port;
  uint16_t pad;
  uint64_t host_fp;  // same value <=> same host (shm-transport eligible)
  uint64_t boot_token;  // per-process incarnation id (reconnect identity)
};
static_assert(sizeof(PeerAddr) == 24, "PeerAddr wire layout");

std::vector<uint64_t> g_host_fps;  // world_size entries
std::string g_job;                 // unique job id (shm segment namespace)

uint64_t host_fingerprint() {
  // FNV-1a over the boot uuid (unique per host+boot), the hostname,
  // and the IPC + mount namespace identities: two ranks only count as
  // "same host" for the shm transport when they share the kernel AND
  // can actually see one another's /dev/shm — containers on one node
  // share boot_id but have distinct ns inodes.
  uint64_t h = 1469598103934665603ULL;
  auto mix = [&h](const char* s, size_t n) {
    for (size_t i = 0; i < n; ++i) {
      h ^= static_cast<uint8_t>(s[i]);
      h *= 1099511628211ULL;
    }
  };
  FILE* f = std::fopen("/proc/sys/kernel/random/boot_id", "r");
  if (f) {
    char buf[64] = {0};
    size_t n = std::fread(buf, 1, sizeof(buf) - 1, f);
    std::fclose(f);
    mix(buf, n);
  }
  char host[256] = {0};
  ::gethostname(host, sizeof(host) - 1);
  mix(host, std::strlen(host));
  for (const char* ns : {"/proc/self/ns/ipc", "/proc/self/ns/mnt"}) {
    char link[128] = {0};
    ssize_t n = ::readlink(ns, link, sizeof(link) - 1);
    if (n > 0) mix(link, static_cast<size_t>(n));
  }
  // T4J_NO_SHM rides the fingerprint: a rank with shm disabled must
  // never be classified same-host by ENABLED peers, or a divergent env
  // (hand-launched ranks) would split the transport — member 0 falling
  // straight to TCP while the others block in the agreement rounds.
  // Mixed-in (not zeroed) so an all-disabled job still agrees among
  // itself and falls back together through the ok=0 round.
  if (shm::disabled()) mix("t4j-no-shm", 10);
  // T4J_EMU_LOCAL=k folds rank/k into the fingerprint so one box
  // emulates ceil(size/k) nodes of k local ranks: same-emulated-node
  // ranks keep the shm transports, cross-node pairs ride real TCP —
  // which is what lets the hierarchical path (and its tests/benches)
  // run on a single host.  The launcher propagates the env, so the
  // partition is uniform by construction.
  const char* emu = std::getenv("T4J_EMU_LOCAL");
  if (emu && emu[0]) {
    long k = std::atol(emu);
    if (k >= 1) {
      char tag[48];
      int m = std::snprintf(tag, sizeof(tag), "t4j-emu-node-%ld",
                            static_cast<long>(g_rank) / k);
      mix(tag, static_cast<size_t>(m));
    }
  }
  return h ? h : 1;
}

void pipe_reader_loop(int peer, shm::Pipe* pipe) {
  for (;;) {
    WireHeader h;
    // g_stop: a posted fault must unblock the pipe reader too
    if (!shm::pipe_read(pipe, &h, sizeof(h), g_stop))
      return;  // shutdown or fault
    if (h.magic != kMagic) {
      post_fault(err_prefix() + "garbled shm-pipe frame from peer r" +
                 std::to_string(peer) + " (magic check failed)");
      return;
    }
    Frame f;
    f.src = static_cast<int>(h.src);
    f.ctx = static_cast<int>(h.ctx);
    f.tag = static_cast<int>(h.tag) - 1;
    f.data = Buf(h.nbytes);
    if (h.nbytes &&
        !shm::pipe_read(pipe, f.data.data(), h.nbytes, g_stop))
      return;
    if (h.epoch != cur_epoch()) {
      // stale-epoch pipe frame (see reader_loop): drop, never deliver
      g_stale_frames.fetch_add(1, std::memory_order_relaxed);
      continue;
    }
    {
      std::lock_guard<std::mutex> lk(g_mail_mu);
      g_mailbox.push_back(std::move(f));
    }
    g_mail_cv.notify_all();
    poke_engine();
    tel::trace_event(tel::kFrameRx, tel::kInstant, tel::kPlaneShm, -1,
                     peer, h.nbytes);
  }
}

// Wire up the same-host pipe transport after the bootstrap table (and
// host fingerprints) exist.  Like the collective arena, the transport
// choice is AGREED over TCP so a partial failure can never split a
// pair across transports or aim a pipe at a reader-less segment:
//   round 1: every rank creates its own inbound segment, then the
//     group leader gathers "created" bytes and broadcasts the AND —
//     only after that does anyone attach (so a stale leaked segment
//     from a crashed prior run can never be attached: every name was
//     just unlinked+recreated by its owner);
//   round 2: attach results are gathered/broadcast the same way, and
//     pipes go live (g_tx_pipes published, readers started) only when
//     EVERY member succeeded — otherwise everyone drops to TCP.
// The agreement frames ride raw TCP (g_tx_pipes is still empty while
// the rounds run, so raw_send cannot route them through a pipe).
constexpr int kPipeTagCreated = (1 << 24) + 12;
constexpr int kPipeTagFinal = (1 << 24) + 13;

void setup_pipes() {
  {
    std::lock_guard<std::mutex> lk(g_pipe_pub_mu);
    g_tx_pipes.assign(g_size, nullptr);
  }
  if (g_size < 2 || static_cast<int>(g_host_fps.size()) != g_size) return;
  // the pipe segment namespace carries the world epoch: a resize
  // rebuilds the same-host transport from scratch over the SURVIVING
  // members, and epoch-suffixed names can never collide with the old
  // world's (already-unlinked) segments
  std::string pipe_job = g_job;
  if (cur_epoch() != 0)
    pipe_job += "_e" + std::to_string(cur_epoch());
  std::vector<int> local;  // same-host ALIVE world ranks, ascending
  for (int r = 0; r < g_size; ++r)
    if (rank_alive(r) && g_host_fps[r] == g_host_fps[g_rank])
      local.push_back(r);
  if (local.size() < 2) return;
  int leader = local[0];
  // the WORLD comm's collective channel (epoch-derived after a
  // resize): the agreement rounds must ride the current world's ctx
  int wctx = enc_ctx(g_world_ctx, /*coll=*/true);

  auto agree = [&](uint8_t mine, int tag) -> uint8_t {
    uint8_t ok = mine;
    if (g_rank == leader) {
      for (int r : local) {
        if (r == leader) continue;
        Frame f = raw_recv(r, wctx, tag);
        ok &= f.data.size() == 1 ? f.data.data()[0] : 0;
      }
      for (int r : local) {
        if (r == leader) continue;
        raw_send(r, wctx, tag, &ok, 1);
      }
    } else {
      raw_send(leader, wctx, tag, &mine, 1);
      Frame f = raw_recv(leader, wctx, tag);
      ok = f.data.size() == 1 ? f.data.data()[0] : 0;
    }
    return ok;
  };

  auto slot_of = [&](int dest, int src) {
    // source slot within dest's inbound segment: index of src in the
    // ascending same-host list with dest itself excluded
    int slot = 0;
    for (int r : local) {
      if (r == dest) continue;
      if (r == src) return slot;
      ++slot;
    }
    return -1;
  };
  int n_sources = static_cast<int>(local.size()) - 1;

  {
    std::lock_guard<std::mutex> lk(g_pipe_pub_mu);
    g_my_pipes = shm::pipes_create(pipe_job.c_str(), g_rank, n_sources);
  }
  if (!agree(g_my_pipes != nullptr, kPipeTagCreated)) {
    std::lock_guard<std::mutex> lk(g_pipe_pub_mu);
    if (g_my_pipes) {
      shm::pipes_destroy(g_my_pipes);
      g_my_pipes = nullptr;
    }
    return;
  }

  std::vector<shm::Pipe*> tx(g_size, nullptr);
  bool all_ok = true;
  for (int r : local) {
    if (r == g_rank) continue;
    tx[r] = shm::pipe_attach(pipe_job.c_str(), r, slot_of(r, g_rank),
                             n_sources);
    if (!tx[r]) {
      all_ok = false;
      break;
    }
  }
  if (!agree(all_ok, kPipeTagFinal)) {
    for (auto*& t : tx)
      if (t) {
        shm::pipe_close(t);
        t = nullptr;
      }
    std::lock_guard<std::mutex> lk(g_pipe_pub_mu);
    shm::pipes_destroy(g_my_pipes);
    g_my_pipes = nullptr;
    return;
  }
  // every peer holds its attached mapping now (the round-2 agreement
  // proves it): drop the segment NAME immediately, shrinking the crash
  // window that could leak /dev/shm to the few ms of setup itself
  shm::pipes_unlink(g_my_pipes);
  {
    std::lock_guard<std::mutex> lk(g_pipe_pub_mu);
    g_tx_pipes = std::move(tx);  // publish: raw_send may now route pipes
  }
  for (int r : local) {
    if (r == g_rank) continue;
    g_pipe_readers.v.emplace_back(
        pipe_reader_loop, r,
        shm::pipe_of(g_my_pipes, slot_of(g_rank, r)));
  }
}

// Deadline-bounded bootstrap read/write with attributable failures.
void boot_read(int fd, void* buf, size_t n, const std::string& what) {
  Deadline dl = Deadline::after(connect_timeout());
  switch (nb_read_all(fd, buf, n, dl)) {
    case IoStatus::kOk:
      return;
    case IoStatus::kEof:
      fail_boot(what + ": peer closed the connection mid-handshake "
                       "(rank died during startup)");
    case IoStatus::kTimeout:
      fail_boot(what + ": no data within " +
                std::to_string(connect_timeout()) +
                "s (T4J_CONNECT_TIMEOUT)");
    case IoStatus::kStopped:
      raise_stopped();
    default:
      fail_boot(what + ": " + std::strerror(errno));
  }
}

void boot_write(int fd, const void* buf, size_t n, const std::string& what) {
  Deadline dl = Deadline::after(connect_timeout());
  iovec iov[1] = {{const_cast<void*>(buf), n}};
  IoStatus st = nb_write_all(fd, iov, 1, dl);
  if (st == IoStatus::kOk) return;
  if (st == IoStatus::kStopped) raise_stopped();
  fail_boot(what + ": " +
            (st == IoStatus::kTimeout ? "stalled (T4J_CONNECT_TIMEOUT)"
                                      : std::strerror(errno)));
}

void bootstrap(const std::string& coord_host, uint16_t coord_port) {
  // Per-process incarnation token: the reconnect handshake's identity.
  // A restarted process gets a fresh token, so a re-dial from it can
  // never be mistaken for the recoverable peer bootstrap recorded.
  {
    std::mt19937_64 rng(std::random_device{}() ^
                        static_cast<uint64_t>(::getpid()));
    g_my_boot_token = rng();
    if (!g_my_boot_token) g_my_boot_token = 1;
  }

  // Every rank opens a listener for the full-mesh phase (kept open
  // afterwards as the reconnect listener when resilience is on).
  uint16_t my_port = 0;
  int listen_fd = tcp_listen(&my_port);

  std::vector<PeerAddr> table(g_size);

  uint64_t my_fp = host_fingerprint();

  if (g_rank == 0) {
    // phase 1: collect every rank's (ip, port, host_fp, boot_token) on
    // the coordinator socket
    uint16_t cport = coord_port;
    int coord_fd = tcp_listen(&cport);
    table[0] = PeerAddr{htonl(INADDR_LOOPBACK), my_port, 0, my_fp,
                        g_my_boot_token};
    std::vector<int> fds(g_size, -1);
    for (int i = 1; i < g_size; ++i) {
      Deadline dl = Deadline::after(connect_timeout());
      int fd = tcp_accept(coord_fd, dl,
                          std::to_string(g_size - i) +
                              " more rank check-in(s) at the coordinator");
      set_nonblock(fd);
      sockaddr_in peer{};
      socklen_t len = sizeof(peer);
      ::getpeername(fd, reinterpret_cast<sockaddr*>(&peer), &len);
      uint32_t rank_and_port[2];
      boot_read(fd, rank_and_port, sizeof(rank_and_port),
                "coordinator handshake");
      uint64_t fp = 0;
      boot_read(fd, &fp, sizeof(fp), "coordinator fp handshake");
      uint64_t token = 0;
      boot_read(fd, &token, sizeof(token), "coordinator token handshake");
      int r = static_cast<int>(rank_and_port[0]);
      if (r < 1 || r >= g_size)
        fail_boot("coordinator check-in claimed invalid rank " +
                  std::to_string(r) + " (world size " +
                  std::to_string(g_size) + ")");
      table[r] = PeerAddr{peer.sin_addr.s_addr,
                          static_cast<uint16_t>(rank_and_port[1]), 0, fp,
                          token};
      fds[r] = fd;
    }
    // phase 2: broadcast the table
    for (int i = 1; i < g_size; ++i) {
      boot_write(fds[i], table.data(), sizeof(PeerAddr) * g_size,
                 "coordinator table broadcast to rank " + std::to_string(i));
      ::close(fds[i]);
    }
    if (elastic_mode() == kElasticRejoin) {
      // the coordinator port stays open for the job's lifetime: a
      // relaunched replacement process (T4J_REJOIN=1) re-bootstraps
      // through it into the surviving mesh at the next epoch fence
      g_coord_listen_fd = coord_fd;
    } else {
      ::close(coord_fd);
    }
  } else {
    int fd = tcp_connect(coord_host, coord_port, "coordinator (rank 0)");
    uint32_t rank_and_port[2] = {static_cast<uint32_t>(g_rank), my_port};
    boot_write(fd, rank_and_port, sizeof(rank_and_port),
               "coordinator check-in");
    boot_write(fd, &my_fp, sizeof(my_fp), "coordinator fp check-in");
    boot_write(fd, &g_my_boot_token, sizeof(g_my_boot_token),
               "coordinator token check-in");
    boot_read(fd, table.data(), sizeof(PeerAddr) * g_size,
              "coordinator table read (waiting for every rank to check "
              "in)");
    ::close(fd);
  }

  g_host_fps.resize(g_size);
  g_endpoints.assign(g_size, PeerEndpoint{});
  for (int i = 0; i < g_size; ++i) {
    g_host_fps[i] = table[i].host_fp;
    char ip[INET_ADDRSTRLEN];
    in_addr a{table[i].ip};
    ::inet_ntop(AF_INET, &a, ip, sizeof(ip));
    // the coordinator's table records its own address as loopback;
    // dial it the way bootstrap reached it
    g_endpoints[i].host = (i == 0) ? coord_host : std::string(ip);
    g_endpoints[i].port = table[i].port;
    g_endpoints[i].boot_token = table[i].boot_token;
  }

  // phase 3: full mesh -- rank i accepts from ranks > i, connects to
  // < i; each pair builds T4J_STRIPES parallel connections (the
  // striping substrate), dialed CONCURRENTLY per link so an N-stripe
  // world does not multiply bootstrap time by N (the old serial loop
  // would).  The 8-byte mesh hello is {rank, stripe}.
  int nstripes = g_built_stripes;
  g_peers = std::vector<PeerLink>(g_size);
  for (int r = 0; r < g_size; ++r)
    if (r != g_rank) g_peers[r].alloc_stripes(nstripes);
  for (int lower = 0; lower < g_rank; ++lower) {
    std::vector<std::thread> dials;
    std::mutex err_mu;
    std::string dial_err;
    for (int si = 0; si < nstripes; ++si) {
      dials.emplace_back([&, lower, si] {
        try {
          int fd = tcp_connect(
              g_endpoints[lower].host, g_endpoints[lower].port,
              "rank " + std::to_string(lower) + " mesh listener (stripe " +
                  std::to_string(si) + ")");
          uint32_t hello[2] = {static_cast<uint32_t>(g_rank),
                               static_cast<uint32_t>(si)};
          boot_write(fd, hello, sizeof(hello),
                     "mesh handshake with rank " + std::to_string(lower));
          g_peers[lower].s[si].fd = fd;
          stripe_enable_zc(g_peers[lower].s[si]);
        } catch (const std::exception& e) {
          std::lock_guard<std::mutex> lk(err_mu);
          if (dial_err.empty()) dial_err = e.what();
        }
      });
    }
    for (auto& t : dials) t.join();
    if (!dial_err.empty()) throw BridgeError(dial_err);
  }
  {
    int expect = (g_size - g_rank - 1) * nstripes;
    for (int k = 0; k < expect; ++k) {
      Deadline dl = Deadline::after(connect_timeout());
      int fd = tcp_accept(listen_fd, dl,
                          "mesh connections from higher ranks (" +
                              std::to_string(expect - k) +
                              " stripe connection(s) outstanding)");
      tune_socket(fd);
      set_nonblock(fd);
      uint32_t hello[2] = {0, 0};
      boot_read(fd, hello, sizeof(hello), "mesh handshake");
      int who = static_cast<int>(hello[0]);
      int si = static_cast<int>(hello[1]);
      if (who <= g_rank || who >= g_size || si < 0 || si >= nstripes)
        fail_boot("mesh handshake claimed invalid rank/stripe " +
                  std::to_string(hello[0]) + "/" +
                  std::to_string(hello[1]));
      if (g_peers[who].s[si].fd >= 0)
        fail_boot("duplicate mesh connection for rank " +
                  std::to_string(who) + " stripe " + std::to_string(si));
      g_peers[who].s[si].fd = fd;
      stripe_enable_zc(g_peers[who].s[si]);
    }
  }

  for (int p = 0; p < g_size; ++p) {
    if (p == g_rank) continue;
    for (int si = 0; si < g_peers[p].nstripes; ++si) {
      Stripe& st = g_peers[p].s[si];
      if (st.fd >= 0)
        st.reader = std::thread(reader_loop, p, si, st.fd);
    }
  }
  if (resilience_on()) {
    // the mesh listener stays open: broken links are re-dialed here
    g_listen_fd = listen_fd;
    g_accept_thread.v.emplace_back(accept_loop);
  } else {
    ::close(listen_fd);
  }
  if (g_coord_listen_fd >= 0)
    g_accept_thread.v.emplace_back(coord_accept_loop);
  setup_pipes();
}

// --------------------------------------------------------- communicators

struct Comm {
  std::vector<int> ranks;  // world ranks, ascending caller order
  int ctx;
  int my_index;  // index of g_rank in ranks, or -1
  // same-host shm collective arena (lazy; nullptr = use TCP algorithms)
  shm::Arena* arena = nullptr;
  bool arena_checked = false;
  // gather-instance counter: every member advances it in lockstep (one
  // per gather call), tagging each instance uniquely so the root can
  // receive ANY_SOURCE without a run-ahead rank's next-gather frame
  // being mistaken for this one.  Only the collective-calling thread
  // touches it (MPI serialises collectives per comm).
  uint32_t gather_seq = 0;
  // Hierarchical (shm leaf + leader ring) layer, negotiated lazily on
  // the first large multi-host collective (see hier_setup).  The
  // topology vectors are pure functions of the bootstrap fingerprint
  // table, so every member derives identical values.
  bool hier_checked = false;
  bool hier_ok = false;
  int local_comm = -1;        // handle: members sharing my host
  int leader_comm = -1;       // handle: one leader per host (host order)
  std::vector<int> host_of;   // comm index -> host ordinal
  std::vector<int> local_of;  // comm index -> index within its host
  std::vector<int> host_size; // host ordinal -> member count
  std::vector<int> leader_idx;  // host ordinal -> comm index of leader
  int my_host = -1;
  bool is_leader = false;
  bool host_contiguous = false;  // comm order == host-grouped order
};

std::mutex g_comm_mu;
// deque: push_back never invalidates references to existing elements,
// so in-flight collectives can hold Comm& across concurrent comm_create
std::deque<Comm> g_comms;

// Collective traffic uses the upper tag space so it can never collide
// with user p2p tags (which are >= 0 and modest).
constexpr int kCollTagBase = 1 << 24;

Comm& get_comm(int handle) {
  std::lock_guard<std::mutex> lk(g_comm_mu);
  if (handle < 0 || handle >= static_cast<int>(g_comms.size())) {
    std::string hint;
    if (g_world_epoch.load(std::memory_order_relaxed) != 0)
      hint = " (the world resized at epoch " +
             std::to_string(
                 g_world_epoch.load(std::memory_order_relaxed)) +
             ": pre-resize communicator handles are stale — rebuild "
             "them over the resized world)";
    fail_arg("invalid communicator handle " + std::to_string(handle) +
             hint);
  }
  return g_comms[handle];
}

// Arena negotiation runs over the TCP collective channel with reserved
// tags, so it can never collide with user traffic or collectives.
constexpr int kArenaTagCreated = kCollTagBase + 9;
constexpr int kArenaTagAttach = kCollTagBase + 10;
constexpr int kArenaTagFinal = kCollTagBase + 11;

void csend(Comm& c, int dest_idx, int tag, const void* buf, size_t n,
           bool coll);
Frame crecv(Comm& c, int src_idx, int tag, bool coll);

// Same-host shm arena for a communicator (lazy).  Eligible when every
// member's bootstrap host fingerprint matches ours — then collectives
// move through shared memory instead of TCP frames (the role libmpi's
// shm BTL plays for the reference, mpi_xla_bridge.pyx:149-167).
//
// Setup is an explicit agreement protocol so the transport choice can
// never split the communicator (a rank silently falling back to TCP
// while its peers wait in shm would deadlock the job):
//   1. member 0 creates + fully initialises the segment, then tells
//      everyone whether that worked;
//   2. the others attach (no polling: the segment provably exists) and
//      report success back to member 0;
//   3. member 0 broadcasts the AND of every report — the arena is used
//      only when every member attached, else every member drops it and
//      the whole comm stays on TCP.
// The three rounds ride the TCP collective channel, which is always up.
shm::Arena* negotiate_arena(Comm& c) {
  int n = static_cast<int>(c.ranks.size());
  // fingerprints come from one bootstrap table, so this predicate is
  // identical on every member: either all enter the rounds or none do
  bool same_host = n > 1 && c.my_index >= 0 && !shm::disabled() &&
                   static_cast<int>(g_host_fps.size()) == g_size;
  if (same_host) {
    for (int r : c.ranks)
      if (g_host_fps[r] != g_host_fps[g_rank]) {
        same_host = false;
        break;
      }
  }
  if (!same_host) return nullptr;

  shm::Arena* a = nullptr;
  uint8_t ok = 0;
  if (c.my_index == 0) {
    a = shm::create(g_job.c_str(), c.ctx, n);
    ok = a != nullptr;
    for (int i = 1; i < n; ++i)
      csend(c, i, kArenaTagCreated, &ok, 1, true);
  } else {
    Frame f = crecv(c, 0, kArenaTagCreated, true);
    ok = f.data.size() == 1 ? f.data.data()[0] : 0;
    if (ok) {
      a = shm::attach(g_job.c_str(), c.ctx, n, c.my_index);
      ok = a != nullptr;
    }
  }
  if (c.my_index == 0) {
    for (int i = 1; i < n; ++i) {
      Frame f = crecv(c, i, kArenaTagAttach, true);
      ok &= f.data.size() == 1 ? f.data.data()[0] : 0;
    }
    for (int i = 1; i < n; ++i)
      csend(c, i, kArenaTagFinal, &ok, 1, true);
  } else {
    csend(c, 0, kArenaTagAttach, &ok, 1, true);
    Frame f = crecv(c, 0, kArenaTagFinal, true);
    ok = f.data.size() == 1 ? f.data.data()[0] : 0;
  }
  if (!ok && a) {
    shm::destroy(a);
    a = nullptr;
  }
  // every member holds a mapping now, so drop the NAME immediately: an
  // abnormal exit (die/_exit/SIGKILL) can then never leak the segment —
  // the kernel frees the tmpfs pages with the last mapping
  if (a) shm::unlink_name(a);
  return a;
}

shm::Arena* comm_arena(Comm& c) {
  {
    std::lock_guard<std::mutex> lk(g_comm_mu);
    if (c.arena_checked) return c.arena;
  }
  // Negotiation happens OUTSIDE the registry mutex: it blocks on TCP
  // rounds, and holding g_comm_mu there would stall every unrelated
  // bridge call in the process.  Concurrent first-collectives on the
  // SAME comm cannot happen (MPI serialises collectives per comm).
  shm::Arena* a = negotiate_arena(c);
  std::lock_guard<std::mutex> lk(g_comm_mu);
  c.arena = a;
  c.arena_checked = true;
  return c.arena;
}

// ------------------------------------------------------------ reductions

template <typename T>
void combine_typed(ReduceOp op, const T* a, T* acc, size_t n) {
  switch (op) {
    case ReduceOp::kSum:
      for (size_t i = 0; i < n; ++i) acc[i] = acc[i] + a[i];
      return;
    case ReduceOp::kProd:
      for (size_t i = 0; i < n; ++i) acc[i] = acc[i] * a[i];
      return;
    case ReduceOp::kMin:
      if constexpr (!std::is_same_v<T, std::complex<float>> &&
                    !std::is_same_v<T, std::complex<double>>) {
        for (size_t i = 0; i < n; ++i) acc[i] = a[i] < acc[i] ? a[i] : acc[i];
        return;
      }
      fail_arg("MIN on complex dtype");
    case ReduceOp::kMax:
      if constexpr (!std::is_same_v<T, std::complex<float>> &&
                    !std::is_same_v<T, std::complex<double>>) {
        for (size_t i = 0; i < n; ++i) acc[i] = acc[i] < a[i] ? a[i] : acc[i];
        return;
      }
      fail_arg("MAX on complex dtype");
    default:
      break;
  }
  if constexpr (std::is_integral_v<T>) {
    switch (op) {
      case ReduceOp::kLand:
        for (size_t i = 0; i < n; ++i) acc[i] = (acc[i] && a[i]) ? 1 : 0;
        return;
      case ReduceOp::kLor:
        for (size_t i = 0; i < n; ++i) acc[i] = (acc[i] || a[i]) ? 1 : 0;
        return;
      case ReduceOp::kLxor:
        for (size_t i = 0; i < n; ++i)
          acc[i] = ((acc[i] != 0) != (a[i] != 0)) ? 1 : 0;
        return;
      case ReduceOp::kBand:
        for (size_t i = 0; i < n; ++i) acc[i] = acc[i] & a[i];
        return;
      case ReduceOp::kBor:
        for (size_t i = 0; i < n; ++i) acc[i] = acc[i] | a[i];
        return;
      case ReduceOp::kBxor:
        for (size_t i = 0; i < n; ++i) acc[i] = acc[i] ^ a[i];
        return;
      default:
        break;
    }
  }
  fail_arg("unsupported reduce op for dtype");
}

// half-precision types travel as uint16 and reduce via float
float half_to_float(uint16_t h, bool bf16) {
  if (bf16) {
    uint32_t bits = static_cast<uint32_t>(h) << 16;
    float f;
    std::memcpy(&f, &bits, 4);
    return f;
  }
  // IEEE f16 -> f32
  uint32_t sign = (h >> 15) & 1, exp = (h >> 10) & 0x1f, frac = h & 0x3ff;
  uint32_t bits;
  if (exp == 0) {
    if (frac == 0) {
      bits = sign << 31;
    } else {
      exp = 127 - 15 + 1;
      while (!(frac & 0x400)) {
        frac <<= 1;
        --exp;
      }
      frac &= 0x3ff;
      bits = (sign << 31) | (exp << 23) | (frac << 13);
    }
  } else if (exp == 0x1f) {
    bits = (sign << 31) | 0x7f800000u | (frac << 13);
  } else {
    bits = (sign << 31) | ((exp - 15 + 127) << 23) | (frac << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

uint16_t float_to_half(float f, bool bf16) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  if (bf16) {
    // round-to-nearest-even
    uint32_t rounding = ((bits >> 16) & 1) + 0x7fff;
    return static_cast<uint16_t>((bits + rounding) >> 16);
  }
  uint32_t sign = (bits >> 31) & 1, exp = (bits >> 23) & 0xff,
           frac = bits & 0x7fffff;
  uint16_t h;
  if (exp >= 0xff) {
    h = static_cast<uint16_t>((sign << 15) | 0x7c00 | (frac ? 0x200 : 0));
  } else if (exp > 127 + 15) {
    h = static_cast<uint16_t>((sign << 15) | 0x7c00);
  } else if (exp < 127 - 14) {
    h = static_cast<uint16_t>(sign << 15);  // flush tiny to zero
  } else {
    h = static_cast<uint16_t>((sign << 15) | ((exp - 127 + 15) << 10) |
                              (frac >> 13));
  }
  return h;
}

void combine_half(ReduceOp op, const uint16_t* a, uint16_t* acc, size_t n,
                  bool bf16) {
  for (size_t i = 0; i < n; ++i) {
    float x = half_to_float(a[i], bf16), y = half_to_float(acc[i], bf16);
    float r;
    switch (op) {
      case ReduceOp::kSum:
        r = y + x;
        break;
      case ReduceOp::kProd:
        r = y * x;
        break;
      case ReduceOp::kMin:
        r = x < y ? x : y;
        break;
      case ReduceOp::kMax:
        r = y < x ? x : y;
        break;
      default:
        fail_arg("unsupported reduce op for half dtype");
    }
    acc[i] = float_to_half(r, bf16);
  }
}

}  // namespace (reopened below: combine is linked from shm.cc)

namespace detail {
void combine(ReduceOp op, DType dt, const void* contrib, void* acc,
             size_t count) {
  switch (dt) {
    case DType::kF32:
      return combine_typed(op, static_cast<const float*>(contrib),
                           static_cast<float*>(acc), count);
    case DType::kF64:
      return combine_typed(op, static_cast<const double*>(contrib),
                           static_cast<double*>(acc), count);
    case DType::kI8:
      return combine_typed(op, static_cast<const int8_t*>(contrib),
                           static_cast<int8_t*>(acc), count);
    case DType::kI16:
      return combine_typed(op, static_cast<const int16_t*>(contrib),
                           static_cast<int16_t*>(acc), count);
    case DType::kI32:
      return combine_typed(op, static_cast<const int32_t*>(contrib),
                           static_cast<int32_t*>(acc), count);
    case DType::kI64:
      return combine_typed(op, static_cast<const int64_t*>(contrib),
                           static_cast<int64_t*>(acc), count);
    case DType::kU8:
    case DType::kBool:
      return combine_typed(op, static_cast<const uint8_t*>(contrib),
                           static_cast<uint8_t*>(acc), count);
    case DType::kU16:
      return combine_typed(op, static_cast<const uint16_t*>(contrib),
                           static_cast<uint16_t*>(acc), count);
    case DType::kU32:
      return combine_typed(op, static_cast<const uint32_t*>(contrib),
                           static_cast<uint32_t*>(acc), count);
    case DType::kU64:
      return combine_typed(op, static_cast<const uint64_t*>(contrib),
                           static_cast<uint64_t*>(acc), count);
    case DType::kC64:
      return combine_typed(op, static_cast<const std::complex<float>*>(contrib),
                           static_cast<std::complex<float>*>(acc), count);
    case DType::kC128:
      return combine_typed(op,
                           static_cast<const std::complex<double>*>(contrib),
                           static_cast<std::complex<double>*>(acc), count);
    case DType::kF16:
      return combine_half(op, static_cast<const uint16_t*>(contrib),
                          static_cast<uint16_t*>(acc), count, false);
    case DType::kBF16:
      return combine_half(op, static_cast<const uint16_t*>(contrib),
                          static_cast<uint16_t*>(acc), count, true);
  }
  fail_arg("unknown dtype");
}
}  // namespace detail

namespace {

using detail::combine;

// comm-relative send/recv; coll=true routes through the internal
// collective channel (separate wire ctx), so user-facing ANY_SOURCE /
// ANY_TAG receives can never capture collective frames
void csend(Comm& c, int dest_idx, int tag, const void* buf, size_t n,
           bool coll = true) {
  raw_send(c.ranks[dest_idx], enc_ctx(c.ctx, coll), tag, buf, n);
}

Frame crecv(Comm& c, int src_idx, int tag, bool coll = true) {
  int world_src = src_idx == kAnySource ? kAnySource : c.ranks[src_idx];
  return raw_recv(world_src, enc_ctx(c.ctx, coll), tag);
}

// A matched frame of the wrong size means the ranks disagree on
// shapes/dtypes for this op — attributable, abort-broadcast-worthy.
[[noreturn]] void fail_size(const Frame& f, size_t expected) {
  fail_op("size mismatch: expected " + std::to_string(expected) +
          " bytes but matched a " + std::to_string(f.data.size()) +
          "-byte message from world rank r" + std::to_string(f.src) +
          " (tag " + std::to_string(f.tag) +
          ") — ranks disagree on shapes or dtypes");
}

// ---------------------------------------- fused multi-part frames
//
// Small-message coalescing (docs/performance.md "small-message
// coalescing"): a run of small messages for one peer travels as ONE
// wire frame whose payload is a fused sub-header (magic, part count,
// per-part sizes) followed by the concatenated part payloads.  The
// frame goes through the ordinary csend/crecv path, so sequencing,
// the replay ring, shm pipes, deadlines and telemetry need no new
// code.  The receiver validates the sub-header against its own part
// list — a mismatch means the ranks disagree on the fusion plan
// (divergent T4J_COALESCE_BYTES or program), which is attributable
// and abort-broadcast-worthy like any size mismatch.

constexpr uint32_t kFusedMagic = 0x7446f001;

struct FusedHead {
  uint32_t magic;
  uint32_t nparts;
};
static_assert(sizeof(FusedHead) == 8, "fused sub-header layout");

size_t fused_payload_size(const size_t* nbytes, int n) {
  size_t total = sizeof(FusedHead) +
                 static_cast<size_t>(n) * sizeof(uint64_t);
  for (int i = 0; i < n; ++i) total += nbytes[i];
  return total;
}

// Gather `n` parts into one fused frame payload.
Buf build_fused(const void* const* parts, const size_t* nbytes, int n) {
  Buf b(fused_payload_size(nbytes, n));
  auto* head = reinterpret_cast<FusedHead*>(b.data());
  head->magic = kFusedMagic;
  head->nparts = static_cast<uint32_t>(n);
  auto* sizes = reinterpret_cast<uint64_t*>(b.data() + sizeof(FusedHead));
  uint8_t* payload =
      b.data() + sizeof(FusedHead) +
      static_cast<size_t>(n) * sizeof(uint64_t);
  for (int i = 0; i < n; ++i) {
    sizes[i] = nbytes[i];
    if (nbytes[i]) std::memcpy(payload, parts[i], nbytes[i]);
    payload += nbytes[i];
  }
  return b;
}

// Scatter a matched fused frame into `n` caller part buffers,
// validating the sub-header first.
void scatter_fused(const Frame& f, void* const* parts,
                   const size_t* nbytes, int n) {
  auto bad = [&](const std::string& why) {
    fail_op("fused frame from world rank r" + std::to_string(f.src) +
            " (tag " + std::to_string(f.tag) + "): " + why +
            " — ranks disagree on the coalescing plan (divergent "
            "T4J_COALESCE_BYTES or shapes)");
  };
  if (f.data.size() < sizeof(FusedHead)) {
    bad("matched a " + std::to_string(f.data.size()) +
        "-byte message, too short for a fused sub-header");
  }
  const auto* head = reinterpret_cast<const FusedHead*>(f.data.data());
  if (head->magic != kFusedMagic)
    bad("matched a non-fused message where a fused frame was expected");
  if (head->nparts != static_cast<uint32_t>(n))
    bad("carries " + std::to_string(head->nparts) +
        " part(s), receiver expected " + std::to_string(n));
  if (f.data.size() != fused_payload_size(nbytes, n))
    bad("total payload is " + std::to_string(f.data.size()) +
        " bytes, receiver expected " +
        std::to_string(fused_payload_size(nbytes, n)));
  const auto* sizes =
      reinterpret_cast<const uint64_t*>(f.data.data() + sizeof(FusedHead));
  for (int i = 0; i < n; ++i) {
    if (sizes[i] != static_cast<uint64_t>(nbytes[i]))
      bad("part " + std::to_string(i) + " is " +
          std::to_string(sizes[i]) + " bytes, receiver expected " +
          std::to_string(nbytes[i]));
  }
  const uint8_t* payload =
      f.data.data() + sizeof(FusedHead) +
      static_cast<size_t>(n) * sizeof(uint64_t);
  for (int i = 0; i < n; ++i) {
    if (nbytes[i]) std::memcpy(parts[i], payload, nbytes[i]);
    payload += nbytes[i];
  }
}

// ------------------------------------------------------------ ring engine
//
// Bandwidth-optimal segmented ring collectives for the TCP tier.  The
// trees (binomial reduce+bcast, root-funnel gather+bcast) move the
// FULL payload across a link once per level — ~2*ceil(log2 n)*S wire
// bytes per allreduce of S bytes.  The ring schedule (NCCL/Horovod)
// moves 2*S*(n-1)/n: reduce-scatter walks each block once around the
// ring accumulating, allgather walks the reduced blocks once more.
// Messages below T4J_RING_MIN_BYTES keep the trees (fewer rounds wins
// when latency, not bandwidth, dominates).
//
// Transfers are segmented at T4J_SEG_BYTES: the combine of segment k
// runs while the reader thread is already pulling segment k+1 off the
// socket, instead of buffering the whole block as one Frame before any
// arithmetic starts.  Every segment send/recv goes through the normal
// csend/crecv path, so the per-op deadline, fault fail-fast and abort
// broadcast of docs/failure-semantics.md apply per segment — a peer
// dying mid-ring surfaces as the usual contextual BridgeError.

constexpr int kTagRingRS = kCollTagBase + 14;
constexpr int kTagRingAG = kCollTagBase + 15;

// Gather-instance tag window (see Comm::gather_seq): 64Ki consecutive
// gather calls get distinct tags.  After a wrap, FIFO matching per
// (src, ctx, tag) still pairs the oldest outstanding frame with the
// oldest outstanding recv, so correctness never depends on the window.
constexpr int kTagGatherSeqBase = kCollTagBase + (1 << 16);

int ring_mod(int a, int n) {
  int r = a % n;
  return r < 0 ? r + n : r;
}

// Partition of `count` elements over n ranks (allreduce blocks): the
// first count%n blocks carry one extra element, so any count — not
// divisible by n included — rides the ring without padding.
struct BlockPart {
  size_t base, extra;
  BlockPart(size_t count, int n)
      : base(count / static_cast<size_t>(n)),
        extra(count % static_cast<size_t>(n)) {}
  size_t off(int b) const {
    size_t ub = static_cast<size_t>(b);
    return ub * base + (ub < extra ? ub : extra);
  }
  size_t len(int b) const {
    return base + (static_cast<size_t>(b) < extra ? 1 : 0);
  }
};

// Effective segment size in bytes for elements of size dsize: at least
// one element, rounded down to a whole number of elements so every
// segment can be combined independently.
size_t seg_for(size_t dsize) {
  size_t seg = static_cast<size_t>(seg_bytes());
  size_t elems = seg / dsize;
  return (elems < 1 ? 1 : elems) * dsize;
}

// Effective wire dtype for ONE collective on ONE comm (docs/
// performance.md "Compressed collectives").  Compression requires f32
// SUM (the only dtype x op pair with a defined wire cast — integer and
// MIN/MAX payloads always travel exact), a multi-member ring, and
// EVERY ring hop crossing hosts: a single same-host (pipe-eligible)
// pair would mix compressed and exact hops, and in the allgather
// phase — where each block passes through every member — ranks
// downstream of the exact hop would see different result bits than
// the rest.  g_host_fps is the bootstrap-agreed host table (T4J_NO_SHM
// and T4J_EMU_LOCAL already ride the fingerprint), so every rank
// reaches the same verdict with no negotiation round.
int comm_wire_dtype(const Comm& c, DType dt, ReduceOp op) {
  if (dt != DType::kF32 || op != ReduceOp::kSum) return kWireOff;
  int wdt = wire_dtype_mode();
  if (wdt == kWireOff) return kWireOff;
  int n = static_cast<int>(c.ranks.size());
  if (n < 2 || c.my_index < 0) return kWireOff;
  if (static_cast<int>(g_host_fps.size()) != g_size) return kWireOff;
  for (int j = 0; j < n; ++j) {
    if (g_host_fps[c.ranks[j]] ==
        g_host_fps[c.ranks[ring_mod(j + 1, n)]])
      return kWireOff;
  }
  return wdt;
}

void send_segmented(Comm& c, int dest_idx, int tag, const uint8_t* p,
                    size_t nbytes, size_t seg) {
  int wd = c.ranks[dest_idx];
  bool piped = wd < static_cast<int>(g_tx_pipes.size()) &&
               g_tx_pipes[wd] != nullptr;
  if (wd == g_rank || piped) {
    for (size_t o = 0; o < nbytes; o += seg) {
      size_t k = nbytes - o < seg ? nbytes - o : seg;
      csend(c, dest_idx, tag, p + o, k);
    }
    return;
  }
  // TCP: hand the whole segment run to the striped send engine in ONE
  // call — segments deal round-robin across the stripes and small
  // ones gather into T4J_SENDMSG_BATCH-frame sendmsg calls
  // (docs/performance.md "striped links and the zero-copy path")
  if (g_stop.load(std::memory_order_acquire)) raise_stopped();
  if (nbytes == 0) return;
  std::vector<const void*> bufs;
  std::vector<size_t> sizes;
  bufs.reserve(nbytes / seg + 1);
  sizes.reserve(nbytes / seg + 1);
  for (size_t o = 0; o < nbytes; o += seg) {
    size_t k = nbytes - o < seg ? nbytes - o : seg;
    bufs.push_back(p + o);
    sizes.push_back(k);
  }
  link_send(wd, enc_ctx(c.ctx, /*coll=*/true), tag, bufs.data(),
            sizes.data(), bufs.size());
}

// Compressed variant of send_segmented for f32 payloads: downcast each
// segment into a wire staging buffer and hand the (smaller) frames to
// the striped send engine in one call.  The staging buffer IS the
// frame payload, so with healing enabled the replay arena copies —
// and on a link break replays — the already-compressed bytes, and
// striping / syscall batching / flow emulation / per-frame telemetry
// see nothing but ordinary smaller frames.  Caller guarantees
// wdt != kWireOff, nbytes % 4 == 0, seg % 4 == 0, and (via
// comm_wire_dtype) that the destination is a cross-host TCP peer.
void send_segmented_compressed(Comm& c, int dest_idx, int tag,
                               const uint8_t* p, size_t nbytes,
                               size_t seg, int wdt) {
  if (g_stop.load(std::memory_order_acquire)) raise_stopped();
  if (nbytes == 0) return;
  int wd = c.ranks[dest_idx];
  size_t wsize = wire_elem_size(wdt);
  size_t nelems = nbytes / 4;
  Buf wire(nelems * wsize);
  downcast_wire(wdt, reinterpret_cast<const float*>(p), wire.data(),
                nelems);
  std::vector<const void*> bufs;
  std::vector<size_t> sizes;
  bufs.reserve(nbytes / seg + 1);
  sizes.reserve(nbytes / seg + 1);
  size_t wseg = (seg / 4) * wsize;
  for (size_t o = 0; o < nelems * wsize; o += wseg) {
    size_t k = nelems * wsize - o < wseg ? nelems * wsize - o : wseg;
    bufs.push_back(wire.data() + o);
    sizes.push_back(k);
  }
  link_send(wd, enc_ctx(c.ctx, /*coll=*/true), tag, bufs.data(),
            sizes.data(), bufs.size());
  g_wire_logical_bytes.fetch_add(nbytes, std::memory_order_relaxed);
  g_wire_comp_bytes.fetch_add(nelems * wsize,
                              std::memory_order_relaxed);
}

// Quantise a resident f32 range in place (downcast then upcast).  The
// allgather owner's copy of its own block must equal what every
// receiver reconstructs from the wire bytes, or ranks would end the
// collective with different result bits — the replicated-result
// contract.  Round-tripping is idempotent (a wire-representable value
// downcasts back to the same code), so the owner's subsequent send
// carries exactly the codes the receivers already decode.
void quantize_inplace_wire(int wdt, uint8_t* p, size_t nbytes) {
  size_t nelems = nbytes / 4;
  if (nelems == 0) return;
  Buf tmp(nelems * wire_elem_size(wdt));
  downcast_wire(wdt, reinterpret_cast<const float*>(p), tmp.data(),
                nelems);
  upcast_copy_wire(wdt, tmp.data(), reinterpret_cast<float*>(p), nelems);
}

template <typename T>
void add_into(const void* a, const void* b, void* out, size_t n) {
  const T* pa = static_cast<const T*>(a);
  const T* pb = static_cast<const T*>(b);
  T* po = static_cast<T*>(out);
  for (size_t i = 0; i < n; ++i) po[i] = pa[i] + pb[i];
}

// Fused out = local + received for the hot SUM dtypes: the generic
// path (memcpy local into acc, then combine received into acc) pays an
// extra read+write pass over every byte, and the reduce-scatter inner
// loop is memory-bound on loopback.  Operand order matches
// combine_typed (acc=local, contrib=received), so results stay
// bit-identical to the unfused path.  Returns false when the caller
// must fall back.
bool combine_fused(ReduceOp op, DType dt, const void* local,
                   const void* received, void* out, size_t count) {
  if (op != ReduceOp::kSum) return false;
  switch (dt) {
    case DType::kF32:
      add_into<float>(local, received, out, count);
      return true;
    case DType::kF64:
      add_into<double>(local, received, out, count);
      return true;
    case DType::kI32:
      add_into<int32_t>(local, received, out, count);
      return true;
    case DType::kI64:
      add_into<int64_t>(local, received, out, count);
      return true;
    case DType::kU32:
      add_into<uint32_t>(local, received, out, count);
      return true;
    case DType::kU64:
      add_into<uint64_t>(local, received, out, count);
      return true;
    default:
      return false;
  }
}

// Receive a block as segments, folding each with the local
// contribution (`local`, same length) into `acc` as it lands: the fold
// of segment k overlaps the wire transfer of segment k+1, and the
// just-touched segment stays cache-hot between init and combine.
// acc == local (the in-place ring the hier leader tier runs on its
// output buffer) is legal: the accumulator already holds the local
// contribution, so the init pass is skipped.
void recv_combine_segmented(Comm& c, int src_idx, int tag,
                            const uint8_t* local, uint8_t* acc,
                            size_t nbytes, size_t seg, DType dt,
                            ReduceOp op) {
  size_t dsize = dtype_size(dt);
  for (size_t o = 0; o < nbytes; o += seg) {
    size_t k = nbytes - o < seg ? nbytes - o : seg;
    Frame f = crecv(c, src_idx, tag);
    if (f.data.size() != k) fail_size(f, k);
    if (!combine_fused(op, dt, local + o, f.data.data(), acc + o,
                       k / dsize)) {
      if (acc != local) std::memcpy(acc + o, local + o, k);
      combine(op, dt, f.data.data(), acc + o, k / dsize);
    }
  }
}

void recv_copy_segmented(Comm& c, int src_idx, int tag, uint8_t* dst,
                         size_t nbytes, size_t seg) {
  for (size_t o = 0; o < nbytes; o += seg) {
    size_t k = nbytes - o < seg ? nbytes - o : seg;
    Frame f = crecv(c, src_idx, tag);
    if (f.data.size() != k) fail_size(f, k);
    std::memcpy(dst + o, f.data.data(), k);
  }
}

// Compressed counterpart of recv_combine_segmented: the upcast is
// fused into the combine fold (acc[i] = local[i] + upcast(wire[i]),
// one pass, no intermediate f32 buffer).  nbytes/seg are LOGICAL
// (f32) quantities; the expected frame carries nbytes/4 wire
// elements.  acc == local (the in-place hier leader ring) is legal,
// exactly as for the exact path.
void recv_combine_segmented_compressed(Comm& c, int src_idx, int tag,
                                       const uint8_t* local,
                                       uint8_t* acc, size_t nbytes,
                                       size_t seg, int wdt) {
  size_t wsize = wire_elem_size(wdt);
  for (size_t o = 0; o < nbytes; o += seg) {
    size_t k = nbytes - o < seg ? nbytes - o : seg;
    size_t wk = (k / 4) * wsize;
    Frame f = crecv(c, src_idx, tag);
    if (f.data.size() != wk) fail_size(f, wk);
    upcast_add_wire(wdt, reinterpret_cast<const float*>(local + o),
                    f.data.data(), reinterpret_cast<float*>(acc + o),
                    k / 4);
  }
}

// Compressed counterpart of recv_copy_segmented (the allgather phase):
// upcast while copying.  A block forwarded on the next step is
// re-downcast, which is exact — downcast(upcast(x)) == x — so every
// member of the ring materialises identical result bytes no matter
// how many compressed hops a block took.
void recv_copy_segmented_compressed(Comm& c, int src_idx, int tag,
                                    uint8_t* dst, size_t nbytes,
                                    size_t seg, int wdt) {
  size_t wsize = wire_elem_size(wdt);
  for (size_t o = 0; o < nbytes; o += seg) {
    size_t k = nbytes - o < seg ? nbytes - o : seg;
    size_t wk = (k / 4) * wsize;
    Frame f = crecv(c, src_idx, tag);
    if (f.data.size() != wk) fail_size(f, wk);
    upcast_copy_wire(wdt, f.data.data(),
                     reinterpret_cast<float*>(dst + o), k / 4);
  }
}

// Ring reduce-scatter: block b starts accumulating at rank b+1 and
// travels the ring once, so rank r ends holding block r fully reduced
// in `out_block`.  Step s (0..n-2): send the partial of block r-1-s to
// the right, receive block r-2-s from the left and combine it with the
// local contribution.  `in` is the caller's untouched input; scratch
// is two blocks (the partial being sent and the one being built), not
// a full-message copy.  off/len are byte offsets/lengths per block;
// zero-length blocks (count < n) simply move no frames.
void ring_reduce_scatter(Comm& c, const uint8_t* in, uint8_t* out_block,
                         const std::vector<size_t>& off,
                         const std::vector<size_t>& len, DType dt,
                         ReduceOp op, int wdt = kWireOff) {
  int n = static_cast<int>(c.ranks.size());
  int me = c.my_index;
  int right = ring_mod(me + 1, n), left = ring_mod(me - 1, n);
  size_t seg = seg_for(dtype_size(dt));
  size_t maxlen = 0;
  for (size_t l : len) maxlen = maxlen < l ? l : maxlen;
  Buf scratch_a(maxlen), scratch_b(maxlen);
  uint8_t* building = scratch_a.data();
  uint8_t* sending = scratch_b.data();  // partial built the step before
  for (int s = 0; s < n - 1; ++s) {
    int sb = ring_mod(me - 1 - s, n);
    int rb = ring_mod(me - 2 - s, n);
    const uint8_t* sp = s == 0 ? in + off[sb] : sending;
    if (wdt == kWireOff)
      send_segmented(c, right, kTagRingRS, sp, len[sb], seg);
    else
      send_segmented_compressed(c, right, kTagRingRS, sp, len[sb], seg,
                                wdt);
    uint8_t* acc = s == n - 2 ? out_block : building;
    if (wdt == kWireOff)
      recv_combine_segmented(c, left, kTagRingRS, in + off[rb], acc,
                             len[rb], seg, dt, op);
    else
      recv_combine_segmented_compressed(c, left, kTagRingRS,
                                        in + off[rb], acc, len[rb],
                                        seg, wdt);
    std::swap(building, sending);
  }
}

// Ring allgather: on entry block `me` of `buf` is valid; each block
// then travels the ring once.  Step s: send block r-s right, receive
// block r-1-s from the left.
void ring_allgather(Comm& c, uint8_t* buf, const std::vector<size_t>& off,
                    const std::vector<size_t>& len, int wdt = kWireOff) {
  int n = static_cast<int>(c.ranks.size());
  int me = c.my_index;
  int right = ring_mod(me + 1, n), left = ring_mod(me - 1, n);
  // compressed blocks are f32: segments must stay element-aligned so
  // each one downcasts/upcasts independently
  size_t seg = wdt == kWireOff ? seg_for(1) : seg_for(4);
  if (wdt != kWireOff) quantize_inplace_wire(wdt, buf + off[me], len[me]);
  for (int s = 0; s < n - 1; ++s) {
    int sb = ring_mod(me - s, n);
    int rb = ring_mod(me - 1 - s, n);
    if (wdt == kWireOff) {
      send_segmented(c, right, kTagRingAG, buf + off[sb], len[sb], seg);
      recv_copy_segmented(c, left, kTagRingAG, buf + off[rb], len[rb],
                          seg);
    } else {
      send_segmented_compressed(c, right, kTagRingAG, buf + off[sb],
                                len[sb], seg, wdt);
      recv_copy_segmented_compressed(c, left, kTagRingAG, buf + off[rb],
                                     len[rb], seg, wdt);
    }
  }
}

// Switchover: ring for messages at or above T4J_RING_MIN_BYTES (total
// message size), trees below.
bool use_ring(const Comm& c, size_t total_bytes) {
  return c.ranks.size() > 1 &&
         static_cast<long long>(total_bytes) >= ring_min_bytes();
}

// ------------------------------------------------- interleaved root send
//
// One frame per destination, progressed round-robin over every pending
// TCP socket, so the root's fan-out is bounded by ITS uplink — one
// slow or stalled peer no longer serialises delivery to the others
// (the old scatter loop wrote whole payloads one peer at a time).
// Self and same-host pipe destinations are delivered up front: those
// writes are bounded local memcpys, not throttleable sockets.

struct RootSend {
  int dest_idx;  // comm-relative index
  const uint8_t* p;
  size_t nbytes;
};

void multi_send(Comm& c, int tag, std::vector<RootSend>& msgs) {
  if (g_stop.load(std::memory_order_acquire)) raise_stopped();
  std::vector<RootSend> tcp;
  for (const RootSend& m : msgs) {
    int wd = c.ranks[m.dest_idx];
    bool piped = wd < static_cast<int>(g_tx_pipes.size()) &&
                 g_tx_pipes[wd] != nullptr;
    if (wd == g_rank || piped)
      csend(c, m.dest_idx, tag, m.p, m.nbytes);
    else
      tcp.push_back(m);
  }
  if (tcp.empty()) return;
  if (tcp.size() == 1) {
    csend(c, tcp[0].dest_idx, tag, tcp[0].p, tcp[0].nbytes);
    return;
  }
  // ascending world-rank lock order: concurrent multi_sends (different
  // comms on different threads) then acquire send_mu in one global
  // order, and single raw_sends hold one lock only — no cycle
  std::sort(tcp.begin(), tcp.end(), [&](const RootSend& a,
                                        const RootSend& b) {
    return c.ranks[a.dest_idx] < c.ranks[b.dest_idx];
  });

  struct Tx {
    int wdest;
    int stripe;
    int fd;
    WireHeader h;
    iovec iov[2];
    int iovcnt;
    std::unique_lock<std::mutex> lk;
    bool done = false;
  };
  bool healing = resilience_on() &&
                 !g_finalizing.load(std::memory_order_acquire);
  double limit_s = effective_op_timeout();
  Deadline dl = Deadline::after(limit_s);
  // injection checks run BEFORE any send_mu is held: the flaky drop
  // try_locks every stripe's send_mu, and a thread must never try_lock
  // a mutex it already owns
  for (size_t i = 0; i < tcp.size(); ++i) maybe_inject_send_fault();
  // deal each destination's frame onto a stripe (one frame per dest
  // here, so the per-link round-robin advances one step per fan-out)
  std::vector<WirePart> parts(tcp.size());
  for (size_t i = 0; i < tcp.size(); ++i) {
    parts[i].buf = tcp[i].p;
    parts[i].nbytes = tcp[i].nbytes;
    deal_frames(g_peers[c.ranks[tcp[i].dest_idx]],
                enc_ctx(c.ctx, true), tag, &parts[i], 1, healing);
  }
  if (healing) {
    // park on broken stripes like link_send does (also before any lock
    // is held): without this, repeated fan-outs during one outage
    // would keep appending to the replay ring unthrottled and could
    // evict the unacked tail — turning a healable drop into an abort.
    // Striped links blind-buffer in the write loop instead of waiting.
    for (size_t i = 0; i < tcp.size(); ++i) {
      int wd = c.ranks[tcp[i].dest_idx];
      if (g_peers[wd].nstripes == 1)
        wait_stripe_up(wd, parts[i].stripe, dl, tcp[i].nbytes, tag,
                       limit_s);
    }
  }
  std::vector<Tx> txs(tcp.size());
  for (size_t i = 0; i < tcp.size(); ++i) {
    int wd = c.ranks[tcp[i].dest_idx];
    PeerLink& p = g_peers[wd];
    Tx& t = txs[i];
    t.wdest = wd;
    for (;;) {
      t.stripe = parts[i].stripe;
      Stripe& st = p.s[t.stripe];
      if (st.fd < 0 && !healing)
        fail_arg("send to unconnected peer r" + std::to_string(wd));
      t.lk = std::unique_lock<std::mutex>(st.send_mu);
      if (healing && st.migrated) {
        // the stripe died and its ring migrated between dealing and
        // this append: buffering here would strand the frame — redeal
        // onto a live sibling (none left = the link verdict is in)
        t.lk.unlock();
        std::lock_guard<std::mutex> dlk(p.deal_mu);
        int cand = pick_live_stripe(p);
        if (cand < 0) raise_stopped();
        parts[i].stripe = cand;
        continue;
      }
      t.fd = st.fd;  // read under send_mu: stable while the lock is held
      t.h = parts[i].h;
      const uint8_t* payload = tcp[i].p;
      if (healing) {
        Replay& rep = ring_append(st, t.h, tcp[i].p, tcp[i].nbytes);
        // write from the arena copy: uniform with the striped path,
        // and a broken-stripe blind-buffer needs the arena resident
        payload = st.ring_buf.get() + rep.off;
      }
      throttle_stripe(st, sizeof(t.h) + tcp[i].nbytes);
      t.iov[0] = {&t.h, sizeof(t.h)};
      t.iov[1] = {const_cast<uint8_t*>(payload), tcp[i].nbytes};
      t.iovcnt = tcp[i].nbytes ? 2 : 1;
      bool broken;
      {
        std::lock_guard<std::mutex> slk(st.mu);
        broken = st.state == Stripe::kBroken;
      }
      if (broken && healing && p.nstripes > 1) {
        // the frame is in this stripe's ring: the repair redelivers
        // it — the fan-out keeps moving on every other socket
        t.done = true;
        t.lk.unlock();
      }
      break;
    }
  }

  dl = Deadline::after(limit_s);  // fresh window for the write phase
  size_t remaining = 0;
  for (const Tx& t : txs)
    if (!t.done) ++remaining;  // blind-buffered frames are already done
  std::string failure;  // set -> release all locks, then fail_op
  bool stopped = false;
  while (remaining > 0 && failure.empty() && !stopped) {
    bool progressed = false;
    for (Tx& t : txs) {
      if (t.done) continue;
      msghdr mh{};
      mh.msg_iov = t.iov;
      mh.msg_iovlen = t.iovcnt;
      ssize_t w = ::sendmsg(t.fd, &mh, MSG_NOSIGNAL);
      if (w < 0) {
        if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK)
          continue;
        if (healing) {
          // the frame is in this stripe's replay ring: hand delivery
          // to the repair cycle and keep the rest of the fan-out
          // moving
          int err = errno;
          t.done = true;
          t.lk.unlock();
          --remaining;
          mark_stripe_broken(t.wdest, t.stripe,
                             std::string("root send failed: ") +
                                 std::strerror(err));
          progressed = true;
          continue;
        }
        failure = "send to peer r" + std::to_string(t.wdest) +
                  " failed: " + std::strerror(errno) +
                  " (peer process likely dead)";
        break;
      }
      progressed = true;
      size_t done = static_cast<size_t>(w);
      while (t.iovcnt > 0 && done >= t.iov[0].iov_len) {
        done -= t.iov[0].iov_len;
        t.iov[0] = t.iov[1];  // shift down (2-entry array; once iovcnt
        --t.iovcnt;           // hits 0 the slot is never read again)
      }
      if (t.iovcnt > 0 && done > 0) {
        t.iov[0].iov_base = static_cast<char*>(t.iov[0].iov_base) + done;
        t.iov[0].iov_len -= done;
      }
      if (t.iovcnt == 0) {
        t.done = true;
        t.lk.unlock();
        --remaining;
        tel::trace_event(tel::kFrameTx, tel::kInstant, tel::kPlaneNone,
                         t.stripe, t.wdest, t.h.nbytes);
      }
    }
    if (remaining == 0 || !failure.empty()) break;
    if (g_stop.load(std::memory_order_acquire)) {
      stopped = true;
      break;
    }
    if (progressed) {
      // true PROGRESS deadline, matching the knob's documented
      // semantics: it fires only after limit_s with no bytes moving to
      // ANY peer — a large fan-out that is steadily draining never
      // trips it (the sequential loop gave each peer a fresh window;
      // one shared non-resetting window would be stricter than both)
      dl = Deadline::after(limit_s);
    } else {
      if (dl.expired()) {
        std::string who;
        for (const Tx& t : txs)
          if (!t.done) who += (who.empty() ? "r" : ", r") +
                              std::to_string(t.wdest);
        failure = "root send made no progress to peer(s) " + who +
                  " for " + std::to_string(limit_s) + "s (" +
                  deadline_knob() + ") — peer stalled or not draining";
        break;
      }
      std::vector<pollfd> pfds;
      for (const Tx& t : txs)
        if (!t.done) pfds.push_back({t.fd, POLLOUT, 0});
      ::poll(pfds.data(), pfds.size(), dl.remaining_ms(100));
    }
  }
  if (stopped || !failure.empty()) {
    // Abandoning the fan-out can leave a TORN frame on any unfinished
    // socket, and fail_op's in-band abort broadcast would then be
    // parsed as that frame's remaining payload — the peer either hangs
    // waiting for body bytes that never come or silently accepts
    // corrupted data.  Shut those sockets down (while still holding
    // their send_mu, so the abort writer cannot interleave): the
    // peer's reader sees EOF mid-frame immediately and raises the
    // usual attributable lost-peer error instead.
    for (Tx& t : txs)
      if (!t.done) ::shutdown(t.fd, SHUT_RDWR);
  }
  for (Tx& t : txs)
    if (t.lk.owns_lock()) t.lk.unlock();
  if (stopped) raise_stopped();
  if (!failure.empty()) fail_op(failure);
}

// -------------------------------------------------- hierarchical engine
//
// NCCL-style two-tier collectives for communicators that span several
// hosts with more than one rank on at least one of them: same-host
// members reduce (or gather) into their host leader through the shm
// arena, the leaders — one per host — run the segmented ring over the
// TCP tier among themselves, and results fan back out through the
// arena.  Cross-host wire traffic shrinks by the local world size
// (the flat ring crosses the inter-host link once per LOCAL rank).
//
// Topology is a pure function of the bootstrap fingerprint table, so
// every member derives identical host groups and leaders; the leaf
// arenas and the agreement that the whole comm switches together are
// negotiated lazily on first use (hier_setup), reusing the arena
// agreement protocol via internal sub-communicators.  The intra- and
// inter-node phases pipeline at T4J_SEG_BYTES granularity: the leader
// rings chunk k while its locals are already staging/combining chunk
// k+1 into the arena.  Every phase runs through the normal
// csend/crecv/arena paths, so the per-op deadline, fault fail-fast and
// abort broadcast apply — a dead non-leader local rank surfaces on
// every survivor as a contextual BridgeError (its sockets close; shm
// waiters observe the posted fault via detail::stopped()).

// Fused-alltoall channel (small-message coalescing): distinct from the
// plain alltoall tag so a fused and an unfused alltoall on one comm can
// never cross-match.
constexpr int kTagA2AFused = kCollTagBase + 19;

constexpr int kHierTagOk = kCollTagBase + 16;
constexpr int kHierTagVerdict = kCollTagBase + 17;
constexpr int kHierTagRoot = kCollTagBase + 18;

// Deterministic 30-bit wire context for the internal sub-comms: a pure
// function of the parent ctx + host identity, so every member derives
// the same channel regardless of local creation order (the same
// requirement _stable_ctx satisfies on the Python side).
int derive_hier_ctx(int parent_ctx, uint32_t salt, uint64_t key) {
  uint32_t h = 0x811C9DC5u;
  auto mix32 = [&h](uint32_t v) {
    h ^= v;
    h *= 0x01000193u;
  };
  mix32(static_cast<uint32_t>(parent_ctx));
  mix32(salt);
  mix32(static_cast<uint32_t>(key));
  mix32(static_cast<uint32_t>(key >> 32));
  int ctx = static_cast<int>(h & 0x3FFFFFFF);
  return ctx ? ctx : 1;
}

// Fill c's topology vectors from the bootstrap fingerprints; returns
// eligibility (>= 2 hosts, at least one with >= 2 members).  Pure and
// deterministic: host ordinals are first-occurrence order over comm
// indices, the leader of a host is its lowest comm index.
bool compute_hier_topology(Comm& c) {
  int n = static_cast<int>(c.ranks.size());
  if (n < 2 || c.my_index < 0 || shm::disabled()) return false;
  if (static_cast<int>(g_host_fps.size()) != g_size) return false;
  c.host_of.assign(n, -1);
  c.local_of.assign(n, 0);
  c.host_size.clear();
  c.leader_idx.clear();
  std::vector<uint64_t> fps;
  for (int j = 0; j < n; ++j) {
    uint64_t fp = g_host_fps[c.ranks[j]];
    int h = -1;
    for (size_t k = 0; k < fps.size(); ++k)
      if (fps[k] == fp) {
        h = static_cast<int>(k);
        break;
      }
    if (h < 0) {
      h = static_cast<int>(fps.size());
      fps.push_back(fp);
      c.host_size.push_back(0);
      c.leader_idx.push_back(j);
    }
    c.host_of[j] = h;
    c.local_of[j] = c.host_size[h]++;
  }
  int max_local = 0;
  for (int s : c.host_size) max_local = max_local < s ? s : max_local;
  c.my_host = c.host_of[c.my_index];
  c.is_leader = c.leader_idx[c.my_host] == c.my_index;
  // comm order == host-grouped order iff host ordinals never decrease
  // along comm indices (lets reduce_scatter skip a reorder pass)
  c.host_contiguous = true;
  for (int j = 1; j < n; ++j)
    if (c.host_of[j] < c.host_of[j - 1]) c.host_contiguous = false;
  return static_cast<int>(fps.size()) >= 2 && max_local >= 2;
}

// Comm-wide agreement that every host's leaf arena came up: leaders
// AND their local verdicts through comm member 0 (a leader by
// construction), then fan the result to their locals over the parent
// channel — the arena cannot carry the "no" verdict because on "no" it
// may not exist.  Mirrors negotiate_arena's shape one level up.
uint8_t hier_agree(Comm& c, uint8_t mine) {
  int nh = static_cast<int>(c.host_size.size());
  int coord = c.leader_idx[0];
  uint8_t verdict = mine;
  if (c.is_leader) {
    if (c.my_index == coord) {
      for (int h = 1; h < nh; ++h) {
        Frame f = crecv(c, c.leader_idx[h], kHierTagOk);
        verdict &= f.data.size() == 1 ? f.data.data()[0] : 0;
      }
      for (int h = 1; h < nh; ++h)
        csend(c, c.leader_idx[h], kHierTagVerdict, &verdict, 1);
    } else {
      csend(c, coord, kHierTagOk, &mine, 1);
      Frame f = crecv(c, coord, kHierTagVerdict);
      verdict = f.data.size() == 1 ? f.data.data()[0] : 0;
    }
    for (int j = 0; j < static_cast<int>(c.ranks.size()); ++j)
      if (c.host_of[j] == c.my_host && j != c.my_index)
        csend(c, j, kHierTagVerdict, &verdict, 1);
  } else {
    Frame f = crecv(c, c.leader_idx[c.my_host], kHierTagVerdict);
    verdict = f.data.size() == 1 ? f.data.data()[0] : 0;
  }
  return verdict;
}

// Lazy, collective: first caller (same call site on every member — MPI
// serialises collectives per comm) derives the topology, creates the
// internal local/leader sub-comms, negotiates the leaf arena through
// the existing agreement protocol, and agrees comm-wide.  On any
// failure the whole comm drops to the flat algorithms together.
bool hier_setup(Comm& c) {
  {
    std::lock_guard<std::mutex> lk(g_comm_mu);
    if (c.hier_checked) return c.hier_ok;
  }
  bool ok = false;
  int local_h = -1, leader_h = -1;
  if (compute_hier_topology(c)) {
    int n = static_cast<int>(c.ranks.size());
    int nh = static_cast<int>(c.host_size.size());
    std::vector<int> local_world, leader_world;
    for (int j = 0; j < n; ++j)
      if (c.host_of[j] == c.my_host) local_world.push_back(c.ranks[j]);
    for (int h = 0; h < nh; ++h)
      leader_world.push_back(c.ranks[c.leader_idx[h]]);
    int leader_wr = c.ranks[c.leader_idx[c.my_host]];
    local_h = comm_create(local_world.data(),
                          static_cast<int>(local_world.size()),
                          derive_hier_ctx(c.ctx, 'L', leader_wr));
    leader_h = comm_create(leader_world.data(),
                           static_cast<int>(leader_world.size()),
                           derive_hier_ctx(c.ctx, 'H', 0));
    // a single-member host needs no arena: its leader IS the member
    // and the leaf phases degenerate to copies (the impls branch on
    // host_size).  Only multi-member hosts negotiate a leaf arena.
    shm::Arena* a = nullptr;
    uint8_t mine = 1;
    if (local_world.size() > 1) {
      a = comm_arena(get_comm(local_h));
      mine = a != nullptr;
    }
    if (std::getenv("T4J_HIER_DEBUG"))
      std::fprintf(stderr, "r%d | hier_setup: host=%d leader=%d mine=%d\n",
                   g_rank, c.my_host, c.leader_idx[c.my_host], mine);
    ok = hier_agree(c, mine) != 0;
    if (!ok && a) {
      // every member of this host reached the same "no": drop the
      // now-unused arena together (finalize would also reap it)
      Comm& lcomm = get_comm(local_h);
      std::lock_guard<std::mutex> lk(g_comm_mu);
      if (lcomm.arena) {
        shm::destroy(lcomm.arena);
        lcomm.arena = nullptr;
      }
    }
  }
  std::lock_guard<std::mutex> lk(g_comm_mu);
  c.local_comm = local_h;
  c.leader_comm = leader_h;
  c.hier_ok = ok;
  c.hier_checked = true;
  return ok;
}

// Mode/size gate shared by the live selection (use_hier) and the
// benchmark-labeling query (hier_would_select) — one predicate, so
// record labels can never drift from what actually ran: T4J_HIER off
// kills the path, on forces it wherever the topology allows, auto
// (default) takes it at or above T4J_LEADER_RING_MIN_BYTES.
bool hier_mode_allows(size_t total_bytes) {
  int mode = hier_mode();
  if (mode == kHierOff || total_bytes == 0) return false;
  if (mode == kHierAuto &&
      static_cast<long long>(total_bytes) < leader_ring_min_bytes())
    return false;
  return true;
}

// Selection.  Knobs and the message size are uniform across ranks, so
// negotiation triggers at the same call everywhere.
bool use_hier(Comm& c, size_t total_bytes) {
  return hier_mode_allows(total_bytes) && hier_setup(c);
}

struct HierView {
  Comm* lc;       // my host's local sub-comm
  Comm* hc;       // leader sub-comm (my_index >= 0 only on leaders)
  shm::Arena* a;  // leaf arena (null only on a single-member host)
  bool solo;      // I am my host's only member: leaf phases are copies
};

HierView hier_view(Comm& c) {
  HierView v;
  v.lc = &get_comm(c.local_comm);
  v.hc = &get_comm(c.leader_comm);
  v.solo = c.host_size[c.my_host] == 1;
  std::lock_guard<std::mutex> lk(g_comm_mu);
  v.a = v.lc->arena;
  return v;
}

// Pipeline chunk for the hier phases, in bytes: total/8 keeps the
// leader ring and the leaf folds overlapped across ~8 stages, the
// T4J_SEG_BYTES floor keeps small messages in one or two chunks, and
// the slot cap ceiling lets each chunk ride ONE arena piece (every
// piece costs a 3-futex-gate rotation of all local ranks through the
// scheduler — chunking at raw seg granularity measured 30% slower at
// 64 MB on the 2-core box purely from gate overhead).  The slot cap
// applies UNCONDITIONALLY — slot_bytes() is a uniform env read even
// on an arena-less single-member host, and a solo leader computing a
// different chunk count than its peers would desynchronise the
// leader ring (mismatched frame sizes/iteration counts).
size_t hier_chunk_bytes(size_t total, size_t esz) {
  size_t chunk = seg_for(esz);
  size_t target = total / 8;
  if (target > chunk) chunk = target;
  size_t cap = shm::slot_bytes();
  if (chunk > cap) chunk = cap;
  size_t elems = chunk / esz;
  return (elems < 1 ? 1 : elems) * esz;
}

// Pipelined hier allreduce: per chunk, locals reduce into the leader
// through the arena (split-phase: stage, then fold), leaders
// allreduce the chunk over their ring, the arena fans it back out.
// Software pipeline: everyone STAGES chunk k+1 before the leader
// rings chunk k, so the locals' leaf fold of k+1 (shm::reduce_finish)
// runs while the leader is still on the wire with k.
void hier_allreduce_impl(Comm& c, const void* in, void* out, size_t count,
                         DType dt, ReduceOp op) {
  if (count == 0) return;  // nothing to move; stay out of the arena
  HierView v = hier_view(c);
  size_t esz = dtype_size(dt);
  size_t chunk = hier_chunk_bytes(count * esz, esz) / esz;
  size_t nchunks = (count + chunk - 1) / chunk;
  const uint8_t* i8 = static_cast<const uint8_t*>(in);
  uint8_t* o8 = static_cast<uint8_t*>(out);
  auto clen = [&](size_t k) {
    size_t left = count - k * chunk;
    return left < chunk ? left : chunk;
  };
  std::vector<uint64_t> piece(nchunks, 0);
  auto stage = [&](size_t k) {
    if (!v.solo)
      piece[k] = shm::reduce_stage(v.a, i8 + k * chunk * esz,
                                   clen(k) * esz);
  };
  auto finish = [&](size_t k) {
    if (v.solo)
      std::memcpy(o8 + k * chunk * esz, i8 + k * chunk * esz,
                  clen(k) * esz);
    else
      shm::reduce_finish(v.a, piece[k], o8 + k * chunk * esz, clen(k),
                         dt, op, 0);
  };
  stage(0);
  finish(0);
  int nl = static_cast<int>(c.host_size.size());
  for (size_t k = 0; k < nchunks; ++k) {
    size_t o = k * chunk * esz, len = clen(k);
    if (k + 1 < nchunks) stage(k + 1);
    if (c.is_leader) {
      // in-place segmented ring directly on the output chunk (leader
      // ordinals equal leader-comm indices): no scratch allocation, no
      // copy-back pass — recv_combine_segmented folds into the block
      // it already holds
      BlockPart bp(len, nl);
      std::vector<size_t> boff(nl), blen(nl);
      for (int b = 0; b < nl; ++b) {
        boff[b] = bp.off(b) * esz;
        blen[b] = bp.len(b) * esz;
      }
      // leaders sit on distinct hosts by construction, so the leader
      // comm is all-TCP and compression engages whenever the knob is
      // on and the payload is f32 SUM — the shm leaf phases above and
      // below stay exact
      int wdt = comm_wire_dtype(*v.hc, dt, op);
      ring_reduce_scatter(*v.hc, o8 + o, o8 + o + boff[c.my_host], boff,
                          blen, dt, op, wdt);
      ring_allgather(*v.hc, o8 + o, boff, blen, wdt);
    }
    // locals reach this fold while the leader is ringing chunk k (its
    // chunk-k+1 contribution is already staged, so the fold needs
    // nothing more from it until wait_folded)
    if (k + 1 < nchunks) finish(k + 1);
    if (!v.solo) shm::bcast(v.a, o8 + o, len * esz, 0);
  }
}

void hier_reduce_impl(Comm& c, const void* in, void* out, size_t count,
                      DType dt, ReduceOp op, int root) {
  if (count == 0) return;  // nothing to move; stay out of the arena
  HierView v = hier_view(c);
  size_t esz = dtype_size(dt);
  int rhost = c.host_of[root];
  int rleader = c.leader_idx[rhost];
  const uint8_t* i8 = static_cast<const uint8_t*>(in);
  uint8_t* o8 = static_cast<uint8_t*>(out);
  // non-root members must leave `out` untouched (the off-root output
  // mirrors the input by contract), so non-root leaders accumulate
  // into a scratch buffer
  Buf tmp(c.is_leader && c.my_index != root ? count * esz : 0);
  uint8_t* acc = c.my_index == root ? o8 : (c.is_leader ? tmp.data() : o8);
  size_t chunk = hier_chunk_bytes(count * esz, esz) / esz;
  size_t nchunks = (count + chunk - 1) / chunk;
  auto clen = [&](size_t k) {
    size_t left = count - k * chunk;
    return left < chunk ? left : chunk;
  };
  std::vector<uint64_t> piece(nchunks, 0);
  auto stage = [&](size_t k) {
    if (!v.solo)
      piece[k] = shm::reduce_stage(v.a, i8 + k * chunk * esz,
                                   clen(k) * esz);
  };
  auto finish = [&](size_t k) {
    if (v.solo)
      std::memcpy(acc + k * chunk * esz, i8 + k * chunk * esz,
                  clen(k) * esz);
    else
      shm::reduce_finish(v.a, piece[k], acc + k * chunk * esz, clen(k),
                         dt, op, 0);
  };
  stage(0);
  finish(0);
  for (size_t k = 0; k < nchunks; ++k) {
    if (k + 1 < nchunks) stage(k + 1);
    if (c.is_leader)
      reduce(c.leader_comm, acc + k * chunk * esz, acc + k * chunk * esz,
             clen(k), dt, op, rhost);
    if (k + 1 < nchunks) finish(k + 1);
  }
  // a non-leader root gets the result over the same-host pipes,
  // segmented — a whole-message Frame would transiently buffer the
  // full payload on both sides (the allocation class PR 2 removed)
  if (root != rleader) {
    if (c.my_index == rleader)
      send_segmented(c, root, kHierTagRoot, acc, count * esz,
                     seg_for(esz));
    else if (c.my_index == root)
      recv_copy_segmented(c, rleader, kHierTagRoot, o8, count * esz,
                          seg_for(esz));
  }
}

void hier_bcast_impl(Comm& c, void* buf, size_t nbytes, int root) {
  HierView v = hier_view(c);
  int rhost = c.host_of[root];
  int rleader = c.leader_idx[rhost];
  uint8_t* b = static_cast<uint8_t*>(buf);
  // hop 1: a non-leader root hands the payload to its host leader
  // (same-host: the frames ride the shm pipes), segmented to keep the
  // transient buffering bounded
  if (root != rleader) {
    if (c.my_index == root)
      send_segmented(c, rleader, kHierTagRoot, b, nbytes, seg_for(1));
    else if (c.my_index == rleader)
      recv_copy_segmented(c, root, kHierTagRoot, b, nbytes, seg_for(1));
  }
  // hops 2+3, chunked: leaders bcast chunk k among themselves (the
  // leader of the root's host is leader-comm member rhost — leader
  // ordinals equal host ordinals by construction), each arena fans it
  // out while the leader tier moves chunk k+1
  size_t chunk = hier_chunk_bytes(nbytes, 1);
  for (size_t o = 0; o < nbytes; o += chunk) {
    size_t len = nbytes - o < chunk ? nbytes - o : chunk;
    if (c.is_leader) bcast(c.leader_comm, b + o, len, rhost);
    if (!v.solo) shm::bcast(v.a, b + o, len, 0);
  }
}

void hier_allgather_impl(Comm& c, const void* in, void* out,
                         size_t nbytes_each) {
  HierView v = hier_view(c);
  int n = static_cast<int>(c.ranks.size());
  int nh = static_cast<int>(c.host_size.size());
  uint8_t* o8 = static_cast<uint8_t*>(out);
  // host-block partition of the gathered payload, host-ordinal order
  std::vector<size_t> off(nh), len(nh);
  size_t total = 0;
  for (int h = 0; h < nh; ++h) {
    off[h] = total;
    len[h] = static_cast<size_t>(c.host_size[h]) * nbytes_each;
    total += len[h];
  }
  if (c.is_leader) {
    Buf hostbuf(total);
    // the local gather lands my host's members (local order) exactly
    // at this host's ring block
    if (v.solo)
      std::memcpy(hostbuf.data() + off[c.my_host], in, nbytes_each);
    else
      shm::gather(v.a, in, hostbuf.data() + off[c.my_host], nbytes_each,
                  0);
    ring_allgather(*v.hc, hostbuf.data(), off, len);
    // host-grouped -> comm order
    for (int j = 0; j < n; ++j)
      std::memcpy(o8 + static_cast<size_t>(j) * nbytes_each,
                  hostbuf.data() + off[c.host_of[j]] +
                      static_cast<size_t>(c.local_of[j]) * nbytes_each,
                  nbytes_each);
  } else {
    shm::gather(v.a, in, nullptr, nbytes_each, 0);
  }
  if (!v.solo) shm::bcast(v.a, o8, total, 0);
}

void hier_reduce_scatter_impl(Comm& c, const void* in, void* out,
                              size_t count_each, DType dt, ReduceOp op) {
  HierView v = hier_view(c);
  int n = static_cast<int>(c.ranks.size());
  int nh = static_cast<int>(c.host_size.size());
  size_t esz = dtype_size(dt);
  size_t block = count_each * esz;
  if (c.is_leader) {
    // host-partial reduction of the whole payload lands on the leader,
    // then the leader ring reduce-scatters host-sized partitions: each
    // leader ends with its own members' blocks fully reduced
    Buf full(block * static_cast<size_t>(n));
    if (v.solo)
      std::memcpy(full.data(), in, block * static_cast<size_t>(n));
    else
      shm::reduce(v.a, in, full.data(),
                  count_each * static_cast<size_t>(n), dt, op, 0);
    std::vector<size_t> off(nh), len(nh);
    size_t total = 0;
    for (int h = 0; h < nh; ++h) {
      off[h] = total;
      len[h] = static_cast<size_t>(c.host_size[h]) * block;
      total += len[h];
    }
    const uint8_t* ringin = full.data();
    Buf grouped;
    if (!c.host_contiguous) {
      grouped = Buf(block * static_cast<size_t>(n));
      for (int j = 0; j < n; ++j)
        std::memcpy(grouped.data() + off[c.host_of[j]] +
                        static_cast<size_t>(c.local_of[j]) * block,
                    full.data() + static_cast<size_t>(j) * block, block);
      ringin = grouped.data();
    }
    Buf myblk(len[c.my_host]);
    ring_reduce_scatter(*v.hc, ringin, myblk.data(), off, len, dt, op,
                        comm_wire_dtype(*v.hc, dt, op));
    // one block per local member in local order: exactly the arena
    // scatter's root layout
    if (v.solo)
      std::memcpy(out, myblk.data(), block);
    else
      shm::scatter(v.a, myblk.data(), out, block, 0);
  } else {
    shm::reduce(v.a, in, nullptr, count_each * static_cast<size_t>(n), dt,
                op, 0);
    shm::scatter(v.a, nullptr, out, block, 0);
  }
}

// ---------------------------------------------- async progress engine
//
// Nonblocking collectives and p2p (docs/async.md): submit returns a
// request handle immediately, and a dedicated progress thread drains
// the submission queue, executing each operation through the SAME
// public op bodies the blocking tier uses — segment pipelining,
// replay-ring self-healing, per-segment deadlines and the fault/abort
// contract all apply unchanged, just off the caller's thread.  The
// blocking public ops with an async counterpart are routed through
// the engine too (blocking = submit + wait), so there is exactly one
// wire path.
//
// Execution model: ops run in submission order (which MPI requires
// for collectives anyway — every rank must submit collectives on a
// comm in the same order), EXCEPT irecv, which never blocks the
// engine: an unmatched irecv is parked and re-polled against the
// mailbox, so posting irecv before iallreduce cannot wedge the queue
// the way a blocking recv would wedge a thread.  A parked irecv's
// deadline (T4J_OP_TIMEOUT) is armed at its first attempt; expiry
// fails the op through the usual fail_op path (fault + abort
// broadcast), which also drains every other in-flight request — the
// deadline/abort contract lives in one place.
//
// Waiters need no deadline of their own: a wedged EXECUTING op
// enforces its own T4J_OP_TIMEOUT and posts a fault, and the fault
// drains the queue and wakes every waiter.  With the deadline
// disabled (the default) wait blocks indefinitely, matching MPI_Wait.

struct AsyncOp {
  // kGeneric = a routed blocking collective with no nonblocking
  // counterpart (bcast/reduce/gather/...): the op carries its body as
  // a closure and the submitting caller blocks in wait until the
  // engine has run it — same single wire path, no second thread on
  // the sockets/arena.
  enum Kind { kAllreduce, kReduceScatter, kSend, kRecv, kGeneric };
  enum State { kQueued = 0, kRunning = 1, kDone = 2, kFailed = 3 };

  uint64_t id = 0;
  Kind kind = kSend;
  int comm = -1;
  const void* in = nullptr;  // caller-owned; valid until completion
  void* out = nullptr;       // caller-owned; valid until completion
  size_t count = 0;  // elements (reductions) / bytes (p2p)
  DType dt = DType::kF32;
  ReduceOp rop = ReduceOp::kSum;
  int peer = kAnySource;  // dest (isend) / source (irecv), comm index
  int tag = 0;
  uint64_t payload_bytes = 0;

  // irecv matching, cached at submit so the engine's parked-recv
  // polling never needs the comm registry lock
  int wire_ctx = 0;
  int world_src = kAnySource;
  int src_out = -1;  // matched envelope, filled at completion
  int tag_out = -1;
  bool deadline_armed = false;
  Deadline deadline;
  // A pre-posted irecv may legally sit unmatched for arbitrarily long
  // (the caller is off computing); T4J_OP_TIMEOUT's progress contract
  // covers *blocked callers*, so the parked deadline arms only once a
  // waiter is actually inside wait/waitall for this request.
  std::atomic<bool> wait_requested{false};

  uint64_t t_start_ns = 0;  // first execution attempt (telemetry)

  // owned-buffer variants (the XLA FFI submit handlers): the request
  // owns its operand copy and result storage, so custom-call operands
  // may be reused the moment the handler returns; in/out point here
  std::vector<uint8_t> own_in;
  std::vector<uint8_t> own_out;

  // kGeneric body; captures the caller's stack buffers, which stay
  // valid because the caller blocks in wait until completion
  std::function<void()> body;

  // guarded by engine().mu; src/tag/error are written by the engine
  // BEFORE the state flips, so the mutex hand-off publishes them
  State state = kQueued;
  std::string error;
};

struct AsyncEngine {
  std::mutex mu;
  std::condition_variable cv;       // engine wakeups: submit / quit
  std::condition_variable done_cv;  // waiter wakeups: completion
  std::deque<std::shared_ptr<AsyncOp>> queue;                      // mu
  std::unordered_map<uint64_t, std::shared_ptr<AsyncOp>> inflight; // mu
  uint64_t next_id = 1;  // mu
  std::thread thread;    // start/join under mu/stop protocol
  bool running = false;  // mu
  bool quit = false;     // mu
  std::atomic<int> depth{0};  // submitted, not yet complete (gauge)
  std::atomic<int> qsize{0};  // queued, not yet popped
};

// leaked: the progress thread and async waiters touch it until the
// process exits (see the g_fault_mu comment)
AsyncEngine& engine() {
  static AsyncEngine& e = *new AsyncEngine;
  return e;
}

// The progress thread executes op bodies through the public entry
// points; this flag makes the blocking=submit+wait routing in those
// entry points fall through to the direct implementation.
thread_local bool tls_engine_thread = false;

// Blocking ops route through the engine only on real multi-process
// worlds; single-rank calls keep the inline fast path.
bool async_route() {
  return g_initialized && g_size > 1 && !tls_engine_thread;
}

void wake_async_engine() {
  AsyncEngine& e = engine();
  // empty critical sections: a waiter that just checked its predicate
  // and is about to sleep cannot miss the notification
  { std::lock_guard<std::mutex> lk(e.mu); }
  e.cv.notify_all();
  e.done_cv.notify_all();
  poke_engine();
}

// Async lifecycle events pack the submitted op's kind into the comm
// field's high byte ((kind+1) << 24 | comm & 0xFFFFFF; mirrored by
// telemetry/schema.py decode_async_comm) so t4j-top can attribute
// queue depth and engine busy time per op without per-event ids.
int async_evt_comm(const AsyncOp& op) {
  return ((static_cast<int>(op.kind) + 1) << 24) |
         (op.comm & 0xFFFFFF);
}

// Terminal state transition; called only from the engine thread (or
// from the drain path before the thread exists).
void async_complete(const std::shared_ptr<AsyncOp>& op, bool failed,
                    std::string error) {
  AsyncEngine& e = engine();
  uint64_t dur = op->t_start_ns ? tel::now_ns() - op->t_start_ns : 0;
  {
    std::lock_guard<std::mutex> lk(e.mu);
    op->error = std::move(error);
    op->state = failed ? AsyncOp::kFailed : AsyncOp::kDone;
  }
  int d = e.depth.fetch_sub(1, std::memory_order_relaxed) - 1;
  // kOpComplete carries the op's execution duration in `bytes` and
  // the post-completion in-flight depth in `peer` (telemetry.h)
  tel::trace_event(tel::kOpComplete, tel::kInstant, tel::kPlaneNone,
                   async_evt_comm(*op), d, dur);
  e.done_cv.notify_all();
}

// Non-blocking mailbox match for a parked irecv: raw_recv's matching
// (FIFO per (source, ctx, tag) with wildcards) minus the blocking.
bool mailbox_try_pop(int ctx, int world_source, int tag, Frame* out) {
  std::lock_guard<std::mutex> lk(g_mail_mu);
  for (auto it = g_mailbox.begin(); it != g_mailbox.end(); ++it) {
    if (!frame_matches(*it, ctx, world_source, tag)) continue;
    *out = std::move(*it);
    g_mailbox.erase(it);
    return true;
  }
  return false;
}

// One attempt at a parked irecv.  Returns true when the op reached a
// terminal state; false = still parked.
bool engine_try_recv(const std::shared_ptr<AsyncOp>& op) {
  try {
    if (!op->deadline_armed &&
        op->wait_requested.load(std::memory_order_acquire)) {
      op->deadline = Deadline::after(effective_op_timeout());
      op->deadline_armed = true;
    }
    Frame f;
    if (mailbox_try_pop(op->wire_ctx, op->world_src, op->tag, &f)) {
      LogScope log("MPI_Irecv",
                   "<- " + std::to_string(op->peer) + " with tag " +
                       std::to_string(op->tag) + " and " +
                       std::to_string(op->count) + " bytes");
      if (f.data.size() != op->count) fail_size(f, op->count);
      if (op->count) std::memcpy(op->out, f.data.data(), op->count);
      Comm& c = get_comm(op->comm);
      op->src_out = 0;
      for (size_t i = 0; i < c.ranks.size(); ++i)
        if (c.ranks[i] == f.src) op->src_out = static_cast<int>(i);
      op->tag_out = f.tag;
      if (tel::mode() >= tel::kCounters)
        tel::count_op(op->comm, tel::kRecv, tel::kPlaneNone, op->count,
                      tel::now_ns() - op->t_start_ns);
      async_complete(op, false, "");
      return true;
    }
    if (g_stop.load(std::memory_order_acquire)) {
      std::string why = posted_fault_msg();
      if (why.empty())
        why = err_prefix() + "MPI_Irecv: bridge already shut down";
      async_complete(op, true, why);
      return true;
    }
    if (op->deadline_armed && op->deadline.expired()) {
      LogScope log("MPI_Irecv", "");
      std::string src = op->world_src == kAnySource
                            ? std::string("ANY_SOURCE")
                            : "r" + std::to_string(op->world_src);
      std::string tg = op->tag == kAnyTag ? std::string("ANY_TAG")
                                          : std::to_string(op->tag);
      fail_op("no matching message from " + src + " (tag " + tg +
              ") within " + std::to_string(effective_op_timeout()) +
              "s (" + deadline_knob() +
              ") — mismatched send/recv, dead peer, or a peer running "
              "behind");
    }
    return false;
  } catch (const BridgeError& e2) {
    async_complete(op, true, e2.what());
    return true;
  } catch (const std::exception& e2) {
    async_complete(op, true, err_prefix() +
                                 std::string("async recv failed: ") +
                                 e2.what());
    return true;
  }
}

// Execute a blocking-kind op on the engine thread through the public
// entry point (tls_engine_thread makes it run the direct body).
void engine_run_blocking(const std::shared_ptr<AsyncOp>& op) {
  try {
    switch (op->kind) {
      case AsyncOp::kAllreduce:
        allreduce(op->comm, op->in, op->out, op->count, op->dt, op->rop);
        break;
      case AsyncOp::kReduceScatter:
        reduce_scatter(op->comm, op->in, op->out, op->count, op->dt,
                       op->rop);
        break;
      case AsyncOp::kSend:
        send(op->comm, op->in, op->count, op->peer, op->tag);
        break;
      case AsyncOp::kGeneric:
        op->body();
        break;
      default:
        throw BridgeError(err_prefix() + "async engine: bad op kind");
    }
    async_complete(op, false, "");
  } catch (const BridgeError& e2) {
    async_complete(op, true, e2.what());
  } catch (const std::exception& e2) {
    async_complete(op, true, err_prefix() +
                                 std::string("async op failed: ") +
                                 e2.what());
  }
}

// Completion-queue reaper (docs/performance.md "striped links and the
// zero-copy path"): the engine thread opportunistically drains every
// stripe's MSG_ZEROCOPY errqueue between ops so the ring-eviction
// gate rarely has to block.  try_lock only — never stall the engine
// on a busy writer.
void reap_all_zc() {
  if (!g_zc_supported || zc_min_bytes() <= 0) return;
  for (auto& p : g_peers)
    for (int si = 0; si < p.nstripes; ++si) {
      Stripe& st = p.s[si];
      std::unique_lock<std::mutex> lk(st.send_mu, std::try_to_lock);
      if (lk.owns_lock()) reap_zc(st);
    }
}

void engine_loop() {
  tls_engine_thread = true;
  AsyncEngine& e = engine();
  std::vector<std::shared_ptr<AsyncOp>> parked;  // unmatched irecvs
#if T4J_HAVE_URING
  // Completion-driven idle progress (uring backend): the idle
  // cv.wait_for becomes an io_uring_enter wait on a persistent
  // POLL_ADD over the engine eventfd — notifiers poke the evfd (see
  // poke_engine), the flight-recorder heartbeat still bumps per poll
  // tick at the call sites.  Falls back to the condvars whenever the
  // ring is unavailable.
  struct EngineWait {
    UringRing ring;
    bool ok = false;
    bool armed = false;            // POLL_ADD queued or in flight
    unsigned pending_submit = 0;
  } ew;
  if (uring_active() && ew.ring.open_ring(8)) {
    int efd = ::eventfd(0, EFD_NONBLOCK);
    if (efd >= 0) {
      g_engine_evfd.store(efd, std::memory_order_release);
      ew.ok = true;
    }
  }
  auto uring_idle_wait = [&](int ms) -> bool {
    if (!ew.ok) return false;
    int efd = g_engine_evfd.load(std::memory_order_relaxed);
    if (efd < 0) return false;
    if (!ew.armed) {
      io_uring_sqe* sq = ew.ring.get_sqe();
      sq->opcode = IORING_OP_POLL_ADD;
      sq->fd = efd;
      sq->poll32_events = POLLIN;
      sq->user_data = 1;
      ew.armed = true;
      ew.pending_submit += 1;
    }
    int rc = uring_enter(ew.ring, ew.pending_submit, 1, ms);
    if (rc >= 0) {
      unsigned sub = static_cast<unsigned>(rc);
      ew.pending_submit -=
          sub < ew.pending_submit ? sub : ew.pending_submit;
    } else if (errno != ETIME && errno != EINTR && errno != EAGAIN &&
               errno != EBUSY) {
      ew.ok = false;  // wedged: permanent fallback to the condvars
      return false;
    }
    io_uring_cqe cqe;
    while (ew.ring.pop_cqe(&cqe)) {
      if (cqe.user_data != 1) continue;
      ew.armed = false;  // poked (or poll error): re-arm next round
      uint64_t v;
      (void)!::read(efd, &v, sizeof(v));
    }
    return true;
  };
#endif
  for (;;) {
    std::shared_ptr<AsyncOp> next;
    bool quit;
    {
      std::unique_lock<std::mutex> lk(e.mu);
      while (e.queue.empty() && !e.quit && parked.empty() &&
             !g_stop.load(std::memory_order_acquire)) {
        // bounded idle wait so the progress engine keeps bumping the
        // flight-recorder heartbeat even when no op (and no socket
        // poll) is in flight
        tel::flight_heartbeat();
#if T4J_HAVE_URING
        if (ew.ok) {
          // park flag set BEFORE unlocking e.mu: any notifier that
          // mutates engine state after our predicate check reads it
          // after its own e.mu section, so its poke cannot be lost
          g_engine_parked.store(true, std::memory_order_seq_cst);
          lk.unlock();
          bool used = uring_idle_wait(io_tick_ms());
          g_engine_parked.store(false, std::memory_order_relaxed);
          lk.lock();
          if (used) continue;
        }
#endif
        e.cv.wait_for(lk, std::chrono::milliseconds(200));
      }
      quit = e.quit;
      if (!e.queue.empty()) {
        next = e.queue.front();
        e.queue.pop_front();
        e.qsize.fetch_sub(1, std::memory_order_relaxed);
      }
    }
    if (quit || g_stop.load(std::memory_order_acquire)) {
      // no further progress is possible right now: drain everything as
      // failed so waiters observe the context instead of hanging.  A
      // fault is terminal; an elastic resize is NOT — interrupted
      // requests fail with a ResizeInterrupted status and the engine
      // resumes service once the resized world is up (g_stop clears).
      std::string why = posted_fault_msg();
      if (why.empty()) {
        if (!quit && g_resizing.load(std::memory_order_acquire))
          why = err_prefix() +
                "async request interrupted by elastic world resize — "
                "ResizeInterrupted: reissue it on the resized world "
                "(docs/failure-semantics.md \"elastic membership\")";
        else
          why = err_prefix() + "async request abandoned: bridge " +
                std::string(quit ? "finalized" : "stopped");
      }
      if (next) async_complete(next, true, why);
      for (;;) {
        std::shared_ptr<AsyncOp> q;
        {
          std::lock_guard<std::mutex> lk(e.mu);
          if (e.queue.empty()) break;
          q = e.queue.front();
          e.queue.pop_front();
          e.qsize.fetch_sub(1, std::memory_order_relaxed);
        }
        async_complete(q, true, why);
      }
      for (auto& p : parked) async_complete(p, true, why);
      parked.clear();
      if (quit) return;
      // stopped but not finalizing: submits are rejected at the door
      // once g_stop is set, but a submit that passed that check just
      // before the stop may still land in the queue — keep draining
      // late arrivals as failed (their waiters would otherwise block
      // forever) until finalize joins us, OR until a completed elastic
      // resize clears the stop, in which case normal service resumes
      // on the resized world (finish_resize notifies e.cv).
      bool resume = false;
      while (!resume) {
        std::shared_ptr<AsyncOp> late;
        {
          std::unique_lock<std::mutex> lk(e.mu);
          while (e.queue.empty() && !e.quit &&
                 g_stop.load(std::memory_order_acquire)) {
            // same bounded wait while soft-stopped (resize in flight):
            // a resizing rank is alive, and its heartbeat must say so
            tel::flight_heartbeat();
#if T4J_HAVE_URING
            if (ew.ok) {
              g_engine_parked.store(true, std::memory_order_seq_cst);
              lk.unlock();
              bool used = uring_idle_wait(io_tick_ms());
              g_engine_parked.store(false, std::memory_order_relaxed);
              lk.lock();
              if (used) continue;
            }
#endif
            e.cv.wait_for(lk, std::chrono::milliseconds(200));
          }
          if (e.quit && e.queue.empty()) return;
          if (!e.quit && !g_stop.load(std::memory_order_acquire)) {
            resume = true;  // resized world is up: back to service
            break;
          }
          if (e.queue.empty()) return;  // e.quit
          late = e.queue.front();
          e.queue.pop_front();
          e.qsize.fetch_sub(1, std::memory_order_relaxed);
        }
        async_complete(late, true, why);
      }
      continue;
    }
    if (next) {
      {
        std::lock_guard<std::mutex> lk(e.mu);
        next->state = AsyncOp::kRunning;
      }
      next->t_start_ns = tel::now_ns();
      tel::trace_event(tel::kOpProgress, tel::kInstant, tel::kPlaneNone,
                       async_evt_comm(*next),
                       e.depth.load(std::memory_order_relaxed),
                       next->payload_bytes);
      if (next->kind == AsyncOp::kRecv) {
        // append, don't try immediately: older parked receives must
        // get first crack at the mailbox (MPI posted-order matching —
        // the poll below walks `parked` oldest-first, and the queue is
        // FIFO, so post order is preserved end to end)
        parked.push_back(next);
      } else {
        engine_run_blocking(next);
      }
    }
    // reap zerocopy completions between ops (cheap; no-op when the
    // zerocopy path is off)
    reap_all_zc();
    // poll parked irecvs every iteration: they never block the engine
    for (size_t i = 0; i < parked.size();) {
      if (engine_try_recv(parked[i]))
        parked.erase(parked.begin() + static_cast<long>(i));
      else
        ++i;
    }
    if (!next && !parked.empty()) {
      // idle with parked recvs: sleep on the MAILBOX condvar so an
      // arriving frame wakes us immediately (submits notify it too);
      // the 100ms tick bounds the parked-deadline checks.  The match
      // re-check under the lock closes the scan-then-sleep window.
      std::unique_lock<std::mutex> mlk(g_mail_mu);
      bool ready = false;
      for (auto it = g_mailbox.begin();
           it != g_mailbox.end() && !ready; ++it)
        for (auto& p : parked)
          if (frame_matches(*it, p->wire_ctx, p->world_src, p->tag)) {
            ready = true;
            break;
          }
      if (!ready && e.qsize.load(std::memory_order_relaxed) == 0 &&
          !g_stop.load(std::memory_order_acquire)) {
        tel::flight_heartbeat();
#if T4J_HAVE_URING
        if (ew.ok) {
          g_engine_parked.store(true, std::memory_order_seq_cst);
          mlk.unlock();
          bool used = uring_idle_wait(io_tick_ms());
          g_engine_parked.store(false, std::memory_order_relaxed);
          if (!used) {
            mlk.lock();
            g_mail_cv.wait_for(mlk, std::chrono::milliseconds(100));
          }
        } else
#endif
          // the tick bounds the parked-deadline checks; adaptive so an
          // idle engine with parked recvs does not spin
          g_mail_cv.wait_for(
              mlk, std::chrono::milliseconds(io_tick_ms()));
      }
    }
  }
}

uint64_t async_submit(const std::shared_ptr<AsyncOp>& op) {
  if (g_stop.load(std::memory_order_acquire)) raise_stopped();
  AsyncEngine& e = engine();
  uint64_t id;
  {
    std::lock_guard<std::mutex> lk(e.mu);
    if (e.quit)
      throw BridgeError(err_prefix() + cur_op() +
                        ": async submit during finalize");
    id = e.next_id++;
    op->id = id;
    e.inflight.emplace(id, op);
    e.queue.push_back(op);
    e.qsize.fetch_add(1, std::memory_order_relaxed);
    e.depth.fetch_add(1, std::memory_order_relaxed);
    if (!e.running) {
      e.running = true;
      e.thread = std::thread(engine_loop);
    }
  }
  // kOpQueued carries the post-submit in-flight depth in `peer`
  tel::trace_event(tel::kOpQueued, tel::kInstant, tel::kPlaneNone,
                   async_evt_comm(*op),
                   e.depth.load(std::memory_order_relaxed),
                   op->payload_bytes);
  e.cv.notify_one();
  // the engine may be sleeping on the mailbox condvar (parked recvs)
  { std::lock_guard<std::mutex> lk(g_mail_mu); }
  g_mail_cv.notify_all();
  poke_engine();
  return id;
}

// Route a blocking collective with no nonblocking counterpart through
// the engine: submit the body as a kGeneric op and block until it ran.
// Keeps the single-wire-path invariant — without this, a caller-thread
// bcast could crecv the same (src, ctx, tag) FIFO as an in-flight
// engine collective on the same comm and steal its frames.
void run_on_engine(int comm, std::function<void()> body) {
  auto a = std::make_shared<AsyncOp>();
  a->kind = AsyncOp::kGeneric;
  a->comm = comm;
  a->body = std::move(body);
  wait(async_submit(a), nullptr, nullptr);
}

// Bounded wait for the engine to go idle (finalize path): leaked
// in-flight requests get one chance to complete normally — if every
// rank leaked the same collective it just finishes — before the
// teardown breaks whatever is left via g_stop.
void quiesce_async_engine(double limit_s) {
  AsyncEngine& e = engine();
  Deadline dl = Deadline::after(limit_s);
  std::unique_lock<std::mutex> lk(e.mu);
  while (e.depth.load(std::memory_order_relaxed) > 0 && !dl.expired() &&
         !g_stop.load(std::memory_order_acquire))
    e.done_cv.wait_for(lk, std::chrono::milliseconds(100));
}

// Finalize-path teardown: fail whatever is still queued/parked, join
// the thread, report leaked (never-waited) requests, and reset so a
// re-init in the same process gets a fresh engine.
void stop_async_engine() {
  AsyncEngine& e = engine();
  std::thread t;
  {
    std::lock_guard<std::mutex> lk(e.mu);
    if (!e.running && e.inflight.empty()) return;
    e.quit = true;
    t = std::move(e.thread);
  }
  e.cv.notify_all();
  // a leaked RUNNING op may be blocked in the mailbox wait; g_stop is
  // already set on this path, so one notify makes it raise and drain
  { std::lock_guard<std::mutex> lk(g_mail_mu); }
  g_mail_cv.notify_all();
  poke_engine();
  if (t.joinable()) t.join();
  size_t leaked;
  std::string kinds;
  {
    std::lock_guard<std::mutex> lk(e.mu);
    leaked = e.inflight.size();
    int shown = 0;
    for (auto& kv : e.inflight) {
      if (shown++ == 4) {
        kinds += ", ...";
        break;
      }
      static const char* names[] = {"iallreduce", "ireduce_scatter",
                                    "isend", "irecv", "blocking-op"};
      static_assert(AsyncOp::kGeneric + 1 ==
                        sizeof(names) / sizeof(names[0]),
                    "names[] must cover every AsyncOp::Kind");
      if (!kinds.empty()) kinds += ", ";
      kinds += names[kv.second->kind];
    }
    e.inflight.clear();
    e.running = false;
    e.quit = false;
  }
  if (leaked) {
    std::fprintf(stderr,
                 "r%d | t4j: %zu async request(s) never waited (%s) — "
                 "every iallreduce/isend/irecv/ireduce_scatter must be "
                 "completed by wait/waitall exactly once (request leak; "
                 "docs/async.md)\n",
                 g_rank, leaked, kinds.c_str());
    std::fflush(stderr);
  }
}

// -------------------------------------------------- elastic resize engine
//
// Shrink-to-survive and rejoin instead of whole-job abort
// (docs/failure-semantics.md "elastic membership").  The escalation
// ladder grows one rung: retry -> reconnect+replay -> SHRINK/REJOIN ->
// abort.  When escalate_link declares a rank unrecoverable and
// T4J_ELASTIC is shrink|rejoin:
//
//   1. Every survivor that notices (or is told) enters a resize: the
//      bridge soft-stops (g_stop) so every in-flight op — blocked
//      callers, shm-arena waiters, queued/parked/running engine
//      requests — drains promptly with a ResizeInterrupted status
//      (NOT a fault: the stop clears when the resized world is up).
//   2. Survivors flood their suspected-dead masks to every presumed-
//      alive peer over FRESH dials to the mesh listeners (the same
//      out-of-band channel the reconnect handshake uses, incarnation
//      tokens verifying identity), so the agreement never rides the
//      possibly-torn data-plane streams.  The lowest surviving rank
//      arbitrates: it ANDs the reports within T4J_RESIZE_TIMEOUT
//      (silent ranks are dead — cascades fold in), floors the result
//      against T4J_MIN_WORLD, and floods the verdict (the final alive
//      mask).  A silent arbiter is itself presumed dead and the
//      next-lowest survivor takes over — every rank flooded to
//      everyone, so the successor already holds the reports.
//   3. Survivors apply the verdict: world epoch bumps (stamped into
//      every wire frame; stale-epoch traffic is dropped), per-link
//      sequence/replay state resets, the world communicator is rebuilt
//      over the members (every other comm handle is invalidated — the
//      Python tier surfaces WorldResized and rebuilds), fresh TCP
//      links come up pair-by-pair (bootstrap orientation, epoch-
//      checked handshake), the same-host pipe transport re-negotiates
//      under an epoch-suffixed namespace, and a barrier over the new
//      world fences the epoch before user traffic resumes.
//   4. rejoin mode: rank 0 keeps the bootstrap coordinator port open.
//      A relaunched replacement process (T4J_REJOIN=1) dials it with a
//      FRESH incarnation token; rank 0 runs a grow resize — the
//      verdict carries the rejoiner's new endpoint/token to every
//      survivor and the full endpoint table back to the rejoiner —
//      and the rejoiner joins the link rebuild at the next epoch
//      fence.  (This is the same incarnation-token machinery that
//      makes a RESTARTED process unrecoverable for plain reconnect:
//      the fresh token now has a legal path back in.)
//
// Failure at any step falls back to the legacy rung: posted fault,
// job over — fail-stop remains the backstop.

struct ResizeState {
  std::mutex mu;
  std::condition_variable cv;  // inbox arrivals, epoch advances
  bool active = false;         // a resize thread owns the protocol
  uint64_t pending_dead = 0;   // accumulated suspected-dead mask
  // out-of-band inbox: reports/verdicts landed on the mesh listener
  // (addrs is index-parallel: the grow verdict's PeerAddr payload)
  std::vector<ResizeMsg> inbox;
  std::vector<PeerAddr> addrs;
  // rejoin trigger (rank 0 only): the replacement's identity and its
  // still-open coordinator connection (answered at the verdict)
  int grow_rank = -1;
  PeerAddr grow_addr{};
  int grow_fd = -1;
};

// leaked: handler threads and the resize thread are detached
ResizeState& g_resize = *new ResizeState;

// One 32-byte control message (plus an optional PeerAddr payload) on a
// fresh dial to `dest`'s mesh listener.  Fire-and-forget: a false
// return means the listener is unreachable — for the agreement that
// IS information (the rank is dead).
bool send_resize_msg(int dest, const ResizeMsg& m, const PeerAddr* addr) {
  if (dest < 0 || dest >= static_cast<int>(g_endpoints.size()))
    return false;
  std::string why;
  int fd = dial_once(g_endpoints[dest].host, g_endpoints[dest].port,
                     Deadline::after(connect_timeout()), &why, nullptr,
                     /*ignore_stop=*/true);
  if (fd < 0) return false;
  Deadline dl = Deadline::after(connect_timeout());
  iovec iov[2] = {{const_cast<ResizeMsg*>(&m), sizeof(m)},
                  {const_cast<PeerAddr*>(addr),
                   addr ? sizeof(PeerAddr) : 0}};
  IoStatus st = nb_write_all(fd, iov, addr ? 2 : 1, dl,
                             /*ignore_stop=*/true);
  ::close(fd);
  return st == IoStatus::kOk;
}

// Quiesce the local data plane for the membership change: the readers
// and the engine drain against g_stop, the same-host transports are
// dropped (they are rebuilt over the new membership), every TCP link
// is closed and its sequence/replay state reset (no replay crosses an
// epoch — interrupted ops are REISSUED by the caller, not resumed),
// and pre-resize mailbox frames are purged.
void quiesce_for_resize() {
  for (auto& p : g_peers) {
    for (int si = 0; si < p.nstripes; ++si) {
      Stripe& st = p.s[si];
      {
        std::lock_guard<std::mutex> lk(st.send_mu);
        if (st.fd >= 0) ::shutdown(st.fd, SHUT_RDWR);
      }
      st.cv.notify_all();
      std::lock_guard<std::mutex> jk(st.join_mu);
      if (st.reader.joinable()) st.reader.join();
    }
  }
  g_pipe_readers.join_all();
  // the engine fails its queued/parked/running requests against the
  // stop; bound the wait (a wedged op body is additionally bounded by
  // its own per-op deadline and the overall resize window)
  Deadline dl = Deadline::after(resize_timeout());
  while (engine().depth.load(std::memory_order_relaxed) > 0 &&
         !dl.expired())
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  {
    std::lock_guard<std::mutex> lk(g_pipe_pub_mu);
    for (auto*& tx : g_tx_pipes) {
      if (tx) shm::pipe_close(tx);
      tx = nullptr;
    }
    if (g_my_pipes) {
      shm::pipes_destroy(g_my_pipes);
      g_my_pipes = nullptr;
    }
  }
  {
    std::lock_guard<std::mutex> lk(g_comm_mu);
    for (auto& c : g_comms) {
      if (c.arena) shm::destroy(c.arena);
      c.arena = nullptr;
      c.arena_checked = true;
    }
  }
  for (auto& p : g_peers) {
    for (int si = 0; si < p.nstripes; ++si) {
      Stripe& st = p.s[si];
      std::lock_guard<std::mutex> slk(st.send_mu);
      if (st.fd >= 0) {
        // in-flight zerocopy sends pin the arena we are about to
        // clear; the socket is already shut down, so completions are
        // immediate — drain them before the reset
        (void)zc_wait(st, st.zc_sent, Deadline::after(2.0));
        ::close(st.fd);
        st.fd = -1;
      }
      st.ring.clear();
      st.ring_head = 0;
      st.max_evicted_seq = 0;
      st.migrated = false;
      st.zc_sent = 0;
      st.zc_done = 0;
      st.zc_enabled = false;
      st.seen_seq.store(0, std::memory_order_relaxed);
      std::lock_guard<std::mutex> lk(st.mu);
      if (st.state != Stripe::kDead) st.state = Stripe::kBroken;
      st.repairing = false;
    }
    std::lock_guard<std::mutex> dlk(p.deal_mu);
    p.send_seq = 0;
    p.dealt = 0;
    std::lock_guard<std::mutex> rlk(p.ro_mu);
    p.delivered = 0;
    p.reorder.clear();
  }
  {
    std::lock_guard<std::mutex> lk(g_mail_mu);
    g_mailbox.clear();
  }
}

// Commit a membership verdict: mark departures, adopt a rejoiner's
// fresh identity, bump the epoch, and rebuild the world communicator
// over the members.  Every other comm handle is invalidated (the
// Python tier clears its cache when it surfaces WorldResized).
void apply_membership(uint64_t final_alive, uint32_t epoch, int grow_rank,
                      const PeerAddr* grow_addr) {
  uint64_t old = g_alive_mask.load(std::memory_order_relaxed);
  uint64_t died = old & ~final_alive;
  for (int r = 0; r < g_size && r < 64; ++r) {
    if (!((died >> r) & 1)) continue;
    tel::control_event(tel::kRankDead, r, epoch);
    std::fprintf(stderr,
                 "r%d | t4j: rank r%d left the world at epoch %u\n",
                 g_rank, r, epoch);
    PeerLink& p = g_peers[r];
    for (int si = 0; si < p.nstripes; ++si) {
      std::lock_guard<std::mutex> lk(p.s[si].mu);
      p.s[si].state = Stripe::kDead;
    }
    p.dead_mask.store(
        p.nstripes >= 32 ? ~0u : ((1u << p.nstripes) - 1),
        std::memory_order_relaxed);
  }
  std::fflush(stderr);
  if (grow_rank >= 0 && grow_addr) {
    char ip[INET_ADDRSTRLEN];
    in_addr a{grow_addr->ip};
    ::inet_ntop(AF_INET, &a, ip, sizeof(ip));
    g_endpoints[grow_rank].host = ip;
    g_endpoints[grow_rank].port = grow_addr->port;
    g_endpoints[grow_rank].boot_token = grow_addr->boot_token;
    if (grow_rank < static_cast<int>(g_host_fps.size()))
      g_host_fps[grow_rank] = grow_addr->host_fp;
    PeerLink& p = g_peers[grow_rank];
    for (int si = 0; si < p.nstripes; ++si) {
      std::lock_guard<std::mutex> lk(p.s[si].mu);
      p.s[si].state = Stripe::kBroken;  // rebuilt like every survivor
    }
    p.dead_mask.store(0, std::memory_order_relaxed);
  }
  g_alive_mask.store(final_alive, std::memory_order_relaxed);
  g_world_epoch.store(epoch, std::memory_order_release);
  tel::flight_set_epoch(epoch);  // postmortems order deaths vs resizes
  g_world_ctx = derive_hier_ctx(0, 'E', epoch);
  std::lock_guard<std::mutex> lk(g_comm_mu);
  g_comms.clear();
  Comm world;
  world.my_index = -1;
  for (int r = 0; r < g_size; ++r)
    if ((final_alive >> r) & 1) {
      if (r == g_rank)
        world.my_index = static_cast<int>(world.ranks.size());
      world.ranks.push_back(r);
    }
  world.ctx = g_world_ctx;
  g_comms.push_back(world);
}

// Install a freshly handshaken stripe connection (reader started
// separately once the stop clears — a reader started under g_stop
// would exit at once).  The LINK-level dealing/delivery cursors were
// already reset by quiesce_for_resize; marking the last stripe kUp is
// what flips the link live.
void install_link(int r, int si, int fd) {
  PeerLink& p = g_peers[r];
  Stripe& st = p.s[si];
  {
    std::lock_guard<std::mutex> lk(st.send_mu);
    if (st.fd >= 0) ::shutdown(st.fd, SHUT_RDWR);
  }
  {
    std::lock_guard<std::mutex> jk(st.join_mu);
    if (st.reader.joinable()) st.reader.join();
  }
  {
    std::lock_guard<std::mutex> slk(st.send_mu);
    if (st.fd >= 0) ::close(st.fd);
    st.fd = fd;
    st.ring.clear();
    st.ring_head = 0;
    st.max_evicted_seq = 0;
    st.migrated = false;
    st.zc_sent = 0;
    st.zc_done = 0;
    stripe_enable_zc(st);
    st.seen_seq.store(0, std::memory_order_relaxed);
    std::lock_guard<std::mutex> lk(st.mu);
    st.state = Stripe::kUp;
    ++st.epoch;
  }
  p.dead_mask.fetch_and(~(1u << si), std::memory_order_relaxed);
  st.cv.notify_all();
}

void start_reader(int r) {
  PeerLink& p = g_peers[r];
  for (int si = 0; si < p.nstripes; ++si) {
    Stripe& st = p.s[si];
    std::lock_guard<std::mutex> slk(st.send_mu);
    if (st.fd < 0) continue;
    std::lock_guard<std::mutex> jk(st.join_mu);
    if (!st.reader.joinable())
      st.reader = std::thread(reader_loop, r, si, st.fd);
  }
}

void start_readers(uint64_t alive) {
  for (int r = 0; r < g_size && r < 64; ++r)
    if (r != g_rank && ((alive >> r) & 1)) start_reader(r);
}

// Dialer side of the pair-by-pair link rebuild (bootstrap
// orientation: the higher rank dials the lower rank's mesh listener),
// one handshake per stripe — the dial's ResizeMsg carries the stripe
// index in `mask`.
bool rebuild_dial(int r, int si, uint32_t epoch, const Deadline& dl) {
  std::string why = "dial failed";
  int attempt = 0;
  while (!dl.expired()) {
    if (g_shutting_down.load(std::memory_order_acquire) ||
        g_faulted.load(std::memory_order_acquire))
      return false;
    int fd = dial_once(g_endpoints[r].host, g_endpoints[r].port,
                       Deadline::after(connect_timeout()), &why, nullptr,
                       /*ignore_stop=*/true);
    if (fd >= 0) {
      Deadline io = Deadline::after(connect_timeout());
      ResizeMsg m{kResizeMagic, kResizeDial,
                  static_cast<uint32_t>(g_rank), epoch,
                  static_cast<uint64_t>(si), g_my_boot_token};
      iovec iov[1] = {{&m, sizeof(m)}};
      ResizeMsg ack{};
      if (nb_write_all(fd, iov, 1, io, true) == IoStatus::kOk &&
          nb_read_all(fd, &ack, sizeof(ack), io, true) == IoStatus::kOk &&
          ack.magic == kResizeMagic && ack.type == kResizeAck &&
          ack.mask == 1 && ack.epoch == epoch) {
        install_link(r, si, fd);
        return true;
      }
      ::close(fd);
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(
        static_cast<int>(backoff_delay_s(attempt++) * 1000)));
  }
  return false;
}

// Rebuild every surviving pair's TCP link at `epoch`: dial the lower
// alive ranks (every stripe of a link concurrently — the bootstrap
// bugfix applies here too), wait for the higher ones to dial us
// (their handshakes are answered by handle_resize_msg on the accept
// thread).
bool rebuild_links(uint64_t alive, uint32_t epoch) {
  Deadline dl = Deadline::after(resize_timeout() + connect_timeout());
  for (int r = 0; r < g_rank && r < 64; ++r) {
    if (!((alive >> r) & 1)) continue;
    int ns = g_peers[r].nstripes;
    std::vector<std::thread> dials;
    std::atomic<int> ok{0};
    for (int si = 0; si < ns; ++si)
      dials.emplace_back([&, r, si] {
        if (rebuild_dial(r, si, epoch, dl))
          ok.fetch_add(1, std::memory_order_relaxed);
      });
    for (auto& t : dials) t.join();
    if (ok.load(std::memory_order_relaxed) != ns) return false;
  }
  for (int r = g_rank + 1; r < g_size && r < 64; ++r) {
    if (!((alive >> r) & 1)) continue;
    PeerLink& p = g_peers[r];
    for (int si = 0; si < p.nstripes; ++si) {
      Stripe& st = p.s[si];
      std::unique_lock<std::mutex> lk(st.mu);
      while (st.state != Stripe::kUp) {
        if (dl.expired() ||
            g_shutting_down.load(std::memory_order_acquire) ||
            g_faulted.load(std::memory_order_acquire))
          return false;
        st.cv.wait_for(lk, std::chrono::milliseconds(100));
      }
    }
  }
  return true;
}

// Resize failure: fall back to the legacy rung.  The data links are
// already torn down, so there is no abort broadcast to ride — peers
// that cannot complete their own resize reach this same conclusion
// through their T4J_RESIZE_TIMEOUT.
void resize_abort(const std::string& why) {
  std::string msg = err_prefix() + "elastic resize failed: " + why +
                    " — escalating to abort "
                    "(docs/failure-semantics.md \"elastic membership\")";
  int stale_fd = -1;
  {
    std::lock_guard<std::mutex> lk(g_resize.mu);
    g_resize.inbox.clear();
    g_resize.addrs.clear();
    g_resize.pending_dead = 0;
    g_resize.active = false;
    stale_fd = g_resize.grow_fd;
    g_resize.grow_fd = -1;
    g_resize.grow_rank = -1;
  }
  if (stale_fd >= 0) ::close(stale_fd);
  post_fault(msg);
  g_resizing.store(false, std::memory_order_release);
  g_resize.cv.notify_all();
  wake_async_engine();
}

// Membership agreement for a shrink.  Every entrant floods its
// suspected-dead mask to every presumed survivor; the lowest
// surviving rank arbitrates.  Returns true with *out_alive = the
// agreed membership; false = the job must abort.
bool shrink_agreement(uint64_t alive, uint64_t dead, uint32_t epoch,
                      uint64_t* out_alive) {
  auto flood = [&](uint64_t d) {
    ResizeMsg m{kResizeMagic, kResizeReport,
                static_cast<uint32_t>(g_rank), epoch, d,
                g_my_boot_token};
    for (int r = 0; r < g_size && r < 64; ++r) {
      if (r == g_rank || !((alive >> r) & 1) || ((d >> r) & 1)) continue;
      m.mask = d;
      if (!send_resize_msg(r, m, nullptr))
        d |= 1ull << r;  // unreachable listener: fold the cascade in
    }
    return d;
  };
  dead = flood(dead);
  Deadline total = Deadline::after(3 * resize_timeout() + 5.0);
  for (;;) {
    if (g_shutting_down.load(std::memory_order_acquire) ||
        g_faulted.load(std::memory_order_acquire) || total.expired())
      return false;
    int coord = -1;
    for (int r = 0; r < g_size && r < 64; ++r)
      if (((alive >> r) & 1) && !((dead >> r) & 1)) {
        coord = r;
        break;
      }
    if (coord < 0) return false;
    if ((dead >> g_rank) & 1) return false;  // peers declared me dead
    if (coord == g_rank) {
      // arbiter: collect every survivor's flood, AND silence into the
      // dead set, floor against T4J_MIN_WORLD, flood the verdict
      Deadline dl = Deadline::after(resize_timeout());
      uint64_t have = 1ull << g_rank;
      {
        std::unique_lock<std::mutex> lk(g_resize.mu);
        for (;;) {
          for (const ResizeMsg& r : g_resize.inbox) {
            if (r.type != kResizeReport || r.epoch != epoch) continue;
            dead |= r.mask;
            if (r.rank < 64) have |= 1ull << r.rank;
          }
          uint64_t expected = alive & ~dead & ~(1ull << g_rank);
          if ((have & expected) == expected) break;
          if (dl.expired()) {
            dead |= expected & ~have;  // silent ranks are gone too
            break;
          }
          g_resize.cv.wait_for(lk, std::chrono::milliseconds(50));
        }
      }
      uint64_t final_alive = alive & ~dead;
      bool ok = popcount64(final_alive) >= min_world() &&
                ((final_alive >> g_rank) & 1);
      ResizeMsg v{kResizeMagic, kResizeVerdict,
                  static_cast<uint32_t>(g_rank), epoch,
                  ok ? final_alive : 0, g_my_boot_token};
      for (int r = 0; r < g_size && r < 64; ++r) {
        if (r == g_rank || !((final_alive >> r) & 1)) continue;
        (void)send_resize_msg(r, v, nullptr);
      }
      if (!ok) return false;
      *out_alive = final_alive;
      return true;
    }
    // follower: wait for the arbiter's verdict, folding in any late
    // reports (cascades).  A silent arbiter is itself dead — mark it
    // and loop; the next-lowest survivor already holds every flood.
    Deadline dl = Deadline::after(resize_timeout() + 2.0);
    bool got = false;
    uint64_t verdict = 0;
    {
      std::unique_lock<std::mutex> lk(g_resize.mu);
      while (!dl.expired() &&
             !g_shutting_down.load(std::memory_order_acquire)) {
        for (const ResizeMsg& r : g_resize.inbox) {
          if (r.epoch != epoch) continue;
          if (r.type == kResizeVerdict &&
              static_cast<int>(r.rank) == coord) {
            got = true;
            verdict = r.mask;
          } else if (r.type == kResizeReport) {
            dead |= r.mask;
          }
        }
        if (got || ((dead >> coord) & 1)) break;
        g_resize.cv.wait_for(lk, std::chrono::milliseconds(50));
      }
    }
    if (got) {
      if (verdict == 0 || !((verdict >> g_rank) & 1))
        return false;  // abort verdict, or I am not in the new world
      *out_alive = verdict;
      return true;
    }
    if (!((dead >> coord) & 1)) {
      dead |= 1ull << coord;
      dead = flood(dead);  // the successor arbiter must hear of it
    }
  }
}

// Close out a successful resize: resume the data plane, fence the
// epoch with a barrier over the new world, release the Python gate.
void finish_resize(uint32_t epoch) {
  int stale_fd = -1;
  {
    std::lock_guard<std::mutex> lk(g_resize.mu);
    g_resize.inbox.clear();
    g_resize.addrs.clear();
    g_resize.pending_dead = 0;
    stale_fd = g_resize.grow_fd;  // a rejoin that raced this resize
    g_resize.grow_fd = -1;        // re-dials once we are done
    g_resize.grow_rank = -1;
  }
  if (stale_fd >= 0) ::close(stale_fd);
  // back in service: the stop clears FIRST (readers started under
  // g_stop would exit immediately), then the data plane comes up
  if (!g_faulted.load(std::memory_order_acquire))
    g_stop.store(false, std::memory_order_release);
  start_readers(g_alive_mask.load(std::memory_order_relaxed));
  wake_async_engine();  // the drained engine resumes service
  // same-host transports re-negotiate over the members now that the
  // data plane is live again (the agreement rounds ride raw TCP)
  setup_pipes();
  std::fprintf(stderr,
               "r%d | t4j: world resized: epoch %u, %d member(s), "
               "mask 0x%llx\n",
               g_rank, epoch, alive_count(),
               static_cast<unsigned long long>(
                   g_alive_mask.load(std::memory_order_relaxed)));
  std::fflush(stderr);
  // protocol ownership ends before the fence: a member dying DURING
  // the fence may legitimately start the next resize
  {
    std::lock_guard<std::mutex> lk(g_resize.mu);
    g_resize.active = false;
  }
  // epoch fence: every member reaches the new epoch before user
  // traffic resumes (the rejoiner pairs this with its init barrier)
  try {
    barrier(0);
  } catch (const BridgeError&) {
    // a member died at the fence: the live escalation machinery owns
    // the follow-up (next resize, or abort)
  }
  tel::control_event(tel::kResizeDone, alive_count(), epoch);
  {
    // release the Python-side gate unless a NEW resize already took
    // ownership during the fence
    std::lock_guard<std::mutex> lk(g_resize.mu);
    if (!g_resize.active)
      g_resizing.store(false, std::memory_order_release);
  }
  g_resize.cv.notify_all();
}

// The resize protocol body (one detached thread per resize, spawned
// by the first enter_resize).
void resize_main() {
  quiesce_for_resize();
  if (g_shutting_down.load(std::memory_order_acquire)) return;
  uint64_t alive = g_alive_mask.load(std::memory_order_relaxed);
  uint32_t epoch = cur_epoch() + 1;
  uint64_t dead;
  int grow_rank;
  PeerAddr grow_addr{};
  int grow_fd;
  {
    std::lock_guard<std::mutex> lk(g_resize.mu);
    dead = g_resize.pending_dead;
    grow_rank = g_resize.grow_rank;
    grow_addr = g_resize.grow_addr;
    grow_fd = g_resize.grow_fd;
    g_resize.grow_rank = -1;
    g_resize.grow_fd = -1;
  }
  uint64_t final_alive = 0;
  int add_rank = -1;
  PeerAddr add_addr{};
  bool ok = false;
  if (grow_rank >= 0 && dead == 0) {
    // grow resize, coordinator side (rank 0): announce the rejoiner's
    // fresh identity to every survivor, then answer the rejoiner with
    // the verdict + the full endpoint table over its coordinator dial
    add_rank = grow_rank;
    add_addr = grow_addr;
    final_alive = alive | (1ull << grow_rank);
    ResizeMsg v{kResizeMagic, kResizeGrow,
                static_cast<uint32_t>(grow_rank), epoch, final_alive,
                g_my_boot_token};
    for (int r = 0; r < g_size && r < 64; ++r) {
      if (r == g_rank || !((alive >> r) & 1)) continue;
      (void)send_resize_msg(r, v, &add_addr);
    }
    if (grow_fd >= 0) {
      std::vector<PeerAddr> table(g_size);
      for (int r = 0; r < g_size; ++r) {
        in_addr a{};
        ::inet_pton(AF_INET, g_endpoints[r].host.c_str(), &a);
        table[r].ip = a.s_addr;
        table[r].port = g_endpoints[r].port;
        table[r].pad = 0;
        table[r].host_fp =
            r < static_cast<int>(g_host_fps.size()) ? g_host_fps[r] : 0;
        table[r].boot_token = g_endpoints[r].boot_token;
      }
      table[grow_rank] = add_addr;
      Deadline io = Deadline::after(connect_timeout());
      iovec iov[2] = {{&v, sizeof(v)},
                      {table.data(), sizeof(PeerAddr) * table.size()}};
      (void)nb_write_all(grow_fd, iov, 2, io, /*ignore_stop=*/true);
      ::close(grow_fd);
      grow_fd = -1;
    }
    ok = true;
  } else {
    if (grow_fd >= 0) {
      ::close(grow_fd);  // a shrink takes precedence; the rejoiner
      grow_fd = -1;      // re-dials once the world settles
    }
    // survivor side of a grow: the coordinator's verdict is already
    // in the inbox (it is what triggered this resize)
    {
      std::lock_guard<std::mutex> lk(g_resize.mu);
      for (size_t i = 0; i < g_resize.inbox.size(); ++i) {
        const ResizeMsg& msg = g_resize.inbox[i];
        if (msg.type == kResizeGrow && msg.epoch == epoch &&
            static_cast<int>(msg.rank) < 64) {
          add_rank = static_cast<int>(msg.rank);
          add_addr = i < g_resize.addrs.size() ? g_resize.addrs[i]
                                               : PeerAddr{};
          final_alive = msg.mask;
          ok = true;
        }
      }
    }
    if (!ok)
      ok = shrink_agreement(alive, dead, epoch, &final_alive);
  }
  if (!ok) {
    resize_abort(
        "the membership agreement did not converge (arbiter verdict "
        "missing, this rank voted out, or the surviving world would "
        "fall below T4J_MIN_WORLD=" + std::to_string(min_world()) + ")");
    return;
  }
  apply_membership(final_alive, epoch, add_rank,
                   add_rank >= 0 ? &add_addr : nullptr);
  if (!rebuild_links(final_alive, epoch)) {
    resize_abort("could not re-establish the mesh over the agreed "
                 "membership within T4J_RESIZE_TIMEOUT");
    return;
  }
  finish_resize(epoch);
}

// resize_main runs on a detached thread: nothing may escape it.
void resize_main_guarded() {
  try {
    resize_main();
  } catch (const std::exception& e) {
    resize_abort(std::string("unexpected failure in the resize "
                             "protocol: ") + e.what());
  }
}

bool try_begin_resize(int peer, const std::string& why) {
  uint64_t bit =
      (peer >= 0 && peer < 64) ? (1ull << peer) : 0;
  if (bit && !rank_alive(peer))
    return true;  // already outside the membership: a resize owns it
  uint64_t pending;
  {
    std::lock_guard<std::mutex> lk(g_resize.mu);
    pending = g_resize.pending_dead;
  }
  uint64_t survivors =
      g_alive_mask.load(std::memory_order_relaxed) & ~(pending | bit);
  if (popcount64(survivors) < min_world()) return false;
  enter_resize(bit, "link to peer r" + std::to_string(peer) +
                        " unrecoverable: " + why);
  return true;
}

void enter_resize(uint64_t dead_delta, const std::string& why) {
  bool first = false;
  {
    std::lock_guard<std::mutex> lk(g_resize.mu);
    g_resize.pending_dead |= dead_delta;
    if (!g_resize.active) {
      g_resize.active = true;
      first = true;
      g_resizing.store(true, std::memory_order_release);
    }
  }
  g_resize.cv.notify_all();
  if (!first) return;
  uint32_t next = cur_epoch() + 1;
  std::fprintf(stderr,
               "r%d | t4j: elastic resize toward epoch %u "
               "(T4J_ELASTIC=%s): %s\n",
               g_rank, next,
               elastic_mode() == kElasticRejoin ? "rejoin" : "shrink",
               why.c_str());
  std::fflush(stderr);
  tel::control_event(tel::kResizeBegin, -1, next);
  // soft stop: every blocked op drains with ResizeInterrupted; the
  // stop clears again in finish_resize
  g_stop.store(true, std::memory_order_release);
  wake_all_pipes();
  wake_async_engine();
  for (auto& p : g_peers)
    for (int si = 0; si < p.nstripes; ++si) p.s[si].cv.notify_all();
  std::thread(resize_main_guarded).detach();
}

// Acceptor side of the out-of-band resize channel (dials landing on
// the mesh listener whose first 4 bytes are kResizeMagic).
void handle_resize_msg(int fd, const ResizeMsg& m) {
  Deadline dl = Deadline::after(connect_timeout());
  int r = static_cast<int>(m.rank);
  if (elastic_mode() == kElasticOff || r < 0 || r >= g_size ||
      r >= 64 || static_cast<int>(g_endpoints.size()) != g_size) {
    ::close(fd);
    return;
  }
  switch (m.type) {
    case kResizeReport:
    case kResizeVerdict: {
      if (m.token != g_endpoints[r].boot_token) break;  // stale sender
      {
        std::lock_guard<std::mutex> lk(g_resize.mu);
        g_resize.inbox.push_back(m);
        g_resize.addrs.push_back(PeerAddr{});
      }
      enter_resize(
          m.type == kResizeReport ? m.mask : 0,
          m.type == kResizeReport
              ? "peer r" + std::to_string(r) +
                    " flooded a suspected-dead set"
              : "membership verdict from arbiter r" + std::to_string(r));
      g_resize.cv.notify_all();
      break;
    }
    case kResizeGrow: {
      // from the grow coordinator (rank 0); the payload is the
      // rejoiner's fresh endpoint/incarnation
      if (m.token != g_endpoints[0].boot_token) break;
      PeerAddr addr{};
      if (nb_read_all(fd, &addr, sizeof(addr), dl,
                      /*ignore_stop=*/true) != IoStatus::kOk)
        break;
      {
        std::lock_guard<std::mutex> lk(g_resize.mu);
        g_resize.inbox.push_back(m);
        g_resize.addrs.push_back(addr);
      }
      enter_resize(0, "rank r" + std::to_string(r) +
                          " rejoins at the next epoch fence");
      g_resize.cv.notify_all();
      break;
    }
    case kResizeDial: {
      // link-rebuild handshake (the dial's `mask` carries the stripe
      // index): answer once OUR membership reaches the dial's epoch
      // (the verdict may still be in flight here)
      int si = static_cast<int>(m.mask);
      bool accept_dial = m.token != 0;
      {
        std::unique_lock<std::mutex> lk(g_resize.mu);
        Deadline wd = Deadline::after(resize_timeout());
        while (cur_epoch() < m.epoch && !wd.expired() &&
               !g_shutting_down.load(std::memory_order_acquire))
          g_resize.cv.wait_for(lk, std::chrono::milliseconds(50));
      }
      accept_dial = accept_dial && cur_epoch() == m.epoch &&
                    rank_alive(r) &&
                    m.token == g_endpoints[r].boot_token &&
                    si >= 0 && si < g_peers[r].nstripes;
      ResizeMsg ack{kResizeMagic, kResizeAck,
                    static_cast<uint32_t>(g_rank), cur_epoch(),
                    accept_dial ? 1ull : 0ull, g_my_boot_token};
      iovec iov[1] = {{&ack, sizeof(ack)}};
      if (nb_write_all(fd, iov, 1, dl, /*ignore_stop=*/true) !=
              IoStatus::kOk ||
          !accept_dial) {
        ::close(fd);
        return;
      }
      install_link(r, si, fd);
      if (!g_stop.load(std::memory_order_acquire)) start_reader(r);
      return;  // fd now owned by the stripe
    }
    default:
      break;
  }
  ::close(fd);
}

// Rank 0's coordinator listener (rejoin mode): replacement processes
// re-bootstrap through it.
void handle_rejoin_dial(int fd) {
  Deadline dl = Deadline::after(connect_timeout());
  ResizeMsg m{};
  PeerAddr addr{};
  if (nb_read_all(fd, &m, sizeof(m), dl, true) != IoStatus::kOk ||
      m.magic != kResizeMagic || m.type != kRejoinHello ||
      nb_read_all(fd, &addr, sizeof(addr), dl, true) != IoStatus::kOk) {
    ::close(fd);
    return;
  }
  int r = static_cast<int>(m.rank);
  if (r <= 0 || r >= g_size || r >= 64 || rank_alive(r) ||
      elastic_mode() != kElasticRejoin ||
      g_faulted.load(std::memory_order_acquire) ||
      g_shutting_down.load(std::memory_order_acquire)) {
    // rank still a member (old incarnation not yet declared dead), a
    // bad slot, or nothing to rejoin: the replacement re-dials with
    // backoff until the world settles
    ::close(fd);
    return;
  }
  sockaddr_in peer{};
  socklen_t len = sizeof(peer);
  ::getpeername(fd, reinterpret_cast<sockaddr*>(&peer), &len);
  addr.ip = peer.sin_addr.s_addr;
  addr.boot_token = m.token;
  {
    std::lock_guard<std::mutex> lk(g_resize.mu);
    if (g_resize.active || g_resize.grow_fd >= 0) {
      ::close(fd);  // a resize is running: the replacement re-dials
      return;
    }
    g_resize.grow_rank = r;
    g_resize.grow_addr = addr;
    g_resize.grow_fd = fd;
  }
  std::fprintf(stderr,
               "r%d | t4j: rank r%d re-bootstrapped (fresh incarnation) "
               "— growing the world back\n",
               g_rank, r);
  std::fflush(stderr);
  enter_resize(0, "rank r" + std::to_string(r) +
                      " re-bootstrapped and requests rejoin");
}

void coord_accept_loop() {
  while (!g_shutting_down.load(std::memory_order_acquire)) {
    pollfd pfd{g_coord_listen_fd, POLLIN, 0};
    int rc = ::poll(&pfd, 1, 100);
    if (rc <= 0) continue;
    sockaddr_in peer{};
    socklen_t len = sizeof(peer);
    int fd = ::accept(g_coord_listen_fd,
                      reinterpret_cast<sockaddr*>(&peer), &len);
    if (fd < 0) continue;
    set_nonblock(fd);
    tune_socket(fd);
    std::thread(handle_rejoin_dial, fd).detach();
  }
}

// Replacement-process bootstrap (T4J_REJOIN=1, docs/failure-semantics
// "elastic membership"): instead of the full-world rendezvous, dial
// rank 0's kept-open coordinator port with a FRESH incarnation token,
// receive the surviving world's endpoint table + membership + target
// epoch, and join the link rebuild at the epoch fence.
void rejoin_bootstrap(const std::string& coord_host, uint16_t coord_port) {
  {
    std::mt19937_64 rng(std::random_device{}() ^
                        static_cast<uint64_t>(::getpid()));
    g_my_boot_token = rng();
    if (!g_my_boot_token) g_my_boot_token = 1;
  }
  uint16_t my_port = 0;
  int listen_fd = tcp_listen(&my_port);
  uint64_t my_fp = host_fingerprint();
  ResizeMsg grow{};
  std::vector<PeerAddr> table(g_size);
  Deadline dl = Deadline::after(connect_timeout() + 2 * resize_timeout());
  int attempt = 0;
  for (;;) {
    if (dl.expired())
      fail_boot(
          "rejoin: the surviving world did not accept the re-bootstrap "
          "within the window (is the job running with "
          "T4J_ELASTIC=rejoin, and is rank 0 alive?)");
    std::string why;
    int fd = dial_once(coord_host, coord_port,
                       Deadline::after(connect_timeout()), &why);
    if (fd < 0) {
      if (!backoff_sleep(backoff_delay_s(attempt++))) raise_stopped();
      continue;
    }
    ResizeMsg hello{kResizeMagic, kRejoinHello,
                    static_cast<uint32_t>(g_rank), 0, 0,
                    g_my_boot_token};
    PeerAddr me{0, my_port, 0, my_fp, g_my_boot_token};
    iovec iov[2] = {{&hello, sizeof(hello)}, {&me, sizeof(me)}};
    Deadline io = Deadline::after(connect_timeout() + resize_timeout());
    if (nb_write_all(fd, iov, 2, io) == IoStatus::kOk &&
        nb_read_all(fd, &grow, sizeof(grow), io) == IoStatus::kOk &&
        grow.magic == kResizeMagic && grow.type == kResizeGrow &&
        static_cast<int>(grow.rank) == g_rank && grow.mask != 0 &&
        nb_read_all(fd, table.data(), sizeof(PeerAddr) * g_size, io) ==
            IoStatus::kOk) {
      ::close(fd);
      break;
    }
    ::close(fd);
    if (!backoff_sleep(backoff_delay_s(attempt++))) raise_stopped();
  }
  // adopt the surviving world's identity table and membership
  g_host_fps.resize(g_size);
  g_endpoints.assign(g_size, PeerEndpoint{});
  for (int i = 0; i < g_size; ++i) {
    g_host_fps[i] = table[i].host_fp;
    char ip[INET_ADDRSTRLEN];
    in_addr a{table[i].ip};
    ::inet_ntop(AF_INET, &a, ip, sizeof(ip));
    g_endpoints[i].host = (i == 0) ? coord_host : std::string(ip);
    g_endpoints[i].port = table[i].port;
    g_endpoints[i].boot_token = table[i].boot_token;
  }
  g_host_fps[g_rank] = my_fp;
  g_endpoints[g_rank].boot_token = g_my_boot_token;
  g_alive_mask.store(grow.mask, std::memory_order_relaxed);
  g_world_epoch.store(grow.epoch, std::memory_order_release);
  tel::flight_set_epoch(grow.epoch);
  g_world_ctx = derive_hier_ctx(0, 'E', grow.epoch);
  g_peers = std::vector<PeerLink>(g_size);
  for (int r = 0; r < g_size; ++r) {
    if (r == g_rank) continue;
    PeerLink& p = g_peers[r];
    p.alloc_stripes(g_built_stripes);
    bool alive = rank_alive(r);
    for (int si = 0; si < p.nstripes; ++si) {
      std::lock_guard<std::mutex> lk(p.s[si].mu);
      p.s[si].state = alive ? Stripe::kBroken : Stripe::kDead;
    }
    if (!alive)
      p.dead_mask.store(
          p.nstripes >= 32 ? ~0u : ((1u << p.nstripes) - 1),
          std::memory_order_relaxed);
  }
  g_listen_fd = listen_fd;
  g_accept_thread.v.emplace_back(accept_loop);
  {
    std::lock_guard<std::mutex> lk(g_comm_mu);
    g_comms.clear();
    Comm world;
    world.my_index = -1;
    for (int r = 0; r < g_size; ++r)
      if (rank_alive(r)) {
        if (r == g_rank)
          world.my_index = static_cast<int>(world.ranks.size());
        world.ranks.push_back(r);
      }
    world.ctx = g_world_ctx;
    g_comms.push_back(world);
  }
  std::fprintf(stderr,
               "r%d | t4j: rejoining the world at epoch %u "
               "(%d member(s))\n",
               g_rank, grow.epoch, alive_count());
  std::fflush(stderr);
  if (!rebuild_links(grow.mask, grow.epoch))
    fail_boot("rejoin: could not re-establish the mesh with the "
              "survivors within T4J_RESIZE_TIMEOUT");
  start_readers(grow.mask);
  setup_pipes();
  // the caller (init_from_env) runs the join barrier, which pairs
  // with the survivors' epoch-fence barrier
}

}  // namespace

// ---------------------------------------------------------------- public

size_t dtype_size(DType dt) {
  switch (dt) {
    case DType::kI8:
    case DType::kU8:
    case DType::kBool:
      return 1;
    case DType::kI16:
    case DType::kU16:
    case DType::kF16:
    case DType::kBF16:
      return 2;
    case DType::kF32:
    case DType::kI32:
    case DType::kU32:
      return 4;
    case DType::kF64:
    case DType::kI64:
    case DType::kU64:
    case DType::kC64:
      return 8;
    case DType::kC128:
      return 16;
  }
  fail_arg("unknown dtype");
}

bool initialized() { return g_initialized; }
int world_rank() { return g_rank; }
int world_size() { return g_size; }
void set_logging(bool enabled) { g_logging = enabled; }

void set_timeouts(double op_s, double connect_s) {
  // op_s: < 0 keeps the current value, 0 disables, > 0 sets.
  // connect_s: <= 0 keeps (a connect deadline cannot be disabled).
  if (op_s >= 0) g_op_timeout_s.store(op_s, std::memory_order_relaxed);
  if (connect_s > 0)
    g_connect_timeout_s.store(connect_s, std::memory_order_relaxed);
}

void set_tuning(long long ring_min, long long seg) {
  // ring_min: < 0 keeps the current value, 0 = always ring, > 0 sets
  // the switchover.  seg: < 1 keeps (a segment cannot be empty).
  // Must be uniform across ranks (the launcher propagates the env):
  // ranks disagreeing on the switchover would run mismatched
  // algorithms and deadlock, exactly like divergent T4J_NO_SHM.
  if (ring_min >= 0)
    g_ring_min_bytes.store(ring_min, std::memory_order_relaxed);
  if (seg >= 1) g_seg_bytes.store(seg, std::memory_order_relaxed);
}

void set_coalesce(long long bytes) {
  // bytes: < 0 keeps, 0 disables fusion, > 0 sets the combined-payload
  // threshold.  Must be uniform across ranks, like set_tuning: the two
  // sides of a fused exchange must agree on the part list.
  if (bytes >= 0)
    g_coalesce_bytes.store(bytes, std::memory_order_relaxed);
}

long long coalesce_threshold() { return coalesce_bytes(); }

void set_hier(int mode, long long min_bytes) {
  // mode: 0 auto, 1 on, 2 off (anything else keeps); min_bytes < 0
  // keeps.  Must be uniform across ranks, like set_tuning.
  if (mode >= kHierAuto && mode <= kHierOff)
    g_hier_mode.store(mode, std::memory_order_relaxed);
  if (min_bytes >= 0)
    g_leader_ring_min_bytes.store(min_bytes, std::memory_order_relaxed);
}

void set_resilience(int retry, double base_s, double max_s,
                    long long replay) {
  // retry: < 0 keeps, 0 disables self-healing (fail-stop, the PR-1
  // behaviour), > 0 sets the reconnect attempt cap.  base_s/max_s:
  // <= 0 keeps.  replay: < 0 keeps, >= 0 sets the per-peer replay-ring
  // byte cap.  Must be set before init (the ring and the reconnect
  // listener are wired at bootstrap) and uniformly across ranks.
  if (retry >= 0) g_retry_max.store(retry, std::memory_order_relaxed);
  if (base_s > 0) g_backoff_base_s.store(base_s, std::memory_order_relaxed);
  if (max_s > 0) g_backoff_max_s.store(max_s, std::memory_order_relaxed);
  if (replay >= 0) g_replay_bytes.store(replay, std::memory_order_relaxed);
}

void set_elastic(int mode, int min_world_v, double resize_timeout_s) {
  // mode: 0 off, 1 shrink, 2 rejoin (other values keep).  min_world:
  // >= 1 sets, else keeps.  resize_timeout_s: > 0 sets, else keeps.
  // Must be set before init (rejoin mode decides whether rank 0 keeps
  // the coordinator port open at bootstrap) and uniformly across
  // ranks; utils/config.py owns validation, including the rejection
  // of elastic + T4J_RETRY_MAX=0 (escalation — elastic's trigger — is
  // the self-healing ladder's last rung).
  if (mode >= kElasticOff && mode <= kElasticRejoin)
    g_elastic_mode.store(mode, std::memory_order_relaxed);
  if (min_world_v >= 1)
    g_min_world.store(min_world_v, std::memory_order_relaxed);
  if (resize_timeout_s > 0)
    g_resize_timeout_s.store(resize_timeout_s, std::memory_order_relaxed);
}

bool world_info(WorldInfo* out) {
  if (!out || !g_initialized) return false;
  out->epoch = g_world_epoch.load(std::memory_order_acquire);
  out->boot_size = g_size;
  out->alive_count = alive_count();
  out->alive_mask = g_alive_mask.load(std::memory_order_relaxed);
  out->resizing = g_resizing.load(std::memory_order_acquire);
  out->stale_frames = g_stale_frames.load(std::memory_order_relaxed);
  return true;
}

bool resize_wait(double timeout_s) {
  if (!g_resizing.load(std::memory_order_acquire)) return true;
  Deadline dl = Deadline::after(timeout_s);
  std::unique_lock<std::mutex> lk(g_resize.mu);
  while (g_resizing.load(std::memory_order_acquire)) {
    if (timeout_s <= 0 || dl.expired()) break;
    g_resize.cv.wait_for(lk, std::chrono::milliseconds(50));
  }
  return !g_resizing.load(std::memory_order_acquire);
}

bool link_stats(int peer, LinkStats* out) {
  if (!out || !g_initialized ||
      static_cast<int>(g_peers.size()) != g_size)
    return false;
  auto one = [](PeerLink& p, LinkStats* s) {
    // a LINK's counters are the sum over its stripes; its state is
    // derived stripe-wise — dead only when EVERY stripe is dead,
    // broken when any stripe is not up (docs/failure-semantics.md
    // "per-stripe replay and escalation")
    s->reconnects = 0;
    s->replayed_frames = 0;
    s->replayed_bytes = 0;
    s->tx_syscalls = 0;
    s->rx_syscalls = 0;
    int up = 0, dead = 0;
    for (int si = 0; si < p.nstripes; ++si) {
      Stripe& st = p.s[si];
      s->reconnects += st.reconnects.load(std::memory_order_relaxed);
      s->replayed_frames +=
          st.replayed_frames.load(std::memory_order_relaxed);
      s->replayed_bytes +=
          st.replayed_bytes.load(std::memory_order_relaxed);
      s->tx_syscalls += st.tx_syscalls.load(std::memory_order_relaxed);
      s->rx_syscalls += st.rx_syscalls.load(std::memory_order_relaxed);
      std::lock_guard<std::mutex> lk(st.mu);
      if (st.state == Stripe::kUp) ++up;
      else if (st.state == Stripe::kDead) ++dead;
    }
    if (p.nstripes > 0 && dead == p.nstripes) s->state = 2;
    else if (up == p.nstripes) s->state = 0;
    else s->state = 1;
  };
  if (peer < 0) {  // aggregate over every link
    LinkStats total{0, 0, 0, 0, 0, 0};
    for (int r = 0; r < g_size; ++r) {
      if (r == g_rank) continue;
      LinkStats s{0, 0, 0, 0, 0, 0};
      one(g_peers[r], &s);
      total.reconnects += s.reconnects;
      total.replayed_frames += s.replayed_frames;
      total.replayed_bytes += s.replayed_bytes;
      total.tx_syscalls += s.tx_syscalls;
      total.rx_syscalls += s.rx_syscalls;
      if (s.state > total.state) total.state = s.state;
    }
    *out = total;
    return true;
  }
  if (peer >= g_size || peer == g_rank) return false;
  one(g_peers[peer], out);
  return true;
}

bool link_stripe_stats(int peer, int stripe, LinkStats* out) {
  if (!out || !g_initialized ||
      static_cast<int>(g_peers.size()) != g_size)
    return false;
  if (peer < 0 || peer >= g_size || peer == g_rank) return false;
  PeerLink& p = g_peers[peer];
  if (stripe < 0 || stripe >= p.nstripes) return false;
  Stripe& st = p.s[stripe];
  out->reconnects = st.reconnects.load(std::memory_order_relaxed);
  out->replayed_frames =
      st.replayed_frames.load(std::memory_order_relaxed);
  out->replayed_bytes = st.replayed_bytes.load(std::memory_order_relaxed);
  out->tx_syscalls = st.tx_syscalls.load(std::memory_order_relaxed);
  out->rx_syscalls = st.rx_syscalls.load(std::memory_order_relaxed);
  std::lock_guard<std::mutex> lk(st.mu);
  out->state = static_cast<int>(st.state);
  return true;
}

void set_wire(int stripes, long long zc_min, int batch,
              long long emu_flow_bps_v) {
  // stripes: >= 1 sets the dealing width (clamped to the built width
  // after init and kMaxStripes always), <= 0 keeps — pre-init it also
  // fixes the number of connections bootstrap builds per link.
  // zc_min: < 0 keeps, 0 disables MSG_ZEROCOPY, > 0 sets the opt-in
  // floor.  batch: >= 1 sets the frames-per-sendmsg cap, <= 0 keeps.
  // emu_flow_bps: < 0 keeps, 0 disables, > 0 sets (bytes/second).
  // Must be uniform across ranks like the other data-plane knobs.
  if (stripes >= 1) {
    if (stripes > kMaxStripes) stripes = kMaxStripes;
    g_wire_stripes.store(stripes, std::memory_order_relaxed);
  }
  if (zc_min >= 0) g_zc_min_bytes.store(zc_min, std::memory_order_relaxed);
  if (batch >= 1) g_sendmsg_batch.store(batch, std::memory_order_relaxed);
  if (emu_flow_bps_v >= 0)
    g_emu_flow_bps.store(emu_flow_bps_v, std::memory_order_relaxed);
}

void wire_info(WireInfo* out) {
  if (!out) return;
  out->stripes_built = g_initialized ? g_built_stripes : requested_stripes();
  out->stripes_active = active_stripes();
  out->zc_min_bytes = zc_min_bytes();
  out->sendmsg_batch = sendmsg_batch();
  out->emu_flow_bps = emu_flow_bps();
  out->zerocopy = g_zc_supported && zc_min_bytes() > 0;
  out->zc_completions = g_zc_completions.load(std::memory_order_relaxed);
  out->zc_copied = g_zc_copied.load(std::memory_order_relaxed);
}

void set_wire_dtype(int mode) {
  // < 0 keeps (the "<0 keeps" convention of every set_* entry);
  // 0/1/2 = off/bf16/fp8.  Runtime-changeable like the dealing width:
  // the calibrator and the interleaved benchmark arms A/B it inside
  // one world.  utils/config.py owns env validation; out-of-range
  // values are clamped to off rather than trusted.
  if (mode < 0) return;
  if (mode > kWireFp8) mode = kWireOff;
  g_wire_dtype.store(mode, std::memory_order_relaxed);
}

void wire_dtype_info(int* mode, unsigned long long* logical_bytes,
                     unsigned long long* wire_bytes) {
  if (mode) *mode = wire_dtype_mode();
  if (logical_bytes)
    *logical_bytes = g_wire_logical_bytes.load(std::memory_order_relaxed);
  if (wire_bytes)
    *wire_bytes = g_wire_comp_bytes.load(std::memory_order_relaxed);
}

void set_wire_backend(int mode) {
  // < 0 keeps; 0/1/2 = sendmsg/uring/auto.  Out-of-range values are
  // clamped to auto rather than trusted (utils/config.py owns env
  // validation; the calibrator writes the fitted arm through here).
  // Runtime-changeable: the stripe send contexts are built lazily on
  // the first uring write and readers pick their path per connection,
  // so the interleaved benchmark arms can A/B it inside one world
  // (in-flight frames finish on the backend they started on — wire
  // bytes are identical either way).
  if (mode < 0) return;
  if (mode > kBackendAuto) mode = kBackendAuto;
  g_wire_backend.store(mode, std::memory_order_relaxed);
}

void wire_backend_info(int* mode, int* supported, int* active) {
  // Valid before init: the probe is one cheap io_uring_setup, cached.
  // Python's ensure_initialized uses `supported` to reject an
  // explicit T4J_WIRE_BACKEND=uring on kernels that cannot honour it.
  if (mode) *mode = wire_backend_mode();
  if (supported) *supported = uring_supported() ? 1 : 0;
  if (active) *active = uring_active() ? 1 : 0;
}

bool topology(TopoInfo* out) {
  if (!g_initialized || !out) return false;
  if (static_cast<int>(g_host_fps.size()) != g_size) {
    if (g_size != 1) return false;
    *out = TopoInfo{0, 0, 1, 0, 1};  // single-process job: trivial map
    return true;
  }
  std::vector<uint64_t> fps;
  TopoInfo t{-1, 0, 0, -1, 0};
  uint64_t mine = g_host_fps[g_rank];
  for (int r = 0; r < g_size; ++r) {
    if (!rank_alive(r)) continue;  // departed members leave the map
    uint64_t fp = g_host_fps[r];
    bool seen = false;
    for (uint64_t k : fps)
      if (k == fp) {
        seen = true;
        break;
      }
    if (!seen) {
      if (fp == mine) t.host_id = static_cast<int>(fps.size());
      fps.push_back(fp);
    }
    if (fp == mine) {
      if (t.leader_rank < 0) t.leader_rank = r;
      if (r < g_rank) ++t.local_rank;
      ++t.local_size;
    }
  }
  t.n_hosts = static_cast<int>(fps.size());
  *out = t;
  return true;
}

bool hier_would_select(int comm, size_t total_bytes) {
  Comm& c = get_comm(comm);
  if (!hier_mode_allows(total_bytes)) return false;
  {
    std::lock_guard<std::mutex> lk(g_comm_mu);
    if (c.hier_checked) return c.hier_ok;
  }
  // not yet negotiated: answer from the pure topology predicate on a
  // scratch copy (this query must never communicate or mutate)
  Comm probe;
  probe.ranks = c.ranks;
  probe.my_index = c.my_index;
  return compute_hier_topology(probe);
}

bool hier_active(int comm) {
  Comm& c = get_comm(comm);
  std::lock_guard<std::mutex> lk(g_comm_mu);
  return c.hier_checked && c.hier_ok;
}

void hier_allreduce(int comm, const void* in, void* out, size_t count,
                    DType dt, ReduceOp op) {
  if (async_route()) {
    run_on_engine(comm,
                  [&] { hier_allreduce(comm, in, out, count, dt, op); });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Allreduce_hier",
               "with " + std::to_string(count) + " items");
  if (!hier_setup(c))
    fail_arg(
        "hierarchical path unavailable (single-host communicator, no "
        "multi-rank host, T4J_NO_SHM, or the leaf arena negotiation "
        "failed)");
  tel::OpScope ts(tel::kHierAllreduce, comm, count * dtype_size(dt));
  ts.plane = tel::kPlaneHier;
  hier_allreduce_impl(c, in, out, count, dt, op);
}

// -- nonblocking ops (async progress engine; docs/async.md) ---------------
// Argument validation happens here on the caller's thread (fail_arg,
// no fault); transport failures during execution surface from
// wait/test after the usual fault posting.

uint64_t iallreduce(int comm, const void* in, void* out, size_t count,
                    DType dt, ReduceOp op) {
  get_comm(comm);  // validates the handle
  LogScope log("MPI_Iallreduce",
               "with " + std::to_string(count) + " items");
  auto a = std::make_shared<AsyncOp>();
  a->kind = AsyncOp::kAllreduce;
  a->comm = comm;
  a->in = in;
  a->out = out;
  a->count = count;
  a->dt = dt;
  a->rop = op;
  a->payload_bytes = count * dtype_size(dt);
  return async_submit(a);
}

uint64_t ireduce_scatter(int comm, const void* in, void* out,
                         size_t count_each, DType dt, ReduceOp op) {
  get_comm(comm);
  LogScope log("MPI_Ireduce_scatter",
               "with " + std::to_string(count_each) + " items per rank");
  auto a = std::make_shared<AsyncOp>();
  a->kind = AsyncOp::kReduceScatter;
  a->comm = comm;
  a->in = in;
  a->out = out;
  a->count = count_each;
  a->dt = dt;
  a->rop = op;
  a->payload_bytes = count_each * dtype_size(dt);
  return async_submit(a);
}

uint64_t isend(int comm, const void* buf, size_t nbytes, int dest,
               int tag) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Isend", "-> " + std::to_string(dest) + " with tag " +
                                std::to_string(tag) + " and " +
                                std::to_string(nbytes) + " bytes");
  if (dest < 0 || dest >= static_cast<int>(c.ranks.size()))
    fail_arg("destination rank " + std::to_string(dest) +
             " out of range for a " + std::to_string(c.ranks.size()) +
             "-member communicator");
  auto a = std::make_shared<AsyncOp>();
  a->kind = AsyncOp::kSend;
  a->comm = comm;
  a->in = buf;
  a->count = nbytes;
  a->peer = dest;
  a->tag = tag;
  a->payload_bytes = nbytes;
  return async_submit(a);
}

uint64_t irecv(int comm, void* buf, size_t nbytes, int source, int tag) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Irecv", "<- " + std::to_string(source) +
                                " with tag " + std::to_string(tag) +
                                " and " + std::to_string(nbytes) +
                                " bytes");
  if (source != kAnySource &&
      (source < 0 || source >= static_cast<int>(c.ranks.size())))
    fail_arg("source rank " + std::to_string(source) +
             " out of range for a " + std::to_string(c.ranks.size()) +
             "-member communicator");
  auto a = std::make_shared<AsyncOp>();
  a->kind = AsyncOp::kRecv;
  a->comm = comm;
  a->out = buf;
  a->count = nbytes;
  a->peer = source;
  a->tag = tag;
  a->payload_bytes = nbytes;
  a->wire_ctx = enc_ctx(c.ctx, /*coll=*/false);
  a->world_src = source == kAnySource ? kAnySource : c.ranks[source];
  return async_submit(a);
}

// Shared body of wait/wait_into: block until terminal, consume the
// handle, surface failures, fill the irecv envelope.  Returns the op
// so the owned-buffer path can copy the result out.
std::shared_ptr<AsyncOp> reap_request(uint64_t req, int* src_out,
                                      int* tag_out) {
  AsyncEngine& e = engine();
  std::shared_ptr<AsyncOp> op;
  {
    std::unique_lock<std::mutex> lk(e.mu);
    auto it = e.inflight.find(req);
    if (it == e.inflight.end()) {
      lk.unlock();
      throw BridgeError(
          err_prefix() + "MPI_Wait: request " + std::to_string(req) +
          " is unknown or already consumed (a request may be waited "
          "exactly once)");
    }
    op = it->second;
    // a waiter is now blocked on this request: the engine may arm the
    // parked-recv deadline (see AsyncOp::wait_requested)
    op->wait_requested.store(true, std::memory_order_release);
    // caller-side blocked bracket (telemetry.h kWait): the op body's
    // OpScope lands on the ENGINE lane, so this pair is the only
    // trace record of the CALLER sitting in a wait — blocking
    // collectives (submit + wait) included
    tel::trace_event(tel::kWait, tel::kBegin, tel::kPlaneNone,
                     async_evt_comm(*op), -1, op->payload_bytes);
    // the 100ms tick is a backstop only: completions notify done_cv,
    // and a wedged op faults within its own T4J_OP_TIMEOUT, draining
    // the queue and flipping this state
    while (op->state < AsyncOp::kDone)
      e.done_cv.wait_for(lk, std::chrono::milliseconds(100));
    tel::trace_event(tel::kWait, tel::kEnd, tel::kPlaneNone,
                     async_evt_comm(*op), -1, op->payload_bytes);
    e.inflight.erase(req);
  }
  if (op->state == AsyncOp::kFailed) throw BridgeError(op->error);
  if (op->kind == AsyncOp::kRecv) {
    if (src_out) *src_out = op->src_out;
    if (tag_out) *tag_out = op->tag_out;
  }
  return op;
}

void wait(uint64_t req, int* src_out, int* tag_out) {
  reap_request(req, src_out, tag_out);
}

bool test(uint64_t req, int* src_out, int* tag_out) {
  AsyncEngine& e = engine();
  std::shared_ptr<AsyncOp> op;
  {
    std::lock_guard<std::mutex> lk(e.mu);
    auto it = e.inflight.find(req);
    if (it == e.inflight.end())
      throw BridgeError(
          err_prefix() + "MPI_Test: request " + std::to_string(req) +
          " is unknown or already consumed (a request may be waited "
          "exactly once)");
    op = it->second;
    if (op->state < AsyncOp::kDone) return false;
    if (op->state == AsyncOp::kFailed) e.inflight.erase(req);
  }
  if (op->state == AsyncOp::kFailed) throw BridgeError(op->error);
  // complete: report done WITHOUT consuming — wait reaps the handle
  if (op->kind == AsyncOp::kRecv) {
    if (src_out) *src_out = op->src_out;
    if (tag_out) *tag_out = op->tag_out;
  }
  return true;
}

void waitall(const uint64_t* reqs, int n) {
  for (int i = 0; i < n; ++i) wait(reqs[i], nullptr, nullptr);
}

// -- owned-buffer variants (dcn.h: the XLA FFI submit handlers) -----------

uint64_t iallreduce_owned(int comm, const void* in, size_t count,
                          DType dt, ReduceOp op) {
  get_comm(comm);
  LogScope log("MPI_Iallreduce",
               "with " + std::to_string(count) + " items (owned)");
  size_t nbytes = count * dtype_size(dt);
  auto a = std::make_shared<AsyncOp>();
  a->kind = AsyncOp::kAllreduce;
  a->comm = comm;
  a->own_in.assign(static_cast<const uint8_t*>(in),
                   static_cast<const uint8_t*>(in) + nbytes);
  a->own_out.resize(nbytes);
  a->in = a->own_in.data();
  a->out = a->own_out.data();
  a->count = count;
  a->dt = dt;
  a->rop = op;
  a->payload_bytes = nbytes;
  return async_submit(a);
}

uint64_t ireduce_scatter_owned(int comm, const void* in,
                               size_t count_each, DType dt, ReduceOp op) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Ireduce_scatter",
               "with " + std::to_string(count_each) +
                   " items per rank (owned)");
  size_t block = count_each * dtype_size(dt);
  size_t in_bytes = block * c.ranks.size();
  auto a = std::make_shared<AsyncOp>();
  a->kind = AsyncOp::kReduceScatter;
  a->comm = comm;
  a->own_in.assign(static_cast<const uint8_t*>(in),
                   static_cast<const uint8_t*>(in) + in_bytes);
  a->own_out.resize(block);
  a->in = a->own_in.data();
  a->out = a->own_out.data();
  a->count = count_each;
  a->dt = dt;
  a->rop = op;
  a->payload_bytes = block;
  return async_submit(a);
}

uint64_t isend_owned(int comm, const void* buf, size_t nbytes, int dest,
                     int tag) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Isend", "-> " + std::to_string(dest) + " with tag " +
                                std::to_string(tag) + " and " +
                                std::to_string(nbytes) + " bytes (owned)");
  if (dest < 0 || dest >= static_cast<int>(c.ranks.size()))
    fail_arg("destination rank " + std::to_string(dest) +
             " out of range for a " + std::to_string(c.ranks.size()) +
             "-member communicator");
  auto a = std::make_shared<AsyncOp>();
  a->kind = AsyncOp::kSend;
  a->comm = comm;
  a->own_in.assign(static_cast<const uint8_t*>(buf),
                   static_cast<const uint8_t*>(buf) + nbytes);
  a->in = a->own_in.data();
  a->count = nbytes;
  a->peer = dest;
  a->tag = tag;
  a->payload_bytes = nbytes;
  return async_submit(a);
}

uint64_t irecv_owned(int comm, size_t nbytes, int source, int tag) {
  Comm& c = get_comm(comm);
  LogScope log("MPI_Irecv", "<- " + std::to_string(source) +
                                " with tag " + std::to_string(tag) +
                                " and " + std::to_string(nbytes) +
                                " bytes (owned)");
  if (source != kAnySource &&
      (source < 0 || source >= static_cast<int>(c.ranks.size())))
    fail_arg("source rank " + std::to_string(source) +
             " out of range for a " + std::to_string(c.ranks.size()) +
             "-member communicator");
  auto a = std::make_shared<AsyncOp>();
  a->kind = AsyncOp::kRecv;
  a->comm = comm;
  a->own_out.resize(nbytes);
  a->out = a->own_out.data();
  a->count = nbytes;
  a->peer = source;
  a->tag = tag;
  a->payload_bytes = nbytes;
  a->wire_ctx = enc_ctx(c.ctx, /*coll=*/false);
  a->world_src = source == kAnySource ? kAnySource : c.ranks[source];
  return async_submit(a);
}

void wait_into(uint64_t req, void* dst, size_t nbytes, int* src_out,
               int* tag_out) {
  std::shared_ptr<AsyncOp> op = reap_request(req, src_out, tag_out);
  if (op->kind == AsyncOp::kSend) return;  // no result payload
  if (nbytes != op->own_out.size())
    throw BridgeError(
        err_prefix() + "MPI_Wait: destination size " +
        std::to_string(nbytes) + " B does not match the request's " +
        "result size " + std::to_string(op->own_out.size()) +
        " B (wait_into requires an owned-buffer request; zero-copy "
        "requests return results in the caller's buffer)");
  if (nbytes) std::memcpy(dst, op->own_out.data(), nbytes);
}

int async_inflight() {
  return engine().depth.load(std::memory_order_relaxed);
}

int async_pending() {
  AsyncEngine& e = engine();
  std::lock_guard<std::mutex> lk(e.mu);
  return static_cast<int>(e.inflight.size());
}

bool faulted() { return g_faulted.load(std::memory_order_acquire); }

std::string fault_message() { return posted_fault_msg(); }

void abort_notify(const char* why) {
  if (!g_initialized) return;
  broadcast_abort(err_prefix() + (why ? why : "job aborted"));
}

void abort_job(int code, const char* why) {
  std::fprintf(stderr, "r%d | t4j abort: %s\n", g_rank, why);
  std::fflush(stderr);
  broadcast_abort(err_prefix() + "MPI_Abort: " + (why ? why : ""));
  _exit(code);
}

namespace detail {

bool stopped() { return g_stop.load(std::memory_order_acquire); }

[[noreturn]] void raise_stop() { raise_stopped(); }

double op_timeout_seconds() { return op_timeout(); }

[[noreturn]] void fail_op(const std::string& what) {
  t4j::fail_op(what);  // anon-namespace impl: broadcast + post + throw
}

}  // namespace detail

int init_from_env() {
  if (g_initialized) return 0;
  const char* rank_s = std::getenv("T4J_RANK");
  const char* size_s = std::getenv("T4J_SIZE");
  const char* coord_s = std::getenv("T4J_COORD");
  if (!rank_s || !size_s) return 1;  // not a multi-process job
  g_rank = std::atoi(rank_s);
  g_size = std::atoi(size_s);
  if (g_size < 1 || g_rank < 0 || g_rank >= g_size)
    throw BridgeError(err_prefix() + "invalid T4J_RANK=" +
                      std::string(rank_s) + " / T4J_SIZE=" +
                      std::string(size_s));
  // full bootstrap membership (elastic resizes flip bits later); a
  // rejoining replacement adopts the survivors' mask/epoch instead
  g_alive_mask.store(
      g_size >= 64 ? ~0ull : ((1ull << g_size) - 1),
      std::memory_order_relaxed);
  g_world_epoch.store(0, std::memory_order_relaxed);
  g_world_ctx = 0;
  // crash-consistent flight recorder (T4J_FLIGHT=on): map the event
  // ring + metrics table into a per-rank file NOW, while the process
  // is still single-threaded (the bootstrap below spawns the accept/
  // reader threads), so even bootstrap-phase control events land in
  // storage that survives a SIGKILL (docs/observability.md "flight
  // recorder")
  tel::flight_init(g_rank, g_size, 0);
  const char* rejoin_s = std::getenv("T4J_REJOIN");
  bool rejoining = rejoin_s && rejoin_s[0] &&
                   std::strcmp(rejoin_s, "0") != 0 &&
                   elastic_mode() == kElasticRejoin && g_rank != 0 &&
                   g_size > 1 && g_size <= 64;
  // Wire path (docs/performance.md "striped links and the zero-copy
  // path"), fixed while still single-threaded: the per-link connection
  // count bootstrap builds, and whether MSG_ZEROCOPY is usable at all.
  // An unsupported-kernel zerocopy request degrades LOUDLY to the copy
  // path instead of failing the job — the knob is a perf opt-in, not a
  // correctness contract.
  g_built_stripes = requested_stripes();
  if (zc_min_bytes() > 0) {
    g_zc_supported = probe_zerocopy_support();
    if (!g_zc_supported) {
      std::fprintf(stderr,
                   "r%d | t4j: T4J_ZEROCOPY_MIN_BYTES=%lld requested "
                   "but this kernel does not honour SO_ZEROCOPY — "
                   "degrading to the copy path "
                   "(docs/performance.md \"striped links and the "
                   "zero-copy path\")\n",
                   g_rank, zc_min_bytes());
      std::fflush(stderr);
      g_zc_min_bytes.store(0, std::memory_order_relaxed);
    }
  }
  // Wire backend (docs/performance.md "io_uring wire backend"):
  // resolve the request while single-threaded so the probe and the
  // loud no-io_uring degrade happen exactly once, before any reader
  // or sender thread consults uring_active().
  if (wire_backend_mode() == kBackendUring) {
    if (uring_active()) {
      // WRITE_FIXED on a socket has write(2) semantics — no
      // MSG_NOSIGNAL — so make a dead peer surface as EPIPE instead
      // of a process-killing SIGPIPE.  CPython already ignores
      // SIGPIPE; this covers bare embedders, and an installed
      // handler is respected.
      struct sigaction sa;
      std::memset(&sa, 0, sizeof(sa));
      if (::sigaction(SIGPIPE, nullptr, &sa) == 0 &&
          sa.sa_handler == SIG_DFL) {
        sa.sa_handler = SIG_IGN;
        (void)::sigaction(SIGPIPE, &sa, nullptr);
      }
    }
    // !uring_active(): the explicit-request degrade already printed
    // its one loud line inside uring_active()
  }
  parse_fault_plan();
  if (fault_armed(FaultPlan::kRefuse)) {
    // connect-failure injection: never join the bootstrap, so every
    // peer exercises its connect/accept deadline.  Park (bounded) so
    // the test harness can reap us, then exit distinctly.
    std::fprintf(stderr,
                 "r%d | t4j fault-injection: refusing to join the "
                 "bootstrap\n",
                 g_rank);
    std::fflush(stderr);
    std::this_thread::sleep_for(std::chrono::seconds(600));
    _exit(41);
  }
  // The native LogScope has its own switch, separate from the Python
  // layer's MPI4JAX_TPU_DEBUG: with both keyed to one var every MPI
  // call would log two begin/done pairs with different call ids.
  const char* dbg = std::getenv("MPI4JAX_TPU_NATIVE_DEBUG");
  if (dbg && dbg[0] && std::strcmp(dbg, "0") != 0) g_logging = true;

  // unique job id namespaces the shm segments (launcher sets T4J_JOB;
  // fall back to a sanitised coordinator address + uid)
  const char* job_s = std::getenv("T4J_JOB");
  if (job_s && job_s[0]) {
    g_job = job_s;
  } else {
    g_job = coord_s ? coord_s : "local";
    g_job += "_u" + std::to_string(::getuid());
  }
  for (auto& ch : g_job)
    if (!std::isalnum(static_cast<unsigned char>(ch))) ch = '_';
  if (g_job.size() > 80) g_job.resize(80);

  if (g_size > 1) {
    std::string coord = coord_s ? coord_s : "127.0.0.1:45677";
    auto colon = coord.rfind(':');
    if (colon == std::string::npos)
      throw BridgeError(err_prefix() + "bad T4J_COORD=" + coord +
                        " (want host:port)");
    std::string host = coord.substr(0, colon);
    uint16_t port = static_cast<uint16_t>(std::atoi(coord.c_str() + colon + 1));
    g_in_init.store(true, std::memory_order_relaxed);
    if (rejoining)
      rejoin_bootstrap(host, port);  // builds the world comm itself
    else
      bootstrap(host, port);
  }

  if (!rejoining) {
    std::lock_guard<std::mutex> lk(g_comm_mu);
    Comm world;
    for (int i = 0; i < g_size; ++i) world.ranks.push_back(i);
    world.ctx = 0;
    world.my_index = g_rank;
    g_comms.push_back(world);
  }
  g_initialized = true;
  // the join barrier absorbs rank startup skew, so it runs under the
  // connect deadline (g_in_init), not the per-op one
  barrier(0);
  // telemetry clock anchor, captured immediately after the join
  // barrier on every rank: the cross-rank trace merger treats the
  // anchors as (near-)simultaneous — barrier-exit skew is the
  // alignment error, not wall-clock skew (docs/observability.md
  // "clock alignment")
  tel::capture_anchor();
  // flight-recorder identity: the bootstrap incarnation token pairs
  // the file with the link-layer identity peers saw, and a rejoining
  // replacement adopts the survivors' epoch during rejoin_bootstrap
  tel::flight_set_token(g_my_boot_token);
  tel::flight_set_epoch(g_world_epoch.load(std::memory_order_relaxed));
  g_in_init.store(false, std::memory_order_relaxed);
  if (fault_armed(FaultPlan::kDieAfter)) {
    // time-based death, armed only after init: kills the rank even
    // when its data plane is frameless (shm arena), so tests can land
    // a deterministic mid-collective death on e.g. a non-leader local
    // rank of a hierarchical collective
    long ms = g_fault_plan.delay_ms;
    std::thread([ms] {
      std::this_thread::sleep_for(std::chrono::milliseconds(ms));
      std::fprintf(stderr,
                   "r%d | t4j fault-injection: dying %ld ms after init\n",
                   g_rank, ms);
      std::fflush(stderr);
      _exit(42);
    }).detach();
  }
  return 0;
}

void finalize() {
  if (!g_initialized) return;
  // let an in-progress elastic resize settle first: tearing the
  // transports down under the rebuild would race the resize thread
  (void)resize_wait(resize_timeout());
  g_finalizing.store(true, std::memory_order_release);
  // A leaked in-flight async request may still be executing on the
  // progress thread — let it finish (bounded by the connect deadline,
  // like the exit barrier: if every rank leaked the same collective it
  // completes normally) BEFORE the exit barrier, so the engine cannot
  // be mid-collective in the shm arena while the barrier (or the arena
  // teardown below) runs.  A wedged op falls through to the g_stop
  // break further down.
  if (!g_faulted.load(std::memory_order_acquire))
    quiesce_async_engine(connect_timeout());
  // After a fault there is nobody reliable to synchronise with: skip
  // the exit barrier (it would throw or hang) and go straight to
  // teardown.  A fault arriving DURING the barrier must not escape a
  // teardown path either.
  if (!g_faulted.load(std::memory_order_acquire)) {
    // like the join barrier, the exit barrier absorbs end-of-job rank
    // skew: bound it by the connect deadline, not a tight per-op one
    g_in_init.store(true, std::memory_order_relaxed);
    try {
      barrier(0);
    } catch (const BridgeError&) {
      // peer died while we were leaving: proceed with teardown
    }
    g_in_init.store(false, std::memory_order_relaxed);
  }
  g_shutting_down.store(true);
  g_stop.store(true);
  // wake every pipe waiter (readers blocked on empty, writers on full):
  // they observe the stop flag and exit
  {
    std::lock_guard<std::mutex> lk(g_pipe_pub_mu);
    if (g_my_pipes)
      for (int i = 0;; ++i) {
        shm::Pipe* p = shm::pipe_of(g_my_pipes, i);
        if (!p) break;
        shm::pipe_wake(p);
      }
    for (auto* tx : g_tx_pipes)
      if (tx) shm::pipe_wake(tx);
  }
  // async progress engine: g_stop is set, so a leaked running op
  // raises out of its blocking wait; the stop drains queued/parked
  // requests, joins the thread and reports never-waited leaks.  The
  // shm arenas are destroyed only AFTER the join — the engine may
  // have been mid-arena-collective until this point.
  stop_async_engine();
  {
    std::lock_guard<std::mutex> lk(g_comm_mu);
    for (auto& c : g_comms) {
      if (c.arena) shm::destroy(c.arena);
      c.arena = nullptr;
      c.arena_checked = true;
    }
  }
  g_pipe_readers.join_all();
  {
    std::lock_guard<std::mutex> lk(g_pipe_pub_mu);
    for (auto*& tx : g_tx_pipes) {
      if (tx) shm::pipe_close(tx);
      tx = nullptr;
    }
    if (g_my_pipes) {
      shm::pipes_destroy(g_my_pipes);
      g_my_pipes = nullptr;
    }
  }
  // the reconnect/coordinator acceptors observe the teardown flags
  // within their poll ticks
  g_accept_thread.join_all();
  if (g_listen_fd >= 0) {
    ::close(g_listen_fd);
    g_listen_fd = -1;
  }
  if (g_coord_listen_fd >= 0) {
    ::close(g_coord_listen_fd);
    g_coord_listen_fd = -1;
  }
  // shutdown first (wakes blocked readers with EOF/error), close only
  // after every reader has exited — closing a fd a thread is blocked on
  // is undefined behaviour and produced spurious EBADF aborts.  The
  // shutdown runs under send_mu so it cannot race a finish_repair
  // mid-swap: any repair that completes after this point re-checked
  // g_stop, and any that completed before left its fresh fd here to be
  // shut down.
  for (auto& p : g_peers)
    for (int si = 0; si < p.nstripes; ++si) {
      Stripe& st = p.s[si];
      {
        std::lock_guard<std::mutex> lk(st.send_mu);
        if (st.fd >= 0) ::shutdown(st.fd, SHUT_RDWR);
      }
      st.cv.notify_all();
      std::lock_guard<std::mutex> jk(st.join_mu);
      if (st.reader.joinable()) st.reader.join();
    }
  for (auto& p : g_peers)
    for (int si = 0; si < p.nstripes; ++si) {
      Stripe& st = p.s[si];
      // under send_mu: a straggling detached repair handler may still
      // read st.fd (its finish_repair bails on g_stop under this lock)
      std::lock_guard<std::mutex> lk(st.send_mu);
      if (st.fd >= 0) {
        ::close(st.fd);
        st.fd = -1;
      }
    }
  // flight recorder: mark the clean exit so a postmortem never
  // mistakes this rank's file for a hard death (the mapping itself
  // stays live — teardown-phase events keep landing in it)
  tel::flight_mark_finalized();
  g_initialized = false;
}

int comm_create(const int* world_ranks, int n, int ctx) {
  std::lock_guard<std::mutex> lk(g_comm_mu);
  Comm c;
  c.my_index = -1;
  for (int i = 0; i < n; ++i) {
    int r = world_ranks[i];
    if (r < 0 || r >= g_size) fail_arg("comm_create: world rank " + std::to_string(r) + " out of range [0, " + std::to_string(g_size) + ")");
    if (!rank_alive(r))
      fail_arg("comm_create: world rank " + std::to_string(r) +
               " is not a member of the current world (left at or "
               "before epoch " +
               std::to_string(g_world_epoch.load(
                   std::memory_order_relaxed)) +
               " — rebuild communicators over the resized world)");
    if (r == g_rank) c.my_index = i;
    c.ranks.push_back(r);
  }
  // ctx is supplied by the caller as a deterministic function of
  // (ranks, clone-generation) so every member derives the same channel
  // id regardless of local comm-creation order (per-process counters
  // would desynchronise under MPMD control flow)
  c.ctx = ctx;
  g_comms.push_back(c);
  return static_cast<int>(g_comms.size()) - 1;
}

int comm_rank(int comm) { return get_comm(comm).my_index; }
int comm_size(int comm) {
  return static_cast<int>(get_comm(comm).ranks.size());
}

void send(int comm, const void* buf, size_t nbytes, int dest, int tag) {
  if (async_route()) {
    wait(isend(comm, buf, nbytes, dest, tag), nullptr, nullptr);
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Send", "-> " + std::to_string(dest) + " with tag " +
                             std::to_string(tag) + " and " +
                             std::to_string(nbytes) + " bytes");
  if (dest < 0 || dest >= static_cast<int>(c.ranks.size()))
    fail_arg("destination rank " + std::to_string(dest) + " out of range for a " + std::to_string(c.ranks.size()) + "-member communicator");
  tel::OpScope ts(tel::kSend, comm, nbytes, c.ranks[dest]);
  csend(c, dest, tag, buf, nbytes, /*coll=*/false);
}

void recv(int comm, void* buf, size_t nbytes, int source, int tag,
          int* src_out, int* tag_out) {
  if (async_route()) {
    wait(irecv(comm, buf, nbytes, source, tag), src_out, tag_out);
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Recv", "<- " + std::to_string(source) + " with tag " +
                             std::to_string(tag) + " and " +
                             std::to_string(nbytes) + " bytes");
  if (source != kAnySource &&
      (source < 0 || source >= static_cast<int>(c.ranks.size())))
    fail_arg("source rank " + std::to_string(source) + " out of range for a " + std::to_string(c.ranks.size()) + "-member communicator");
  tel::OpScope ts(tel::kRecv, comm, nbytes,
                  source == kAnySource ? -1 : c.ranks[source]);
  Frame f = crecv(c, source, tag, /*coll=*/false);
  if (f.data.size() != nbytes) fail_size(f, nbytes);
  std::memcpy(buf, f.data.data(), nbytes);
  if (src_out) {
    *src_out = 0;
    for (size_t i = 0; i < c.ranks.size(); ++i)
      if (c.ranks[i] == f.src) *src_out = static_cast<int>(i);
  }
  if (tag_out) *tag_out = f.tag;
}

void sendrecv(int comm, const void* sendbuf, size_t send_nbytes,
              void* recvbuf, size_t recv_nbytes, int source, int dest,
              int sendtag, int recvtag, int* src_out, int* tag_out) {
  if (async_route()) {
    run_on_engine(comm, [&] {
      sendrecv(comm, sendbuf, send_nbytes, recvbuf, recv_nbytes, source,
               dest, sendtag, recvtag, src_out, tag_out);
    });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Sendrecv", "<- " + std::to_string(source) +
                                 " (tag " + std::to_string(recvtag) +
                                 ") / -> " + std::to_string(dest) +
                                 " (tag " + std::to_string(sendtag) + ")");
  tel::OpScope ts(
      tel::kSendrecv, comm, send_nbytes + recv_nbytes,
      dest >= 0 && dest < static_cast<int>(c.ranks.size())
          ? c.ranks[dest]
          : -1);
  // eager sends cannot block: send first, then receive (the pattern the
  // reference's deadlock test guards, test_send_and_recv.py:104-117).
  // Send and recv sizes are independent (MPI_Sendrecv semantics).
  csend(c, dest, sendtag, sendbuf, send_nbytes, /*coll=*/false);
  Frame f = crecv(c, source, recvtag, /*coll=*/false);
  if (f.data.size() != recv_nbytes) fail_size(f, recv_nbytes);
  std::memcpy(recvbuf, f.data.data(), recv_nbytes);
  if (src_out) {
    *src_out = 0;
    for (size_t i = 0; i < c.ranks.size(); ++i)
      if (c.ranks[i] == f.src) *src_out = static_cast<int>(i);
  }
  if (tag_out) *tag_out = f.tag;
}

void sendrecv_fused(int comm, const void* const* send_parts,
                    const size_t* send_nbytes, int n_send,
                    void* const* recv_parts, const size_t* recv_nbytes,
                    int n_recv, int source, int dest, int sendtag,
                    int recvtag, int* src_out, int* tag_out) {
  if (async_route()) {
    run_on_engine(comm, [&] {
      sendrecv_fused(comm, send_parts, send_nbytes, n_send, recv_parts,
                     recv_nbytes, n_recv, source, dest, sendtag, recvtag,
                     src_out, tag_out);
    });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Sendrecv_fused",
               "<- " + std::to_string(source) + " (" +
                   std::to_string(n_recv) + " parts, tag " +
                   std::to_string(recvtag) + ") / -> " +
                   std::to_string(dest) + " (" + std::to_string(n_send) +
                   " parts, tag " + std::to_string(sendtag) + ")");
  int n = static_cast<int>(c.ranks.size());
  if (n_send < 0 || n_recv < 0 || (n_send == 0 && n_recv == 0))
    fail_arg("fused sendrecv needs at least one send or recv part");
  if (n_send > 0 && (dest < 0 || dest >= n))
    fail_arg("destination rank " + std::to_string(dest) +
             " out of range for a " + std::to_string(n) +
             "-member communicator");
  if (n_recv > 0 && source != kAnySource && (source < 0 || source >= n))
    fail_arg("source rank " + std::to_string(source) +
             " out of range for a " + std::to_string(n) +
             "-member communicator");
  size_t total = 0;
  for (int i = 0; i < n_send; ++i) total += send_nbytes[i];
  for (int i = 0; i < n_recv; ++i) total += recv_nbytes[i];
  tel::OpScope ts(
      n_send == 0 ? tel::kRecv : (n_recv == 0 ? tel::kSend : tel::kSendrecv),
      comm, total,
      n_send > 0 ? c.ranks[dest]
                 : (source == kAnySource ? -1 : c.ranks[source]));
  // eager send-first order, exactly like sendrecv (a fused send cannot
  // block the matching fused receive)
  if (n_send > 0) {
    Buf payload = build_fused(send_parts, send_nbytes, n_send);
    csend(c, dest, sendtag, payload.data(), payload.size(),
          /*coll=*/false);
  }
  if (n_recv > 0) {
    Frame f = crecv(c, source, recvtag, /*coll=*/false);
    scatter_fused(f, recv_parts, recv_nbytes, n_recv);
    if (src_out) {
      *src_out = 0;
      for (size_t i = 0; i < c.ranks.size(); ++i)
        if (c.ranks[i] == f.src) *src_out = static_cast<int>(i);
    }
    if (tag_out) *tag_out = f.tag;
  }
}

void alltoall_fused(int comm, const void* const* parts, void* const* outs,
                    const size_t* nbytes_each, int nparts) {
  if (async_route()) {
    run_on_engine(comm, [&] {
      alltoall_fused(comm, parts, outs, nbytes_each, nparts);
    });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Alltoall_fused",
               std::to_string(nparts) + " parts per peer");
  if (nparts < 0) fail_arg("negative part count");
  if (nparts == 0) return;
  int n = static_cast<int>(c.ranks.size());
  int me = c.my_index;
  size_t per_peer = 0;
  for (int i = 0; i < nparts; ++i) per_peer += nbytes_each[i];
  tel::OpScope ts(tel::kAlltoall, comm,
                  per_peer * static_cast<size_t>(n));
  if (shm::Arena* a = comm_arena(c)) {
    // same-host arena: no wire frames exist to fuse — run the parts
    // through the arena individually (bit-identical by construction)
    ts.plane = tel::kPlaneShm;
    for (int i = 0; i < nparts; ++i)
      shm::alltoall(a, parts[i], outs[i], nbytes_each[i]);
    return;
  }
  ts.plane = tel::kPlaneTree;
  for (int i = 0; i < nparts; ++i) {
    std::memcpy(static_cast<uint8_t*>(outs[i]) + nbytes_each[i] * me,
                static_cast<const uint8_t*>(parts[i]) + nbytes_each[i] * me,
                nbytes_each[i]);
  }
  // staggered pairwise exchange (same schedule as alltoall), one fused
  // frame per peer instead of nparts frames
  std::vector<const void*> sp(nparts);
  std::vector<void*> rp(nparts);
  for (int off = 1; off < n; ++off) {
    int to = (me + off) % n;
    int from = ((me - off) % n + n) % n;
    for (int i = 0; i < nparts; ++i)
      sp[i] = static_cast<const uint8_t*>(parts[i]) + nbytes_each[i] * to;
    Buf payload = build_fused(sp.data(), nbytes_each, nparts);
    csend(c, to, kTagA2AFused, payload.data(), payload.size());
    Frame f = crecv(c, from, kTagA2AFused);
    for (int i = 0; i < nparts; ++i)
      rp[i] = static_cast<uint8_t*>(outs[i]) + nbytes_each[i] * from;
    scatter_fused(f, rp.data(), nbytes_each, nparts);
  }
}

void barrier(int comm) {
  if (async_route()) {
    run_on_engine(comm, [&] { barrier(comm); });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Barrier", "");
  int n = static_cast<int>(c.ranks.size());
  if (n == 1) return;
  tel::OpScope ts(tel::kBarrier, comm, 0);
  if (shm::Arena* a = comm_arena(c)) {
    ts.plane = tel::kPlaneShm;
    return shm::barrier(a);
  }
  ts.plane = tel::kPlaneTree;
  int me = c.my_index;
  // dissemination barrier
  for (int k = 1; k < n; k <<= 1) {
    uint8_t b = 1;
    csend(c, (me + k) % n, kCollTagBase + 1, &b, 1);
    crecv(c, ((me - k) % n + n) % n, kCollTagBase + 1);
  }
}

void bcast(int comm, void* buf, size_t nbytes, int root) {
  if (async_route()) {
    run_on_engine(comm, [&] { bcast(comm, buf, nbytes, root); });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Bcast", "-> " + std::to_string(root) + " with " +
                              std::to_string(nbytes) + " bytes");
  int n = static_cast<int>(c.ranks.size());
  if (n == 1) return;
  tel::OpScope ts(tel::kBcast, comm, nbytes,
                  root >= 0 && root < n ? c.ranks[root] : -1);
  if (shm::Arena* a = comm_arena(c)) {
    ts.plane = tel::kPlaneShm;
    return shm::bcast(a, buf, nbytes, root);
  }
  if (use_hier(c, nbytes)) {
    ts.plane = tel::kPlaneHier;
    return hier_bcast_impl(c, buf, nbytes, root);
  }
  ts.plane = tel::kPlaneTree;
  // binomial tree rooted at `root` (rotate indices so root -> 0)
  int me = (c.my_index - root % n + n) % n;
  for (int k = 1; k < n; k <<= 1) {
    if (me < k) {
      int partner = me + k;
      if (partner < n)
        csend(c, (partner + root) % n, kCollTagBase + 2, buf, nbytes);
    } else if (me < 2 * k) {
      Frame f = crecv(c, ((me - k) + root) % n, kCollTagBase + 2);
      if (f.data.size() != nbytes) fail_size(f, nbytes);
      std::memcpy(buf, f.data.data(), nbytes);
    }
  }
}

void reduce(int comm, const void* in, void* out, size_t count, DType dt,
            ReduceOp op, int root) {
  if (async_route()) {
    run_on_engine(comm,
                  [&] { reduce(comm, in, out, count, dt, op, root); });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Reduce", "-> " + std::to_string(root) + " with " +
                               std::to_string(count) + " items");
  int n = static_cast<int>(c.ranks.size());
  tel::OpScope ts(tel::kReduce, comm, count * dtype_size(dt),
                  root >= 0 && root < n ? c.ranks[root] : -1);
  if (shm::Arena* a = comm_arena(c)) {
    ts.plane = tel::kPlaneShm;
    return shm::reduce(a, in, out, count, dt, op, root);
  }
  if (use_hier(c, count * dtype_size(dt))) {
    ts.plane = tel::kPlaneHier;
    return hier_reduce_impl(c, in, out, count, dt, op, root);
  }
  ts.plane = tel::kPlaneTree;
  size_t nbytes = count * dtype_size(dt);
  std::vector<uint8_t> acc(static_cast<const uint8_t*>(in),
                           static_cast<const uint8_t*>(in) + nbytes);
  // binomial tree towards root (rotated)
  int me = (c.my_index - root % n + n) % n;
  int k = 1;
  while (k < n) k <<= 1;
  for (k >>= 1; k >= 1; k >>= 1) {
    if (me < k) {
      int partner = me + k;
      if (partner < n) {
        Frame f = crecv(c, (partner + root) % n, kCollTagBase + 3);
        if (f.data.size() != nbytes) fail_size(f, nbytes);
        combine(op, dt, f.data.data(), acc.data(), count);
      }
    } else if (me < 2 * k) {
      csend(c, ((me - k) + root) % n, kCollTagBase + 3, acc.data(), nbytes);
      break;
    }
  }
  if (c.my_index == root) std::memcpy(out, acc.data(), nbytes);
}

void allreduce(int comm, const void* in, void* out, size_t count, DType dt,
               ReduceOp op) {
  if (async_route()) {
    // blocking = submit + wait: one wire path through the progress
    // engine (docs/async.md); the engine re-enters here with the
    // routing disabled and runs the body below on its own thread
    wait(iallreduce(comm, in, out, count, dt, op), nullptr, nullptr);
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Allreduce", "with " + std::to_string(count) + " items");
  tel::OpScope ts(tel::kAllreduce, comm, count * dtype_size(dt));
  if (shm::Arena* a = comm_arena(c)) {
    ts.plane = tel::kPlaneShm;
    return shm::allreduce(a, in, out, count, dt, op);
  }
  size_t dsize = dtype_size(dt);
  size_t nbytes = count * dsize;
  if (use_hier(c, nbytes)) {
    ts.plane = tel::kPlaneHier;
    return hier_allreduce_impl(c, in, out, count, dt, op);
  }
  if (use_ring(c, nbytes)) {
    ts.plane = tel::kPlaneRing;
    // segmented ring reduce-scatter + ring allgather: each link
    // carries 2*(n-1)/n of the payload instead of the tree's full
    // payload per level.  The reduce-scatter writes this rank's block
    // of `out`; the allgather circulates the reduced blocks to fill
    // the rest — no whole-message staging copy.
    int n = static_cast<int>(c.ranks.size());
    BlockPart part(count, n);
    std::vector<size_t> off(n), len(n);
    for (int b = 0; b < n; ++b) {
      off[b] = part.off(b) * dsize;
      len[b] = part.len(b) * dsize;
    }
    const uint8_t* i8 = static_cast<const uint8_t*>(in);
    uint8_t* o8 = static_cast<uint8_t*>(out);
    // one verdict for BOTH phases: a compressed reduce-scatter with an
    // exact allgather (or vice versa) would be fine numerically, but
    // the knob's contract is "payload compressed on the wire" per
    // collective, and the counters/tests key on that
    int wdt = comm_wire_dtype(c, dt, op);
    ring_reduce_scatter(c, i8, o8 + off[c.my_index], off, len, dt, op,
                        wdt);
    ring_allgather(c, o8, off, len, wdt);
    return;
  }
  ts.plane = tel::kPlaneTree;
  reduce(comm, in, out, count, dt, op, 0);
  if (c.my_index != 0) std::memcpy(out, in, nbytes);  // placate valgrind
  bcast(comm, out, nbytes, 0);
}

void reduce_scatter(int comm, const void* in, void* out, size_t count_each,
                    DType dt, ReduceOp op) {
  if (async_route()) {
    wait(ireduce_scatter(comm, in, out, count_each, dt, op), nullptr,
         nullptr);
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Reduce_scatter",
               "with " + std::to_string(count_each) + " items per rank");
  int n = static_cast<int>(c.ranks.size());
  size_t dsize = dtype_size(dt);
  size_t block = count_each * dsize;
  if (n == 1) {
    if (block) std::memmove(out, in, block);
    return;
  }
  tel::OpScope ts(tel::kReduceScatter, comm, block * n);
  if (shm::Arena* a = comm_arena(c)) {
    // intra-host the arena moves memory, not wire bytes: one shm
    // allreduce then take this rank's block
    ts.plane = tel::kPlaneShm;
    Buf tmp(block * n);
    shm::allreduce(a, in, tmp.data(), count_each * n, dt, op);
    std::memcpy(out, tmp.data() + block * c.my_index, block);
    return;
  }
  if (use_hier(c, block * n)) {
    ts.plane = tel::kPlaneHier;
    return hier_reduce_scatter_impl(c, in, out, count_each, dt, op);
  }
  if (use_ring(c, block * n)) {
    ts.plane = tel::kPlaneRing;
    std::vector<size_t> off(n), len(n, block);
    for (int b = 0; b < n; ++b) off[b] = block * b;
    ring_reduce_scatter(c, static_cast<const uint8_t*>(in),
                        static_cast<uint8_t*>(out), off, len, dt, op,
                        comm_wire_dtype(c, dt, op));
    return;
  }
  // small messages: binomial reduce to member 0, scatter the blocks
  ts.plane = tel::kPlaneTree;
  Buf tmp(block * n);
  reduce(comm, in, tmp.data(), count_each * n, dt, op, 0);
  scatter(comm, tmp.data(), out, block, 0);
}

void scan(int comm, const void* in, void* out, size_t count, DType dt,
          ReduceOp op) {
  if (async_route()) {
    run_on_engine(comm, [&] { scan(comm, in, out, count, dt, op); });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Scan", "with " + std::to_string(count) + " items");
  tel::OpScope ts(tel::kScan, comm, count * dtype_size(dt));
  if (shm::Arena* a = comm_arena(c)) {
    ts.plane = tel::kPlaneShm;
    return shm::scan(a, in, out, count, dt, op);
  }
  ts.plane = tel::kPlaneTree;
  int n = static_cast<int>(c.ranks.size());
  size_t nbytes = count * dtype_size(dt);
  std::memcpy(out, in, nbytes);
  // linear inclusive prefix chain (MPI_Scan semantics)
  if (c.my_index > 0) {
    Frame f = crecv(c, c.my_index - 1, kCollTagBase + 4);
    if (f.data.size() != nbytes) fail_size(f, nbytes);
    combine(op, dt, in, f.data.data(), count);
    std::memcpy(out, f.data.data(), nbytes);
  }
  if (c.my_index + 1 < n) csend(c, c.my_index + 1, kCollTagBase + 4, out, nbytes);
}

void allgather(int comm, const void* in, void* out, size_t nbytes_each) {
  if (async_route()) {
    run_on_engine(comm, [&] { allgather(comm, in, out, nbytes_each); });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Allgather", "sending " + std::to_string(nbytes_each) +
                                  " bytes each");
  tel::OpScope ts(tel::kAllgather, comm,
                  nbytes_each * c.ranks.size());
  if (shm::Arena* a = comm_arena(c)) {
    ts.plane = tel::kPlaneShm;
    return shm::allgather(a, in, out, nbytes_each);
  }
  int n = static_cast<int>(c.ranks.size());
  if (use_hier(c, nbytes_each * n)) {
    ts.plane = tel::kPlaneHier;
    return hier_allgather_impl(c, in, out, nbytes_each);
  }
  if (use_ring(c, nbytes_each * n)) {
    ts.plane = tel::kPlaneRing;
    // ring allgather: every block travels once, (n-1)/n of the output
    // per link — vs the root-funnel gather+bcast's ~2*log2(n) copies
    uint8_t* o8 = static_cast<uint8_t*>(out);
    std::memcpy(o8 + nbytes_each * c.my_index, in, nbytes_each);
    std::vector<size_t> off(n), len(n, nbytes_each);
    for (int b = 0; b < n; ++b) off[b] = nbytes_each * b;
    ring_allgather(c, o8, off, len);
    return;
  }
  ts.plane = tel::kPlaneTree;
  gather(comm, in, out, nbytes_each, 0);
  bcast(comm, out, nbytes_each * c.ranks.size(), 0);
}

void gather(int comm, const void* in, void* out, size_t nbytes_each,
            int root) {
  if (async_route()) {
    run_on_engine(comm,
                  [&] { gather(comm, in, out, nbytes_each, root); });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Gather", "-> " + std::to_string(root) + " sending " +
                               std::to_string(nbytes_each) + " bytes each");
  tel::OpScope ts(
      tel::kGather, comm, nbytes_each * c.ranks.size(),
      root >= 0 && root < static_cast<int>(c.ranks.size())
          ? c.ranks[root]
          : -1);
  if (shm::Arena* a = comm_arena(c)) {
    ts.plane = tel::kPlaneShm;
    return shm::gather(a, in, out, nbytes_each, root);
  }
  ts.plane = tel::kPlaneTree;
  int n = static_cast<int>(c.ranks.size());
  // Per-instance tag (every member advances the counter in lockstep):
  // lets the root receive in ARRIVAL order below without a run-ahead
  // rank's next-gather frame matching this instance.
  int tag = kTagGatherSeqBase +
            static_cast<int>(c.gather_seq++ & 0xFFFFu);
  if (c.my_index == root) {
    uint8_t* o = static_cast<uint8_t*>(out);
    std::memcpy(o + nbytes_each * root, in, nbytes_each);
    // arrival order, not rank order: a slow peer no longer serialises
    // the root behind the untouched mailbox frames of the fast ones
    for (int k = 1; k < n; ++k) {
      Frame f = crecv(c, kAnySource, tag);
      if (f.data.size() != nbytes_each) fail_size(f, nbytes_each);
      int idx = -1;
      for (size_t i = 0; i < c.ranks.size(); ++i)
        if (c.ranks[i] == f.src) idx = static_cast<int>(i);
      if (idx < 0)
        fail_op("gather frame from non-member world rank r" +
                std::to_string(f.src));
      std::memcpy(o + nbytes_each * idx, f.data.data(), nbytes_each);
    }
  } else {
    csend(c, root, tag, in, nbytes_each);
  }
}

void scatter(int comm, const void* in, void* out, size_t nbytes_each,
             int root) {
  if (async_route()) {
    run_on_engine(comm,
                  [&] { scatter(comm, in, out, nbytes_each, root); });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Scatter", "-> " + std::to_string(root) + " sending " +
                                std::to_string(nbytes_each) + " bytes each");
  tel::OpScope ts(
      tel::kScatter, comm, nbytes_each * c.ranks.size(),
      root >= 0 && root < static_cast<int>(c.ranks.size())
          ? c.ranks[root]
          : -1);
  if (shm::Arena* a = comm_arena(c)) {
    ts.plane = tel::kPlaneShm;
    return shm::scatter(a, in, out, nbytes_each, root);
  }
  ts.plane = tel::kPlaneTree;
  int n = static_cast<int>(c.ranks.size());
  if (c.my_index == root) {
    const uint8_t* i8 = static_cast<const uint8_t*>(in);
    // interleaved non-blocking fan-out: all peers' frames progress
    // round-robin, so one slow peer cannot serialise the rest
    std::vector<RootSend> msgs;
    msgs.reserve(n - 1);
    for (int i = 0; i < n; ++i) {
      if (i == root) continue;
      msgs.push_back(RootSend{i, i8 + nbytes_each * i, nbytes_each});
    }
    multi_send(c, kCollTagBase + 6, msgs);
    std::memcpy(out, i8 + nbytes_each * root, nbytes_each);
  } else {
    Frame f = crecv(c, root, kCollTagBase + 6);
    if (f.data.size() != nbytes_each) fail_size(f, nbytes_each);
    std::memcpy(out, f.data.data(), nbytes_each);
  }
}

void alltoall(int comm, const void* in, void* out, size_t nbytes_each) {
  if (async_route()) {
    run_on_engine(comm, [&] { alltoall(comm, in, out, nbytes_each); });
    return;
  }
  Comm& c = get_comm(comm);
  LogScope log("MPI_Alltoall", "sending " + std::to_string(nbytes_each) +
                                 " bytes each");
  tel::OpScope ts(tel::kAlltoall, comm,
                  nbytes_each * c.ranks.size());
  if (shm::Arena* a = comm_arena(c)) {
    ts.plane = tel::kPlaneShm;
    return shm::alltoall(a, in, out, nbytes_each);
  }
  ts.plane = tel::kPlaneTree;
  int n = static_cast<int>(c.ranks.size());
  int me = c.my_index;
  const uint8_t* i8 = static_cast<const uint8_t*>(in);
  uint8_t* o8 = static_cast<uint8_t*>(out);
  std::memcpy(o8 + nbytes_each * me, i8 + nbytes_each * me, nbytes_each);
  // staggered pairwise exchange
  for (int off = 1; off < n; ++off) {
    int to = (me + off) % n;
    int from = ((me - off) % n + n) % n;
    csend(c, to, kCollTagBase + 7, i8 + nbytes_each * to, nbytes_each);
    Frame f = crecv(c, from, kCollTagBase + 7);
    if (f.data.size() != nbytes_each) fail_size(f, nbytes_each);
    std::memcpy(o8 + nbytes_each * from, f.data.data(), nbytes_each);
  }
}

}  // namespace t4j
