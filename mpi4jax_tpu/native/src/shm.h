// Same-host shared-memory collective arena (internal).
//
// The reference's intra-host data plane is libmpi's shared-memory BTL,
// which its bridge inherits for free (mpi_xla_bridge.pyx:149-167 just
// calls MPI_Allreduce).  This is the native equivalent for the DCN
// bridge: when every member of a communicator lives on one host, its
// collectives run through a POSIX shm segment — per-rank contribution
// slots plus a result buffer, synchronized with futex-backed monotone
// counters — instead of the TCP frame path.  Cross-host communicators
// keep the TCP algorithms (dcn.cc).
//
// Memory traffic per allreduce of S bytes over n ranks: n stage-in
// copies (n*S), one segment-parallel fold (each rank folds its 1/n of
// the result across all n slots: ~(n+1)*S read+write total), n
// copy-outs (n*S) — the minimum a one-copy-in/one-copy-out shm design
// can do.  On a multi-core host the per-rank copies and per-segment
// folds run concurrently; on a single core the total is the bound (see
// docs/performance.md "single-core ceiling").

#pragma once

#include <atomic>
#include <cstddef>

#include "dcn.h"

namespace t4j {
namespace shm {

struct Arena;  // opaque

// Two-phase setup, driven by dcn.cc's agreement protocol (the comm's
// member 0 creates and fully initialises the segment, THEN the others
// attach — orderd by TCP agreement rounds, so attachers never poll and
// a failed rank makes every member fall back to TCP together):
//   create: unlink any stale segment, create O_EXCL, init header.
//   attach: open the existing segment (no O_CREAT), validate.
// Either returns nullptr on failure (caller must then agree the whole
// comm onto the TCP path).  T4J_NO_SHM=1 disables shm entirely.
// `job` uniquely names the launcher job; `ctx` the comm.
Arena* create(const char* job, int ctx, int n);
Arena* attach(const char* job, int ctx, int n, int my_index);

bool disabled();  // T4J_NO_SHM / n-range gate shared with dcn.cc

// Remove the segment NAME once every member has attached (the mappings
// stay valid).  After this, no crash/abort path can leak the segment:
// the kernel frees the tmpfs pages when the last member's mapping dies
// with its process.
void unlink_name(Arena* a);

void destroy(Arena* a);  // munmap (+ unlink from the creator)

void allreduce(Arena* a, const void* in, void* out, size_t count, DType dt,
               ReduceOp op);
void reduce(Arena* a, const void* in, void* out, size_t count, DType dt,
            ReduceOp op, int root);

// Split-phase reduce for the hierarchical pipeline (dcn.cc): stage
// copies this member's contribution into its slot and returns without
// waiting for the fold; finish completes it (fold my segment; root
// additionally collects the result).  This is what lets the leaf fold
// of pipeline chunk k+1 run on the local ranks while their leader is
// still ringing chunk k over the wire.  Constraints: the payload must
// fit ONE arena piece (nbytes <= slot_bytes()), every member pairs
// the calls with the same arguments, and the staged/finish pairs
// interleave with other arena ops in the same order on every member.
uint64_t reduce_stage(Arena* a, const void* in, size_t nbytes);
void reduce_finish(Arena* a, uint64_t piece, void* out, size_t count,
                   DType dt, ReduceOp op, int root);

size_t slot_bytes();  // per-rank slot capacity (one piece's max size)
void scan(Arena* a, const void* in, void* out, size_t count, DType dt,
          ReduceOp op);
void bcast(Arena* a, void* buf, size_t nbytes, int root);
void allgather(Arena* a, const void* in, void* out, size_t nbytes_each);
void gather(Arena* a, const void* in, void* out, size_t nbytes_each,
            int root);
void scatter(Arena* a, const void* in, void* out, size_t nbytes_each,
             int root);
void alltoall(Arena* a, const void* in, void* out, size_t nbytes_each);
void barrier(Arena* a);

// ---- p2p byte pipes (same-host send/recv fast path) --------------------
//
// One SPSC blocking byte pipe per same-host ordered pair, living in a
// segment owned by the RECEIVER (one segment per process, a pipe slot
// per same-host source).  The dcn transport writes the exact TCP wire
// format (WireHeader + payload) into the pipe instead of the loopback
// socket; a reader thread per source drains into the same mailbox, so
// matching semantics and per-pair ordering are identical to TCP.

struct PipeSeg;  // receiver-owned segment (opaque)
struct Pipe;     // one directional pipe endpoint (opaque)

// Create my inbound segment with `n_sources` pipes (my_rank names it).
PipeSeg* pipes_create(const char* job, int my_rank, int n_sources);
// Receiver-side view of pipe `slot` in my own segment.
Pipe* pipe_of(PipeSeg* seg, int slot);
// Sender side: attach to `dest_rank`'s segment and take pipe `slot`.
// Called only after the agreement round confirmed the owner created
// and initialised the segment.  nullptr = fall back to TCP.
Pipe* pipe_attach(const char* job, int dest_rank, int slot, int n_sources);

// Blocking byte stream.  Returns false when `shutdown` became true
// while waiting (teardown); partial progress is fine then — the job is
// exiting.
bool pipe_write(Pipe* p, const void* data, size_t n,
                const std::atomic<bool>& shutdown);
bool pipe_read(Pipe* p, void* data, size_t n,
               const std::atomic<bool>& shutdown);

// Wake every waiter on the pipe (teardown: blocked readers/writers
// re-check `shutdown` and bail).
void pipe_wake(Pipe* p);

void pipes_unlink(PipeSeg* seg);   // drop the NAME once every sender attached
void pipes_destroy(PipeSeg* seg);  // munmap receiver view
void pipe_close(Pipe* p);          // munmap a sender's attached view

}  // namespace shm

namespace detail {
// dtype-dispatched pairwise combine (implemented in dcn.cc): acc op= a.
void combine(ReduceOp op, DType dt, const void* a, void* acc, size_t count);
}  // namespace detail

}  // namespace t4j
