// Same-host shared-memory collective arena (internal).
//
// The reference's intra-host data plane is libmpi's shared-memory BTL,
// which its bridge inherits for free (mpi_xla_bridge.pyx:149-167 just
// calls MPI_Allreduce).  This is the native equivalent for the DCN
// bridge: when every member of a communicator lives on one host, its
// collectives run through a POSIX shm segment — per-rank contribution
// slots plus a result buffer, synchronized with futex-backed monotone
// counters — instead of the TCP frame path.  Cross-host communicators
// keep the TCP algorithms (dcn.cc).
//
// Memory traffic per allreduce of S bytes over n ranks: n stage-in
// copies (n*S), one segment-parallel fold (each rank folds its 1/n of
// the result across all n slots: ~(n+1)*S read+write total), n
// copy-outs (n*S) — the minimum a one-copy-in/one-copy-out shm design
// can do.  On a multi-core host the per-rank copies and per-segment
// folds run concurrently; on a single core the total is the bound (see
// docs/performance.md "single-core ceiling").

#pragma once

#include <cstddef>

#include "dcn.h"

namespace t4j {
namespace shm {

struct Arena;  // opaque

// Two-phase setup, driven by dcn.cc's agreement protocol (the comm's
// member 0 creates and fully initialises the segment, THEN the others
// attach — orderd by TCP agreement rounds, so attachers never poll and
// a failed rank makes every member fall back to TCP together):
//   create: unlink any stale segment, create O_EXCL, init header.
//   attach: open the existing segment (no O_CREAT), validate.
// Either returns nullptr on failure (caller must then agree the whole
// comm onto the TCP path).  T4J_NO_SHM=1 disables shm entirely.
// `job` uniquely names the launcher job; `ctx` the comm.
Arena* create(const char* job, int ctx, int n);
Arena* attach(const char* job, int ctx, int n, int my_index);

bool disabled();  // T4J_NO_SHM / n-range gate shared with dcn.cc

// Remove the segment NAME once every member has attached (the mappings
// stay valid).  After this, no crash/abort path can leak the segment:
// the kernel frees the tmpfs pages when the last member's mapping dies
// with its process.
void unlink_name(Arena* a);

void destroy(Arena* a);  // munmap (+ unlink from the creator)

void allreduce(Arena* a, const void* in, void* out, size_t count, DType dt,
               ReduceOp op);
void reduce(Arena* a, const void* in, void* out, size_t count, DType dt,
            ReduceOp op, int root);
void scan(Arena* a, const void* in, void* out, size_t count, DType dt,
          ReduceOp op);
void bcast(Arena* a, void* buf, size_t nbytes, int root);
void allgather(Arena* a, const void* in, void* out, size_t nbytes_each);
void gather(Arena* a, const void* in, void* out, size_t nbytes_each,
            int root);
void scatter(Arena* a, const void* in, void* out, size_t nbytes_each,
             int root);
void alltoall(Arena* a, const void* in, void* out, size_t nbytes_each);
void barrier(Arena* a);

}  // namespace shm

namespace detail {
// dtype-dispatched pairwise combine (implemented in dcn.cc): acc op= a.
void combine(ReduceOp op, DType dt, const void* a, void* acc, size_t count);
}  // namespace detail

}  // namespace t4j
