"""Build the native DCN bridge shared library.

The reference compiles its Cython bridge with mpicc at pip-install time
(setup.py:75-86 custom_build_ext); here the C++ bridge is compiled with
g++ against the XLA FFI headers shipped inside jaxlib
(``jax.ffi.include_dir()``), cached by source mtime, on first use.

Also usable standalone:  python -m mpi4jax_tpu.native.build
"""

import pathlib
import subprocess
import sys

__all__ = ["lib_path", "ensure_built", "build"]

_SRC_DIR = pathlib.Path(__file__).resolve().parent / "src"
_OUT = pathlib.Path(__file__).resolve().parent / "_t4j_dcn.so"
_SOURCES = ["dcn.cc", "shm.cc", "ffi.cc"]


def lib_path():
    return _OUT


_HEADERS = ["dcn.h", "shm.h", "telemetry.h"]


def _sanitize_flags():
    """Opt-in sanitizer build: T4J_SANITIZE=address compiles the bridge
    under ASan so the fault-injection suite can double as a memory-
    safety harness locally, and T4J_SANITIZE=thread under TSan so the
    same suite exercises the bridge's progress/abort threads for data
    races (tools/ci_smoke.sh has a build leg for each).  Other values
    are passed through to -fsanitize verbatim (e.g. undefined)."""
    import os

    san = os.environ.get("T4J_SANITIZE", "").strip().lower()
    if not san:
        return []
    if san in ("address", "asan", "1"):
        san = "address"
    elif san in ("thread", "tsan"):
        san = "thread"
    return [f"-fsanitize={san}", "-fno-omit-frame-pointer", "-g"]


def _strict():
    """T4J_NATIVE_STRICT=1 promotes the bridge build to
    -Wall -Wextra -Werror and runs clang-tidy (bugprone-*,
    concurrency-*; .clang-tidy at the repo root) when the tool is
    installed.  Our sources must stay warning-clean; the jaxlib FFI
    headers are third-party and enter via -isystem so their warnings
    never gate our build."""
    import os

    from mpi4jax_tpu.utils.config import truthy

    return truthy(os.environ.get("T4J_NATIVE_STRICT"), default=False)


def _machine_key():
    """CPU-feature + build-mode fingerprint: the cached .so contains
    -march=native codegen, so a package dir shared across heterogeneous
    hosts (NFS conda env) must rebuild per machine instead of
    SIGILL-ing; toggling T4J_SANITIZE must rebuild too, or a cached
    plain .so would silently satisfy a sanitizer run."""
    import hashlib

    san = "|".join(_sanitize_flags())
    if _strict():
        san = f"{san}|strict" if san else "strict"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    key = hashlib.sha256(line.encode()).hexdigest()[:16]
                    return f"{key}|{san}" if san else key
    except OSError:
        pass
    import platform

    key = platform.machine()
    return f"{key}|{san}" if san else key


def _needs_build():
    if not _OUT.exists():
        return True
    key_file = _OUT.with_suffix(".buildinfo")
    try:
        if key_file.read_text().strip() != _machine_key():
            return True
    except OSError:
        return True
    out_mtime = _OUT.stat().st_mtime
    for s in _SOURCES + _HEADERS:
        if (_SRC_DIR / s).stat().st_mtime > out_mtime:
            return True
    return False


def _ffi_include_dir():
    """The XLA FFI headers inside the installed jaxlib.  jax>=0.7
    exposes them as jax.ffi; older lines (which cannot import the
    package but can still build/lint the bridge standalone) as
    jax.extend.ffi."""
    try:
        import jax.ffi as ffi
    except ImportError:
        from jax.extend import ffi
    return ffi.include_dir()


def build(verbose=False):
    import os

    include = _ffi_include_dir()
    tmp = _OUT.with_suffix(f".tmp{os.getpid()}.so")
    # compiler override mirrors the reference's MPI4JAX_BUILD_MPICC
    # (setup.py:78); CXX is the conventional spelling here
    cxx = os.environ.get("MPI4JAX_TPU_BUILD_CXX") or os.environ.get(
        "CXX", "g++"
    )
    strict = _strict()
    # the jaxlib FFI headers are third-party: -isystem keeps their
    # (numerous) -Wextra findings out of our warning surface, so the
    # strict gate measures only this repo's sources
    warn = ["-Wall", "-Wextra", "-Werror"] if strict else ["-Wall"]

    def cmd_for(extra):
        return [
            cxx,
            "-O3",
            *extra,
            *_sanitize_flags(),
            "-fPIC",
            "-shared",
            "-std=c++17",
            *warn,
            f"-isystem{include}",
            *[str(_SRC_DIR / s) for s in _SOURCES],
            "-o",
            str(tmp),
            "-lpthread",
            "-lrt",
        ]

    if strict:
        _run_clang_tidy(include)

    # -march=native vectorises the reduction combines (the shm arena's
    # fold is memory-bound only when SIMD keeps up); the library is
    # JIT-built per machine on first use, so native codegen is safe.
    # Fall back to portable flags if the toolchain rejects it.
    proc = None
    for extra in (["-march=native"], []):
        cmd = cmd_for(extra)
        if verbose:
            print(" ".join(cmd), file=sys.stderr)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode == 0:
            break
    if proc.returncode != 0:
        tmp.unlink(missing_ok=True)
        raise RuntimeError(
            f"native bridge build failed:\n{proc.stderr[-4000:]}"
        )
    os.replace(tmp, _OUT)  # atomic: concurrent loaders never see a torn .so
    _OUT.with_suffix(".buildinfo").write_text(_machine_key() + "\n")
    return _OUT


def _run_clang_tidy(include):
    """clang-tidy leg of the strict build (checks from the repo-root
    .clang-tidy: bugprone-*, concurrency-*, warnings-as-errors).  Skips
    with a note when clang-tidy is not installed — the strict *compile*
    still gates; containers with the full toolchain get both."""
    import os
    import shutil

    tidy = shutil.which(os.environ.get("T4J_CLANG_TIDY", "clang-tidy"))
    if tidy is None:
        print(
            "t4j strict build: clang-tidy not found, running the "
            "-Werror compile gate only",
            file=sys.stderr,
        )
        return
    cmd = [
        tidy,
        *[str(_SRC_DIR / s) for s in _SOURCES],
        "--warnings-as-errors=*",
        "--",
        "-std=c++17",
        f"-isystem{include}",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"clang-tidy failed (T4J_NATIVE_STRICT=1):\n"
            f"{(proc.stdout + proc.stderr)[-4000:]}"
        )


def ensure_built():
    if not _needs_build():
        return _OUT
    # N launcher children may hit a cold cache simultaneously; serialise
    # through a file lock so exactly one compiles and the rest reuse it
    import fcntl

    lock = _OUT.with_suffix(".lock")
    with open(lock, "w") as fh:
        fcntl.flock(fh, fcntl.LOCK_EX)
        try:
            if _needs_build():
                build()
        finally:
            fcntl.flock(fh, fcntl.LOCK_UN)
    return _OUT


if __name__ == "__main__":
    build(verbose=True)
    print(f"built {_OUT}")
