"""Build the native DCN bridge shared library.

The reference compiles its Cython bridge with mpicc at pip-install time
(setup.py:75-86 custom_build_ext); here the C++ bridge is compiled with
g++ against the XLA FFI headers shipped inside jaxlib
(``jax.ffi.include_dir()``), cached by source mtime, on first use.

Also usable standalone:  python -m mpi4jax_tpu.native.build
"""

import pathlib
import subprocess
import sys

__all__ = ["lib_path", "ensure_built", "build"]

_SRC_DIR = pathlib.Path(__file__).resolve().parent / "src"
_OUT = pathlib.Path(__file__).resolve().parent / "_t4j_dcn.so"
_SOURCES = ["dcn.cc", "ffi.cc"]


def lib_path():
    return _OUT


def _needs_build():
    if not _OUT.exists():
        return True
    out_mtime = _OUT.stat().st_mtime
    for s in _SOURCES + ["dcn.h"]:
        if (_SRC_DIR / s).stat().st_mtime > out_mtime:
            return True
    return False


def build(verbose=False):
    import os
    import jax.ffi

    include = jax.ffi.include_dir()
    tmp = _OUT.with_suffix(f".tmp{os.getpid()}.so")
    # compiler override mirrors the reference's MPI4JAX_BUILD_MPICC
    # (setup.py:78); CXX is the conventional spelling here
    cxx = os.environ.get("MPI4JAX_TPU_BUILD_CXX") or os.environ.get(
        "CXX", "g++"
    )
    cmd = [
        cxx,
        "-O2",
        "-fPIC",
        "-shared",
        "-std=c++17",
        "-Wall",
        f"-I{include}",
        *[str(_SRC_DIR / s) for s in _SOURCES],
        "-o",
        str(tmp),
        "-lpthread",
    ]
    if verbose:
        print(" ".join(cmd), file=sys.stderr)
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        tmp.unlink(missing_ok=True)
        raise RuntimeError(
            f"native bridge build failed:\n{proc.stderr[-4000:]}"
        )
    os.replace(tmp, _OUT)  # atomic: concurrent loaders never see a torn .so
    return _OUT


def ensure_built():
    if not _needs_build():
        return _OUT
    # N launcher children may hit a cold cache simultaneously; serialise
    # through a file lock so exactly one compiles and the rest reuse it
    import fcntl

    lock = _OUT.with_suffix(".lock")
    with open(lock, "w") as fh:
        fcntl.flock(fh, fcntl.LOCK_EX)
        try:
            if _needs_build():
                build()
        finally:
            fcntl.flock(fh, fcntl.LOCK_UN)
    return _OUT


if __name__ == "__main__":
    build(verbose=True)
    print(f"built {_OUT}")
