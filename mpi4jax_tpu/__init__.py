"""mpi4jax_tpu — TPU-native, jit-compatible MPI-style communication for JAX.

A ground-up redesign of the capabilities of mpi4jax (reference public API:
mpi4jax/__init__.py:9-38 — twelve token-threaded communication primitives
plus a capability probe) built TPU-first instead of wrapping CPU/CUDA MPI
in Cython:

* **mesh backend** (:class:`MeshComm`): ops called inside ``jax.shard_map``
  lower to XLA ICI collectives (``psum`` / ``ppermute`` / ``all_gather`` /
  ``all_to_all``) — jitted code never leaves HBM (the reference's GPU
  backend instead stages device→host→MPI→host→device,
  mpi_xla_bridge_gpu.pyx:211-251; that round trip does not exist here).
* **self backend** (:class:`SelfComm`): the single-process world, ops are
  local identities (the reference's behaviour with one MPI process).
* **proc backend** (:class:`ProcComm`): true multi-process MPMD over the
  native C++ DCN bridge (replaces mpi_xla_bridge_cpu.pyx).

Ordering is guaranteed by threading a :class:`Token` through every op,
preserving the reference's token discipline (docs/sharp-bits.rst:6-34)
via data dependence instead of side-effect annotations.
"""

import jax as _jax

from mpi4jax_tpu.utils.jax_compat import check_jax_version as _check_jax_version

_check_jax_version()

from mpi4jax_tpu.ops import (
    ANY_SOURCE,
    ANY_TAG,
    BAND,
    BOR,
    BXOR,
    LAND,
    LOR,
    LXOR,
    MAX,
    MIN,
    PROD,
    SUM,
    BucketedGradSync,
    Op,
    Request,
    Status,
    Token,
    allgather,
    allreduce,
    alltoall,
    alltoall_multi,
    annotate_step,
    as_token,
    barrier,
    assert_requests_drained,
    bcast,
    create_token,
    current_step,
    end_step,
    gather,
    iallreduce,
    ireduce_scatter,
    irecv,
    isend,
    recv,
    reduce,
    reduce_scatter,
    scan,
    scatter,
    send,
    sendrecv,
    sendrecv_multi,
    step_scope,
    test,
    token_array,
    wait,
    waitall,
)
from mpi4jax_tpu.native.runtime import WorldResized
from mpi4jax_tpu.parallel import (
    Comm,
    MeshComm,
    ProcComm,
    SelfComm,
    default_comm,
    get_default_comm,
    set_default_comm,
)

def __getattr__(name):
    # lazy: version resolution may shell out to git (checkout installs);
    # don't pay that — or import anything — at package-import time
    if name == "__version__":
        from mpi4jax_tpu._version import get_version

        version = get_version()
        globals()["__version__"] = version
        return version
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def has_tpu_support():
    """True if a TPU device backs the default JAX platform.

    Capability probe in the spirit of the reference's
    ``has_cuda_support()`` (mpi4jax/_src/utils.py:102-108).
    """
    try:
        return any(
            d.platform in ("tpu", "axon") for d in _jax.devices()
        )
    except RuntimeError:
        return False


def has_cuda_support():
    """True if a CUDA device backs the default JAX platform.

    Reference analog: ``mpi4jax.has_cuda_support()``
    (mpi4jax/_src/utils.py:102-108) — there it reports whether the CUDA
    XLA extension was *built*; here the staged (``io_callback``) native
    tier is platform-generic, so the question is simply whether CUDA
    devices are live: the same HBM↔host staging that serves TPU serves
    them (tests/proc/test_staged_backend.py::test_staged_ops_cuda).
    """
    try:
        if not any(d.platform == "gpu" for d in _jax.devices()):
            return False
        # 'gpu' covers ROCm too — require the backend to really be CUDA
        from jax.extend import backend as _jxb

        version = getattr(_jxb.get_backend(), "platform_version", "")
        return "cuda" in version.lower()
    except RuntimeError:
        return False


__all__ = [
    "ANY_SOURCE",
    "ANY_TAG",
    "BAND",
    "BOR",
    "BXOR",
    "BucketedGradSync",
    "Comm",
    "LAND",
    "LOR",
    "LXOR",
    "MAX",
    "MIN",
    "MeshComm",
    "Op",
    "PROD",
    "ProcComm",
    "Request",
    "SUM",
    "SelfComm",
    "Status",
    "Token",
    "WorldResized",
    "allgather",
    "allreduce",
    "alltoall",
    "alltoall_multi",
    "annotate_step",
    "assert_requests_drained",
    "as_token",
    "barrier",
    "bcast",
    "create_token",
    "current_step",
    "default_comm",
    "end_step",
    "gather",
    "get_default_comm",
    "has_cuda_support",
    "has_tpu_support",
    "iallreduce",
    "ireduce_scatter",
    "irecv",
    "isend",
    "recv",
    "reduce",
    "reduce_scatter",
    "scan",
    "scatter",
    "send",
    "sendrecv",
    "sendrecv_multi",
    "set_default_comm",
    "step_scope",
    "test",
    "token_array",
    "wait",
    "waitall",
]
