"""Version resolution (the reference vendors versioneer for
git-tag-derived versions, mpi4jax/_version.py + versioneer.py — SURVEY
§2.1 #28; this is the same capability in ~40 lines on modern tooling).

Resolution order:

1. ``git describe`` when running from a checkout (a ``.git`` exists
   next to the package) — tag-derived with commit distance and hash,
   versioneer-style: ``0.1.0+12.gabc1234``; checked *first* so a stale
   installed copy can't shadow the checkout's real version,
2. installed package metadata,
3. the static fallback (also what sdist-without-git builds get).
"""

import re
import subprocess
from pathlib import Path

def _fallback():
    """Static fallback, read from pyproject.toml when present (sdists
    carry it) so the release number lives in exactly one place."""
    pp = Path(__file__).resolve().parent.parent / "pyproject.toml"
    try:
        import tomllib

        with open(pp, "rb") as f:
            return tomllib.load(f)["project"]["version"]
    except Exception:
        pass
    try:  # Python 3.10: no tomllib — a one-key regex suffices here
        import re

        m = re.search(
            r'^version\s*=\s*"([^"]+)"', pp.read_text(), re.MULTILINE
        )
        if m:
            return m.group(1)
    except Exception:
        pass
    return "0.1.0"


_FALLBACK = _fallback()


def _munge_describe(desc):
    """git-describe output -> PEP 440 version string."""
    if desc.startswith("v"):
        desc = desc[1:]
    # pre-release tags (v0.1.0-rc1 / -a2 / -b3) become PEP 440
    # pre-release segments (0.1.0rc1) — NOT local versions
    # ('0.1.0+rc1' would sort *after* 0.1.0)
    desc = re.sub(
        r"^(\d[\d.]*)-(rc|a|b|alpha|beta)\.?(\d+)",
        lambda m: m.group(1)
        + {"alpha": "a", "beta": "b"}.get(m.group(2), m.group(2))
        + m.group(3),
        desc,
    )
    return desc.replace("-", "+", 1).replace("-", ".")


def get_version():
    root = Path(__file__).resolve().parent.parent
    try:
        if not (root / ".git").exists():
            raise OSError("not a checkout")

        def git(*args):
            out = subprocess.run(
                ["git", *args], cwd=root, capture_output=True, text=True,
                timeout=5,
            )
            return out.stdout.strip() if out.returncode == 0 else ""

        # only version-shaped tags (a stray non-version tag must not
        # leak into __version__ — versioneer's tag-prefix guard)
        desc = git("describe", "--tags", "--dirty", "--match", "v[0-9]*")
        if not desc:
            desc = git("describe", "--tags", "--dirty", "--match", "[0-9]*")
        if desc:
            return _munge_describe(desc)
        sha = git("rev-parse", "--short", "HEAD")
        if sha:
            return f"{_FALLBACK}+g{sha}"
    except Exception:
        pass
    try:
        from importlib.metadata import version

        return version("mpi4jax_tpu")
    except Exception:
        pass
    return _FALLBACK
