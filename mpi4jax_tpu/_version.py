"""Version resolution (the reference vendors versioneer for
git-tag-derived versions, mpi4jax/_version.py + versioneer.py — SURVEY
§2.1 #28; this is the same capability in ~40 lines on modern tooling).

Resolution order:

1. ``git describe`` when running from a checkout (a ``.git`` exists
   next to the package) — tag-derived with commit distance and hash,
   versioneer-style: ``0.1.0+12.gabc1234``; checked *first* so a stale
   installed copy can't shadow the checkout's real version,
2. installed package metadata,
3. the static fallback (also what sdist-without-git builds get).
"""

import subprocess
from pathlib import Path

_FALLBACK = "0.1.0"


def get_version():
    root = Path(__file__).resolve().parent.parent
    try:
        if not (root / ".git").exists():
            raise OSError("not a checkout")

        def git(*args):
            out = subprocess.run(
                ["git", *args], cwd=root, capture_output=True, text=True,
                timeout=5,
            )
            return out.stdout.strip() if out.returncode == 0 else ""

        desc = git("describe", "--tags", "--dirty")  # fails without tags
        if desc:
            if desc.startswith("v"):
                desc = desc[1:]
            return desc.replace("-", "+", 1).replace("-", ".")
        sha = git("rev-parse", "--short", "HEAD")
        if sha:
            return f"{_FALLBACK}+g{sha}"
    except Exception:
        pass
    try:
        from importlib.metadata import version

        return version("mpi4jax_tpu")
    except Exception:
        pass
    return _FALLBACK
