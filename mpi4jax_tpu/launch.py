"""Multi-process job launcher: the ``mpirun`` equivalent.

    python -m mpi4jax_tpu.launch -np 4 prog.py [args...]

Spawns N worker processes, wires the DCN-bridge bootstrap environment
(T4J_RANK / T4J_SIZE / T4J_COORD), initialises the native runtime in
each child before handing control to the user program, and propagates
the first nonzero exit (terminating the rest) — the fail-fast job
semantics of ``mpirun`` + the reference's MPI_Abort behaviour.

Children default to the CPU platform (one XLA CPU per process, the
reference's process model); override with ``--platform``.
"""

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def child_main(argv):
    """Entry for worker processes (internal)."""
    prog, *prog_args = argv
    platform = os.environ.get("T4J_PLATFORM")
    if platform and platform != "default":
        import jax

        jax.config.update("jax_platforms", platform)
    from mpi4jax_tpu.native import runtime

    runtime.ensure_initialized()
    sys.argv = [prog] + prog_args
    import runpy

    runpy.run_path(prog, run_name="__main__")


def main(argv=None):
    parser = argparse.ArgumentParser(prog="mpi4jax_tpu.launch")
    parser.add_argument("-np", "--nprocs", type=int, required=False)
    parser.add_argument(
        "--platform",
        default="cpu",
        help="jax platform to pin workers to (default: cpu). Pass "
        "'default' to leave the site/environment platform untouched — "
        "e.g. to run workers against a real accelerator.",
    )
    parser.add_argument(
        "--shims",
        action="store_true",
        help="prepend the mpi4py/mpi4jax import shims to the workers' "
        "PYTHONPATH (run unmodified reference programs)",
    )
    parser.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("prog", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.child:
        child_main(args.prog)
        return 0

    if not args.nprocs or not args.prog:
        parser.error("usage: python -m mpi4jax_tpu.launch -np N prog.py ...")

    n = args.nprocs
    coord = f"127.0.0.1:{_free_port()}"
    # unique job id: namespaces the bridge's same-host shm segments so
    # concurrent/successive jobs can never collide on stale segments
    import uuid

    job = uuid.uuid4().hex[:12]
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.update(
            T4J_RANK=str(rank),
            T4J_SIZE=str(n),
            T4J_COORD=coord,
            T4J_PLATFORM=args.platform,
            T4J_JOB=job,
        )
        if args.shims:
            from mpi4jax_tpu import shims

            env["PYTHONPATH"] = shims.path() + os.pathsep + env.get(
                "PYTHONPATH", ""
            )
        cmd = [
            sys.executable,
            "-m",
            "mpi4jax_tpu.launch",
            "--child",
            *args.prog,
        ]
        procs.append(subprocess.Popen(cmd, env=env))

    exit_code = 0
    try:
        remaining = set(range(n))
        while remaining:
            for i in list(remaining):
                rc = procs[i].poll()
                if rc is None:
                    continue
                remaining.discard(i)
                if rc != 0 and exit_code == 0:
                    exit_code = rc
                    # fail fast: take the rest of the job down
                    for j in remaining:
                        procs[j].terminate()
            if remaining:
                import time

                time.sleep(0.05)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        exit_code = 130
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
