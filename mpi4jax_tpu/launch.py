"""Multi-process job launcher: the ``mpirun`` equivalent.

    python -m mpi4jax_tpu.launch -np 4 prog.py [args...]

Spawns N worker processes, wires the DCN-bridge bootstrap environment
(T4J_RANK / T4J_SIZE / T4J_COORD), initialises the native runtime in
each child before handing control to the user program, and propagates
the first failure (terminating the rest) — the fail-fast job semantics
of ``mpirun`` + the reference's MPI_Abort behaviour.  The summary
names WHICH rank failed first and how (nonzero exit vs. signal kill),
and a dying child broadcasts an abort to its peers first so survivors
raise a contextual error instead of hanging until the kill
(docs/failure-semantics.md).

``--timeout SECONDS`` adds a whole-job deadline: past it the job is
torn down and the launcher exits 124, naming the ranks that were still
running (the likely hang participants).

``--restarts N`` adds bounded auto-relaunch: a job that exits nonzero
(other than Ctrl-C) is relaunched up to N more times with a fresh
coordinator port and job id, the attempt count and final status
reported per attempt.  This is the coarse-grained rung under the
transport's fine-grained self-healing (docs/failure-semantics.md):
pair it with ``utils/checkpoint.py`` so the relaunched job resumes at
the last saved step instead of from scratch.

``--telemetry DIR`` turns on comm telemetry for every rank
(``T4J_TELEMETRY=trace`` unless the environment already chose a mode,
docs/observability.md): each rank drains its native event ring +
metrics snapshot into ``DIR/rank<k>.t4j.json`` at exit — on the abort
path too, so a dying rank's last events reach the first-failure
report — and after the job the launcher merges the per-rank files into
one Perfetto-loadable ``DIR/job.trace.json`` with all ranks on one
aligned timeline.  Inspect with ``t4j-top DIR`` or load the merged
trace at https://ui.perfetto.dev.

It also arms the crash-consistent flight recorder (``T4J_FLIGHT=on``
into ``DIR`` unless the environment explicitly chose, docs/
observability.md "flight recorder"): each rank's event ring + metrics
table live in an mmap'd ``DIR/rank<k>-<boot>.t4jflight`` file, so a
rank killed by SIGKILL / segfault / OOM — which never runs any drain —
still leaves its last events on disk.  On a failed job the launcher
runs ``t4j-postmortem DIR`` and prints the verdict (first-failing
rank, its last in-flight op, the affected links, and how the death
ordered against any elastic resize) under the first-failure report.

Children default to the CPU platform (one XLA CPU per process, the
reference's process model); override with ``--platform``.
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _say(msg):
    print(f"mpi4jax_tpu.launch: {msg}", file=sys.stderr, flush=True)


def _swallow(fn):
    """Run ``fn`` ignoring every failure (best-effort side work, e.g.
    the exit-time metrics scrape — it must never take the job down)."""
    try:
        fn()
    except Exception:
        pass


def child_main(argv):
    """Entry for worker processes (internal)."""
    prog, *prog_args = argv
    platform = os.environ.get("T4J_PLATFORM")
    if platform and platform != "default":
        import jax

        jax.config.update("jax_platforms", platform)
    from mpi4jax_tpu.native import runtime

    runtime.ensure_initialized()
    sys.argv = [prog] + prog_args
    import runpy

    try:
        runpy.run_path(prog, run_name="__main__")
    except BaseException as e:
        # the MPI_Abort analog: tell peers this rank is going down so
        # their blocked collectives raise within their deadline instead
        # of hanging until the launcher's terminate
        code = e.code if isinstance(e, SystemExit) else None
        if not (isinstance(e, SystemExit) and code in (0, None)):
            why = (
                f"rank {os.environ.get('T4J_RANK', '?')} died: "
                f"{type(e).__name__}: {e}"
            )
            try:
                runtime.notify_abort(why)
            except Exception:
                pass
            # drain telemetry NOW (not only at atexit): a rank about to
            # be signal-killed by the launcher's teardown would lose
            # its ring, and the dying rank's last events are the most
            # valuable part of the first-failure report
            tel_dir = os.environ.get("T4J_TELEMETRY_DIR")
            if tel_dir:
                try:
                    from mpi4jax_tpu.telemetry import dump

                    dump.write_rank_file(tel_dir)
                except Exception:
                    pass
            # first-failure report: when the self-healing transport saw
            # action before the death, say so — a rank dying AFTER
            # surviving reconnects usually points at a flaky fabric
            try:
                stats = runtime.link_stats()
                if stats and stats["reconnects"]:
                    print(
                        f"r{os.environ.get('T4J_RANK', '?')} | t4j link "
                        f"stats at failure: {stats['reconnects']} "
                        f"reconnect(s), {stats['replayed_frames']} "
                        f"frame(s) / {stats['replayed_bytes']} bytes "
                        "replayed (docs/failure-semantics.md)",
                        file=sys.stderr,
                        flush=True,
                    )
            except Exception:
                pass
        raise


def _describe_exit(rc):
    """Human-readable child status: signal kills are reported
    distinctly from nonzero exits (satellite: fail-fast summary)."""
    if rc is not None and rc < 0:
        try:
            name = signal.Signals(-rc).name
        except ValueError:
            name = f"signal {-rc}"
        return f"killed by {name} (signal {-rc})"
    return f"exited with code {rc}"


def _job_exit_code(rc):
    """Normalise a child status into a valid launcher exit code:
    signal-killed children map to the shell convention 128+signum."""
    if rc is None:
        return 1
    if rc < 0:
        return 128 - rc  # rc = -signum
    return rc


def main(argv=None):
    parser = argparse.ArgumentParser(prog="mpi4jax_tpu.launch")
    parser.add_argument("-np", "--nprocs", type=int, required=False)
    parser.add_argument(
        "--platform",
        default="cpu",
        help="jax platform to pin workers to (default: cpu). Pass "
        "'default' to leave the site/environment platform untouched — "
        "e.g. to run workers against a real accelerator.",
    )
    parser.add_argument(
        "--shims",
        action="store_true",
        help="prepend the mpi4py/mpi4jax import shims to the workers' "
        "PYTHONPATH (run unmodified reference programs)",
    )
    parser.add_argument(
        "--timeout",
        type=float,
        default=None,
        metavar="SECONDS",
        help="whole-job deadline: past it every worker is torn down and "
        "the launcher exits 124, naming the ranks still running",
    )
    parser.add_argument(
        "--restarts",
        type=int,
        default=0,
        metavar="N",
        help="bounded auto-relaunch: a job exiting nonzero (other than "
        "Ctrl-C) is relaunched up to N more times with a fresh "
        "coordinator/job id — pair with utils/checkpoint.py so the "
        "relaunch resumes at the last saved step",
    )
    parser.add_argument(
        "--telemetry",
        default=None,
        metavar="DIR",
        help="comm telemetry (docs/observability.md): every rank "
        "drains its event ring into DIR/rank<k>.t4j.json at exit "
        "(T4J_TELEMETRY=trace unless the environment already set a "
        "mode), and the launcher merges them into a Perfetto-loadable "
        "DIR/job.trace.json; inspect with t4j-top DIR",
    )
    parser.add_argument(
        "--metrics",
        type=int,
        default=None,
        metavar="PORT",
        help="live metrics exporter (docs/observability.md): rank k "
        "serves its metrics snapshot + link stats on 127.0.0.1:PORT+k "
        "(/metrics Prometheus text, /metrics.json), and the launcher "
        "serves the aggregated job view — worst-link and straggler "
        "gauges — on PORT+nprocs",
    )
    parser.add_argument(
        "--elastic",
        choices=("shrink", "rejoin"),
        default=None,
        metavar="MODE",
        help="elastic world membership (docs/failure-semantics.md "
        "\"elastic membership\"): a dead rank no longer takes the job "
        "down — survivors agree on a reduced world and continue "
        "(shrink), and with MODE=rejoin the launcher relaunches ONLY "
        "the dead slot (T4J_REJOIN=1) so the replacement re-bootstraps "
        "into the mesh at the next epoch fence.  Sets T4J_ELASTIC for "
        "every rank; T4J_MIN_WORLD floors the shrink.  Composes with "
        "--restarts: the whole world restarts only when the job "
        "actually failed (e.g. it fell below T4J_MIN_WORLD).",
    )
    parser.add_argument(
        "--autoscale",
        action="store_true",
        help="traffic-driven elastic autoscaling (docs/serving.md "
        "\"Autoscaling\"): sets T4J_AUTOSCALE=on and a grow-request "
        "file (T4J_AUTOSCALE_REQ) for every rank.  The serving "
        "leader's policy posts grow requests to the file; the "
        "launcher answers by relaunching retired slots as "
        "T4J_REJOIN=1 expansion ranks through rank 0's kept-open "
        "coordinator port.  A follower exiting cleanly while the "
        "leader serves on is a scaledown (the in-band retire plan), "
        "recorded in the membership history, and its slot is reused "
        "by the next grow.  Requires --elastic rejoin.",
    )
    parser.add_argument(
        "--autotune",
        action="store_true",
        help="calibrate the data-plane knob vector at init "
        "(docs/performance.md \"trace-guided autotuning\"): every rank "
        "runs a few collective timing rounds, the fit is persisted in "
        "the topology-fingerprinted tuning cache (T4J_TUNING_CACHE) "
        "and applied to this job; later jobs on the same fabric load "
        "it automatically.  Explicit T4J_* knob env vars still win.",
    )
    parser.add_argument(
        "--serve",
        action="store_true",
        help="serving-job wiring (docs/serving.md): sets T4J_ADMIT=on "
        "for every rank (deadline-aware admission control with "
        "honest shed accounting) unless the environment explicitly "
        "chose, pair with --slo for the latency target.  The program "
        "is expected to run a mpi4jax_tpu.serving engine "
        "(benchmarks/serving.py is the reference loop).",
    )
    parser.add_argument(
        "--slo",
        type=float,
        default=None,
        metavar="MS",
        help="with --serve: per-request end-to-end latency SLO in "
        "milliseconds (T4J_SLO_MS for every rank; admission sheds "
        "predicted misses instead of blowing the p99)",
    )
    parser.add_argument(
        "--max-batch",
        type=int,
        default=None,
        metavar="N",
        help="with --serve: concurrent decode slots in the serving "
        "engine's KV pool (T4J_MAX_BATCH)",
    )
    parser.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("prog", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.child:
        child_main(args.prog)
        return 0

    if not args.nprocs or not args.prog:
        parser.error("usage: python -m mpi4jax_tpu.launch -np N prog.py ...")
    if args.timeout is not None and args.timeout <= 0:
        parser.error("--timeout must be > 0 seconds (omit it for no deadline)")
    if args.restarts < 0:
        parser.error("--restarts must be >= 0")
    if args.metrics is not None and not (
        1 <= args.metrics and args.metrics + args.nprocs < 65536
    ):
        parser.error(
            "--metrics PORT must leave room for nprocs+1 ports below "
            "65536"
        )
    if args.slo is not None and not args.serve:
        parser.error("--slo requires --serve (it sets the serving "
                     "engine's T4J_SLO_MS)")
    if args.max_batch is not None and not args.serve:
        parser.error("--max-batch requires --serve (it sets the "
                     "serving engine's T4J_MAX_BATCH)")
    if args.slo is not None and args.slo <= 0:
        parser.error("--slo must be > 0 milliseconds (omit it for no "
                     "SLO)")
    if args.autoscale and args.elastic != "rejoin":
        parser.error("--autoscale requires --elastic rejoin (a grow "
                     "admits replacement ranks through the kept-open "
                     "coordinator port)")

    attempts = args.restarts + 1
    for attempt in range(1, attempts + 1):
        exit_code = _run_job(args)
        if exit_code == 0 or exit_code == 130:
            break
        if attempt < attempts:
            _say(
                f"attempt {attempt}/{attempts} exited with code "
                f"{exit_code}; restarting the job "
                f"({attempts - attempt} restart(s) left)"
            )
        elif args.restarts:
            # without --restarts the launcher's failure output must
            # stay exactly the pre-restart-feature report
            _say(
                f"attempt {attempt}/{attempts} exited with code "
                f"{exit_code}; restart budget exhausted (--restarts "
                f"{args.restarts})"
            )
    if args.restarts and exit_code == 0 and attempt > 1:
        _say(f"job succeeded on attempt {attempt}/{attempts}")
    return exit_code


def _flight_dir(tel_dir):
    """Where the children actually wrote their flight files: spawn()
    lets an explicit ambient T4J_FLIGHT_DIR win over the telemetry
    dir, so the post-mortem readers must follow the same choice."""
    return os.environ.get("T4J_FLIGHT_DIR", "").strip() or tel_dir


def _telemetry_failure_report(tel_dir, rank):
    """Print the dying rank's last telemetry events under the
    first-failure line — the post-mortem shows WHAT the rank was
    doing, not just that it died.  Prefers the drained rank file (the
    abort path wrote it); a hard-killed rank never drained, so fall
    back to its crash-consistent flight-recorder file, whose mmap'd
    ring survived the kill (docs/observability.md "flight
    recorder")."""
    try:
        from mpi4jax_tpu.native.runtime import _format_recent_events
        from mpi4jax_tpu.telemetry import dump, schema

        path = os.path.join(tel_dir, dump.rank_file_name(rank))
        events = []
        source = "drained"
        if os.path.exists(path):
            obj = schema.load_rank_file(path)
            events = [schema.event_from_list(r)
                      for r in obj["events"][-8:]]
        else:
            fdir = _flight_dir(tel_dir)
            flights = sorted(
                f for f in os.listdir(fdir)
                if f.startswith(f"rank{rank}-")
                and f.endswith(".t4jflight")
            )
            if not flights:
                return
            obj = schema.read_flight_file(
                os.path.join(fdir, flights[-1]))
            events = obj["events"][-8:]
            source = "flight recorder"
        tail = _format_recent_events(events)
        if tail:
            _say(f"rank {rank} last telemetry events ({source}): {tail}")
    except Exception:
        pass  # the report must never mask the real failure


def _postmortem_report(tel_dir):
    """Run the cross-rank death analysis over the drained + flight
    files and print the verdict under the first-failure report: WHO
    failed first, its last in-flight op/step, the affected links, each
    peer's view, and the death-vs-resize ordering (t4j-postmortem's
    summary, docs/observability.md "flight recorder")."""
    try:
        from mpi4jax_tpu.telemetry import postmortem

        # stale_s=0: every child has been reaped by now, so a fresh
        # heartbeat only dates the death — it cannot mean "alive"
        fdir = _flight_dir(tel_dir)
        report = postmortem.analyze_dir(tel_dir, stale_s=0.0,
                                        flight_dir=fdir)
        for line in postmortem.summary_lines(report):
            _say(f"postmortem: {line}")
        extra = f" --flight-dir {fdir}" if fdir != tel_dir else ""
        _say(f"postmortem: full report: t4j-postmortem {tel_dir}{extra}")
    except Exception:
        pass  # best-effort: never mask the real failure


def _merge_telemetry(tel_dir, job):
    try:
        from mpi4jax_tpu.telemetry import trace

        out = trace.merge_dir(tel_dir, job=job)
        _say(
            f"telemetry merged into {out} (load in "
            "https://ui.perfetto.dev, or run: t4j-top "
            f"{tel_dir})"
        )
    except FileNotFoundError:
        _say(f"telemetry: no rank files appeared in {tel_dir}")
    except Exception as e:
        _say(f"telemetry merge failed: {type(e).__name__}: {e}")


def _start_job_metrics(port, n, job):
    """Serve the aggregated job metrics view on ``port + n``: each
    scrape of the job endpoint scrapes every rank's ``/metrics.json``
    (ranks that have not bootstrapped yet, or died, simply drop out of
    ``ranks_reporting``) and aggregates — no polling thread, the
    freshness is the scraper's.  Returns the exporter or None."""
    try:
        from mpi4jax_tpu.telemetry import exporter

        def collect():
            snaps = []
            for r in range(n):
                try:
                    snaps.append(exporter.scrape(
                        f"http://127.0.0.1:{port + r}/metrics.json",
                        timeout=0.5,
                    ))
                except Exception:
                    continue
            if not snaps:
                return None
            agg = exporter.aggregate_snapshots(snaps, job=job)
            # the exit-time summary runs after the rank endpoints are
            # gone: remember the freshest live view any scrape saw
            srv.last_agg = agg
            return agg

        srv = exporter.MetricsExporter(port + n, collect_fn=collect)
        srv.last_agg = None
        srv.start()
        _say(
            f"job metrics on http://127.0.0.1:{port + n}/metrics "
            f"(per-rank: ports {port}..{port + n - 1})"
        )
        return srv
    except Exception as e:  # noqa: BLE001 — metrics must not kill the launch
        _say(f"job metrics aggregator failed: {type(e).__name__}: {e}")
        return None


def _load_autoscale_module():
    """The pure scale-policy module holds the request-file protocol
    (serving/autoscale.py).  Importing it through the package trips
    the jax version gate on old-jax containers, so fall back to
    loading the file directly — it only needs the stdlib."""
    try:
        from mpi4jax_tpu.serving import autoscale

        return autoscale
    except Exception:
        import importlib.util

        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "serving", "autoscale.py",
        )
        spec = importlib.util.spec_from_file_location(
            "_t4j_launch_autoscale", path
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod


def _run_job(args):
    """One launch attempt: spawn the workers, wait, fail fast."""
    n = args.nprocs
    coord = f"127.0.0.1:{_free_port()}"
    # unique job id: namespaces the bridge's same-host shm segments so
    # concurrent/successive jobs can never collide on stale segments
    import uuid

    job = uuid.uuid4().hex[:12]
    tel_dir = None
    if args.telemetry:
        tel_dir = os.path.abspath(args.telemetry)
        os.makedirs(tel_dir, exist_ok=True)
    metrics_srv = None
    if args.metrics is not None:
        metrics_srv = _start_job_metrics(args.metrics, n, job)
    autoscale_api = None
    autoscale_req = None
    if args.autoscale:
        import tempfile

        autoscale_api = _load_autoscale_module()
        # per-job request file: the leader posts grow requests here
        # (T4J_AUTOSCALE_REQ), the poll loop below consumes them
        autoscale_req = os.path.join(
            tempfile.gettempdir(), f"t4j-scale-{job}.json"
        )
    def spawn(rank, rejoin=False):
        env = dict(os.environ)
        env.update(
            T4J_RANK=str(rank),
            T4J_SIZE=str(n),
            T4J_COORD=coord,
            T4J_PLATFORM=args.platform,
            T4J_JOB=job,
        )
        if args.elastic:
            env["T4J_ELASTIC"] = args.elastic
        if args.autoscale:
            env["T4J_AUTOSCALE"] = "on"
            env["T4J_AUTOSCALE_REQ"] = autoscale_req
        if rejoin:
            # replacement slot: re-bootstrap through rank 0's kept-open
            # coordinator port instead of the full-world rendezvous
            env["T4J_REJOIN"] = "1"
        if tel_dir:
            env["T4J_TELEMETRY_DIR"] = tel_dir
            # trace unless the caller already chose a mode (counters
            # keeps the overhead at metrics-only for perf runs)
            env.setdefault("T4J_TELEMETRY", "trace")
            # crash-consistent flight recorder: without it a
            # SIGKILL'd/segfaulted rank loses its entire ring — and
            # that is the rank every postmortem needs.  An explicit
            # ambient T4J_FLIGHT (off included) still wins.
            env.setdefault("T4J_FLIGHT", "on")
            env.setdefault("T4J_FLIGHT_DIR", tel_dir)
        if args.autotune:
            env["T4J_AUTOTUNE"] = "1"
        if args.serve:
            # serving wiring (docs/serving.md): admission on unless
            # the environment explicitly chose (off included — the
            # uncontrolled-baseline arm of the benchmarks)
            env.setdefault("T4J_ADMIT", "on")
            if args.slo is not None:
                env["T4J_SLO_MS"] = str(args.slo)
            if args.max_batch is not None:
                env["T4J_MAX_BATCH"] = str(args.max_batch)
        if args.metrics is not None:
            env["T4J_METRICS_PORT"] = str(args.metrics)
            # the exporter serves the metrics table + link stats —
            # counters mode records them at <=5% overhead; an explicit
            # ambient choice (off included) still wins
            env.setdefault("T4J_TELEMETRY", "counters")
        if args.shims:
            from mpi4jax_tpu import shims

            env["PYTHONPATH"] = shims.path() + os.pathsep + env.get(
                "PYTHONPATH", ""
            )
        cmd = [
            sys.executable,
            "-m",
            "mpi4jax_tpu.launch",
            "--child",
            *args.prog,
        ]
        return subprocess.Popen(cmd, env=env)

    procs = [spawn(rank) for rank in range(n)]

    exit_code = 0
    start = time.monotonic()
    terminated_at = None  # first terminate time, for SIGKILL escalation
    elastic = args.elastic
    # membership bookkeeping for the elastic summary: the launcher's
    # view of the epoch history (boot -> shrink -> rejoin -> ...),
    # printed next to the children's link-stats dumps at job end
    epoch_guess = 0
    members = n
    history = [f"boot({n})"]
    exited_ok = set()
    last_bad_rc = None
    relaunches = 0
    scaled_down = []  # slots the autoscaler retired; reused by grows
    last_scale_poll = 0.0

    try:
        remaining = set(range(n))
        final_scrape_started = False
        while remaining:
            for i in list(remaining):
                rc = procs[i].poll()
                if rc is None:
                    continue
                remaining.discard(i)
                if metrics_srv is not None and remaining \
                        and not final_scrape_started:
                    # first exit: the surviving ranks still serve —
                    # grab one job view (off-loop: the serial 0.5 s/
                    # rank scrape must not delay the fail-fast kill
                    # below) so the exit-time summary has data even
                    # when nothing external ever scraped
                    final_scrape_started = True
                    threading.Thread(
                        target=lambda: _swallow(metrics_srv.collect),
                        daemon=True,
                    ).start()
                if rc == 0:
                    exited_ok.add(i)
                    if (autoscale_req and i != 0 and 0 in remaining
                            and exit_code == 0
                            and terminated_at is None):
                        # a clean follower exit while the leader serves
                        # on is the autoscaler's in-band retire plan,
                        # not a fault: record the scaledown (the
                        # survivors' native layer is committing the
                        # smaller world right now) and keep the slot
                        # for a later grow
                        epoch_guess += 1
                        members -= 1
                        scaled_down.append(i)
                        history.append(
                            f"e{epoch_guess}:scaledown({members}) "
                            f"[rank {i} retired at "
                            f"+{time.monotonic() - start:.1f}s]"
                        )
                        _say(
                            f"rank {i} retired by the autoscaler — "
                            f"{members} rank(s) serving"
                        )
                    continue
                if elastic and exit_code == 0 and terminated_at is None:
                    # elastic membership: a dead rank is a shrink, not
                    # the job's end — the survivors' native layer is
                    # agreeing on the reduced world right now
                    last_bad_rc = rc
                    epoch_guess += 1
                    members -= 1
                    history.append(
                        f"e{epoch_guess}:shrink({members}) "
                        f"[rank {i} {_describe_exit(rc)} at "
                        f"+{time.monotonic() - start:.1f}s]"
                    )
                    _say(
                        f"rank {i} {_describe_exit(rc)} — elastic "
                        f"{args.elastic}: {len(remaining)} rank(s) "
                        "continue"
                    )
                    if tel_dir:
                        _telemetry_failure_report(tel_dir, i)
                    if (args.elastic == "rejoin" and i != 0
                            and relaunches < n):
                        # relaunch ONLY the dead slot; the replacement
                        # re-bootstraps via the incarnation handshake
                        # and joins at the next epoch fence.  (A dead
                        # rank 0 cannot rejoin — it owns the
                        # coordinator port — so its world stays
                        # shrunk.)
                        relaunches += 1
                        epoch_guess += 1
                        members += 1
                        history.append(
                            f"e{epoch_guess}:rejoin({members}) "
                            f"[rank {i} relaunched]"
                        )
                        _say(f"relaunching rank {i} as a rejoin "
                             f"replacement ({relaunches} so far)")
                        procs[i] = spawn(i, rejoin=True)
                        remaining.add(i)
                    continue
                if rc != 0 and exit_code == 0:
                    exit_code = _job_exit_code(rc)
                    # fail fast: take the rest of the job down, and say
                    # WHO failed first and HOW — the post-mortem anchor
                    _say(
                        f"rank {i} {_describe_exit(rc)} — first failure; "
                        f"terminating {len(remaining)} remaining rank(s)"
                    )
                    if tel_dir:
                        _telemetry_failure_report(tel_dir, i)
                    terminated_at = time.monotonic()
                    for j in remaining:
                        procs[j].terminate()
            if remaining:
                now = time.monotonic()
                if (autoscale_req and exit_code == 0
                        and terminated_at is None
                        and now - last_scale_poll > 0.5):
                    # answer the serving leader's grow requests: each
                    # retired slot relaunches as a T4J_REJOIN=1
                    # expansion rank (one epoch per admit).  Malformed
                    # or stale files are consumed and ignored —
                    # read_request never raises.
                    last_scale_poll = now
                    req = autoscale_api.read_request(autoscale_req)
                    if req is not None:
                        autoscale_api.clear_request(autoscale_req)
                        want = min(int(req["want_world"]), n)
                        scaled_down.sort()
                        while (members < want and scaled_down
                               and relaunches < 4 * n):
                            slot = scaled_down.pop(0)
                            relaunches += 1
                            epoch_guess += 1
                            members += 1
                            history.append(
                                f"e{epoch_guess}:grow({members}) "
                                f"[rank {slot} relaunched: "
                                f"{req['reason'] or 'grow request'}]"
                            )
                            _say(
                                f"autoscale grow to {want}: "
                                f"relaunching rank {slot} as an "
                                f"expansion rank ({members} serving)"
                            )
                            exited_ok.discard(slot)
                            procs[slot] = spawn(slot, rejoin=True)
                            remaining.add(slot)
                if (
                    args.timeout is not None
                    and exit_code == 0
                    and now - start > args.timeout
                ):
                    exit_code = 124
                    still = ", ".join(str(i) for i in sorted(remaining))
                    _say(
                        f"job deadline of {args.timeout:g}s exceeded; "
                        f"rank(s) {still} still running — terminating "
                        "the job"
                    )
                    terminated_at = now
                    for j in remaining:
                        procs[j].terminate()
                if terminated_at is not None and now - terminated_at > 10:
                    # a worker wedged in native code can ignore SIGTERM
                    # forever; escalate so the launcher itself cannot hang
                    for j in remaining:
                        procs[j].kill()
                time.sleep(0.05)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        exit_code = 130
    if elastic and exit_code == 0:
        # the job succeeded iff the final membership — every rank that
        # was not declared dead — finished cleanly and stayed at or
        # above the floor; below it, the nonzero code flows into
        # --restarts' whole-world relaunch
        try:
            from mpi4jax_tpu.utils import config as _config

            floor = _config.min_world()
        except Exception:
            # an unparsable floor already failed every child loudly at
            # ensure_initialized; the summary check degrades quietly
            floor = 1
        if len(exited_ok) < max(floor, 1):
            exit_code = _job_exit_code(last_bad_rc)
            _say(
                f"only {len(exited_ok)} rank(s) finished cleanly — "
                f"below T4J_MIN_WORLD={floor}; the elastic world did "
                "not survive"
            )
    if elastic and exit_code != 130:
        # the membership/epoch history, next to the children's
        # link-stats dumps: the post-mortem (or success report) shows
        # how the world evolved, not just how it ended
        final = sorted(exited_ok) if exited_ok else []
        _say("world membership history: " + " -> ".join(history))
        _say(
            f"final world membership: {len(final)}/{n} rank(s) "
            f"[{', '.join(str(r) for r in final)}] after "
            f"{epoch_guess} membership epoch(s)"
        )
    if metrics_srv is not None:
        # the workers have exited, so their endpoints are gone — a
        # fresh scrape can only come up empty; fall back to the
        # freshest live view any scrape cached so the job's final
        # straggler / worst-link line still lands in the launch log
        try:
            agg = metrics_srv.collect() or getattr(
                metrics_srv, "last_agg", None
            )
            if agg:
                worst = agg["worst_link"]
                where = (f" (rank {worst['rank']})"
                         if worst["rank"] is not None else "")
                _say(
                    f"job metrics final: {agg['ranks_reporting']} "
                    f"rank(s) reporting, straggler="
                    f"{agg['straggler'] if agg['straggler'] is not None else 'n/a'}, "
                    f"worst link reconnects={worst['reconnects']}"
                    + where
                )
        except Exception:
            pass
        metrics_srv.stop()
    if autoscale_req:
        # consume any request posted after the last poll: a leftover
        # file would leak into the temp dir (the job id namespaces it,
        # so a successor job can never mistake it for its own)
        autoscale_api.clear_request(autoscale_req)
    if tel_dir and exit_code != 130:
        # cross-rank death analysis from the drained + flight files:
        # on a failed job it names the first failure; on an elastic
        # job that shrank-and-survived it documents the departures
        # next to the membership history above
        if exit_code != 0 or (elastic and epoch_guess > 0):
            _postmortem_report(tel_dir)
        _merge_telemetry(tel_dir, job)
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
