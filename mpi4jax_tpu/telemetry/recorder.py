"""Python-level op recorder: begin/end events bracketing the native tier.

The native event ring sees wire frames and native op scopes; what it
cannot see is the Python-side span around them — io_callback staging,
numpy marshalling, ctypes dispatch.  The op layer (ops/_core.py trace
hook, ops/_proc.py staged-callback hook) brackets each op with
:func:`py_op`, and the drain (telemetry/dump.py) writes these rows next
to the native events so the merged timeline shows a ``python`` lane
above the native lanes per rank.

Events are (t_ns, op_name, phase, nbytes) with ``time.monotonic_ns``
timestamps — the same CLOCK_MONOTONIC the native steady_clock reads on
Linux, so the two lanes share a timebase and the native anchor aligns
both.  The buffer is bounded (oldest dropped first, counted) and
thread-safe; recording is a no-op unless T4J_TELEMETRY=trace.

Import-free of jax (stdlib only).
"""

import os
import threading
import time
from collections import deque
from contextlib import contextmanager

PHASE_INSTANT, PHASE_BEGIN, PHASE_END = 0, 1, 2

_MAX_EVENTS = 65536

_state = {
    "events": deque(maxlen=_MAX_EVENTS),
    "dropped": 0,
    "lock": threading.Lock(),
    "mode": None,  # resolved lazily; tests reset via _reset()
}


def _resolve_mode():
    """T4J_TELEMETRY via utils.config when importable (loud validation
    already happened at bridge init), raw env otherwise (standalone
    loads on old-jax containers must not import the package)."""
    try:
        from mpi4jax_tpu.utils import config

        return config.telemetry_mode()
    except Exception:
        v = os.environ.get("T4J_TELEMETRY", "").strip().lower()
        return v if v in ("counters", "trace") else "off"


def mode():
    m = _state["mode"]
    if m is None:
        m = _state["mode"] = _resolve_mode()
    return m


def tracing():
    """True when Python-level events should be recorded."""
    return mode() == "trace"


def _reset(mode=None):
    """Test hook: clear the buffer and pin (or re-resolve) the mode."""
    with _state["lock"]:
        _state["events"].clear()
        _state["dropped"] = 0
        _state["mode"] = mode


def set_mode(mode):
    """Pin the recorder's mode without touching recorded events —
    runtime.set_telemetry() calls this so a runtime override keeps
    the Python lane in lockstep with the native ring."""
    _state["mode"] = str(mode)


def record(op, phase, nbytes=0, t_ns=None):
    if not tracing():
        return
    if t_ns is None:
        t_ns = time.monotonic_ns()
    with _state["lock"]:
        q = _state["events"]
        if len(q) == q.maxlen:
            _state["dropped"] += 1
        q.append((int(t_ns), str(op), int(phase), int(nbytes)))


@contextmanager
def py_op(op, nbytes=0):
    """Bracket one op invocation with begin/end events (no-op unless
    trace mode is on)."""
    if not tracing():
        yield
        return
    record(op, PHASE_BEGIN, nbytes)
    try:
        yield
    finally:
        record(op, PHASE_END, nbytes)


def drain():
    """Consume and return every recorded row ([t_ns, op, phase,
    nbytes], oldest first)."""
    with _state["lock"]:
        rows = [list(r) for r in _state["events"]]
        _state["events"].clear()
        return rows


def dropped():
    with _state["lock"]:
        return _state["dropped"]
