"""``t4j-diagnose``: cross-rank per-step performance diagnosis.

    t4j-diagnose DIR                  # a --telemetry dir of rank files
    t4j-diagnose DIR/job.trace.json   # or the merged Perfetto trace
    t4j-diagnose DIR --json           # machine-readable report
    t4j-diagnose DIR --diff base.json # A/B against a saved --json run

The interpretation layer over the raw telemetry substrate
(docs/observability.md "diagnosing a slow step"): where ``t4j-top``
totals what happened, this answers *why a step was slow and who made
it so*.  Anchored on the step markers every rank emits
(:mod:`mpi4jax_tpu.ops.step` -> native event kind 60), it reconstructs,
per step and per rank, the phase decomposition

* **compute** — wall time outside every comm bracket (the caller was
  doing its own work),
* **blocked** — wall time the CALLER sat inside a comm bracket:
  native ``wait`` brackets (kind 53, emitted on the caller's lane
  around every blocking wait — routed blocking collectives included),
  op scopes on non-engine lanes, and python-lane spans.  Op scopes on
  the ENGINE lane are the op bodies executing on the progress thread
  and are deliberately NOT caller-blocked time,
* **wire** — progress-engine execution time (``op_complete`` events
  carry the duration; for a blocking job wire ⊆ blocked),
* **stall** — link repair time (``link_break``→``reconnect``) and
  replay events inside the step,

and from the cross-rank comparison derives:

* the step's **critical rank** (straggler) and which phase bounds it —
  late entry / excess compute, wire pacing (outbound frame gaps, the
  slow-NIC / injected-delay signature), or link stalls;
* per-rank straggler tallies and an entry-skew histogram;
* the **measured per-step overlap ratio** — the share of engine wire
  time NOT covered by a caller blocked in a comm bracket (replacing
  t4j-top's rank-global estimate; docs/async.md "overlap caveats");
* **per-link wait-cause attribution**: outbound-frame pacing gaps and
  self-healing repair/replay events tied to the ops they stalled;
* a **plane-choice audit**: bytes served by the tree plane at sizes
  where the ring (or hierarchical) plane would have been selected.

Jobs without step markers are analysed as ONE step spanning the whole
trace, so the tool still works on pre-marker recordings — per-step
attribution just degrades to per-job.

Import-free of jax (stdlib only), like the rest of this package; the
console-script twin of ``t4j-top`` (pyproject.toml).
"""

import argparse
import json
import pathlib
import sys

from . import schema
from .trace import MERGED_NAME, RANK_FILE_GLOB

DIAG_SCHEMA = "t4j-diagnose-v1"

DEFAULT_RING_MIN_BYTES = 256 << 10         # dcn.cc kDefaultRingMinBytes
DEFAULT_LEADER_RING_MIN_BYTES = 256 << 10  # kDefaultLeaderRingMinBytes
DEFAULT_STALL_GAP_MS = 5.0

# a rank is only called the straggler when its excess over the median
# exceeds this share of the step's job-level duration — below it the
# step is reported balanced instead of blaming noise
BALANCED_FRACTION = 0.10

# entry-skew histogram bucket upper bounds, in ms (last = overflow)
SKEW_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, float("inf"))

# collectives with a size-based plane switchover (the plane audit set)
_SWITCHED_OPS = frozenset(
    schema.KIND_IDS[k] for k in ("allreduce", "reduce_scatter",
                                 "allgather")
)


def parse_bytes(value, name="value"):
    """``256K``/``4M``-style byte counts (the T4J_* knob syntax)."""
    s = str(value).strip()
    mult = 1
    if s and s[-1] in "kKmMgG":
        mult = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}[s[-1].lower()]
        s = s[:-1]
    try:
        return int(s) * mult
    except ValueError:
        raise ValueError(
            f"cannot interpret {name}={value!r} as a byte count"
        ) from None


# ---- interval arithmetic -------------------------------------------------


def _union(intervals):
    """Sorted, merged copy of ``[(lo, hi), ...]``."""
    out = []
    for lo, hi in sorted(i for i in intervals if i[1] > i[0]):
        if out and lo <= out[-1][1]:
            if hi > out[-1][1]:
                out[-1] = (out[-1][0], hi)
        else:
            out.append((lo, hi))
    return out

def _clip(intervals, lo, hi):
    return [(max(a, lo), min(b, hi)) for a, b in intervals
            if min(b, hi) > max(a, lo)]


def _total(intervals):
    return sum(b - a for a, b in intervals)


def _overlap(a, b):
    """Total length of the intersection of two merged interval lists."""
    out = 0
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            out += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def _median(values):
    s = sorted(values)
    if not s:
        return 0.0
    n = len(s)
    return (s[(n - 1) // 2] + s[n // 2]) / 2


# ---- per-rank extraction -------------------------------------------------


class RankView:
    """One rank's telemetry re-expressed on the job-relative timeline
    (ns since the rank's bootstrap anchor), pre-digested for per-step
    slicing."""

    def __init__(self, rank):
        self.rank = rank
        self.steps = {}        # index -> [t0, t1 | None]
        self.step_names = {}   # index -> name
        self.op_spans = []     # (t0, t1, kind, plane, bytes, lane)
        self.py_spans = []     # (t0, t1, name)
        self.wait_spans = []   # (t0, t1) caller-lane wait brackets
        self.engine_busy = []  # (t0, t1) from op_complete
        self.engine_lanes = set()  # lanes carrying engine lifecycle
        self.frame_tx = {}     # peer -> [t, ...]
        self.frame_rx = {}     # peer -> [t, ...]
        self.ctrl = []         # (t, kind_name, peer, stripe-or-None)
        self.step_problems = []
        self.last_t = 0
        self.link_stats = {}
        self.topology = {}
        self.tuning = {}

    @property
    def blocked_spans(self):
        """Merged union of every CALLER-side comm bracket: native wait
        brackets (kind 53 — blocking collectives route submit + wait,
        so these cover them too), op scopes on non-engine lanes (the
        pre-engine caller-thread path, e.g. p2p), and python-lane
        spans (async submit/wait whose native scope is negligible).
        Op scopes on an engine lane are the bodies executing on the
        progress thread — wire time, not caller-blocked time."""
        return _union(
            [(a, b) for a, b, _k, _p, _n, lane in self.op_spans
             if lane not in self.engine_lanes]
            + list(self.wait_spans)
            + [(a, b) for a, b, _n in self.py_spans]
        )

    def finish(self):
        """Close truncated structures at the rank's last seen event."""
        for idx, span in self.steps.items():
            if span[1] is None:
                span[1] = max(self.last_t, span[0])
                span.append(True)   # truncated marker
            elif len(span) == 2:
                span.append(False)
        self.op_spans.sort()
        self.py_spans.sort()
        self.wait_spans.sort()
        self.engine_busy = _union(self.engine_busy)
        for ts in self.frame_tx.values():
            ts.sort()
        for ts in self.frame_rx.values():
            ts.sort()
        # key excludes the stripe: None (unstriped) and int stripes
        # may share a timestamp and must not be compared to each other
        self.ctrl.sort(key=lambda c: (c[0], c[1], c[2]))


def rank_view_from_obj(obj):
    """Build a :class:`RankView` from a validated per-rank telemetry
    file (the primary input path)."""
    view = RankView(int(obj["rank"]))
    anchor = int(obj["anchor"]["mono_ns"])
    view.link_stats = obj.get("link_stats") or {}
    view.topology = obj.get("topology") or {}
    view.tuning = obj.get("tuning") or {}
    events = [schema.event_from_list(r) for r in obj["events"]]
    view.step_problems = schema.check_step_balance(events)
    op_stacks = {}    # lane -> [(t, kind), ...] for top-level detection
    wait_stacks = {}  # lane -> [t, ...]
    for e in events:
        t = e.t_ns - anchor
        view.last_t = max(view.last_t, t)
        if e.kind == schema.STEP_KIND:
            if e.phase == schema.PHASE_BEGIN:
                # a re-begun index (restarted job half-drained) keeps
                # the first span; check_step_balance already flagged it
                view.steps.setdefault(e.bytes, [t, None])
            elif e.phase == schema.PHASE_END:
                span = view.steps.get(e.bytes)
                if span is not None and span[1] is None:
                    span[1] = t
        elif e.kind in schema.OP_KINDS:
            stack = op_stacks.setdefault(e.lane, [])
            if e.phase == schema.PHASE_BEGIN:
                stack.append((t, e.kind))
            elif e.phase == schema.PHASE_END and stack:
                t0, kind = stack.pop()
                if not stack and kind == e.kind:
                    # top-level span; END carries the served plane
                    view.op_spans.append(
                        (t0, t, e.kind, e.plane, e.bytes, e.lane)
                    )
        elif e.kind == schema.WAIT_KIND:
            stack = wait_stacks.setdefault(e.lane, [])
            if e.phase == schema.PHASE_BEGIN:
                stack.append(t)
            elif e.phase == schema.PHASE_END and stack:
                view.wait_spans.append((stack.pop(), t))
        elif e.kind in (schema.KIND_IDS["op_progress"],
                        schema.KIND_IDS["op_complete"]):
            # only the engine thread emits these: its lane's op scopes
            # are body executions, not caller-blocked time
            view.engine_lanes.add(e.lane)
            if e.kind == schema.KIND_IDS["op_complete"]:
                # bytes = execution duration in ns (field overload)
                view.engine_busy.append((t - int(e.bytes), t))
        elif e.kind == schema.KIND_IDS["frame_tx"]:
            if e.peer >= 0:
                view.frame_tx.setdefault(e.peer, []).append(t)
        elif e.kind == schema.KIND_IDS["frame_rx"]:
            if e.peer >= 0:
                view.frame_rx.setdefault(e.peer, []).append(t)
        elif e.kind in schema.CONTROL_KINDS:
            view.ctrl.append((t, schema.kind_name(e.kind), e.peer,
                              schema.event_stripe(e)))
    # python lane: spans + step names
    py_stack = {}
    for t_ns, op, phase, nbytes in obj.get("py_events", ()):
        t = int(t_ns) - anchor
        view.last_t = max(view.last_t, t)
        if str(op).startswith("step:"):
            if phase == schema.PHASE_BEGIN:
                view.step_names[int(nbytes)] = str(op)[5:]
            continue
        if phase == schema.PHASE_BEGIN:
            py_stack.setdefault(op, []).append(t)
        elif phase == schema.PHASE_END and py_stack.get(op):
            t0 = py_stack[op].pop()
            view.py_spans.append((t0, t, str(op)))
    view.finish()
    return view


# ---- merged-trace input --------------------------------------------------


def rank_views_from_trace(trace_obj):
    """Rebuild per-rank views from a merged Chrome/Perfetto
    ``job.trace.json`` (the secondary input path: the per-rank files
    may have been cleaned up, the merged artifact archived).  The
    merger wrote job-relative µs with the anchor already subtracted,
    so the anchor here is zero."""
    views = {}
    plane_ids = {v: k for k, v in schema.PLANE_NAMES.items()}
    op_stacks = {}    # (pid, tid) -> [(t, kind)]
    wait_stacks = {}  # (pid, tid) -> [t]
    py_stacks = {}    # pid -> {name: [t]}
    for e in trace_obj["traceEvents"]:
        if e["ph"] == "M":
            continue
        pid = int(e["pid"])
        view = views.get(pid)
        if view is None:
            view = views[pid] = RankView(pid)
        t = int(round(float(e["ts"]) * 1000.0))  # µs -> ns
        view.last_t = max(view.last_t, t)
        name = e["name"]
        args = e.get("args") or {}
        if name.startswith("py:"):
            op = name[3:]
            if op.startswith("step:"):
                if e["ph"] == "B":
                    view.step_names[int(args.get("bytes", 0))] = op[5:]
                continue
            stacks = py_stacks.setdefault(pid, {})
            if e["ph"] == "B":
                stacks.setdefault(op, []).append(t)
            elif e["ph"] == "E" and stacks.get(op):
                t0 = stacks[op].pop()
                if not args.get("truncated"):  # parity w/ rank files
                    view.py_spans.append((t0, t, op))
            continue
        if name == "step":
            idx = int(args.get("bytes", 0))
            if e["ph"] == "B":
                view.steps.setdefault(idx, [t, None])
            elif e["ph"] == "E":
                span = view.steps.get(idx)
                if span is not None and span[1] is None:
                    span[1] = t
                    if args.get("truncated"):
                        # a merger-synthesized close of a dead rank's
                        # open step: keep the truncated tag the
                        # rank-file path would have derived
                        span.append(True)
            continue
        kind = schema.KIND_IDS.get(name)
        if kind is None:
            continue
        if kind in schema.OP_KINDS:
            key = (pid, e["tid"])
            stack = op_stacks.setdefault(key, [])
            if e["ph"] == "B":
                stack.append((t, kind))
            elif e["ph"] == "E" and stack:
                t0, k0 = stack.pop()
                # merger-synthesized truncated closes are skipped for
                # parity with the rank-file path, where an op begin
                # with no end never becomes a span
                if (not stack and k0 == kind
                        and not args.get("truncated")):
                    view.op_spans.append((
                        t0, t, kind,
                        plane_ids.get(args.get("plane"), 0),
                        int(args.get("bytes", 0)),
                        e["tid"],
                    ))
        elif kind == schema.WAIT_KIND:
            key = (pid, e["tid"])
            stack = wait_stacks.setdefault(key, [])
            if e["ph"] == "B":
                stack.append(t)
            elif e["ph"] == "E" and stack:
                t0 = stack.pop()
                if not args.get("truncated"):
                    view.wait_spans.append((t0, t))
        elif name == "frame_tx" and int(args.get("peer", -1)) >= 0:
            view.frame_tx.setdefault(int(args["peer"]), []).append(t)
        elif name == "frame_rx" and int(args.get("peer", -1)) >= 0:
            view.frame_rx.setdefault(int(args["peer"]), []).append(t)
        elif kind in schema.CONTROL_KINDS:
            # trace args carry the raw comm field, which holds the
            # stripe index for the per-link control kinds (schema v2)
            comm = int(args.get("comm", -1))
            stripe = (comm if kind in schema.STRIPE_COMM_KINDS
                      and comm >= 0 else None)
            view.ctrl.append((t, name, int(args.get("peer", -1)),
                              stripe))
        elif name in ("op_progress", "op_complete"):
            # engine lifecycle instants mark the engine's tid: its op
            # slices are body executions, not caller-blocked time
            view.engine_lanes.add(e["tid"])
            if name == "op_complete":
                dur = int(args.get("bytes", 0))
                view.engine_busy.append((t - dur, t))
    for view in views.values():
        view.finish()
    return [views[k] for k in sorted(views)]


def load_views(path):
    """Path (telemetry dir, one rank file, or a merged trace) -> list
    of :class:`RankView`."""
    p = pathlib.Path(path)
    if p.is_dir():
        files = sorted(p.glob(RANK_FILE_GLOB))
        if files:
            return [rank_view_from_obj(schema.load_rank_file(f))
                    for f in files]
        merged = p / MERGED_NAME
        if merged.exists():
            return rank_views_from_trace(schema.load_trace(merged))
        raise FileNotFoundError(
            f"no {RANK_FILE_GLOB} files and no {MERGED_NAME} in {p}"
        )
    with open(p) as f:
        obj = json.load(f)
    if "traceEvents" in obj:
        return rank_views_from_trace(schema.validate_trace(obj))
    return [rank_view_from_obj(schema.validate_rank_file(obj))]


# ---- the analysis --------------------------------------------------------


def _tx_stall(view, lo, hi, gap_ns):
    """(total stall ns, per-peer {peer: stall}, per-(peer, op) stall,
    max local gap ns) from outbound frame pacing inside [lo, hi).

    The metric is the **local send latency** of each outbound frame:
    the time from the moment this rank's inputs were ready — its
    previous tx, its last INBOUND frame, or the enclosing comm
    activity's start, whichever is latest — to the tx itself.  The
    distinction is what makes straggler attribution localise: in a
    segmented ring a slow sender paces every downstream rank, so raw
    inter-tx gaps inherit the delay fleet-wide, but downstream ranks
    send immediately after their rx arrives (local latency ~0) while
    the slow rank sits on ready inputs (local latency = its injected
    or NIC-level delay).  Gaps above ``gap_ns`` count; frames outside
    any comm-activity interval (op scope or engine-busy span) never
    do, so compute pauses between collectives are not wire stalls."""
    activity = _union(
        [(a, b) for a, b, _k, _p, _n, _l in view.op_spans]
        + list(view.engine_busy)
    )
    activity = _clip(activity, lo, hi)
    # spans overlapping the window, start-sorted (op_spans already is).
    # The probes below ride the time-ordered frame timeline, so both
    # lookups advance monotone pointers — O(frames + spans) per step,
    # not O(frames x spans): a 32Ki-event trace stays interactive.
    win_ops = [(a, b, kind)
               for a, b, kind, _p, _n, _l in view.op_spans
               if min(b, hi) > max(a, lo)]
    act_i = 0
    op_j = 0
    op_active = []  # started spans not yet ended, insertion = start order

    def containing(t):
        nonlocal act_i
        while act_i < len(activity) and activity[act_i][1] < t:
            act_i += 1
        if (act_i < len(activity)
                and activity[act_i][0] <= t <= activity[act_i][1]):
            return activity[act_i]
        return None

    def op_of(t):
        nonlocal op_j, op_active
        while op_j < len(win_ops) and win_ops[op_j][0] <= t:
            op_active.append(win_ops[op_j])
            op_j += 1
        op_active = [s for s in op_active if s[1] >= t]
        if op_active:  # earliest-started (outermost) containing span
            return schema.kind_name(op_active[0][2])
        return "engine"

    # merged wire-event timeline: (t, is_tx, peer), time-ordered
    timeline = sorted(
        [(t, True, peer) for peer, ts in view.frame_tx.items()
         for t in ts]
        + [(t, False, peer) for peer, ts in view.frame_rx.items()
           for t in ts]
    )
    total = 0
    per_peer = {}
    per_peer_op = {}  # (peer, op name) -> stalled ns
    max_gap = 0
    last_ready = None  # latest own-tx or inbound-frame instant
    last_act = None
    for t, is_tx, peer in timeline:
        if t < lo or t > hi:
            last_ready = None
            continue
        act = containing(t)
        if not is_tx:
            if act is not None:
                last_ready, last_act = t, act
            continue
        if act is not None:
            ref = act[0] if (last_ready is None or last_act != act) \
                else last_ready
            gap = t - ref
            max_gap = max(max_gap, gap)
            if gap > gap_ns:
                total += gap
                per_peer[peer] = per_peer.get(peer, 0) + gap
                key = (peer, op_of(t))
                per_peer_op[key] = per_peer_op.get(key, 0) + gap
            last_ready, last_act = t, act
    return total, per_peer, per_peer_op, max_gap


def _ctrl_stall(view, lo, hi):
    """``(per_peer, resize_ns)`` inside [lo, hi).

    ``per_peer`` is ``{peer: {"ns", "replays", "breaks",
    "by_stripe"}}``: a ``link_break`` opens a repair window closed by
    the next ``reconnect`` on the same (peer, stripe) — striped links
    repair per stripe (docs/performance.md "striped links"), so the
    windows are keyed per stripe and a break on stripe 1 can never be
    closed by stripe 0's reconnect.  ``by_stripe`` maps stripe ->
    repair ns so the links table can name the ONE slow stripe instead
    of blaming the whole link; ``None`` keys cover unstriped/legacy
    events.  Replay and break counts are per peer too, so the links
    table attributes each event to its own link, never the sum over
    all of them.

    ``resize_ns`` is the time spent inside elastic-resize windows
    (``resize_begin`` → ``resize_done``, docs/failure-semantics.md
    "elastic membership").  Resize stall is its OWN phase, not link
    repair: per-peer repair intervals are clipped against the resize
    windows, so a link that broke because the whole world was resizing
    is never misbinned as that link's repair time."""
    open_break = {}
    repair_ivs = {}  # (peer, stripe) -> [(t0, t1)]
    per_peer = {}
    resize_open = None
    resize_ivs = []

    def rec(peer):
        return per_peer.setdefault(
            peer, {"ns": 0, "replays": 0, "breaks": 0, "by_stripe": {}}
        )

    for t, kind, peer, stripe in view.ctrl:
        if t < lo or t > hi:
            continue
        if kind == "resize_begin":
            if resize_open is None:
                resize_open = t
        elif kind == "resize_done":
            if resize_open is not None:
                resize_ivs.append((resize_open, t))
                resize_open = None
            else:
                # the begin predates this window: charge from its start
                resize_ivs.append((lo, t))
        elif kind == "link_break":
            rec(peer)["breaks"] += 1
            open_break.setdefault((peer, stripe), t)
        elif kind == "reconnect" and (peer, stripe) in open_break:
            repair_ivs.setdefault((peer, stripe), []).append(
                (open_break.pop((peer, stripe)), t)
            )
        elif kind == "replay":
            rec(peer)["replays"] += 1
    if resize_open is not None:
        resize_ivs.append((resize_open, hi))
    for key, t0 in open_break.items():
        repair_ivs.setdefault(key, []).append((t0, hi))
    resize_ivs = _union(resize_ivs)
    resize_ns = _total(resize_ivs)
    for (peer, stripe), ivs in repair_ivs.items():
        ivs = _union(ivs)
        ns = _total(ivs) - _overlap(ivs, resize_ivs)
        r = rec(peer)
        r["ns"] += ns
        r["by_stripe"][stripe] = r["by_stripe"].get(stripe, 0) + ns
    return per_peer, resize_ns


def _step_table(views):
    """{index: {rank: (t0, t1, truncated)}} over every rank; when no
    rank recorded a single step marker, the whole trace becomes step
    -1 ("job")."""
    table = {}
    for view in views:
        for idx, (t0, t1, trunc) in view.steps.items():
            table.setdefault(int(idx), {})[view.rank] = (t0, t1, trunc)
    if table:
        return table
    whole = {}
    for view in views:
        lo = min(
            [a for a, _b, *_ in view.op_spans]
            + [a for a, _b, _n in view.py_spans]
            + ([view.last_t] if view.last_t else [0])
        )
        whole[view.rank] = (lo, view.last_t, False)
    return {-1: whole}


def diagnose(views, ring_min_bytes=None, leader_ring_min_bytes=None,
             stall_gap_ms=DEFAULT_STALL_GAP_MS):
    """The full report dict over a list of :class:`RankView` (the
    ``--json`` payload; :func:`render` turns it into tables)."""
    gap_ns = int(stall_gap_ms * 1e6)
    # knobs: CLI override > the job's recorded tuning > defaults
    tunings = [v.tuning for v in views if v.tuning]
    if ring_min_bytes is None:
        ring_min_bytes = next(
            (t["ring_min_bytes"] for t in tunings
             if t.get("ring_min_bytes") is not None),
            DEFAULT_RING_MIN_BYTES,
        )
    if leader_ring_min_bytes is None:
        leader_ring_min_bytes = next(
            (t["leader_ring_min_bytes"] for t in tunings
             if t.get("leader_ring_min_bytes") is not None),
            DEFAULT_LEADER_RING_MIN_BYTES,
        )

    table = _step_table(views)
    by_rank = {v.rank: v for v in views}
    names = {}
    for view in views:
        names.update(view.step_names)

    steps = []
    led = {}
    skew_hist = [0] * len(SKEW_BUCKETS_MS)
    rank_totals = {
        v.rank: {"compute_ms": 0.0, "blocked_ms": 0.0, "wire_ms": 0.0,
                 "tx_stall_ms": 0.0, "ctrl_stall_ms": 0.0,
                 "resize_ms": 0.0,
                 "overlap_num": 0.0, "overlap_den": 0.0, "steps": 0}
        for v in views
    }
    link_stall = {}   # (rank, peer) -> {"pacing_ms", "repair_ms", ...}

    for idx in sorted(table):
        spans = table[idx]
        t_begin = min(s[0] for s in spans.values())
        t_end = max(s[1] for s in spans.values())
        job_dur = max(t_end - t_begin, 1)
        entry_skew = max(s[0] for s in spans.values()) - t_begin
        per_rank = []
        for rank in sorted(spans):
            view = by_rank[rank]
            lo, hi, trunc = spans[rank]
            dur = max(hi - lo, 0)
            blocked = _clip(view.blocked_spans, lo, hi)
            blocked_ns = _total(blocked)
            compute_ns = max(dur - blocked_ns, 0)
            wire = _clip(view.engine_busy, lo, hi)
            wire_ns = _total(wire)
            overlap_pct = None
            if wire_ns > 0:
                covered = _overlap(wire, blocked)
                overlap_pct = round(
                    100.0 * max(0.0, 1.0 - covered / wire_ns), 1
                )
            tx_ns, tx_per_peer, tx_per_peer_op, max_gap = _tx_stall(
                view, lo, hi, gap_ns
            )
            ctrl_per_peer, resize_ns = _ctrl_stall(view, lo, hi)
            ctrl_ns = sum(c["ns"] for c in ctrl_per_peer.values())
            for peer, ns in tx_per_peer.items():
                rec = link_stall.setdefault(
                    (rank, peer),
                    {"pacing_ms": 0.0, "repair_ms": 0.0, "replays": 0,
                     "breaks": 0, "ops": {}},
                )
                rec["pacing_ms"] += ns / 1e6
            for peer, c in ctrl_per_peer.items():
                rec = link_stall.setdefault(
                    (rank, peer),
                    {"pacing_ms": 0.0, "repair_ms": 0.0, "replays": 0,
                     "breaks": 0, "ops": {}},
                )
                rec["repair_ms"] += c["ns"] / 1e6
                rec["replays"] += c["replays"]
                rec["breaks"] += c["breaks"]
                by = rec.setdefault("by_stripe", {})
                for stripe, ns in c.get("by_stripe", {}).items():
                    by[stripe] = by.get(stripe, 0.0) + ns / 1e6
            for (peer, op), ns in tx_per_peer_op.items():
                rec = link_stall[(rank, peer)]
                rec["ops"][op] = rec["ops"].get(op, 0.0) + ns / 1e6
            per_rank.append({
                "rank": rank,
                "dur_ms": dur / 1e6,
                "entry_late_ms": (lo - t_begin) / 1e6,
                "compute_ms": compute_ns / 1e6,
                "blocked_ms": blocked_ns / 1e6,
                "wire_ms": wire_ns / 1e6,
                "overlap_pct": overlap_pct,
                "tx_stall_ms": tx_ns / 1e6,
                "max_tx_gap_ms": max_gap / 1e6,
                "ctrl_stall_ms": ctrl_ns / 1e6,
                "resize_ms": resize_ns / 1e6,
                "truncated": bool(trunc),
            })
            tot = rank_totals[rank]
            tot["compute_ms"] += compute_ns / 1e6
            tot["blocked_ms"] += blocked_ns / 1e6
            tot["wire_ms"] += wire_ns / 1e6
            tot["tx_stall_ms"] += tx_ns / 1e6
            tot["ctrl_stall_ms"] += ctrl_ns / 1e6
            tot["resize_ms"] += resize_ns / 1e6
            if overlap_pct is not None:
                tot["overlap_num"] += overlap_pct
                tot["overlap_den"] += 1
            tot["steps"] += 1

        med_compute = _median([r["compute_ms"] for r in per_rank])
        scores = []
        for r in per_rank:
            compute_excess = (max(0.0, r["compute_ms"] - med_compute)
                              + r["entry_late_ms"])
            components = {
                "compute": compute_excess,
                "wire": r["tx_stall_ms"],
                "stall": r["ctrl_stall_ms"],
                # elastic resizes are their own phase: membership
                # agreement/rebuild time must not masquerade as link
                # repair (docs/failure-semantics.md)
                "resize": r["resize_ms"],
            }
            phase = max(components, key=lambda k: components[k])
            scores.append((sum(components.values()), r["rank"], phase))
        scores.sort(reverse=True)
        critical_rank = None
        critical_phase = "balanced"
        if scores and scores[0][0] * 1e6 > BALANCED_FRACTION * job_dur:
            critical_rank = scores[0][1]
            critical_phase = scores[0][2]
            led[critical_rank] = led.get(critical_rank, 0) + 1
        for bucket, bound in enumerate(SKEW_BUCKETS_MS):
            if entry_skew / 1e6 < bound:
                skew_hist[bucket] += 1
                break
        overlaps = [r["overlap_pct"] for r in per_rank
                    if r["overlap_pct"] is not None]
        steps.append({
            "index": idx,
            "name": names.get(idx, "job" if idx == -1 else "step"),
            "t_begin_ms": t_begin / 1e6,
            "dur_ms": job_dur / 1e6,
            "entry_skew_ms": entry_skew / 1e6,
            "critical_rank": critical_rank,
            "critical_phase": critical_phase,
            "critical_excess_ms": scores[0][0] if scores else 0.0,
            "overlap_pct": (round(sum(overlaps) / len(overlaps), 1)
                            if overlaps else None),
            # an elastic membership epoch committed under this step on
            # at least one rank: its slowdown is the resize, and the
            # phase attribution above will say so instead of blaming
            # link repair (docs/failure-semantics.md)
            "spans_resize": any(r["resize_ms"] > 0 for r in per_rank),
            "ranks": per_rank,
        })

    # plane audit over every top-level op span (END events carry the
    # served plane): bytes the tree plane moved at sizes where the
    # ring / hierarchical planes would have been selected.  The knobs
    # judged against are the job's EFFECTIVE tuning — the rank files
    # record what tuning.startup resolved (env > tuning cache >
    # default), so a job that ran on cache-loaded values is audited
    # against those, and the audit names the cache file + fingerprint
    # it came from instead of assuming env-derived knobs.
    tuning_meta = next(
        (t for t in tunings if t.get("sources") or t.get("cache_file")),
        tunings[0] if tunings else {},
    )
    knob_sources = tuning_meta.get("sources") or {}
    # compressed collectives (docs/performance.md "Compressed
    # collectives"): judged against the job's EFFECTIVE wire dtype —
    # the same provenance rule as the byte knobs — with the per-rank
    # logical/wire counters summed as the evidence
    wire_dtype = next(
        (t.get("wire_dtype") or (t.get("wire") or {}).get("wire_dtype")
         for t in tunings
         if t.get("wire_dtype") or (t.get("wire") or {}).get("wire_dtype")),
        "off",
    )
    wire_logical = sum(
        int((t.get("wire") or {}).get("wire_logical_bytes") or 0)
        for t in tunings
    )
    wire_on_wire = sum(
        int((t.get("wire") or {}).get("wire_bytes") or 0)
        for t in tunings
    )
    # wire backend (docs/performance.md "io_uring wire backend"):
    # judged with the same provenance rule, with the native syscall
    # counters summed as the evidence — the metric the acceptance
    # gate reads, never derived from event counts
    wire_backend = next(
        (t.get("wire_backend")
         or (t.get("wire") or {}).get("wire_backend")
         for t in tunings
         if t.get("wire_backend")
         or (t.get("wire") or {}).get("wire_backend")),
        "auto",
    )
    backend_active = next(
        ((t.get("wire") or {}).get("wire_backend_active")
         for t in tunings
         if (t.get("wire") or {}).get("wire_backend_active")),
        None,
    )
    tx_sys_total = sum(
        int((v.link_stats.get("aggregate") or {}).get("tx_syscalls", 0))
        for v in views
    )
    rx_sys_total = sum(
        int((v.link_stats.get("aggregate") or {}).get("rx_syscalls", 0))
        for v in views
    )
    audit = {
        "ring_min_bytes": int(ring_min_bytes),
        "leader_ring_min_bytes": int(leader_ring_min_bytes),
        "ring_min_source": knob_sources.get("ring_min_bytes"),
        "leader_ring_min_source": knob_sources.get(
            "leader_ring_min_bytes"
        ),
        "coalesce_bytes": tuning_meta.get("coalesce_bytes"),
        "coalesce_source": knob_sources.get("coalesce_bytes"),
        "tuning_cache_file": tuning_meta.get("cache_file"),
        "tuning_fingerprint": tuning_meta.get("fingerprint"),
        "autotuned": bool(tuning_meta.get("autotuned", False)),
        "wire_dtype": wire_dtype,
        "wire_dtype_source": knob_sources.get("wire_dtype"),
        "wire_logical_bytes": wire_logical,
        "wire_bytes": wire_on_wire,
        "wire_ratio": (round(wire_logical / wire_on_wire, 2)
                       if wire_on_wire else None),
        "wire_backend": wire_backend,
        "wire_backend_source": knob_sources.get("wire_backend"),
        "wire_backend_active": backend_active,
        "tx_syscalls": tx_sys_total,
        "rx_syscalls": rx_sys_total,
        "tree_bytes_over_ring_min": 0,
        "tree_calls_over_ring_min": 0,
        "flat_bytes_over_leader_min_on_multihost": 0,
        "flat_calls_over_leader_min_on_multihost": 0,
    }
    plane_ids = {v: k for k, v in schema.PLANE_NAMES.items()}
    for view in views:
        topo = view.topology or {}
        multihost = (int(topo.get("n_hosts", 1) or 1) > 1
                     and int(topo.get("local_size", 1) or 1) > 1)
        for _a, _b, kind, plane, nbytes, _lane in view.op_spans:
            if kind not in _SWITCHED_OPS:
                continue
            if plane == plane_ids["tree"] and nbytes >= ring_min_bytes:
                audit["tree_bytes_over_ring_min"] += nbytes
                audit["tree_calls_over_ring_min"] += 1
            if (multihost
                    and plane in (plane_ids["tree"], plane_ids["ring"])
                    and nbytes >= leader_ring_min_bytes):
                audit["flat_bytes_over_leader_min_on_multihost"] += nbytes
                audit["flat_calls_over_leader_min_on_multihost"] += 1

    ranks_out = []
    for rank in sorted(rank_totals):
        tot = rank_totals[rank]
        n = max(tot["steps"], 1)
        ranks_out.append({
            "rank": rank,
            "steps": tot["steps"],
            "steps_led": led.get(rank, 0),
            "mean_compute_ms": round(tot["compute_ms"] / n, 3),
            "mean_blocked_ms": round(tot["blocked_ms"] / n, 3),
            "mean_wire_ms": round(tot["wire_ms"] / n, 3),
            "tx_stall_ms": round(tot["tx_stall_ms"], 3),
            "ctrl_stall_ms": round(tot["ctrl_stall_ms"], 3),
            "resize_stall_ms": round(tot["resize_ms"], 3),
            "mean_overlap_pct": (
                round(tot["overlap_num"] / tot["overlap_den"], 1)
                if tot["overlap_den"] else None
            ),
        })

    links_out = []
    # per-link native syscall counters (dumped with the rank files):
    # rides the stall rows so the wire attribution can say whether a
    # slow link was syscall-bound and which backend it ran
    sys_by_link = {}
    for v in views:
        for peer, s in (v.link_stats.get("per_peer") or {}).items():
            sys_by_link[(v.rank, int(peer))] = (
                int(s.get("tx_syscalls", 0)), int(s.get("rx_syscalls", 0))
            )
    for (rank, peer), rec in sorted(link_stall.items()):
        stalled_ops = sorted(
            rec["ops"].items(), key=lambda kv: kv[1], reverse=True
        )
        cause = ("repair" if rec["repair_ms"] > rec["pacing_ms"]
                 else "pacing")
        # striped links repair per stripe (docs/performance.md
        # "striped links"): when one stripe owns the repair time, the
        # wait-cause names THAT stripe instead of blaming the link
        by_stripe = {
            s: round(ms, 3)
            for s, ms in (rec.get("by_stripe") or {}).items()
            if s is not None
        }
        slow_stripe = None
        if cause == "repair" and by_stripe:
            top = max(by_stripe, key=by_stripe.get)
            total = sum(by_stripe.values())
            if total > 0 and by_stripe[top] >= 0.8 * total:
                slow_stripe = top
                cause = f"repair (stripe {top})"
        txs, rxs = sys_by_link.get((rank, peer), (0, 0))
        links_out.append({
            "rank": rank,
            "peer": peer,
            "pacing_ms": round(rec["pacing_ms"], 3),
            "repair_ms": round(rec["repair_ms"], 3),
            "tx_syscalls": txs,
            "rx_syscalls": rxs,
            "replays": rec["replays"],
            "breaks": rec["breaks"],
            "cause": cause,
            "slow_stripe": slow_stripe,
            "repair_by_stripe": by_stripe,
            "stalled_ops": [
                {"op": op, "ms": round(ms, 3)} for op, ms in stalled_ops
            ],
        })
    links_out.sort(
        key=lambda r: r["pacing_ms"] + r["repair_ms"], reverse=True
    )

    durs = [s["dur_ms"] for s in steps]
    overlaps = [s["overlap_pct"] for s in steps
                if s["overlap_pct"] is not None]
    attributed = [s for s in steps if s["critical_rank"] is not None]
    top_straggler = max(led, key=lambda r: led[r]) if led else None
    step_problems = sorted({
        p for v in views for p in v.step_problems
    })
    return {
        "schema": DIAG_SCHEMA,
        "ranks": len(views),
        "n_steps": len(steps),
        "summary": {
            "step_ms_median": round(_median(durs), 3) if durs else None,
            "step_ms_max": round(max(durs), 3) if durs else None,
            "entry_skew_ms_median": round(
                _median([s["entry_skew_ms"] for s in steps]), 3
            ) if steps else None,
            "overlap_pct_median": (round(_median(overlaps), 1)
                                   if overlaps else None),
            "steps_attributed": len(attributed),
            "straggler": top_straggler,
            "straggler_share": (
                round(led[top_straggler] / len(attributed), 3)
                if attributed and top_straggler is not None else None
            ),
        },
        "stragglers": {str(r): n for r, n in sorted(led.items())},
        "entry_skew_hist_ms": {
            ("<" + str(SKEW_BUCKETS_MS[i]) if i == 0 else
             (f">={SKEW_BUCKETS_MS[i-1]:g}" if b == float("inf") else
              f"{SKEW_BUCKETS_MS[i-1]:g}-{b:g}")): skew_hist[i]
            for i, b in enumerate(SKEW_BUCKETS_MS)
        },
        "steps": steps,
        "rank_summary": ranks_out,
        "links": links_out,
        "plane_audit": audit,
        "step_marker_problems": step_problems,
    }


def diagnose_path(path, **kwargs):
    return diagnose(load_views(path), **kwargs)


# ---- A/B diff ------------------------------------------------------------

_DIFF_KEYS = (
    ("step_ms_median", "median step ms", False),
    ("step_ms_max", "max step ms", False),
    ("entry_skew_ms_median", "median entry skew ms", False),
    ("overlap_pct_median", "median overlap %", True),
)


def diff_reports(cur, base):
    """A/B delta between two ``--json`` reports: summary metrics with
    relative change (sign-aware: overlap up = better, times down =
    better), straggler movement, and per-link stall deltas.

    Arms with DIFFERENT world sizes (an autoscaled arm against a
    static one, or an elastic job that shrank) diff honestly: a link
    whose endpoint does not exist in the other arm's world gets
    ``delta_ms: None`` and an ``only_in`` tag instead of a signed
    delta — a rank that was never booted is membership, not an
    improvement or regression."""
    out = {"schema": DIAG_SCHEMA + "+diff", "metrics": [], "links": []}
    base_world = int(base.get("ranks") or 0)
    cur_world = int(cur.get("ranks") or 0)
    out["world"] = {"base": base_world, "cur": cur_world}
    for key, label, higher_better in _DIFF_KEYS:
        a = base.get("summary", {}).get(key)
        b = cur.get("summary", {}).get(key)
        delta = None
        better = None
        if a is not None and b is not None:
            delta = round(b - a, 3)
            if a:
                pct = round(100.0 * (b - a) / abs(a), 1)
            else:
                # a zero baseline (e.g. overlap of a pure-blocking
                # run) has no finite relative change: null, never
                # float('inf') — json.dumps would emit bare Infinity,
                # which strict JSON parsers reject
                pct = 0.0 if b == a else None
            better = (delta >= 0) == higher_better or delta == 0
            out["metrics"].append({
                "metric": key, "label": label, "base": a, "cur": b,
                "delta": delta, "delta_pct": pct,
                "improved": better,
            })
        else:
            out["metrics"].append({
                "metric": key, "label": label, "base": a, "cur": b,
                "delta": None, "delta_pct": None, "improved": None,
            })
    out["straggler"] = {
        "base": base.get("summary", {}).get("straggler"),
        "cur": cur.get("summary", {}).get("straggler"),
    }
    def in_world(rank, peer, world):
        # a world size of 0 means the report predates the field;
        # assume comparable rather than suppressing every delta
        return world <= 0 or (rank < world and peer < world)

    base_links = {(r["rank"], r["peer"]): r
                  for r in base.get("links", ())}
    for link in cur.get("links", ()):
        key = (link["rank"], link["peer"])
        prev = base_links.pop(key, None)
        cur_ms = link["pacing_ms"] + link["repair_ms"]
        if prev is None and not in_world(*key, base_world):
            # this link's endpoint was never part of the base arm's
            # world: membership difference, not a regression
            out["links"].append({
                "rank": link["rank"], "peer": link["peer"],
                "base_stall_ms": None,
                "cur_stall_ms": round(cur_ms, 3),
                "delta_ms": None, "only_in": "cur",
            })
            continue
        prev_ms = ((prev["pacing_ms"] + prev["repair_ms"])
                   if prev else 0.0)
        out["links"].append({
            "rank": link["rank"], "peer": link["peer"],
            "base_stall_ms": round(prev_ms, 3),
            "cur_stall_ms": round(cur_ms, 3),
            "delta_ms": round(cur_ms - prev_ms, 3),
        })
    for (rank, peer), prev in sorted(base_links.items()):
        prev_ms = prev["pacing_ms"] + prev["repair_ms"]
        if not in_world(rank, peer, cur_world):
            # the endpoint does not exist in the current arm's world:
            # its stall did not "vanish", the rank did
            out["links"].append({
                "rank": rank, "peer": peer,
                "base_stall_ms": round(prev_ms, 3),
                "cur_stall_ms": None,
                "delta_ms": None, "only_in": "base",
            })
            continue
        out["links"].append({
            "rank": rank, "peer": peer,
            "base_stall_ms": round(prev_ms, 3), "cur_stall_ms": 0.0,
            "delta_ms": round(-prev_ms, 3),
        })
    return out


# ---- rendering -----------------------------------------------------------


def _fmt(v, nd=2, dash="-"):
    return dash if v is None else f"{v:.{nd}f}"


def render(report, max_steps=40):
    out = []
    summ = report["summary"]
    out.append(
        f"t4j-diagnose — {report['ranks']} rank(s), "
        f"{report['n_steps']} step(s), "
        f"median {_fmt(summ['step_ms_median'])} ms / "
        f"max {_fmt(summ['step_ms_max'])} ms per step"
    )
    if summ["straggler"] is not None:
        share = summ["straggler_share"]
        out.append(
            f"  straggler: r{summ['straggler']} led "
            f"{report['stragglers'].get(str(summ['straggler']), 0)} of "
            f"{summ['steps_attributed']} attributed step(s)"
            + (f" ({100 * share:.0f}%)" if share is not None else "")
        )
    else:
        out.append("  straggler: none (steps balanced)")
    if summ["overlap_pct_median"] is not None:
        out.append(
            f"  measured overlap: median {summ['overlap_pct_median']}% "
            "of wire time ran under caller compute"
        )
    hist = report["entry_skew_hist_ms"]
    if any(hist.values()):
        out.append("  entry-skew histogram (ms): " + "  ".join(
            f"{k}:{v}" for k, v in hist.items() if v
        ))
    steps = report["steps"]
    shown = steps if len(steps) <= max_steps else steps[-max_steps:]
    if shown:
        out.append("")
        out.append(
            f"  {'step':<8}{'name':<12}{'dur ms':>10}{'skew ms':>10}"
            f"{'overlap%':>10}{'critical':>10}{'phase':>10}"
        )
        for s in shown:
            crit = ("-" if s["critical_rank"] is None
                    else f"r{s['critical_rank']}")
            out.append(
                f"  {s['index']:<8}{s['name'][:11]:<12}"
                f"{s['dur_ms']:>10.2f}{s['entry_skew_ms']:>10.2f}"
                f"{_fmt(s['overlap_pct'], 1):>10}{crit:>10}"
                f"{s['critical_phase']:>10}"
            )
        if len(steps) > len(shown):
            out.append(f"  ... ({len(steps) - len(shown)} earlier "
                       "step(s) elided; --json has all)")
    if report["rank_summary"]:
        out.append("")
        out.append(
            f"  {'rank':<6}{'led':>5}{'compute':>10}{'blocked':>10}"
            f"{'wire':>10}{'txstall':>10}{'repair':>10}{'overlap%':>10}"
        )
        for r in report["rank_summary"]:
            out.append(
                f"  r{r['rank']:<5}{r['steps_led']:>5}"
                f"{r['mean_compute_ms']:>10.2f}"
                f"{r['mean_blocked_ms']:>10.2f}"
                f"{r['mean_wire_ms']:>10.2f}{r['tx_stall_ms']:>10.2f}"
                f"{r['ctrl_stall_ms']:>10.2f}"
                f"{_fmt(r['mean_overlap_pct'], 1):>10}"
            )
    links = report["links"][:10]
    if links:
        out.append("")
        out.append(
            f"  {'link':<12}{'pacing ms':>11}{'repair ms':>11}"
            f"{'replays':>9}{'cause':>18}  stalled ops"
        )
        for link in links:
            ops = ", ".join(
                f"{o['op']} {o['ms']:.1f}ms"
                for o in link["stalled_ops"][:3]
            )
            out.append(
                f"  r{link['rank']}->r{link['peer']:<8}"
                f"{link['pacing_ms']:>11.2f}{link['repair_ms']:>11.2f}"
                f"{link['replays']:>9}{link['cause']:>18}  {ops}"
            )
    audit = report["plane_audit"]

    def _knob(value, source):
        return (f"{value} B ({source})" if source else f"{value} B")

    if audit.get("tuning_cache_file") or audit.get("autotuned"):
        out.append("")
        origin = "autotuned this run" if audit.get("autotuned") else "loaded"
        out.append(
            f"  effective tuning: {origin} from cache "
            f"{audit.get('tuning_cache_file') or '(not persisted)'} "
            f"(fingerprint {audit.get('tuning_fingerprint')}); "
            "explicit T4J_* env vars override cached values"
        )
    if audit["tree_calls_over_ring_min"]:
        mb = audit["tree_bytes_over_ring_min"] / 1e6
        out.append("")
        out.append(
            f"  plane audit: {audit['tree_calls_over_ring_min']} "
            f"call(s) / {mb:.1f} MB went TREE at sizes >= the job's "
            f"effective T4J_RING_MIN_BYTES="
            f"{_knob(audit['ring_min_bytes'], audit.get('ring_min_source'))}"
            " where the ring plane is selected — check the knob or "
            "re-calibrate (docs/performance.md)"
        )
    if audit["flat_calls_over_leader_min_on_multihost"]:
        mb = audit["flat_bytes_over_leader_min_on_multihost"] / 1e6
        out.append(
            f"  plane audit: {audit['flat_calls_over_leader_min_on_multihost']} "
            f"call(s) / {mb:.1f} MB ran FLAT on a multi-host topology "
            f"at sizes >= the job's effective T4J_LEADER_RING_MIN_BYTES="
            f"{_knob(audit['leader_ring_min_bytes'], audit.get('leader_ring_min_source'))}"
            " where the hierarchical plane applies — check T4J_HIER"
        )
    if audit.get("wire_dtype", "off") != "off":
        src = audit.get("wire_dtype_source")
        knob = (f"{audit['wire_dtype']} ({src})" if src
                else audit["wire_dtype"])
        out.append("")
        if audit.get("wire_bytes"):
            mb_l = audit["wire_logical_bytes"] / 1e6
            mb_w = audit["wire_bytes"] / 1e6
            out.append(
                f"  wire audit: compressed collectives active, "
                f"T4J_WIRE_DTYPE={knob}: {mb_l:.1f} MB logical moved as "
                f"{mb_w:.1f} MB on the wire "
                f"({audit['wire_ratio']:.2f}x saving)"
            )
        else:
            out.append(
                f"  wire audit: T4J_WIRE_DTYPE={knob} but no compressed "
                "traffic was recorded — every eligible hop was same-host "
                "(pipes never compress) or no f32 SUM collective crossed "
                "hosts; the knob costs nothing here but also buys "
                "nothing (docs/performance.md)"
            )
    if audit.get("tx_syscalls") or audit.get("rx_syscalls"):
        src = audit.get("wire_backend_source")
        knob = (f"{audit.get('wire_backend', 'auto')} ({src})" if src
                else audit.get("wire_backend", "auto"))
        active = audit.get("wire_backend_active")
        out.append("")
        out.append(
            f"  wire audit: T4J_WIRE_BACKEND={knob}"
            + (f" (active: {active})" if active else "")
            + f", {audit['tx_syscalls']} tx / {audit['rx_syscalls']} rx "
            "kernel crossings by the wire threads — the uring backend "
            "is judged by this counter dropping per frame, not by "
            "assumption (docs/performance.md \"io_uring wire backend\")"
        )
    if report["step_marker_problems"]:
        out.append("")
        out.append("  step-marker problems: "
                   + "; ".join(report["step_marker_problems"][:5]))
    return "\n".join(out)


def render_diff(diff):
    out = ["t4j-diagnose --diff (cur vs base)"]
    for m in diff["metrics"]:
        if m["delta"] is None:
            out.append(f"  {m['label']:<24} base={m['base']} "
                       f"cur={m['cur']} (n/a)")
            continue
        arrow = "improved" if m["improved"] else "regressed"
        if m["delta"] == 0:
            arrow = "unchanged"
        pct = ("" if m["delta_pct"] is None
               else f"{m['delta_pct']:+.1f}%, ")
        out.append(
            f"  {m['label']:<24} {m['base']} -> {m['cur']} "
            f"({m['delta']:+g}, {pct}{arrow})"
        )
    stra = diff["straggler"]
    if stra["base"] != stra["cur"]:
        out.append(f"  straggler moved: r{stra['base']} -> "
                   f"r{stra['cur']}")
    else:
        out.append(f"  straggler unchanged: {stra['base']}")
    world = diff.get("world") or {}
    if world and world.get("base") != world.get("cur"):
        out.append(
            f"  world differs: base={world['base']} ranks, "
            f"cur={world['cur']} ranks (membership-only links "
            f"excluded from deltas)"
        )
    moved = [link for link in diff["links"]
             if link["delta_ms"] is not None
             and abs(link["delta_ms"]) > 1.0]
    for link in sorted(moved, key=lambda r: -abs(r["delta_ms"]))[:8]:
        out.append(
            f"  link r{link['rank']}->r{link['peer']}: stall "
            f"{link['base_stall_ms']} -> {link['cur_stall_ms']} ms "
            f"({link['delta_ms']:+g})"
        )
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="t4j-diagnose",
        description="cross-rank per-step performance diagnosis "
                    "(docs/observability.md)",
    )
    ap.add_argument("path", help="--telemetry directory, one "
                                 "rank<k>.t4j.json, or a merged "
                                 "job.trace.json")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report")
    ap.add_argument("--diff", metavar="BASELINE.json", default=None,
                    help="compare against a saved --json report")
    ap.add_argument("--ring-min-bytes", default=None, metavar="N[KMG]",
                    help="ring-plane switchover for the plane audit "
                         "(default: the job's recorded tuning, else "
                         f"{DEFAULT_RING_MIN_BYTES})")
    ap.add_argument("--leader-ring-min-bytes", default=None,
                    metavar="N[KMG]",
                    help="hierarchical switchover for the plane audit")
    ap.add_argument("--stall-gap-ms", type=float,
                    default=DEFAULT_STALL_GAP_MS, metavar="MS",
                    help="outbound frame gaps above this count as wire "
                         f"stalls (default {DEFAULT_STALL_GAP_MS})")
    args = ap.parse_args(argv)
    try:
        views = load_views(args.path)
    except (FileNotFoundError, schema.SchemaError) as e:
        print(f"t4j-diagnose: {e}", file=sys.stderr)
        return 2
    report = diagnose(
        views,
        ring_min_bytes=(parse_bytes(args.ring_min_bytes,
                                    "--ring-min-bytes")
                        if args.ring_min_bytes else None),
        leader_ring_min_bytes=(
            parse_bytes(args.leader_ring_min_bytes,
                        "--leader-ring-min-bytes")
            if args.leader_ring_min_bytes else None),
        stall_gap_ms=args.stall_gap_ms,
    )
    if args.diff:
        with open(args.diff) as f:
            base = json.load(f)
        if base.get("schema") != DIAG_SCHEMA:
            print(
                f"t4j-diagnose: {args.diff} is not a saved --json "
                f"report (schema {base.get('schema')!r})",
                file=sys.stderr,
            )
            return 2
        diff = diff_reports(report, base)
        print(json.dumps(diff) if args.json else render_diff(diff))
        return 0
    print(json.dumps(report) if args.json else render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
