"""Metrics registry: counters + fixed-bucket histograms with p50/p99.

The pure-Python twin of the native metrics table (telemetry.h): rows
keyed by (comm, op, plane) holding counts, byte totals and log2
latency/size histograms.  Used two ways:

* hydrated from a native snapshot (``MetricsRegistry.from_snapshot``,
  via ``runtime.metrics_snapshot()``) — the benchmark/`t4j-top` path;
* fed directly (``observe``) — the same bucketing math, so the
  percentile derivation is testable without the native bridge
  (tests/test_telemetry.py runs on old-jax containers).

Percentiles come from the histograms: the value at quantile q is the
geometric midpoint of the bucket where the cumulative count crosses
q * total, clamped to the observed min/max — a <= 2x-per-bucket
estimator, which is what fixed-bucket histograms buy (the native side
cannot afford per-sample reservoirs on the op path).

Import-free of jax (stdlib only), like the rest of this package.
"""

from .schema import (
    SCHEMA_VERSION,
    SchemaError,
    kind_name,
    parse_snapshot,
    plane_name,
)

# native defaults (telemetry.h); from_snapshot overrides from the header
LAT_BUCKETS = 24
LAT_BASE_LOG2 = 10
SIZE_BUCKETS = 20
SIZE_BASE_LOG2 = 6


def log2_bucket(value, base_log2, nbuckets):
    """The native ``tel::log2_bucket``, bit for bit: bucket i covers
    [2^(base+i), 2^(base+i+1)), everything below the base lands in
    bucket 0, everything at or above the top in the last bucket."""
    v = int(value) >> base_log2
    if v == 0:
        return 0
    b = 0
    while v > 1 and b < nbuckets - 1:
        v >>= 1
        b += 1
    return b


class Histogram:
    """Fixed log2-bucket histogram with quantile estimation."""

    def __init__(self, base_log2, nbuckets, counts=None):
        self.base_log2 = int(base_log2)
        self.counts = list(counts) if counts is not None else [0] * nbuckets
        if counts is not None and len(self.counts) != nbuckets:
            raise SchemaError(
                f"histogram has {len(self.counts)} buckets, want {nbuckets}"
            )

    @property
    def total(self):
        return sum(self.counts)

    def add(self, value):
        self.counts[
            log2_bucket(value, self.base_log2, len(self.counts))
        ] += 1

    def merge(self, other):
        if (other.base_log2 != self.base_log2
                or len(other.counts) != len(self.counts)):
            raise SchemaError("cannot merge histograms of different shape")
        for i, c in enumerate(other.counts):
            self.counts[i] += c

    def bucket_bounds(self, i):
        lo = 1 << (self.base_log2 + i)
        hi = 1 << (self.base_log2 + i + 1)
        if i == 0:
            lo = 0
        return lo, hi

    def quantile(self, q):
        """Estimated value at quantile ``q`` in [0, 1], or ``None`` when
        empty: the geometric midpoint of the crossing bucket."""
        total = self.total
        if total == 0:
            return None
        want = q * total
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= want and c:
                lo, hi = self.bucket_bounds(i)
                return ((max(lo, 1)) * hi) ** 0.5
        return None  # unreachable with total > 0


class Row:
    __slots__ = ("count", "bytes", "sum_ns", "min_ns", "max_ns", "lat",
                 "size")

    def __init__(self, lat_base=LAT_BASE_LOG2, lat_n=LAT_BUCKETS,
                 size_base=SIZE_BASE_LOG2, size_n=SIZE_BUCKETS):
        self.count = 0
        self.bytes = 0
        self.sum_ns = 0
        self.min_ns = 0  # 0 = unset, matching the native table
        self.max_ns = 0
        self.lat = Histogram(lat_base, lat_n)
        self.size = Histogram(size_base, size_n)

    def observe(self, nbytes, dur_ns):
        self.count += 1
        self.bytes += int(nbytes)
        self.sum_ns += int(dur_ns)
        if self.min_ns == 0 or dur_ns < self.min_ns:
            self.min_ns = int(dur_ns)
        if dur_ns > self.max_ns:
            self.max_ns = int(dur_ns)
        self.lat.add(dur_ns)
        self.size.add(nbytes)

    def merge(self, other):
        self.count += other.count
        self.bytes += other.bytes
        self.sum_ns += other.sum_ns
        if other.min_ns and (self.min_ns == 0 or other.min_ns < self.min_ns):
            self.min_ns = other.min_ns
        if other.max_ns > self.max_ns:
            self.max_ns = other.max_ns
        self.lat.merge(other.lat)
        self.size.merge(other.size)

    def latency_ns(self, q):
        """Quantile estimate clamped to the exact observed extremes."""
        v = self.lat.quantile(q)
        if v is None:
            return None
        if self.min_ns:
            v = max(v, self.min_ns)
        if self.max_ns:
            v = min(v, self.max_ns)
        return v

    def stats(self):
        return {
            "count": self.count,
            "bytes": self.bytes,
            "mean_ms": (self.sum_ns / self.count / 1e6) if self.count
            else None,
            "min_ms": self.min_ns / 1e6 if self.min_ns else None,
            "max_ms": self.max_ns / 1e6 if self.max_ns else None,
            "p50_ms": (lambda v: v / 1e6 if v else None)(
                self.latency_ns(0.50)),
            "p99_ms": (lambda v: v / 1e6 if v else None)(
                self.latency_ns(0.99)),
        }


class MetricsRegistry:
    """Rows keyed by (comm, op name, plane name); see module docstring."""

    def __init__(self, lat_base=LAT_BASE_LOG2, lat_n=LAT_BUCKETS,
                 size_base=SIZE_BASE_LOG2, size_n=SIZE_BUCKETS):
        self._shape = (lat_base, lat_n, size_base, size_n)
        self.rows = {}
        self.version = SCHEMA_VERSION

    def _row(self, comm, op, plane):
        key = (int(comm), str(op), str(plane))
        row = self.rows.get(key)
        if row is None:
            row = self.rows[key] = Row(*self._shape)
        return row

    def observe(self, comm, op, plane, nbytes, dur_ns):
        self._row(comm, op, plane).observe(nbytes, dur_ns)

    @classmethod
    def from_snapshot(cls, words):
        """Hydrate from a native u64-word snapshot (or an already
        ``parse_snapshot``-ed dict)."""
        snap = words if isinstance(words, dict) else parse_snapshot(words)
        first = snap["rows"][0] if snap["rows"] else None
        reg = cls(
            snap["lat_base_log2"],
            len(first["lat"]) if first else LAT_BUCKETS,
            snap["size_base_log2"],
            len(first["size"]) if first else SIZE_BUCKETS,
        )
        for r in snap["rows"]:
            row = reg._row(r["comm"], kind_name(r["kind"]),
                           plane_name(r["plane"]))
            row.count += r["count"]
            row.bytes += r["bytes"]
            row.sum_ns += r["sum_ns"]
            row.min_ns = r["min_ns"]
            row.max_ns = r["max_ns"]
            row.lat.merge(Histogram(snap["lat_base_log2"], len(r["lat"]),
                                    r["lat"]))
            row.size.merge(Histogram(snap["size_base_log2"],
                                     len(r["size"]), r["size"]))
        return reg

    def merge(self, other):
        """Fold another registry in (cross-rank aggregation)."""
        for key, row in other.rows.items():
            mine = self.rows.get(key)
            if mine is None:
                mine = self.rows[key] = Row(*self._shape)
            mine.merge(row)
        return self

    def diff(self, prev):
        """Window delta: this registry minus ``prev`` (both cumulative
        native snapshots).  Counters and histogram buckets subtract;
        min/max are reset to unset — the native table tracks them over
        the whole process, so the window extremes are unknowable and a
        stale clamp would distort the window's percentiles.  Benchmarks
        use this to attribute latencies to ONE timed phase instead of
        everything since init."""
        out = MetricsRegistry(*self._shape)
        for key, row in self.rows.items():
            base = prev.rows.get(key)
            d = out._row(*key)
            d.count = row.count - (base.count if base else 0)
            d.bytes = row.bytes - (base.bytes if base else 0)
            d.sum_ns = row.sum_ns - (base.sum_ns if base else 0)
            for i, c in enumerate(row.lat.counts):
                d.lat.counts[i] = c - (base.lat.counts[i] if base else 0)
            for i, c in enumerate(row.size.counts):
                d.size.counts[i] = c - (base.size.counts[i] if base else 0)
            if d.count <= 0:
                del out.rows[(int(key[0]), str(key[1]), str(key[2]))]
        return out

    def aggregate(self, op=None, plane=None, comm=None):
        """One merged :class:`Row` over every row matching the filters
        (``None`` = any), or ``None`` when nothing matches."""
        out = None
        for (c, o, p), row in self.rows.items():
            if op is not None and o != op:
                continue
            if plane is not None and p != plane:
                continue
            if comm is not None and c != int(comm):
                continue
            if out is None:
                out = Row(*self._shape)
            out.merge(row)
        return out

    def op_latency(self, op, plane=None, comm=None):
        """{count, bytes, mean_ms, min_ms, max_ms, p50_ms, p99_ms} for
        one op (optionally one plane/comm), or ``None``."""
        row = self.aggregate(op=op, plane=plane, comm=comm)
        return row.stats() if row is not None else None

    def bytes_by_plane(self):
        """Total payload bytes per data plane over the op rows (the
        per-plane byte counters BENCH records track)."""
        out = {}
        for (_c, _o, plane), row in self.rows.items():
            out[plane] = out.get(plane, 0) + row.bytes
        return out

    def ops(self):
        return sorted({o for (_c, o, _p) in self.rows})
