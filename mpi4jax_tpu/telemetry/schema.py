"""Telemetry wire schema: the single Python mirror of native/src/telemetry.h.

Three layouts live here (docs/observability.md "event schema"):

* the 32-byte packed native event record (``EVENT_STRUCT``, drained via
  ``t4j_telemetry_drain`` / ``t4j_telemetry_peek_last``),
* the u64-word metrics snapshot (``parse_snapshot``, from
  ``t4j_metrics_snapshot``),
* the per-rank JSON file every rank drains at exit
  (``rank<k>.t4j.json``, ``validate_rank_file``) and the merged Chrome/
  Perfetto trace (``job.trace.json``, ``validate_trace``).

This module is deliberately import-free of jax (stdlib only), like
analysis/contracts.py: its tests and the CI telemetry lane run on every
container, including old-jax ones where the package itself cannot
import.  Bump ``SCHEMA_VERSION`` in lockstep with
``tel::kSchemaVersion``.
"""

import json
import struct
from collections import namedtuple

SCHEMA_VERSION = 1
RANK_FILE_SCHEMA = f"t4j-telemetry-v{SCHEMA_VERSION}"

# t_ns, kind, phase, plane, comm, peer, lane, bytes  (telemetry.h Event)
EVENT_STRUCT = struct.Struct("<QHBBiiIQ")
assert EVENT_STRUCT.size == 32, "event layout drifted from telemetry.h"

Event = namedtuple(
    "Event", ["t_ns", "kind", "phase", "plane", "comm", "peer", "lane",
              "bytes"]
)

# Stable wire ids (telemetry.h Kind).
KIND_NAMES = {
    1: "send",
    2: "recv",
    3: "sendrecv",
    4: "barrier",
    5: "bcast",
    6: "reduce",
    7: "allreduce",
    8: "reduce_scatter",
    9: "scan",
    10: "allgather",
    11: "gather",
    12: "scatter",
    13: "alltoall",
    14: "hier_allreduce",
    20: "frame_tx",
    21: "frame_rx",
    30: "link_break",
    31: "reconnect",
    32: "replay",
    33: "link_dead",
    34: "fault",
    40: "shm_stage",
    41: "shm_fold",
    # async progress engine (docs/async.md).  Field overloads (the
    # 32-byte record has no spare): `peer` carries the in-flight-depth
    # gauge; `bytes` is the payload size for op_queued/op_progress but
    # the op's EXECUTION duration in ns for op_complete — t4j-top
    # derives queue depth and the engine overlap ratio from these.
    50: "op_queued",
    51: "op_progress",
    52: "op_complete",
    # caller-side blocked wait (trace mode): begin/end on the CALLER's
    # lane around reap_request's blocked region.  Op bodies execute on
    # the engine thread (their scopes land on the ENGINE lane), so
    # these pairs are the only native record of the caller sitting in
    # a wait — blocking collectives (submit + wait) included.
    # diagnose.py builds caller-blocked time from them.
    53: "wait",
    # step markers (ops.step.annotate_step / step_scope): begin/end
    # pairs whose `bytes` field carries the step INDEX — the ground
    # truth every per-step aggregation (telemetry/diagnose.py) anchors
    # on.  Step NAMES ride the python lane as "step:<name>" rows with
    # the index in nbytes (the 32-byte native record has no string
    # field).
    60: "step",
    # elastic world membership (docs/failure-semantics.md "elastic
    # membership"), recorded from counters mode up like the other
    # control events.  resize_begin/resize_done carry the forming/
    # committed world epoch in `bytes` (done also carries the new
    # member count in `peer`); rank_dead marks a rank leaving the
    # membership (`peer` = the departed world rank, `bytes` = the
    # epoch that removed it) — distinct from link_dead, which is one
    # LINK's terminal verdict.
    61: "resize_begin",
    62: "resize_done",
    63: "rank_dead",
}
KIND_IDS = {v: k for k, v in KIND_NAMES.items()}

# Op-level kinds: the ones that appear as begin/end pairs and as
# metrics-table rows.
OP_KINDS = frozenset(range(1, 15))
CONTROL_KINDS = frozenset((30, 31, 32, 33, 34, 61, 62, 63))
# Elastic membership instants (a subset of the control kinds).
RESIZE_BEGIN_KIND, RESIZE_DONE_KIND, RANK_DEAD_KIND = 61, 62, 63
# Async engine instants (docs/async.md): per-request lifecycle markers.
ASYNC_KINDS = frozenset((50, 51, 52))
# Caller-lane blocked-wait spans (begin/end pairs like op scopes).
WAIT_KIND = 53
# Step-boundary markers (docs/observability.md "step markers").
STEP_KIND = 60

# Async events pack the submitted op's kind into the comm field's high
# byte ((kind+1) << 24 | comm & 0xFFFFFF — dcn.cc async_evt_comm), so
# t4j-top can attribute depth/busy-time per op without per-event ids.
ASYNC_OP_NAMES = {1: "iallreduce", 2: "ireduce_scatter", 3: "isend",
                  4: "irecv", 5: "blocking"}


def decode_async_comm(field):
    """(async op name, comm handle) from an async event's comm field."""
    f = int(field)
    return ASYNC_OP_NAMES.get((f >> 24) & 0xFF, "?"), f & 0xFFFFFF

PHASE_INSTANT, PHASE_BEGIN, PHASE_END = 0, 1, 2
PHASE_NAMES = {0: "instant", 1: "begin", 2: "end"}

PLANE_NAMES = {
    0: "none",
    1: "tree",
    2: "ring",
    3: "hier",
    4: "shm",
    5: "ctrl",
}

SNAP_HEADER_WORDS = 8


class SchemaError(ValueError):
    """A telemetry artifact does not match the documented schema."""


def kind_name(kind):
    return KIND_NAMES.get(int(kind), f"kind{int(kind)}")


def plane_name(plane):
    return PLANE_NAMES.get(int(plane), f"plane{int(plane)}")


def decode_events(buf):
    """Packed native drain buffer -> list of :class:`Event` (ring
    order, oldest first)."""
    if len(buf) % EVENT_STRUCT.size:
        raise SchemaError(
            f"drain buffer of {len(buf)} bytes is not a whole number of "
            f"{EVENT_STRUCT.size}-byte events"
        )
    return [Event(*f) for f in EVENT_STRUCT.iter_unpack(bytes(buf))]


def encode_events(events):
    """Inverse of :func:`decode_events` (tests, synthetic fixtures)."""
    return b"".join(EVENT_STRUCT.pack(*e) for e in events)


def event_to_list(e):
    """JSON-friendly row for the per-rank file (schema: 8-element list
    in EVENT_STRUCT field order)."""
    return [e.t_ns, e.kind, e.phase, e.plane, e.comm, e.peer, e.lane,
            e.bytes]


def event_from_list(row):
    if len(row) != 8:
        raise SchemaError(f"event row has {len(row)} fields, want 8")
    return Event(*row)


def parse_snapshot(words):
    """u64-word metrics snapshot (t4j_metrics_snapshot) -> dict.

    Returns ``{"version", "mode", "lat_base_log2", "size_base_log2",
    "rows": [{comm, kind, plane, count, bytes, sum_ns, min_ns, max_ns,
    lat: [...], size: [...]}, ...]}``.
    """
    words = list(words)
    if len(words) < SNAP_HEADER_WORDS:
        raise SchemaError("metrics snapshot shorter than its header")
    (version, n_rows, row_words, lat_buckets, lat_base, size_buckets,
     size_base, mode) = words[:SNAP_HEADER_WORDS]
    if version != SCHEMA_VERSION:
        raise SchemaError(
            f"metrics snapshot version {version} != {SCHEMA_VERSION}"
        )
    want = SNAP_HEADER_WORDS + n_rows * row_words
    if len(words) < want:
        raise SchemaError(
            f"metrics snapshot truncated: {len(words)} words < {want}"
        )
    if row_words != 8 + lat_buckets + size_buckets:
        raise SchemaError("metrics snapshot row shape is inconsistent")
    rows = []
    off = SNAP_HEADER_WORDS
    for _ in range(n_rows):
        r = words[off:off + row_words]
        rows.append({
            "comm": int(r[0]),
            "kind": int(r[1]),
            "plane": int(r[2]),
            "count": int(r[3]),
            "bytes": int(r[4]),
            "sum_ns": int(r[5]),
            "min_ns": int(r[6]),
            "max_ns": int(r[7]),
            "lat": [int(v) for v in r[8:8 + lat_buckets]],
            "size": [int(v) for v in r[8 + lat_buckets:row_words]],
        })
        off += row_words
    return {
        "version": int(version),
        "mode": int(mode),
        "lat_base_log2": int(lat_base),
        "size_base_log2": int(size_base),
        "rows": rows,
    }


# ---- per-rank file -------------------------------------------------------

_RANK_REQUIRED = ("schema", "rank", "world", "mode", "anchor", "dropped",
                  "events", "py_events", "metrics")


def validate_rank_file(obj):
    """Raise :class:`SchemaError` unless ``obj`` is a well-formed
    per-rank telemetry file; returns ``obj``."""
    if not isinstance(obj, dict):
        raise SchemaError("rank file is not a JSON object")
    for key in _RANK_REQUIRED:
        if key not in obj:
            raise SchemaError(f"rank file is missing {key!r}")
    if obj["schema"] != RANK_FILE_SCHEMA:
        raise SchemaError(
            f"rank file schema {obj['schema']!r} != {RANK_FILE_SCHEMA!r}"
        )
    anchor = obj["anchor"]
    if (not isinstance(anchor, dict) or "mono_ns" not in anchor
            or "unix_ns" not in anchor):
        raise SchemaError("rank file anchor must carry mono_ns + unix_ns")
    if not 0 <= int(obj["rank"]) < int(obj["world"]):
        raise SchemaError(
            f"rank {obj['rank']} out of range for world {obj['world']}"
        )
    for row in obj["events"]:
        event_from_list(row)
    for row in obj["py_events"]:
        if len(row) != 4:
            raise SchemaError(
                f"py_event row has {len(row)} fields, want "
                "[t_ns, op, phase, bytes]"
            )
    return obj


def load_rank_file(path):
    with open(path) as f:
        return validate_rank_file(json.load(f))


# ---- merged Chrome/Perfetto trace ---------------------------------------

_TRACE_PHASES = frozenset("BEiM")


def validate_trace(obj):
    """Raise :class:`SchemaError` unless ``obj`` is a schema-valid
    merged trace (chrome://tracing / Perfetto "JSON object format"):

    * ``traceEvents`` list where every event carries name/ph/pid/tid
      (+ a numeric ``ts`` for non-metadata phases), ``ph`` one of
      B/E/i/M;
    * begin/end events balance per (pid, tid) with LIFO name matching
      (Perfetto rejects crossed or dangling duration events);
    * every pid carries a ``process_name`` metadata event.

    Returns ``obj``.
    """
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise SchemaError("trace is not an object with traceEvents")
    events = obj["traceEvents"]
    if not isinstance(events, list):
        raise SchemaError("traceEvents is not a list")
    named_pids = set()
    pids = set()
    stacks = {}
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            raise SchemaError(f"traceEvents[{i}] is not an object")
        for key in ("name", "ph", "pid", "tid"):
            if key not in e:
                raise SchemaError(f"traceEvents[{i}] is missing {key!r}")
        ph = e["ph"]
        if ph not in _TRACE_PHASES:
            raise SchemaError(
                f"traceEvents[{i}] has unsupported phase {ph!r}"
            )
        if ph == "M":
            if e["name"] == "process_name":
                named_pids.add(e["pid"])
            continue
        pids.add(e["pid"])
        if not isinstance(e.get("ts"), (int, float)):
            raise SchemaError(f"traceEvents[{i}] has no numeric ts")
    # LIFO begin/end balance per (pid, tid), in list order: the merger
    # emits each lane in ring order (time order), and sorting by the
    # microsecond-rounded ts would mis-order zero-length spans
    for e in events:
        if e["ph"] not in "BE":
            continue
        stack = stacks.setdefault((e["pid"], e["tid"]), [])
        if e["ph"] == "B":
            stack.append(e["name"])
        else:
            if not stack or stack[-1] != e["name"]:
                raise SchemaError(
                    f"unbalanced duration events on pid={e['pid']} "
                    f"tid={e['tid']}: E {e['name']!r} does not close "
                    f"{stack[-1] if stack else 'anything'!r}"
                )
            stack.pop()
    for key, stack in stacks.items():
        if stack:
            raise SchemaError(
                f"dangling begin event(s) {stack!r} on pid/tid {key}"
            )
    missing = pids - named_pids
    if missing:
        raise SchemaError(
            f"pid(s) {sorted(missing)} carry events but no process_name "
            "metadata"
        )
    return obj


def load_trace(path):
    with open(path) as f:
        return validate_trace(json.load(f))


def format_recent_events(events):
    """Compact one-line rendering of a ring tail: op, peer, age
    relative to the newest event.  THE shared formatter for every
    surface that shows "last telemetry events" — runtime.check_health's
    fault message, the launcher's first-failure report, and the
    exporter's one-shot file export all call this, so the post-mortem
    and live views agree byte for byte."""
    if not events:
        return ""
    newest = max(e.t_ns for e in events)
    parts = []
    for e in events:
        desc = kind_name(e.kind)
        phase = PHASE_NAMES.get(e.phase, "?")
        if phase != "instant":
            desc += f" {phase}"
        if e.kind == STEP_KIND:
            desc += f" #{e.bytes}"
        elif e.peer >= 0:
            desc += f" peer=r{e.peer}"
        age_ms = (newest - e.t_ns) / 1e6
        parts.append(f"{desc} ({age_ms:.1f}ms ago)")
    return "; ".join(parts)


def check_step_balance(events):
    """Problems list for the step-marker stream of one rank: every step
    begin must be closed by an end carrying the SAME index before the
    next begin opens (steps never nest — annotate_step auto-closes),
    and indices must be monotone.  A dangling final begin is NOT a
    problem: a rank that dies (or is drained) mid-step legitimately
    leaves its last step open, and diagnose closes it at the last seen
    event.  Empty list = clean."""
    problems = []
    open_idx = None
    last_idx = None
    for e in events:
        if e.kind != STEP_KIND:
            continue
        if e.phase == PHASE_BEGIN:
            if open_idx is not None:
                problems.append(
                    f"step #{e.bytes} began while step #{open_idx} was "
                    "still open"
                )
            if last_idx is not None and e.bytes <= last_idx:
                problems.append(
                    f"step index went backwards: #{e.bytes} after "
                    f"#{last_idx}"
                )
            open_idx = e.bytes
            last_idx = e.bytes
        elif e.phase == PHASE_END:
            if open_idx is None:
                problems.append(f"step #{e.bytes} ended but never began")
            elif e.bytes != open_idx:
                problems.append(
                    f"step end #{e.bytes} closes step #{open_idx}"
                )
            open_idx = None
    return problems


def check_begin_end_balance(events):
    """Problems list for a drained native event sequence: every op
    begin must be closed by a matching end on the same thread lane
    (LIFO per lane), and timestamps must be monotone in ring order per
    lane.  Empty list = clean.  (The tests/proc 2-rank job asserts
    this on real drains.)"""
    problems = []
    stacks = {}
    last_t = {}
    for e in events:
        if e.t_ns < last_t.get(e.lane, 0):
            problems.append(
                f"lane {e.lane}: timestamp went backwards at "
                f"{kind_name(e.kind)} ({e.t_ns} < {last_t[e.lane]})"
            )
        last_t[e.lane] = e.t_ns
        if e.kind not in OP_KINDS:
            continue
        stack = stacks.setdefault(e.lane, [])
        if e.phase == PHASE_BEGIN:
            stack.append(e.kind)
        elif e.phase == PHASE_END:
            if not stack or stack[-1] != e.kind:
                problems.append(
                    f"lane {e.lane}: end {kind_name(e.kind)} closes "
                    + (kind_name(stack[-1]) if stack else "nothing")
                )
            else:
                stack.pop()
    for lane, stack in stacks.items():
        for kind in stack:
            problems.append(
                f"lane {lane}: begin {kind_name(kind)} never ended"
            )
    return problems
