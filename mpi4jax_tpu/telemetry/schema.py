"""Telemetry wire schema: the single Python mirror of native/src/telemetry.h.

Four layouts live here (docs/observability.md "event schema"):

* the 32-byte packed native event record (``EVENT_STRUCT``, drained via
  ``t4j_telemetry_drain`` / ``t4j_telemetry_peek_last``),
* the u64-word metrics snapshot (``parse_snapshot``, from
  ``t4j_metrics_snapshot``),
* the per-rank JSON file every rank drains at exit
  (``rank<k>.t4j.json``, ``validate_rank_file``) and the merged Chrome/
  Perfetto trace (``job.trace.json``, ``validate_trace``),
* the crash-consistent flight-recorder file (``rank<k>-<boot>.t4jflight``,
  ``read_flight_file``): the raw mmap'd arena a hard-killed rank
  leaves behind — 160-byte header + the seqlock slot array + the raw
  metrics table, every piece independently validatable so a reader
  can recover a truncated/torn tail without any cooperation from the
  (dead) writer.

This module is deliberately import-free of jax (stdlib only), like
analysis/contracts.py: its tests and the CI telemetry lane run on every
container, including old-jax ones where the package itself cannot
import.  Bump ``SCHEMA_VERSION`` in lockstep with
``tel::kSchemaVersion``, and ``FLIGHT_VERSION`` with
``tel::kFlightVersion``.
"""

import json
import struct
from collections import namedtuple

# v2: frame_tx/frame_rx and the link control events (link_break /
# reconnect / replay / link_dead) carry the STRIPE index in the
# previously unused ``comm`` field (-1 = unstriped/unknown;
# docs/performance.md "striped links and the zero-copy path").  The
# 32-byte record layout itself is unchanged — bump in lockstep with
# tel::kSchemaVersion.
SCHEMA_VERSION = 2
RANK_FILE_SCHEMA = f"t4j-telemetry-v{SCHEMA_VERSION}"
# Versions the READERS accept: v1 artifacts (pre-striping) remain
# losslessly readable — the record layout is identical and v1's comm
# field was -1 for the reinterpreted kinds, which event_stripe already
# maps to "unstriped".  A crash postmortem of an old run must never be
# rejected by a tooling upgrade.
COMPAT_SCHEMA_VERSIONS = frozenset((1, SCHEMA_VERSION))
_COMPAT_RANK_FILE_SCHEMAS = frozenset(
    f"t4j-telemetry-v{v}" for v in COMPAT_SCHEMA_VERSIONS
)

# t_ns, kind, phase, plane, comm, peer, lane, bytes  (telemetry.h Event)
EVENT_STRUCT = struct.Struct("<QHBBiiIQ")
assert EVENT_STRUCT.size == 32, "event layout drifted from telemetry.h"

Event = namedtuple(
    "Event", ["t_ns", "kind", "phase", "plane", "comm", "peer", "lane",
              "bytes"]
)

# Stable wire ids (telemetry.h Kind).
KIND_NAMES = {
    1: "send",
    2: "recv",
    3: "sendrecv",
    4: "barrier",
    5: "bcast",
    6: "reduce",
    7: "allreduce",
    8: "reduce_scatter",
    9: "scan",
    10: "allgather",
    11: "gather",
    12: "scatter",
    13: "alltoall",
    14: "hier_allreduce",
    20: "frame_tx",
    21: "frame_rx",
    30: "link_break",
    31: "reconnect",
    32: "replay",
    33: "link_dead",
    34: "fault",
    40: "shm_stage",
    41: "shm_fold",
    # async progress engine (docs/async.md).  Field overloads (the
    # 32-byte record has no spare): `peer` carries the in-flight-depth
    # gauge; `bytes` is the payload size for op_queued/op_progress but
    # the op's EXECUTION duration in ns for op_complete — t4j-top
    # derives queue depth and the engine overlap ratio from these.
    50: "op_queued",
    51: "op_progress",
    52: "op_complete",
    # caller-side blocked wait (trace mode): begin/end on the CALLER's
    # lane around reap_request's blocked region.  Op bodies execute on
    # the engine thread (their scopes land on the ENGINE lane), so
    # these pairs are the only native record of the caller sitting in
    # a wait — blocking collectives (submit + wait) included.
    # diagnose.py builds caller-blocked time from them.
    53: "wait",
    # step markers (ops.step.annotate_step / step_scope): begin/end
    # pairs whose `bytes` field carries the step INDEX — the ground
    # truth every per-step aggregation (telemetry/diagnose.py) anchors
    # on.  Step NAMES ride the python lane as "step:<name>" rows with
    # the index in nbytes (the 32-byte native record has no string
    # field).
    60: "step",
    # elastic world membership (docs/failure-semantics.md "elastic
    # membership"), recorded from counters mode up like the other
    # control events.  resize_begin/resize_done carry the forming/
    # committed world epoch in `bytes` (done also carries the new
    # member count in `peer`); rank_dead marks a rank leaving the
    # membership (`peer` = the departed world rank, `bytes` = the
    # epoch that removed it) — distinct from link_dead, which is one
    # LINK's terminal verdict.
    61: "resize_begin",
    62: "resize_done",
    63: "rank_dead",
}
KIND_IDS = {v: k for k, v in KIND_NAMES.items()}

# Op-level kinds: the ones that appear as begin/end pairs and as
# metrics-table rows.
OP_KINDS = frozenset(range(1, 15))
CONTROL_KINDS = frozenset((30, 31, 32, 33, 34, 61, 62, 63))
# Elastic membership instants (a subset of the control kinds).
RESIZE_BEGIN_KIND, RESIZE_DONE_KIND, RANK_DEAD_KIND = 61, 62, 63
# Async engine instants (docs/async.md): per-request lifecycle markers.
ASYNC_KINDS = frozenset((50, 51, 52))
# Caller-lane blocked-wait spans (begin/end pairs like op scopes).
WAIT_KIND = 53
# Step-boundary markers (docs/observability.md "step markers").
STEP_KIND = 60

# Async events pack the submitted op's kind into the comm field's high
# byte ((kind+1) << 24 | comm & 0xFFFFFF — dcn.cc async_evt_comm), so
# t4j-top can attribute depth/busy-time per op without per-event ids.
ASYNC_OP_NAMES = {1: "iallreduce", 2: "ireduce_scatter", 3: "isend",
                  4: "irecv", 5: "blocking"}


def decode_async_comm(field):
    """(async op name, comm handle) from an async event's comm field."""
    f = int(field)
    return ASYNC_OP_NAMES.get((f >> 24) & 0xFF, "?"), f & 0xFFFFFF


# Kinds whose `comm` field carries the wire STRIPE index (schema v2):
# the data-plane frame instants and the per-link control events.
STRIPE_COMM_KINDS = frozenset((20, 21, 30, 31, 32, 33))


def event_stripe(e):
    """The stripe index an event belongs to, or ``None`` when the
    event kind has no stripe attribution or predates striping
    (docs/performance.md "striped links and the zero-copy path")."""
    if int(e.kind) in STRIPE_COMM_KINDS and int(e.comm) >= 0:
        return int(e.comm)
    return None

PHASE_INSTANT, PHASE_BEGIN, PHASE_END = 0, 1, 2
PHASE_NAMES = {0: "instant", 1: "begin", 2: "end"}

PLANE_NAMES = {
    0: "none",
    1: "tree",
    2: "ring",
    3: "hier",
    4: "shm",
    5: "ctrl",
}

SNAP_HEADER_WORDS = 8


class SchemaError(ValueError):
    """A telemetry artifact does not match the documented schema."""


def kind_name(kind):
    return KIND_NAMES.get(int(kind), f"kind{int(kind)}")


def plane_name(plane):
    return PLANE_NAMES.get(int(plane), f"plane{int(plane)}")


def decode_events(buf):
    """Packed native drain buffer -> list of :class:`Event` (ring
    order, oldest first)."""
    if len(buf) % EVENT_STRUCT.size:
        raise SchemaError(
            f"drain buffer of {len(buf)} bytes is not a whole number of "
            f"{EVENT_STRUCT.size}-byte events"
        )
    return [Event(*f) for f in EVENT_STRUCT.iter_unpack(bytes(buf))]


def encode_events(events):
    """Inverse of :func:`decode_events` (tests, synthetic fixtures)."""
    return b"".join(EVENT_STRUCT.pack(*e) for e in events)


def event_to_list(e):
    """JSON-friendly row for the per-rank file (schema: 8-element list
    in EVENT_STRUCT field order)."""
    return [e.t_ns, e.kind, e.phase, e.plane, e.comm, e.peer, e.lane,
            e.bytes]


def event_from_list(row):
    if len(row) != 8:
        raise SchemaError(f"event row has {len(row)} fields, want 8")
    return Event(*row)


def parse_snapshot(words):
    """u64-word metrics snapshot (t4j_metrics_snapshot) -> dict.

    Returns ``{"version", "mode", "lat_base_log2", "size_base_log2",
    "rows": [{comm, kind, plane, count, bytes, sum_ns, min_ns, max_ns,
    lat: [...], size: [...]}, ...]}``.
    """
    words = list(words)
    if len(words) < SNAP_HEADER_WORDS:
        raise SchemaError("metrics snapshot shorter than its header")
    (version, n_rows, row_words, lat_buckets, lat_base, size_buckets,
     size_base, mode) = words[:SNAP_HEADER_WORDS]
    if version not in COMPAT_SCHEMA_VERSIONS:
        raise SchemaError(
            f"metrics snapshot version {version} != {SCHEMA_VERSION}"
        )
    want = SNAP_HEADER_WORDS + n_rows * row_words
    if len(words) < want:
        raise SchemaError(
            f"metrics snapshot truncated: {len(words)} words < {want}"
        )
    if row_words != 8 + lat_buckets + size_buckets:
        raise SchemaError("metrics snapshot row shape is inconsistent")
    rows = []
    off = SNAP_HEADER_WORDS
    for _ in range(n_rows):
        r = words[off:off + row_words]
        rows.append({
            "comm": int(r[0]),
            "kind": int(r[1]),
            "plane": int(r[2]),
            "count": int(r[3]),
            "bytes": int(r[4]),
            "sum_ns": int(r[5]),
            "min_ns": int(r[6]),
            "max_ns": int(r[7]),
            "lat": [int(v) for v in r[8:8 + lat_buckets]],
            "size": [int(v) for v in r[8 + lat_buckets:row_words]],
        })
        off += row_words
    return {
        "version": int(version),
        "mode": int(mode),
        "lat_base_log2": int(lat_base),
        "size_base_log2": int(size_base),
        "rows": rows,
    }


# ---- per-rank file -------------------------------------------------------

_RANK_REQUIRED = ("schema", "rank", "world", "mode", "anchor", "dropped",
                  "events", "py_events", "metrics")


def validate_rank_file(obj):
    """Raise :class:`SchemaError` unless ``obj`` is a well-formed
    per-rank telemetry file; returns ``obj``."""
    if not isinstance(obj, dict):
        raise SchemaError("rank file is not a JSON object")
    for key in _RANK_REQUIRED:
        if key not in obj:
            raise SchemaError(f"rank file is missing {key!r}")
    if obj["schema"] not in _COMPAT_RANK_FILE_SCHEMAS:
        raise SchemaError(
            f"rank file schema {obj['schema']!r} != {RANK_FILE_SCHEMA!r}"
        )
    anchor = obj["anchor"]
    if (not isinstance(anchor, dict) or "mono_ns" not in anchor
            or "unix_ns" not in anchor):
        raise SchemaError("rank file anchor must carry mono_ns + unix_ns")
    if not 0 <= int(obj["rank"]) < int(obj["world"]):
        raise SchemaError(
            f"rank {obj['rank']} out of range for world {obj['world']}"
        )
    for row in obj["events"]:
        event_from_list(row)
    for row in obj["py_events"]:
        if len(row) != 4:
            raise SchemaError(
                f"py_event row has {len(row)} fields, want "
                "[t_ns, op, phase, bytes]"
            )
    return obj


def load_rank_file(path):
    with open(path) as f:
        return validate_rank_file(json.load(f))


# ---- merged Chrome/Perfetto trace ---------------------------------------

_TRACE_PHASES = frozenset("BEiM")


def validate_trace(obj):
    """Raise :class:`SchemaError` unless ``obj`` is a schema-valid
    merged trace (chrome://tracing / Perfetto "JSON object format"):

    * ``traceEvents`` list where every event carries name/ph/pid/tid
      (+ a numeric ``ts`` for non-metadata phases), ``ph`` one of
      B/E/i/M;
    * begin/end events balance per (pid, tid) with LIFO name matching
      (Perfetto rejects crossed or dangling duration events);
    * every pid carries a ``process_name`` metadata event.

    Returns ``obj``.
    """
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise SchemaError("trace is not an object with traceEvents")
    events = obj["traceEvents"]
    if not isinstance(events, list):
        raise SchemaError("traceEvents is not a list")
    named_pids = set()
    pids = set()
    stacks = {}
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            raise SchemaError(f"traceEvents[{i}] is not an object")
        for key in ("name", "ph", "pid", "tid"):
            if key not in e:
                raise SchemaError(f"traceEvents[{i}] is missing {key!r}")
        ph = e["ph"]
        if ph not in _TRACE_PHASES:
            raise SchemaError(
                f"traceEvents[{i}] has unsupported phase {ph!r}"
            )
        if ph == "M":
            if e["name"] == "process_name":
                named_pids.add(e["pid"])
            continue
        pids.add(e["pid"])
        if not isinstance(e.get("ts"), (int, float)):
            raise SchemaError(f"traceEvents[{i}] has no numeric ts")
    # LIFO begin/end balance per (pid, tid), in list order: the merger
    # emits each lane in ring order (time order), and sorting by the
    # microsecond-rounded ts would mis-order zero-length spans
    for e in events:
        if e["ph"] not in "BE":
            continue
        stack = stacks.setdefault((e["pid"], e["tid"]), [])
        if e["ph"] == "B":
            stack.append(e["name"])
        else:
            if not stack or stack[-1] != e["name"]:
                raise SchemaError(
                    f"unbalanced duration events on pid={e['pid']} "
                    f"tid={e['tid']}: E {e['name']!r} does not close "
                    f"{stack[-1] if stack else 'anything'!r}"
                )
            stack.pop()
    for key, stack in stacks.items():
        if stack:
            raise SchemaError(
                f"dangling begin event(s) {stack!r} on pid/tid {key}"
            )
    missing = pids - named_pids
    if missing:
        raise SchemaError(
            f"pid(s) {sorted(missing)} carry events but no process_name "
            "metadata"
        )
    return obj


def load_trace(path):
    with open(path) as f:
        return validate_trace(json.load(f))


def format_recent_events(events):
    """Compact one-line rendering of a ring tail: op, peer, age
    relative to the newest event.  THE shared formatter for every
    surface that shows "last telemetry events" — runtime.check_health's
    fault message, the launcher's first-failure report, and the
    exporter's one-shot file export all call this, so the post-mortem
    and live views agree byte for byte."""
    if not events:
        return ""
    newest = max(e.t_ns for e in events)
    parts = []
    for e in events:
        desc = kind_name(e.kind)
        phase = PHASE_NAMES.get(e.phase, "?")
        if phase != "instant":
            desc += f" {phase}"
        if e.kind == STEP_KIND:
            desc += f" #{e.bytes}"
        elif e.peer >= 0:
            desc += f" peer=r{e.peer}"
            stripe = event_stripe(e)
            if stripe is not None:
                desc += f"/s{stripe}"
        age_ms = (newest - e.t_ns) / 1e6
        parts.append(f"{desc} ({age_ms:.1f}ms ago)")
    return "; ".join(parts)


def check_step_balance(events):
    """Problems list for the step-marker stream of one rank: every step
    begin must be closed by an end carrying the SAME index before the
    next begin opens (steps never nest — annotate_step auto-closes),
    and indices must be monotone.  A dangling final begin is NOT a
    problem: a rank that dies (or is drained) mid-step legitimately
    leaves its last step open, and diagnose closes it at the last seen
    event.  Empty list = clean."""
    problems = []
    open_idx = None
    last_idx = None
    for e in events:
        if e.kind != STEP_KIND:
            continue
        if e.phase == PHASE_BEGIN:
            if open_idx is not None:
                problems.append(
                    f"step #{e.bytes} began while step #{open_idx} was "
                    "still open"
                )
            if last_idx is not None and e.bytes <= last_idx:
                problems.append(
                    f"step index went backwards: #{e.bytes} after "
                    f"#{last_idx}"
                )
            open_idx = e.bytes
            last_idx = e.bytes
        elif e.phase == PHASE_END:
            if open_idx is None:
                problems.append(f"step #{e.bytes} ended but never began")
            elif e.bytes != open_idx:
                problems.append(
                    f"step end #{e.bytes} closes step #{open_idx}"
                )
            open_idx = None
    return problems


def check_begin_end_balance(events):
    """Problems list for a drained native event sequence: every op
    begin must be closed by a matching end on the same thread lane
    (LIFO per lane), and timestamps must be monotone in ring order per
    lane.  Empty list = clean.  (The tests/proc 2-rank job asserts
    this on real drains.)"""
    problems = []
    stacks = {}
    last_t = {}
    for e in events:
        if e.t_ns < last_t.get(e.lane, 0):
            problems.append(
                f"lane {e.lane}: timestamp went backwards at "
                f"{kind_name(e.kind)} ({e.t_ns} < {last_t[e.lane]})"
            )
        last_t[e.lane] = e.t_ns
        if e.kind not in OP_KINDS:
            continue
        stack = stacks.setdefault(e.lane, [])
        if e.phase == PHASE_BEGIN:
            stack.append(e.kind)
        elif e.phase == PHASE_END:
            if not stack or stack[-1] != e.kind:
                problems.append(
                    f"lane {e.lane}: end {kind_name(e.kind)} closes "
                    + (kind_name(stack[-1]) if stack else "nothing")
                )
            else:
                stack.pop()
    for lane, stack in stacks.items():
        for kind in stack:
            problems.append(
                f"lane {lane}: begin {kind_name(kind)} never ended"
            )
    return problems


# ---- flight-recorder file (crash-consistent mmap arena) ------------------
#
# Mirror of telemetry.h FlightHeader/Slot/Table: a 160-byte header,
# then nslots 40-byte slots (u64 seqlock ticket + the 32-byte event
# record), then the raw metrics table (fixed shape, 49 u64 words per
# (comm, kind, plane) row).  The writer publishes each slot with a
# release store of ticket = global_index + 1 AFTER the payload stores,
# so any slot whose ticket passes the position check below carries a
# fully-written event even if the process was SIGKILL'd the next
# instant — mmap(MAP_SHARED) means the page cache, not the process,
# owns the bytes.

FLIGHT_MAGIC = b"T4JFLT1\x00"
FLIGHT_VERSION = 1
FLIGHT_FILE_SCHEMA = f"t4j-flight-v{FLIGHT_VERSION}"
FLIGHT_FILE_GLOB = "rank*.t4jflight"
FLIGHT_HEADER_BYTES = 160
# magic, version, schema, rank, world, epoch, mode, boot_unix_ns,
# boot_token, anchor_mono_ns, anchor_unix_ns, nslots, widx, dropped,
# heartbeat_ns, heartbeat_count, flags, pad, slots_off, metrics_off,
# metrics_bytes  (24 reserved bytes follow)
FLIGHT_HEADER_STRUCT = struct.Struct("<8sIIiiIIQQQQQQQQQIIQQQ")
assert FLIGHT_HEADER_STRUCT.size == 136, "flight header drifted"
FLIGHT_SLOT_STRUCT = struct.Struct("<Q" + EVENT_STRUCT.format[1:])
assert FLIGHT_SLOT_STRUCT.size == 40, "flight slot drifted"
FLIGHT_FINALIZED = 1  # flags bit: clean finalize ran

# telemetry.h metrics-table shape (kMaxComm x kMaxKind x kMaxPlane
# rows of [count, bytes, sum_ns, min_ns, max_ns, lat..., size...]).
FLIGHT_MAX_COMM = 8
FLIGHT_MAX_KIND = 16
FLIGHT_MAX_PLANE = 6
FLIGHT_LAT_BUCKETS = 24
FLIGHT_SIZE_BUCKETS = 20
FLIGHT_ROW_WORDS = 5 + FLIGHT_LAT_BUCKETS + FLIGHT_SIZE_BUCKETS
FLIGHT_TABLE_BYTES = (FLIGHT_ROW_WORDS * 8 * FLIGHT_MAX_COMM
                      * FLIGHT_MAX_KIND * FLIGHT_MAX_PLANE)

_TEL_MODE_NAMES = {0: "off", 1: "counters", 2: "trace"}


def flight_file_name(rank, boot_unix_ns):
    return f"rank{int(rank)}-{int(boot_unix_ns)}.t4jflight"


def parse_flight_header(buf):
    """First ``FLIGHT_HEADER_BYTES`` of a flight file -> header dict.
    Raises :class:`SchemaError` on a wrong magic/version (a torn or
    foreign file must never parse as evidence)."""
    if len(buf) < FLIGHT_HEADER_STRUCT.size:
        raise SchemaError(
            f"flight header truncated: {len(buf)} bytes < "
            f"{FLIGHT_HEADER_STRUCT.size}"
        )
    (magic, version, schema_v, rank, world, epoch, mode, boot_unix_ns,
     boot_token, anchor_mono_ns, anchor_unix_ns, nslots, widx, dropped,
     heartbeat_ns, heartbeat_count, flags, _pad, slots_off, metrics_off,
     metrics_bytes) = FLIGHT_HEADER_STRUCT.unpack(
        buf[:FLIGHT_HEADER_STRUCT.size])
    if magic != FLIGHT_MAGIC:
        raise SchemaError(f"not a flight file (magic {magic!r})")
    if version != FLIGHT_VERSION:
        raise SchemaError(
            f"flight file version {version} != {FLIGHT_VERSION}"
        )
    if schema_v not in COMPAT_SCHEMA_VERSIONS:
        raise SchemaError(
            f"flight file event schema {schema_v} != {SCHEMA_VERSION}"
        )
    return {
        "schema": FLIGHT_FILE_SCHEMA,
        "rank": int(rank),
        "world": int(world),
        "epoch": int(epoch),
        "mode": _TEL_MODE_NAMES.get(int(mode), f"mode{int(mode)}"),
        "boot_unix_ns": int(boot_unix_ns),
        "boot_token": int(boot_token),
        "anchor": {"mono_ns": int(anchor_mono_ns),
                   "unix_ns": int(anchor_unix_ns)},
        "nslots": int(nslots),
        "widx": int(widx),
        "dropped": int(dropped),
        "heartbeat_ns": int(heartbeat_ns),
        "heartbeat_count": int(heartbeat_count),
        "finalized": bool(flags & FLIGHT_FINALIZED),
        "slots_off": int(slots_off),
        "metrics_off": int(metrics_off),
        "metrics_bytes": int(metrics_bytes),
    }


def _recover_flight_slots(buf, hdr):
    """Slot region bytes -> (events in publish order, torn count).

    A slot is accepted only when its seqlock ticket is internally
    consistent: nonzero, at most the header's write cursor, and
    pointing back at the slot's own position ((ticket-1) % nslots).
    Anything else — an in-flight writer killed between the fetch_add
    and the publish, a half-grown file, garbage — is counted as torn
    and dropped, never misread as an event.  Publish order (the
    ticket) is the ground truth even when timestamps tie."""
    nslots = hdr["nslots"]
    widx = hdr["widx"]
    recovered = []
    torn = 0
    usable = min(nslots, len(buf) // FLIGHT_SLOT_STRUCT.size)
    for pos in range(usable):
        off = pos * FLIGHT_SLOT_STRUCT.size
        fields = FLIGHT_SLOT_STRUCT.unpack_from(buf, off)
        ticket = fields[0]
        if ticket == 0:
            continue  # never written (or invalidated mid-claim)
        if ticket > widx or (ticket - 1) % nslots != pos:
            torn += 1
            continue
        recovered.append((ticket, Event(*fields[1:])))
    recovered.sort(key=lambda te: te[0])
    return [e for _t, e in recovered], torn


def _parse_flight_table(buf, mode_name):
    """Raw metrics-table bytes -> the :func:`parse_snapshot` dict shape
    (only rows with count > 0, comm-major order like the native
    snapshot)."""
    rows = []
    row_bytes = FLIGHT_ROW_WORDS * 8
    idx = 0
    for comm in range(FLIGHT_MAX_COMM):
        for kind in range(FLIGHT_MAX_KIND):
            for plane in range(FLIGHT_MAX_PLANE):
                off = idx * row_bytes
                idx += 1
                if off + row_bytes > len(buf):
                    return None  # truncated table: no partial rows
                words = struct.unpack_from(f"<{FLIGHT_ROW_WORDS}Q", buf,
                                           off)
                if not words[0]:
                    continue
                rows.append({
                    "comm": comm,
                    "kind": kind,
                    "plane": plane,
                    "count": int(words[0]),
                    "bytes": int(words[1]),
                    "sum_ns": int(words[2]),
                    "min_ns": int(words[3]),
                    "max_ns": int(words[4]),
                    "lat": [int(v) for v in
                            words[5:5 + FLIGHT_LAT_BUCKETS]],
                    "size": [int(v) for v in
                             words[5 + FLIGHT_LAT_BUCKETS:]],
                })
    mode_id = {v: k for k, v in _TEL_MODE_NAMES.items()}.get(mode_name, 0)
    return {"version": SCHEMA_VERSION, "mode": mode_id,
            "lat_base_log2": 10, "size_base_log2": 6, "rows": rows}


def read_flight_file(path):
    """Read and recover a flight-recorder file WITHOUT any writer
    cooperation (the writer may be dead, or still running — both are
    safe: every slot is independently validated).

    Returns the header dict plus ``events`` (recovered, publish
    order), ``metrics`` (parse_snapshot shape, or None when the table
    region is truncated), ``torn_slots``, ``recovered_events``,
    ``file_bytes`` and ``path``."""
    with open(path, "rb") as f:
        data = f.read()
    hdr = parse_flight_header(data)
    slots_lo = hdr["slots_off"]
    slots_hi = min(hdr["metrics_off"], len(data))
    events, torn = _recover_flight_slots(data[slots_lo:slots_hi], hdr)
    metrics = _parse_flight_table(
        data[hdr["metrics_off"]:hdr["metrics_off"] + hdr["metrics_bytes"]],
        hdr["mode"])
    obj = dict(hdr)
    obj.update(
        events=events,
        metrics=metrics,
        torn_slots=torn,
        recovered_events=len(events),
        file_bytes=len(data),
        path=str(path),
    )
    return obj


def encode_flight_file(rank, world, events=(), *, epoch=0, mode="trace",
                       boot_unix_ns=0, boot_token=0, anchor_mono_ns=0,
                       anchor_unix_ns=0, nslots=256, heartbeat_ns=0,
                       heartbeat_count=0, finalized=False, dropped=0,
                       widx=None, torn_positions=(), metrics_rows=()):
    """Synthesize the byte-exact flight-file layout (tests and
    fixtures: the inverse of :func:`read_flight_file`, mirroring what
    tel::flight_init + emit produce).  ``events`` land in ring order
    starting at ticket 1; positions in ``torn_positions`` get a
    deliberately inconsistent ticket (an in-flight writer's slot).
    ``metrics_rows`` are parse_snapshot-shaped row dicts."""
    events = list(events)
    n_written = len(events)
    w = n_written if widx is None else int(widx)
    flags = FLIGHT_FINALIZED if finalized else 0
    slots_off = FLIGHT_HEADER_BYTES
    metrics_off = slots_off + nslots * FLIGHT_SLOT_STRUCT.size
    header = FLIGHT_HEADER_STRUCT.pack(
        FLIGHT_MAGIC, FLIGHT_VERSION, SCHEMA_VERSION, int(rank),
        int(world), int(epoch),
        {v: k for k, v in _TEL_MODE_NAMES.items()}.get(mode, 2),
        int(boot_unix_ns), int(boot_token), int(anchor_mono_ns),
        int(anchor_unix_ns), int(nslots), w, int(dropped),
        int(heartbeat_ns), int(heartbeat_count), flags, 0, slots_off,
        metrics_off, FLIGHT_TABLE_BYTES,
    ) + b"\x00" * (FLIGHT_HEADER_BYTES - FLIGHT_HEADER_STRUCT.size)
    slots = bytearray(nslots * FLIGHT_SLOT_STRUCT.size)
    for i, e in enumerate(events):
        ticket = i + 1
        pos = (ticket - 1) % nslots
        FLIGHT_SLOT_STRUCT.pack_into(slots,
                                     pos * FLIGHT_SLOT_STRUCT.size,
                                     ticket, *e)
    for pos in torn_positions:
        # a ticket that fails the position check: reader must drop it
        FLIGHT_SLOT_STRUCT.pack_into(
            slots, pos * FLIGHT_SLOT_STRUCT.size, pos + 2,
            0, 0, 0, 0, 0, 0, 0, 0)
    table = bytearray(FLIGHT_TABLE_BYTES)
    row_bytes = FLIGHT_ROW_WORDS * 8
    for r in metrics_rows:
        idx = ((r["comm"] * FLIGHT_MAX_KIND) + r["kind"]) \
            * FLIGHT_MAX_PLANE + r["plane"]
        words = ([r["count"], r["bytes"], r["sum_ns"], r["min_ns"],
                  r["max_ns"]]
                 + list(r.get("lat", [0] * FLIGHT_LAT_BUCKETS))
                 + list(r.get("size", [0] * FLIGHT_SIZE_BUCKETS)))
        struct.pack_into(f"<{FLIGHT_ROW_WORDS}Q", table,
                         idx * row_bytes, *words)
    return bytes(header) + bytes(slots) + bytes(table)
