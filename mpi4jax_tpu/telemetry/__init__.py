"""Comm telemetry: native event ring + metrics -> snapshots, Perfetto
timelines, and ``t4j-top`` (docs/observability.md).

The measurement layer over the native bridge's instrumentation
(native/src/telemetry.h): each rank's lock-free event ring and metrics
table drain through ``native.runtime`` into per-rank JSON files
(:mod:`.dump`), which merge into one cross-rank Chrome/Perfetto trace
(:mod:`.trace`) and render as console tables (:mod:`.top`, the
``t4j-top`` script).  :mod:`.schema` is the wire-format mirror,
:mod:`.registry` the counters/histograms/percentile core, and
:mod:`.recorder` the Python-level op bracket.

Enable with ``T4J_TELEMETRY=counters|trace`` (validated in
utils/config.py; ``off`` is a zero-cost no-op) or run jobs under
``python -m mpi4jax_tpu.launch --telemetry DIR``.

Every module here is import-free of jax (stdlib only), like
``analysis.contracts``: the cores load standalone on containers where
the package itself cannot import.
"""

from . import diagnose  # the submodule: diagnose.diagnose/main/...
from . import postmortem  # the submodule: postmortem.analyze_dir/main/...
from .diagnose import diagnose_path, diff_reports
from .postmortem import analyze_dir as postmortem_dir
from .exporter import (
    MetricsExporter,
    aggregate_snapshots,
    build_snapshot,
    render_prometheus,
    validate_snapshot,
)
from .recorder import py_op
from .registry import Histogram, MetricsRegistry
from .schema import (
    EVENT_STRUCT,
    FLIGHT_FILE_GLOB,
    FLIGHT_VERSION,
    KIND_NAMES,
    PLANE_NAMES,
    RANK_FILE_SCHEMA,
    SCHEMA_VERSION,
    Event,
    SchemaError,
    check_begin_end_balance,
    check_step_balance,
    decode_events,
    encode_flight_file,
    format_recent_events,
    load_rank_file,
    load_trace,
    parse_snapshot,
    read_flight_file,
    validate_rank_file,
    validate_trace,
)
from .trace import merge_dir, merge_rank_objs, rank_to_chrome_events

__all__ = [
    "EVENT_STRUCT",
    "Event",
    "FLIGHT_FILE_GLOB",
    "FLIGHT_VERSION",
    "Histogram",
    "KIND_NAMES",
    "MetricsExporter",
    "MetricsRegistry",
    "PLANE_NAMES",
    "RANK_FILE_SCHEMA",
    "SCHEMA_VERSION",
    "SchemaError",
    "aggregate_snapshots",
    "build_snapshot",
    "check_begin_end_balance",
    "check_step_balance",
    "decode_events",
    "diagnose",
    "diagnose_path",
    "diff_reports",
    "encode_flight_file",
    "format_recent_events",
    "load_rank_file",
    "load_trace",
    "merge_dir",
    "merge_rank_objs",
    "parse_snapshot",
    "postmortem",
    "postmortem_dir",
    "py_op",
    "rank_to_chrome_events",
    "read_flight_file",
    "render_prometheus",
    "validate_rank_file",
    "validate_snapshot",
    "validate_trace",
]
