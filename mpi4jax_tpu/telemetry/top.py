"""``t4j-top``: render a job's comm telemetry as console tables.

    t4j-top DIR            # a --telemetry directory of rank<k>.t4j.json
    t4j-top rank0.t4j.json # one rank
    t4j-top DIR --follow 2 # live: re-render every 2s while a job runs
    t4j-top DIR --json     # machine-readable summary

Retrospective or live (``--follow`` re-reads the directory each tick —
ranks rewrite their files at exit, and long-running jobs can call
``mpi4jax_tpu.telemetry.dump.write_rank_file`` periodically), showing:

* per-op latency percentiles (p50/p99 from the metrics histograms) and
  byte totals per data plane — the measured per-comm x size numbers
  trace-guided autotuning consumes;
* per-link throughput (from trace-mode frame events) plus the
  self-healing reconnect/replay counters per link — the worst-link
  signal serving admission control keys on;
* the async progress engine's per-op queue depth (max/mean), engine
  busy time, and the per-rank overlap ratio — the share of engine comm
  time NOT covered by a caller blocked in wait (docs/async.md);
* per-rank totals (events, drops, faults).

Console-script twin of ``t4j-lint`` (pyproject.toml); import-free of
jax so it runs anywhere the files do.
"""

import argparse
import json
import pathlib
import sys
import time

from . import schema
from .postmortem import STALE_S as FLIGHT_STALE_S
from .registry import MetricsRegistry
from .trace import RANK_FILE_GLOB


def load_rank_objs(path, lenient=False):
    """Path (dir of rank files, or one rank file) -> list of validated
    rank objects.

    ``lenient`` (the ``--follow`` mode) skips files that fail to read
    or validate instead of raising: mid-job, rank files appear one at
    a time as ranks drain (late ranks simply have no file yet), and a
    non-atomic third-party writer can expose a torn file for one tick
    — the next re-read picks both up.  With every present file broken
    it still raises FileNotFoundError so the follow loop keeps
    waiting."""
    p = pathlib.Path(path)
    if p.is_dir():
        files = sorted(p.glob(RANK_FILE_GLOB))
        if not files:
            raise FileNotFoundError(f"no {RANK_FILE_GLOB} files in {p}")
        objs = []
        for f in files:
            try:
                objs.append(schema.load_rank_file(f))
            except (OSError, ValueError):  # SchemaError is a ValueError
                if not lenient:
                    raise
        if not objs:
            raise FileNotFoundError(
                f"no readable {RANK_FILE_GLOB} files in {p} (yet)"
            )
        return objs
    return [schema.load_rank_file(p)]


def load_flight_status(path, now_unix_ns=None):
    """Per-rank flight-recorder status from the raw ``.t4jflight``
    headers in a directory (docs/observability.md "flight recorder").

    Header-only reads — cheap enough for ``--follow`` — translated to
    wall time through each file's clock anchor, so a rank that is
    alive-but-wedged shows a fresh heartbeat while a dead one goes
    stale.  Newest boot incarnation wins per rank.  Returns ``{}``
    for a non-directory path or when no flight files exist."""
    p = pathlib.Path(path)
    if not p.is_dir():
        return {}
    now = time.time_ns() if now_unix_ns is None else now_unix_ns
    out = {}
    for f in sorted(p.glob(schema.FLIGHT_FILE_GLOB)):
        try:
            with open(f, "rb") as fh:
                hdr = schema.parse_flight_header(
                    fh.read(schema.FLIGHT_HEADER_BYTES))
            size = f.stat().st_size
        except (OSError, ValueError):
            continue  # torn/foreign file: skip, keep rendering
        rank = hdr["rank"]
        prev = out.get(rank)
        if prev and prev["boot_unix_ns"] > hdr["boot_unix_ns"]:
            continue
        age = None
        a = hdr["anchor"]
        if hdr["heartbeat_ns"] and a["mono_ns"] and a["unix_ns"]:
            hb_unix = hdr["heartbeat_ns"] - a["mono_ns"] + a["unix_ns"]
            age = max(0.0, (now - hb_unix) / 1e9)
        out[rank] = {
            "rank": rank,
            "path": str(f),
            "file_bytes": size,
            "heartbeat_age_s": round(age, 3) if age is not None else None,
            "heartbeat_count": hdr["heartbeat_count"],
            "finalized": hdr["finalized"],
            "epoch": hdr["epoch"],
            "boot_unix_ns": hdr["boot_unix_ns"],
            "stale": (age is not None and age > FLIGHT_STALE_S
                      and not hdr["finalized"]),
        }
    return out


def _fmt_ms(v):
    return "-" if v is None else f"{v:9.3f}"


def _fmt_bytes(v):
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if v < 1024 or unit == "TB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024.0
    return f"{v:.1f}TB"


def summarize(rank_objs, flight=None):
    """The data model behind both renderings (table and --json).
    ``flight`` is :func:`load_flight_status`'s per-rank dict; ranks
    that only have a flight file (still running, wedged, or dead
    before any drain) still get a row, so a live ``--follow`` shows
    them instead of silently omitting the most interesting rank."""
    flight = flight or {}
    reg = MetricsRegistry()
    per_rank = []
    links = {}
    wire_by_rank = {}  # rank -> compressed-collective state
    async_rows = {}  # (rank, op) -> accumulators
    for obj in rank_objs:
        reg.merge(MetricsRegistry.from_snapshot(obj["metrics"]))
        rank = int(obj["rank"])
        events = [schema.event_from_list(r) for r in obj["events"]]
        faults = sum(1 for e in events
                     if e.kind == schema.KIND_IDS["fault"])
        # elastic membership (docs/failure-semantics.md): resize_done
        # carries the committed epoch in bytes and the new member
        # count in peer; rank_dead names each departure
        resizes = sum(1 for e in events
                      if e.kind == schema.RESIZE_BEGIN_KIND)
        dones = [e for e in events if e.kind == schema.RESIZE_DONE_KIND]
        world_epoch = int(dones[-1].bytes) if dones else 0
        world_size = int(dones[-1].peer) if dones else None
        dead_ranks = sorted({int(e.peer) for e in events
                             if e.kind == schema.RANK_DEAD_KIND})
        t_lo = min((e.t_ns for e in events), default=0)
        t_hi = max((e.t_ns for e in events), default=0)
        span_s = (t_hi - t_lo) / 1e9 if t_hi > t_lo else 0.0
        for e in events:
            if e.kind == schema.KIND_IDS["frame_tx"] and e.peer >= 0:
                link = links.setdefault(
                    (rank, e.peer),
                    {"bytes": 0, "frames": 0, "t_lo": e.t_ns,
                     "t_hi": e.t_ns},
                )
                link["bytes"] += e.bytes
                link["frames"] += 1
                link["t_lo"] = min(link["t_lo"], e.t_ns)
                link["t_hi"] = max(link["t_hi"], e.t_ns)
        # async progress engine (docs/async.md): queue depth rides the
        # events' peer field, op_complete's bytes is the execution
        # duration in ns (schema.py ASYNC_KINDS field overloads)
        for e in events:
            if e.kind not in schema.ASYNC_KINDS:
                continue
            op, _comm = schema.decode_async_comm(e.comm)
            row = async_rows.setdefault(
                (rank, op),
                {"submitted": 0, "completed": 0, "max_depth": 0,
                 "depth_sum": 0, "depth_n": 0, "busy_ns": 0},
            )
            row["max_depth"] = max(row["max_depth"], max(e.peer, 0))
            row["depth_sum"] += max(e.peer, 0)
            row["depth_n"] += 1
            if e.kind == schema.KIND_IDS["op_queued"]:
                row["submitted"] += 1
            elif e.kind == schema.KIND_IDS["op_complete"]:
                row["completed"] += 1
                row["busy_ns"] += e.bytes
        # caller-side blocked time (python lane, trace mode): EVERY
        # comm-op bracket occupies its caller — a blocking collective
        # (routed through the engine as submit+wait) for its whole
        # span, a submit for its (tiny) handoff, a wait for the
        # residual comm the caller failed to hide.  Of the engine's
        # busy time, the share NOT covered by a caller inside some comm
        # bracket is comm overlapped with the callers' own compute.
        # (Counting only "wait" spans here scored fully-blocking
        # workloads as 100% overlapped — the inverse of the truth.)
        blocked_ns = 0
        begins = {}
        for row_ in obj.get("py_events", ()):
            t_ns, name, phase, _nb = row_[0], row_[1], row_[2], row_[3]
            if phase == 1:
                begins[name] = t_ns
            elif phase == 2 and name in begins:
                blocked_ns += max(0, t_ns - begins.pop(name))
        rank_busy = sum(v["busy_ns"] for (r, _o), v in async_rows.items()
                        if r == rank)
        overlap_pct = None
        if rank_busy > 0 and obj.get("py_events"):
            overlap_pct = max(0.0, min(1.0, 1.0 - blocked_ns / rank_busy))
            overlap_pct = round(100.0 * overlap_pct, 1)
        for (r, _o), v in async_rows.items():
            if r == rank:
                v["overlap_pct"] = overlap_pct
        # compressed-collective state (docs/performance.md "Compressed
        # collectives"): the rank dump's tuning.wire record carries the
        # effective mode plus logical-vs-wire byte counters, so the
        # console can PROVE the wire saving rather than assert the knob
        tun = obj.get("tuning") or {}
        w = tun.get("wire") or {}
        mode = w.get("wire_dtype") or tun.get("wire_dtype") or "off"
        logical = int(w.get("wire_logical_bytes") or 0)
        on_wire = int(w.get("wire_bytes") or 0)
        wire_by_rank[rank] = {
            "wire_dtype": mode,
            "wire_logical_bytes": logical,
            "wire_bytes": on_wire,
            "ratio": round(logical / on_wire, 2) if on_wire else None,
        }
        per_peer = (obj.get("link_stats") or {}).get("per_peer", {})
        for peer, s in per_peer.items():
            link = links.setdefault(
                (rank, int(peer)),
                {"bytes": 0, "frames": 0, "t_lo": 0, "t_hi": 0},
            )
            # striped links (docs/performance.md "striped links"):
            # keep the per-stripe breakdown so the console can show
            # width and point at THE stripe that repaired/replayed
            stripes = s.get("stripes") or []
            link.update(
                reconnects=s.get("reconnects", 0),
                replayed_frames=s.get("replayed_frames", 0),
                replayed_bytes=s.get("replayed_bytes", 0),
                tx_syscalls=s.get("tx_syscalls", 0),
                rx_syscalls=s.get("rx_syscalls", 0),
                state=s.get("state", 0),
                stripes=len(stripes),
                stripe_detail=stripes,
            )
        per_rank.append({
            "rank": rank,
            "mode": obj["mode"],
            "events": len(events),
            "py_events": len(obj["py_events"]),
            "dropped": int(obj.get("dropped", 0)),
            "faults": faults,
            "span_s": span_s,
            "reconnects": ((obj.get("link_stats") or {})
                           .get("aggregate") or {}).get("reconnects", 0),
            "resizes": resizes,
            "world_epoch": world_epoch,
            "world_size": world_size,
            "dead_ranks": dead_ranks,
            "flight": flight.get(rank),
        })
    # flight-only ranks (no drained file yet — running, wedged, or
    # hard-dead): surface them instead of hiding the problem rank
    drained_ranks = {r["rank"] for r in per_rank}
    for rank, st in sorted(flight.items()):
        if rank in drained_ranks:
            continue
        per_rank.append({
            "rank": rank, "mode": "-", "events": 0, "py_events": 0,
            "dropped": 0, "faults": 0, "span_s": 0.0, "reconnects": 0,
            "resizes": 0, "world_epoch": st["epoch"], "world_size": None,
            "dead_ranks": [], "flight": st,
        })
    per_rank.sort(key=lambda r: r["rank"])
    ops = []
    for op in reg.ops():
        for plane in sorted({p for (_c, o, p) in reg.rows if o == op}):
            row = reg.aggregate(op=op, plane=plane)
            stats = row.stats()
            stats.update(op=op, plane=plane)
            ops.append(stats)
    link_rows = []
    for (rank, peer), link in sorted(links.items()):
        span = (link["t_hi"] - link["t_lo"]) / 1e9
        detail = link.get("stripe_detail") or []
        # the stripe carrying the repairs, when exactly attributable
        hot = [i for i, s in enumerate(detail) if s.get("reconnects")]
        link_rows.append({
            "rank": rank,
            "peer": peer,
            "bytes": link["bytes"],
            "frames": link["frames"],
            "gbps": link["bytes"] / span / 1e9 if span > 0 else None,
            "reconnects": link.get("reconnects", 0),
            "replayed_frames": link.get("replayed_frames", 0),
            # kernel crossings made by the wire threads (native
            # counters, docs/performance.md "io_uring wire backend");
            # sys/frame is what the uring backend is supposed to cut
            "tx_syscalls": link.get("tx_syscalls", 0),
            "rx_syscalls": link.get("rx_syscalls", 0),
            "syscalls_per_frame": (
                round(link.get("tx_syscalls", 0) / link["frames"], 2)
                if link["frames"] else None
            ),
            "state": link.get("state", 0),
            "stripes": link.get("stripes", 0),
            "hot_stripe": hot[0] if len(hot) == 1 else None,
            "stripe_detail": detail,
            # the SENDING rank's compression state: downcast happens on
            # the tx side, so that is whose counters describe this link
            "wire": wire_by_rank.get(rank),
        })
    async_out = []
    for (rank, op), v in sorted(async_rows.items()):
        async_out.append({
            "rank": rank,
            "op": op,
            "submitted": v["submitted"],
            "completed": v["completed"],
            "max_depth": v["max_depth"],
            # None (rendered "-"), not 0.0: a row whose events carried
            # no depth samples has an UNKNOWN queue depth — zero would
            # read as "measured empty" in the --json consumer
            "mean_depth": round(v["depth_sum"] / v["depth_n"], 2)
            if v["depth_n"] else None,
            "busy_ms": round(v["busy_ns"] / 1e6, 3),
            "overlap_pct": v.get("overlap_pct"),
        })
    # serving gauges (docs/serving.md): the frontend's (lowest
    # serving rank's) block owns queue/shed/SLO truth; followers only
    # corroborate occupancy
    serving = {}
    for obj in sorted(rank_objs, key=lambda o: int(o["rank"])):
        sv = obj.get("serving") or {}
        if sv:
            serving = dict(sv)
            serving["rank"] = int(obj["rank"])
            break
    return {
        "ranks": per_rank,
        "ops": ops,
        "links": link_rows,
        "async": async_out,
        "bytes_by_plane": reg.bytes_by_plane(),
        "flight": {str(r): st for r, st in sorted(flight.items())},
        "serving": serving,
    }


_STATE_NAMES = {0: "up", 1: "broken", 2: "dead"}


def render(summary):
    out = []
    ranks = summary["ranks"]
    out.append(
        f"t4j-top — {len(ranks)} rank(s), "
        f"{sum(r['events'] for r in ranks)} native event(s), "
        f"{sum(r['dropped'] for r in ranks)} dropped"
    )
    plane = summary["bytes_by_plane"]
    if plane:
        out.append("  plane bytes: " + "  ".join(
            f"{k}={_fmt_bytes(v)}" for k, v in sorted(plane.items())
        ))
    resized = [r for r in ranks if r.get("world_epoch")]
    if resized:
        r = max(resized, key=lambda x: x["world_epoch"])
        departed = ", ".join(f"r{d}" for d in r.get("dead_ranks", []))
        members = r["world_size"] if r["world_size"] is not None else "?"
        out.append(
            f"  elastic: world epoch {r['world_epoch']}, "
            f"{members} member(s); departed: {departed or '-'}"
        )
    # flight-recorder status in the membership line: heartbeat age
    # tells a wedged-but-alive rank (fresh beat, no progress) from a
    # dead one (STALE) while the job still runs
    flight = summary.get("flight") or {}
    if flight:
        parts = []
        for key in sorted(flight, key=int):
            st = flight[key]
            if st["finalized"]:
                word = "done"
            elif st["stale"]:
                word = "STALE"
            elif st["heartbeat_age_s"] is not None:
                word = f"live {st['heartbeat_age_s']:.1f}s"
            else:
                word = "live"
            parts.append(
                f"r{key} {word} {_fmt_bytes(st['file_bytes'])}"
            )
        out.append("  flight: " + " | ".join(parts))
    sv = summary.get("serving") or {}
    if sv:
        # serving line (docs/serving.md): queue/occupancy/shed and
        # p99 against the SLO, from the frontend's published gauges
        p99 = sv.get("latency_p99_ms")
        slo = sv.get("slo_ms")
        vs = ("-" if p99 is None
              else f"{p99:.0f}ms" + (f"/{slo:.0f}ms SLO" if slo else ""))
        att = sv.get("slo_attainment")
        out.append(
            f"  serving: admit={sv.get('admit_mode', '?')} queue "
            f"{sv.get('queue_depth', 0)} occupancy "
            f"{sv.get('batch_occupancy', 0)}/{sv.get('max_batch', '?')}"
            f" done {sv.get('completed', 0)} shed {sv.get('shed', 0)}"
            f" p99 {vs}"
            + (f" attain {att:.2f}" if att is not None else "")
        )
    if summary["ops"]:
        out.append("")
        out.append(f"  {'op':<16}{'plane':<7}{'count':>8}{'bytes':>10}"
                   f"{'p50 ms':>10}{'p99 ms':>10}{'max ms':>10}")
        for s in summary["ops"]:
            out.append(
                f"  {s['op']:<16}{s['plane']:<7}{s['count']:>8}"
                f"{_fmt_bytes(s['bytes']):>10}"
                f" {_fmt_ms(s['p50_ms'])}{_fmt_ms(s['p99_ms'])}"
                f"{_fmt_ms(s['max_ms'])}"
            )
    if summary.get("async"):
        out.append("")
        out.append(f"  {'async op':<18}{'rank':>5}{'subm':>7}{'done':>7}"
                   f"{'maxQ':>6}{'meanQ':>7}{'busy ms':>10}"
                   f"{'overlap%':>10}")
        for a in summary["async"]:
            # pure-blocking traces (or drains that raced the engine)
            # can leave overlap/queue-depth unknown: render "-", never
            # a fabricated number
            ov = "-" if a["overlap_pct"] is None else f"{a['overlap_pct']:.1f}"
            md = ("-" if a["mean_depth"] is None
                  else f"{a['mean_depth']:.2f}")
            out.append(
                f"  {a['op']:<18}r{a['rank']:<4}{a['submitted']:>7}"
                f"{a['completed']:>7}{a['max_depth']:>6}"
                f"{md:>7}{a['busy_ms']:>10.3f}{ov:>10}"
            )
    if summary["links"]:
        out.append("")
        out.append(f"  {'link':<12}{'bytes':>10}{'frames':>8}"
                   f"{'GB/s':>8}{'stripes':>8}{'reconn':>8}"
                   f"{'replay':>8}{'txsys':>8}{'rxsys':>8}"
                   f"{'sys/fr':>8}{'state':>8}{'wire:':>12}")
        for link in summary["links"]:
            gbps = ("-" if link["gbps"] is None
                    else f"{link['gbps']:.3f}")
            # width, plus the one stripe that repaired when exactly
            # attributable ("2:s1" = 2 stripes, stripe 1 repaired)
            nstripes = link.get("stripes", 0)
            stripes = "-" if not nstripes else str(nstripes)
            if link.get("hot_stripe") is not None:
                stripes += f":s{link['hot_stripe']}"
            # compression on the tx side: mode plus the measured
            # logical/wire ratio ("bf16 2.00x"); "-" = uncompressed f32
            wi = link.get("wire") or {}
            if wi.get("wire_dtype", "off") == "off":
                wire = "-"
            elif wi.get("ratio"):
                wire = f"{wi['wire_dtype']} {wi['ratio']:.2f}x"
            else:
                wire = wi["wire_dtype"]
            spf = link.get("syscalls_per_frame")
            out.append(
                f"  r{link['rank']}->r{link['peer']:<8}"
                f"{_fmt_bytes(link['bytes']):>10}{link['frames']:>8}"
                f"{gbps:>8}{stripes:>8}{link['reconnects']:>8}"
                f"{link['replayed_frames']:>8}"
                f"{link.get('tx_syscalls', 0):>8}"
                f"{link.get('rx_syscalls', 0):>8}"
                f"{'-' if spf is None else f'{spf:.2f}':>8}"
                f"{_STATE_NAMES.get(link['state'], '?'):>8}"
                f"{wire:>12}"
            )
    if summary["ranks"]:
        out.append("")
        out.append(f"  {'rank':<6}{'mode':<10}{'events':>8}{'py':>6}"
                   f"{'dropped':>9}{'reconn':>8}{'faults':>8}"
                   f"{'span s':>9}")
        for r in summary["ranks"]:
            out.append(
                f"  r{r['rank']:<5}{r['mode']:<10}{r['events']:>8}"
                f"{r['py_events']:>6}{r['dropped']:>9}"
                f"{r['reconnects']:>8}{r['faults']:>8}"
                f"{r['span_s']:>9.2f}"
            )
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="t4j-top",
        description="render mpi4jax_tpu comm telemetry "
                    "(docs/observability.md)",
    )
    ap.add_argument("path", help="--telemetry directory or one "
                                 "rank<k>.t4j.json")
    ap.add_argument("--follow", type=float, default=None, metavar="SECS",
                    help="live mode: re-read and re-render every SECS")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable summary instead of "
                         "tables")
    args = ap.parse_args(argv)
    while True:
        flight = load_flight_status(args.path)
        try:
            summary = summarize(
                load_rank_objs(args.path, lenient=args.follow is not None),
                flight=flight,
            )
        except FileNotFoundError as e:
            if flight:
                # no drained rank file yet, but live flight headers
                # exist (the job is still running, or died hard before
                # any drain): render what the recorder knows
                summary = summarize([], flight=flight)
            elif args.follow is None:
                print(f"t4j-top: {e}", file=sys.stderr)
                return 2
            else:
                summary = None
        except (OSError, ValueError) as e:
            # --follow mid-job: a single-file path can be mid-write by
            # a non-atomic writer; report and keep following
            if args.follow is None:
                print(f"t4j-top: {e}", file=sys.stderr)
                return 2
            print(f"t4j-top: transient read failure, retrying: {e}",
                  file=sys.stderr)
            summary = None
        if summary is not None:
            if args.json:
                print(json.dumps(summary))
            else:
                if args.follow is not None:
                    print("\x1b[2J\x1b[H", end="")
                print(render(summary), flush=True)
        if args.follow is None:
            return 0
        time.sleep(args.follow)


if __name__ == "__main__":
    sys.exit(main())
