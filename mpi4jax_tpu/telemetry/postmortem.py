"""``t4j-postmortem``: cross-rank death analysis from surviving evidence.

    t4j-postmortem DIR             # a --telemetry directory
    t4j-postmortem DIR --json      # machine-readable report
    t4j-postmortem DIR --window 10 # merge only the last 10s

The retrospective counterpart of ``t4j-top``/``t4j-diagnose`` for jobs
that did NOT end cooperatively (docs/observability.md "flight
recorder"): a SIGKILL'd, segfaulted or OOM-killed rank never runs its
telemetry drain, so its ``rank<k>.t4j.json`` does not exist — but with
``T4J_FLIGHT=on`` its event ring, metrics table and header live in a
crash-consistent mmap'd ``rank<k>-<boot>.t4jflight`` file whose seqlock
slot tickets let this reader validate and recover the tail without any
cooperation from the (dead) writer.

Given a flight directory this module loads BOTH artifact kinds —
survivors' drained rank files and dead ranks' raw flight files —
validates/recovers truncated tails, merges the last N seconds onto one
job-relative timeline (every rank's monotonic clock pinned through its
bootstrap anchor), and names:

* the first-failing rank, how it died (hard kill vs clean exit vs
  still alive-but-wedged, told apart by the finalize flag and the
  heartbeat age), and when;
* its last in-flight op (open op-scope spans), step marker, and wire
  activity (last frame tx/rx peers = the affected links);
* each surviving peer's view of the break (link_break / reconnect /
  link_dead / rank_dead events naming the victim);
* whether the death preceded or followed an elastic resize epoch
  (docs/failure-semantics.md "elastic membership").

``launch.py`` runs this automatically under the first-failure report
when a telemetry dir is configured.  Import-free of jax (stdlib only),
like the rest of the package.
"""

import argparse
import json
import pathlib
import sys
import time

from . import schema
from .trace import RANK_FILE_GLOB

# A heartbeat older than this (vs the analysis instant) means the
# process is gone; younger means alive — possibly wedged, which is
# exactly what the caller wants surfaced.  The native side bumps at
# least every ~200ms while any thread polls, so 5s is generous.
STALE_S = 5.0

DEFAULT_WINDOW_S = 30.0

_PEER_VIEW_KINDS = ("link_break", "reconnect", "link_dead", "rank_dead")


# ---- loading -------------------------------------------------------------


def load_dir(path, flight_dir=None):
    """Read every artifact in a flight/telemetry directory.

    ``flight_dir`` names a SEPARATE flight-recorder directory when the
    job split them (an explicit ``T4J_FLIGHT_DIR`` next to the
    launcher's ``--telemetry DIR``); flight files are read from both.

    Returns ``{"drained": {rank: rank_obj}, "flights": {rank:
    [flight_obj, ...]}}`` — flights sorted oldest boot first, so
    ``[-1]`` is the current incarnation (restarts and rejoins leave
    their dead predecessors' files behind on purpose)."""
    p = pathlib.Path(path)
    drained = {}
    for f in sorted(p.glob(RANK_FILE_GLOB)):
        try:
            obj = schema.load_rank_file(f)
        except (OSError, ValueError):
            continue  # torn mid-write: the flight file still speaks
        drained[int(obj["rank"])] = obj
    flight_paths = sorted(p.glob(schema.FLIGHT_FILE_GLOB))
    if flight_dir is not None:
        fp = pathlib.Path(flight_dir)
        if fp.resolve() != p.resolve():
            flight_paths += sorted(fp.glob(schema.FLIGHT_FILE_GLOB))
    flights = {}
    for f in flight_paths:
        try:
            obj = schema.read_flight_file(f)
        except (OSError, ValueError):
            continue
        flights.setdefault(int(obj["rank"]), []).append(obj)
    for objs in flights.values():
        objs.sort(key=lambda o: o["boot_unix_ns"])
    return {"drained": drained, "flights": flights}


def _to_unix(t_ns, anchor):
    mono = int(anchor.get("mono_ns", 0))
    unix = int(anchor.get("unix_ns", 0))
    if not mono or not unix:
        return None
    return int(t_ns) - mono + unix


def _rank_events(drained_obj, flight_obj):
    """Union of a rank's drained and flight events (the drain CONSUMES
    the ring but the mapped slots retain everything, so the two
    overlap), deduped on the full record, publish/time order."""
    seen = set()
    out = []
    for e in (flight_obj["events"] if flight_obj else []):
        t = tuple(e)
        if t not in seen:
            seen.add(t)
            out.append(e)
    if drained_obj:
        for row in drained_obj["events"]:
            e = schema.event_from_list(row)
            t = tuple(e)
            if t not in seen:
                seen.add(t)
                out.append(e)
    out.sort(key=lambda e: e.t_ns)
    return out


# ---- per-rank evidence ---------------------------------------------------


def _last_inflight(events):
    """What was open when the stream stopped: per-lane LIFO op spans
    (the begin/end discipline check_begin_end_balance enforces), the
    open step marker, and the last wire frames."""
    stacks = {}
    open_step = None
    last_step = None
    last_tx = last_rx = None
    queued = completed = 0
    last_async = None
    for e in events:
        if e.kind in schema.OP_KINDS:
            stack = stacks.setdefault(e.lane, [])
            if e.phase == schema.PHASE_BEGIN:
                stack.append(e)
            elif e.phase == schema.PHASE_END and stack \
                    and stack[-1].kind == e.kind:
                stack.pop()
        elif e.kind == schema.STEP_KIND:
            if e.phase == schema.PHASE_BEGIN:
                open_step = int(e.bytes)
                last_step = int(e.bytes)
            elif e.phase == schema.PHASE_END:
                open_step = None
        elif e.kind == schema.KIND_IDS["frame_tx"]:
            last_tx = e
        elif e.kind == schema.KIND_IDS["frame_rx"]:
            last_rx = e
        elif e.kind in schema.ASYNC_KINDS:
            name = schema.KIND_NAMES[e.kind]
            if name == "op_queued":
                queued += 1
            elif name == "op_complete":
                completed += 1
            else:
                last_async = e
    open_ops = [e for stack in stacks.values() for e in stack]
    open_ops.sort(key=lambda e: e.t_ns)
    ops = [{
        "op": schema.kind_name(e.kind),
        "t_ns": e.t_ns,
        "comm": e.comm,
        "peer": e.peer,
        "bytes": e.bytes,
        "plane": schema.plane_name(e.plane),
    } for e in open_ops]
    links = sorted({e.peer for e in open_ops if e.peer >= 0}
                   | ({last_tx.peer} if last_tx and last_tx.peer >= 0
                      else set())
                   | ({last_rx.peer} if last_rx and last_rx.peer >= 0
                      else set()))
    out = {
        "ops": ops,
        "step": open_step,
        "last_step": last_step,
        "links": links,
        "inflight_async": max(0, queued - completed),
    }
    if last_tx:
        out["last_frame_tx"] = {"peer": last_tx.peer, "t_ns": last_tx.t_ns,
                                "bytes": last_tx.bytes}
    if last_rx:
        out["last_frame_rx"] = {"peer": last_rx.peer, "t_ns": last_rx.t_ns,
                                "bytes": last_rx.bytes}
    if last_async is not None:
        op, _comm = schema.decode_async_comm(last_async.comm)
        out["last_async_op"] = op
    return out


def _rank_evidence(rank, drained_obj, flight_obj, now_unix_ns, stale_s):
    flight_hdr = None
    anchor = {"mono_ns": 0, "unix_ns": 0}
    if flight_obj:
        anchor = flight_obj["anchor"]
        flight_hdr = {k: flight_obj[k] for k in (
            "epoch", "boot_unix_ns", "boot_token", "finalized",
            "heartbeat_ns", "heartbeat_count", "dropped", "torn_slots",
            "recovered_events", "file_bytes", "path", "mode")}
    elif drained_obj:
        anchor = drained_obj["anchor"]
    events = _rank_events(drained_obj, flight_obj)
    last_event_unix = None
    if events:
        last_event_unix = _to_unix(events[-1].t_ns, anchor)
    heartbeat_unix = None
    if flight_obj and flight_obj["heartbeat_ns"]:
        heartbeat_unix = _to_unix(flight_obj["heartbeat_ns"], anchor)
    # classification: a drained rank file proves a cooperative exit
    # (the abort path and atexit both write it); a finalized flight
    # header proves the native teardown ran; everything else is dead
    # or — heartbeat still fresh — alive-but-unaccounted-for
    if drained_obj is not None:
        verdict = "drained"
    elif flight_obj is None:
        verdict = "no-evidence"
    elif flight_obj["finalized"]:
        verdict = "finalized"
    else:
        age_s = None
        if heartbeat_unix is not None:
            age_s = (now_unix_ns - heartbeat_unix) / 1e9
        verdict = "alive" if age_s is not None and age_s < stale_s \
            else "dead"
    evid = []
    if heartbeat_unix is not None:
        evid.append(heartbeat_unix)
    if last_event_unix is not None:
        evid.append(last_event_unix)
    epoch = 0
    if flight_obj:
        epoch = flight_obj["epoch"]
    return {
        "rank": rank,
        "verdict": verdict,
        "sources": ([] if drained_obj is None else ["drained"])
        + ([] if flight_obj is None else ["flight"]),
        "epoch": epoch,
        "anchor": dict(anchor),
        "flight": flight_hdr,
        "events": events,
        "last_event_unix_ns": last_event_unix,
        "heartbeat_unix_ns": heartbeat_unix,
        "last_evidence_unix_ns": max(evid) if evid else None,
        "inflight": _last_inflight(events),
    }


# ---- the analysis --------------------------------------------------------


def _peer_views(ranks, victim):
    """Each other rank's control events naming the victim, plus the
    resize instants — the peers' side of the break."""
    views = {}
    for r, ev in ranks.items():
        if r == victim:
            continue
        rows = []
        for e in ev["events"]:
            name = schema.KIND_NAMES.get(e.kind)
            if name in _PEER_VIEW_KINDS and e.peer == victim:
                rows.append({"kind": name, "t_ns": e.t_ns,
                             "t_unix_ns": _to_unix(e.t_ns, ev["anchor"]),
                             "bytes": e.bytes})
            elif name in ("resize_begin", "resize_done"):
                rows.append({"kind": name, "t_ns": e.t_ns,
                             "t_unix_ns": _to_unix(e.t_ns, ev["anchor"]),
                             "epoch": int(e.bytes),
                             "members": (int(e.peer)
                                         if name == "resize_done"
                                         else None)})
        if rows:
            views[r] = rows
    return views


def _resize_relation(victim_ev, peer_views, death_unix_ns):
    """Order the death against the elastic resize epochs the survivors
    observed.  Returns (relation dict or None)."""
    resizes = {}
    for rows in peer_views.values():
        for row in rows:
            if row["kind"] not in ("resize_begin", "resize_done"):
                continue
            rec = resizes.setdefault(row["epoch"], {})
            key = "begin_unix_ns" if row["kind"] == "resize_begin" \
                else "done_unix_ns"
            t = row["t_unix_ns"]
            if t is not None and (key not in rec or t < rec[key]):
                rec[key] = t
            if row.get("members") is not None:
                rec["members"] = row["members"]
    if not resizes:
        return None
    victim_epoch = victim_ev["epoch"]
    removing = min((e for e in resizes if e > victim_epoch),
                   default=None)
    out = {
        "victim_epoch": victim_epoch,
        "epochs": {str(e): rec for e, rec in sorted(resizes.items())},
        "removing_epoch": removing,
    }
    if removing is not None and death_unix_ns is not None:
        begin = resizes[removing].get("begin_unix_ns")
        if begin is not None:
            out["death_preceded_resize"] = bool(death_unix_ns <= begin)
            out["death_to_resize_ms"] = round(
                (begin - death_unix_ns) / 1e6, 3)
    if victim_epoch > 0:
        out["death_followed_epoch"] = victim_epoch
    return out


def analyze(loaded, window_s=DEFAULT_WINDOW_S, now_unix_ns=None,
            stale_s=STALE_S):
    """The report dict behind both renderings (tables and --json)."""
    if now_unix_ns is None:
        now_unix_ns = time.time_ns()
    all_ranks = sorted(set(loaded["drained"]) | set(loaded["flights"]))
    ranks = {}
    for r in all_ranks:
        flights = loaded["flights"].get(r, [])
        ranks[r] = _rank_evidence(
            r, loaded["drained"].get(r), flights[-1] if flights else None,
            now_unix_ns, stale_s)
        ranks[r]["incarnations"] = len(flights)
    world = max(
        [int(o["world"]) for o in loaded["drained"].values()]
        + [o["world"] for fl in loaded["flights"].values() for o in fl]
        + [len(all_ranks)],
        default=0,
    )
    dead = [r for r in all_ranks if ranks[r]["verdict"] == "dead"]
    wedged = [r for r in all_ranks if ranks[r]["verdict"] == "alive"]
    # the first failure: among hard deaths, the one whose evidence
    # stops earliest (heartbeats tick every <=200ms while alive, so
    # the freshest surviving word is within a beat of the death)
    first = None
    if dead:
        def death_key(r):
            t = ranks[r]["last_evidence_unix_ns"]
            return (0, t) if t is not None else (1, r)

        first = min(dead, key=death_key)
    elif wedged:
        first = min(
            wedged, key=lambda r: ranks[r]["last_event_unix_ns"] or 0)
    # corroboration: who do the survivors accuse? (link_break /
    # link_dead / rank_dead events naming a peer)
    accusations = {}
    for r, ev in ranks.items():
        for e in ev["events"]:
            if schema.KIND_NAMES.get(e.kind) in ("link_break",
                                                 "link_dead",
                                                 "rank_dead") \
                    and e.peer >= 0 and e.peer != r:
                accusations[e.peer] = accusations.get(e.peer, 0) + 1
    most_accused = max(accusations, key=lambda k: accusations[k]) \
        if accusations else None
    if first is None and most_accused is not None:
        first = most_accused
    peer_views = _peer_views(ranks, first) if first is not None else {}
    death_unix = ranks[first]["last_evidence_unix_ns"] \
        if first is not None and first in ranks else None
    resize = _resize_relation(ranks[first], peer_views, death_unix) \
        if first is not None and first in ranks else None
    # job-relative timeline of the last window_s seconds, all ranks
    t0 = min((ev["anchor"]["unix_ns"] for ev in ranks.values()
              if ev["anchor"].get("unix_ns")), default=None)
    t_hi = max((ev["last_evidence_unix_ns"] or 0
                for ev in ranks.values()), default=0)
    cutoff = t_hi - int(window_s * 1e9) if window_s else None
    timeline = []
    for r, ev in ranks.items():
        for e in ev["events"]:
            tu = _to_unix(e.t_ns, ev["anchor"])
            if tu is None or (cutoff is not None and tu < cutoff):
                continue
            if e.kind in schema.CONTROL_KINDS \
                    or e.kind == schema.STEP_KIND:
                desc = schema.kind_name(e.kind)
                if e.kind == schema.STEP_KIND:
                    desc += (" begin" if e.phase == schema.PHASE_BEGIN
                             else " end") + f" #{e.bytes}"
                elif e.peer >= 0:
                    desc += f" peer=r{e.peer}"
                if e.kind in (schema.RESIZE_BEGIN_KIND,
                              schema.RESIZE_DONE_KIND):
                    desc += f" epoch={e.bytes}"
                timeline.append({
                    "t_unix_ns": tu,
                    "t_rel_s": round((tu - t0) / 1e9, 3)
                    if t0 else None,
                    "rank": r,
                    "event": desc,
                })
    timeline.sort(key=lambda row: row["t_unix_ns"])
    report = {
        "schema": "t4j-postmortem-v1",
        "world": world,
        "ranks_with_evidence": len(all_ranks),
        "window_s": window_s,
        "t0_unix_ns": t0,
        "verdicts": {str(r): ranks[r]["verdict"] for r in all_ranks},
        "dead_ranks": dead,
        "wedged_ranks": wedged,
        "first_failing_rank": first,
        "accusations": {str(k): v for k, v in sorted(
            accusations.items())},
        "peer_views": {str(r): rows for r, rows in peer_views.items()},
        "resize": resize,
        "timeline": timeline[-200:],
        "ranks": {},
    }
    if first is not None and first not in ranks:
        # accused by every survivor but left no file at all (flight
        # recorder off, or the file location was lost with the host)
        report["verdicts"][str(first)] = "no-evidence"
        report["ranks"][str(first)] = {
            "verdict": "no-evidence", "sources": [], "incarnations": 0,
            "epoch": 0, "events": 0, "last_evidence_rel_s": None,
            "heartbeat_age_s": None, "heartbeat_count": None,
            "torn_slots": 0, "dropped": 0,
            "inflight": {"ops": [], "step": None, "last_step": None,
                         "inflight_async": 0},
            "affected_links": [],
        }
    for r in all_ranks:
        ev = ranks[r]
        inflight = dict(ev["inflight"])
        inflight.pop("links", None)
        report["ranks"][str(r)] = {
            "verdict": ev["verdict"],
            "sources": ev["sources"],
            "incarnations": ev["incarnations"],
            "epoch": ev["epoch"],
            "events": len(ev["events"]),
            "last_evidence_rel_s": round(
                (ev["last_evidence_unix_ns"] - t0) / 1e9, 3)
            if t0 and ev["last_evidence_unix_ns"] else None,
            "heartbeat_age_s": round(
                (now_unix_ns - ev["heartbeat_unix_ns"]) / 1e9, 3)
            if ev["heartbeat_unix_ns"] else None,
            "heartbeat_count": (ev["flight"] or {}).get(
                "heartbeat_count"),
            "torn_slots": (ev["flight"] or {}).get("torn_slots", 0),
            "dropped": (ev["flight"] or {}).get("dropped", 0),
            "inflight": inflight,
            "affected_links": ev["inflight"]["links"],
        }
    return report


def analyze_dir(path, window_s=DEFAULT_WINDOW_S, now_unix_ns=None,
                stale_s=STALE_S, flight_dir=None):
    """Load + analyze a flight/telemetry directory (``flight_dir``:
    optional separate flight-file location, see :func:`load_dir`);
    raises FileNotFoundError when it holds no evidence at all."""
    loaded = load_dir(path, flight_dir=flight_dir)
    if not loaded["drained"] and not loaded["flights"]:
        raise FileNotFoundError(
            f"no {RANK_FILE_GLOB} or {schema.FLIGHT_FILE_GLOB} files "
            f"in {path}"
        )
    return analyze(loaded, window_s=window_s, now_unix_ns=now_unix_ns,
                   stale_s=stale_s)


# ---- rendering -----------------------------------------------------------


def _rel(report, t_unix_ns):
    t0 = report.get("t0_unix_ns")
    if t0 is None or t_unix_ns is None:
        return "?"
    return f"+{(t_unix_ns - t0) / 1e9:.3f}s"


def summary_lines(report):
    """The compact first-failure lines (what launch.py prints under
    its report): who died, what it was doing, who saw it, resize
    ordering."""
    out = []
    first = report["first_failing_rank"]
    if first is None:
        out.append(
            f"no hard deaths: {report['ranks_with_evidence']} rank(s) "
            "accounted for "
            f"({', '.join(sorted(set(report['verdicts'].values())))})"
        )
        return out
    rk = report["ranks"][str(first)]
    how = {"dead": "died hard (no drain; flight heartbeat stopped)",
           "alive": "alive but wedged (heartbeat fresh, no progress)",
           "drained": "exited with a drained telemetry file",
           "finalized": "finalized without a drained file",
           "no-evidence": "left no evidence"}.get(rk["verdict"],
                                                  rk["verdict"])
    when = (f" at +{rk['last_evidence_rel_s']}s"
            if rk["last_evidence_rel_s"] is not None else "")
    out.append(f"first failure: rank {first} — {how}{when} "
               f"[epoch {rk['epoch']}, evidence: "
               f"{'+'.join(rk['sources']) or 'none'}]")
    inflight = rk["inflight"]
    if inflight["ops"]:
        op = inflight["ops"][-1]
        peer = f" peer=r{op['peer']}" if op["peer"] >= 0 else ""
        out.append(
            f"  last in-flight op: {op['op']} (comm {op['comm']},"
            f"{peer} {op['bytes']}B, plane {op['plane']})"
        )
    elif inflight.get("last_async_op"):
        out.append(
            f"  last in-flight op: {inflight['last_async_op']} "
            f"({inflight['inflight_async']} async request(s) open)"
        )
    if inflight.get("step") is not None:
        out.append(f"  died inside step #{inflight['step']}")
    elif inflight.get("last_step") is not None:
        out.append(f"  last completed step: #{inflight['last_step']}")
    for key, label in (("last_frame_tx", "tx"), ("last_frame_rx", "rx")):
        fr = inflight.get(key)
        if fr:
            out.append(
                f"  last wire {label}: peer=r{fr['peer']} "
                f"({fr['bytes']}B)"
            )
    if rk["affected_links"]:
        out.append("  affected link(s): " + ", ".join(
            f"r{first}<->r{p}" for p in rk["affected_links"]))
    for r, rows in sorted(report["peer_views"].items(),
                          key=lambda kv: int(kv[0])):
        names = []
        for row in rows:
            if row["kind"] not in _PEER_VIEW_KINDS:
                continue
            when = (" " + _rel(report, row["t_unix_ns"])
                    if row["t_unix_ns"] else "")
            names.append(f"{row['kind']}{when}")
        if names:
            out.append(f"  r{r} saw: " + ", ".join(names[:6]))
    resize = report.get("resize")
    if resize:
        if resize.get("removing_epoch") is not None:
            rel = ("preceded"
                   if resize.get("death_preceded_resize", True)
                   else "followed")
            out.append(
                f"  death {rel} resize epoch "
                f"{resize['removing_epoch']} (victim was a member of "
                f"epoch {resize['victim_epoch']})"
            )
        elif resize.get("death_followed_epoch") is not None:
            out.append(
                "  death followed resize epoch "
                f"{resize['death_followed_epoch']} (no later resize "
                "observed)"
            )
    return out


def render(report):
    out = [
        f"t4j-postmortem — {report['ranks_with_evidence']}/"
        f"{report['world']} rank(s) with evidence, "
        f"{len(report['dead_ranks'])} dead, "
        f"{len(report['wedged_ranks'])} wedged"
    ]
    out.extend(summary_lines(report))
    out.append("")
    out.append(f"  {'rank':<6}{'verdict':<12}{'evidence':<16}"
               f"{'epoch':>6}{'events':>8}{'hb age':>9}{'torn':>6}"
               f"{'last seen':>11}")
    for r in sorted(report["ranks"], key=int):
        rk = report["ranks"][r]
        hb = (f"{rk['heartbeat_age_s']:.1f}s"
              if rk["heartbeat_age_s"] is not None else "-")
        seen = (f"+{rk['last_evidence_rel_s']:.2f}s"
                if rk["last_evidence_rel_s"] is not None else "-")
        out.append(
            f"  r{r:<5}{rk['verdict']:<12}"
            f"{'+'.join(rk['sources']) or '-':<16}{rk['epoch']:>6}"
            f"{rk['events']:>8}{hb:>9}{rk['torn_slots']:>6}{seen:>11}"
        )
    if report["timeline"]:
        out.append("")
        out.append(f"  last {report['window_s']:g}s of control events "
                   "(job-relative):")
        for row in report["timeline"][-40:]:
            rel = (f"+{row['t_rel_s']:.3f}s"
                   if row["t_rel_s"] is not None else "?")
            out.append(f"  {rel:>12}  r{row['rank']}  {row['event']}")
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="t4j-postmortem",
        description="cross-rank death analysis from drained + "
                    "flight-recorder files (docs/observability.md "
                    "\"flight recorder\")",
    )
    ap.add_argument("path", help="--telemetry / T4J_FLIGHT_DIR directory")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report")
    ap.add_argument("--window", type=float, default=DEFAULT_WINDOW_S,
                    metavar="SECS",
                    help="merge only the last SECS of events "
                         f"(default {DEFAULT_WINDOW_S:g})")
    ap.add_argument("--stale", type=float, default=STALE_S,
                    metavar="SECS",
                    help="heartbeat age past which a rank counts as "
                         f"dead (default {STALE_S:g})")
    ap.add_argument("--flight-dir", default=None, metavar="DIR",
                    help="separate flight-recorder directory, when the "
                         "job set T4J_FLIGHT_DIR away from the "
                         "telemetry dir")
    args = ap.parse_args(argv)
    try:
        report = analyze_dir(args.path, window_s=args.window,
                             stale_s=args.stale,
                             flight_dir=args.flight_dir)
    except (OSError, ValueError) as e:
        print(f"t4j-postmortem: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
