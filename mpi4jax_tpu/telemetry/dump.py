"""Per-rank telemetry drain: ring + metrics -> ``rank<k>.t4j.json``.

Two entry points:

* :func:`build_rank_obj` — the pure builder (stdlib only): standalone
  harnesses (tools/telemetry_smoke.py, old-jax containers) feed it raw
  ctypes drains directly.
* :func:`collect` / :func:`write_rank_file` / :func:`install_atexit` —
  the in-package path: pull everything from ``native.runtime`` and
  write the file.  ``runtime.ensure_initialized`` installs the atexit
  hook when ``T4J_TELEMETRY_DIR`` is set (the launcher's
  ``--telemetry DIR``), and the launcher's child wrapper calls
  :func:`write_rank_file` on the abort path too, so a dying rank's
  last events make it into the first-failure report.

The drain is ordered AFTER the bridge's atexit finalize on purpose
(atexit runs LIFO; ensure_initialized registers this hook first): the
ring and metrics table are process-global and outlive finalize, so the
file also carries teardown-phase events.
"""

import json
import os
import pathlib

from . import recorder, schema

_hook_state = {"installed": False}

# Drains accumulate across calls: the abort path drains first (so a
# rank about to be signal-killed loses nothing) and the atexit hook
# drains AGAIN at interpreter exit — without accumulation the second,
# nearly-empty drain would overwrite the file that held the dying
# rank's last events, which is the post-mortem case the feature
# exists for.  link_stats/topology are cached the same way: the
# atexit drain runs AFTER bridge finalize (LIFO by design), where the
# live queries return None — runtime.finalize() calls
# :func:`capture_runtime_state` just before teardown so the exit file
# still carries the per-link counters.
_accum = {"events": [], "py_events": [], "link_stats": None,
          "topology": None, "tuning": None}


def capture_runtime_state():
    """Snapshot the teardown-sensitive state (link stats, topology)
    while the bridge is still initialized.  Called from
    runtime.finalize() when a telemetry dir is configured; idempotent
    and never raises."""
    try:
        from mpi4jax_tpu.native import runtime

        agg = runtime.link_stats()
        if agg is not None:
            per_peer = {}
            for peer in range(runtime.world_size()):
                s = runtime.link_stats(peer)
                if s is not None:
                    per_peer[str(peer)] = s
            _accum["link_stats"] = {"aggregate": agg,
                                    "per_peer": per_peer}
        topo = runtime.topology()
        if topo is not None:
            _accum["topology"] = topo
    except Exception:
        pass
    # the plane-selection knobs the job ran under: t4j-diagnose's
    # plane audit judges served planes against THESE, not against
    # whatever environment diagnose later runs in.  The job's
    # EFFECTIVE tuning (env > tuning cache > default, as resolved by
    # tuning.startup) is authoritative when available — env-only
    # values would misjudge a job that ran on cache-loaded knobs —
    # and the per-knob provenance plus the cache file/fingerprint ride
    # along so the audit can name them.
    try:
        from mpi4jax_tpu import tuning as _tuning

        eff = _tuning.effective()
    except Exception:
        eff = None
    # the EFFECTIVE wire-path state (built/active stripe width,
    # zerocopy arming) rides the tuning record so t4j-diagnose judges
    # plane/stripe choices against what the job actually ran, not the
    # env the analysis later runs in
    wire = None
    try:
        from mpi4jax_tpu.native import runtime as _runtime

        wire = _runtime.wire_info()
    except Exception:
        pass
    if eff is not None:
        _accum["tuning"] = {
            "ring_min_bytes": eff["knobs"]["ring_min_bytes"],
            "seg_bytes": eff["knobs"]["seg_bytes"],
            "leader_ring_min_bytes":
                eff["knobs"]["leader_ring_min_bytes"],
            "hier": eff["knobs"]["hier"],
            "coalesce_bytes": eff["knobs"]["coalesce_bytes"],
            "stripes": eff["knobs"].get("stripes", "auto"),
            "wire_dtype": eff["knobs"].get("wire_dtype", "off"),
            "wire_backend": eff["knobs"].get("wire_backend", "auto"),
            "sources": dict(eff["sources"]),
            "cache_file": eff["cache_file"],
            "fingerprint": eff["fingerprint"],
            "autotuned": bool(eff["autotuned"]),
            "wire": wire or {},
        }
        return
    try:
        from mpi4jax_tpu.utils import config

        _accum["tuning"] = {
            "ring_min_bytes": config.ring_min_bytes(),
            "seg_bytes": config.seg_bytes(),
            "leader_ring_min_bytes": config.leader_ring_min_bytes(),
            "hier": config.hier_mode(),
            "coalesce_bytes": config.coalesce_bytes(),
            "stripes": config.stripes(),
            "wire_dtype": config.wire_dtype(),
            "wire_backend": config.wire_backend(),
            "wire": wire or {},
        }
    except Exception:
        pass


def rank_file_name(rank):
    return f"rank{int(rank)}.t4j.json"


def build_rank_obj(rank, world, anchor_mono_ns, anchor_unix_ns, mode,
                   events=(), py_events=(), metrics_words=(),
                   dropped=0, link_stats=None, topology=None, job=None,
                   tuning=None, flight=None, serving=None):
    """Assemble a schema-valid per-rank telemetry object from raw
    drains (``events``: iterable of :class:`schema.Event` or 8-field
    rows; ``metrics_words``: the u64 snapshot)."""
    rows = []
    for e in events:
        rows.append(schema.event_to_list(e) if isinstance(e, schema.Event)
                    else list(e))
    metrics = (schema.parse_snapshot(metrics_words) if metrics_words
               else {"version": schema.SCHEMA_VERSION, "mode": 0,
                     "lat_base_log2": 10, "size_base_log2": 6,
                     "rows": []})
    obj = {
        "schema": schema.RANK_FILE_SCHEMA,
        "rank": int(rank),
        "world": int(world),
        "mode": str(mode),
        "job": str(job or ""),
        "anchor": {"mono_ns": int(anchor_mono_ns),
                   "unix_ns": int(anchor_unix_ns)},
        "dropped": int(dropped),
        "events": rows,
        "py_events": [list(r) for r in py_events],
        "metrics": metrics,
        "link_stats": link_stats or {},
        "topology": topology or {},
        "tuning": tuning or {},
        # flight-recorder status (docs/observability.md "flight
        # recorder"): lets t4j-top / t4j-postmortem pair this drain
        # with the rank's raw .t4jflight file
        "flight": flight or {},
        # serving gauges (docs/serving.md): the engine's last
        # published snapshot, so t4j-top shows the serving loop next
        # to the transport it feeds on ({} outside serving jobs)
        "serving": serving or {},
    }
    return schema.validate_rank_file(obj)


def collect():
    """Drain everything this rank has (native ring, python recorder,
    metrics, link stats, topology) into a rank object, or ``None``
    when the native bridge was never loaded.  Cumulative: repeated
    calls (abort path, then atexit; or periodic mid-run dumps) return
    everything drained so far."""
    from mpi4jax_tpu.native import runtime

    if runtime._state["lib"] is None:
        return None
    _accum["events"].extend(runtime.telemetry_drain())
    _accum["py_events"].extend(recorder.drain())
    events = _accum["events"]
    mono, unix = runtime.telemetry_anchor()
    capture_runtime_state()  # refresh while live; no-op post-finalize
    link = _accum["link_stats"] or {}
    try:
        flight = runtime.flight_info()
    except Exception:
        flight = None
    try:
        from mpi4jax_tpu.serving import stats as _serving_stats

        serving = _serving_stats.current()
    except Exception:
        serving = None
    return build_rank_obj(
        rank=int(os.environ.get("T4J_RANK", 0)),
        world=int(os.environ.get("T4J_SIZE", 1)),
        anchor_mono_ns=mono,
        anchor_unix_ns=unix,
        mode=runtime.telemetry_mode_name(),
        events=events,
        py_events=_accum["py_events"],
        metrics_words=runtime.metrics_snapshot(),
        dropped=runtime.telemetry_dropped() + recorder.dropped(),
        link_stats=link,
        topology=_accum["topology"] or {},
        job=os.environ.get("T4J_JOB", ""),
        tuning=_accum["tuning"] or {},
        flight=flight,
        serving=serving,
    )


def write_rank_file(directory):
    """Drain into ``directory/rank<k>.t4j.json``; returns the path or
    ``None`` when there was nothing to drain.  Never raises (the exit
    path must not mask the real failure)."""
    try:
        obj = collect()
        if obj is None:
            return None
        d = pathlib.Path(directory)
        d.mkdir(parents=True, exist_ok=True)
        path = d / rank_file_name(obj["rank"])
        tmp = path.with_suffix(f".tmp{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)  # atomic: the merger never sees a torn file
        return path
    except Exception:
        return None


def install_atexit(directory):
    """Register the exit-time drain once (idempotent)."""
    if _hook_state["installed"]:
        return
    _hook_state["installed"] = True
    import atexit

    atexit.register(write_rank_file, directory)
