"""Chrome-trace / Perfetto exporter and cross-rank merger.

Turns per-rank telemetry files (``rank<k>.t4j.json``, written by
telemetry/dump.py or the standalone smoke workers) into one Chrome
"JSON object format" trace — loadable in Perfetto / chrome://tracing —
with every rank on one aligned timeline:

* one *process* (pid) per rank, named ``rank <k>``;
* per rank, thread 0 is the ``python`` lane (the op-layer begin/end
  recorder) and threads 1..n are the native lanes (one per native
  thread that emitted events: the op thread, reader threads, repair
  dialers);
* op begin/end pairs become nested B/E duration slices, everything
  else (wire frames, arena stages, link break/reconnect/replay/fault)
  becomes thread-scoped instants with the payload in ``args``.

Clock alignment (docs/observability.md "clock alignment"): every
rank's anchor is a (monotonic, realtime) pair captured immediately
after the SAME bootstrap join barrier, so the merger places each event
at ``(t_ns - anchor_mono_r) / 1000`` µs on a job-relative timeline —
ranks align up to barrier-exit skew, immune to wall-clock
disagreement.  The earliest anchor's realtime is recorded in
``otherData.job_epoch_unix_ns`` so absolute times are recoverable.

Import-free of jax (stdlib only).
"""

import json
import pathlib

from . import schema

RANK_FILE_GLOB = "rank*.t4j.json"
MERGED_NAME = "job.trace.json"


def _lane_tids(rank_obj):
    """Stable tid assignment: 0 = python lane, then native lanes by
    first appearance in ring order."""
    tids = {}
    for row in rank_obj["events"]:
        lane = schema.event_from_list(row).lane
        if lane not in tids:
            tids[lane] = len(tids) + 1
    return tids


def rank_to_chrome_events(rank_obj):
    """One validated rank file -> list of Chrome trace events (pid =
    rank).  Dangling op begins (a rank that died mid-op, or a drain
    that raced an in-flight op) are closed at the rank's last seen
    timestamp so the merged trace stays schema-valid — the post-mortem
    case is exactly when those spans matter most."""
    rank = int(rank_obj["rank"])
    anchor_mono = int(rank_obj["anchor"]["mono_ns"])
    out = [
        {"name": "process_name", "ph": "M", "pid": rank, "tid": 0,
         "args": {"name": f"rank {rank}"}},
        {"name": "process_sort_index", "ph": "M", "pid": rank, "tid": 0,
         "args": {"sort_index": rank}},
        {"name": "thread_name", "ph": "M", "pid": rank, "tid": 0,
         "args": {"name": "python"}},
    ]
    tids = _lane_tids(rank_obj)
    for lane, tid in tids.items():
        out.append({
            "name": "thread_name", "ph": "M", "pid": rank, "tid": tid,
            "args": {"name": f"native-{tid}" if tid > 1 else "native"},
        })

    def ts_us(t_ns):
        return (int(t_ns) - anchor_mono) / 1000.0

    last_ts = 0.0
    open_spans = {}  # tid -> [(name, begin args), ...]
    for row in rank_obj["events"]:
        e = schema.event_from_list(row)
        tid = tids[e.lane]
        name = schema.kind_name(e.kind)
        ts = ts_us(e.t_ns)
        last_ts = max(last_ts, ts)
        args = {
            "plane": schema.plane_name(e.plane),
            "comm": e.comm,
            "peer": e.peer,
            "bytes": e.bytes,
        }
        # step markers (kind 60) render as duration slices exactly like
        # op scopes: one "step" span framing the ops of that iteration
        # (args.bytes carries the step index), and caller-lane wait
        # brackets (kind 53) as slices on the waiting thread;
        # t4j-diagnose recovers per-step windows and caller-blocked
        # time from a merged trace through these
        is_span = (e.kind in schema.OP_KINDS
                   or e.kind == schema.STEP_KIND
                   or e.kind == schema.WAIT_KIND)
        if is_span and e.phase == schema.PHASE_BEGIN:
            open_spans.setdefault(tid, []).append((name, args))
            out.append({"name": name, "ph": "B", "ts": ts, "pid": rank,
                        "tid": tid, "args": args})
        elif is_span and e.phase == schema.PHASE_END:
            stack = open_spans.get(tid, [])
            if stack and stack[-1][0] == name:
                stack.pop()
                out.append({"name": name, "ph": "E", "ts": ts,
                            "pid": rank, "tid": tid, "args": args})
            # an end with no open begin: the begin was lapped out of
            # the bounded ring — drop it rather than emit an
            # unbalanced E
        else:
            out.append({"name": name, "ph": "i", "ts": ts, "s": "t",
                        "pid": rank, "tid": tid, "args": args})
    # py events extend the rank's last-seen instant too: a rank that
    # died inside Python-side staging (no native event for the op yet)
    # must not get its truncated end placed BEFORE its begin
    for t_ns, _op, _phase, _nbytes in rank_obj["py_events"]:
        last_ts = max(last_ts, ts_us(t_ns))
    # close spans cut off by death/drain at the last seen instant,
    # keeping the BEGIN's args (plane/bytes — for a step span the step
    # index): consumers of the merged trace (t4j-diagnose) must see
    # the same identity + truncated flag the rank-file path derives
    for tid, stack in open_spans.items():
        while stack:
            name, bargs = stack.pop()
            out.append({"name": name, "ph": "E", "ts": last_ts,
                        "pid": rank, "tid": tid,
                        "args": dict(bargs, truncated=True)})
    # python lane: same discipline as the native lanes — an end whose
    # begin is missing (dropped from the bounded recorder deque, or
    # crossed by another thread's bracket interleaving on this shared
    # lane) is SKIPPED rather than emitted unbalanced, and begins cut
    # off by death are closed at the rank's last seen instant; one
    # dangling slice must not make validate_trace reject the whole
    # merged job.trace.json.
    py_stack = []
    for t_ns, op, phase, nbytes in rank_obj["py_events"]:
        ts = ts_us(t_ns)
        name = f"py:{op}"
        if phase == schema.PHASE_BEGIN:
            py_stack.append(name)
            out.append({"name": name, "ph": "B", "ts": ts,
                        "pid": rank, "tid": 0,
                        "args": {"bytes": nbytes}})
        elif phase == schema.PHASE_END:
            if py_stack and py_stack[-1] == name:
                py_stack.pop()
                out.append({"name": name, "ph": "E", "ts": ts,
                            "pid": rank, "tid": 0,
                            "args": {"bytes": nbytes}})
            # else: begin lost to the bounded deque — drop the end
        else:
            out.append({"name": name, "ph": "i", "ts": ts,
                        "s": "t", "pid": rank, "tid": 0,
                        "args": {"bytes": nbytes}})
    for name in reversed(py_stack):
        out.append({"name": name, "ph": "E", "ts": last_ts, "pid": rank,
                    "tid": 0, "args": {"truncated": True}})
    return out


def merge_rank_objs(rank_objs, job=None):
    """Validated rank files -> one schema-valid merged trace dict."""
    rank_objs = sorted(rank_objs, key=lambda o: int(o["rank"]))
    events = []
    for obj in rank_objs:
        schema.validate_rank_file(obj)
        events.extend(rank_to_chrome_events(obj))
    epoch = min(
        (int(o["anchor"]["unix_ns"]) for o in rank_objs), default=0
    )
    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "schema": schema.RANK_FILE_SCHEMA,
            "job": job or "",
            "ranks": len(rank_objs),
            "job_epoch_unix_ns": epoch,
            "dropped_events": sum(
                int(o.get("dropped", 0)) for o in rank_objs
            ),
        },
    }
    return schema.validate_trace(trace)


def merge_dir(dir_path, out_name=MERGED_NAME, job=None):
    """Merge every per-rank file in ``dir_path`` into
    ``dir_path/out_name``; returns the output path.  Raises
    FileNotFoundError when no rank files exist."""
    d = pathlib.Path(dir_path)
    paths = sorted(d.glob(RANK_FILE_GLOB))
    if not paths:
        raise FileNotFoundError(f"no {RANK_FILE_GLOB} files in {d}")
    objs = [schema.load_rank_file(p) for p in paths]
    trace = merge_rank_objs(objs, job=job)
    out = d / out_name
    with open(out, "w") as f:
        json.dump(trace, f)
    return out
