"""Live metrics exporter: per-rank snapshots over HTTP + one-shot files.

The continuously-exported half of docs/observability.md: where the
rank files / ``t4j-diagnose`` are retrospective, this serves each
rank's CURRENT metrics table, link stats, and last telemetry events
while the job runs — the data source serving admission control
(ROADMAP item 5) and any Prometheus scrape needs.

* ``T4J_METRICS_PORT=P`` (or the launcher's ``--metrics P``) makes
  rank k serve ``127.0.0.1:P+k``:

  - ``/metrics``       Prometheus text exposition
  - ``/metrics.json``  the full JSON snapshot (:func:`validate_snapshot`)

  wired in ``native.runtime.ensure_initialized`` / stopped at finalize.
* :func:`export_file` writes the same snapshot once to disk — and
  includes the ``check_health`` post-mortem surfaces (the "last
  telemetry events" tail via the shared
  :func:`schema.format_recent_events`, and the link-stats aggregate
  WITH per-peer maxima), so the live view and the post-mortem agree.
* ``launch.py --metrics P`` scrapes every rank's ``/metrics.json`` and
  serves the :func:`aggregate_snapshots` job view — worst-link and
  straggler gauges included — on port ``P + nprocs``.

Import-free of jax (stdlib only): standalone harnesses plug their own
``collect_fn`` (any zero-arg callable returning a snapshot dict).
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import schema
from .registry import MetricsRegistry

SNAPSHOT_SCHEMA = "t4j-metrics-v1"

_SNAP_REQUIRED = ("schema", "rank", "world", "mode", "ts_unix_ns",
                  "ops", "bytes_by_plane", "link_stats", "last_events",
                  "dropped")


class SnapshotError(schema.SchemaError):
    """A metrics snapshot does not match the documented schema."""


def validate_snapshot(obj):
    """Raise :class:`SnapshotError` unless ``obj`` is a well-formed
    exporter snapshot; returns ``obj``."""
    if not isinstance(obj, dict):
        raise SnapshotError("snapshot is not a JSON object")
    for key in _SNAP_REQUIRED:
        if key not in obj:
            raise SnapshotError(f"snapshot is missing {key!r}")
    if obj["schema"] != SNAPSHOT_SCHEMA:
        raise SnapshotError(
            f"snapshot schema {obj['schema']!r} != {SNAPSHOT_SCHEMA!r}"
        )
    for row in obj["ops"]:
        for key in ("op", "plane", "count", "bytes"):
            if key not in row:
                raise SnapshotError(f"ops row is missing {key!r}")
    if not isinstance(obj["last_events"], list):
        raise SnapshotError("last_events must be a list")
    return obj


def build_snapshot(rank, world, mode, metrics, link_stats=None,
                   last_events=(), dropped=0, step=None, job="",
                   ts_unix_ns=None, world_info=None, serving=None):
    """Assemble a schema-valid snapshot from raw pieces.

    ``metrics`` is a native u64-word snapshot, a parsed snapshot dict,
    or a :class:`MetricsRegistry`; ``last_events`` an iterable of
    :class:`schema.Event` (formatted via the shared
    :func:`schema.format_recent_events` so this export and
    ``check_health`` render identically)."""
    if isinstance(metrics, MetricsRegistry):
        reg = metrics
    elif metrics:
        reg = MetricsRegistry.from_snapshot(metrics)
    else:
        reg = MetricsRegistry()
    ops = []
    for op in reg.ops():
        for plane in sorted({p for (_c, o, p) in reg.rows if o == op}):
            row = reg.aggregate(op=op, plane=plane)
            stats = row.stats()
            stats.update(op=op, plane=plane)
            ops.append(stats)
    events = list(last_events)
    obj = {
        "schema": SNAPSHOT_SCHEMA,
        "rank": int(rank),
        "world": int(world),
        "mode": str(mode),
        "job": str(job or ""),
        "ts_unix_ns": int(ts_unix_ns if ts_unix_ns is not None
                          else time.time_ns()),
        "step": step,
        "dropped": int(dropped),
        "ops": ops,
        "bytes_by_plane": reg.bytes_by_plane(),
        "link_stats": link_stats or {},
        # elastic membership view (docs/failure-semantics.md "elastic
        # membership"): {} outside elastic jobs / before init
        "world_info": dict(world_info or {}),
        # serving gauges (docs/serving.md): the engine's published
        # queue/occupancy/shed/SLO snapshot; {} outside serving jobs
        "serving": dict(serving or {}),
        "last_events": schema.format_recent_events(events).split("; ")
        if events else [],
        "last_events_raw": [schema.event_to_list(e) for e in events],
    }
    return validate_snapshot(obj)


def collect_snapshot():
    """The in-package collector: pull everything from
    ``native.runtime`` (``None`` when the bridge was never loaded).
    The default ``collect_fn`` of :class:`MetricsExporter` and the
    default source of :func:`export_file`."""
    import os

    from mpi4jax_tpu.native import runtime

    if runtime._state["lib"] is None:
        return None
    step = None
    try:
        from mpi4jax_tpu.ops import step as step_mod

        open_step = step_mod.current_step()
        if open_step is not None:
            step = {"index": open_step[0], "name": open_step[1]}
    except Exception:
        pass
    try:
        from mpi4jax_tpu.serving import stats as _serving_stats

        serving = _serving_stats.current()
    except Exception:
        serving = None
    return build_snapshot(
        rank=int(os.environ.get("T4J_RANK", 0)),
        world=int(os.environ.get("T4J_SIZE", 1)),
        mode=runtime.telemetry_mode_name(),
        metrics=runtime.metrics_snapshot(),
        link_stats=runtime.link_stats(),
        last_events=runtime.telemetry_last(8),
        dropped=runtime.telemetry_dropped(),
        step=step,
        job=os.environ.get("T4J_JOB", ""),
        world_info=runtime.world_info(),
        serving=serving,
    )


def export_file(path, obj=None):
    """One-shot export: write a snapshot to ``path`` (collecting from
    the live runtime when ``obj`` is None).  The file carries the same
    "last telemetry events" tail and link-stats maxima check_health
    reports, so post-mortem and live views agree.  Returns the path,
    or ``None`` when there was nothing to export."""
    import os
    import pathlib

    if obj is None:
        obj = collect_snapshot()
    if obj is None:
        return None
    p = pathlib.Path(path)
    if p.parent.name:
        p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(f".tmp{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(validate_snapshot(obj), f)
    os.replace(tmp, p)
    return p


# ---- Prometheus text exposition ------------------------------------------


def _esc(value):
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def render_prometheus(obj, prefix="t4j"):
    """Snapshot dict -> Prometheus text exposition format."""
    validate_snapshot(obj)
    rank = obj["rank"]
    lines = []

    def emit(name, labels, value, help_=None, type_="gauge"):
        if value is None:
            return
        if help_ is not None:
            lines.append(f"# HELP {prefix}_{name} {help_}")
            lines.append(f"# TYPE {prefix}_{name} {type_}")
        lbl = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items())
        lines.append(f"{prefix}_{name}{{{lbl}}} {value}")

    base = {"rank": rank}
    emit("up", base, 1, help_="rank exporter liveness")
    emit("telemetry_dropped_total", base, obj["dropped"],
         help_="native ring events dropped to overflow",
         type_="counter")
    if obj.get("step"):
        emit("step_index", base, obj["step"].get("index"),
             help_="index of the currently open step marker")
    first = True
    for row in obj["ops"]:
        labels = dict(base, op=row["op"], plane=row["plane"])
        emit("op_count_total", labels, row["count"],
             help_="op invocations" if first else None, type_="counter")
        emit("op_bytes_total", labels, row["bytes"],
             help_="payload bytes" if first else None, type_="counter")
        for q in ("p50", "p99", "max"):
            v = row.get(f"{q}_ms")
            if v is not None:
                emit(f"op_latency_{q}_ms", labels, round(v, 4),
                     help_=f"{q} op latency (histogram estimate)"
                     if first else None)
        first = False
    first = True
    for plane, nbytes in sorted(obj["bytes_by_plane"].items()):
        emit("plane_bytes_total", dict(base, plane=plane), nbytes,
             help_="payload bytes per data plane" if first else None,
             type_="counter")
        first = False
    links = obj.get("link_stats") or {}
    per_peer = links.get("per_peer") or {}
    first = True
    for peer, s in sorted(per_peer.items(), key=lambda kv: int(kv[0])):
        labels = dict(base, peer=peer)
        emit("link_reconnects_total", labels, s.get("reconnects"),
             help_="self-healing reconnects per link" if first else None,
             type_="counter")
        emit("link_replayed_bytes_total", labels,
             s.get("replayed_bytes"), type_="counter")
        emit("link_state", labels, s.get("state"),
             help_="0 up, 1 broken/repairing, 2 dead" if first else None)
        first = False
    agg = {k: v for k, v in links.items() if k != "per_peer"}
    if agg:
        emit("link_reconnects_sum", base, agg.get("reconnects"),
             help_="reconnects over every link", type_="counter")
        emit("worst_link_reconnects", base, agg.get("max_reconnects"),
             help_="reconnects on the worst link (admission-control "
                   "signal)")
        emit("worst_link_replayed_bytes", base,
             agg.get("max_replayed_bytes"))
        if agg.get("worst_peer") is not None:
            emit("worst_link_peer", base, agg.get("worst_peer"),
                 help_="peer rank of the worst link")
        emit("link_state_worst", base, agg.get("state"))
    wi = obj.get("world_info") or {}
    if wi:
        # elastic membership gauges: dashboards follow the RESIZED
        # world instead of flatlining on the bootstrap size
        emit("world_size", base, wi.get("alive_count"),
             help_="current world membership (elastic resizes shrink "
                   "and regrow it)")
        emit("world_epoch", base, wi.get("epoch"),
             help_="membership epoch (0 = bootstrap; +1 per resize)")
        emit("world_resizing", base,
             1 if wi.get("resizing") else 0,
             help_="1 while a membership agreement/rebuild is running")
        # canonical autoscaler-facing name for the same signal
        # (docs/serving.md "Autoscaling"): kept alongside
        # world_resizing so older dashboards keep working
        emit("resize_in_progress", base,
             1 if wi.get("resizing") else 0,
             help_="1 while a membership agreement/rebuild is running")
        emit("world_epoch_transitions_total", base,
             wi.get("epoch_transitions"),
             help_="resize epochs this process observed and survived",
             type_="counter")
    sv = obj.get("serving") or {}
    if sv:
        # serving gauges (docs/serving.md): the continuous-batching
        # loop next to the transport signals admission control reads
        emit("serving_queue_depth", base, sv.get("queue_depth"),
             help_="requests queued for a free KV slot")
        emit("serving_batch_occupancy", base,
             sv.get("batch_occupancy"),
             help_="KV slots holding a request (of "
                   "serving_max_batch)")
        emit("serving_max_batch", base, sv.get("max_batch"))
        emit("serving_submitted_total", base, sv.get("submitted"),
             help_="requests offered", type_="counter")
        emit("serving_completed_total", base, sv.get("completed"),
             help_="requests completed", type_="counter")
        emit("serving_shed_total", base, sv.get("shed"),
             help_="requests shed by admission control",
             type_="counter")
        emit("serving_reissued_total", base, sv.get("reissued"),
             help_="in-flight requests reissued after a resize wiped "
                   "their slot state", type_="counter")
        emit("serving_epochs_survived_total", base,
             sv.get("epochs_survived"),
             help_="resize epochs the serving engine rode out",
             type_="counter")
        for q in ("p50", "p99"):
            v = sv.get(f"latency_{q}_ms")
            if v is not None:
                emit(f"serving_latency_{q}_ms", base, round(v, 3),
                     help_=f"end-to-end request latency {q}")
        if sv.get("slo_ms"):
            emit("serving_slo_ms", base, sv["slo_ms"],
                 help_="configured end-to-end latency SLO")
        att = sv.get("slo_attainment")
        if att is not None:
            emit("serving_slo_attainment", base, round(att, 4),
                 help_="requests finished within SLO over requests "
                       "offered (sheds count against)")
        if sv.get("stopped"):
            emit("serving_stopped", base, 1,
                 help_="1 once the engine broadcast its stop plan "
                       "(the gauges above are its final state, not "
                       "live)")
    return "\n".join(lines) + "\n"


# ---- job-level aggregation (the launcher's --metrics view) ---------------


def aggregate_snapshots(objs, job=""):
    """Per-rank snapshots -> one job-level view: totals, worst-link
    gauges, and a straggler gauge (the rank with the LEAST time spent
    inside comm ops — in a collective job everyone waits on the
    straggler, so the rank that waits least is the one gating the
    rest; ``t4j-diagnose`` is the precise per-step tool, this is the
    live approximation admission control can poll)."""
    objs = [o for o in objs if o]
    ranks = []
    worst = {"peer": None, "rank": None, "reconnects": 0,
             "replayed_bytes": 0, "state": 0}
    bytes_by_plane = {}
    comm_ms = {}
    total_dropped = 0
    for obj in objs:
        validate_snapshot(obj)
        rank = int(obj["rank"])
        ranks.append(rank)
        total_dropped += int(obj["dropped"])
        for plane, nbytes in obj["bytes_by_plane"].items():
            bytes_by_plane[plane] = bytes_by_plane.get(plane, 0) + nbytes
        busy = 0.0
        for row in obj["ops"]:
            mean = row.get("mean_ms")
            if mean is not None:
                busy += mean * row["count"]
        comm_ms[rank] = round(busy, 3)
        links = obj.get("link_stats") or {}
        state = links.get("state", 0) or 0
        if (links.get("max_reconnects", 0), state) > (
                worst["reconnects"], worst["state"]):
            worst.update(
                rank=rank,
                peer=links.get("worst_peer"),
                reconnects=links.get("max_reconnects", 0),
                replayed_bytes=links.get("max_replayed_bytes", 0),
                state=state,
            )
    straggler = None
    if len(comm_ms) > 1:
        straggler = min(comm_ms, key=lambda r: comm_ms[r])
    # serving gauges: the frontend (lowest serving rank — rank 0 in
    # the engine's control plane) owns queue/shed/SLO truth; follower
    # occupancy corroborates, so the job view carries the frontend
    # block plus how many ranks are serving
    serving = {}
    serving_ranks = []
    for obj in sorted(objs, key=lambda o: int(o["rank"])):
        sv = obj.get("serving") or {}
        if sv:
            serving_ranks.append(int(obj["rank"]))
            if not serving:
                serving = dict(sv)
    # elastic membership: the freshest epoch any rank reports wins
    # (mid-resize scrapes can catch ranks on both sides of the fence);
    # resize_in_progress is an ANY — one rank still rebuilding means
    # the job is mid-transition; transitions is a MAX — survivors
    # carry the full count, a rejoined replacement restarts at 0
    world = {}
    any_resizing = False
    max_transitions = 0
    for obj in objs:
        wi = obj.get("world_info") or {}
        if not wi:
            continue
        any_resizing = any_resizing or bool(wi.get("resizing"))
        max_transitions = max(
            max_transitions, int(wi.get("epoch_transitions", 0) or 0)
        )
        if int(wi.get("epoch", 0)) >= int(world.get("epoch", -1)):
            world = wi
    departed = []
    if world:
        boot = int(world.get("boot_size", 0))
        mask = int(world.get("alive_mask", 0))
        if 0 < boot <= 64:
            departed = [r for r in range(boot) if not (mask >> r) & 1]
    return {
        "schema": SNAPSHOT_SCHEMA + "+job",
        "job": job,
        "ts_unix_ns": time.time_ns(),
        "ranks": sorted(ranks),
        "ranks_reporting": len(ranks),
        "dropped": total_dropped,
        "bytes_by_plane": bytes_by_plane,
        "comm_ms_by_rank": {str(r): comm_ms[r] for r in sorted(comm_ms)},
        "straggler": straggler,
        "worst_link": worst,
        "world_size": world.get("alive_count"),
        "world_epoch": world.get("epoch"),
        "resize_in_progress": any_resizing if world else None,
        "epoch_transitions": max_transitions if world else None,
        "departed_ranks": departed,
        "serving": serving,
        "serving_ranks": serving_ranks,
    }


def render_prometheus_job(agg, prefix="t4j_job"):
    """Job aggregate -> Prometheus text."""
    lines = [
        f"# HELP {prefix}_ranks_reporting ranks whose exporter "
        "answered the last scrape",
        f"# TYPE {prefix}_ranks_reporting gauge",
        f"{prefix}_ranks_reporting {agg['ranks_reporting']}",
        f"{prefix}_dropped_total {agg['dropped']}",
    ]
    for plane, nbytes in sorted(agg["bytes_by_plane"].items()):
        lines.append(
            f'{prefix}_plane_bytes_total{{plane="{_esc(plane)}"}} '
            f"{nbytes}"
        )
    for rank, ms in agg["comm_ms_by_rank"].items():
        lines.append(f'{prefix}_comm_ms{{rank="{rank}"}} {ms}')
    if agg["straggler"] is not None:
        lines.append(f"{prefix}_straggler_rank {agg['straggler']}")
    worst = agg["worst_link"]
    lines.append(f"{prefix}_worst_link_reconnects {worst['reconnects']}")
    lines.append(
        f"{prefix}_worst_link_replayed_bytes {worst['replayed_bytes']}"
    )
    lines.append(f"{prefix}_worst_link_state {worst['state']}")
    if worst["rank"] is not None:
        lines.append(f"{prefix}_worst_link_rank {worst['rank']}")
    sv = agg.get("serving") or {}
    if sv:
        # the launcher job view's serving block (docs/serving.md):
        # queue depth, batch occupancy, shed count, p99 vs SLO
        for key, name in (
            ("queue_depth", "serving_queue_depth"),
            ("batch_occupancy", "serving_batch_occupancy"),
            ("shed", "serving_shed_total"),
            ("completed", "serving_completed_total"),
        ):
            if sv.get(key) is not None:
                lines.append(f"{prefix}_{name} {sv[key]}")
        if sv.get("latency_p99_ms") is not None:
            lines.append(
                f"{prefix}_serving_latency_p99_ms "
                f"{round(sv['latency_p99_ms'], 3)}"
            )
        if sv.get("slo_ms"):
            lines.append(f"{prefix}_serving_slo_ms {sv['slo_ms']}")
        if sv.get("slo_attainment") is not None:
            lines.append(
                f"{prefix}_serving_slo_attainment "
                f"{round(sv['slo_attainment'], 4)}"
            )
        lines.append(
            f"{prefix}_serving_ranks "
            f"{len(agg.get('serving_ranks') or [])}"
        )
    if agg.get("world_size") is not None:
        # the t4j_world_size / t4j_world_epoch membership gauges
        # (docs/failure-semantics.md "elastic membership"): dashboards
        # track the resized world; departed ranks stay visible as
        # marked series instead of silently flatlining
        lines.append(f"t4j_world_size {agg['world_size']}")
        lines.append(f"t4j_world_epoch {agg['world_epoch']}")
        lines.append(
            "t4j_resize_in_progress "
            f"{1 if agg.get('resize_in_progress') else 0}"
        )
        if agg.get("epoch_transitions") is not None:
            lines.append(
                "t4j_world_epoch_transitions_total "
                f"{agg['epoch_transitions']}"
            )
        for r in agg.get("departed_ranks", []):
            lines.append(f't4j_rank_departed{{rank="{r}"}} 1')
    return "\n".join(lines) + "\n"


# ---- the HTTP server -----------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    server_version = "t4j-exporter/1"

    def do_GET(self):  # noqa: N802 — http.server API
        exporter = self.server.exporter  # type: ignore[attr-defined]
        try:
            obj = exporter.collect()
        except Exception as e:  # noqa: BLE001 — a scrape must not kill a rank
            self._reply(500, "text/plain",
                        f"collect failed: {type(e).__name__}: {e}\n")
            return
        if obj is None:
            self._reply(503, "text/plain", "no telemetry yet\n")
            return
        if self.path.startswith("/metrics.json"):
            self._reply(200, "application/json", json.dumps(obj))
        elif self.path.startswith("/metrics"):
            render = (render_prometheus_job
                      if str(obj.get("schema", "")).endswith("+job")
                      else render_prometheus)
            self._reply(200, "text/plain; version=0.0.4", render(obj))
        else:
            self._reply(404, "text/plain",
                        "try /metrics or /metrics.json\n")

    def _reply(self, code, ctype, body):
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *args):  # scrapes must not spam the job log
        pass


class MetricsExporter:
    """Serve a snapshot callable on ``127.0.0.1:port`` in a daemon
    thread.  ``port=0`` picks an ephemeral port (read it back from
    ``.port`` after :meth:`start` — the tests' idiom)."""

    def __init__(self, port, collect_fn=None, host="127.0.0.1"):
        self._requested = (host, int(port))
        self._collect = (collect_fn if collect_fn is not None
                         else collect_snapshot)
        self._httpd = None
        self._thread = None

    def collect(self):
        return self._collect()

    @property
    def port(self):
        if self._httpd is None:
            return self._requested[1]
        return self._httpd.server_address[1]

    def start(self):
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer(self._requested, _Handler)
        httpd.daemon_threads = True
        httpd.exporter = self  # type: ignore[attr-defined]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="t4j-metrics", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def scrape(url, timeout=1.0):
    """GET ``url`` and parse the JSON body (the launcher's aggregator
    helper); raises on HTTP/connection errors."""
    from urllib.request import urlopen

    with urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())
