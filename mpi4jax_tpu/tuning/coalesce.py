"""Coalescing planner: find fusible runs of small same-peer messages
in a recorded communication schedule.

The PR-4 analyzer (mpi4jax_tpu/analysis/) records every public op's
comm / pattern / shape before execution; this pass walks that schedule
and reports maximal runs of consecutive small point-to-point ops that
address the same peer on the same communicator — exactly the shapes
the fused wire path (``sendrecv_multi`` / ``alltoall_multi``,
docs/performance.md "small-message coalescing") collapses into one
frame.  ``t4j-lint --coalesce`` prints the plan as an advisory so the
feed-forward is visible: the ops layer applies the same
``T4J_COALESCE_BYTES`` gate at run time.

Events are duck-typed (``analysis.contracts.CommEvent`` or plain
dicts with the same vocabulary) so the planner stays stdlib-only and
loadable on old-jax containers.
"""

__all__ = ["message_bytes", "find_runs", "render_plan"]

# dtype -> itemsize for the analyzer's string dtypes (the native
# bridge's 15-entry table, dcn.h)
_ITEMSIZE = {
    "float32": 4, "float64": 8, "int8": 1, "int16": 2, "int32": 4,
    "int64": 8, "uint8": 1, "uint16": 2, "uint32": 4, "uint64": 8,
    "bool": 1, "complex64": 8, "complex128": 16, "float16": 2,
    "bfloat16": 2,
}

# op kinds the fused p2p path can absorb (collectives other than
# alltoall have their own wire schedules and are out of scope)
_P2P_KINDS = ("send", "sendrecv", "sendrecv_multi")
_A2A_KINDS = ("alltoall",)


def _get(ev, name, default=None):
    if isinstance(ev, dict):
        return ev.get(name, default)
    return getattr(ev, name, default)


def message_bytes(ev):
    """Payload bytes of a recorded op, or ``None`` when the record has
    no shape/dtype (e.g. barrier)."""
    shape = _get(ev, "shape") or ()
    dtype = str(_get(ev, "dtype") or "")
    if dtype not in _ITEMSIZE:
        return None
    n = 1
    for d in shape:
        n *= int(d)
    return n * _ITEMSIZE[dtype]


def _peer_key(ev):
    """Identity of the wire peer a p2p op addresses (dest spec as the
    analyzer normalised it: int, pair tuple, or marker string)."""
    dest = _get(ev, "dest")
    if dest is None:
        return None
    return (str(_get(ev, "comm_key")), repr(dest), _get(ev, "tag"))


def find_runs(events, threshold, min_run=2):
    """Maximal runs of consecutive small same-peer p2p ops.

    Returns a list of dicts: ``{"kind", "comm_key", "peer", "count",
    "total_bytes", "first_seq", "last_seq", "anchors"}``.  A run is
    reported when it has at least ``min_run`` members and its combined
    payload is at or below ``threshold`` bytes (``threshold <= 0``
    disables coalescing: no runs).  Consecutive small alltoalls on one
    comm are reported as ``kind="alltoall"`` runs (the
    ``alltoall_multi`` shape).
    """
    runs = []
    if threshold is None or threshold <= 0:
        return runs
    cur = None

    def flush():
        nonlocal cur
        if cur is not None and cur["count"] >= min_run:
            runs.append(cur)
        cur = None

    for ev in events or ():
        kind = str(_get(ev, "kind") or "")
        nbytes = message_bytes(ev)
        if kind in _P2P_KINDS:
            key = ("p2p", _peer_key(ev))
        elif kind in _A2A_KINDS:
            key = ("alltoall", str(_get(ev, "comm_key")))
        else:
            flush()
            continue
        if nbytes is None or key[1] is None:
            flush()
            continue
        if cur is not None and cur["_key"] == key and \
                cur["total_bytes"] + nbytes <= threshold:
            cur["count"] += 1
            cur["total_bytes"] += nbytes
            cur["last_seq"] = _get(ev, "seq")
            anchor = _get(ev, "src_info")
            if anchor and anchor not in cur["anchors"]:
                cur["anchors"].append(anchor)
            continue
        flush()
        if nbytes <= threshold:
            cur = {
                "_key": key,
                "kind": "alltoall" if key[0] == "alltoall" else "p2p",
                "comm_key": str(_get(ev, "comm_key")),
                "peer": None if key[0] == "alltoall" else key[1][1],
                "count": 1,
                "total_bytes": nbytes,
                "first_seq": _get(ev, "seq"),
                "last_seq": _get(ev, "seq"),
                "anchors": [a for a in [_get(ev, "src_info")] if a],
            }
    flush()
    for r in runs:
        r.pop("_key", None)
    return runs


def render_plan(runs, threshold):
    """Human-readable advisory (one line per run)."""
    if not runs:
        return (f"no coalescable runs at T4J_COALESCE_BYTES="
                f"{int(threshold)}")
    lines = [
        f"{len(runs)} coalescable run(s) at T4J_COALESCE_BYTES="
        f"{int(threshold)}:"
    ]
    for r in runs:
        where = f" ({r['anchors'][0]})" if r["anchors"] else ""
        target = ("alltoall_multi" if r["kind"] == "alltoall"
                  else "sendrecv_multi")
        lines.append(
            f"  steps {r['first_seq']}..{r['last_seq']}: {r['count']} "
            f"{r['kind']} op(s), {r['total_bytes']} bytes total -> one "
            f"fused frame via {target}{where}"
        )
    return "\n".join(lines)
