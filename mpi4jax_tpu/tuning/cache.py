"""On-disk tuning cache: fingerprint-keyed knob vectors.

One JSON file per topology fingerprint under the cache directory
(``T4J_TUNING_CACHE``, default ``~/.cache/mpi4jax_tpu``;
``T4J_TUNING_CACHE=off`` disables the cache entirely).  The file holds
the calibrated knob vector plus the measurements it was fitted from,
so ``t4j-diagnose`` can name both the file and the evidence.

Precedence is resolved per knob in :func:`resolve`: an explicitly set
``T4J_*`` environment variable always wins over a cached value, which
wins over the built-in default — the operator's hand on a knob must
never be silently overridden by a stale measurement.

stdlib only (package-stub loadable on old-jax containers); the loud
env validation lives in utils/config.py and already ran at bridge
init, so the local parser here only has to agree with it on valid
input.
"""

import json
import os
import pathlib

from mpi4jax_tpu.tuning.fingerprint import KNOB_SCHEMA_VERSION

__all__ = [
    "CACHE_SCHEMA_VERSION",
    "KNOBS",
    "KNOB_DEFAULTS",
    "cache_dir",
    "cache_path",
    "load",
    "store",
    "resolve",
]

# File-format version (independent of the knob schema: the file layout
# can evolve without invalidating measurements, and vice versa).
CACHE_SCHEMA_VERSION = 1

# The calibratable knob vector, env name -> cache key.  hier is the
# T4J_HIER mode string; stripes is "auto" or an int 1..16 (the wire
# dealing width, docs/performance.md "striped links"); wire_dtype is
# the compressed-collective mode string off|bf16|fp8
# (docs/performance.md "Compressed collectives"); wire_backend is the
# data-plane mode string auto|sendmsg|uring (docs/performance.md
# "io_uring wire backend"); everything else is a byte count.
KNOBS = {
    "T4J_RING_MIN_BYTES": "ring_min_bytes",
    "T4J_SEG_BYTES": "seg_bytes",
    "T4J_LEADER_RING_MIN_BYTES": "leader_ring_min_bytes",
    "T4J_HIER": "hier",
    "T4J_COALESCE_BYTES": "coalesce_bytes",
    "T4J_STRIPES": "stripes",
    "T4J_WIRE_DTYPE": "wire_dtype",
    "T4J_WIRE_BACKEND": "wire_backend",
}

KNOB_DEFAULTS = {
    "ring_min_bytes": 256 << 10,
    "seg_bytes": 1 << 20,
    "leader_ring_min_bytes": 256 << 10,
    "hier": "auto",
    "coalesce_bytes": 16 << 10,
    "stripes": "auto",
    "wire_dtype": "off",
    "wire_backend": "auto",
}

_WIRE_DTYPES = ("off", "bf16", "fp8")
_WIRE_BACKENDS = ("auto", "sendmsg", "uring")

_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}


def _parse_bytes(value):
    """Local K/M/G byte parser agreeing with utils.config.byte_count on
    valid input (invalid input already failed loudly at bridge init)."""
    s = str(value).strip()
    mult = 1
    if s and s[-1].lower() in _SUFFIX:
        mult = _SUFFIX[s[-1].lower()]
        s = s[:-1].strip()
    return int(s, 10) * mult


def cache_dir(env=None):
    """The cache directory, or ``None`` when disabled
    (``T4J_TUNING_CACHE=off``).

    With no explicit ``env`` this delegates to
    ``utils.config.tuning_cache_dir`` — ONE implementation of the
    default-path/"off" resolution — falling back to the local parse
    only for standalone loads where the config module is unreachable
    (the telemetry/recorder pattern).  The ``env`` parameter exists
    for the pure-core tests.
    """
    if env is None:
        try:
            from mpi4jax_tpu.utils import config

            v = config.tuning_cache_dir()
            return None if v is None else pathlib.Path(v)
        except Exception:
            env = os.environ
    v = str(env.get("T4J_TUNING_CACHE") or "").strip()
    if v.lower() == "off":
        return None
    if v:
        return pathlib.Path(v)
    return pathlib.Path(os.path.expanduser("~")) / ".cache" / "mpi4jax_tpu"


def cache_path(directory, fingerprint):
    return pathlib.Path(directory) / f"t4j-tuning-{fingerprint}.json"


def load(path, fingerprint, knob_schema=KNOB_SCHEMA_VERSION):
    """Load and validate a cache file.

    Returns the cache object, or ``None`` when the file is missing,
    unreadable, written under another cache/knob schema, or carries a
    different fingerprint (a renamed/copied file must not smuggle a
    foreign fabric's knobs in).
    """
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(obj, dict):
        return None
    if obj.get("cache_schema") != CACHE_SCHEMA_VERSION:
        return None
    if obj.get("knob_schema") != knob_schema:
        return None
    if obj.get("fingerprint") != fingerprint:
        return None
    if not isinstance(obj.get("knobs"), dict):
        return None
    return obj


def store(path, fingerprint, knobs, measurements=None,
          knob_schema=KNOB_SCHEMA_VERSION):
    """Atomically write a cache file; returns the path."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    obj = {
        "cache_schema": CACHE_SCHEMA_VERSION,
        "knob_schema": knob_schema,
        "fingerprint": fingerprint,
        "knobs": {k: knobs[k] for k in knobs},
        "measurements": measurements or [],
    }
    tmp = path.with_suffix(f".tmp{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
    os.replace(tmp, path)
    return path


def resolve(cache_knobs, env=None):
    """Per-knob effective value + provenance.

    Returns ``(knobs, sources)`` — ``knobs`` maps cache key -> value,
    ``sources`` maps cache key -> ``"env" | "cache" | "default"``.
    An explicitly set (non-empty) env var wins over the cache, which
    wins over the default.
    """
    env = os.environ if env is None else env
    cache_knobs = cache_knobs or {}
    knobs, sources = {}, {}
    for env_name, key in KNOBS.items():
        raw = env.get(env_name)
        explicit = raw is not None and str(raw).strip() != ""
        if explicit and key in ("stripes", "wire_backend") \
                and str(raw).strip().lower() == "auto":
            # "auto" is the ask-the-calibrator value, not an operator
            # override: a cached fitted width/backend must still win
            # over it
            explicit = False
        if explicit:
            if key in ("hier", "wire_dtype", "wire_backend"):
                knobs[key] = str(raw).strip().lower()
            elif key == "stripes":
                s = str(raw).strip().lower()
                knobs[key] = "auto" if s == "auto" else int(s, 10)
            else:
                knobs[key] = _parse_bytes(raw)
            sources[key] = "env"
        elif key in cache_knobs and cache_knobs[key] is not None:
            v = cache_knobs[key]
            if key == "hier":
                knobs[key] = str(v)
            elif key == "wire_dtype":
                # a cache file edited to an unknown dtype must not
                # smuggle an un-runnable mode past config validation
                knobs[key] = str(v) if str(v) in _WIRE_DTYPES else "off"
            elif key == "wire_backend":
                # same smuggle guard: an edited cache must not name a
                # backend config validation would have rejected
                knobs[key] = (
                    str(v) if str(v) in _WIRE_BACKENDS else "auto"
                )
            elif key == "stripes":
                knobs[key] = "auto" if str(v) == "auto" else int(v)
            else:
                knobs[key] = int(v)
            sources[key] = "cache"
        else:
            knobs[key] = KNOB_DEFAULTS[key]
            sources[key] = "default"
    return knobs, sources
