"""Topology fingerprint for the on-disk tuning cache.

A cached knob vector is only valid for the fabric it was measured on:
the same process count, the same host layout (hosts x locals-per-host
— the inputs the hierarchical-plane selection is built from), and the
same knob schema (a knob added or re-interpreted invalidates every
older cache).  The fingerprint hashes exactly those inputs; anything
else (link health, co-tenant load) is deliberately NOT covered — see
docs/sharp-bits.md "stale tuning caches" for why a cache can go stale
without the fingerprint changing.

stdlib only: the pure-core tests (tests/test_tuning.py) load this on
old-jax containers through the package-stub loader.
"""

import hashlib
import json

__all__ = ["KNOB_SCHEMA_VERSION", "topology_fingerprint"]

# Bump whenever the knob vector's meaning changes (a knob added,
# removed, or re-interpreted): caches written under another schema are
# ignored wholesale rather than half-applied.
# v2: the `stripes` knob joined the vector (striped multi-connection
# links, docs/performance.md "striped links and the zero-copy path").
# v3: the `wire_dtype` knob joined the vector (compressed collectives,
# docs/performance.md "Compressed collectives").
# v4: the `wire_backend` knob joined the vector (io_uring data plane,
# docs/performance.md "io_uring wire backend").
KNOB_SCHEMA_VERSION = 4


def topology_fingerprint(topology, world_size,
                         schema_version=KNOB_SCHEMA_VERSION):
    """Stable hex fingerprint of (host layout, nprocs, knob schema).

    ``topology`` is the bridge's bootstrap map (``runtime.topology()``:
    ``{"n_hosts", ...}``) or ``None``/``{}`` for a single-host world
    with no native topology.  Only rank-invariant fields participate:
    per-rank fields (``host_id``, ``local_rank``, ``leader_rank``)
    would make ranks disagree on the fingerprint, and so would the
    raw ``local_size`` on an UNEVEN host layout (a 6+2 split gives
    different values per host) — locals-per-host is therefore derived
    as ``ceil(nprocs / n_hosts)``, which every rank computes
    identically.
    """
    topo = topology or {}
    n_hosts = int(topo.get("n_hosts", 1) or 1)
    basis = {
        "schema": int(schema_version),
        "nprocs": int(world_size),
        "n_hosts": n_hosts,
        "locals_per_host": -(-int(world_size) // max(n_hosts, 1)),
    }
    digest = hashlib.sha256(
        json.dumps(basis, sort_keys=True).encode("utf-8")
    ).hexdigest()
    return digest[:16]
