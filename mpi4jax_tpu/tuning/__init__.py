"""Trace-guided autotuning and small-message coalescing
(docs/performance.md "trace-guided autotuning").

Two coupled halves:

* **Autotuner** — :mod:`tuning.calibrate` self-measures the data-plane
  knob vector (ring/tree and flat/hier crossovers, segment size,
  coalescing threshold) through the existing native ops using the PR-6
  telemetry metrics table, and :mod:`tuning.cache` persists the fit in
  an on-disk cache keyed by a topology fingerprint
  (:mod:`tuning.fingerprint`).  :func:`startup` runs at
  ``runtime.ensure_initialized``: load cache -> resolve (explicit
  ``T4J_*`` env always wins) -> broadcast rank 0's resolution ->
  thread through the existing ``set_tuning``/``set_hier``/
  ``set_coalesce`` plumbing.  ``T4J_AUTOTUNE=1`` (the launcher's
  ``--autotune``) calibrates first and writes the cache.

* **Coalescer** — :mod:`tuning.coalesce` plans fused wire frames from
  the analyzer's recorded schedules; the ops layer applies the same
  ``T4J_COALESCE_BYTES`` gate at run time via
  :func:`coalesce_eligible`.

This package is import-free of jax (like analysis/contracts.py and
telemetry/), so the pure core runs on old-jax containers through the
package-stub loader (tests/test_tuning.py).
"""

import os

from mpi4jax_tpu.tuning import cache, calibrate, coalesce, fingerprint
from mpi4jax_tpu.tuning.cache import KNOB_DEFAULTS, resolve
from mpi4jax_tpu.tuning.fingerprint import (
    KNOB_SCHEMA_VERSION,
    topology_fingerprint,
)

__all__ = [
    "cache",
    "calibrate",
    "coalesce",
    "fingerprint",
    "KNOB_DEFAULTS",
    "KNOB_SCHEMA_VERSION",
    "topology_fingerprint",
    "resolve",
    "startup",
    "effective",
    "coalesce_bytes",
    "coalesce_eligible",
    "override_coalesce",
    "autotune_and_store",
]

# The job's effective tuning after startup(): {"knobs", "sources",
# "fingerprint", "cache_file", "autotuned"}.  None before startup (or
# outside multi-process jobs) — readers fall back to env/defaults.
_state = {"effective": None, "coalesce_override": None}


def effective():
    """The effective tuning meta recorded at startup, or ``None``."""
    return _state["effective"]


def _reset():
    """Test hook."""
    _state["effective"] = None
    _state["coalesce_override"] = None


def coalesce_bytes():
    """The effective coalescing threshold in bytes (0 = fusion off).

    Resolution: explicit override (:func:`override_coalesce`, used by
    benchmarks to flip sides in interleaved pairs) > the startup
    resolution (env > cache > default) > env/default for jobs that
    never ran startup."""
    ov = _state["coalesce_override"]
    if ov is not None:
        return int(ov)
    eff = _state["effective"]
    if eff is not None:
        return int(eff["knobs"]["coalesce_bytes"])
    raw = os.environ.get("T4J_COALESCE_BYTES")
    if raw is not None and str(raw).strip() != "":
        try:
            return cache._parse_bytes(raw)
        except ValueError:
            return KNOB_DEFAULTS["coalesce_bytes"]
    return KNOB_DEFAULTS["coalesce_bytes"]


def coalesce_eligible(total_bytes, nparts):
    """Should a run of ``nparts`` messages totalling ``total_bytes``
    travel as one fused frame?  A single message gains nothing (the
    fused sub-header is pure overhead), and 0 disables fusion — the
    exact pre-coalescing wire behaviour."""
    if nparts < 2:
        return False
    threshold = coalesce_bytes()
    return threshold > 0 and int(total_bytes) <= threshold


def override_coalesce(bytes_or_none):
    """Force the coalescing threshold for this process (``None``
    restores the startup/env resolution).  Benchmark plumbing for
    interleaved on/off pairs; uniform-across-ranks rules apply exactly
    as for the env knob.  Mirrors into the native knob so standalone
    introspection agrees."""
    _state["coalesce_override"] = (
        None if bytes_or_none is None else int(bytes_or_none)
    )
    try:
        from mpi4jax_tpu.native import runtime

        runtime.set_coalesce(coalesce_bytes())
    except Exception:
        pass


def autotune_and_store(progress=None):
    """Calibrate (collective!) and persist the fit on rank 0; returns
    the fitted knob dict.  Requires an initialized bridge."""
    import sys

    from mpi4jax_tpu.native import runtime

    knobs, measurements = calibrate.autotune(progress=progress)
    topo = runtime.topology()
    # the EFFECTIVE world (elastic resizes shrink it): a resized job
    # fingerprints — and caches — as the topology it actually runs on
    fp = topology_fingerprint(topo, runtime.effective_world_size())
    directory = cache.cache_dir()
    if directory is not None and runtime.world_rank() == 0:
        merged = dict(KNOB_DEFAULTS)
        merged.update({k: v for k, v in knobs.items() if v is not None})
        try:
            cache.store(cache.cache_path(directory, fp), fp, merged,
                        measurements)
        except OSError as e:
            # an unwritable cache dir must not take the job down — and
            # CRUCIALLY must not stop rank 0 short of the knob
            # broadcast in startup(), where every other rank is
            # already blocked (they would sit until the op deadline)
            print(
                f"t4j: tuning cache not persisted "
                f"({type(e).__name__}: {e}); the fit still applies to "
                "this job",
                file=sys.stderr,
                flush=True,
            )
    return knobs


_HIER_CODES = {"auto": 0, "on": 1, "off": 2}
_HIER_NAMES = {v: k for k, v in _HIER_CODES.items()}

_WIRE_CODES = {"off": 0, "bf16": 1, "fp8": 2}
_WIRE_NAMES = {v: k for k, v in _WIRE_CODES.items()}

_BACKEND_CODES = {"auto": 0, "sendmsg": 1, "uring": 2}
_BACKEND_NAMES = {v: k for k, v in _BACKEND_CODES.items()}


def startup(progress=None):
    """Load/resolve/apply the tuning vector for this job (called from
    ``runtime.ensure_initialized`` after bootstrap; idempotent enough
    to re-run, the last application wins).

    Rank 0's resolution is broadcast to every rank before applying:
    ranks can legitimately see different cache files (per-host
    filesystems), and a divergent knob vector would run mismatched
    wire algorithms and deadlock.
    """
    from mpi4jax_tpu.native import runtime

    if not runtime.is_initialized():
        return None

    topo = runtime.topology()
    # the EFFECTIVE world: after an elastic resize the topology
    # fingerprint changes with the membership, so
    # runtime.refresh_after_resize() re-resolving through here lands
    # on the resized world's own cache entry
    world = runtime.effective_world_size()
    fp = topology_fingerprint(topo, world)
    directory = cache.cache_dir()
    cache_file = None
    cached = None
    if directory is not None:
        path = cache.cache_path(directory, fp)
        cached = cache.load(path, fp)
        if cached is not None:
            cache_file = str(path)

    autotuned = False
    try:
        from mpi4jax_tpu.utils import config

        want_autotune = config.truthy(
            os.environ.get("T4J_AUTOTUNE"), default=False
        )
    except Exception:
        want_autotune = str(
            os.environ.get("T4J_AUTOTUNE", "")
        ).strip().lower() in ("1", "true", "on", "yes")
    if want_autotune:
        # calibration is collective: every rank reaches here from
        # ensure_initialized before any user traffic
        fitted = autotune_and_store(progress=progress)
        cached = {"knobs": fitted}
        cache_file = (
            str(cache.cache_path(directory, fp))
            if directory is not None else None
        )
        autotuned = True

    knobs, sources = resolve((cached or {}).get("knobs"))

    if world > 1:
        # rank 0's resolution wins everywhere (uniformity contract).
        # The per-knob provenance rides along: without it a rank whose
        # own filesystem has no cache file would record
        # sources="default" for values that actually came from rank
        # 0's cache — and t4j-diagnose would then name the wrong knob
        # origin in the post-mortem.
        import numpy as np

        src_codes = {"default": 0, "cache": 1, "env": 2}
        src_names = {v: k for k, v in src_codes.items()}
        order = ("ring_min_bytes", "seg_bytes", "leader_ring_min_bytes",
                 "hier", "coalesce_bytes", "stripes", "wire_dtype",
                 "wire_backend")
        # stripes travels as an int: 0 encodes "auto" (no fitted width)
        stripes_v = knobs.get("stripes", "auto")
        vec = np.asarray(
            [
                knobs["ring_min_bytes"],
                knobs["seg_bytes"],
                knobs["leader_ring_min_bytes"],
                _HIER_CODES.get(knobs["hier"], 0),
                knobs["coalesce_bytes"],
                0 if stripes_v == "auto" else int(stripes_v),
                _WIRE_CODES.get(knobs.get("wire_dtype", "off"), 0),
                _BACKEND_CODES.get(
                    knobs.get("wire_backend", "auto"), 0
                ),
                *[src_codes.get(sources[k], 0) for k in order],
            ],
            np.int64,
        )
        vec = runtime.host_bcast(0, vec, 0)
        knobs = {
            "ring_min_bytes": int(vec[0]),
            "seg_bytes": int(vec[1]),
            "leader_ring_min_bytes": int(vec[2]),
            "hier": _HIER_NAMES.get(int(vec[3]), "auto"),
            "coalesce_bytes": int(vec[4]),
            "stripes": "auto" if int(vec[5]) == 0 else int(vec[5]),
            "wire_dtype": _WIRE_NAMES.get(int(vec[6]), "off"),
            "wire_backend": _BACKEND_NAMES.get(int(vec[7]), "auto"),
        }
        sources = {
            k: src_names.get(int(vec[8 + i]), "default")
            for i, k in enumerate(order)
        }

    runtime.set_tuning(
        ring_min_bytes=knobs["ring_min_bytes"],
        seg_bytes=knobs["seg_bytes"],
    )
    runtime.set_hier(
        mode=knobs["hier"],
        leader_ring_min_bytes=knobs["leader_ring_min_bytes"],
    )
    runtime.set_coalesce(knobs["coalesce_bytes"])
    # wire dealing width (docs/performance.md "striped links"): a
    # fitted/cached width applies up to the BUILT width (connections
    # are fixed at bootstrap — a cached 4 on a world built with 1
    # takes effect on the next striped launch, not this one); "auto"
    # keeps the native default
    if knobs.get("stripes", "auto") != "auto":
        runtime.set_wire(stripes=int(knobs["stripes"]))
    # compressed-collective wire dtype (docs/performance.md
    # "Compressed collectives"): a fitted/cached mode applies at
    # runtime like the dealing width — the uniformity contract rides
    # the same rank-0 broadcast as every other knob
    runtime.set_wire_dtype(knobs.get("wire_dtype", "off"))
    # wire data-plane backend (docs/performance.md "io_uring wire
    # backend"): a fitted/cached backend applies at runtime — the
    # native layer degrades loudly to sendmsg if this kernel cannot
    # honour a cached "uring" (cache written on another machine);
    # "auto" keeps the native default (sendmsg)
    if knobs.get("wire_backend", "auto") != "auto":
        runtime.set_wire_backend(knobs["wire_backend"])

    eff = {
        "knobs": dict(knobs),
        "sources": dict(sources),
        "fingerprint": fp,
        "cache_file": cache_file,
        "autotuned": autotuned,
    }
    _state["effective"] = eff
    return eff
