"""Calibration driver: self-measure the data-plane knob vector.

The shipped ``T4J_*`` defaults were measured once on one loopback box
(docs/performance.md) and are wrong everywhere else.  This driver runs
a few timed rounds per comm x size-bucket x plane through the EXISTING
native ops, measured via the PR-6 telemetry metrics table (snapshot
deltas of the per-op latency histograms — no new timing code path),
fits the crossovers, and hands the result to :mod:`tuning.cache`.

Two layers:

* pure fitters (:func:`fit_crossover`, :func:`fit_seg`,
  :func:`fit_coalesce`, :func:`fit_records`) — stdlib only, consumed
  by the ``proc_busbw.py --calibrate`` JSON as well, unit-tested on
  old-jax containers;
* the collective driver (:func:`autotune`) — every rank runs the SAME
  arm schedule, each arm's measured time is max-reduced across ranks
  through the native allreduce so all ranks fit the identical knob
  vector (a collective is only as fast as its slowest member, and a
  divergent fit would desynchronise the data plane).
"""

import time

__all__ = [
    "DEFAULT_SIZES",
    "SEG_CANDIDATES",
    "COALESCE_SIZES",
    "STRIPE_MARGIN",
    "WIRE_MARGIN",
    "BACKEND_MARGIN",
    "fit_crossover",
    "fit_seg",
    "fit_coalesce",
    "fit_stripes",
    "fit_wire_dtype",
    "fit_wire_backend",
    "fit_records",
    "autotune",
]

# Size ladder straddling both shipped crossover defaults (256 KiB).
DEFAULT_SIZES = (16 << 10, 64 << 10, 256 << 10, 1 << 20, 4 << 20)
# Segment candidates around the shipped 1 MiB default.
SEG_CANDIDATES = (128 << 10, 256 << 10, 512 << 10, 1 << 20, 2 << 20)
# Combined-payload sizes for the fused-vs-unfused p2p pair (4 parts).
COALESCE_SIZES = (1 << 10, 4 << 10, 16 << 10, 64 << 10, 256 << 10)


# --------------------------------------------------------------- fitters


def fit_crossover(points):
    """Tree->ring (or flat->hier) switchover from paired timings.

    ``points``: iterable of ``(size_bytes, small_ms, big_ms)`` where
    ``small_ms`` is the latency-optimal arm (tree/flat) and ``big_ms``
    the bandwidth-optimal arm (ring/hier).  Returns the switchover in
    bytes: the boundary that minimises the total time of always
    choosing the small arm below it and the big arm at/above it.  This
    is robust to a noisy single inversion, unlike "first size where
    big wins".  Falls back to ``None`` on no data.
    """
    pts = sorted((int(s), float(a), float(b)) for s, a, b in points)
    if not pts:
        return None
    # candidate boundaries: below everything, between sizes, above all
    bounds = [pts[0][0]] + [pts[i][0] for i in range(1, len(pts))] + [
        pts[-1][0] * 4
    ]
    best_bound, best_cost = None, None
    for bound in bounds:
        cost = sum(a if s < bound else b for s, a, b in pts)
        if best_cost is None or cost < best_cost:
            best_bound, best_cost = bound, cost
    return int(best_bound)


def fit_seg(points):
    """Best ring segment size from ``(seg_bytes, ms)`` pairs (argmin;
    ties break toward the larger segment — fewer per-segment deadline
    checks).  ``None`` on no data."""
    pts = sorted(((float(ms), -int(seg)) for seg, ms in points))
    if not pts:
        return None
    return -pts[0][1]


def fit_coalesce(points):
    """Coalescing threshold from fused-vs-unfused pairs.

    ``points``: ``(total_bytes, fused_ms, unfused_ms)``.  Returns the
    largest total size at which fusing won (as the inclusive
    threshold), or 0 when fusing never won (coalescing off).
    """
    best = 0
    for total, fused, unfused in points:
        if float(fused) < float(unfused) and int(total) > best:
            best = int(total)
    return best


# A wider dealing still has to EARN its keep: below this speedup over
# one flow the fit keeps stripes=1 (reorder bookkeeping and extra
# sockets are pure overhead when one flow already fills the pipe —
# the "within 5% of single-flow when striping is not profitable"
# contract, docs/performance.md "striped links").
STRIPE_MARGIN = 1.05


def fit_stripes(points, margin=STRIPE_MARGIN):
    """Dealing width from ``(stripes, ms)`` pairs: the fastest width,
    except that any width > 1 must beat width 1 by ``margin`` —
    otherwise 1 wins (striping that is not profitable must cost
    nothing).  ``None`` on no data."""
    pts = {int(s): float(ms) for s, ms in points}
    if not pts:
        return None
    base = pts.get(1)
    best, best_ms = None, None
    for s, ms in sorted(pts.items()):
        if best_ms is None or ms < best_ms:
            best, best_ms = s, ms
    if best is None or best == 1:
        return 1 if 1 in pts else best
    if base is not None and base <= best_ms * margin:
        return 1
    return best


# A compressed wire dtype has to EARN its keep the same way a wider
# dealing does: below this speedup over the exact f32 wire the fit
# keeps off — off is additionally bit-exact, so a tie must never tip
# toward compression.  On bandwidth-bound (DCN/flow-capped) planes
# halved bytes clear the margin easily; on shm/loopback planes the
# cast passes are pure overhead and off wins (docs/performance.md
# "Compressed collectives").
WIRE_MARGIN = 1.05


def fit_wire_dtype(points, margin=WIRE_MARGIN):
    """Compressed wire dtype from ``(mode, ms)`` pairs
    (``off``/``bf16``/``fp8``): the fastest mode, except any
    compressed mode must beat ``off`` by ``margin`` — otherwise
    ``off`` wins (compression that is not profitable must cost
    nothing, and only off is bit-exact).  ``None`` on no data."""
    pts = {str(m): float(ms) for m, ms in points}
    if not pts:
        return None
    base = pts.get("off")
    best, best_ms = None, None
    for m, ms in sorted(pts.items()):
        if best_ms is None or ms < best_ms:
            best, best_ms = m, ms
    if best is None or best == "off":
        return "off" if "off" in pts else best
    if base is not None and base <= best_ms * margin:
        return "off"
    return best


# The io_uring data plane has to EARN its keep the same way: below
# this speedup over the classic sendmsg loops the fit keeps sendmsg —
# sendmsg is additionally the backend every prior release shipped, so
# a tie must never tip toward the newer data plane.  Batched
# submission pays on small-frame (syscall-bound) traffic; on payloads
# where one sendmsg already moves megabytes the submission ring saves
# nothing (docs/performance.md "io_uring wire backend").
BACKEND_MARGIN = 1.05


def fit_wire_backend(points, margin=BACKEND_MARGIN):
    """Wire backend from ``(backend, ms)`` pairs
    (``sendmsg``/``uring``): the fastest backend, except ``uring``
    must beat ``sendmsg`` by ``margin`` — otherwise ``sendmsg`` wins
    (a data plane that is not profitable must cost nothing, and
    sendmsg is the longest-proven path).  ``None`` on no data."""
    pts = {str(b): float(ms) for b, ms in points}
    if not pts:
        return None
    base = pts.get("sendmsg")
    best, best_ms = None, None
    for b, ms in sorted(pts.items()):
        if best_ms is None or ms < best_ms:
            best, best_ms = b, ms
    if best is None or best == "sendmsg":
        return "sendmsg" if "sendmsg" in pts else best
    if base is not None and base <= best_ms * margin:
        return "sendmsg"
    return best


def fit_records(records):
    """Fit the knob vector from ``proc_busbw.py --calibrate`` JSON
    records (each: ``{"arm", "payload_bytes", "mean_ms", ...}``, arms
    ``tree|ring|hier|flat|seg:<bytes>|stripes:<n>|wire:<dtype>|``
    ``backend:<sendmsg|uring>|fused|unfused``).

    Returns a partial knob dict (only the knobs the records cover).
    """
    by = {}
    for r in records or ():
        by.setdefault(str(r.get("arm")), []).append(r)

    def pair(small_arm, big_arm):
        small = {int(r["payload_bytes"]): float(r["mean_ms"])
                 for r in by.get(small_arm, ())}
        big = {int(r["payload_bytes"]): float(r["mean_ms"])
               for r in by.get(big_arm, ())}
        return [(s, small[s], big[s]) for s in sorted(small)
                if s in big]

    knobs = {}
    ring_pts = pair("tree", "ring")
    if ring_pts:
        knobs["ring_min_bytes"] = fit_crossover(ring_pts)
    seg_pts = []
    for arm, rows in by.items():
        if arm.startswith("seg:"):
            for r in rows:
                seg_pts.append((int(arm[4:]), float(r["mean_ms"])))
    if seg_pts:
        knobs["seg_bytes"] = fit_seg(seg_pts)
    stripe_pts = []
    for arm, rows in by.items():
        if arm.startswith("stripes:"):
            for r in rows:
                stripe_pts.append((int(arm[8:]), float(r["mean_ms"])))
    if stripe_pts:
        knobs["stripes"] = fit_stripes(stripe_pts)
    wire_pts = []
    for arm, rows in by.items():
        if arm.startswith("wire:"):
            for r in rows:
                wire_pts.append((arm[5:], float(r["mean_ms"])))
    if wire_pts:
        knobs["wire_dtype"] = fit_wire_dtype(wire_pts)
    backend_pts = []
    for arm, rows in by.items():
        if arm.startswith("backend:"):
            for r in rows:
                backend_pts.append((arm[8:], float(r["mean_ms"])))
    if backend_pts:
        knobs["wire_backend"] = fit_wire_backend(backend_pts)
    hier_pts = pair("flat", "hier")
    if hier_pts:
        knobs["leader_ring_min_bytes"] = fit_crossover(hier_pts)
        knobs["hier"] = "auto"
    co_pts = pair("unfused", "fused")
    if co_pts:
        # pair() returns (size, unfused, fused); fit wants (size, fused,
        # unfused)
        knobs["coalesce_bytes"] = fit_coalesce(
            [(s, f, u) for s, u, f in co_pts]
        )
    return knobs


# --------------------------------------------------------------- driver


def _metrics_registry(runtime):
    from mpi4jax_tpu.telemetry.registry import MetricsRegistry

    words = runtime.metrics_snapshot()
    return MetricsRegistry.from_snapshot(words) if words else None


def _measure_arm(runtime, run_one, op, reps):
    """Wall time per rep of ``run_one`` measured through the telemetry
    metrics table (snapshot delta over the window, docs/observability.md)
    — the PR-6 measurement path, not new timing code.  Falls back to
    wall-clock when the table is unavailable (telemetry hard-off)."""
    t0 = time.perf_counter()
    before = _metrics_registry(runtime)
    for _ in range(reps):
        run_one()
    wall = (time.perf_counter() - t0) / reps
    after = _metrics_registry(runtime)
    if before is not None and after is not None:
        row = after.diff(before).aggregate(op=op)
        if row is not None and row.count:
            s = row.stats()
            if s["mean_ms"]:
                # total measured op time in the window, per rep
                return s["mean_ms"] * s["count"] / reps
    return wall * 1e3


def autotune(sizes=None, seg_candidates=None, coalesce_sizes=None,
             reps=5, progress=None):
    """Collective knob calibration on the world communicator.

    Every rank must call this at the same point (it runs real
    collectives).  Returns ``(knobs, measurements)`` — identical on
    every rank (per-arm times are MAX-reduced across ranks before the
    fit).  The caller owns persisting/applying the result
    (:func:`tuning.startup` does both for ``--autotune`` runs).

    The ladders default to the MODULE attributes at call time (not at
    def time), so a harness that shrinks ``calibrate.DEFAULT_SIZES``
    before calling :func:`tuning.startup` actually shrinks the run.
    """
    import numpy as np

    from mpi4jax_tpu.native import runtime

    if sizes is None:
        sizes = DEFAULT_SIZES
    if seg_candidates is None:
        seg_candidates = SEG_CANDIDATES
    if coalesce_sizes is None:
        coalesce_sizes = COALESCE_SIZES

    lib = runtime._state["lib"]
    if lib is None or not lib.t4j_initialized():
        raise RuntimeError("autotune requires an initialized bridge")
    world = 0  # pre-created world communicator handle
    n = int(lib.t4j_comm_size(world))
    me = int(lib.t4j_comm_rank(world))

    # measurement rides the PR-6 metrics table: make sure it counts
    prev_mode = runtime.telemetry_mode_name()
    if prev_mode == "off":
        runtime.set_telemetry(mode="counters")

    def say(msg):
        if progress is not None and me == 0:
            progress(f"[autotune] {msg}")

    def sync_max(ms):
        """MAX across ranks so every rank fits identical numbers."""
        out = runtime.host_allreduce(
            world, np.asarray([ms], np.float64), 3  # 3 = MAX
        )
        return float(out[0])

    measurements = []

    def arm(name, payload_bytes, op, run_one):
        runtime.host_barrier(world)
        run_one()  # warm (negotiation, first-touch) outside the window
        runtime.host_barrier(world)
        ms = sync_max(_measure_arm(runtime, run_one, op, reps))
        measurements.append(
            {"arm": name, "payload_bytes": int(payload_bytes),
             "mean_ms": ms, "op": op}
        )
        return ms

    # ---- ring_min: tree vs ring per size --------------------------------
    ring_pts = []
    for size in sizes:
        count = max(size // 4, n)
        x = np.ones(count, np.float32)
        run = lambda: runtime.host_allreduce(world, x, 0)  # noqa: E731
        runtime.set_tuning(ring_min_bytes=1 << 40)  # force trees
        t_tree = arm("tree", count * 4, "allreduce", run)
        runtime.set_tuning(ring_min_bytes=0)  # force ring
        t_ring = arm("ring", count * 4, "allreduce", run)
        ring_pts.append((count * 4, t_tree, t_ring))
        say(f"allreduce {count * 4}B: tree {t_tree:.3f}ms "
            f"ring {t_ring:.3f}ms")
    knobs = {"ring_min_bytes": fit_crossover(ring_pts)}

    # ---- seg: ring segment size at the largest payload ------------------
    big = max(sizes)
    count = max(big // 4, n)
    x = np.ones(count, np.float32)
    runtime.set_tuning(ring_min_bytes=0)
    seg_pts = []
    for seg in seg_candidates:
        runtime.set_tuning(seg_bytes=seg)
        ms = arm(f"seg:{seg}", count * 4, "allreduce",
                 lambda: runtime.host_allreduce(world, x, 0))
        seg_pts.append((seg, ms))
        say(f"seg {seg}B: {ms:.3f}ms")
    knobs["seg_bytes"] = fit_seg(seg_pts)

    # ---- stripes: dealing width at the largest payload ------------------
    #
    # The BUILT width is fixed at bootstrap (connections exist or they
    # do not), so the arm A/Bs the runtime DEALING width 1..built
    # inside one world — only meaningful when the job was launched
    # striped (T4J_STRIPES >= 2; proc_busbw --stripes and --autotune
    # runs do that).  The fitted width is cached for the fabric; a
    # width that does not beat single-flow by STRIPE_MARGIN fits 1, so
    # unprofitable striping costs nothing (docs/performance.md
    # "striped links and the zero-copy path").
    winfo = runtime.wire_info() or {}
    built = int(winfo.get("stripes_built", 1) or 1)
    if built > 1 and n > 1:
        count = max(big // 4, n)
        x = np.ones(count, np.float32)
        widths = sorted({1, 2, built} & set(range(1, built + 1)))
        stripe_pts = []
        for w in widths:
            runtime.set_wire(stripes=w)
            ms = arm(f"stripes:{w}", count * 4, "allreduce",
                     lambda: runtime.host_allreduce(world, x, 0))
            stripe_pts.append((w, ms))
            say(f"stripes {w}: {ms:.3f}ms")
        runtime.set_wire(stripes=built)  # restore full width for the rest
        knobs["stripes"] = fit_stripes(stripe_pts)

    # ---- wire dtype: compressed vs exact f32 at the largest payload -----
    #
    # The mode is runtime-changeable like the dealing width, and — key
    # property — a wire dtype that cannot engage (shm arena plane,
    # same-host ring hops, non-f32/SUM payloads) changes NOTHING on
    # the wire, so the arms are always safe to run: where compression
    # never engages the three arms measure equal within noise and the
    # margin fits `off`, which is exactly the wanted verdict for the
    # shm plane (docs/performance.md "Compressed collectives").
    if n > 1:
        count = max(big // 4, n)
        x = np.ones(count, np.float32)
        wire_pts = []
        for wmode in ("off", "bf16", "fp8"):
            runtime.set_wire_dtype(wmode)
            ms = arm(f"wire:{wmode}", count * 4, "allreduce",
                     lambda: runtime.host_allreduce(world, x, 0))
            wire_pts.append((wmode, ms))
            say(f"wire {wmode}: {ms:.3f}ms")
        runtime.set_wire_dtype("off")  # exact wire for the remaining arms
        knobs["wire_dtype"] = fit_wire_dtype(wire_pts)

    # ---- wire backend: sendmsg vs io_uring at the smallest payload ------
    #
    # Batched SQ submission pays where the wire is syscall-bound —
    # small frames, the decode-step and compressed-latency regime —
    # so the arm A/Bs at the SMALLEST ladder size, not the largest.
    # Both backends put identical bytes on the wire (the arms are
    # always safe); a kernel without io_uring skips the arm entirely
    # and the fit records nothing rather than a fake tie
    # (docs/performance.md "io_uring wire backend").
    if n > 1 and (runtime.wire_backend_info() or {}).get("uring_supported"):
        small = min(sizes)
        count = max(small // 4, n)
        x = np.ones(count, np.float32)
        backend_pts = []
        for bmode in ("sendmsg", "uring"):
            runtime.set_wire_backend(bmode)
            ms = arm(f"backend:{bmode}", count * 4, "allreduce",
                     lambda: runtime.host_allreduce(world, x, 0))
            backend_pts.append((bmode, ms))
            say(f"backend {bmode}: {ms:.3f}ms")
        runtime.set_wire_backend("auto")  # native default for the rest
        knobs["wire_backend"] = fit_wire_backend(backend_pts)

    # ---- hier: flat vs hierarchical per size (topology permitting) ------
    topo = runtime.topology() or {}
    if int(topo.get("n_hosts", 1)) > 1 and int(topo.get("local_size", 1)) > 1:
        hier_pts = []
        for size in sizes:
            count = max(size // 4, n)
            x = np.ones(count, np.float32)
            run = lambda: runtime.host_allreduce(world, x, 0)  # noqa: E731
            runtime.set_hier(mode="off")
            t_flat = arm("flat", count * 4, "allreduce", run)
            runtime.set_hier(mode="on")
            t_hier = arm("hier", count * 4, "allreduce", run)
            hier_pts.append((count * 4, t_flat, t_hier))
            say(f"hier {count * 4}B: flat {t_flat:.3f}ms "
                f"hier {t_hier:.3f}ms")
        knobs["leader_ring_min_bytes"] = fit_crossover(hier_pts)
        knobs["hier"] = "auto"
        runtime.set_hier(mode="auto")

    # ---- coalesce: fused vs unfused 4-part neighbour exchange -----------
    if n > 1:
        dest, source = (me + 1) % n, (me - 1) % n
        co_pts = []
        for total in coalesce_sizes:
            # 4 float32 parts summing to ~total bytes
            part = max(total // 16, 1)
            parts = [np.full(part, float(i), np.float32)
                     for i in range(4)]
            tmpl = [np.empty(part, np.float32) for _ in range(4)]

            def fused():
                runtime.host_sendrecv_fused(
                    world, parts, tmpl, source, dest, 31, 31
                )

            def unfused():
                for p, t in zip(parts, tmpl):
                    runtime.host_sendrecv(world, p, t, source, dest,
                                          32, 32)

            t_f = arm("fused", part * 16, "sendrecv", fused)
            t_u = arm("unfused", part * 16, "sendrecv", unfused)
            co_pts.append((part * 16, t_f, t_u))
            say(f"coalesce {part * 16}B: fused {t_f:.3f}ms "
                f"unfused {t_u:.3f}ms")
        knobs["coalesce_bytes"] = fit_coalesce(co_pts)

    if prev_mode == "off":
        runtime.set_telemetry(mode="off")
    say(f"fitted {knobs}")
    return knobs, measurements
