"""Drop-in call-surface compatibility with the reference (mpi4jax +
mpi4py).

A user of the reference writes (README.rst:61-80 there):

    from mpi4py import MPI
    import mpi4jax

    comm = MPI.COMM_WORLD
    rank = comm.Get_rank()
    res, token = mpi4jax.allreduce(x, op=MPI.SUM, comm=comm)

The same program runs here with only the imports changed:

    from mpi4jax_tpu import compat as mpi4jax
    from mpi4jax_tpu.compat import MPI

``MPI`` exposes the reduction operators (``SUM``, ``PROD``, ... — these
ARE :class:`mpi4jax_tpu.Op` objects, no translation layer),
``ANY_SOURCE`` / ``ANY_TAG`` / ``Status``, and ``COMM_WORLD`` — a lazy
proxy over :func:`mpi4jax_tpu.get_default_comm` with mpi4py-style
methods (``Get_rank``, ``Get_size``, ``Clone``, ``Split``).  The module
itself re-exports the twelve communication functions with the
reference's exact signatures (they already match — e.g.
``allreduce(x, op, *, comm=None, token=None)`` mirrors
mpi4jax/_src/collective_ops/allreduce.py:36-66) plus
``has_cuda_support``.

On the multi-process backend (``python -m mpi4jax_tpu.launch -np 4``)
``Get_rank()`` is a Python int and per-rank control flow works exactly
as in the reference's MPMD model.  On the mesh backend ``Get_rank()``
is a traced value inside ``shard_map`` (SPMD — see docs/usage.md).
"""

import functools as _functools

import mpi4jax_tpu as _m

__all__ = [
    "MPI",
    "allgather",
    "allreduce",
    "alltoall",
    "barrier",
    "bcast",
    "gather",
    "recv",
    "reduce",
    "scan",
    "scatter",
    "send",
    "sendrecv",
    "has_cuda_support",
    "create_token",
]


class _CommProxy:
    """mpi4py-flavoured view of an mpi4jax_tpu communicator."""

    def __init__(self, comm=None):
        self._comm = comm

    def _resolve(self):
        return self._comm if self._comm is not None else _m.get_default_comm()

    # mpi4py surface
    def Get_rank(self):
        return self._resolve().rank()

    def Get_size(self):
        return self._resolve().size

    def Clone(self):
        return _CommProxy(self._resolve().clone())

    def Split(self, color=0, key=None):
        """mpi4py-style Split.

        On the static backends the arguments follow this library's split
        contract: functions of rank or explicit per-rank sequences (one
        SPMD/static program must derive the partition identically
        everywhere); plain ints (every rank same color — a clone-like
        split) are accepted too.
        """
        comm = self._resolve()
        if isinstance(color, int):
            if comm.backend == "proc" and comm.size > 1:
                # mpi4py's per-process scalar color cannot be inferred
                # here (each process would see only its own value and
                # silently build the wrong group) — demand the static form
                raise ValueError(
                    "Split(color) with a per-rank scalar is ambiguous on "
                    "the multi-process backend: every process must "
                    "derive the full partition. Pass a function of rank "
                    "or a length-size sequence, e.g. "
                    "Split(lambda r: r % 2)."
                )
            color = [color] * comm.size
        if isinstance(key, int):
            if comm.backend == "proc" and comm.size > 1:
                # same ambiguity as scalar colors — and the guard must
                # fire identically on EVERY process (a value-dependent
                # check would raise on some ranks and hang the rest in
                # the collective), so any explicit scalar is rejected;
                # omit key (the default) for rank ordering
                raise ValueError(
                    "Split(..., key=<per-rank scalar>) is ambiguous on "
                    "the multi-process backend; pass a function of rank "
                    "or a length-size sequence (or omit key for rank "
                    "ordering)."
                )
            key = None  # uniform key == default (rank) ordering
        out = comm.split(color, key)
        return _CommProxy(out) if out is not None else None

    def __repr__(self):
        return f"compat.Comm({self._resolve()!r})"


def _unwrap(comm):
    return comm._resolve() if isinstance(comm, _CommProxy) else comm


class _MPINamespace:
    """Stand-in for ``from mpi4py import MPI`` (operators, constants,
    Status, COMM_WORLD)."""

    SUM = _m.SUM
    PROD = _m.PROD
    MIN = _m.MIN
    MAX = _m.MAX
    LAND = _m.LAND
    LOR = _m.LOR
    LXOR = _m.LXOR
    BAND = _m.BAND
    BOR = _m.BOR
    BXOR = _m.BXOR
    ANY_SOURCE = _m.ANY_SOURCE
    ANY_TAG = _m.ANY_TAG
    Status = _m.Status
    COMM_WORLD = _CommProxy()

    Op = _m.Op

    @staticmethod
    def get_vendor():
        """mpi4py.MPI.get_vendor analog: identifies this backend."""
        import re

        import mpi4jax_tpu

        nums = re.findall(r"\d+", mpi4jax_tpu.__version__)[:3]
        return ("mpi4jax_tpu", tuple(int(p) for p in nums) or (0,))

    def __repr__(self):
        return "<mpi4jax_tpu.compat.MPI>"


MPI = _MPINamespace()


def _wrap(fn):
    @_functools.wraps(fn)
    def wrapper(*args, comm=None, **kwargs):
        return fn(*args, comm=_unwrap(comm), **kwargs)

    return wrapper


# the reference's experimental namespace (auto_tokenize) rides along
from mpi4jax_tpu import experimental  # noqa: E402,F401

allgather = _wrap(_m.allgather)
allreduce = _wrap(_m.allreduce)
alltoall = _wrap(_m.alltoall)
barrier = _wrap(_m.barrier)
bcast = _wrap(_m.bcast)
gather = _wrap(_m.gather)
recv = _wrap(_m.recv)
reduce = _wrap(_m.reduce)
scan = _wrap(_m.scan)
scatter = _wrap(_m.scatter)
send = _wrap(_m.send)
sendrecv = _wrap(_m.sendrecv)
create_token = _m.create_token
has_cuda_support = _m.has_cuda_support
