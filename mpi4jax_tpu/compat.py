"""Drop-in call-surface compatibility with the reference (mpi4jax +
mpi4py).

A user of the reference writes (README.rst:61-80 there):

    from mpi4py import MPI
    import mpi4jax

    comm = MPI.COMM_WORLD
    rank = comm.Get_rank()
    res, token = mpi4jax.allreduce(x, op=MPI.SUM, comm=comm)

The same program runs here with only the imports changed:

    from mpi4jax_tpu import compat as mpi4jax
    from mpi4jax_tpu.compat import MPI

``MPI`` exposes the reduction operators (``SUM``, ``PROD``, ... — these
ARE :class:`mpi4jax_tpu.Op` objects, no translation layer),
``ANY_SOURCE`` / ``ANY_TAG`` / ``Status``, and ``COMM_WORLD`` — a lazy
proxy over :func:`mpi4jax_tpu.get_default_comm` with mpi4py-style
methods (``Get_rank``, ``Get_size``, ``Clone``, ``Split``).  The module
itself re-exports the twelve communication functions with the
reference's exact signatures (they already match — e.g.
``allreduce(x, op, *, comm=None, token=None)`` mirrors
mpi4jax/_src/collective_ops/allreduce.py:36-66) plus
``has_cuda_support``.

On the multi-process backend (``python -m mpi4jax_tpu.launch -np 4``)
``Get_rank()`` is a Python int and per-rank control flow works exactly
as in the reference's MPMD model.  On the mesh backend ``Get_rank()``
is a traced value inside ``shard_map`` (SPMD — see docs/usage.md).
"""

import functools as _functools
import sys as _sys

import mpi4jax_tpu as _m

__all__ = [
    "MPI",
    "allgather",
    "allreduce",
    "alltoall",
    "barrier",
    "bcast",
    "gather",
    "recv",
    "reduce",
    "scan",
    "scatter",
    "send",
    "sendrecv",
    "has_cuda_support",
    "create_token",
]


class _CommProxy:
    """mpi4py-flavoured view of an mpi4jax_tpu communicator."""

    def __init__(self, comm=None):
        self._comm = comm

    def _resolve(self):
        return self._comm if self._comm is not None else _m.get_default_comm()

    # mpi4py surface
    def Get_rank(self):
        return self._resolve().rank()

    def Get_size(self):
        return self._resolve().size

    def Clone(self):
        return _CommProxy(self._resolve().clone())

    def Split(self, color=0, key=None):
        """mpi4py-style Split.

        On the static backends the arguments follow this library's split
        contract: functions of rank or explicit per-rank sequences (one
        SPMD/static program must derive the partition identically
        everywhere); plain ints (every rank same color — a clone-like
        split) are accepted too.
        """
        comm = self._resolve()
        if isinstance(color, int):
            if comm.backend == "proc" and comm.size > 1:
                # mpi4py's per-process scalar color cannot be inferred
                # here (each process would see only its own value and
                # silently build the wrong group) — demand the static form
                raise ValueError(
                    "Split(color) with a per-rank scalar is ambiguous on "
                    "the multi-process backend: every process must "
                    "derive the full partition. Pass a function of rank "
                    "or a length-size sequence, e.g. "
                    "Split(lambda r: r % 2)."
                )
            color = [color] * comm.size
        if isinstance(key, int):
            if comm.backend == "proc" and comm.size > 1:
                # same ambiguity as scalar colors — and the guard must
                # fire identically on EVERY process (a value-dependent
                # check would raise on some ranks and hang the rest in
                # the collective), so any explicit scalar is rejected;
                # omit key (the default) for rank ordering
                raise ValueError(
                    "Split(..., key=<per-rank scalar>) is ambiguous on "
                    "the multi-process backend; pass a function of rank "
                    "or a length-size sequence (or omit key for rank "
                    "ordering)."
                )
            key = None  # uniform key == default (rank) ordering
        out = comm.split(color, key)
        return _CommProxy(out) if out is not None else None

    def __repr__(self):
        return f"compat.Comm({self._resolve()!r})"


def _unwrap(comm):
    return comm._resolve() if isinstance(comm, _CommProxy) else comm


class _MPINamespace:
    """Stand-in for ``from mpi4py import MPI`` (operators, constants,
    Status, COMM_WORLD)."""

    SUM = _m.SUM
    PROD = _m.PROD
    MIN = _m.MIN
    MAX = _m.MAX
    LAND = _m.LAND
    LOR = _m.LOR
    LXOR = _m.LXOR
    BAND = _m.BAND
    BOR = _m.BOR
    BXOR = _m.BXOR
    ANY_SOURCE = _m.ANY_SOURCE
    ANY_TAG = _m.ANY_TAG
    Status = _m.Status
    COMM_WORLD = _CommProxy()

    Op = _m.Op

    @staticmethod
    def get_vendor():
        """mpi4py.MPI.get_vendor analog: identifies this backend."""
        import re

        import mpi4jax_tpu

        nums = re.findall(r"\d+", mpi4jax_tpu.__version__)[:3]
        return ("mpi4jax_tpu", tuple(int(p) for p in nums) or (0,))

    def __repr__(self):
        return "<mpi4jax_tpu.compat.MPI>"


MPI = _MPINamespace()


def _wrap(fn):
    @_functools.wraps(fn)
    def wrapper(*args, comm=None, **kwargs):
        return fn(*args, comm=_unwrap(comm), **kwargs)

    return wrapper


_MPI_ERR_RANK = 6  # the canonical MPI error class for an invalid rank


def _wrap_p2p(fn, mpi_op):
    """p2p wrapper that additionally reproduces the reference bridge's
    death wire format on an invalid partner rank.

    The reference aborts at *execution* time with
    ``r{rank} | MPI_{op} returned error code {ierr}: {err} - aborting``
    on stderr (mpi_xla_bridge.pyx:75-91; pinned by the reference's own
    tests/collective_ops/test_common.py::test_abort_on_error).  This
    library rejects the bad rank *earlier* — an eager trace-time
    ValueError naming it — which is the better diagnostic, but the
    observable death contract is part of the compat surface: emit the
    reference's line before the raise, so tooling that greps stderr
    for it keeps working.  The process still dies by the (clearer)
    exception; under the launcher, fail-fast kills the job exactly as
    MPI_Abort would."""

    @_functools.wraps(fn)
    def wrapper(*args, comm=None, **kwargs):
        comm = _unwrap(comm)
        try:
            return fn(*args, comm=comm, **kwargs)
        except ValueError as e:
            if "out of range for communicator" in str(e):
                try:
                    from mpi4jax_tpu.utils.validation import check_comm

                    rank = check_comm(comm).rank()
                    rank = rank if isinstance(rank, int) else 0
                except Exception:  # traced rank (mesh) or no default
                    rank = 0
                print(
                    f"r{rank} | MPI_{mpi_op} returned error code "
                    f"{_MPI_ERR_RANK}: {e} - aborting",
                    file=_sys.stderr,
                    flush=True,
                )
            raise

    return wrapper


# the reference's experimental namespace (auto_tokenize) rides along
from mpi4jax_tpu import experimental  # noqa: E402,F401

allgather = _wrap(_m.allgather)
allreduce = _wrap(_m.allreduce)
alltoall = _wrap(_m.alltoall)
barrier = _wrap(_m.barrier)
bcast = _wrap(_m.bcast)
gather = _wrap(_m.gather)
recv = _wrap_p2p(_m.recv, "Recv")
reduce = _wrap(_m.reduce)
scan = _wrap(_m.scan)
scatter = _wrap(_m.scatter)
send = _wrap_p2p(_m.send, "Send")
sendrecv = _wrap_p2p(_m.sendrecv, "Sendrecv")
create_token = _m.create_token
has_cuda_support = _m.has_cuda_support
