"""Environment-driven configuration.

Mirrors the reference's env-var config surface
(mpi4jax/_src/decorators.py:37-42 truthy parser; MPI4JAX_DEBUG at
xla_bridge/__init__.py:22) with the ``MPI4JAX_TPU_`` prefix:

* ``MPI4JAX_TPU_DEBUG``        — per-call wire-format logging (Python op
                                 layer; the reference's MPI4JAX_DEBUG)
* ``MPI4JAX_TPU_NATIVE_DEBUG`` — the native DCN bridge's own LogScope
                                 (separate switch so one MPI call never
                                 logs two begin/done pairs)
* ``MPI4JAX_TPU_NO_FENCE``     — drop optimization-barrier token fences
                                 (perf experiments only; ordering
                                 becomes UB)
"""

import os

__all__ = ["truthy", "debug_enabled", "fences_enabled", "set_debug"]

_TRUE = {"1", "true", "on", "yes"}
_FALSE = {"0", "false", "off", "no", ""}

_state = {"debug": None}


def truthy(value, default=False):
    if value is None:
        return default
    v = str(value).strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    raise ValueError(f"cannot interpret {value!r} as a boolean flag")


def debug_enabled():
    if _state["debug"] is not None:
        return _state["debug"]
    return truthy(os.environ.get("MPI4JAX_TPU_DEBUG"), default=False)


def set_debug(enabled):
    """Runtime toggle (overrides the env var; None resets to env).

    Mirrors the reference's ``mpi_xla_bridge.set_logging``
    (mpi_xla_bridge.pyx:38-40).  Toggles the Python-layer per-op log
    only; the native DCN bridge's LogScope has its own switch
    (``MPI4JAX_TPU_NATIVE_DEBUG`` / ``native.runtime.set_logging``) so
    one MPI call never logs two begin/done pairs with different ids.
    """
    _state["debug"] = enabled


def fences_enabled():
    return not truthy(os.environ.get("MPI4JAX_TPU_NO_FENCE"), default=False)
