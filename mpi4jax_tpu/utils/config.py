"""Environment-driven configuration.

Mirrors the reference's env-var config surface
(mpi4jax/_src/decorators.py:37-42 truthy parser; MPI4JAX_DEBUG at
xla_bridge/__init__.py:22) with the ``MPI4JAX_TPU_`` prefix:

* ``MPI4JAX_TPU_DEBUG``        — per-call wire-format logging (Python op
                                 layer; the reference's MPI4JAX_DEBUG)
* ``MPI4JAX_TPU_NATIVE_DEBUG`` — the native DCN bridge's own LogScope
                                 (separate switch so one MPI call never
                                 logs two begin/done pairs)
* ``MPI4JAX_TPU_NO_FENCE``     — drop optimization-barrier token fences
                                 (perf experiments only; ordering
                                 becomes UB)

Robustness deadlines for the multi-process DCN bridge
(docs/failure-semantics.md):

* ``T4J_OP_TIMEOUT``      — per-call progress deadline in seconds for
                            bridge sends/recvs/collectives; 0 (the
                            default) waits forever, matching MPI.
* ``T4J_CONNECT_TIMEOUT`` — bootstrap connect/accept deadline in
                            seconds (default 30).

Values are validated here and handed to the native bridge before init
(native/runtime.py), so a typo'd deadline fails loudly at launch
instead of silently running unbounded.
"""

import math
import os

__all__ = [
    "truthy",
    "debug_enabled",
    "fences_enabled",
    "set_debug",
    "seconds",
    "op_timeout",
    "connect_timeout",
]

_TRUE = {"1", "true", "on", "yes"}
_FALSE = {"0", "false", "off", "no", ""}

_state = {"debug": None}


def truthy(value, default=False):
    if value is None:
        return default
    v = str(value).strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    raise ValueError(f"cannot interpret {value!r} as a boolean flag")


def debug_enabled():
    if _state["debug"] is not None:
        return _state["debug"]
    return truthy(os.environ.get("MPI4JAX_TPU_DEBUG"), default=False)


def set_debug(enabled):
    """Runtime toggle (overrides the env var; None resets to env).

    Mirrors the reference's ``mpi_xla_bridge.set_logging``
    (mpi_xla_bridge.pyx:38-40).  Toggles the Python-layer per-op log
    only; the native DCN bridge's LogScope has its own switch
    (``MPI4JAX_TPU_NATIVE_DEBUG`` / ``native.runtime.set_logging``) so
    one MPI call never logs two begin/done pairs with different ids.
    """
    _state["debug"] = enabled


def fences_enabled():
    return not truthy(os.environ.get("MPI4JAX_TPU_NO_FENCE"), default=False)


def seconds(value, default, name="value", minimum=0.0):
    """Parse an env-var duration in seconds.

    ``None``/empty returns ``default``; anything that is not a finite
    number >= ``minimum`` raises ``ValueError`` naming the variable —
    a mistyped deadline must fail at launch, not silently disable the
    deadline."""
    if value is None or str(value).strip() == "":
        return float(default)
    try:
        v = float(str(value).strip())
    except (TypeError, ValueError):
        raise ValueError(
            f"cannot interpret {name}={value!r} as seconds (want a number)"
        )
    if not math.isfinite(v):
        raise ValueError(f"{name}={value!r} must be finite")
    if v < minimum:
        raise ValueError(f"{name}={value!r} must be >= {minimum}")
    return v


def op_timeout():
    """Per-call progress deadline for DCN bridge ops, in seconds.

    0 disables the deadline (wait forever — MPI's behaviour, and the
    default: a slow peer compiling a large program is legal)."""
    return seconds(
        os.environ.get("T4J_OP_TIMEOUT"), 0.0, name="T4J_OP_TIMEOUT"
    )


def connect_timeout():
    """Bootstrap connect/accept deadline in seconds (strictly positive;
    default 30 — the old hardcoded 600 x 50ms retry loop)."""
    v = seconds(
        os.environ.get("T4J_CONNECT_TIMEOUT"),
        30.0,
        name="T4J_CONNECT_TIMEOUT",
    )
    if v <= 0:
        raise ValueError(
            "T4J_CONNECT_TIMEOUT must be > 0 (the bootstrap cannot wait "
            "forever for a rank that never starts)"
        )
    return v
