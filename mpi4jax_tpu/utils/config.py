"""Environment-driven configuration.

Mirrors the reference's env-var config surface
(mpi4jax/_src/decorators.py:37-42 truthy parser; MPI4JAX_DEBUG at
xla_bridge/__init__.py:22) with the ``MPI4JAX_TPU_`` prefix:

* ``MPI4JAX_TPU_DEBUG``        — per-call wire-format logging (Python op
                                 layer; the reference's MPI4JAX_DEBUG)
* ``MPI4JAX_TPU_NATIVE_DEBUG`` — the native DCN bridge's own LogScope
                                 (separate switch so one MPI call never
                                 logs two begin/done pairs)
* ``MPI4JAX_TPU_NO_FENCE``     — drop optimization-barrier token fences
                                 (perf experiments only; ordering
                                 becomes UB)

Robustness deadlines for the multi-process DCN bridge
(docs/failure-semantics.md):

* ``T4J_OP_TIMEOUT``      — per-call progress deadline in seconds for
                            bridge sends/recvs/collectives; 0 (the
                            default) waits forever, matching MPI.
* ``T4J_CONNECT_TIMEOUT`` — bootstrap connect/accept deadline in
                            seconds (default 30).

Self-healing transport knobs (docs/failure-semantics.md "self-healing
transport" — the retry -> reconnect+replay -> abort escalation ladder):

* ``T4J_RETRY_MAX``     — reconnect attempts per broken link (default
                          3); 0 disables self-healing entirely (the
                          first transport error fails the job, the
                          pre-PR-5 behaviour).
* ``T4J_BACKOFF_BASE``  — first re-dial delay in seconds (default
                          0.05); subsequent attempts double it, with
                          ±25 % jitter.
* ``T4J_BACKOFF_MAX``   — re-dial delay cap in seconds (default 2).
* ``T4J_REPLAY_BYTES``  — per-peer replay-ring capacity (default 32M;
                          docs/performance.md covers the memory and
                          copy cost).  Must exceed the bytes a drop
                          can lose in flight (the two kernel socket
                          buffers) or recovery escalates to abort.

Elastic world membership (docs/failure-semantics.md "elastic
membership" — the shrink/rejoin rung between reconnect+replay and
abort):

* ``T4J_ELASTIC``        — ``off`` (default: a dead rank aborts the
                           whole job, today's exact behaviour),
                           ``shrink`` (survivors agree on a reduced
                           world and continue; the Python tier raises
                           ``WorldResized`` at the next op), or
                           ``rejoin`` (shrink, plus rank 0 keeps the
                           bootstrap coordinator port open so a
                           relaunched replacement re-bootstraps into
                           the mesh at the next epoch fence).
                           Requires ``T4J_RETRY_MAX > 0`` (escalation
                           — elastic's trigger — is the self-healing
                           ladder's last rung) and a world of at most
                           64 ranks.
* ``T4J_MIN_WORLD``      — survivor floor (default 1): a shrink that
                           would leave fewer members than this fires
                           the legacy abort instead.
* ``T4J_RESIZE_TIMEOUT`` — per-phase bound on the membership
                           agreement / link rebuild in seconds
                           (default 30).

Data-plane tuning for the TCP-tier collectives (docs/performance.md
"TCP-tier algorithm selection"):

* ``T4J_RING_MIN_BYTES`` — total message size at or above which
                           allreduce/allgather/reduce_scatter use the
                           segmented ring algorithms instead of the
                           latency-optimal trees (default 256 KiB, the
                           measured crossover; 0 = always ring).
* ``T4J_SEG_BYTES``      — ring segment/pipelining granularity
                           (default 1 MiB; must be >= 1).

Hierarchical (shm leaf + leader ring) selection for multi-host
communicators with multiple ranks per host (docs/performance.md
"hierarchical collectives"):

* ``T4J_HIER``                  — ``auto`` (default: size threshold),
                                  ``on`` (force wherever the topology
                                  allows), ``off`` (never).
* ``T4J_LEADER_RING_MIN_BYTES`` — auto mode's switchover: total
                                  message size at or above which the
                                  hierarchical path is taken (default
                                  256 KiB, the measured crossover).

Striped multi-connection links and the zero-copy wire path
(docs/performance.md "striped links and the zero-copy path"):

* ``T4J_STRIPES``            — parallel TCP connections per peer link
                               (``auto``, the default, or 1..16).  The
                               built width is fixed at bootstrap; the
                               dealing width can be lowered/raised up
                               to it at runtime (the calibrator does).
* ``T4J_ZEROCOPY_MIN_BYTES`` — frames at or above this many bytes are
                               sent with MSG_ZEROCOPY (0 = off, the
                               default).  On kernels without
                               SO_ZEROCOPY the bridge degrades LOUDLY
                               to the copy path at init.
* ``T4J_SENDMSG_BATCH``      — max frames gathered into one sendmsg
                               iovec call (default 8, 1..256).
* ``T4J_EMU_FLOW_BPS``       — testing: per-connection token-bucket
                               throttle in bytes/second (0 = off) so a
                               loopback box can demonstrate the
                               multi-flow busbw step real fabrics get
                               from multiple NIC queues.
* ``T4J_WIRE_DTYPE``         — compressed-collective wire dtype
                               (``off``, the default — bit-identical
                               to the uncompressed build — or
                               ``bf16``/``fp8``): f32 SUM ring/hier
                               payloads travel low-precision on
                               cross-host hops while accumulation and
                               results stay f32 (docs/performance.md
                               "Compressed collectives").  The
                               calibrator fits it per fabric.
* ``T4J_WIRE_BACKEND``       — wire data-plane backend (``auto``, the
                               default, or ``sendmsg``/``uring``):
                               ``uring`` drives the stripe loops
                               through io_uring submission rings with
                               the replay arena registered as a fixed
                               buffer; kernels without io_uring
                               degrade loudly to sendmsg
                               (docs/performance.md "io_uring wire
                               backend").  The calibrator fits it.

Trace-guided autotuning + small-message coalescing
(docs/performance.md "trace-guided autotuning"):

* ``T4J_COALESCE_BYTES`` — fuse runs of small same-peer messages into
                           one wire frame when their combined payload
                           is at or below this many bytes (default
                           16 KiB; 0 disables fusion — the exact
                           pre-coalescing wire behaviour).  The
                           autotuner calibrates it.
* ``T4J_TUNING_CACHE``   — directory of the fingerprint-keyed tuning
                           cache (default ``~/.cache/mpi4jax_tpu``;
                           ``off`` disables cache load AND store).
* ``T4J_AUTOTUNE``       — truthy: calibrate the knob vector at init
                           (collective, a few seconds) and persist it
                           to the cache; the launcher's ``--autotune``
                           sets it.  Explicit ``T4J_*`` knob env vars
                           always win over calibrated/cached values.

Async progress engine / gradient bucketing (docs/async.md):

* ``T4J_BUCKET_BYTES`` — gradient-bucket size for ``BucketedGradSync``
                         (default 4 MiB): backprop-ordered gradients
                         are packed into buckets of about this size and
                         each bucket's ``iallreduce`` overlaps the rest
                         of the backward pass.

Telemetry (docs/observability.md):

* ``T4J_TELEMETRY``       — ``off`` (default: zero-cost no-op),
                            ``counters`` (metrics table + control-plane
                            events), ``trace`` (plus per-event records
                            for ops / wire segments / arena stages —
                            the Perfetto timeline feed).
* ``T4J_TELEMETRY_BYTES`` — per-rank event-ring capacity (default 1M =
                            32Ki events; writers lapping the drain
                            cursor drop the oldest, never block).
* ``T4J_TELEMETRY_DIR``   — when set, every rank drains its ring and
                            metrics snapshot into
                            ``<dir>/rank<k>.t4j.json`` at exit (the
                            launcher's ``--telemetry DIR`` sets it and
                            merges the files into one Perfetto
                            ``job.trace.json``).
* ``T4J_METRICS_PORT``    — live metrics exporter base port: rank k
                            serves its metrics snapshot + link stats on
                            ``127.0.0.1:<port>+k`` (Prometheus text at
                            ``/metrics``, JSON at ``/metrics.json``);
                            the launcher's ``--metrics PORT`` sets it
                            and aggregates the job view on
                            ``<port>+nprocs``.  Unset/0 = disabled.
* ``T4J_FLIGHT``          — truthy: crash-consistent flight recorder
                            (docs/observability.md "flight recorder"):
                            the event ring + metrics table live in a
                            per-rank mmap'd file, so a SIGKILL'd/
                            segfaulted/OOM-killed rank's last events
                            survive for ``t4j-postmortem`` without any
                            cooperative drain.  Sized by
                            ``T4J_TELEMETRY_BYTES``.
* ``T4J_FLIGHT_DIR``      — where the flight files land
                            (``rank<k>-<boot>.t4jflight``); falls back
                            to ``T4J_TELEMETRY_DIR``, then the current
                            directory.  The launcher's ``--telemetry
                            DIR`` turns the recorder on there unless
                            ``T4J_FLIGHT`` explicitly says off.

Serving (docs/serving.md — the continuous-batching inference loop):

* ``T4J_SLO_MS``    — end-to-end latency SLO per request in
                      milliseconds (0/unset = no SLO).  Requires
                      ``T4J_ADMIT=on``: an SLO with admission off
                      cannot be enforced, only missed — the
                      combination is rejected at init.
* ``T4J_MAX_BATCH`` — concurrent decode slots in the serving engine's
                      KV-cache pool (default 8, 1..1024).
* ``T4J_ADMIT``     — ``off`` (default: admit everything — the
                      uncontrolled baseline) or ``on`` (token-bucket
                      + SLO-estimator admission: predicted deadline
                      misses are shed at the door, and counted).
* ``T4J_AUTOSCALE`` — ``off`` (default) or ``on``: traffic-driven
                      elastic autoscaling of the serving world
                      (docs/serving.md "Autoscaling"); requires
                      ``T4J_ELASTIC=rejoin``.
* ``T4J_SCALE_UP_WINDOWS`` / ``T4J_SCALE_DOWN_WINDOWS`` /
  ``T4J_SCALE_DOWN_OCC`` / ``T4J_SCALE_COOLDOWN_WINDOWS`` — the
                      autoscaler's hysteresis pair, shrink threshold
                      and flap-suppression cooldown.
* ``T4J_AUTOSCALE_REQ`` — grow-request file the leader posts and
                      ``launch.py --autoscale`` polls.

The byte knobs accept an optional K/M/G suffix
(``T4J_SEG_BYTES=256K``) and all of them must be uniform across ranks
— the launcher propagates the env, and ranks disagreeing on a
switchover would run mismatched algorithms.

Values are validated here and handed to the native bridge before init
(native/runtime.py), so a typo'd deadline fails loudly at launch
instead of silently running unbounded.
"""

import math
import os

__all__ = [
    "truthy",
    "debug_enabled",
    "fences_enabled",
    "set_debug",
    "seconds",
    "op_timeout",
    "connect_timeout",
    "byte_count",
    "int_count",
    "ring_min_bytes",
    "seg_bytes",
    "stripes",
    "zerocopy_min_bytes",
    "sendmsg_batch",
    "emu_flow_bps",
    "wire_dtype",
    "coalesce_bytes",
    "tuning_cache_dir",
    "autotune_enabled",
    "hier_mode",
    "leader_ring_min_bytes",
    "retry_max",
    "backoff_base",
    "backoff_max",
    "replay_bytes",
    "elastic_mode",
    "min_world",
    "resize_timeout",
    "bucket_bytes",
    "verify_mode",
    "slo_ms",
    "max_batch",
    "admit_mode",
    "autoscale_mode",
    "scale_up_windows",
    "scale_down_occ",
    "scale_down_windows",
    "scale_cooldown_windows",
    "autoscale_req_path",
    "telemetry_mode",
    "telemetry_bytes",
    "telemetry_dir",
    "metrics_port",
    "flight_enabled",
    "flight_dir",
]

_TRUE = {"1", "true", "on", "yes"}
_FALSE = {"0", "false", "off", "no", ""}

_state = {"debug": None}


def truthy(value, default=False):
    if value is None:
        return default
    v = str(value).strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    raise ValueError(f"cannot interpret {value!r} as a boolean flag")


def debug_enabled():
    if _state["debug"] is not None:
        return _state["debug"]
    return truthy(os.environ.get("MPI4JAX_TPU_DEBUG"), default=False)


def set_debug(enabled):
    """Runtime toggle (overrides the env var; None resets to env).

    Mirrors the reference's ``mpi_xla_bridge.set_logging``
    (mpi_xla_bridge.pyx:38-40).  Toggles the Python-layer per-op log
    only; the native DCN bridge's LogScope has its own switch
    (``MPI4JAX_TPU_NATIVE_DEBUG`` / ``native.runtime.set_logging``) so
    one MPI call never logs two begin/done pairs with different ids.
    """
    _state["debug"] = enabled


def fences_enabled():
    return not truthy(os.environ.get("MPI4JAX_TPU_NO_FENCE"), default=False)


def seconds(value, default, name="value", minimum=0.0):
    """Parse an env-var duration in seconds.

    ``None``/empty returns ``default``; anything that is not a finite
    number >= ``minimum`` raises ``ValueError`` naming the variable —
    a mistyped deadline must fail at launch, not silently disable the
    deadline."""
    if value is None or str(value).strip() == "":
        return float(default)
    try:
        v = float(str(value).strip())
    except (TypeError, ValueError):
        raise ValueError(
            f"cannot interpret {name}={value!r} as seconds (want a number)"
        )
    if not math.isfinite(v):
        raise ValueError(f"{name}={value!r} must be finite")
    if v < minimum:
        raise ValueError(f"{name}={value!r} must be >= {minimum}")
    return v


_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}


def byte_count(value, default, name="value", minimum=0):
    """Parse an env-var byte count with an optional K/M/G suffix.

    ``None``/empty returns ``default``; anything that is not a whole
    number of bytes >= ``minimum`` raises ``ValueError`` naming the
    variable — a mistyped tuning knob must fail at launch, not silently
    fall back and mislabel every benchmark after it."""
    if value is None or str(value).strip() == "":
        return int(default)
    s = str(value).strip()
    mult = 1
    if s and s[-1].lower() in _SUFFIX:
        mult = _SUFFIX[s[-1].lower()]
        s = s[:-1].strip()
    try:
        v = int(s, 10)
    except (TypeError, ValueError):
        raise ValueError(
            f"cannot interpret {name}={value!r} as a byte count "
            "(want an integer, optionally suffixed K/M/G)"
        )
    v *= mult
    if v < minimum:
        raise ValueError(f"{name}={value!r} must be >= {minimum}")
    if v >= 1 << 62:
        # the native side takes an int64; a value this large is a typo,
        # and letting it through would crash in ctypes with an error
        # that does not name the variable
        raise ValueError(f"{name}={value!r} is implausibly large")
    return v


def int_count(value, default, name="value", minimum=0):
    """Parse an env-var plain integer count (no size suffix).

    ``None``/empty returns ``default``; anything that is not a whole
    number >= ``minimum`` raises ``ValueError`` naming the variable."""
    if value is None or str(value).strip() == "":
        return int(default)
    try:
        v = int(str(value).strip(), 10)
    except (TypeError, ValueError):
        raise ValueError(
            f"cannot interpret {name}={value!r} as an integer count"
        )
    if v < minimum:
        raise ValueError(f"{name}={value!r} must be >= {minimum}")
    return v


def retry_max():
    """Reconnect attempts per broken DCN link before escalating to the
    abort broadcast (docs/failure-semantics.md "self-healing
    transport").  0 disables self-healing: the first transport error
    fails the job, the pre-self-healing behaviour."""
    return int_count(
        os.environ.get("T4J_RETRY_MAX"), 3, name="T4J_RETRY_MAX"
    )


def backoff_base():
    """First re-dial delay in seconds (strictly positive); each
    subsequent attempt doubles it, with ±25 % jitter so the two ends of
    a broken link never re-dial in lockstep."""
    v = seconds(
        os.environ.get("T4J_BACKOFF_BASE"), 0.05, name="T4J_BACKOFF_BASE"
    )
    if v <= 0:
        raise ValueError("T4J_BACKOFF_BASE must be > 0 seconds")
    return v


def backoff_max():
    """Re-dial delay cap in seconds; must be >= T4J_BACKOFF_BASE (a cap
    below the base would silently shrink the first delay)."""
    v = seconds(
        os.environ.get("T4J_BACKOFF_MAX"), 2.0, name="T4J_BACKOFF_MAX"
    )
    if v <= 0:
        raise ValueError("T4J_BACKOFF_MAX must be > 0 seconds")
    if v < backoff_base():
        raise ValueError(
            "T4J_BACKOFF_MAX must be >= T4J_BACKOFF_BASE "
            f"(got {v} < {backoff_base()})"
        )
    return v


def elastic_mode():
    """Elastic world-membership mode (docs/failure-semantics.md
    "elastic membership"): ``off`` (default — a dead rank aborts the
    whole job), ``shrink`` (survivors agree on a reduced world and
    continue) or ``rejoin`` (shrink, plus a relaunched replacement can
    re-bootstrap into the mesh).  Anything else raises — a typo'd mode
    must fail at launch, not silently fall back to fail-stop."""
    v = os.environ.get("T4J_ELASTIC")
    if v is None or not str(v).strip():
        return "off"
    v = str(v).strip().lower()
    if v not in ("off", "shrink", "rejoin"):
        raise ValueError(
            f"cannot interpret T4J_ELASTIC={v!r} (want off|shrink|rejoin)"
        )
    return v


def min_world():
    """Survivor floor for an elastic shrink (default 1, must be >= 1):
    a shrink that would leave fewer members than this fires the legacy
    abort instead — the job is presumed no longer viable at that
    size."""
    v = int_count(os.environ.get("T4J_MIN_WORLD"), 1,
                  name="T4J_MIN_WORLD")
    if v < 1:
        raise ValueError(
            "T4J_MIN_WORLD must be >= 1 (a world cannot shrink to "
            "nothing)"
        )
    return v


def resize_timeout():
    """Per-phase bound on the elastic membership agreement and link
    rebuild, in seconds (default 30, strictly positive): past it the
    resize escalates to the legacy abort."""
    v = seconds(
        os.environ.get("T4J_RESIZE_TIMEOUT"), 30.0,
        name="T4J_RESIZE_TIMEOUT",
    )
    if v <= 0:
        raise ValueError(
            "T4J_RESIZE_TIMEOUT must be > 0 (the membership agreement "
            "cannot wait forever for a dead rank's reports)"
        )
    return v


def replay_bytes():
    """Per-peer replay-ring capacity in bytes for the self-healing
    transport (default 32M).  Sized to exceed the bytes a connection
    drop can lose in flight (the two kernel socket buffers, ~8 MB each
    when pinned); a reconnect that needs frames already evicted
    escalates to abort.  docs/performance.md covers the memory cost."""
    return byte_count(
        os.environ.get("T4J_REPLAY_BYTES"),
        32 << 20,
        name="T4J_REPLAY_BYTES",
    )


def bucket_bytes():
    """Gradient-bucket size for ``BucketedGradSync`` in bytes (default
    4 MiB; must be >= 1).  Backprop-ordered gradients are packed into
    buckets of about this size and each bucket's ``iallreduce`` is
    submitted as soon as the bucket is full, so its wire phase overlaps
    the rest of the backward pass (docs/async.md "gradient bucketing").
    Smaller buckets start overlapping earlier but pay more per-op
    latency; larger ones amortise better but delay the first submit."""
    v = byte_count(
        os.environ.get("T4J_BUCKET_BYTES"), 4 << 20,
        name="T4J_BUCKET_BYTES",
    )
    if v < 1:
        raise ValueError(
            "T4J_BUCKET_BYTES must be >= 1 (a gradient bucket cannot "
            "be empty)"
        )
    return v


def ring_min_bytes():
    """Tree->ring switchover for the TCP-tier collectives, in bytes.

    Messages at or above this total size use the segmented ring
    algorithms (bandwidth-optimal); smaller ones keep the trees
    (latency-optimal).  0 forces the ring path for every size.  The
    default is the measured 8-proc crossover (docs/performance.md
    "TCP-tier algorithm selection")."""
    return byte_count(
        os.environ.get("T4J_RING_MIN_BYTES"),
        256 << 10,
        name="T4J_RING_MIN_BYTES",
    )


def seg_bytes():
    """Ring segment size in bytes (strictly positive): the granularity
    at which ring transfers are pipelined — the combine of segment k
    overlaps the receive of segment k+1."""
    v = byte_count(
        os.environ.get("T4J_SEG_BYTES"), 1 << 20, name="T4J_SEG_BYTES"
    )
    if v < 1:
        raise ValueError(
            "T4J_SEG_BYTES must be >= 1 (a ring segment cannot be empty)"
        )
    return v


MAX_STRIPES = 16


def stripes():
    """Parallel TCP connections per peer link (docs/performance.md
    "striped links and the zero-copy path"): ``"auto"`` (the default —
    one connection until the trace-guided calibrator learns a better
    width for the fabric) or an explicit 1..16.  Anything else raises:
    a typo'd stripe count must fail at launch, not silently run a
    different wire topology than the operator asked for.  Must be
    uniform across ranks (both ends of a link must build the same
    number of connections)."""
    v = os.environ.get("T4J_STRIPES")
    if v is None or not str(v).strip():
        return "auto"
    s = str(v).strip().lower()
    if s == "auto":
        return "auto"
    try:
        n = int(s, 10)
    except ValueError:
        raise ValueError(
            f"cannot interpret T4J_STRIPES={v!r} (want auto or an "
            f"integer 1..{MAX_STRIPES})"
        ) from None
    if not 1 <= n <= MAX_STRIPES:
        raise ValueError(
            f"T4J_STRIPES={n} out of range (want 1..{MAX_STRIPES}: one "
            "flow cannot be split below one connection, and the "
            "per-stripe reader/replay state is bounded)"
        )
    return n


def zerocopy_min_bytes():
    """MSG_ZEROCOPY opt-in floor in bytes (0 = the copy path
    everywhere, the default).  Frames at or above it are transmitted
    straight from the replay arena (or the caller's buffer with
    ``T4J_RETRY_MAX=0``) with the kernel-buffer copy elided; kernels
    without SO_ZEROCOPY degrade loudly at init
    (docs/performance.md "striped links and the zero-copy path")."""
    return byte_count(
        os.environ.get("T4J_ZEROCOPY_MIN_BYTES"), 0,
        name="T4J_ZEROCOPY_MIN_BYTES",
    )


def sendmsg_batch():
    """Max frames gathered into one ``sendmsg`` iovec call (default 8,
    1..256 — two iovecs per frame against the kernel's IOV_MAX)."""
    v = int_count(
        os.environ.get("T4J_SENDMSG_BATCH"), 8, name="T4J_SENDMSG_BATCH"
    )
    if not 1 <= v <= 256:
        raise ValueError(
            f"T4J_SENDMSG_BATCH={v} out of range (want 1..256: a batch "
            "cannot be empty, and each frame costs two iovec entries "
            "against IOV_MAX)"
        )
    return v


def emu_flow_bps():
    """Per-connection token-bucket throttle in bytes/second (0 = off,
    the default).  A TEST knob: it emulates the per-flow bottleneck of
    a real NIC-bound fabric so the loopback box can demonstrate the
    multi-flow busbw step (docs/performance.md "striped links and the
    zero-copy path")."""
    return byte_count(
        os.environ.get("T4J_EMU_FLOW_BPS"), 0, name="T4J_EMU_FLOW_BPS"
    )


WIRE_DTYPES = ("off", "bf16", "fp8")


def wire_dtype():
    """Compressed-collective wire dtype (docs/performance.md
    "Compressed collectives"): ``off`` (the default — payloads travel
    f32, bit-identical to the uncompressed build), ``bf16`` or ``fp8``
    (e4m3).  Compression applies only to f32 SUM collectives on
    all-cross-host rings — integer and MIN/MAX payloads have no
    defined wire cast and always travel exact, and a single
    shm/pipe-eligible hop disables it for the whole comm so every rank
    sees identical result bytes.  Anything else raises: a typo'd wire
    dtype must fail at launch, not silently run uncompressed (the
    operator would read "bf16 busbw" off a f32 run).  Must be uniform
    across ranks (mismatched wire dtypes exchange mismatched frame
    sizes; t4j-lint rule T4J009 names the divergence)."""
    v = os.environ.get("T4J_WIRE_DTYPE")
    if v is None or not str(v).strip():
        return "off"
    v = str(v).strip().lower()
    if v not in WIRE_DTYPES:
        raise ValueError(
            f"cannot interpret T4J_WIRE_DTYPE={v!r} "
            f"(want {'|'.join(WIRE_DTYPES)})"
        )
    return v


WIRE_BACKENDS = ("auto", "sendmsg", "uring")


def wire_backend():
    """Wire data-plane backend (docs/performance.md "io_uring wire
    backend"): ``auto`` (the default — sendmsg until the trace-guided
    calibrator learns that uring pays on this kernel/fabric),
    ``sendmsg`` (the classic readv/sendmsg loops, byte-stable with
    every prior release) or ``uring`` (io_uring submission/completion
    rings with the replay arena registered as a fixed buffer).
    Anything else raises: a typo'd backend must fail at launch, not
    silently benchmark the wrong data plane.  Both backends put
    identical bytes on the wire, so the choice need not be uniform
    across ranks; an explicit ``uring`` on a kernel whose io_uring
    probe fails is rejected at ``ensure_initialized`` (standalone
    ctypes users get the native layer's loud degrade to sendmsg
    instead)."""
    v = os.environ.get("T4J_WIRE_BACKEND")
    if v is None or not str(v).strip():
        return "auto"
    v = str(v).strip().lower()
    if v not in WIRE_BACKENDS:
        raise ValueError(
            f"cannot interpret T4J_WIRE_BACKEND={v!r} "
            f"(want {'|'.join(WIRE_BACKENDS)})"
        )
    return v


def coalesce_bytes():
    """Small-message coalescing threshold in bytes (docs/performance.md
    "small-message coalescing"): runs of small same-peer messages whose
    combined payload is at or below this travel as ONE fused wire
    frame.  0 disables fusion entirely — the exact pre-coalescing wire
    behaviour.  Must be uniform across ranks (both sides of a fused
    exchange must agree to fuse); the autotuner calibrates it."""
    return byte_count(
        os.environ.get("T4J_COALESCE_BYTES"),
        16 << 10,
        name="T4J_COALESCE_BYTES",
    )


def tuning_cache_dir():
    """Directory of the fingerprint-keyed on-disk tuning cache
    (docs/performance.md "trace-guided autotuning"), or ``None`` when
    disabled with ``T4J_TUNING_CACHE=off``.  Defaults to
    ``~/.cache/mpi4jax_tpu``."""
    v = str(os.environ.get("T4J_TUNING_CACHE") or "").strip()
    if v.lower() == "off":
        return None
    if v:
        return v
    return os.path.join(os.path.expanduser("~"), ".cache", "mpi4jax_tpu")


def autotune_enabled():
    """Truthy ``T4J_AUTOTUNE``: run the collective knob calibration at
    bridge init and persist the fit (the launcher's ``--autotune``)."""
    return truthy(os.environ.get("T4J_AUTOTUNE"), default=False)


def hier_mode():
    """Hierarchical-collective selection mode: ``auto`` (size
    threshold), ``on`` (force wherever the topology allows) or
    ``off``.  Anything else raises — a typo'd mode must fail at
    launch, not silently fall back to auto."""
    v = os.environ.get("T4J_HIER")
    if v is None or not str(v).strip():
        return "auto"
    v = str(v).strip().lower()
    if v not in ("auto", "on", "off"):
        raise ValueError(
            f"cannot interpret T4J_HIER={v!r} (want auto|on|off)"
        )
    return v


def leader_ring_min_bytes():
    """Auto-mode switchover for the hierarchical path, in bytes: total
    message size at or above which multi-host collectives run
    shm-leaf-reduce + leader-ring instead of the flat algorithms
    (default 256 KiB; 0 = whenever the topology allows)."""
    return byte_count(
        os.environ.get("T4J_LEADER_RING_MIN_BYTES"),
        256 << 10,
        name="T4J_LEADER_RING_MIN_BYTES",
    )


def verify_mode():
    """Communication-contract verification mode for analysis.guard
    (docs/static-analysis.md):

    * ``off`` (default) — zero-overhead passthrough.
    * ``fingerprint`` — exchange schedule digests across ranks before
      executing; divergence raises CommContractError immediately
      instead of hanging until T4J_OP_TIMEOUT.
    * ``full`` — fingerprint plus the whole static rule catalog
      (T4J001...) on every new input signature.

    Anything else raises — a typo'd mode must fail at launch, not
    silently skip verification."""
    v = os.environ.get("T4J_VERIFY")
    if v is None or not str(v).strip():
        return "off"
    v = str(v).strip().lower()
    if v not in ("off", "fingerprint", "full"):
        raise ValueError(
            f"cannot interpret T4J_VERIFY={v!r} "
            "(want off|fingerprint|full)"
        )
    return v


def slo_ms():
    """Per-request end-to-end latency SLO in milliseconds
    (docs/serving.md), or 0 when unset.  Must be finite and >= 0; a
    typo'd SLO must fail at launch, not silently serve without a
    deadline.  ``ensure_initialized`` additionally rejects an SLO with
    ``T4J_ADMIT=off`` — nothing would enforce it."""
    v = seconds(os.environ.get("T4J_SLO_MS"), 0.0, name="T4J_SLO_MS")
    return v


def max_batch():
    """Concurrent decode slots in the serving engine's KV-cache pool
    (default 8).  Bounded 1..1024: the slot cache is
    ``layers x 2 x max_batch x max_len`` KV positions of real memory,
    and the per-step plan vector scales with it."""
    v = int_count(os.environ.get("T4J_MAX_BATCH"), 8,
                  name="T4J_MAX_BATCH")
    if not 1 <= v <= 1024:
        raise ValueError(
            f"T4J_MAX_BATCH={v} out of range (want 1..1024: at least "
            "one slot, and the KV slot pool is real memory)"
        )
    return v


def admit_mode():
    """Serving admission-control mode (docs/serving.md): ``off``
    (default — every request is admitted; the uncontrolled baseline)
    or ``on`` (token bucket + SLO-estimator shedding).  Anything else
    raises — a typo'd mode must fail at launch, not silently serve
    uncontrolled while the operator believes the SLO is guarded."""
    v = os.environ.get("T4J_ADMIT")
    if v is None or not str(v).strip():
        return "off"
    v = str(v).strip().lower()
    if v not in ("off", "on"):
        raise ValueError(
            f"cannot interpret T4J_ADMIT={v!r} (want off|on)"
        )
    return v


def autoscale_mode():
    """Traffic-driven elastic autoscaling for the serving engine
    (docs/serving.md "Autoscaling"): ``off`` (default — the world size
    is whatever the launcher started) or ``on`` (the leader's
    :class:`serving.autoscale.Autoscaler` grows/shrinks the world from
    the SLO estimator's load signal).  Anything else raises — a typo'd
    mode must fail at launch, not silently serve at fixed capacity
    while the operator believes the fleet is elastic."""
    v = os.environ.get("T4J_AUTOSCALE")
    if v is None or not str(v).strip():
        return "off"
    v = str(v).strip().lower()
    if v not in ("off", "on"):
        raise ValueError(
            f"cannot interpret T4J_AUTOSCALE={v!r} (want off|on)"
        )
    return v


def scale_up_windows():
    """Consecutive decision windows of predicted-wait-over-budget
    before the autoscaler requests a grow (default 3, must be >= 1).
    The scale-up half of the hysteresis pair — one bad window is
    noise, a streak is a trend (docs/serving.md "Autoscaling")."""
    v = int_count(os.environ.get("T4J_SCALE_UP_WINDOWS"), 3,
                  name="T4J_SCALE_UP_WINDOWS")
    if v < 1:
        raise ValueError(
            "T4J_SCALE_UP_WINDOWS must be >= 1 (a grow needs at least "
            "one qualifying window)"
        )
    return v


def scale_down_occ():
    """Batch-occupancy fraction below which a window counts toward
    scale-down (default 0.35, must be in [0, 1)).  1 would make every
    window qualify whenever a single slot is free — the shrink trigger
    must mean 'mostly idle', not 'not perfectly full'."""
    raw = os.environ.get("T4J_SCALE_DOWN_OCC")
    if raw is None or not str(raw).strip():
        return 0.35
    try:
        v = float(str(raw).strip())
    except ValueError:
        raise ValueError(
            f"cannot interpret T4J_SCALE_DOWN_OCC={raw!r} as a "
            "fraction"
        ) from None
    if not (math.isfinite(v) and 0.0 <= v < 1.0):
        raise ValueError(
            f"T4J_SCALE_DOWN_OCC={v} out of range (want 0 <= occ < 1)"
        )
    return v


def scale_down_windows():
    """Consecutive low-occupancy windows before the autoscaler starts
    a drain (default 6, must be >= 1).  Deliberately defaulted above
    T4J_SCALE_UP_WINDOWS: capacity should arrive eagerly and leave
    reluctantly — a shrink the next ramp immediately undoes costs a
    full resize epoch both ways."""
    v = int_count(os.environ.get("T4J_SCALE_DOWN_WINDOWS"), 6,
                  name="T4J_SCALE_DOWN_WINDOWS")
    if v < 1:
        raise ValueError(
            "T4J_SCALE_DOWN_WINDOWS must be >= 1 (a shrink needs at "
            "least one qualifying window)"
        )
    return v


def scale_cooldown_windows():
    """Refractory windows after any resize commit during which the
    autoscaler accumulates no streaks (default 4, must be >= 0) — the
    flap suppressor: post-resize windows measure a world still
    refilling its batch, and acting on them oscillates."""
    v = int_count(os.environ.get("T4J_SCALE_COOLDOWN_WINDOWS"), 4,
                  name="T4J_SCALE_COOLDOWN_WINDOWS")
    if v < 0:
        raise ValueError(
            "T4J_SCALE_COOLDOWN_WINDOWS must be >= 0"
        )
    return v


def autoscale_req_path():
    """Path of the grow-request file the serving leader posts and
    ``launch.py --autoscale`` polls (serving/autoscale.py), or ``None``
    when unset.  The launcher sets it for every rank; a leader with no
    path simply cannot request grows (shrinks still work — they ride
    the in-band plan retire flag)."""
    v = os.environ.get("T4J_AUTOSCALE_REQ")
    if v is None or not str(v).strip():
        return None
    return str(v).strip()


_TELEMETRY_MODES = ("off", "counters", "trace")


def telemetry_mode():
    """Comm-telemetry mode (docs/observability.md):

    * ``off`` (default) — zero-cost no-op: every instrumented native
      site is one relaxed atomic load + compare.
    * ``counters`` — the per comm x op x plane metrics table (counts,
      bytes, latency/size histograms -> p50/p99) plus the rare
      control-plane events (link break / reconnect / replay / fault).
    * ``trace`` — counters plus per-event records for ops, wire frames
      and shm arena stages: the Perfetto timeline feed.

    Anything else raises — a typo'd mode must fail at launch, not
    silently record nothing."""
    v = os.environ.get("T4J_TELEMETRY")
    if v is None or not str(v).strip():
        return "off"
    v = str(v).strip().lower()
    if v not in _TELEMETRY_MODES:
        raise ValueError(
            f"cannot interpret T4J_TELEMETRY={v!r} "
            "(want off|counters|trace)"
        )
    return v


def telemetry_bytes():
    """Per-rank telemetry event-ring capacity in bytes (default 1M =
    32Ki 32-byte events; floor 4K).  Writers lapping the drain cursor
    drop the oldest events (counted, never blocking); grow this for
    long jobs drained only at exit."""
    v = byte_count(
        os.environ.get("T4J_TELEMETRY_BYTES"),
        1 << 20,
        name="T4J_TELEMETRY_BYTES",
        minimum=4 << 10,
    )
    return v


def telemetry_dir():
    """Directory every rank drains its telemetry into at exit
    (``<dir>/rank<k>.t4j.json``), or ``None`` when unset.  The
    launcher's ``--telemetry DIR`` sets it for every rank and merges
    the per-rank files into one Perfetto ``job.trace.json``."""
    v = os.environ.get("T4J_TELEMETRY_DIR")
    if v is None or not str(v).strip():
        return None
    return str(v).strip()


def metrics_port():
    """Base port of the live metrics exporter (docs/observability.md
    "live exporter"), or 0 when unset (disabled, the default).

    Rank k serves its metrics snapshot + link stats on
    ``127.0.0.1:<port>+k`` as Prometheus text (``/metrics``) and JSON
    (``/metrics.json``); the launcher's ``--metrics PORT`` sets this
    for every rank and serves the aggregated job view on
    ``<port>+nprocs``.  The base must leave room for every rank below
    65536 — validated against T4J_SIZE when present."""
    v = os.environ.get("T4J_METRICS_PORT")
    if v is None or not str(v).strip():
        return 0
    try:
        port = int(str(v).strip())
    except ValueError:
        raise ValueError(
            f"cannot interpret T4J_METRICS_PORT={v!r} as a port number"
        ) from None
    if port == 0:
        return 0
    world = int(os.environ.get("T4J_SIZE", "1") or 1)
    if not 1 <= port or port + world > 65536:
        raise ValueError(
            f"T4J_METRICS_PORT={port} does not leave room for "
            f"{world} rank port(s) below 65536"
        )
    return port


def flight_enabled():
    """Crash-consistent flight recorder (docs/observability.md "flight
    recorder"): truthy ``T4J_FLIGHT`` backs the telemetry event ring +
    metrics table with a per-rank mmap'd file
    (``<dir>/rank<k>-<boot>.t4jflight``, sized by
    ``T4J_TELEMETRY_BYTES``) whose contents survive a SIGKILL /
    segfault / OOM kill — the evidence ``t4j-postmortem`` reads.  An
    unparsable value raises (a typo'd flag must not silently record
    nothing)."""
    return truthy(os.environ.get("T4J_FLIGHT"), default=False)


def flight_dir():
    """Directory the flight-recorder files land in, or ``None`` when
    unset (the native side then falls back to ``T4J_TELEMETRY_DIR``,
    then the current directory)."""
    v = os.environ.get("T4J_FLIGHT_DIR")
    if v is None or not str(v).strip():
        return None
    return str(v).strip()


def op_timeout():
    """Per-call progress deadline for DCN bridge ops, in seconds.

    0 disables the deadline (wait forever — MPI's behaviour, and the
    default: a slow peer compiling a large program is legal)."""
    return seconds(
        os.environ.get("T4J_OP_TIMEOUT"), 0.0, name="T4J_OP_TIMEOUT"
    )


def connect_timeout():
    """Bootstrap connect/accept deadline in seconds (strictly positive;
    default 30 — the old hardcoded 600 x 50ms retry loop)."""
    v = seconds(
        os.environ.get("T4J_CONNECT_TIMEOUT"),
        30.0,
        name="T4J_CONNECT_TIMEOUT",
    )
    if v <= 0:
        raise ValueError(
            "T4J_CONNECT_TIMEOUT must be > 0 (the bootstrap cannot wait "
            "forever for a rank that never starts)"
        )
    return v
