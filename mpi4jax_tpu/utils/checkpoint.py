"""Checkpoint / resume for sharded state (SURVEY §5.4: the reference has
no checkpointing — its closest analog is gathering the solution to rank
0 for post-processing, examples/shallow_water.py:586-593 there; this
module makes resumable state a first-class subsystem).

Built on orbax (the TPU-native checkpoint stack): each device writes its
own shards (OCDBT), so saving a pod-sharded pytree never funnels the
whole state through one host — the distributed analog of the
reference's gather-to-root, without the gather.

    from mpi4jax_tpu.utils import checkpoint as ckpt

    ckpt.save(path, {"state": state, "step": step})
    restored = ckpt.restore(path, like={"state": state, "step": step})

``like`` supplies shapes/dtypes/shardings (pass the live pytree or one
built from ``jax.eval_shape``); restored arrays come back with the same
sharding they were saved from, ready to feed the next jitted step.
"""

import pathlib

import jax

__all__ = ["save", "restore", "latest_step", "Manager"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save(path, tree, *, force=True):
    """Write ``tree`` (any pytree of arrays / scalars) to ``path``.

    Safe for sharded arrays: every process writes only its addressable
    shards.  ``force=True`` overwrites an existing checkpoint.
    """
    path = pathlib.Path(path).absolute()
    ckptr = _checkpointer()
    ckptr.save(path, tree, force=force)
    ckptr.wait_until_finished()
    ckptr.close()


def restore(path, *, like):
    """Read a pytree written by :func:`save`.

    ``like`` is a pytree matching the saved structure whose leaves
    provide shape/dtype/sharding — pass the live state (its values are
    not read) or abstract leaves from ``jax.eval_shape`` with shardings
    attached.
    """
    path = pathlib.Path(path).absolute()
    abstract = jax.tree.map(_abstractify, like)
    ckptr = _checkpointer()
    try:
        return ckptr.restore(path, abstract)
    finally:
        ckptr.close()


def _abstractify(leaf):
    if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
        sharding = getattr(leaf, "sharding", None)
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sharding)
    return leaf


def latest_step(directory):
    """Highest step number saved by a :class:`Manager` in ``directory``,
    or None."""
    import orbax.checkpoint as ocp

    directory = pathlib.Path(directory).absolute()
    if not directory.exists():
        return None
    mgr = ocp.CheckpointManager(directory)
    try:
        return mgr.latest_step()
    finally:
        mgr.close()


class Manager:
    """Stepped checkpoint series with retention — resume-after-failure
    for long solver / training runs (the elastic-recovery building block
    the reference lacks, SURVEY §5.3/§5.4).

        with checkpoint.Manager(dir, max_to_keep=3) as mgr:
            start = mgr.latest_step() or 0
            state = mgr.restore(start, like=state) if start else state
            for step in range(start, n):
                state = advance(state)
                mgr.maybe_save(step + 1, state, every=100)
    """

    def __init__(self, directory, *, max_to_keep=3):
        import orbax.checkpoint as ocp

        self._mgr = ocp.CheckpointManager(
            pathlib.Path(directory).absolute(),
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def latest_step(self):
        return self._mgr.latest_step()

    def save(self, step, tree):
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(tree))

    def maybe_save(self, step, tree, *, every):
        if every and step % every == 0:
            self.save(step, tree)
            return True
        return False

    def restore(self, step, *, like):
        import orbax.checkpoint as ocp

        abstract = jax.tree.map(_abstractify, like)
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )

    def wait_until_finished(self):
        """Durability barrier: block until every pending save is
        COMMITTED (the orbax step dir renamed out of its ``.tmp``
        form).  Fault-tolerant loops call this before telling other
        ranks the step is safe — a crash after ``save()`` but before
        commit would otherwise leave only a ``.orbax-checkpoint-tmp``
        dir that ``latest_step()`` ignores on restart."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
