from mpi4jax_tpu.utils import config, validation

__all__ = ["config", "validation"]
