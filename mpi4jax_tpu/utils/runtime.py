"""Runtime helpers shared by the bench/example drivers.

``drain`` exists because ``jax.block_until_ready`` is a no-op on some
experimental PJRT platforms (observed on the axon TPU plugin): fetching
a scalar element forces the execution queue to finish on every backend.
The per-tick flush role matches the reference's exit/loop hygiene
(mpi4jax/_src/flush.py:1-12 — device_put+0 noop as a work barrier).
"""

import math

import numpy as np

__all__ = ["drain", "best_mesh_shape"]


def drain(x):
    """Block until device work producing ``x`` has finished.

    ``x`` may be any jax array; returns the first element as a numpy
    scalar (cheap single-element transfer).
    """
    import jax

    arr = x
    while getattr(arr, "ndim", 0) > 0:
        arr = arr[(0,) * arr.ndim]
    return np.asarray(jax.device_get(arr))


def best_mesh_shape(n):
    """Closest-to-square (py, px) with py * px == n and py <= px."""
    best = (1, n)
    for py in range(1, int(math.isqrt(n)) + 1):
        if n % py == 0:
            best = (py, n // py)
    return best
