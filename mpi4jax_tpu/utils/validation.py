"""Runtime argument validation for the public ops.

Re-creates the reference's ``@enforce_types`` behaviour
(mpi4jax/_src/validation.py:8-94) with a lighter mechanism: explicit
check helpers rather than a signature-walking decorator.  The load-bearing
part is the error ergonomics — in particular the "traced value used as a
static argument" hint (validation.py:77-88), which is the most common user
error when wrapping these ops in ``jax.jit``.
"""

import numpy as np

import jax.core

__all__ = [
    "check_static_int",
    "check_rank_range",
    "check_comm",
    "check_op",
    "check_root",
]


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def check_static_int(value, name, allow_none=False):
    """Validate a static integer parameter (root, tag, source, dest...)."""
    if value is None and allow_none:
        return None
    if _is_tracer(value):
        raise TypeError(
            f"{name} must be a static (trace-time) integer, but got a traced "
            f"value. If you are calling this inside jax.jit, mark {name} as "
            f"static (e.g. via functools.partial or jit's static_argnums)."
        )
    if isinstance(value, (bool, np.bool_)):
        raise TypeError(f"{name} must be an integer, got bool")
    if isinstance(value, (int, np.integer)):
        return int(value)
    raise TypeError(f"{name} must be an integer, got {type(value).__name__}")


def check_rank_range(value, name, size):
    """Validate a static partner rank: an int (bools rejected, matching
    :func:`check_static_int`) in ``[0, size)``."""
    if isinstance(value, (bool, np.bool_)):
        raise TypeError(f"{name} must be an integer, got bool")
    value = int(value)
    if not 0 <= value < size:
        raise ValueError(
            f"{name}={value} out of range for communicator of size {size}"
        )
    return value


def check_comm(comm):
    from mpi4jax_tpu.parallel.comm import Comm, get_default_comm

    if comm is None:
        return get_default_comm()
    if not isinstance(comm, Comm):
        raise TypeError(
            f"comm must be an mpi4jax_tpu communicator "
            f"(MeshComm / SelfComm / ProcComm), got {type(comm).__name__}"
        )
    return comm


def check_op(op):
    from mpi4jax_tpu.ops.reductions import Op, named_op

    if isinstance(op, Op):
        return op
    if isinstance(op, str):
        return named_op(op)
    raise TypeError(
        f"op must be an mpi4jax_tpu.Op (e.g. mpi4jax_tpu.SUM) or an op "
        f"name, got {type(op).__name__}"
    )


def check_root(root, comm):
    """Validate a root rank against the communicator size (MPI and the
    reference both reject out-of-range roots; a silent mismatch here
    would zero data instead of erroring)."""
    root = check_static_int(root, "root")
    if not 0 <= root < comm.size:
        raise ValueError(
            f"root={root} out of range for communicator of size {comm.size}"
        )
    return root
