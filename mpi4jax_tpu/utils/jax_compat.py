"""JAX version gate.

The reference warns when running against a jax newer than the last
version it was validated with (mpi4jax/_src/jax_compat.py:59-83 +
_latest_jax_version.txt), because it leans on jax internals.  This
framework uses only public API (jax.shard_map / jax.P / lax collectives
/ jax.ffi), so the gate is a soft warning with the same opt-out
semantics, spelled MPI4JAX_TPU_NO_WARN_JAX_VERSION.
"""

import os
import warnings

# newest jax line this package's test suite has been run against
LATEST_TESTED_JAX = (0, 9)

# oldest jax with the public APIs we require (see pyproject.toml)
MINIMUM_JAX = (0, 7)

__all__ = ["check_jax_version", "LATEST_TESTED_JAX", "MINIMUM_JAX"]


def _parse(version):
    parts = []
    for tok in version.split(".")[:2]:
        digits = "".join(ch for ch in tok if ch.isdigit())
        parts.append(int(digits or 0))
    return tuple(parts)


def check_jax_version(jax_version=None):
    """Warn (once per process) when jax is newer than the tested pin or
    error when older than the supported floor."""
    import jax

    v = _parse(jax_version or jax.__version__)
    if v < MINIMUM_JAX:
        raise RuntimeError(
            f"mpi4jax_tpu requires jax>={'.'.join(map(str, MINIMUM_JAX))}, "
            f"found {jax_version or jax.__version__}"
        )
    if v > LATEST_TESTED_JAX and not os.environ.get(
        "MPI4JAX_TPU_NO_WARN_JAX_VERSION"
    ):
        warnings.warn(
            f"jax {jax_version or jax.__version__} is newer than the last "
            f"version mpi4jax_tpu was validated against "
            f"({'.'.join(map(str, LATEST_TESTED_JAX))}.x). Things probably "
            "work — set MPI4JAX_TPU_NO_WARN_JAX_VERSION=1 to silence this.",
            stacklevel=3,
        )
