"""Request lifecycle for the serving subsystem (docs/serving.md).

A request is one prompt → one bounded continuation.  Its state walks

    QUEUED → ADMITTED → ACTIVE → DONE
       └──────────→ SHED   (admission refused, or deadline hopeless)

with every transition stamped in caller-supplied milliseconds (time is
injected, never read — the state machines stay deterministic under
test).  Import-free of jax, like the rest of the pure core.
"""

__all__ = ["Request", "RequestState"]


class RequestState:
    """String-valued request states (compared by identity-safe str)."""

    QUEUED = "queued"
    ADMITTED = "admitted"  # slot assigned, prefill pending/running
    ACTIVE = "active"      # decoding in a slot
    DONE = "done"
    SHED = "shed"

    ALL = (QUEUED, ADMITTED, ACTIVE, DONE, SHED)


class Request:
    """One inference request.

    ``prompt`` is a tuple of token ids (the pure core never interprets
    them; the engine feeds them to the model).  ``max_new`` is the
    requested continuation length; the effective value is clamped by
    the engine's ``max_len`` budget at admission.  ``deadline_ms`` is
    absolute (arrival + SLO) or ``None`` when the job has no SLO.
    """

    __slots__ = (
        "rid", "prompt", "max_new", "arrival_ms", "deadline_ms",
        "state", "slot", "last_slot", "generated", "admitted_ms",
        "first_token_ms", "done_ms", "shed_reason",
        "reissues", "emitted",
    )

    def __init__(self, rid, prompt, max_new, arrival_ms,
                 deadline_ms=None):
        if max_new < 1:
            raise ValueError(
                f"request {rid}: max_new must be >= 1, got {max_new}"
            )
        if len(prompt) < 1:
            raise ValueError(f"request {rid}: empty prompt")
        self.rid = int(rid)
        self.prompt = tuple(int(t) for t in prompt)
        self.max_new = int(max_new)
        self.arrival_ms = float(arrival_ms)
        self.deadline_ms = (
            None if deadline_ms is None else float(deadline_ms)
        )
        self.state = RequestState.QUEUED
        self.slot = None
        self.last_slot = None  # survives completion (the engine's
        # harvest reads the freed slot's token buffer the same step)
        self.generated = 0
        self.admitted_ms = None
        self.first_token_ms = None
        self.done_ms = None
        self.shed_reason = None
        # elastic epoch survival (docs/failure-semantics.md): how many
        # times this request was reissued after a resize wiped its slot
        # state, and how many leading tokens had already been emitted
        # to the client before the loss.  Re-generation is
        # deterministic (greedy argmax), so the engine re-runs the
        # request from its prompt but only emits tokens at index >=
        # ``emitted`` — the rid+position dedupe contract: completed
        # tokens are never re-emitted.
        self.reissues = 0
        self.emitted = 0

    @property
    def prompt_len(self):
        return len(self.prompt)

    def latency_ms(self):
        """End-to-end latency (arrival → completion), or ``None`` while
        in flight."""
        if self.done_ms is None:
            return None
        return self.done_ms - self.arrival_ms

    def within_slo(self):
        """Did the request complete before its deadline?  ``True`` for
        completed requests without a deadline; ``False`` for shed or
        unfinished ones (a shed request by definition missed the
        service it asked for — the honest accounting docs/serving.md
        insists on)."""
        if self.state != RequestState.DONE:
            return False
        if self.deadline_ms is None:
            return True
        return self.done_ms <= self.deadline_ms

    def __repr__(self):
        return (
            f"Request(rid={self.rid}, p={self.prompt_len}, "
            f"new={self.generated}/{self.max_new}, {self.state}"
            + (f", slot={self.slot}" if self.slot is not None else "")
            + ")"
        )
