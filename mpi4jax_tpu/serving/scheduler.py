"""Slot-based continuous-batching scheduler (docs/serving.md).

The KV cache is a fixed pool of ``max_batch`` *slots*, each a
``max_len``-position budget.  Decode runs every ACTIVE slot one
position per engine step; a free slot can be filled by a *prefill*
(one causal pass over a queued request's prompt) **while the other
slots keep decoding** — that interleaving is the whole point of
continuous batching: a long generation never blocks a short request
behind it, and the batch stays as full as the queue allows.

This module is the pure state machine: which request enters which slot
when, where each slot's write position is, and when a request
completes.  It never touches jax or the clock — the engine supplies
time and executes the plans; tests drive it step by step.

Invariant (checked by :meth:`SlotScheduler.check_accounting`): every
submitted request is in exactly one of queued / holding-a-slot /
done / shed, with each elastic *reissue* (a resize wiped a slot's
state and the request went back to the queue) counted as a fresh
submission balanced by the ``reissued`` counter:

    queued + in_slots + done + shed + reissued == submitted

A violated invariant raises :class:`SchedulerError` instead of
silently leaking a slot — a leaked slot is capacity the admission
controller thinks it has.
"""

from collections import deque

from .request import Request, RequestState

__all__ = ["FollowerMirror", "SchedulerError", "SlotScheduler",
           "StepPlan", "slots_digest"]


def slots_digest(rows):
    """FNV-1a digest over slot-table rows ``(rid_or_-1, pos, end)`` —
    THE shared digest between the leader's :class:`SlotScheduler` and
    a follower's :class:`FollowerMirror`, carried in every step plan
    so state drift fails attributably (:mod:`.plan`)."""
    acc = 2166136261
    for i, (rid, pos, end) in enumerate(rows):
        for v in (i, rid, pos, end):
            acc ^= (v + 1) & 0xFFFFFFFF
            acc = (acc * 16777619) & 0xFFFFFFFF
    return acc


class SchedulerError(RuntimeError):
    """A scheduler invariant was violated (slot leak, double admit,
    stepping an empty batch...)."""


class StepPlan:
    """One engine step, as decided by the scheduler: which queued
    requests enter which free slots (``admissions``: list of
    ``(slot, Request)``) and which slots decode this step
    (``decode_slots``: sorted slot indices, with ``positions[i]`` the
    KV write position of ``decode_slots[i]``)."""

    __slots__ = ("step", "admissions", "decode_slots", "positions")

    def __init__(self, step, admissions, decode_slots, positions):
        self.step = step
        self.admissions = admissions
        self.decode_slots = decode_slots
        self.positions = positions

    @property
    def empty(self):
        return not self.admissions and not self.decode_slots

    def __repr__(self):
        return (
            f"StepPlan(step={self.step}, "
            f"admit={[(s, r.rid) for s, r in self.admissions]}, "
            f"decode={list(zip(self.decode_slots, self.positions))})"
        )


class _Slot:
    __slots__ = ("req", "pos", "end")

    def __init__(self):
        self.req = None   # Request holding this slot (None = free)
        self.pos = 0      # next KV write position (absolute)
        self.end = 0      # stop when pos reaches this (exclusive)


class SlotScheduler:
    """Continuous-batching slot allocator + step planner.

    ``max_prefill_per_step`` bounds how many prefills one step admits
    (each prefill is a full causal pass — admitting many at once would
    stall the in-flight decodes it shares the step with; 1 is the
    classic continuous-batching choice).
    """

    def __init__(self, max_batch, max_len, max_prefill_per_step=1):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_len < 2:
            raise ValueError(
                f"max_len must be >= 2 (a prompt position plus at "
                f"least one generated token), got {max_len}"
            )
        if max_prefill_per_step < 1:
            raise ValueError("max_prefill_per_step must be >= 1")
        self.max_batch = int(max_batch)
        self.max_len = int(max_len)
        self.max_prefill_per_step = int(max_prefill_per_step)
        self._slots = [_Slot() for _ in range(self.max_batch)]
        self._queue = deque()
        self._step = 0
        self._hold = False  # admissions held (autoscale drain)
        # accounting
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        self.reissued = 0
        self.finished = []  # completed Requests, engine drains this

    # ---- queue side ------------------------------------------------------

    def submit(self, req, now_ms):
        """Enqueue an (admission-approved) request."""
        if req.state != RequestState.QUEUED:
            raise SchedulerError(
                f"submit of request {req.rid} in state {req.state}"
            )
        if req.prompt_len >= self.max_len:
            raise SchedulerError(
                f"request {req.rid}: prompt length {req.prompt_len} "
                f"leaves no room to generate within max_len="
                f"{self.max_len} (admission should have shed it)"
            )
        self.submitted += 1
        self._queue.append(req)

    def shed_request(self, req, now_ms, reason):
        """Mark a request shed (admission refusal, or a hopeless
        deadline discovered while queued) and drop it from the queue if
        it is there.  Sheds are COUNTED — a controller that quietly
        drops work would fake its SLO numbers (docs/serving.md)."""
        if req.state != RequestState.QUEUED:
            raise SchedulerError(
                f"shed of request {req.rid} in state {req.state} "
                "(in-slot requests run to completion)"
            )
        was_submitted = req in self._queue
        if was_submitted:
            self._queue.remove(req)
        req.state = RequestState.SHED
        req.shed_reason = str(reason)
        req.done_ms = float(now_ms)
        if was_submitted:
            self.shed += 1
        else:
            # shed at the door (never submitted): count it here so the
            # accounting invariant covers both shed paths
            self.submitted += 1
            self.shed += 1

    def queue_depth(self):
        return len(self._queue)

    def queued(self):
        """The queued requests, arrival order (read-only view)."""
        return tuple(self._queue)

    # ---- slot side -------------------------------------------------------

    def free_slots(self):
        return [i for i, s in enumerate(self._slots) if s.req is None]

    def occupancy(self):
        """Slots currently held (admitted or decoding)."""
        return self.max_batch - len(self.free_slots())

    def active_requests(self):
        return tuple(
            s.req for s in self._slots if s.req is not None
        )

    # ---- planning --------------------------------------------------------

    def plan_step(self, now_ms):
        """Decide one engine step: admit queue-head requests into free
        slots (bounded by ``max_prefill_per_step``) and decode every
        slot that is past its prefill.  Admitted requests transition to
        ADMITTED here; the engine reports their prefill via
        :meth:`prefill_done` (same step — prefill yields the first
        generated token)."""
        admissions = []
        free = [] if self._hold else self.free_slots()
        while (self._queue and free
               and len(admissions) < self.max_prefill_per_step):
            req = self._queue.popleft()
            slot = free.pop(0)
            s = self._slots[slot]
            s.req = req
            s.pos = req.prompt_len
            # effective continuation: clamped by the slot budget.
            # pos runs prompt_len .. end-1; prefill emits token at
            # index prompt_len, each decode step one more, and the
            # LAST token needs no KV write, so end = prompt_len +
            # max_new - 1 decode positions (bounded by max_len - 1:
            # position max_len-1 is the last writable one).
            s.end = min(
                req.prompt_len + req.max_new - 1, self.max_len - 1
            )
            req.state = RequestState.ADMITTED
            req.slot = slot
            req.last_slot = slot
            req.admitted_ms = float(now_ms)
            admissions.append((slot, req))
        decode_slots = []
        positions = []
        for i, s in enumerate(self._slots):
            if s.req is not None and s.req.state == RequestState.ACTIVE:
                decode_slots.append(i)
                positions.append(s.pos)
        plan = StepPlan(self._step, admissions, decode_slots, positions)
        self._step += 1
        return plan

    # ---- execution reports ----------------------------------------------

    def prefill_done(self, slot, now_ms):
        """The engine finished the prefill for ``slot``: the request
        got its first generated token and joins decode from the next
        step on (or completes right here when it asked for a single
        token / its prompt fills the budget)."""
        s = self._slots[slot]
        req = s.req
        if req is None or req.state != RequestState.ADMITTED:
            raise SchedulerError(
                f"prefill_done on slot {slot} in state "
                f"{req.state if req else 'free'}"
            )
        req.state = RequestState.ACTIVE
        req.generated = 1
        req.first_token_ms = float(now_ms)
        if s.pos >= s.end:
            self._complete(slot, now_ms)

    def step_done(self, plan, now_ms):
        """The engine executed ``plan``'s decode: every decoded slot
        advanced one position and emitted one token.  Completions free
        their slots; the freed capacity is visible to the very next
        :meth:`plan_step`."""
        for slot in plan.decode_slots:
            s = self._slots[slot]
            req = s.req
            if req is None or req.state != RequestState.ACTIVE:
                raise SchedulerError(
                    f"step_done on slot {slot} in state "
                    f"{req.state if req else 'free'}"
                )
            s.pos += 1
            req.generated += 1
            if s.pos >= s.end:
                self._complete(slot, now_ms)

    def _complete(self, slot, now_ms):
        s = self._slots[slot]
        req = s.req
        req.state = RequestState.DONE
        req.done_ms = float(now_ms)
        req.slot = None
        s.req = None
        s.pos = s.end = 0
        self.completed += 1
        self.finished.append(req)

    # ---- elastic epoch survival -----------------------------------------

    def hold_admissions(self, hold=True):
        """Stop (or resume) admitting queued requests into free slots.
        Used by the autoscaler's drain phase: in-flight requests run to
        completion while the batch empties, and by the resize window
        itself (no request should enter a slot the next epoch will not
        remember)."""
        self._hold = bool(hold)

    @property
    def admissions_held(self):
        return self._hold

    def clamp_completions(self, max_remaining):
        """Clamp every occupied slot to at most ``max_remaining`` more
        generated tokens — the autoscaler's drain bound.  Requests
        still complete through the normal :meth:`step_done` path (DONE,
        not shed; ``generated`` reflects what they actually got), the
        drain just finishes within a known number of steps instead of
        waiting out the longest continuation.  Returns the number of
        slots whose horizon actually moved."""
        if max_remaining < 0:
            raise ValueError(
                f"max_remaining must be >= 0, got {max_remaining}"
            )
        clamped = 0
        for s in self._slots:
            if s.req is None:
                continue
            new_end = s.pos + int(max_remaining)
            if new_end < s.end:
                s.end = new_end
                clamped += 1
        return clamped

    def snapshot_inflight(self):
        """The requests currently holding slots, as ``(slot, Request)``
        pairs — the leader's pre-resize snapshot (engine epoch
        survival) and the promotion handoff's source of truth."""
        return [
            (i, s.req)
            for i, s in enumerate(self._slots)
            if s.req is not None
        ]

    def reissue_inflight(self, now_ms):
        """A resize wiped the KV/slot state: return every in-slot
        request to the FRONT of the queue (they were admitted first;
        they re-enter first) and free all slots.

        Each reissued request remembers how many tokens it had already
        emitted (``req.emitted``) so the engine's dedupe-on-rid+position
        contract holds: re-generation is deterministic, and only tokens
        past the reissue point are emitted again.  Accounting-wise a
        reissue is a fresh submission balanced by ``reissued`` — see
        :meth:`check_accounting`.  Returns the reissued requests in
        re-queue order."""
        lost = self.snapshot_inflight()
        out = []
        # Reverse so appendleft preserves slot order at the queue head.
        for slot, req in reversed(lost):
            s = self._slots[slot]
            req.emitted = max(req.emitted, req.generated)
            req.reissues += 1
            req.state = RequestState.QUEUED
            req.slot = None
            req.generated = 0
            s.req = None
            s.pos = s.end = 0
            self._queue.appendleft(req)
            self.submitted += 1
            self.reissued += 1
            out.append(req)
        out.reverse()
        return out

    # ---- lifecycle -------------------------------------------------------

    def idle(self):
        """Nothing queued, nothing in a slot — safe to stop stepping."""
        return not self._queue and all(
            s.req is None for s in self._slots
        )

    def check_accounting(self):
        """Raise :class:`SchedulerError` unless every submitted request
        is queued, in a slot, done, or shed — with each elastic reissue
        counted as a fresh submission balanced by ``reissued`` — the
        request-leak check shutdown runs (tests/proc/
        test_serving_proc.py pins it; tools/autoscale_smoke.py asserts
        it on every rank at every epoch)."""
        in_slots = sum(1 for s in self._slots if s.req is not None)
        total = (len(self._queue) + in_slots + self.completed
                 + self.shed + self.reissued)
        if total != self.submitted:
            raise SchedulerError(
                f"request leak: submitted={self.submitted} but "
                f"queued={len(self._queue)} + in_slots={in_slots} + "
                f"done={self.completed} + shed={self.shed} + "
                f"reissued={self.reissued} = {total}"
            )
        return True

    def state_digest(self):
        """Slot-table digest (:func:`slots_digest`) — cross-rank step
        plans carry it so a follower whose mirrored state drifted
        raises attributably instead of decoding garbage
        (:mod:`.plan`)."""
        return slots_digest(
            (-1 if s.req is None else s.req.rid, s.pos, s.end)
            for s in self._slots
        )


class FollowerMirror:
    """A follower rank's slot-table mirror, fed ONLY by decoded step
    plans (docs/serving.md "the control plane").

    Followers never see the queue or the admission decisions — they
    execute what the leader broadcast.  The mirror tracks exactly the
    slot rows the digest covers, so :meth:`state_digest` must match
    the leader's pre-plan digest every step; :meth:`apply` returns
    the slots freed by completions this step (the engine clears their
    output rows)."""

    def __init__(self, max_batch, max_len):
        self.max_batch = int(max_batch)
        self.max_len = int(max_len)
        # slot -> [rid, pos, end] (absent = free)
        self._rows = {}
        self.completed = 0

    def state_digest(self):
        return slots_digest(
            (self._rows[i][0], self._rows[i][1], self._rows[i][2])
            if i in self._rows else (-1, 0, 0)
            for i in range(self.max_batch)
        )

    def apply(self, decoded):
        """Apply a :func:`.plan.decode_plan` dict: admissions fill
        slots, decodes advance positions, completions free.  Returns
        ``(admitted, finished)`` — ``admitted`` the list of
        ``(slot, rid, prompt, max_new)`` to prefill, ``finished`` the
        ``(slot, rid)`` pairs whose requests completed this step."""
        finished = []
        admitted = []
        for (slot, rid, p_len, max_new, _dl), prompt in zip(
                decoded["admissions"], decoded["prompts"]):
            if slot in self._rows:
                raise SchedulerError(
                    f"plan step {decoded['step']}: admission of "
                    f"request {rid} into occupied slot {slot}"
                )
            end = min(p_len + max_new - 1, self.max_len - 1)
            self._rows[slot] = [rid, p_len, end]
            admitted.append((slot, rid, prompt, max_new))
        for slot, pos in zip(decoded["decode_slots"],
                             decoded["positions"]):
            row = self._rows.get(slot)
            if row is None or row[1] != pos:
                raise SchedulerError(
                    f"plan step {decoded['step']}: decode of slot "
                    f"{slot} at pos {pos} but mirror has "
                    f"{row if row else 'free'}"
                )
            row[1] += 1
            if row[1] >= row[2]:
                finished.append((slot, row[0]))
                del self._rows[slot]
                self.completed += 1
        return admitted, finished

    def prefill_done(self, slot):
        """Prefill-instant completion check (a request whose prompt
        fills its budget completes without any decode step — the
        leader's :meth:`SlotScheduler.prefill_done` path).  Returns
        the ``(slot, rid)`` pair if the request completed."""
        row = self._rows.get(slot)
        if row is None:
            raise SchedulerError(f"prefill_done on free slot {slot}")
        if row[1] >= row[2]:
            del self._rows[slot]
            self.completed += 1
            return (slot, row[0])
        return None

    def occupancy(self):
        return len(self._rows)

    def idle(self):
        return not self._rows

    def rows(self):
        """Occupied slots as ``{slot: (rid, pos, end)}`` — read-only
        copy for promotion (a follower elected leader after rank 0
        died rebuilds a :class:`SlotScheduler` from its mirror plus the
        per-rid requests it retained) and for rebuild verification."""
        return {i: tuple(r) for i, r in self._rows.items()}

    def reset(self):
        """Drop every mirrored slot (resize wiped the KV state; the
        new epoch's plans re-admit from scratch)."""
        self._rows.clear()
