"""Continuous-batching tensor-parallel inference engine
(docs/serving.md).

Turns ``models/transformer.py``'s ``_prefill_sharded`` /
``_decode_step_sharded`` KV-cache machinery into a served loop on the
proc tier: the KV cache is a pool of ``max_batch`` *slots*, every
engine step decodes all active slots one position (one jitted
executable, per-slot positions) while a queued request's prefill is
admitted into a free slot *in the same step* — so a long generation
never blocks a short request, and the batch stays as full as
admission allows.

Control plane: rank 0 is the frontend (load generator, admission
controller, scheduler); every step it broadcasts a fixed-size plan
vector (:mod:`.plan`) over ``host_bcast``, and followers execute it
against a :class:`~.scheduler.FollowerMirror` whose digest is checked
every step — scheduling reads live telemetry only rank 0 sees, so the
plan cannot be recomputed per rank (the same uniformity argument as
tuning's rank-0 knob broadcast).

Data plane: the per-layer Megatron f/g collectives inside the decode
and prefill executables.  Since PR 7 every collective body runs on
the async progress engine (blocking = submit + wait on the one wire
path), so decode's wire phase progresses off the caller's thread;
with ``overlap=True`` the engine dispatches the step's prefill
executables BEFORE blocking on the decode logits, so prefill compute
overlaps decode comm (docs/async.md; docs/serving.md reports the
measured effect honestly — a CPU-oversubscribed loopback box has
little idle to harvest).

Each step is wrapped in a ``step_scope`` marker, so ``t4j-diagnose``
decomposes any p99 blowup into compute / caller-blocked / wire /
repair per rank — the acceptance demo uses exactly that to attribute
a delayed rank (docs/serving.md "diagnosing a p99 blowup").

Elastic epochs (docs/failure-semantics.md "serving epoch survival"):
with ``T4J_ELASTIC`` enabled, a membership change surfaces mid-step as
``WorldResized`` / a ``ResizeInterrupted`` collective status.  The
engine *rides* it instead of dying: every survivor waits the resize
out, re-resolves tuning on the new fingerprint, re-shards the model
for the surviving membership, and the leader reissues every in-slot
request (completed tokens are never re-emitted — completions are
delivered exactly once, and greedy decode re-generates the lost
prefix deterministically).  If rank 0 died, the lowest surviving rank
promotes itself: followers retain each admitted request's prompt
exactly so the successor can rebuild a scheduler from its mirror.  A
``T4J_REJOIN=1`` expansion rank rebuilds its mirror by replaying the
leader's plan log before serving its first step, and the per-step
digest check proves agreement.  :meth:`autoscale_window` feeds the
:class:`~.autoscale.Autoscaler` policy that drives these epochs from
traffic instead of faults.
"""

import os
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from mpi4jax_tpu.models import transformer as tfm
from mpi4jax_tpu.ops import reductions
from mpi4jax_tpu.serving import autoscale as autoscale_mod
from mpi4jax_tpu.ops import step as step_mod
from mpi4jax_tpu.ops._core import create_token
from mpi4jax_tpu.ops.allreduce import allreduce
from mpi4jax_tpu.parallel.longseq import local_attention
from mpi4jax_tpu.serving import plan as plan_mod
from mpi4jax_tpu.serving import stats as stats_mod
from mpi4jax_tpu.serving.admission import (
    AdmissionController,
    SLOEstimator,
    TokenBucket,
)
from mpi4jax_tpu.serving.scheduler import (
    FollowerMirror,
    SlotScheduler,
)
from mpi4jax_tpu.serving.stats import ServingStats
from mpi4jax_tpu.utils import config

__all__ = ["ServingEngine", "shard_params"]


def _is_resize_error(exc):
    """A mid-step exception that means "the world changed", not "the
    world broke": the elastic layer's WorldResized, or a collective
    status stringifying to ResizeInterrupted (ops raise BridgeError
    with that tag when a resize commits under an in-flight op)."""
    from mpi4jax_tpu.native.runtime import WorldResized

    if isinstance(exc, WorldResized):
        return True
    s = str(exc)
    return "ResizeInterrupted" in s or "world resized" in s


def shard_params(params, tp, rank):
    """Slice full (replicated) transformer params to rank ``rank``'s
    tensor-parallel shard: qkv/mlp-up column shards, o/mlp-down row
    shards, everything else replicated — the same layout
    ``param_specs`` declares for the mesh tier."""
    if tp == 1:
        return params
    b = params.blocks

    def cols(w):  # (L, d, n) -> rank's n/tp column block
        n = w.shape[2]
        if n % tp:
            raise ValueError(
                f"cannot shard {n} columns over tp={tp}"
            )
        k = n // tp
        return w[:, :, rank * k:(rank + 1) * k]

    def rows(w):  # (L, n, d) -> rank's n/tp row block
        n = w.shape[1]
        if n % tp:
            raise ValueError(f"cannot shard {n} rows over tp={tp}")
        k = n // tp
        return w[:, rank * k:(rank + 1) * k, :]

    blocks = b._replace(
        wq=cols(b.wq), wk=cols(b.wk), wv=cols(b.wv), wo=rows(b.wo),
        w1=cols(b.w1), w2=rows(b.w2),
    )
    return params._replace(blocks=blocks)


def _decode_step_slots(params, cache, last_tok, pos, cfg, comm_tp,
                       hq_l, hk_l):
    """One decode step over EVERY slot with per-slot positions —
    ``models.transformer._decode_step_sharded`` generalised from one
    scalar ``pos`` to a ``[B]`` vector (continuous batching runs each
    slot at its own depth).  Inactive slots are computed and ignored
    (static shapes; the waste is the classic static-batch cost,
    docs/serving.md).  Same math per row, so responses stay
    token-identical to the offline decoder."""
    dh = cfg.head_dim
    b = last_tok.shape[0]
    s_max = cache.shape[3]
    x = params.embed[last_tok][:, None, :]  # (B, 1, d)
    token = create_token()
    # one-hot write mask for the per-row KV position: 0/1 multiply-add
    # is exact in f32, so the update matches dynamic_update_slice bit
    # for bit
    oh = (jnp.arange(s_max)[None, :] == pos[:, None])
    ohf = oh.astype(cache.dtype)[..., None, None]  # (B, S, 1, 1)

    def layer(carry, inputs):
        x, token = carry
        bp, kv = inputs
        h = tfm._rmsnorm(x, bp.ln1, cfg.eps)
        h, token = tfm._f_collective(h, comm_tp, token)
        q = (h @ bp.wq).reshape(b, 1, hq_l, dh)
        k_new = (h @ bp.wk).reshape(b, 1, hk_l, dh)
        v_new = (h @ bp.wv).reshape(b, 1, hk_l, dh)
        k_cache = kv[0] * (1 - ohf) + k_new * ohf
        v_cache = kv[1] * (1 - ohf) + v_new * ohf
        # per-row causal offset: vmap local_attention over the batch
        # with each row's own q_offset (the scalar-pos decode step is
        # the B=const special case)
        attn = jax.vmap(
            lambda q1, k1, v1, p1: local_attention(
                q1[None], k1[None], v1[None], causal=True,
                q_offset=p1, impl="xla",
            )[0]
        )(q, k_cache, v_cache, pos)
        a_part = attn.reshape(b, 1, hq_l * dh) @ bp.wo
        a, token = allreduce(
            a_part, reductions.SUM, comm=comm_tp, token=token
        )
        x = x + a
        h2 = tfm._rmsnorm(x, bp.ln2, cfg.eps)
        h2, token = tfm._f_collective(h2, comm_tp, token)
        m_part = jax.nn.gelu(h2 @ bp.w1) @ bp.w2
        m, token = allreduce(
            m_part, reductions.SUM, comm=comm_tp, token=token
        )
        return (x + m, token), jnp.stack([k_cache, v_cache])

    (x, _token), cache = lax.scan(
        layer, (x, token), (params.blocks, cache)
    )
    x = tfm._rmsnorm(x, params.ln_f, cfg.eps)
    logits = (x @ params.head)[:, 0, :]  # (B, V)
    return cache, logits


class ServingEngine:
    """One rank's half of the serving loop (leader on rank 0).

    ``comm`` is the tensor-parallel communicator (the proc world in
    the benchmarks); ``params`` are FULL (replicated) parameters —
    the engine shards them.  Knobs default from the environment
    (``T4J_MAX_BATCH`` / ``T4J_ADMIT`` / ``T4J_SLO_MS``,
    utils/config.py).
    """

    def __init__(self, comm, cfg, params, *, max_len, max_batch=None,
                 admit=None, slo_ms=None, rate_limit=0.0, burst=8,
                 overlap=True, markers=True, seed_step_ms=20.0,
                 fabric_poll_s=0.5, estimator=None, plan_log=None):
        self.comm = comm
        self.cfg = cfg
        self.tp = comm.size
        self.rank = comm.rank()
        self.is_leader = self.rank == 0
        self.max_len = int(max_len)
        self.max_batch = (config.max_batch() if max_batch is None
                          else int(max_batch))
        self.admit_mode = (config.admit_mode() if admit is None
                           else admit)
        slo = config.slo_ms() if slo_ms is None else float(slo_ms)
        if self.admit_mode == "off":
            slo = 0.0  # cannot be enforced; config rejects it being set
        self.slo_ms = slo
        self.overlap = bool(overlap)
        self.markers = bool(markers)
        tfm._check_tp_divisibility(cfg, self.tp)
        self.hq_l = cfg.heads // self.tp
        self.hk_l = cfg.kv_heads // self.tp
        self.params = shard_params(params, self.tp, self.rank)
        self.cache = jnp.zeros(
            (cfg.layers, 2, self.max_batch, self.max_len, self.hk_l,
             cfg.head_dim),
            self.params.embed.dtype,
        )
        # host-side token buffers (one row per slot)
        self.toks = np.zeros((self.max_batch, self.max_len), np.int64)
        self._row_len = np.zeros(self.max_batch, np.int64)
        self.finished = []  # (rid, token tuple) in completion order

        if self.is_leader:
            self.sched = SlotScheduler(self.max_batch, self.max_len)
            est = estimator or SLOEstimator(seed_step_ms=seed_step_ms)
            bucket = (TokenBucket(rate_limit, burst)
                      if rate_limit else None)
            self.ctrl = AdmissionController(
                self.admit_mode, slo_ms=self.slo_ms, estimator=est,
                bucket=bucket,
            )
            self.stats = ServingStats(
                slo_ms=self.slo_ms, max_batch=self.max_batch,
                admit_mode=self.admit_mode,
            )
            self.mirror = None
        else:
            self.sched = None
            self.ctrl = None
            self.stats = ServingStats(
                slo_ms=self.slo_ms, max_batch=self.max_batch,
                admit_mode=self.admit_mode,
            )
            self.mirror = FollowerMirror(self.max_batch, self.max_len)

        # leader-side plan-stream recorder: every broadcast vector is
        # appended so follower-drift bugs replay offline through
        # ``t4j-verify --plan-stream`` (serving/plan.py replay_stream)
        if plan_log is None:
            plan_log = os.environ.get("T4J_PLAN_LOG") or None
        self._plan_log_path = plan_log  # kept for joiners + promotion
        self.plan_log = plan_log if self.is_leader else None

        self._plan_words = plan_mod.plan_words(self.max_batch,
                                               self.max_len)
        self._decode_jit = jax.jit(self._decode_fn)
        self._prefill_jits = {}
        self._step_idx = 0
        self._stopped = False
        self._fabric_poll_s = float(fabric_poll_s)
        self._last_fabric_poll = 0.0

        # ---- elastic epoch survival state --------------------------------
        # full params are kept so a resize can re-shard for the new
        # world; followers retain each admitted request (prompt and
        # all) so a promoted successor can rebuild the scheduler.
        self._full_params = params
        self._leader_rank = 0
        self._rank_index = self.rank  # shard index == world rank at boot
        self._model_ready = True
        self._epoch = None
        self._retained = {}  # follower: rid -> Request
        self._scaler = None
        self._autoscale_req = None
        self._budget_ms = 0.0
        self._retire_queue = []  # world ranks to retire, one per plan
        self._drain_clamp = 8
        if not self.is_leader and os.environ.get("T4J_REJOIN") == "1":
            self._joiner_bootstrap()

    # ---- jitted bodies ---------------------------------------------------

    def _decode_fn(self, params, cache, last_tok, pos):
        return _decode_step_slots(
            params, cache, last_tok, pos, self.cfg, self.comm,
            self.hq_l, self.hk_l,
        )

    def _prefill_bucket(self, p_len):
        """Compile-size bucket: smallest power of two >= p_len (floor
        8), capped at max_len — one executable per bucket instead of
        one per prompt length."""
        b = 8
        while b < p_len:
            b <<= 1
        return min(b, self.max_len)

    def _prefill_jit(self, bucket):
        fn = self._prefill_jits.get(bucket)
        if fn is None:
            cfg, comm = self.cfg, self.comm
            hq_l, hk_l, max_len = self.hq_l, self.hk_l, self.max_len

            def prefill(params, cache, prompt, slot, p_len):
                kv, logits = tfm._prefill_sharded(
                    params, prompt, cfg, comm, hq_l, hk_l, max_len,
                    logits_pos=p_len - 1,
                )
                cache = lax.dynamic_update_slice(
                    cache, kv, (0, 0, slot, 0, 0, 0)
                )
                return cache, logits[0]

            fn = jax.jit(prefill)
            self._prefill_jits[bucket] = fn
        return fn

    # ---- leader: request intake -----------------------------------------

    SHED_PROMPT = "prompt-too-long"

    def offer(self, req, now_ms):
        """Admission decision for one arriving request (leader only).
        Returns ``"admit"`` or ``"shed"``."""
        assert self.is_leader, "offer() is the leader's entry point"
        self.stats.observe_submitted()
        if req.prompt_len >= self.max_len:
            # unservable regardless of load: the slot budget leaves no
            # room to generate.  Shed (counted) instead of letting
            # sched.submit raise and take the whole serving loop down
            # with one oversized client request.
            self.sched.shed_request(req, now_ms, self.SHED_PROMPT)
            self.stats.observe_shed(self.SHED_PROMPT)
            return "shed"
        verdict, reason = self.ctrl.decide(req, now_ms, self.sched)
        if verdict == "admit":
            self.sched.submit(req, now_ms)
        else:
            self.sched.shed_request(req, now_ms, reason)
            self.stats.observe_shed(reason)
        return verdict

    def _poll_fabric(self, now_ms):
        """Feed the admission controller the live fabric signals: the
        worst-link gauges from this rank's own link stats (the PR-8
        exporter's job view carries the same fields aggregated; pass
        one through :meth:`set_fabric_view` when a launcher aggregator
        is scraping)."""
        if now_ms - self._last_fabric_poll < self._fabric_poll_s * 1e3:
            return
        self._last_fabric_poll = now_ms
        try:
            from mpi4jax_tpu.native import runtime

            agg = runtime.link_stats() or {}
        except Exception:
            return
        view = {"worst_link": {
            "state": agg.get("state", 0),
            "reconnects": agg.get("max_reconnects", 0),
            "peer": agg.get("worst_peer"),
            "rank": self.rank,
        }}
        self.ctrl.observe_fabric(view)

    def set_fabric_view(self, job_view):
        """Feed an exporter job-view dict (launcher ``--metrics``
        aggregate) into admission's degradation model."""
        if self.ctrl is not None:
            self.ctrl.observe_fabric(job_view)

    # ---- the step --------------------------------------------------------

    def _bcast(self, vec_or_none):
        if vec_or_none is None:
            vec = np.zeros(self._plan_words, np.int64)
        else:
            vec = np.asarray(vec_or_none, np.int64)
        if self.tp == 1:
            # single-member world: the leader is the whole control
            # plane (SelfComm tests and tp=1 serving)
            return vec
        from mpi4jax_tpu.native import runtime

        return runtime.host_bcast(
            runtime.comm_handle(self.comm), vec, 0
        )

    def _execute(self, admissions, decode_slots, positions):
        """Run one step's executables: the decode over all slots (when
        any slot is active) and each admission's prefill.  With
        ``overlap=True`` prefills are dispatched before the decode
        result is blocked on, so their compute overlaps the decode
        collectives' wire phase (every collective body runs on the
        PR-7 progress engine).

        Returns ``(decode_ms, prefill_ms)``: the wall up to the decode
        logits landing, and the MARGINAL wall the prefills added after
        that — attributed separately so a batch that always has a slot
        decoding still teaches the prefill estimator (a combined wall
        would inflate the step EWMA at every admission and freeze the
        prefill model at its seed)."""
        t0 = time.perf_counter()
        decode_out = None
        if decode_slots:
            pos_all = np.zeros(self.max_batch, np.int32)
            last_all = np.zeros(self.max_batch, np.int32)
            for s, p in zip(decode_slots, positions):
                pos_all[s] = p
                last_all[s] = self.toks[s, p]
            self.cache, decode_out = self._decode_jit(
                self.params, self.cache, jnp.asarray(last_all),
                jnp.asarray(pos_all),
            )
            if not self.overlap:
                jax.block_until_ready(decode_out)
        prefill_out = []
        for slot, rid, prompt, max_new in admissions:
            p_len = len(prompt)
            bucket = self._prefill_bucket(p_len)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :p_len] = prompt
            self.toks[slot] = 0
            self.toks[slot, :p_len] = prompt
            self._row_len[slot] = p_len
            self.cache, logits = self._prefill_jit(bucket)(
                self.params, self.cache, jnp.asarray(padded),
                jnp.int32(slot), jnp.int32(p_len),
            )
            prefill_out.append((slot, logits))
        # block and write tokens: decode first (its logits were
        # dispatched first), then the prefills' first tokens
        if decode_out is not None:
            logits_np = np.asarray(decode_out)
            for s, p in zip(decode_slots, positions):
                nxt = int(np.argmax(logits_np[s]))
                self.toks[s, p + 1] = nxt
                self._row_len[s] = p + 2
        t_decode = time.perf_counter()
        for slot, logits in prefill_out:
            row = np.asarray(logits)
            p_len = int(self._row_len[slot])
            nxt = int(np.argmax(row))
            self.toks[slot, p_len] = nxt
            self._row_len[slot] = p_len + 1
        t_end = time.perf_counter()
        return (t_decode - t0) * 1e3, (t_end - t_decode) * 1e3

    def step(self, now_ms=None):
        """One serve step.  Leader: plan + broadcast + execute + book;
        follower: receive + verify + execute + book.  Returns False
        once a stop plan has been processed.

        A resize surfacing mid-step (``WorldResized`` or a
        ``ResizeInterrupted`` collective) is ridden, not fatal: the
        engine rebuilds for the new membership and returns True so the
        caller keeps stepping in the new epoch."""
        if self._stopped:
            return False
        if now_ms is None:
            now_ms = time.monotonic() * 1e3
        try:
            if self.is_leader:
                return self._leader_step(now_ms)
            return self._follower_step()
        except Exception as exc:
            if not _is_resize_error(exc):
                raise
            return self._ride_resize(now_ms)

    def _leader_step(self, now_ms, stop=False):
        self._poll_fabric(now_ms)
        for req in self.ctrl.reconsider_queued(now_ms, self.sched):
            self.stats.observe_shed(req.shed_reason)
        digest = self.sched.state_digest()
        plan = self.sched.plan_step(now_ms)
        retire = None
        if self._retire_queue and not stop:
            # shrink cascade: one victim per plan (the batch is already
            # drained and admissions held, so the plan is empty); the
            # victim executes this step, then exits cleanly, and the
            # elastic layer turns its departure into the next epoch
            retire = self._retire_queue.pop(0)
        vec = plan_mod.encode_plan(
            plan, self.max_batch, self.max_len, digest, stop=stop,
            retire=retire,
        )
        if self.plan_log:
            plan_mod.append_plan_stream(
                self.plan_log, vec, self.max_batch, self.max_len,
                world=self.tp,
            )
        self._bcast(vec)
        admissions = [
            (slot, req.rid, req.prompt, req.max_new)
            for slot, req in plan.admissions
        ]
        t0 = time.perf_counter()
        scope = (step_mod.step_scope(f"serve:{plan.step}")
                 if self.markers else None)
        if scope is not None:
            scope.__enter__()
        try:
            decode_ms, prefill_ms = self._execute(
                admissions, plan.decode_slots, plan.positions
            )
        finally:
            if scope is not None:
                scope.__exit__(None, None, None)
        wall_ms = (time.perf_counter() - t0) * 1e3
        est = self.ctrl.estimator
        if plan.decode_slots:
            est.observe_step(decode_ms)
        if admissions:
            # marginal prefill cost when a decode shared the step (so
            # the prefill model keeps learning under load), full wall
            # otherwise
            p_wall = (prefill_ms if plan.decode_slots
                      else decode_ms + prefill_ms)
            if p_wall > 0:
                est.observe_prefill(
                    p_wall,
                    max(len(p) for _s, _r, p, _m in admissions),
                )
        # completions happened at the END of the executed step, not at
        # the planning instant — stamp them with the post-execution
        # clock or TTFT/latency would exclude the very step that
        # produced the token
        done_ms = now_ms + wall_ms
        for slot, _req in plan.admissions:
            self.sched.prefill_done(slot, done_ms)
        self.sched.step_done(plan, done_ms)
        for req in self.sched.finished:
            # completion and harvest happen in the same step, so the
            # freed slot's host buffer still holds the tokens (a new
            # admission can only land there NEXT plan)
            n = req.prompt_len + req.generated
            row = self.toks[req.last_slot, :n]
            self.finished.append(
                (req.rid, tuple(int(t) for t in row))
            )
            self.stats.observe_completed(req)
        self.sched.finished.clear()
        self.stats.observe_step(
            self.sched.queue_depth(), self.sched.occupancy()
        )
        snap = self.stats.snapshot()
        if stop:
            # keep the final gauges visible (exit-time rank files and
            # post-mortems read them) but marked: a live scrape must
            # be able to tell a stopped engine from a running one
            snap["stopped"] = True
        stats_mod.publish(snap)
        if stop:
            self._stopped = True
            return False
        return True

    def _follower_step(self):
        vec = self._bcast(None)
        decoded = plan_mod.decode_plan(
            vec, self.max_batch, self.max_len,
            expect_digest=self.mirror.state_digest(),
        )
        scope = (step_mod.step_scope(f"serve:{decoded['step']}")
                 if self.markers else None)
        if scope is not None:
            scope.__enter__()
        try:
            admitted, finished = self.mirror.apply(decoded)
            self._execute(
                admitted, decoded["decode_slots"],
                decoded["positions"],
            )
        finally:
            if scope is not None:
                scope.__exit__(None, None, None)
        # retain admitted requests for promotion: if the leader dies,
        # the lowest survivor rebuilds a scheduler from its mirror plus
        # exactly these (prompt included — the plan carried it)
        for _slot, rid, prompt, mn in admitted:
            self._retained[rid] = plan_mod.follower_request(
                rid, prompt, mn
            )
        # same completion order as the leader: prefill-instant
        # completions first (prefill_done runs before step_done
        # there), then the decode completions
        for slot, _rid, _prompt, _mn in admitted:
            done = self.mirror.prefill_done(slot)
            if done is not None:
                s, rid = done
                n = int(self._row_len[s])
                self.finished.append(
                    (rid, tuple(int(t) for t in self.toks[s, :n]))
                )
                self._retained.pop(rid, None)
        for slot, rid in finished:
            n = int(self._row_len[slot])
            self.finished.append(
                (rid, tuple(int(t) for t in self.toks[slot, :n]))
            )
            self._retained.pop(rid, None)
        self.stats.observe_step(0, self.mirror.occupancy())
        snap = self.stats.snapshot()
        if decoded["stop"]:
            snap["stopped"] = True
        stats_mod.publish(snap)
        if decoded["stop"]:
            self._stopped = True
            return False
        if decoded.get("retire") == self.rank:
            # the autoscaler retired this rank: leave the loop cleanly
            # after executing the plan — the launcher records a
            # scaledown, and the elastic layer commits the next epoch
            # when it notices the departure
            self._stopped = True
            return False
        return True

    # ---- elastic epoch survival ------------------------------------------

    def _joiner_bootstrap(self):
        """A ``T4J_REJOIN=1`` expansion rank joins mid-stream: rebuild
        the FollowerMirror by replaying the leader's plan log so the
        first live broadcast's digest check proves agreement BEFORE
        this rank serves a step.  A missing log is fine (the leader
        restarts the stream at every epoch commit, and a fresh epoch
        has no history); a corrupt or geometry-mismatched one raises —
        the joiner must not serve from a state it cannot prove."""
        path = self._plan_log_path
        if not path or not os.path.exists(path):
            return
        meta, vecs = plan_mod.load_plan_stream(path)
        if (int(meta.get("max_batch", -1)) != self.max_batch
                or int(meta.get("p_max", -1)) != self.max_len):
            raise plan_mod.PlanError(
                f"plan log {path}: geometry "
                f"{meta.get('max_batch')}x{meta.get('p_max')} != "
                f"engine {self.max_batch}x{self.max_len}; "
                f"joiner must not serve"
            )
        mirror, retained = plan_mod.rebuild_mirror(
            meta, vecs, source=path
        )
        self.mirror = mirror
        self._retained.update(retained)

    def _ride_resize(self, now_ms):
        """Survive a membership change mid-serve (the tentpole of the
        epoch-survival ladder, docs/failure-semantics.md).

        Every survivor: wait the resize out, swallow the pending
        ``WorldResized`` health signal, re-resolve tuning on the new
        fingerprint (collective), and re-shard the model.  The leader
        additionally reissues every in-slot request — the KV cache and
        slot state died with the old epoch, but re-generation is
        deterministic (greedy argmax), completions are delivered
        exactly once, and ``req.emitted`` marks the reissue point for
        audits — then restarts the plan log so late joiners rebuild
        from the post-reissue state the whole world agrees on.  If the
        old leader is among the dead, the lowest surviving rank
        promotes itself first (:meth:`_promote`).

        Returns True (keep stepping) for survivors, False when this
        rank itself was retired from the membership."""
        from mpi4jax_tpu.native import runtime

        runtime.resize_wait()
        try:
            runtime.check_health()
        except runtime.WorldResized:
            pass  # the very epoch we are riding
        info = runtime.world_info() or {}
        alive = runtime.alive_ranks() or tuple(range(self.tp))
        if self.rank not in alive:
            # retired (or evicted) — nothing left to serve here
            self._stopped = True
            return False
        runtime.refresh_after_resize()
        was_leader = self.is_leader
        self._leader_rank = min(alive)
        self.is_leader = self.rank == self._leader_rank
        if self.is_leader and not was_leader:
            self._promote(now_ms)
        self._rebuild_for_world(alive)
        if self.is_leader:
            lost = self.sched.reissue_inflight(now_ms)
            if lost:
                self.stats.observe_reissued(len(lost))
            if self.plan_log:
                # epoch commit restarts the stream: a joiner replaying
                # it lands on the empty-slot state the reissue left
                plan_mod.save_plan_stream(
                    self.plan_log, [], self.max_batch, self.max_len,
                    world=self.tp,
                )
            if self._scaler is not None:
                self._scaler.resize_committed(len(alive))
                self.stats.autoscale_state = self._scaler.state
            if self._model_ready and not self._retire_queue and (
                self._scaler is None
                or self._scaler.state != autoscale_mod.DRAINING
            ):
                self.sched.hold_admissions(False)
        else:
            self.mirror.reset()
            self._retained.clear()
        self.stats.observe_epoch()
        self._epoch = info.get("epoch")
        return True

    def _promote(self, now_ms):
        """The old leader died; this (lowest surviving) rank takes over
        the control plane.  The mirror knows which slots were live and
        the retained map knows their prompts, so every in-flight
        request is resubmitted to a fresh scheduler (requests only the
        old leader had queued are gone — they were never acknowledged
        to any other rank).  The traffic source must redirect to this
        rank; the engine restores the serving state."""
        sched = SlotScheduler(self.max_batch, self.max_len)
        rows = self.mirror.rows()
        for slot in sorted(rows):
            rid, _pos, _end = rows[slot]
            req = self._retained.pop(rid, None)
            if req is None:
                continue  # pre-join history; prompt unknown
            req.arrival_ms = now_ms
            req.reissues += 1
            sched.submit(req, now_ms)
        self._retained.clear()
        self.sched = sched
        self.ctrl = AdmissionController(
            self.admit_mode, slo_ms=self.slo_ms,
            estimator=SLOEstimator(),
        )
        self.mirror = None
        self.plan_log = self._plan_log_path

    def _rebuild_for_world(self, alive):
        """Re-shard model state for the surviving membership.  The
        engine serves only at TP-divisible world sizes; the
        autoscaler's double/halve step policy keeps the fleet on them,
        and a non-divisible transient (mid shrink-cascade) holds
        admissions and carries empty plans instead of crashing."""
        new_tp = len(alive)
        self.tp = new_tp
        self._rank_index = alive.index(self.rank)
        cfg = self.cfg
        try:
            tfm._check_tp_divisibility(cfg, new_tp)
            ready = True
        except ValueError:
            ready = False
        self._model_ready = ready
        self._prefill_jits = {}
        self._decode_jit = jax.jit(self._decode_fn)
        if ready:
            self.hq_l = cfg.heads // new_tp
            self.hk_l = cfg.kv_heads // new_tp
            self.params = shard_params(
                self._full_params, new_tp, self._rank_index
            )
            self.cache = jnp.zeros(
                (cfg.layers, 2, self.max_batch, self.max_len,
                 self.hk_l, cfg.head_dim),
                self.params.embed.dtype,
            )
        elif self.sched is not None:
            self.sched.hold_admissions(True)
        self.toks[:] = 0
        self._row_len[:] = 0

    # ---- autoscaling (leader policy) -------------------------------------

    def enable_autoscale(self, scaler=None, req_path=None,
                         budget_ms=None, drain_clamp=8):
        """Arm the traffic-driven scale policy (leader only).

        Defaults come from the environment knobs
        (``T4J_SCALE_UP_WINDOWS`` / ``T4J_SCALE_DOWN_OCC`` /
        ``T4J_SCALE_DOWN_WINDOWS`` / ``T4J_SCALE_COOLDOWN_WINDOWS``,
        floor ``T4J_MIN_WORLD``, ceiling = the boot world).
        ``budget_ms`` is the wait the policy tolerates before growing
        (default: half the SLO, or 1000 ms without one);
        ``drain_clamp`` bounds each in-slot continuation during a
        drain (``SlotScheduler.clamp_completions``)."""
        assert self.is_leader, "autoscale policy is leader-side"
        if scaler is None:
            scaler = autoscale_mod.Autoscaler(
                floor=config.min_world(),
                ceiling=self.tp,
                up_windows=config.scale_up_windows(),
                down_occ=config.scale_down_occ(),
                down_windows=config.scale_down_windows(),
                cooldown_windows=config.scale_cooldown_windows(),
            )
        self._scaler = scaler
        self._autoscale_req = (req_path if req_path is not None
                               else config.autoscale_req_path())
        if budget_ms is not None:
            self._budget_ms = float(budget_ms)
        elif self.slo_ms:
            self._budget_ms = 0.5 * self.slo_ms
        else:
            self._budget_ms = 1000.0
        self._drain_clamp = int(drain_clamp)
        self.stats.autoscale_state = scaler.state
        return scaler

    def disable_autoscale(self):
        """Disarm the scale policy (e.g. between interleaved bench
        arms).  Releases any in-progress drain so a static arm is not
        served with held admissions."""
        if self.is_leader and self.sched.admissions_held:
            # resume admissions; already-clamped slots just finish
            # early (DONE, not shed)
            self.sched.hold_admissions(False)
        if self._scaler is not None:
            self._scaler = None
            self.stats.autoscale_state = "off"
        self._autoscale_req = None
        self._retire_queue = []

    def autoscale_window(self, now_ms=None):
        """Feed the policy one decision window (call at a cadence much
        coarser than the step loop).  Grow decisions are posted to the
        launcher's request file; drain decisions hold admissions and
        clamp in-slot horizons; a completed drain arms the retire
        cascade.  Returns the :class:`~.autoscale.AutoscaleDecision`,
        or None when nothing was decided this window."""
        if self._scaler is None or not self.is_leader:
            return None
        if now_ms is None:
            now_ms = time.monotonic() * 1e3
        occ = self.sched.occupancy() / float(self.max_batch)
        if self._scaler.state == autoscale_mod.DRAINING:
            if self.sched.occupancy() == 0:
                dec = self._scaler.drain_complete()
                self._retire_queue = list(dec.victims)
                self.stats.autoscale_state = self._scaler.state
                return dec
            return None
        depth = self.sched.queue_depth()
        est = self.ctrl.estimator
        queued = self.sched.queued()
        if queued:
            head = queued[0]
            pred = est.predict_ms(
                head.prompt_len, head.max_new, depth - 1,
                self.sched.occupancy(), self.max_batch,
                residual_ms=est.residual_service_ms(
                    self.sched.active_requests()
                ),
            )
        else:
            pred = 0.0
        dec = self._scaler.observe(
            predicted_wait_ms=pred, budget_ms=self._budget_ms,
            occupancy=occ, world=self._alive_world(),
        )
        if dec.action == "grow":
            if self._autoscale_req:
                autoscale_mod.post_request(
                    self._autoscale_req, dec.target_world,
                    self._epoch or 0, dec.reason,
                )
        elif dec.action == "drain":
            self.sched.hold_admissions(True)
            self.sched.clamp_completions(self._drain_clamp)
        self.stats.autoscale_state = self._scaler.state
        return dec

    def _alive_world(self):
        try:
            from mpi4jax_tpu.native import runtime

            n = runtime.effective_world_size()
            return int(n) if n else self.tp
        except Exception:
            return self.tp

    # ---- lifecycle -------------------------------------------------------

    def reconfigure(self, admit, slo_ms=0.0, rate_limit=0.0, burst=8,
                    stats=None, measure_slo_ms=None):
        """Swap the leader's admission arm between serving windows
        (benchmarks/serving.py interleaves admission-on and -off arms
        in ONE job — followers only execute broadcast plans, so the
        arm switch is purely leader-side).  The learned service-time
        estimator carries over; ``stats`` lets the caller keep one
        accumulating :class:`ServingStats` per arm.
        ``measure_slo_ms`` sets the REPORTING SLO for an off arm
        (measured against, never enforced — the uncontrolled baseline
        still records how badly it missed)."""
        assert self.is_leader, "reconfigure is leader-side"
        if not self.sched.idle():
            raise RuntimeError(
                "reconfigure with requests in flight; drain the "
                "window first"
            )
        est = self.ctrl.estimator
        enforce_slo = float(slo_ms) if admit == "on" else 0.0
        self.admit_mode = admit
        self.slo_ms = enforce_slo
        self.ctrl = AdmissionController(
            admit, slo_ms=enforce_slo, estimator=est,
            bucket=TokenBucket(rate_limit, burst) if rate_limit
            else None,
        )
        report_slo = (measure_slo_ms if measure_slo_ms is not None
                      else enforce_slo)
        self.stats = stats if stats is not None else ServingStats(
            slo_ms=report_slo, max_batch=self.max_batch,
            admit_mode=admit,
        )
        return self

    def drain(self, now_ms_fn=None, max_steps=100000, stop=True):
        """Leader: keep stepping (no new arrivals) until every queued
        and in-flight request finished, then (``stop=True``) broadcast
        the stop plan.  ``stop=False`` leaves followers in the loop —
        the between-windows drain of an interleaved benchmark.
        Verifies the request accounting — a leaked slot fails loudly
        (tests/proc/test_serving_proc.py pins it)."""
        assert self.is_leader
        steps = 0
        while not self.sched.idle():
            now = (now_ms_fn() if now_ms_fn
                   else time.monotonic() * 1e3)
            self._leader_step(now)
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"drain did not converge in {max_steps} steps "
                    f"(queue={self.sched.queue_depth()}, "
                    f"occupancy={self.sched.occupancy()})"
                )
        if stop:
            now = now_ms_fn() if now_ms_fn else time.monotonic() * 1e3
            self._leader_step(now, stop=True)
        self.sched.check_accounting()

    def stop(self, now_ms=None):
        """Leader: broadcast the stop plan (the world must be idle —
        use :meth:`drain` when requests may be in flight)."""
        assert self.is_leader
        if now_ms is None:
            now_ms = time.monotonic() * 1e3
        self._leader_step(now_ms, stop=True)

    def run_follower(self):
        """Follower loop: execute broadcast plans until the stop plan
        (or until a resize promotes this rank — then control returns
        to the caller, which must drive the leader side).  Returns the
        completions seen on this rank."""
        assert not self.is_leader
        while not self._stopped:
            if self.is_leader:
                return self.finished  # promoted mid-loop
            if not self.step():
                break
        return self.finished
