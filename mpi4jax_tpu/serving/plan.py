"""Cross-rank step-plan codec (docs/serving.md "the control plane").

Scheduling is rank 0's job — admission reads live telemetry that other
ranks legitimately see differently, so the plan CANNOT be recomputed
per rank (divergent plans would issue mismatched collectives and
deadlock; the same uniformity argument as tuning's rank-0 knob
broadcast).  Rank 0 encodes each step's decisions into one fixed-size
``int64`` vector, broadcast over the existing ``host_bcast`` control
plane; followers decode and execute.

Layout (``plan_words(max_batch, p_max)`` words total)::

    [0] MAGIC            [1] step index        [2] flags
    [3] n_admissions     [4] n_decode          [5] scheduler digest
    [6 .. 6+5*max_batch) admission entries (slot, rid, p_len,
                         max_new, deadline_ms or -1), -1-padded
    [.. +max_batch)      decode slot indices, -1-padded
    [.. +max_batch)      decode positions,    -1-padded
    [.. +max_batch*p_max) admitted prompts' token ids, row per
                         admission slot order, -1-padded

``flags`` bit 0 is *stop* (followers leave the serve loop after this
step); bits 1+ carry ``retire_rank + 1`` — the autoscaler's
drain-then-shrink handshake: a plan with ``retire == r`` tells rank
``r`` (and only rank ``r``) to exit cleanly after executing the step,
which the launcher's elastic loop observes as a scale-down.  Plans
recorded before this field existed have flags 0/1 and decode with
``retire is None`` — old streams stay replayable.

The ``scheduler digest`` is the leader's
:meth:`SlotScheduler.state_digest` BEFORE applying the plan: a
follower whose mirrored state drifted raises :class:`PlanError`
naming the step instead of decoding garbage with a straight face
(the analysis subsystem's fingerprint philosophy).
"""

import json

from .request import Request

__all__ = ["MAGIC", "PlanError", "append_plan_stream", "decode_plan",
           "encode_plan", "follower_request", "load_plan_stream",
           "plan_stream_schedule", "plan_words", "rebuild_mirror",
           "replay_stream", "save_plan_stream"]

MAGIC = 0x74346A53  # "t4jS"

_HEADER = 6


class PlanError(RuntimeError):
    """A step plan failed validation (bad magic, truncated vector,
    or a leader/follower scheduler-state divergence)."""


def plan_words(max_batch, p_max):
    """Vector length in int64 words for a ``max_batch``-slot engine
    with prompts bounded by ``p_max`` tokens."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if p_max < 1:
        raise ValueError(f"p_max must be >= 1, got {p_max}")
    return _HEADER + 5 * max_batch + 2 * max_batch + max_batch * p_max


def encode_plan(plan, max_batch, p_max, digest, stop=False,
                retire=None):
    """Scheduler :class:`~.scheduler.StepPlan` -> list of ints.

    ``digest`` is the leader scheduler's pre-plan state digest.  A
    ``stop=True`` plan tells followers to leave the serve loop after
    this step (its admissions/decode lists are usually empty).
    ``retire`` names one rank that should exit cleanly after this step
    — the autoscaler's drained-victim handoff."""
    n_admit = len(plan.admissions)
    n_decode = len(plan.decode_slots)
    if n_admit > max_batch or n_decode > max_batch:
        raise PlanError(
            f"plan exceeds max_batch={max_batch}: "
            f"{n_admit} admissions, {n_decode} decodes"
        )
    flags = 1 if stop else 0
    if retire is not None:
        if int(retire) < 0:
            raise PlanError(f"retire rank must be >= 0, got {retire}")
        flags |= (int(retire) + 1) << 1
    vec = [MAGIC, int(plan.step), flags, n_admit, n_decode,
           int(digest)]
    for slot, req in plan.admissions:
        if req.prompt_len > p_max:
            raise PlanError(
                f"request {req.rid}: prompt length {req.prompt_len} "
                f"exceeds the plan payload bound p_max={p_max}"
            )
        dl = -1 if req.deadline_ms is None else int(req.deadline_ms)
        vec += [int(slot), int(req.rid), req.prompt_len,
                int(req.max_new), dl]
    vec += [-1] * (5 * (max_batch - n_admit))
    vec += [int(s) for s in plan.decode_slots]
    vec += [-1] * (max_batch - n_decode)
    vec += [int(p) for p in plan.positions]
    vec += [-1] * (max_batch - n_decode)
    for _slot, req in plan.admissions:
        vec += list(req.prompt) + [-1] * (p_max - req.prompt_len)
    vec += [-1] * (p_max * (max_batch - n_admit))
    assert len(vec) == plan_words(max_batch, p_max)
    return vec


def decode_plan(vec, max_batch, p_max, expect_digest=None):
    """Int vector -> dict with keys ``step``, ``stop``, ``retire``
    (rank told to exit after this step, or ``None``), ``admissions``
    (list of ``(slot, rid, p_len, max_new, deadline_ms-or-None)``),
    ``prompts`` (token tuple per admission), ``decode_slots``,
    ``positions``.

    ``expect_digest`` is the follower's own mirrored-scheduler digest;
    a mismatch raises :class:`PlanError` naming the step (state drift
    must fail attributably, not decode garbage)."""
    vec = [int(v) for v in vec]
    if len(vec) != plan_words(max_batch, p_max):
        raise PlanError(
            f"plan vector has {len(vec)} words, want "
            f"{plan_words(max_batch, p_max)} for "
            f"max_batch={max_batch}, p_max={p_max}"
        )
    if vec[0] != MAGIC:
        raise PlanError(f"bad plan magic {vec[0]:#x} (want {MAGIC:#x})")
    step, flags, n_admit, n_decode, digest = vec[1:_HEADER]
    if not 0 <= n_admit <= max_batch or not 0 <= n_decode <= max_batch:
        raise PlanError(
            f"plan step {step}: counts out of range "
            f"(admit={n_admit}, decode={n_decode}, "
            f"max_batch={max_batch})"
        )
    if expect_digest is not None and digest != int(expect_digest):
        raise PlanError(
            f"scheduler state diverged at step {step}: leader digest "
            f"{digest:#x} != local {int(expect_digest):#x} — a "
            "follower missed or misapplied an earlier plan"
        )
    admissions = []
    base = _HEADER
    for i in range(n_admit):
        slot, rid, p_len, max_new, dl = vec[base + 5 * i:base + 5 * i + 5]
        admissions.append(
            (slot, rid, p_len, max_new, None if dl < 0 else float(dl))
        )
    base += 5 * max_batch
    decode_slots = vec[base:base + n_decode]
    base += max_batch
    positions = vec[base:base + n_decode]
    base += max_batch
    prompts = []
    for i, (_s, _r, p_len, _m, _d) in enumerate(admissions):
        row = vec[base + i * p_max:base + i * p_max + p_len]
        if len(row) != p_len or any(t < 0 for t in row):
            raise PlanError(
                f"plan step {step}: truncated prompt payload for "
                f"admission {i}"
            )
        prompts.append(tuple(row))
    retire = (flags >> 1) - 1
    return {
        "step": step,
        "stop": bool(flags & 1),
        "retire": None if retire < 0 else retire,
        "admissions": admissions,
        "prompts": prompts,
        "decode_slots": decode_slots,
        "positions": positions,
        "digest": digest,
    }


def follower_request(rid, prompt_tokens, max_new, arrival_ms=0.0,
                     deadline_ms=None):
    """Rebuild a :class:`Request` on a follower rank from plan fields +
    the broadcast prompt payload (arrival time is leader-side state the
    follower doesn't need; it defaults inert)."""
    return Request(rid, prompt_tokens, max_new, arrival_ms,
                   deadline_ms)


# ---------------------------------------------------------- plan streams
#
# A recorded plan stream makes follower-drift bugs reproducible offline:
# the engine's leader appends every broadcast vector to a jsonl file
# (``ServingEngine(plan_log=...)`` / ``T4J_PLAN_LOG``), and
# ``t4j-verify --plan-stream`` replays it through a fresh
# :class:`~.scheduler.FollowerMirror` — exactly the code path a live
# follower runs — so a digest mismatch reproduces on a laptop with no
# cluster, no model, and no jax.

_STREAM_FORMAT = "t4j-plan-stream-v1"


def save_plan_stream(path, vecs, max_batch, p_max, world=None):
    """Write a full plan stream: one header line + one line per step."""
    with open(path, "w") as fh:
        fh.write(json.dumps({
            "format": _STREAM_FORMAT, "max_batch": int(max_batch),
            "p_max": int(p_max), "world": world,
        }) + "\n")
        for vec in vecs:
            fh.write(json.dumps({"vec": [int(v) for v in vec]}) + "\n")


def append_plan_stream(path, vec, max_batch, p_max, world=None):
    """Append one step's vector, writing the header first when the file
    is new/empty (the engine calls this once per ``_leader_step``)."""
    import os

    need_header = not os.path.exists(path) or os.path.getsize(path) == 0
    with open(path, "a") as fh:
        if need_header:
            fh.write(json.dumps({
                "format": _STREAM_FORMAT, "max_batch": int(max_batch),
                "p_max": int(p_max), "world": world,
            }) + "\n")
        fh.write(json.dumps({"vec": [int(v) for v in vec]}) + "\n")


def load_plan_stream(path):
    """Read a stream back as ``(meta, [vec, ...])``; raises
    :class:`PlanError` on a malformed file."""
    meta = None
    vecs = []
    with open(path) as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError as exc:
                raise PlanError(f"{path}:{ln}: not JSON: {exc}")
            if meta is None:
                if doc.get("format") != _STREAM_FORMAT:
                    raise PlanError(
                        f"{path}: not a {_STREAM_FORMAT} stream "
                        f"(format={doc.get('format')!r})"
                    )
                meta = doc
            else:
                vec = doc.get("vec")
                if not isinstance(vec, list):
                    raise PlanError(f"{path}:{ln}: missing 'vec' list")
                vecs.append(vec)
    if meta is None:
        raise PlanError(f"{path}: empty plan stream")
    return meta, vecs


def replay_stream(meta, vecs, source="<plan-stream>"):
    """Replay a recorded stream through a fresh follower mirror.

    Runs the literal follower code path (``decode_plan`` with the
    mirror's own digest as ``expect_digest``, then
    :meth:`FollowerMirror.apply`), so any drift a live follower would
    hit reproduces here.  Returns a list of
    :class:`~mpi4jax_tpu.analysis.contracts.Finding` — drift maps to
    rule T4J007 (cross-rank schedule divergence: the leader's plan and
    the follower's mirrored state ARE the two diverging schedules).
    """
    from mpi4jax_tpu.analysis.contracts import Finding

    from .scheduler import FollowerMirror, SchedulerError

    max_batch = int(meta["max_batch"])
    p_max = int(meta["p_max"])
    mirror = FollowerMirror(max_batch, p_max)
    findings = []
    for i, vec in enumerate(vecs):
        anchor = f"{source}:step {i}"
        try:
            decoded = decode_plan(
                vec, max_batch, p_max,
                expect_digest=mirror.state_digest(),
            )
        except PlanError as exc:
            findings.append(Finding(
                rule="T4J007",
                message=(
                    f"plan-stream replay: follower mirror rejects the "
                    f"leader's plan at stream entry {i}: {exc}"
                ),
                src_info=anchor,
            ))
            break
        try:
            admitted, _finished = mirror.apply(decoded)
            for slot, _rid, _prompt, _max_new in admitted:
                mirror.prefill_done(slot)
        except SchedulerError as exc:
            findings.append(Finding(
                rule="T4J007",
                message=(
                    f"plan-stream replay: mirrored scheduler state "
                    f"diverged applying stream entry {i} "
                    f"(plan step {decoded['step']}): {exc}"
                ),
                src_info=anchor,
            ))
            break
        if decoded["stop"]:
            break
    return findings


def rebuild_mirror(meta, vecs, source="<plan-stream>",
                   expect_digest=None):
    """Rebuild a live :class:`~.scheduler.FollowerMirror` from a
    recorded plan stream — the late joiner's bootstrap (docs/
    failure-semantics.md): an expansion rank admitted into a serving
    epoch replays the leader's plan log through the literal follower
    code path and starts serving only if every step's digest agreed.

    Unlike :func:`replay_stream` (offline triage, returns Findings)
    this RAISES :class:`PlanError` on any drift — a joiner with a
    divergent mirror must not serve a single step.  Returns
    ``(mirror, requests)`` where ``requests`` maps rid ->
    :class:`Request` for every request still holding a slot (what the
    joiner needs to decode their remaining tokens, and what a promoted
    leader reissues).  ``expect_digest`` optionally pins the final
    mirror digest to the leader's current one (fetched out-of-band) —
    the digest-agreement gate before the first served step."""
    from .scheduler import FollowerMirror, SchedulerError

    max_batch = int(meta["max_batch"])
    p_max = int(meta["p_max"])
    mirror = FollowerMirror(max_batch, p_max)
    requests = {}
    for i, vec in enumerate(vecs):
        try:
            decoded = decode_plan(
                vec, max_batch, p_max,
                expect_digest=mirror.state_digest(),
            )
            admitted, finished = mirror.apply(decoded)
        except (PlanError, SchedulerError) as exc:
            raise PlanError(
                f"{source}: mirror rebuild diverged at stream entry "
                f"{i}: {exc}"
            )
        for slot, rid, prompt, max_new in admitted:
            dl = next(
                d for s, r, _p, _m, d in decoded["admissions"]
                if r == rid
            )
            requests[rid] = follower_request(rid, prompt, max_new,
                                             deadline_ms=dl)
            done = mirror.prefill_done(slot)
            if done is not None:
                finished = list(finished) + [done]
        for _slot, rid in finished:
            requests.pop(rid, None)
        if decoded["stop"]:
            break
    alive = {row[0] for row in mirror.rows().values()}
    requests = {rid: req for rid, req in requests.items()
                if rid in alive}
    if expect_digest is not None:
        got = mirror.state_digest()
        if got != int(expect_digest):
            raise PlanError(
                f"{source}: rebuilt mirror digest {got:#x} != leader's "
                f"{int(expect_digest):#x} — plan log is stale or "
                "truncated; joiner must not serve"
            )
    return mirror, requests


def plan_stream_schedule(meta, vecs, source="<plan-stream>"):
    """Synthesize per-rank simulator schedules from a recorded stream.

    Every step plan is one ``host_bcast`` of the fixed-size vector from
    rank 0 — a root collective on the serving control comm, identical
    on every rank.  Feeding these through the analysis simulator
    (``t4j-verify --plan-stream``) checks the *transport* shape of the
    control plane — a world-size disagreement or a truncated stream on
    one rank shows up as a collective-slot mismatch — complementing
    :func:`replay_stream`'s state-level drift check.  Returns
    ``[rank0_events, rank1_events, ...]`` as plain dicts.
    """
    world = int(meta.get("world") or 2)
    max_batch = int(meta["max_batch"])
    p_max = int(meta["p_max"])
    words = plan_words(max_batch, p_max)
    schedules = []
    for rank in range(world):
        events = []
        for i, _vec in enumerate(vecs):
            events.append({
                "kind": "bcast",
                "comm_key": "serving-ctrl",
                "comm_size": world,
                "comm_ranks": list(range(world)),
                "dtype": "int64",
                "shape": [words],
                "reduce_op": "",
                "root": 0,
                "rank": rank,
                "tag": None,
                "src_info": f"{source}:step {i}",
            })
        schedules.append(events)
    return schedules
