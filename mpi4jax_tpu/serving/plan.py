"""Cross-rank step-plan codec (docs/serving.md "the control plane").

Scheduling is rank 0's job — admission reads live telemetry that other
ranks legitimately see differently, so the plan CANNOT be recomputed
per rank (divergent plans would issue mismatched collectives and
deadlock; the same uniformity argument as tuning's rank-0 knob
broadcast).  Rank 0 encodes each step's decisions into one fixed-size
``int64`` vector, broadcast over the existing ``host_bcast`` control
plane; followers decode and execute.

Layout (``plan_words(max_batch, p_max)`` words total)::

    [0] MAGIC            [1] step index        [2] flags (bit0 = stop)
    [3] n_admissions     [4] n_decode          [5] scheduler digest
    [6 .. 6+5*max_batch) admission entries (slot, rid, p_len,
                         max_new, deadline_ms or -1), -1-padded
    [.. +max_batch)      decode slot indices, -1-padded
    [.. +max_batch)      decode positions,    -1-padded
    [.. +max_batch*p_max) admitted prompts' token ids, row per
                         admission slot order, -1-padded

The ``scheduler digest`` is the leader's
:meth:`SlotScheduler.state_digest` BEFORE applying the plan: a
follower whose mirrored state drifted raises :class:`PlanError`
naming the step instead of decoding garbage with a straight face
(the analysis subsystem's fingerprint philosophy).
"""

from .request import Request

__all__ = ["MAGIC", "PlanError", "decode_plan", "encode_plan",
           "follower_request", "plan_words"]

MAGIC = 0x74346A53  # "t4jS"

_HEADER = 6


class PlanError(RuntimeError):
    """A step plan failed validation (bad magic, truncated vector,
    or a leader/follower scheduler-state divergence)."""


def plan_words(max_batch, p_max):
    """Vector length in int64 words for a ``max_batch``-slot engine
    with prompts bounded by ``p_max`` tokens."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if p_max < 1:
        raise ValueError(f"p_max must be >= 1, got {p_max}")
    return _HEADER + 5 * max_batch + 2 * max_batch + max_batch * p_max


def encode_plan(plan, max_batch, p_max, digest, stop=False):
    """Scheduler :class:`~.scheduler.StepPlan` -> list of ints.

    ``digest`` is the leader scheduler's pre-plan state digest.  A
    ``stop=True`` plan tells followers to leave the serve loop after
    this step (its admissions/decode lists are usually empty)."""
    n_admit = len(plan.admissions)
    n_decode = len(plan.decode_slots)
    if n_admit > max_batch or n_decode > max_batch:
        raise PlanError(
            f"plan exceeds max_batch={max_batch}: "
            f"{n_admit} admissions, {n_decode} decodes"
        )
    vec = [MAGIC, int(plan.step), 1 if stop else 0, n_admit, n_decode,
           int(digest)]
    for slot, req in plan.admissions:
        if req.prompt_len > p_max:
            raise PlanError(
                f"request {req.rid}: prompt length {req.prompt_len} "
                f"exceeds the plan payload bound p_max={p_max}"
            )
        dl = -1 if req.deadline_ms is None else int(req.deadline_ms)
        vec += [int(slot), int(req.rid), req.prompt_len,
                int(req.max_new), dl]
    vec += [-1] * (5 * (max_batch - n_admit))
    vec += [int(s) for s in plan.decode_slots]
    vec += [-1] * (max_batch - n_decode)
    vec += [int(p) for p in plan.positions]
    vec += [-1] * (max_batch - n_decode)
    for _slot, req in plan.admissions:
        vec += list(req.prompt) + [-1] * (p_max - req.prompt_len)
    vec += [-1] * (p_max * (max_batch - n_admit))
    assert len(vec) == plan_words(max_batch, p_max)
    return vec


def decode_plan(vec, max_batch, p_max, expect_digest=None):
    """Int vector -> dict with keys ``step``, ``stop``,
    ``admissions`` (list of ``(slot, rid, p_len, max_new,
    deadline_ms-or-None)``), ``prompts`` (token tuple per admission),
    ``decode_slots``, ``positions``.

    ``expect_digest`` is the follower's own mirrored-scheduler digest;
    a mismatch raises :class:`PlanError` naming the step (state drift
    must fail attributably, not decode garbage)."""
    vec = [int(v) for v in vec]
    if len(vec) != plan_words(max_batch, p_max):
        raise PlanError(
            f"plan vector has {len(vec)} words, want "
            f"{plan_words(max_batch, p_max)} for "
            f"max_batch={max_batch}, p_max={p_max}"
        )
    if vec[0] != MAGIC:
        raise PlanError(f"bad plan magic {vec[0]:#x} (want {MAGIC:#x})")
    step, flags, n_admit, n_decode, digest = vec[1:_HEADER]
    if not 0 <= n_admit <= max_batch or not 0 <= n_decode <= max_batch:
        raise PlanError(
            f"plan step {step}: counts out of range "
            f"(admit={n_admit}, decode={n_decode}, "
            f"max_batch={max_batch})"
        )
    if expect_digest is not None and digest != int(expect_digest):
        raise PlanError(
            f"scheduler state diverged at step {step}: leader digest "
            f"{digest:#x} != local {int(expect_digest):#x} — a "
            "follower missed or misapplied an earlier plan"
        )
    admissions = []
    base = _HEADER
    for i in range(n_admit):
        slot, rid, p_len, max_new, dl = vec[base + 5 * i:base + 5 * i + 5]
        admissions.append(
            (slot, rid, p_len, max_new, None if dl < 0 else float(dl))
        )
    base += 5 * max_batch
    decode_slots = vec[base:base + n_decode]
    base += max_batch
    positions = vec[base:base + n_decode]
    base += max_batch
    prompts = []
    for i, (_s, _r, p_len, _m, _d) in enumerate(admissions):
        row = vec[base + i * p_max:base + i * p_max + p_len]
        if len(row) != p_len or any(t < 0 for t in row):
            raise PlanError(
                f"plan step {step}: truncated prompt payload for "
                f"admission {i}"
            )
        prompts.append(tuple(row))
    return {
        "step": step,
        "stop": bool(flags & 1),
        "admissions": admissions,
        "prompts": prompts,
        "decode_slots": decode_slots,
        "positions": positions,
        "digest": digest,
    }


def follower_request(rid, prompt_tokens, max_new, arrival_ms=0.0,
                     deadline_ms=None):
    """Rebuild a :class:`Request` on a follower rank from plan fields +
    the broadcast prompt payload (arrival time is leader-side state the
    follower doesn't need; it defaults inert)."""
    return Request(rid, prompt_tokens, max_new, arrival_ms,
                   deadline_ms)
