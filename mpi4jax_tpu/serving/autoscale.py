"""Traffic-driven elastic autoscaling for the serving engine.

Pure-core policy (docs/serving.md "Autoscaling"): the leader feeds one
observation per decision window — the SLOEstimator's predicted wait for
a hypothetical head-of-queue request, the current batch occupancy, and
the live world size — and the :class:`Autoscaler` answers with an
action.  Everything here is time- and world-injected so the state
machine is unit-testable without jax, a bridge, or a clock.

The machine:

::

    IDLE --(predicted wait > budget for up_windows)--> PENDING_GROW
    IDLE --(occupancy < down_occ for down_windows)---> DRAINING
    DRAINING --(batch drained)-----------------------> PENDING_SHRINK
    PENDING_GROW --(epoch commit)--------------------> IDLE (cooldown)
    PENDING_SHRINK --(epoch commits, world==target)--> IDLE (cooldown)

Hysteresis is structural: scale-up and scale-down each require their
own run of *consecutive* qualifying windows (a single good window
resets the streak), a post-resize ``cooldown_windows`` refractory
period suppresses flapping, and the world is clamped to
``[floor, ceiling]`` (floor reuses ``T4J_MIN_WORLD``; the ceiling is
the boot-time rank budget — the launcher cannot mint new hosts).

Scale steps are **doubling/halving**, not +-1: the serving engine is
tensor-parallel, and a model's head counts divide evenly only at a
sparse set of world sizes (8 heads shard over 1/2/4/8 ranks, never
7).  A grow jumps to ``min(ceiling, 2 * world)`` — load is already
hurting, add the capacity in one epoch instead of five — and a shrink
targets ``max(floor, world // 2)``, retiring the top half one rank per
step-plan (the in-band ``retire`` flag) so the launcher observes an
orderly cascade rather than a mass exit.  Scale-down is never abrupt:
the policy first *drains* by holding admissions and clamping in-slot
completion horizons (``SlotScheduler.clamp_completions``), and only
once the batch is empty does it start retiring victims.  Grow
requests travel over a file channel (:func:`post_request` /
:func:`read_request`) that ``launch.py --autoscale`` polls: the
launcher owns process lifecycles, the engine owns policy, and the
kept-open PR-10 coordinator port admits the ``T4J_REJOIN=1`` expansion
ranks into the next epoch.
"""

import json
import os
import tempfile

__all__ = [
    "Autoscaler",
    "AutoscaleDecision",
    "IDLE",
    "PENDING_GROW",
    "DRAINING",
    "PENDING_SHRINK",
    "post_request",
    "read_request",
    "clear_request",
]

IDLE = "idle"
PENDING_GROW = "pending-grow"
DRAINING = "draining"
PENDING_SHRINK = "pending-shrink"

#: request-file format tag (versioned like every other t4j artifact).
_REQ_FORMAT = "t4j-autoscale-req-v1"


class AutoscaleDecision:
    """One window's verdict.  ``action`` is ``"none"``, ``"grow"``,
    ``"drain"`` or ``"shrink"``; ``target_world`` is the world size the
    policy wants next (unchanged for ``"none"``/``"drain"``),
    ``victims`` the world ranks a shrink retires (empty otherwise),
    and ``reason`` a short human-readable trigger description carried
    into telemetry/membership history."""

    __slots__ = ("action", "target_world", "victims", "reason")

    def __init__(self, action, target_world, victims=(), reason=""):
        self.action = action
        self.target_world = int(target_world)
        self.victims = tuple(victims)
        self.reason = reason

    def __repr__(self):  # pragma: no cover - debugging nicety
        return (
            f"AutoscaleDecision({self.action!r}, world={self.target_world},"
            f" victims={self.victims}, reason={self.reason!r})"
        )


class Autoscaler:
    """Hysteresis-guarded scale policy.

    Parameters
    ----------
    floor, ceiling:
        Inclusive world-size bounds.  ``floor`` reuses the PR-10
        ``T4J_MIN_WORLD`` contract; ``ceiling`` is the launch-time rank
        budget.
    up_windows:
        Consecutive windows of predicted-wait > budget before a grow is
        requested (``T4J_SCALE_UP_WINDOWS``).
    down_occ:
        Occupancy threshold below which a window counts toward
        scale-down (``T4J_SCALE_DOWN_OCC``).
    down_windows:
        Consecutive low-occupancy windows before a drain starts
        (``T4J_SCALE_DOWN_WINDOWS``).
    cooldown_windows:
        Refractory windows after any epoch commit during which neither
        streak accumulates (``T4J_SCALE_COOLDOWN_WINDOWS``) — the flap
        suppressor.
    """

    def __init__(
        self,
        *,
        floor,
        ceiling,
        up_windows,
        down_occ,
        down_windows,
        cooldown_windows=4,
    ):
        floor = int(floor)
        ceiling = int(ceiling)
        if floor < 1:
            raise ValueError(f"floor must be >= 1, got {floor}")
        if ceiling < floor:
            raise ValueError(
                f"ceiling must be >= floor, got ceiling={ceiling} floor={floor}"
            )
        if int(up_windows) < 1 or int(down_windows) < 1:
            raise ValueError("up_windows and down_windows must be >= 1")
        if not (0.0 <= float(down_occ) < 1.0):
            raise ValueError(
                f"down_occ must be in [0, 1), got {down_occ}"
            )
        if int(cooldown_windows) < 0:
            raise ValueError(
                f"cooldown_windows must be >= 0, got {cooldown_windows}"
            )
        self.floor = floor
        self.ceiling = ceiling
        self.up_windows = int(up_windows)
        self.down_occ = float(down_occ)
        self.down_windows = int(down_windows)
        self.cooldown_windows = int(cooldown_windows)
        self.state = IDLE
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0
        self._victims = ()
        self._target = None
        # decision history for telemetry: (window_idx, action, reason)
        self.history = []
        self._window = 0

    # -- observation -------------------------------------------------

    def observe(self, *, predicted_wait_ms, budget_ms, occupancy, world):
        """Feed one decision window; returns an :class:`AutoscaleDecision`.

        ``predicted_wait_ms`` is the estimator's queue-wait forecast
        for a head-of-queue arrival, ``budget_ms`` the SLO share spent
        waiting we are willing to tolerate, ``occupancy`` the mean slot
        occupancy over the window in ``[0, 1]``, ``world`` the current
        alive world size.
        """
        self._window += 1
        world = int(world)
        if self.state in (PENDING_GROW, PENDING_SHRINK):
            # A resize is in flight; hold position until the caller
            # reports the epoch commit (or abandonment).
            return self._decide("none", world, reason="resize-pending")
        if self.state == DRAINING:
            # Streaks freeze during a drain; the only way forward is
            # drain_complete() or abandon_drain().
            return self._decide("none", world, reason="draining")
        if self._cooldown > 0:
            self._cooldown -= 1
            self._up_streak = 0
            self._down_streak = 0
            return self._decide("none", world, reason="cooldown")

        over = float(predicted_wait_ms) > float(budget_ms)
        under = float(occupancy) < self.down_occ
        self._up_streak = self._up_streak + 1 if over else 0
        self._down_streak = self._down_streak + 1 if under else 0

        if self._up_streak >= self.up_windows and world < self.ceiling:
            self.state = PENDING_GROW
            # Doubling keeps the world on TP-divisible sizes (see the
            # module docstring); load already breached the budget, so
            # add the capacity in one epoch rather than several.
            self._target = min(world * 2, self.ceiling)
            self._up_streak = 0
            self._down_streak = 0
            return self._decide(
                "grow",
                self._target,
                reason=(
                    f"predicted wait {predicted_wait_ms:.0f}ms > budget"
                    f" {budget_ms:.0f}ms for {self.up_windows} windows"
                ),
            )
        if self._down_streak >= self.down_windows and world > self.floor:
            self.state = DRAINING
            self._target = max(world // 2, self.floor)
            # The highest alive ranks are the victims: rank 0 (the
            # leader and coordinator-port owner) must never be retired,
            # and the launcher reuses the freed top slots on a grow.
            self._victims = tuple(range(world - 1, self._target - 1, -1))
            self._up_streak = 0
            self._down_streak = 0
            return self._decide(
                "drain",
                self._target,
                victims=self._victims,
                reason=(
                    f"occupancy {occupancy:.2f} < {self.down_occ:.2f}"
                    f" for {self.down_windows} windows"
                ),
            )
        return self._decide("none", world)

    # -- transitions reported by the engine --------------------------

    def drain_complete(self):
        """The batch is empty; start retiring the victims now."""
        if self.state != DRAINING:
            raise RuntimeError(
                f"drain_complete in state {self.state!r} (expected draining)"
            )
        self.state = PENDING_SHRINK
        return AutoscaleDecision(
            "shrink",
            self._target,
            victims=self._victims,
            reason="drain complete",
        )

    def abandon_drain(self, reason="load returned"):
        """Cancel an in-progress drain (e.g. traffic came back)."""
        if self.state != DRAINING:
            return
        self.state = IDLE
        self._victims = ()
        self._target = None
        self._cooldown = self.cooldown_windows
        self.history.append((self._window, "abandon-drain", reason))

    def resize_committed(self, new_world):
        """An epoch committed (grow or shrink, ours or not).

        A shrink cascade retires one rank per step-plan, so a single
        scale-down decision produces several epochs; the machine stays
        in PENDING_SHRINK until the world reaches the target, then
        resets to IDLE and arms the cooldown so back-to-back resizes
        can't flap."""
        new_world = int(new_world)
        self.history.append((self._window, "commit", f"world={new_world}"))
        if self.state == PENDING_SHRINK and self._target is not None:
            self._victims = tuple(v for v in self._victims if v < new_world)
            if new_world > self._target:
                return  # mid-cascade; more victims still to retire
        self.state = IDLE
        self._victims = ()
        self._target = None
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = self.cooldown_windows

    @property
    def victims(self):
        return self._victims

    def _decide(self, action, target, victims=(), reason=""):
        if action != "none":
            self.history.append((self._window, action, reason))
        return AutoscaleDecision(action, target, victims=victims, reason=reason)


# -- grow-request file channel ---------------------------------------
#
# The engine cannot fork processes; launch.py can.  A grow request is a
# single JSON object written atomically (tempfile + rename) to the path
# in T4J_AUTOSCALE_REQ.  The launcher polls it from the elastic loop,
# spawns the T4J_REJOIN=1 expansion rank, and clears the file.  Stale
# requests (older epoch than the launcher has seen) are dropped.


def post_request(path, want_world, epoch, reason=""):
    """Atomically publish a grow request for the launcher to act on."""
    req = {
        "format": _REQ_FORMAT,
        "want_world": int(want_world),
        "epoch": int(epoch),
        "reason": str(reason),
    }
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".t4j-scale-", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(req, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return req


def read_request(path):
    """Read and validate a pending grow request; None if absent/bad.

    A malformed file is treated as no-request (and left for
    :func:`clear_request`) — the launcher must never crash because a
    half-written or foreign file appeared at the path.
    """
    try:
        with open(path, "r") as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(obj, dict) or obj.get("format") != _REQ_FORMAT:
        return None
    try:
        want = int(obj["want_world"])
        epoch = int(obj["epoch"])
    except (KeyError, TypeError, ValueError):
        return None
    return {
        "want_world": want,
        "epoch": epoch,
        "reason": str(obj.get("reason", "")),
    }


def clear_request(path):
    """Remove a consumed (or rejected) request file."""
    try:
        os.unlink(path)
    except OSError:
        pass
