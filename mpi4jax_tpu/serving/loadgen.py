"""Seeded open-loop load generator (docs/serving.md "load generator").

Open-loop Poisson arrivals: inter-arrival gaps are exponential at the
configured rate and DO NOT wait for the system — a saturated server
keeps receiving (and must shed), which is exactly the regime admission
control exists for (closed-loop generators self-throttle and hide the
p99 blowup; benchmarks/serving.py explains the choice).

Deterministic: everything (gaps, prompt lengths, output lengths,
prompt token ids) derives from one ``random.Random(seed)``, so every
rank, every re-run, and the offline ``reference_greedy_decode`` oracle
see the identical workload.  Pure stdlib — no jax, no numpy.
"""

import random

from .request import Request

__all__ = ["LoadGen", "make_dist"]


def make_dist(spec):
    """A length distribution from a spec tuple:

    * ``("fixed", n)``           — always ``n``
    * ``("uniform", lo, hi)``    — integer uniform, inclusive
    * ``("bimodal", lo, hi, p)`` — ``lo`` with probability ``p`` else
      ``hi`` (the short-query/long-tail traffic shape)

    Returns ``f(rng) -> int``.  Raises ``ValueError`` on a malformed
    spec — a typo'd distribution must fail at setup, not quietly
    benchmark a different workload."""
    if not isinstance(spec, (tuple, list)) or not spec:
        raise ValueError(f"distribution spec must be a tuple, got {spec!r}")
    kind, *args = spec
    if kind == "fixed":
        (n,) = args
        if n < 1:
            raise ValueError(f"fixed length must be >= 1, got {n}")
        return lambda rng: int(n)
    if kind == "uniform":
        lo, hi = args
        if not 1 <= lo <= hi:
            raise ValueError(
                f"uniform bounds must satisfy 1 <= lo <= hi, got "
                f"({lo}, {hi})"
            )
        return lambda rng: rng.randint(int(lo), int(hi))
    if kind == "bimodal":
        lo, hi, p = args
        if not 1 <= lo <= hi:
            raise ValueError(
                f"bimodal bounds must satisfy 1 <= lo <= hi, got "
                f"({lo}, {hi})"
            )
        if not 0 <= p <= 1:
            raise ValueError(f"bimodal p must be in [0, 1], got {p}")
        return lambda rng: int(lo) if rng.random() < p else int(hi)
    raise ValueError(
        f"unknown distribution kind {kind!r} "
        "(want fixed|uniform|bimodal)"
    )


class LoadGen:
    """Generate a request stream.

    ``rate_rps`` is the open-loop arrival rate; ``prompt_len`` /
    ``max_new`` are distribution specs (:func:`make_dist`); prompt
    token ids are uniform over ``[0, vocab)``.  ``deadline_fn`` maps
    an arrival time to an absolute deadline (or ``None``) — the
    admission controller's ``deadline_for`` plugs in here.
    """

    def __init__(self, seed, rate_rps, prompt_len=("uniform", 4, 16),
                 max_new=("uniform", 4, 16), vocab=64,
                 deadline_fn=None, start_ms=0.0):
        if rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
        if vocab < 2:
            raise ValueError(f"vocab must be >= 2, got {vocab}")
        self.rng = random.Random(seed)
        self.rate_rps = float(rate_rps)
        self._prompt_len = make_dist(prompt_len)
        self._max_new = make_dist(max_new)
        self.vocab = int(vocab)
        self.deadline_fn = deadline_fn
        self._t_ms = float(start_ms)
        self._next_rid = 0
        self._pending_gap = None

    def _gap_ms(self):
        # a gap drawn-but-not-consumed by until() is served first, so
        # interleaved until/take calls see one continuous stream
        if self._pending_gap is not None:
            gap, self._pending_gap = self._pending_gap, None
            return gap
        return self.rng.expovariate(self.rate_rps) * 1e3

    def _emit(self):
        p_len = self._prompt_len(self.rng)
        prompt = tuple(
            self.rng.randrange(self.vocab) for _ in range(p_len)
        )
        req = Request(
            rid=self._next_rid,
            prompt=prompt,
            max_new=self._max_new(self.rng),
            arrival_ms=self._t_ms,
            deadline_ms=(self.deadline_fn(self._t_ms)
                         if self.deadline_fn else None),
        )
        self._next_rid += 1
        return req

    def next_request(self):
        """The next arrival (advances the clock by one Poisson gap)."""
        self._t_ms += self._gap_ms()
        return self._emit()

    def take(self, n):
        """The next ``n`` arrivals as a list."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        return [self.next_request() for _ in range(n)]

    def until(self, t_ms):
        """Every arrival up to absolute time ``t_ms`` (may be empty).

        Peeks one gap ahead without consuming it, so interleaved
        ``until`` calls see exactly the same stream as one big
        ``take``."""
        out = []
        while True:
            gap = self._gap_ms()
            if self._t_ms + gap > t_ms:
                # push the gap back: the NEXT call starts from here.
                # (random streams cannot be unread; keep the drawn gap
                # as a pending offset instead)
                self._pending_gap = gap
                return out
            self._t_ms += gap
            out.append(self._emit())
