"""Deadline-aware admission control (docs/serving.md).

Three composable pieces, all pure and time-injected:

* :class:`TokenBucket` — the classic rate limiter: sustained
  ``rate_per_s`` with a ``burst`` allowance.  The coarse front door.
* :class:`SLOEstimator` — EWMA service-time model fed by the engine's
  measured step/prefill times (themselves sourced from the PR-6
  metrics-table latency histograms when telemetry is on), multiplied
  by a fabric :func:`degradation_factor` read off the live exporter's
  job view (PR-8): a repairing link or a lagging straggler slows every
  decode step, so predicted completions stretch BEFORE the p99 shows
  it.
* :class:`AdmissionController` — the decision: admit, or shed with a
  named reason.  ``mode="off"`` admits everything (the uncontrolled
  baseline every benchmark arm compares against); ``mode="on"`` sheds
  when the bucket is dry or when the predicted completion blows the
  request's deadline.  Sheds are returned to the caller, never
  swallowed — the shed rate is a first-class metric.
"""

__all__ = [
    "AdmissionController",
    "SLOEstimator",
    "TokenBucket",
    "degradation_factor",
]


class TokenBucket:
    """Sustained-rate limiter: ``burst`` tokens capacity, refilled at
    ``rate_per_s``.  ``rate_per_s=0`` disables the bucket (always
    allows) — the SLO gate is then the only control."""

    def __init__(self, rate_per_s, burst):
        if rate_per_s < 0:
            raise ValueError(f"rate_per_s must be >= 0, got {rate_per_s}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._last_ms = None

    def allow(self, now_ms):
        """Consume one token if available; refills lazily."""
        if self.rate == 0:
            return True
        now_ms = float(now_ms)
        if self._last_ms is not None:
            elapsed_s = max(0.0, (now_ms - self._last_ms) / 1e3)
            self._tokens = min(
                self.burst, self._tokens + elapsed_s * self.rate
            )
        self._last_ms = now_ms
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


class SLOEstimator:
    """EWMA service-time model for the continuous-batching engine.

    The engine reports what it measures: ``observe_step(ms)`` after
    each decode step (one token for every active slot) and
    ``observe_prefill(ms, prompt_len)`` after each prefill.  The
    estimator keeps EWMAs (``alpha`` weighting the newest sample) and
    predicts a queued request's completion as

        queue_wait + prefill(p_len) + (new_tokens - 1) · step · degr

    where ``queue_wait`` models the slot it must wait for: with
    ``queue_ahead`` requests already queued and ``max_batch`` slots,
    roughly ``(queue_ahead / max_batch + occupancy_fraction) ·
    mean_residual_service``.  Deliberately simple and CONSERVATIVE in
    shape — admission needs a stable early-warning signal, not a
    simulator; docs/serving.md discusses the bias."""

    def __init__(self, alpha=0.25, seed_step_ms=50.0,
                 seed_prefill_ms_per_tok=1.0):
        if not 0 < alpha <= 1:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self.step_ms = float(seed_step_ms)
        self.prefill_ms_per_tok = float(seed_prefill_ms_per_tok)
        self.samples = 0

    def observe_step(self, ms):
        if ms < 0:
            raise ValueError(f"negative step time {ms}")
        self.step_ms += self.alpha * (float(ms) - self.step_ms)
        self.samples += 1

    def observe_prefill(self, ms, prompt_len):
        if prompt_len < 1:
            raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
        per_tok = float(ms) / float(prompt_len)
        self.prefill_ms_per_tok += self.alpha * (
            per_tok - self.prefill_ms_per_tok
        )

    def residual_service_ms(self, active_requests):
        """Mean remaining decode time over the active requests (0 when
        the batch is empty)."""
        reqs = list(active_requests)
        if not reqs:
            return 0.0
        remaining = [
            max(0, r.max_new - r.generated) for r in reqs
        ]
        return (sum(remaining) / len(remaining)) * self.step_ms

    def predict_ms(self, prompt_len, max_new, queue_ahead, occupancy,
                   max_batch, residual_ms=0.0, degradation=1.0):
        """Predicted arrival→completion latency in ms for a request
        arriving NOW with ``queue_ahead`` requests already queued."""
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        degradation = max(1.0, float(degradation))
        # slot wait: how many "service turns" until a slot frees for
        # THIS request.  Every queued request ahead occupies one turn;
        # a full batch adds the residual of the slot that must drain.
        turns = float(queue_ahead) / float(max_batch)
        wait = turns * max(residual_ms, self.step_ms) * degradation
        if occupancy >= max_batch:
            wait += residual_ms * degradation
        prefill = self.prefill_ms_per_tok * float(prompt_len)
        decode = max(0, int(max_new) - 1) * self.step_ms * degradation
        return wait + prefill + decode


def degradation_factor(job_view, reconnect_penalty=0.5,
                       repairing_penalty=1.0):
    """Fabric health → service-time multiplier (>= 1.0), from the live
    exporter's job aggregate (:func:`telemetry.exporter.
    aggregate_snapshots` — the PR-8 straggler/worst-link gauges).

    * a worst link in the broken/repairing state (``state == 1``)
      means decode collectives are stalling on replay: +
      ``repairing_penalty``;
    * accumulated reconnects on the worst link mean a flaky path that
      will stall again: + ``reconnect_penalty`` once any exist;
    * a missing/empty view degrades to 1.0 — no telemetry is no
      evidence, and admission must not shed on absence of data.

    Returns ``(factor, reasons)`` with ``reasons`` a tuple of short
    strings naming what contributed (the shed log prints them)."""
    if not job_view:
        return 1.0, ()
    factor = 1.0
    reasons = []
    worst = job_view.get("worst_link") or {}
    state = int(worst.get("state") or 0)
    if state >= 1:
        factor += float(repairing_penalty)
        reasons.append(
            f"worst link r{worst.get('rank')}–r{worst.get('peer')} "
            f"state={state}"
        )
    if int(worst.get("reconnects") or 0) > 0:
        factor += float(reconnect_penalty)
        reasons.append(
            f"worst link saw {worst['reconnects']} reconnect(s)"
        )
    return factor, tuple(reasons)


class AdmissionController:
    """The admit/shed decision (docs/serving.md "admission control").

    ``mode`` is validated ``"off"`` | ``"on"`` (utils/config.py
    ``admit_mode``).  ``slo_ms`` stamps every admitted request's
    deadline; with ``mode="on"`` a predicted completion past the
    deadline (x ``headroom``) sheds at the door, and
    :meth:`reconsider_queued` sheds queued requests whose deadline
    became hopeless as the estimator learned — both paths count
    honestly through the scheduler.
    """

    SHED_BUCKET = "token-bucket"
    SHED_PREDICTED = "predicted-miss"
    SHED_HOPELESS = "deadline-hopeless"

    def __init__(self, mode, slo_ms=0.0, estimator=None, bucket=None,
                 headroom=1.0):
        if mode not in ("off", "on"):
            raise ValueError(
                f"admission mode must be 'off' or 'on', got {mode!r}"
            )
        if mode == "off" and slo_ms:
            # mirrors the ensure_initialized rejection: an SLO with
            # admission off cannot be enforced, only missed
            raise ValueError(
                "slo_ms set with admission mode 'off' — nothing would "
                "enforce it (set mode='on' or drop the SLO)"
            )
        if slo_ms < 0:
            raise ValueError(f"slo_ms must be >= 0, got {slo_ms}")
        if headroom <= 0:
            raise ValueError(f"headroom must be > 0, got {headroom}")
        self.mode = mode
        self.slo_ms = float(slo_ms)
        self.estimator = estimator or SLOEstimator()
        self.bucket = bucket
        self.headroom = float(headroom)
        self.degradation = 1.0
        self.degradation_reasons = ()

    def observe_fabric(self, job_view):
        """Feed the latest exporter job view (straggler / worst-link
        gauges) into the service-time model."""
        self.degradation, self.degradation_reasons = degradation_factor(
            job_view
        )

    def deadline_for(self, arrival_ms):
        if not self.slo_ms:
            return None
        return float(arrival_ms) + self.slo_ms

    def decide(self, req, now_ms, scheduler):
        """``(verdict, reason)``: verdict ``"admit"`` or ``"shed"``.
        The caller routes an admitted request to
        ``scheduler.submit`` and a shed one to
        ``scheduler.shed_request`` — decisions and effects stay
        separated so tests can probe decisions alone."""
        if self.mode == "off":
            return "admit", None
        if self.bucket is not None and not self.bucket.allow(now_ms):
            return "shed", self.SHED_BUCKET
        if self.slo_ms and req.deadline_ms is not None:
            est = self.estimator
            predicted = est.predict_ms(
                req.prompt_len, req.max_new,
                queue_ahead=scheduler.queue_depth(),
                occupancy=scheduler.occupancy(),
                max_batch=scheduler.max_batch,
                residual_ms=est.residual_service_ms(
                    scheduler.active_requests()
                ),
                degradation=self.degradation,
            )
            if now_ms + predicted * self.headroom > req.deadline_ms:
                return "shed", self.SHED_PREDICTED
        return "admit", None

    def reconsider_queued(self, now_ms, scheduler):
        """Shed queued requests whose deadline can no longer be met
        even if a slot freed right now (their queue wait already ate
        the budget).  Returns the shed requests."""
        if self.mode == "off" or not self.slo_ms:
            return []
        est = self.estimator
        victims = []
        for req in scheduler.queued():
            if req.deadline_ms is None:
                continue
            floor = (
                est.prefill_ms_per_tok * req.prompt_len
                + max(0, req.max_new - 1) * est.step_ms
                * self.degradation
            )
            if now_ms + floor > req.deadline_ms:
                victims.append(req)
        for req in victims:
            scheduler.shed_request(req, now_ms, self.SHED_HOPELESS)
        return victims
