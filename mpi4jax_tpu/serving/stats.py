"""Serving gauges: the exporter/t4j-top surface (docs/serving.md).

:class:`ServingStats` accumulates the engine's request accounting and
latency histogram; :func:`publish` installs the current snapshot in a
module global the live exporter (:func:`telemetry.exporter.
collect_snapshot`) folds into every scrape as the ``serving`` block —
queue depth, batch occupancy, shed count, p50/p99 vs SLO — so
``t4j-top`` and the launcher job view show the serving loop next to
the transport gauges it feeds on.

Latency percentiles reuse :class:`telemetry.registry.Histogram` (the
same clamped-geometric-midpoint estimate as every other p50/p99 in the
repo — one percentile convention, docs/observability.md).
"""

from mpi4jax_tpu.telemetry.registry import (
    LAT_BASE_LOG2,
    Histogram,
)

__all__ = ["SERVING_SCHEMA", "LatencyHist", "ServingStats", "current",
           "publish"]

SERVING_SCHEMA = "t4j-serving-v1"

# The native metrics table's 24 log2 buckets top out at ~8.6 s — right
# for op latencies, far too small for END-TO-END request latencies
# (an overloaded baseline's drained tail reaches minutes, and a
# saturated top bucket would report a ~12 s p99 for ANY blowup —
# flattering exactly the run the measurement exists to expose).  40
# buckets reach ~2^(10+39) ns ≈ 6 days.
LAT_E2E_BUCKETS = 40

_state = {"snapshot": None}


class LatencyHist:
    """Millisecond latencies over the repo-standard log2 ns bucketing
    (``registry.log2_bucket``), widened to end-to-end range, with the
    same clamp-to-observed-min/max convention as ``registry.Row``."""

    def __init__(self):
        self.hist = Histogram(LAT_BASE_LOG2, LAT_E2E_BUCKETS)
        self.count = 0
        self.min_ns = None
        self.max_ns = None

    def record(self, ms):
        ns = max(0, int(float(ms) * 1e6))
        self.hist.add(ns)
        self.count += 1
        self.min_ns = ns if self.min_ns is None else min(self.min_ns, ns)
        self.max_ns = ns if self.max_ns is None else max(self.max_ns, ns)

    def percentile_ms(self, q):
        v = self.hist.quantile(q)
        if v is None:
            return None
        v = min(max(v, self.min_ns), self.max_ns)
        return v / 1e6


def publish(snapshot):
    """Install ``snapshot`` (a :meth:`ServingStats.snapshot` dict, or
    ``None`` to clear) for the exporter to pick up."""
    _state["snapshot"] = snapshot


def current():
    """The last published serving snapshot, or ``None`` when no engine
    ever ran in this process.  A stopped engine's final snapshot stays
    published with ``"stopped": True`` — exit-time rank files and
    post-mortems want the last gauges, and live scrapers can tell a
    stopped engine from a running one by the flag."""
    return _state["snapshot"]


class ServingStats:
    """Request accounting + latency histogram for one engine.

    ``observe_*`` calls come from the engine/scheduler as requests
    move; :meth:`snapshot` renders the gauge dict.  ``slo_ms=0``
    means no SLO (attainment reported against completion only).
    """

    def __init__(self, slo_ms=0.0, max_batch=1, admit_mode="off"):
        self.slo_ms = float(slo_ms)
        self.max_batch = int(max_batch)
        self.admit_mode = str(admit_mode)
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        self.shed_by_reason = {}
        self.slo_ok = 0
        self.latency = LatencyHist()
        self.first_token = LatencyHist()
        self.queue_depth = 0
        self.occupancy = 0
        self.steps = 0
        # elastic epoch survival: reissued in-flight requests and the
        # number of resize epochs this engine rode out.
        self.reissued = 0
        self.epochs_survived = 0
        self.autoscale_state = None  # autoscaler state string, or None

    # ---- engine feed -----------------------------------------------------

    def observe_submitted(self):
        self.submitted += 1

    def observe_shed(self, reason):
        self.shed += 1
        key = str(reason)
        self.shed_by_reason[key] = self.shed_by_reason.get(key, 0) + 1

    def observe_completed(self, req):
        self.completed += 1
        lat = req.latency_ms()
        if lat is not None:
            self.latency.record(lat)
        if req.first_token_ms is not None:
            self.first_token.record(req.first_token_ms - req.arrival_ms)
        if req.within_slo():
            self.slo_ok += 1

    def observe_step(self, queue_depth, occupancy):
        self.steps += 1
        self.queue_depth = int(queue_depth)
        self.occupancy = int(occupancy)

    def observe_reissued(self, n):
        """``n`` in-flight requests went back to the queue after a
        resize wiped their slot state (docs/failure-semantics.md)."""
        self.reissued += int(n)

    def observe_epoch(self):
        """The engine survived one resize epoch."""
        self.epochs_survived += 1

    # ---- gauges ----------------------------------------------------------

    def slo_attainment(self):
        """Goodput fraction: requests finished WITHIN the SLO over all
        requests OFFERED (completed + shed) — sheds count against
        attainment; a controller that shed everything would score 0,
        not 1 (docs/serving.md "honest accounting")."""
        offered = self.completed + self.shed
        if offered == 0:
            return None
        return self.slo_ok / offered

    @staticmethod
    def _wire_dtype():
        """The effective compressed-collective wire dtype the engine's
        decode/prefill allreduces run under (docs/performance.md
        "Compressed collectives").  The knob is global — it opts in via
        the tuning broadcast at bridge init, not per engine — but the
        serving snapshot surfaces it because a latency regression that
        is really a fleet-wide knob change should be visible from the
        serving gauges alone.  ``"off"`` outside a native job."""
        try:
            from mpi4jax_tpu.native import runtime

            info = runtime.wire_dtype_info()
            if info:
                return info.get("wire_dtype", "off")
        except Exception:
            pass
        return "off"

    def snapshot(self):
        p = [self.latency.percentile_ms(q) for q in (0.50, 0.99)]
        ft = [self.first_token.percentile_ms(q) for q in (0.50, 0.99)]
        return {
            "schema": SERVING_SCHEMA,
            "admit_mode": self.admit_mode,
            "wire_dtype": self._wire_dtype(),
            "slo_ms": self.slo_ms or None,
            "max_batch": self.max_batch,
            "queue_depth": self.queue_depth,
            "batch_occupancy": self.occupancy,
            "steps": self.steps,
            "submitted": self.submitted,
            "completed": self.completed,
            "shed": self.shed,
            "reissued": self.reissued,
            "epochs_survived": self.epochs_survived,
            "autoscale_state": self.autoscale_state,
            "shed_by_reason": dict(self.shed_by_reason),
            "slo_ok": self.slo_ok,
            "slo_attainment": self.slo_attainment(),
            "latency_p50_ms": p[0],
            "latency_p99_ms": p[1],
            "first_token_p50_ms": ft[0],
            "first_token_p99_ms": ft[1],
        }
