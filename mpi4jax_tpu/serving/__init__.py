"""Continuous-batching multi-host inference serving
(docs/serving.md).

The latency-bound workload over the transport (ROADMAP item 3): a
request queue + slot-based batch scheduler (:mod:`.scheduler`) admits
prefills into free KV slots while in-flight decode continues, a
deadline-aware admission controller (:mod:`.admission` — token bucket
+ SLO estimator fed by the live exporter's straggler/worst-link
gauges) sheds load *before* it blows the p99 target, and a seeded
open-loop Poisson load generator (:mod:`.loadgen`) drives the closed
loop ``benchmarks/serving.py`` measures.

Split exactly like ``telemetry/`` and ``tuning/``:

* the **pure core** (:mod:`.request`, :mod:`.scheduler`,
  :mod:`.admission`, :mod:`.loadgen`, :mod:`.plan`, :mod:`.stats`) is
  import-free of jax — it stub-loads on old-jax containers
  (tests/test_serving.py) and under the ctypes smoke
  (tools/serving_smoke.py);
* the **engine** (:mod:`.engine`, imported lazily) turns
  ``models/transformer.py``'s ``_prefill_sharded`` /
  ``_decode_step_sharded`` KV-cache machinery into the actual
  tensor-parallel continuous-batching decoder on the proc tier —
  rank 0 is the frontend (loadgen + scheduler + admission), every
  rank executes the broadcast step plan (:mod:`.plan`).

Knobs (validated in utils/config.py): ``T4J_SLO_MS`` (the p99
latency target), ``T4J_MAX_BATCH`` (decode slots), ``T4J_ADMIT``
(``off`` | ``on``).  ``launch.py --serve`` wires them.

Elastic serving (this PR's arc): :mod:`.autoscale` holds the pure
traffic-driven scale policy (hysteresis state machine + the file
channel ``launch.py --autoscale`` polls), the scheduler grew reissue/
drain primitives, and the engine rides PR-10 resize epochs instead of
dying — see docs/failure-semantics.md "Serving across epochs".
"""

from . import admission, autoscale, loadgen, plan, request, scheduler, stats
from .admission import (
    AdmissionController,
    SLOEstimator,
    TokenBucket,
    degradation_factor,
)
from .autoscale import Autoscaler
from .loadgen import LoadGen
from .plan import (
    PlanError,
    decode_plan,
    encode_plan,
    plan_words,
    rebuild_mirror,
)
from .request import Request, RequestState
from .scheduler import (
    FollowerMirror,
    SchedulerError,
    SlotScheduler,
    StepPlan,
    slots_digest,
)
from .stats import ServingStats, current, publish

__all__ = [
    "AdmissionController",
    "Autoscaler",
    "FollowerMirror",
    "LoadGen",
    "PlanError",
    "Request",
    "RequestState",
    "SLOEstimator",
    "SchedulerError",
    "ServingStats",
    "SlotScheduler",
    "StepPlan",
    "TokenBucket",
    "admission",
    "autoscale",
    "current",
    "decode_plan",
    "degradation_factor",
    "encode_plan",
    "engine",
    "loadgen",
    "plan",
    "plan_words",
    "publish",
    "rebuild_mirror",
    "request",
    "scheduler",
    "slots_digest",
    "stats",
]


def __getattr__(name):
    # the engine imports jax (and the ops layer); loading it lazily
    # keeps the pure core stub-loadable on old-jax containers
    if name == "engine":
        import importlib

        return importlib.import_module(__name__ + ".engine")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
