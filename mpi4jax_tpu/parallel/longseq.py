"""Long-context sequence/context parallelism built on the comm primitives.

The reference ships the *building blocks* for every named sequence-
parallel scheme but no scheme itself (SURVEY §5.7): the ring step is
``sendrecv`` to rank±1 (mpi4jax/_src/collective_ops/sendrecv.py:366-385,
AD-reversible), and head↔sequence resharding is ``alltoall``
(alltoall.py:35-74).  This module assembles both into first-class,
differentiable context-parallel attention:

* :func:`ring_attention` — blockwise attention with an online softmax;
  KV blocks rotate around the communicator ring via :func:`sendrecv`,
  one ICI nearest-neighbour ``ppermute`` per step (Liu et al. 2023,
  "Ring Attention with Blockwise Transformers", arXiv:2310.01889 —
  public algorithm, implemented here from the paper's math).  Memory per
  device is O(T_local); the full sequence is never materialised.
* :func:`ulysses_attention` — DeepSpeed-Ulysses-style resharding
  (Jacobs et al. 2023, arXiv:2309.14509): all-to-all converts
  sequence-sharding into head-sharding, each device runs dense attention
  over the *full* sequence for its head subset, and a second all-to-all
  restores sequence sharding.  One pair of ICI all-to-alls total; heads
  must divide the ring size.

Both run per-device inside ``shard_map``, are reverse-mode
differentiable end to end (the ring's gradient traverses the ring in
the reverse direction via the sendrecv/ppermute transpose), and thread
the ordering token through every exchange.
"""

import math

import jax
import jax.numpy as jnp
from jax import lax

from mpi4jax_tpu.ops._core import Token, as_token, publishes_token
from mpi4jax_tpu.ops.collectives import alltoall
from mpi4jax_tpu.ops.p2p import sendrecv

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "local_attention",
    "zigzag_indices",
    "zigzag_shard",
    "zigzag_unshard",
]

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)  # finite mask value


def _check_gqa(hq, hk, where):
    if hq % hk:
        raise ValueError(
            f"{where}: query heads must be a multiple of kv heads "
            f"(grouped-query attention), got Hq={hq}, Hkv={hk}"
        )


def _scores(q, k, scale):
    """q·kᵀ with GQA support: query head h attends kv head ``h // g``
    (g = Hq/Hkv).  Returns [B, Hq, Tq, Tk] f32 scores."""
    b, tq, hq, d = q.shape
    hk = k.shape[2]
    if hq == hk:
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
    else:
        _check_gqa(hq, hk, "attention")
        g = hq // hk
        qg = q.reshape(b, tq, hk, g, d)
        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
        ).reshape(b, hq, tq, k.shape[1])
    return s * scale


def _weighted_values(w, v, hq):
    """w·v with GQA support; ``w``: [B, Hq, Tq, Tk], ``v``: [B, Tk, Hkv, D]."""
    hk = v.shape[2]
    if hq == hk:
        return jnp.einsum("bhqk,bkhd->bqhd", w, v)
    g = hq // hk
    b, _, tq, tk = w.shape
    wg = w.reshape(b, hk, g, tq, tk)
    return jnp.einsum("bhgqk,bkhd->bqhgd", wg, v).reshape(
        b, tq, hq, v.shape[-1]
    )


def local_attention(
    q, k, v, *, causal=False, scale=None, q_offset=0, k_offset=0, impl="auto"
):
    """Single-device attention: softmax(q k^T) v.

    ``q``: [B, Tq, Hq, D]; ``k``/``v``: [B, Tk, Hkv, D] with
    ``Hq % Hkv == 0`` — grouped-query attention (query head h attends
    kv head ``h // (Hq/Hkv)``; Hkv == Hq is plain MHA, Hkv == 1 is
    MQA).  ``*_offset`` are the global positions of the first
    row/column (for causal masking of sharded blocks).  Accumulates in
    float32.

    ``impl``: ``"xla"`` — dense (materialises the [Tq, Tk] scores, the
    oracle); ``"flash"`` — the Pallas VMEM-blocked kernel
    (ops/flash.py); ``"auto"`` — flash on TPU, dense elsewhere.
    """
    _check_gqa(q.shape[2], k.shape[2], "local_attention")
    if impl == "auto":
        impl = (
            "flash"
            if jax.default_backend() in ("tpu", "axon") and q.shape[1] >= 128
            else "xla"
        )
    if impl == "flash":
        from mpi4jax_tpu.ops.flash import flash_attention

        return flash_attention(
            q, k, v, causal=causal, scale=scale,
            q_offset=q_offset, k_offset=k_offset,
        )
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    s = _scores(q, k, scale)
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    out = _weighted_values(w.astype(v.dtype), v, q.shape[2])
    return out.astype(q.dtype)


def zigzag_indices(p, t_global):
    """Global sequence positions each rank holds under the zigzag layout.

    Rank r holds chunks ``r`` and ``2p-1-r`` of the 2p equal chunks —
    the standard balanced-causal layout (Megatron context parallelism):
    every rank then owns one "early" and one "late" chunk, so causal
    masking wastes the same ~half of the score blocks on every rank
    instead of idling rank 0 while rank p-1 computes everything.

    Returns an int32 array of shape ``(p, t_global // p)``.
    """
    if t_global % (2 * p):
        raise ValueError(
            f"zigzag layout needs the global sequence divisible by "
            f"2*comm.size = {2 * p}, got T={t_global}"
        )
    c = t_global // (2 * p)
    import numpy as _np

    rows = [
        _np.concatenate(
            [
                _np.arange(r * c, (r + 1) * c),
                _np.arange((2 * p - 1 - r) * c, (2 * p - r) * c),
            ]
        )
        for r in range(p)
    ]
    return _np.stack(rows).astype(_np.int32)


def zigzag_shard(x, p, axis=1):
    """Reorder a globally-ordered array so a plain rank-major shard over
    ``axis`` gives each rank its zigzag chunks (apply before sharding)."""
    idx = zigzag_indices(p, x.shape[axis]).reshape(-1)
    return jnp.take(x, jnp.asarray(idx), axis=axis)


def zigzag_unshard(x, p, axis=1):
    """Inverse of :func:`zigzag_shard` on the gathered global array."""
    import numpy as _np

    idx = zigzag_indices(p, x.shape[axis]).reshape(-1)
    inv = _np.empty_like(idx)
    inv[idx] = _np.arange(idx.size, dtype=_np.int32)
    return jnp.take(x, jnp.asarray(inv), axis=axis)


@publishes_token
def ring_attention(
    q, k, v, comm, *, causal=False, scale=None, token=None,
    layout="contiguous", impl="auto",
):
    """Context-parallel attention over a 1-D ring communicator.

    Every device holds the local sequence block ``q``/``k``/``v`` of
    shape [B, T_local, H, D] (global sequence = ring-rank-major
    concatenation).  Returns ``(out, token)`` with ``out`` the local
    block of softmax(QK^T)V over the *global* sequence.

    Algorithm: ``comm.size`` steps of blockwise attention with running
    (max, sum, accumulator) statistics; after each step the KV pair
    moves to the next rank via :func:`sendrecv` (one ``ppermute``).
    Reverse-mode AD reverses the permutation automatically — gradients
    ride the ring the opposite way, the exact transpose contract of the
    reference's sendrecv (sendrecv.py:366-385).

    ``impl`` selects the single-device attention kernel (see
    :func:`local_attention`) for the ``comm.size == 1`` shortcut; the
    multi-rank ring path always uses its own blockwise online-softmax
    updates (the ring IS the flash-style blocking, at shard granularity).

    ``layout``: ``"contiguous"`` — rank r holds global positions
    ``[r*T_local, (r+1)*T_local)``; ``"zigzag"`` — rank r holds chunks
    ``r`` and ``2p-1-r`` (see :func:`zigzag_indices`), which balances
    the causal-masking work across ranks (with contiguous blocks the
    last rank attends to everything while rank 0 sees one block; the
    ring is a barrier per step, so the slowest rank paces everyone).
    Use :func:`zigzag_shard`/:func:`zigzag_unshard` to convert global
    arrays.
    """
    token = as_token(token)
    p = comm.size
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale

    # validate BEFORE the single-rank shortcut, so a bad layout string /
    # GQA mismatch fails in 1-device tests too, not first at scale
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(
            f"layout must be 'contiguous' or 'zigzag', got {layout!r}"
        )
    _check_gqa(q.shape[2], k.shape[2], "ring_attention")

    if comm.backend == "self" or p == 1:
        out = local_attention(q, k, v, causal=causal, scale=scale, impl=impl)
        return out, token

    if comm.backend != "mesh":
        raise NotImplementedError(
            f"ring_attention requires a mesh communicator, got "
            f"{comm.backend!r}"
        )
    if len(comm.axes) != 1:
        raise ValueError(
            f"ring_attention needs a 1-D communicator (one mesh axis), "
            f"got axes {comm.axes}; use comm.sub(axis)"
        )

    rank = comm.rank()
    b, tq, h, _ = q.shape
    tk = k.shape[1]
    if layout == "zigzag":
        if tq != tk:
            raise ValueError(
                f"zigzag layout requires equal q/kv block lengths, got "
                f"Tq={tq}, Tk={tk} (the chunk table is shared)"
            )
        if tq % 2:
            raise ValueError(
                f"zigzag layout needs an even local block length "
                f"(two chunks per rank), got T_local={tq}"
            )
        pos_table = jnp.asarray(zigzag_indices(p, p * tq))
        qpos = pos_table[rank]
    else:
        qpos = rank * tq + jnp.arange(tq)

    # forward ring: the kv block moves to the next rank each step, so at
    # step i this rank holds the block that originated at rank - i
    perm = [(r, (r + 1) % p) for r in range(p)]

    from mpi4jax_tpu.ops._core import promote_vma

    # carries become device-varying after the first step; start them
    # varying so the scan carry type is stable.  The target set is the
    # ring axis PLUS whatever axes the operands already vary on — on a
    # multi-axis mesh (e.g. dp×tp×sp) q/k/v vary on every axis, and a
    # carry promoted to "sp" alone would type-mismatch attend's outputs.
    try:
        operand_vma = (
            jax.typeof(q).vma | jax.typeof(k).vma | jax.typeof(v).vma
        )
    except AttributeError:
        operand_vma = frozenset()
    carry_axes = tuple(dict.fromkeys((*comm.axes, *sorted(operand_vma))))
    acc0 = promote_vma(jnp.zeros((b, tq, h, d), jnp.float32), carry_axes)
    m0 = promote_vma(jnp.full((b, h, tq), _NEG, jnp.float32), carry_axes)
    l0 = promote_vma(jnp.zeros((b, h, tq), jnp.float32), carry_axes)
    token = token.with_stamp(promote_vma(token.stamp, carry_axes))

    def attend(q_sub, qpos_sub, k_blk, v_blk, acc, m, l, kpos, *, mask):
        """Online-softmax update of (acc, m, l) for the q rows in
        ``q_sub``; ``mask=False`` asserts full visibility (no masking
        work, no wasted score FLOPs beyond the block itself)."""
        s = _scores(q_sub, k_blk, scale)
        if mask:
            vis = qpos_sub[:, None] >= kpos[None, :]
            s = jnp.where(vis[None, None], s, _NEG)

        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        w = jnp.exp(s - m_new[..., None])
        l_new = l * corr + w.sum(axis=-1)
        acc_new = acc * corr.transpose(0, 2, 1)[
            ..., None
        ] + _weighted_values(w, v_blk.astype(jnp.float32), q_sub.shape[2])
        return acc_new, m_new, l_new

    c = tq // 2  # zigzag chunk length

    def zigzag_causal_update(i, src, k_blk, v_blk, acc, m, l):
        """Chunk-level causal schedule for the zigzag layout.

        Rank r's q chunks are (r, 2p-1-r); the step-i kv block holds
        src's chunks (src, 2p-1-src).  Chunk-pair visibility collapses
        to three cases, two of which need NO elementwise mask and only
        HALF the block's scores — this is where the zigzag layout's
        balance comes from (every rank does the same half-block of
        work per off-diagonal step, vs the contiguous layout where one
        rank computes a full block while another skips it):

        * i == 0 (src == rank): the local block — diagonal chunks, one
          masked full attend.
        * src < rank: every q row sees ONLY src's early chunk
          (k rows [:c]); late chunk entirely in the future.
        * src > rank: only the late q chunk (rows [c:]) sees anything,
          and it sees the WHOLE kv block.
        """

        def diag():
            return attend(
                q, qpos, k_blk, v_blk, acc, m, l, pos_table[src], mask=True
            )

        def lower():  # src < rank: all q vs early k chunk, unmasked
            return attend(
                q, qpos, k_blk[:, :c], v_blk[:, :c], acc, m, l, None,
                mask=False,
            )

        def upper():  # src > rank: late q chunk vs full kv, unmasked
            a2, m2, l2 = attend(
                q[:, c:], None, k_blk, v_blk,
                acc[:, c:], m[..., c:], l[..., c:], None, mask=False,
            )
            return (
                acc.at[:, c:].set(a2),
                m.at[..., c:].set(m2),
                l.at[..., c:].set(l2),
            )

        return lax.cond(
            i == 0, diag, lambda: lax.cond(src < rank, lower, upper)
        )

    def step(carry, i):
        k_blk, v_blk, acc, m, l, stamp = carry
        src = (rank - i) % p

        if causal and layout == "zigzag":
            acc, m, l = zigzag_causal_update(i, src, k_blk, v_blk, acc, m, l)
        elif causal:
            kpos = src * tk + jnp.arange(tk)
            # blocks entirely in this rank's future contribute nothing:
            # skip the attention math (the communication still happens —
            # the ring must keep rotating). Saves ~half the FLOPs of a
            # causal ring on average, but unevenly: at step i only the
            # ranks with src <= rank do work (the zigzag layout is the
            # balanced alternative).
            block_visible = qpos[-1] >= kpos[0]
            acc, m, l = lax.cond(
                block_visible,
                lambda: attend(q, qpos, k_blk, v_blk, acc, m, l, kpos, mask=True),
                lambda: (acc, m, l),
            )
        else:
            kpos = None
            acc, m, l = attend(
                q, qpos, k_blk, v_blk, acc, m, l, kpos, mask=False
            )

        tok = Token(stamp)
        k_blk, tok = sendrecv(k_blk, k_blk, source=perm, dest=perm, comm=comm, token=tok)
        v_blk, tok = sendrecv(v_blk, v_blk, source=perm, dest=perm, comm=comm, token=tok)
        return (k_blk, v_blk, acc, m, l, tok.stamp), None

    carry0 = (k, v, acc0, m0, l0, token.stamp)
    (k_f, v_f, acc, m, l, stamp), _ = lax.scan(
        step, carry0, jnp.arange(p), length=p
    )
    del k_f, v_f
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype), Token(stamp)


@publishes_token
def ulysses_attention(
    q, k, v, comm, *, causal=False, scale=None, token=None, impl="auto"
):
    """Ulysses-style context parallelism: all-to-all head↔sequence
    reshard, dense local attention over the full sequence, reshard back.

    ``q``/``k``/``v``: local [B, T_local, H, D] with ``H % comm.size ==
    0``.  Cheaper than the ring when the full sequence fits in HBM for
    ``H / p`` heads (2 collectives instead of ``p`` permutes); the ring
    wins at extreme lengths.
    """
    token = as_token(token)
    p = comm.size

    if comm.backend == "self" or p == 1:
        out = local_attention(q, k, v, causal=causal, scale=scale, impl=impl)
        return out, token

    if comm.backend != "mesh":
        raise NotImplementedError(
            f"ulysses_attention requires a mesh communicator, got "
            f"{comm.backend!r}"
        )

    b, t, h, d = q.shape
    hk = k.shape[2]
    _check_gqa(h, hk, "ulysses_attention")
    for name, heads in (("query", h), ("kv", hk)):
        if heads % p:
            raise ValueError(
                f"ulysses_attention needs {name} heads divisible by the "
                f"ring size: H={heads}, comm.size={p}"
                + (
                    " (for GQA with fewer kv heads than ranks, repeat kv "
                    "heads to a multiple of comm.size first)"
                    if name == "kv"
                    else ""
                )
            )

    def to_heads(x, tok):
        # [B, T, H, D] -> rows [p, T, B, hp, D] -> alltoall -> full seq
        # for this rank's head subset [B, p*T, hp, D]
        hp = x.shape[2] // p
        blocks = x.reshape(b, t, p, hp, d).transpose(2, 1, 0, 3, 4)
        mixed, tok = alltoall(blocks, comm=comm, token=tok)
        # row j now holds rank j's sequence block for our heads
        return mixed.transpose(2, 0, 1, 3, 4).reshape(b, p * t, hp, d), tok

    def to_seq(x, tok):
        # inverse of to_heads
        hp = x.shape[2]
        blocks = x.reshape(b, p, t, hp, d).transpose(1, 2, 0, 3, 4)
        mixed, tok = alltoall(blocks, comm=comm, token=tok)
        return mixed.transpose(2, 1, 0, 3, 4).reshape(b, t, p * hp, d), tok

    qh, token = to_heads(q, token)
    kh, token = to_heads(k, token)
    vh, token = to_heads(v, token)

    out = local_attention(qh, kh, vh, causal=causal, scale=scale, impl=impl)

    out, token = to_seq(out, token)
    return out, token
