from mpi4jax_tpu.parallel.comm import (
    Comm,
    MeshComm,
    SelfComm,
    default_comm,
    get_default_comm,
    set_default_comm,
)
from mpi4jax_tpu.parallel.proc import ProcComm

__all__ = [
    "Comm",
    "MeshComm",
    "SelfComm",
    "ProcComm",
    "default_comm",
    "get_default_comm",
    "set_default_comm",
]
