from mpi4jax_tpu.parallel.comm import (
    Comm,
    MeshComm,
    SelfComm,
    default_comm,
    get_default_comm,
    set_default_comm,
)
from mpi4jax_tpu.parallel import distributed
from mpi4jax_tpu.parallel.halo import halo_exchange_2d
from mpi4jax_tpu.parallel.longseq import (
    zigzag_indices,
    zigzag_shard,
    zigzag_unshard,
    local_attention,
    ring_attention,
    ulysses_attention,
)
from mpi4jax_tpu.parallel import moe
from mpi4jax_tpu.parallel.moe import (
    expert_combine,
    expert_dispatch,
    topk_moe,
    topk_route,
)
from mpi4jax_tpu.parallel.proc import ProcComm, ProcGridComm, grid_comm

__all__ = [
    "distributed",
    "moe",
    "Comm",
    "MeshComm",
    "SelfComm",
    "ProcComm",
    "ProcGridComm",
    "grid_comm",
    "halo_exchange_2d",
    "local_attention",
    "ring_attention",
    "zigzag_indices",
    "zigzag_shard",
    "zigzag_unshard",
    "ulysses_attention",
    "expert_dispatch",
    "expert_combine",
    "default_comm",
    "get_default_comm",
    "set_default_comm",
]
