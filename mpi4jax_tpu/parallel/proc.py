"""Multi-process (MPMD) communicator over the native DCN bridge.

This is the TPU-native replacement tier for the reference's mpi4py/libmpi
process model (mpi4jax/_src/__init__.py:3, xla_bridge/mpi_xla_bridge.pyx):
one Python process per host, true per-process rank, and a C++ socket
backend carrying traffic over the hosts' data-center network.

Round-1 status: interface + world discovery; the native bridge lands with
:mod:`mpi4jax_tpu.native`.
"""

from dataclasses import dataclass
from math import prod

import numpy as np

from mpi4jax_tpu.parallel.comm import Comm

__all__ = ["ProcComm", "ProcGridComm", "grid_comm",
           "world_comm_if_initialized"]


@dataclass(frozen=True)
class ProcComm(Comm):
    """Communicator over a group of OS processes (MPMD, static ranks)."""

    ranks: tuple  # world ranks of the members, sorted
    context: int = 0

    backend = "proc"

    @property
    def size(self):
        return len(self.ranks)

    def rank(self):
        from mpi4jax_tpu.native import runtime

        return self.ranks.index(runtime.world_rank())

    def clone(self):
        from mpi4jax_tpu.parallel.comm import _context_counter

        return ProcComm(ranks=self.ranks, context=next(_context_counter))

    def split(self, color, key=None):
        """MPI_Comm_split analog (static form, like MeshComm.split):
        ``color``/``key`` are functions of the comm rank (or explicit
        sequences), evaluated identically on every process.  Unlike the
        SPMD mesh backend, ragged (unequal-size) groups are allowed —
        each process simply joins its own subgroup's communicator.
        Returns None (MPI_COMM_NULL) for ranks whose color is None.
        """
        from mpi4jax_tpu.parallel.comm import _context_counter

        n = self.size
        colors = [color(r) for r in range(n)] if callable(color) else list(color)
        if len(colors) != n:
            raise ValueError(
                f"color must cover all {n} ranks, got {len(colors)}"
            )
        keys = (
            [key(r) for r in range(n)]
            if callable(key)
            else (list(key) if key is not None else [0] * n)
        )
        me = self.rank()
        if colors[me] is None:
            return None
        members = sorted(
            (r for r in range(n) if colors[r] == colors[me]),
            key=lambda r: (keys[r], r),
        )
        # same (deterministic) context on every member: derive from the
        # clone counter only on the lowest member... not possible without
        # communication, so fold the group into the wire context instead
        # (runtime._stable_ctx hashes ranks + context; keep parent ctx).
        return ProcComm(
            ranks=tuple(self.ranks[r] for r in members),
            context=self.context,
        )


@dataclass(frozen=True)
class ProcGridComm(ProcComm):
    """A ProcComm with a Cartesian topology (MPI_Cart_create analog).

    Gives the multi-process backend the same ``sub``/``shift_perm``
    surface as :class:`MeshComm`, so grid-shaped code —
    ``parallel.halo.halo_exchange_2d`` in particular — runs unchanged
    on OS-process worlds.  Ranks are the row-major ravel of the axis
    coordinates over ``self.ranks`` (axis 0 varies slowest), exactly
    the MeshComm convention.
    """

    axes: tuple = ()
    axis_sizes: tuple = ()

    def __post_init__(self):
        object.__setattr__(self, "axes", tuple(self.axes))
        object.__setattr__(
            self, "axis_sizes", tuple(int(s) for s in self.axis_sizes)
        )
        if len(self.axes) != len(self.axis_sizes):
            raise ValueError("axes and axis_sizes must have equal length")
        if prod(self.axis_sizes) != len(self.ranks):
            raise ValueError(
                f"grid {self.axis_sizes} needs "
                f"{prod(self.axis_sizes)} ranks, comm has "
                f"{len(self.ranks)}"
            )

    def clone(self):
        from mpi4jax_tpu.parallel.comm import _context_counter

        return ProcGridComm(
            ranks=self.ranks, context=next(_context_counter),
            axes=self.axes, axis_sizes=self.axis_sizes,
        )

    # -- topology helpers (the MeshComm surface) --------------------------

    def rank_grid(self):
        """ndarray of shape ``axis_sizes`` holding each coordinate's
        COMM rank (not world rank)."""
        return np.arange(self.size).reshape(self.axis_sizes)

    def coords_of(self, rank):
        return tuple(np.unravel_index(rank, self.axis_sizes))

    def shift_perm(self, axis, disp, periodic=True):
        """(source, dest) comm-rank pairs shifting data by ``disp``
        along ``axis`` — same contract as MeshComm.shift_perm (edge
        ranks of a non-periodic shift simply drop out, the
        MPI_PROC_NULL analog)."""
        ax = self.axes.index(axis)
        n = self.axis_sizes[ax]
        grid = self.rank_grid()
        pairs = []
        for src_coord in np.ndindex(*self.axis_sizes):
            dst_coord = list(src_coord)
            d = src_coord[ax] + disp
            if periodic:
                dst_coord[ax] = d % n
            elif 0 <= d < n:
                dst_coord[ax] = d
            else:
                continue
            pairs.append(
                (int(grid[src_coord]), int(grid[tuple(dst_coord)]))
            )
        return pairs

    def sub(self, *axes):
        """Sub-communicator over a subset of axes (MPI_Cart_sub).

        Unlike the SPMD mesh (where one comm description covers every
        device), each PROCESS gets the communicator of its own slab:
        the ranks varying over ``axes`` with this process's other
        coordinates held fixed.  The parent context is kept — the wire
        channel hashes (ranks, context), so different rows get
        disjoint channels automatically."""
        for a in axes:
            if a not in self.axes:
                raise ValueError(f"axis {a!r} not in {self.axes}")
        me = self.rank()
        coords = dict(zip(self.axes, self.coords_of(me)))
        sizes = tuple(self.axis_sizes[self.axes.index(a)] for a in axes)
        grid = self.rank_grid()
        members = []
        for sub_coord in np.ndindex(*sizes):
            full = tuple(
                sub_coord[axes.index(a)] if a in axes else coords[a]
                for a in self.axes
            )
            members.append(int(grid[full]))
        return ProcGridComm(
            ranks=tuple(self.ranks[r] for r in members),
            context=self.context,
            axes=tuple(axes),
            axis_sizes=sizes,
        )


def grid_comm(axis_sizes, axes=None, base=None):
    """Build a :class:`ProcGridComm` over ``base`` (default: the world
    ProcComm) with the given axis sizes; ``axes`` defaults to
    ``("y", "x")`` for 2-D grids, ``("axis0", ...)`` otherwise."""
    if base is None:
        base = world_comm_if_initialized()
        if base is None:
            raise RuntimeError(
                "grid_comm: no multi-process world (launch with "
                "python -m mpi4jax_tpu.launch, or pass base=)"
            )
    axis_sizes = tuple(int(s) for s in axis_sizes)
    if axes is None:
        axes = (("y", "x") if len(axis_sizes) == 2
                else tuple(f"axis{i}" for i in range(len(axis_sizes))))
    return ProcGridComm(
        ranks=tuple(base.ranks), context=base.context,
        axes=tuple(axes), axis_sizes=axis_sizes,
    )


def world_comm_if_initialized():
    """Return the world ProcComm if the native runtime is up, else None.

    After an elastic resize (docs/failure-semantics.md "elastic
    membership") the world is the CURRENT membership, not the bootstrap
    rank range — departed ranks drop out of the communicator."""
    try:
        from mpi4jax_tpu.native import runtime
    except ImportError:
        return None
    if not runtime.is_initialized():
        return None
    alive = runtime.alive_ranks()
    if alive is None:
        alive = tuple(range(runtime.world_size()))
    return ProcComm(ranks=tuple(alive))
