"""Multi-process (MPMD) communicator over the native DCN bridge.

This is the TPU-native replacement tier for the reference's mpi4py/libmpi
process model (mpi4jax/_src/__init__.py:3, xla_bridge/mpi_xla_bridge.pyx):
one Python process per host, true per-process rank, and a C++ socket
backend carrying traffic over the hosts' data-center network.

Round-1 status: interface + world discovery; the native bridge lands with
:mod:`mpi4jax_tpu.native`.
"""

from dataclasses import dataclass

from mpi4jax_tpu.parallel.comm import Comm

__all__ = ["ProcComm", "world_comm_if_initialized"]


@dataclass(frozen=True)
class ProcComm(Comm):
    """Communicator over a group of OS processes (MPMD, static ranks)."""

    ranks: tuple  # world ranks of the members, sorted
    context: int = 0

    backend = "proc"

    @property
    def size(self):
        return len(self.ranks)

    def rank(self):
        from mpi4jax_tpu.native import runtime

        return self.ranks.index(runtime.world_rank())

    def clone(self):
        from mpi4jax_tpu.parallel.comm import _context_counter

        return ProcComm(ranks=self.ranks, context=next(_context_counter))


def world_comm_if_initialized():
    """Return the world ProcComm if the native runtime is up, else None."""
    try:
        from mpi4jax_tpu.native import runtime
    except ImportError:
        return None
    if not runtime.is_initialized():
        return None
    return ProcComm(ranks=tuple(range(runtime.world_size())))
