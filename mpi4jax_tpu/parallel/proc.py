"""Multi-process (MPMD) communicator over the native DCN bridge.

This is the TPU-native replacement tier for the reference's mpi4py/libmpi
process model (mpi4jax/_src/__init__.py:3, xla_bridge/mpi_xla_bridge.pyx):
one Python process per host, true per-process rank, and a C++ socket
backend carrying traffic over the hosts' data-center network.

Round-1 status: interface + world discovery; the native bridge lands with
:mod:`mpi4jax_tpu.native`.
"""

from dataclasses import dataclass

from mpi4jax_tpu.parallel.comm import Comm

__all__ = ["ProcComm", "world_comm_if_initialized"]


@dataclass(frozen=True)
class ProcComm(Comm):
    """Communicator over a group of OS processes (MPMD, static ranks)."""

    ranks: tuple  # world ranks of the members, sorted
    context: int = 0

    backend = "proc"

    @property
    def size(self):
        return len(self.ranks)

    def rank(self):
        from mpi4jax_tpu.native import runtime

        return self.ranks.index(runtime.world_rank())

    def clone(self):
        from mpi4jax_tpu.parallel.comm import _context_counter

        return ProcComm(ranks=self.ranks, context=next(_context_counter))

    def split(self, color, key=None):
        """MPI_Comm_split analog (static form, like MeshComm.split):
        ``color``/``key`` are functions of the comm rank (or explicit
        sequences), evaluated identically on every process.  Unlike the
        SPMD mesh backend, ragged (unequal-size) groups are allowed —
        each process simply joins its own subgroup's communicator.
        Returns None (MPI_COMM_NULL) for ranks whose color is None.
        """
        from mpi4jax_tpu.parallel.comm import _context_counter

        n = self.size
        colors = [color(r) for r in range(n)] if callable(color) else list(color)
        if len(colors) != n:
            raise ValueError(
                f"color must cover all {n} ranks, got {len(colors)}"
            )
        keys = (
            [key(r) for r in range(n)]
            if callable(key)
            else (list(key) if key is not None else [0] * n)
        )
        me = self.rank()
        if colors[me] is None:
            return None
        members = sorted(
            (r for r in range(n) if colors[r] == colors[me]),
            key=lambda r: (keys[r], r),
        )
        # same (deterministic) context on every member: derive from the
        # clone counter only on the lowest member... not possible without
        # communication, so fold the group into the wire context instead
        # (runtime._stable_ctx hashes ranks + context; keep parent ctx).
        return ProcComm(
            ranks=tuple(self.ranks[r] for r in members),
            context=self.context,
        )


def world_comm_if_initialized():
    """Return the world ProcComm if the native runtime is up, else None."""
    try:
        from mpi4jax_tpu.native import runtime
    except ImportError:
        return None
    if not runtime.is_initialized():
        return None
    return ProcComm(ranks=tuple(range(runtime.world_size())))
