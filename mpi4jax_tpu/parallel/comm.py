"""Communicator abstractions mapping MPI-style (rank, size) onto JAX meshes.

The reference's communicator plumbing wraps live mpi4py objects and bakes
their C handles into compiled executables
(mpi4jax/_src/comm.py:4-11, mpi4jax/_src/utils.py:23-39).  Here a
communicator is instead a *hashable description* of a group of devices, so
it can ride along as a static primitive parameter and key compilation
caches:

* :class:`MeshComm` — a subgroup of a ``jax.sharding.Mesh`` identified by
  mesh-axis names.  This is the TPU-native SPMD backend: ops called inside
  ``jax.shard_map`` with these axes in scope lower to XLA ICI collectives
  and never leave HBM.  ``rank()`` is a *traced* value
  (``lax.axis_index``), matching SPMD semantics.
* :class:`SelfComm` — the single-process world (size 1); ops become local
  identities, mirroring the reference's behaviour under ``pytest`` with one
  MPI process.
* ``ProcComm`` (multi-process MPMD over the native DCN bridge) lives in
  :mod:`mpi4jax_tpu.parallel.proc` and registers itself here.

``clone()`` returns a communicator with a fresh ``context`` id — the
analog of the reference's ``COMM_WORLD.Clone()`` default-communicator
firewall (mpi4jax/_src/comm.py:4-11, docs/sharp-bits.rst:80-143).
"""

import itertools
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field, replace
from math import prod

import numpy as np

__all__ = [
    "Comm",
    "MeshComm",
    "SelfComm",
    "get_default_comm",
    "set_default_comm",
    "default_comm",
]

_context_counter = itertools.count(1)


class Comm:
    """Abstract communicator. Subclasses must be hashable value objects."""

    backend = None  # "mesh" | "self" | "proc"

    @property
    def size(self):
        raise NotImplementedError

    def rank(self):
        """This process/device's rank in the communicator.

        May be a traced value (mesh backend) or a Python int (self / proc).
        """
        raise NotImplementedError

    def clone(self):
        """New communicator over the same group with a fresh context id."""
        raise NotImplementedError


@dataclass(frozen=True)
class SelfComm(Comm):
    """The trivial single-member communicator (MPI_COMM_SELF analog)."""

    context: int = 0

    backend = "self"

    @property
    def size(self):
        return 1

    def rank(self):
        return 0

    def clone(self):
        return SelfComm(context=next(_context_counter))


@dataclass(frozen=True)
class MeshComm(Comm):
    """A communicator over one or more named axes of a device mesh.

    Ranks are the row-major ravel of the member axes' indices, i.e.
    ``rank = axis_index(axes)`` — the first axis in ``axes`` varies
    slowest.  All collective ops called with a MeshComm must run inside a
    ``jax.shard_map`` whose mesh has these axes.
    """

    axes: tuple
    axis_sizes: tuple
    context: int = 0
    # Convenience only (not part of identity): lets model code build
    # shard_maps from the comm.  Excluded from eq/hash.
    mesh: object = field(default=None, compare=False, repr=False)

    backend = "mesh"

    def __post_init__(self):
        if isinstance(self.axes, str):
            object.__setattr__(self, "axes", (self.axes,))
        object.__setattr__(self, "axes", tuple(self.axes))
        object.__setattr__(self, "axis_sizes", tuple(int(s) for s in self.axis_sizes))
        if len(self.axes) != len(self.axis_sizes):
            raise ValueError("axes and axis_sizes must have equal length")

    @classmethod
    def from_mesh(cls, mesh, axes=None):
        """Build a MeshComm spanning ``axes`` (default: all) of ``mesh``."""
        if axes is None:
            axes = tuple(mesh.axis_names)
        elif isinstance(axes, str):
            axes = (axes,)
        sizes = tuple(mesh.shape[a] for a in axes)
        return cls(axes=tuple(axes), axis_sizes=sizes, mesh=mesh)

    @property
    def size(self):
        return prod(self.axis_sizes)

    def rank(self):
        from jax import lax

        return lax.axis_index(self.axes)

    def clone(self):
        return replace(self, context=next(_context_counter))

    def sub(self, *axes):
        """Sub-communicator over a subset of axes (MPI_Cart_sub analog).

        E.g. on a ``("y", "x")`` comm, ``comm.sub("x")`` is the row
        communicator: collectives over it run independently per y-index.
        """
        for a in axes:
            if a not in self.axes:
                raise ValueError(f"axis {a!r} not in {self.axes}")
        sizes = tuple(self.axis_sizes[self.axes.index(a)] for a in axes)
        # Keep the context id: a sub-communicator of a clone must stay in
        # the clone's message namespace (the firewall the clone creates).
        return MeshComm(
            axes=tuple(axes),
            axis_sizes=sizes,
            context=self.context,
            mesh=self.mesh,
        )

    # -- topology helpers -------------------------------------------------

    def rank_grid(self):
        """ndarray of shape ``axis_sizes`` holding each coordinate's rank."""
        return np.arange(self.size).reshape(self.axis_sizes)

    def coords_of(self, rank):
        """Static inverse of the rank ravel: rank -> axis coordinates."""
        return tuple(np.unravel_index(rank, self.axis_sizes))

    def shift_perm(self, axis, disp, periodic=True):
        """(source, dest) pairs shifting data by ``disp`` along ``axis``.

        The returned permutation moves each rank's data to the rank whose
        coordinate along ``axis`` is ``disp`` greater (mod the axis size if
        ``periodic``).  Non-periodic shifts drop the wrapping pairs, so
        edge ranks receive nothing: recv/sendrecv then return their recv
        buffer (template) unchanged, matching MPI_PROC_NULL semantics.
        """
        ax = self.axes.index(axis)
        n = self.axis_sizes[ax]
        grid = self.rank_grid()
        pairs = []
        for src_coord in np.ndindex(*self.axis_sizes):
            dst_coord = list(src_coord)
            d = src_coord[ax] + disp
            if periodic:
                dst_coord[ax] = d % n
            elif 0 <= d < n:
                dst_coord[ax] = d
            else:
                continue
            pairs.append((int(grid[src_coord]), int(grid[tuple(dst_coord)])))
        return pairs


class _DefaultCommState(threading.local):
    def __init__(self):
        self.comm = None


_default = _DefaultCommState()
_WORLD_SELF = SelfComm()


def get_default_comm():
    """The ambient communicator used when ops get ``comm=None``.

    Defaults to the process world: :class:`SelfComm` in a single process,
    or the ProcComm world once the multi-process runtime is initialised
    (reference: lazy COMM_WORLD.Clone(), mpi4jax/_src/comm.py:4-11).
    """
    if _default.comm is not None:
        return _default.comm
    from mpi4jax_tpu.parallel import proc

    world = proc.world_comm_if_initialized()
    return world if world is not None else _WORLD_SELF


def set_default_comm(comm):
    _default.comm = comm


@contextmanager
def default_comm(comm):
    """Context manager scoping the default communicator."""
    prev = _default.comm
    _default.comm = comm
    try:
        yield comm
    finally:
        _default.comm = prev
