"""Communicator abstractions mapping MPI-style (rank, size) onto JAX meshes.

The reference's communicator plumbing wraps live mpi4py objects and bakes
their C handles into compiled executables
(mpi4jax/_src/comm.py:4-11, mpi4jax/_src/utils.py:23-39).  Here a
communicator is instead a *hashable description* of a group of devices, so
it can ride along as a static primitive parameter and key compilation
caches:

* :class:`MeshComm` — a subgroup of a ``jax.sharding.Mesh`` identified by
  mesh-axis names.  This is the TPU-native SPMD backend: ops called inside
  ``jax.shard_map`` with these axes in scope lower to XLA ICI collectives
  and never leave HBM.  ``rank()`` is a *traced* value
  (``lax.axis_index``), matching SPMD semantics.
* :class:`SelfComm` — the single-process world (size 1); ops become local
  identities, mirroring the reference's behaviour under ``pytest`` with one
  MPI process.
* ``ProcComm`` (multi-process MPMD over the native DCN bridge) lives in
  :mod:`mpi4jax_tpu.parallel.proc` and registers itself here.

``clone()`` returns a communicator with a fresh ``context`` id — the
analog of the reference's ``COMM_WORLD.Clone()`` default-communicator
firewall (mpi4jax/_src/comm.py:4-11, docs/sharp-bits.rst:80-143).
"""

import itertools
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field, replace
from math import prod

import numpy as np

__all__ = [
    "Comm",
    "MeshComm",
    "SelfComm",
    "get_default_comm",
    "set_default_comm",
    "default_comm",
]

_context_counter = itertools.count(1)


class Comm:
    """Abstract communicator. Subclasses must be hashable value objects."""

    backend = None  # "mesh" | "self" | "proc"

    @property
    def size(self):
        raise NotImplementedError

    def rank(self):
        """This process/device's rank in the communicator.

        May be a traced value (mesh backend) or a Python int (self / proc).
        """
        raise NotImplementedError

    def clone(self):
        """New communicator over the same group with a fresh context id."""
        raise NotImplementedError


@dataclass(frozen=True)
class SelfComm(Comm):
    """The trivial single-member communicator (MPI_COMM_SELF analog)."""

    context: int = 0

    backend = "self"

    @property
    def size(self):
        return 1

    def rank(self):
        return 0

    def clone(self):
        return SelfComm(context=next(_context_counter))

    def split(self, color, key=None):
        """MPI_Comm_split on a size-1 world: the only member keeps a
        size-1 communicator (None color -> MPI_COMM_NULL -> None)."""
        colors = [color(0)] if callable(color) else (
            [color] if isinstance(color, int) or color is None else list(color)
        )
        if len(colors) != 1:
            raise ValueError(
                f"color must cover all 1 ranks, got {len(colors)}"
            )
        if colors[0] is None:
            return None
        return self.clone()


@dataclass(frozen=True)
class MeshComm(Comm):
    """A communicator over one or more named axes of a device mesh.

    Ranks are the row-major ravel of the member axes' indices, i.e.
    ``rank = axis_index(axes)`` — the first axis in ``axes`` varies
    slowest.  All collective ops called with a MeshComm must run inside a
    ``jax.shard_map`` whose mesh has these axes.
    """

    axes: tuple
    axis_sizes: tuple
    context: int = 0
    # Result of split(): a partition of the global mesh ranks into
    # equal-size subgroups.  Collectives then run independently per
    # subgroup (lowering to XLA's axis_index_groups); this device's comm
    # rank is its position within its own group.  None = whole axes.
    groups: tuple = None
    # Convenience only (not part of identity): lets model code build
    # shard_maps from the comm.  Excluded from eq/hash.
    mesh: object = field(default=None, compare=False, repr=False)

    backend = "mesh"

    def __post_init__(self):
        if isinstance(self.axes, str):
            object.__setattr__(self, "axes", (self.axes,))
        object.__setattr__(self, "axes", tuple(self.axes))
        object.__setattr__(self, "axis_sizes", tuple(int(s) for s in self.axis_sizes))
        if len(self.axes) != len(self.axis_sizes):
            raise ValueError("axes and axis_sizes must have equal length")

    @classmethod
    def from_mesh(cls, mesh, axes=None):
        """Build a MeshComm spanning ``axes`` (default: all) of ``mesh``."""
        if axes is None:
            axes = tuple(mesh.axis_names)
        elif isinstance(axes, str):
            axes = (axes,)
        sizes = tuple(mesh.shape[a] for a in axes)
        return cls(axes=tuple(axes), axis_sizes=sizes, mesh=mesh)

    @property
    def size(self):
        if self.groups is not None:
            return len(self.groups[0])
        return prod(self.axis_sizes)

    @property
    def global_size(self):
        """Total devices across the member axes (== size unless split)."""
        return prod(self.axis_sizes)

    def rank(self):
        from jax import lax

        import jax.numpy as jnp

        gr = lax.axis_index(self.axes)
        if self.groups is None:
            return gr
        pos = np.empty(self.global_size, np.int32)
        for g in self.groups:
            for i, r in enumerate(g):
                pos[r] = i
        return jnp.asarray(pos)[gr]

    def group_id(self):
        """Traced id of this device's subgroup (its split color class)."""
        from jax import lax

        import jax.numpy as jnp

        gr = lax.axis_index(self.axes)
        if self.groups is None:
            return gr * 0
        gid = np.empty(self.global_size, np.int32)
        for j, g in enumerate(self.groups):
            for r in g:
                gid[r] = j
        return jnp.asarray(gid)[gr]

    def clone(self):
        return replace(self, context=next(_context_counter))

    def split(self, color, key=None):
        """Partition the communicator (MPI_Comm_split analog).

        Under SPMD the partition must be derivable identically on every
        device, so ``color`` and ``key`` are *static* functions of the
        communicator rank (or explicit length-``size`` sequences), not
        per-process runtime values as in MPI.  Members with equal color
        form a subgroup, ordered by (key, rank); subgroups must be
        equal-sized (one SPMD program has uniform shapes — MPI's ragged
        split is only available on the multi-process backend).  A color
        of None drops the rank from every subgroup (MPI_UNDEFINED);
        such devices still execute the collectives (SPMD) but in a
        group of their own.

        Splitting an already-split communicator partitions *within* each
        existing subgroup (as MPI_Comm_split on a subcomm can never
        escape it); every subgroup is partitioned by the same color
        function, since all devices run one SPMD program.
        """
        n = self.size
        colors = [color(r) for r in range(n)] if callable(color) else list(color)
        if len(colors) != n:
            raise ValueError(
                f"color must cover all {n} ranks, got {len(colors)}"
            )
        keys = (
            [key(r) for r in range(n)]
            if callable(key)
            else (list(key) if key is not None else [0] * n)
        )
        if len(keys) != n:
            raise ValueError(f"key must cover all {n} ranks, got {len(keys)}")
        by_color = {}
        dropped = []
        for r, c in enumerate(colors):
            if c is None:
                dropped.append(r)
            else:
                by_color.setdefault(c, []).append(r)
        local_groups = [
            tuple(sorted(members, key=lambda r: (keys[r], r)))
            for _, members in sorted(by_color.items())
        ]
        sizes = {len(g) for g in local_groups}
        if len(sizes) > 1:
            raise ValueError(
                f"SPMD split requires equal-size subgroups, got sizes "
                f"{sorted(len(g) for g in local_groups)}. Use the "
                f"multi-process backend for ragged splits."
            )
        # MPI_UNDEFINED ranks still execute the SPMD collectives, so they
        # are packed into equal-size groups of their own (communicating
        # only with each other).
        if dropped:
            gsize = len(local_groups[0]) if local_groups else len(dropped)
            if len(dropped) % gsize:
                raise ValueError(
                    f"{len(dropped)} ranks have color None but subgroups "
                    f"have size {gsize}; under SPMD every device runs the "
                    "collective, so dropped ranks must also pack into "
                    "equal-size groups"
                )
            for i in range(0, len(dropped), gsize):
                local_groups.append(tuple(dropped[i : i + gsize]))
        # comm-rank-space subgroups -> global mesh ranks, per parent group
        parents = (
            self.groups
            if self.groups is not None
            else (tuple(range(self.global_size)),)
        )
        groups = tuple(
            tuple(p[i] for i in lg) for p in parents for lg in local_groups
        )
        return replace(self, groups=groups)

    def expand_perm(self, pairs):
        """Map (source, dest) pairs in comm-rank space to global mesh
        ranks (identity when the comm is not split)."""
        if self.groups is None:
            return list(pairs)
        out = []
        for g in self.groups:
            for s, d in pairs:
                out.append((g[s], g[d]))
        return out

    def sub(self, *axes):
        """Sub-communicator over a subset of axes (MPI_Cart_sub analog).

        E.g. on a ``("y", "x")`` comm, ``comm.sub("x")`` is the row
        communicator: collectives over it run independently per y-index.
        """
        if self.groups is not None:
            raise ValueError(
                "cannot take an axis sub-communicator of a split "
                "communicator; split from the parent comm instead"
            )
        for a in axes:
            if a not in self.axes:
                raise ValueError(f"axis {a!r} not in {self.axes}")
        sizes = tuple(self.axis_sizes[self.axes.index(a)] for a in axes)
        # Keep the context id: a sub-communicator of a clone must stay in
        # the clone's message namespace (the firewall the clone creates).
        return MeshComm(
            axes=tuple(axes),
            axis_sizes=sizes,
            context=self.context,
            mesh=self.mesh,
        )

    # -- topology helpers -------------------------------------------------

    def rank_grid(self):
        """ndarray of shape ``axis_sizes`` holding each coordinate's rank."""
        return np.arange(self.size).reshape(self.axis_sizes)

    def coords_of(self, rank):
        """Static inverse of the rank ravel: rank -> axis coordinates."""
        return tuple(np.unravel_index(rank, self.axis_sizes))

    def shift_perm(self, axis, disp, periodic=True):
        """(source, dest) pairs shifting data by ``disp`` along ``axis``.

        The returned permutation moves each rank's data to the rank whose
        coordinate along ``axis`` is ``disp`` greater (mod the axis size if
        ``periodic``).  Non-periodic shifts drop the wrapping pairs, so
        edge ranks receive nothing: recv/sendrecv then return their recv
        buffer (template) unchanged, matching MPI_PROC_NULL semantics.
        """
        if self.groups is not None:
            raise ValueError(
                "a split communicator has no Cartesian topology; pass an "
                "explicit rank->partner callable or (source, dest) pairs"
            )
        ax = self.axes.index(axis)
        n = self.axis_sizes[ax]
        grid = self.rank_grid()
        pairs = []
        for src_coord in np.ndindex(*self.axis_sizes):
            dst_coord = list(src_coord)
            d = src_coord[ax] + disp
            if periodic:
                dst_coord[ax] = d % n
            elif 0 <= d < n:
                dst_coord[ax] = d
            else:
                continue
            pairs.append((int(grid[src_coord]), int(grid[tuple(dst_coord)])))
        return pairs


class _DefaultCommState(threading.local):
    def __init__(self):
        self.comm = None


_default = _DefaultCommState()
_WORLD_SELF = SelfComm()


def get_default_comm():
    """The ambient communicator used when ops get ``comm=None``.

    Defaults to the process world: :class:`SelfComm` in a single process,
    or the ProcComm world once the multi-process runtime is initialised
    (reference: lazy COMM_WORLD.Clone(), mpi4jax/_src/comm.py:4-11).
    """
    if _default.comm is not None:
        return _default.comm
    from mpi4jax_tpu.parallel import proc

    world = proc.world_comm_if_initialized()
    return world if world is not None else _WORLD_SELF


def set_default_comm(comm):
    _default.comm = comm


@contextmanager
def default_comm(comm):
    """Context manager scoping the default communicator."""
    prev = _default.comm
    _default.comm = comm
    try:
        yield comm
    finally:
        _default.comm = prev
