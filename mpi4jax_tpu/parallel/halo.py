"""Halo (ghost-cell) exchange for 2-D domain decomposition.

The reference builds halo exchange by hand from token-ordered
send/recv/sendrecv in a deadlock-free clockwise order
(examples/shallow_water.py:173-271) — four blocking MPI calls per field
per step.  TPU-native equivalent (SURVEY §2.4 "Spatial / domain
decomposition"): each direction is one ``sendrecv`` over a mesh-axis
sub-communicator, which lowers to a single ``lax.ppermute`` — a
nearest-neighbour ICI transfer, the physically native communication
pattern on a TPU torus.

Order: the x exchange moves full columns (including y-halo cells), then
the y exchange moves full rows (including the just-filled x halos), so
corner cells are correct after two rounds — same transitive-corner trick
as the reference's clockwise ordering.
"""

import numpy as np

import jax
import jax.numpy as jnp

from mpi4jax_tpu.ops._core import as_token, publishes_token
from mpi4jax_tpu.ops.p2p import sendrecv, sendrecv_multi

__all__ = ["halo_exchange_2d", "halo_exchange_2d_batch"]


def _axis_shift(arr_slice, template, comm, axis, disp, periodic, token):
    """One directional exchange along ``axis`` (disp = ±1).

    Returns ``(halo, token)``; ``halo is None`` signals a global no-op
    (non-periodic shift on a size-1 axis) — every device keeps its
    existing ghost values, so the caller can skip the ghost write
    entirely instead of re-writing identical values.
    """
    sub = comm.sub(axis)
    pairs = sub.shift_perm(axis, disp, periodic=periodic)
    if not pairs:
        return None, token
    return sendrecv(
        arr_slice,
        template,
        source=pairs,
        dest=pairs,
        comm=sub,
        token=token,
    )


@publishes_token
def halo_exchange_2d(arr, comm, *, periodic=(False, True), token=None, width=1):
    """Exchange ``width``-cell halos of a local block over a ("y", "x")
    MeshComm.

    ``arr`` is the device-local block of shape ``(ny_local + 2*width,
    nx_local + 2*width)`` (interior plus a ``width``-deep ghost ring).
    Returns ``(arr, token)`` with ghost cells holding the neighbours'
    adjacent interior cells.  ``periodic`` is (y, x); non-periodic edge
    devices keep their existing ghost values (apply wall conditions
    separately).

    Works for any decomposition including 1×1 (periodic wrap becomes a
    self-permute, so single-chip runs use the identical program).
    Ghost slabs are written with dynamic-update-slices.  (Measured on
    v5e: the alternatives — one minor-dim concatenate, or iota-masked
    jnp.where selects — are 10% slower than DUS even though DUS makes
    XLA flip some layouts; see docs/shallow-water.md.)
    """
    arrs, token = _exchange(
        [arr], comm, periodic=periodic, token=token, width=width,
        stack=False,
    )
    return arrs[0], token


@publishes_token
def halo_exchange_2d_batch(arrs, comm, *, periodic=(False, True), token=None,
                           width=1):
    """Exchange the halos of several same-shaped blocks at once.

    Same contract as :func:`halo_exchange_2d`, but the per-direction
    slabs of all arrays travel in a single stacked ``sendrecv`` — one
    ``ppermute`` per direction for the whole field group instead of one
    per field.  Fewer, larger ICI transfers win on real multi-chip
    meshes; on a single chip permutes are elided and the stacking copies
    cost, so the per-field function is preferred there.

    Returns ``(list_of_arrs, token)``.
    """
    return _exchange(
        list(arrs), comm, periodic=periodic, token=token, width=width,
        stack=True,
    )


def _exchange(arrs, comm, *, periodic, token, width, stack):
    """Shared four-direction exchange body (x then y so corners fill
    transitively).  ``stack=True`` sends all arrays' slabs in one
    permute per direction; ``stack=False`` sends them one by one."""
    token = as_token(token)
    per_y, per_x = periodic
    w = width

    def shift(slabs, templates, axis, disp, per):
        nonlocal token
        if comm.backend == "proc":
            # multi-process tier: the whole field group's slabs for this
            # direction go through one sendrecv_multi — below
            # T4J_COALESCE_BYTES they travel as ONE fused wire frame
            # instead of one frame per field (docs/performance.md
            # "small-message coalescing"); above it, per-part frames
            # (the exact pre-coalescing behaviour).  No stacking copy
            # either way.
            sub = comm.sub(axis)
            pairs = sub.shift_perm(axis, disp, periodic=per)
            if not pairs:
                return [None] * len(slabs)
            outs, token = sendrecv_multi(
                slabs, templates, source=pairs, dest=pairs, comm=sub,
                token=token,
            )
            return list(outs)
        if stack:
            halo, token = _axis_shift(
                jnp.stack(slabs), jnp.stack(templates), comm, axis, disp,
                per, token,
            )
            return [None] * len(slabs) if halo is None else list(halo)
        out = []
        for slab, template in zip(slabs, templates):
            halo, token = _axis_shift(
                slab, template, comm, axis, disp, per, token
            )
            out.append(halo)
        return out

    def write(arrs, halo, region):
        # halo[i] is None on a global no-op shift: ghosts already hold
        # the right values, skip the (identical) write
        return [
            a if halo[i] is None else a.at[region].set(halo[i])
            for i, a in enumerate(arrs)
        ]

    # --- x direction: full-height column slabs (corners ride along) ---
    halo = shift(
        [a[:, -2 * w : -w] for a in arrs], [a[:, :w] for a in arrs],
        "x", +1, per_x,
    )
    arrs = write(arrs, halo, np.s_[:, :w])
    halo = shift(
        [a[:, w : 2 * w] for a in arrs], [a[:, -w:] for a in arrs],
        "x", -1, per_x,
    )
    arrs = write(arrs, halo, np.s_[:, -w:])

    # --- y direction: full-width row slabs (x halos already current) ---
    halo = shift(
        [a[-2 * w : -w, :] for a in arrs], [a[:w, :] for a in arrs],
        "y", +1, per_y,
    )
    arrs = write(arrs, halo, np.s_[:w, :])
    halo = shift(
        [a[w : 2 * w, :] for a in arrs], [a[-w:, :] for a in arrs],
        "y", -1, per_y,
    )
    arrs = write(arrs, halo, np.s_[-w:, :])

    return arrs, token
