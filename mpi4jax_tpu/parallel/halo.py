"""Halo (ghost-cell) exchange for 2-D domain decomposition.

The reference builds halo exchange by hand from token-ordered
send/recv/sendrecv in a deadlock-free clockwise order
(examples/shallow_water.py:173-271) — four blocking MPI calls per field
per step.  TPU-native equivalent (SURVEY §2.4 "Spatial / domain
decomposition"): each direction is one ``sendrecv`` over a mesh-axis
sub-communicator, which lowers to a single ``lax.ppermute`` — a
nearest-neighbour ICI transfer, the physically native communication
pattern on a TPU torus.

Order: the x exchange moves full columns (including y-halo cells), then
the y exchange moves full rows (including the just-filled x halos), so
corner cells are correct after two rounds — same transitive-corner trick
as the reference's clockwise ordering.
"""

import jax
import jax.numpy as jnp

from mpi4jax_tpu.ops._core import as_token, publishes_token
from mpi4jax_tpu.ops.p2p import sendrecv

__all__ = ["halo_exchange_2d"]


def _axis_shift(arr_slice, template, comm, axis, disp, periodic, token):
    """One directional exchange along ``axis`` (disp = ±1)."""
    sub = comm.sub(axis)
    pairs = sub.shift_perm(axis, disp, periodic=periodic)
    if not pairs:
        return template, token
    return sendrecv(
        arr_slice,
        template,
        source=pairs,
        dest=pairs,
        comm=sub,
        token=token,
    )


@publishes_token
def halo_exchange_2d(arr, comm, *, periodic=(False, True), token=None):
    """Exchange 1-cell halos of a local block over a ("y", "x") MeshComm.

    ``arr`` is the device-local block of shape ``(ny_local + 2,
    nx_local + 2)`` (interior plus one ghost ring).  Returns ``(arr,
    token)`` with ghost cells holding the neighbours' adjacent interior
    cells.  ``periodic`` is (y, x); non-periodic edge devices keep their
    existing ghost values (apply wall conditions separately).

    Works for any decomposition including 1×1 (periodic wrap becomes a
    self-permute, so single-chip runs use the identical program).
    """
    token = as_token(token)
    per_y, per_x = periodic

    # --- x direction: full columns (corner cells ride along) ---
    # Ghost columns are written with single-column dynamic-update-slices.
    # (Measured on v5e: the alternatives — one minor-dim concatenate, or
    # iota-masked jnp.where selects — are 10% slower than DUS even
    # though DUS makes XLA flip some layouts; see docs/shallow-water.md.)
    west_halo, token = _axis_shift(
        arr[:, -2], arr[:, 0], comm, "x", +1, per_x, token
    )
    arr = arr.at[:, 0].set(west_halo)
    east_halo, token = _axis_shift(
        arr[:, 1], arr[:, -1], comm, "x", -1, per_x, token
    )
    arr = arr.at[:, -1].set(east_halo)

    # --- y direction: full rows (x halos already current) ---
    south_halo, token = _axis_shift(
        arr[-2, :], arr[0, :], comm, "y", +1, per_y, token
    )
    arr = arr.at[0, :].set(south_halo)
    north_halo, token = _axis_shift(
        arr[1, :], arr[-1, :], comm, "y", -1, per_y, token
    )
    arr = arr.at[-1, :].set(north_halo)

    return arr, token
