"""Halo (ghost-cell) exchange for 2-D domain decomposition.

The reference builds halo exchange by hand from token-ordered
send/recv/sendrecv in a deadlock-free clockwise order
(examples/shallow_water.py:173-271) — four blocking MPI calls per field
per step.  TPU-native equivalent (SURVEY §2.4 "Spatial / domain
decomposition"): each direction is one ``sendrecv`` over a mesh-axis
sub-communicator, which lowers to a single ``lax.ppermute`` — a
nearest-neighbour ICI transfer, the physically native communication
pattern on a TPU torus.

Order: the x exchange moves full columns (including y-halo cells), then
the y exchange moves full rows (including the just-filled x halos), so
corner cells are correct after two rounds — same transitive-corner trick
as the reference's clockwise ordering.
"""

import jax
import jax.numpy as jnp

from mpi4jax_tpu.ops._core import as_token, publishes_token
from mpi4jax_tpu.ops.p2p import sendrecv

__all__ = ["halo_exchange_2d"]


def _axis_shift(arr_slice, template, comm, axis, disp, periodic, token):
    """One directional exchange along ``axis`` (disp = ±1)."""
    sub = comm.sub(axis)
    pairs = sub.shift_perm(axis, disp, periodic=periodic)
    if not pairs:
        return template, token
    return sendrecv(
        arr_slice,
        template,
        source=pairs,
        dest=pairs,
        comm=sub,
        token=token,
    )


@publishes_token
def halo_exchange_2d(arr, comm, *, periodic=(False, True), token=None, width=1):
    """Exchange ``width``-cell halos of a local block over a ("y", "x")
    MeshComm.

    ``arr`` is the device-local block of shape ``(ny_local + 2*width,
    nx_local + 2*width)`` (interior plus a ``width``-deep ghost ring).
    Returns ``(arr, token)`` with ghost cells holding the neighbours'
    adjacent interior cells.  ``periodic`` is (y, x); non-periodic edge
    devices keep their existing ghost values (apply wall conditions
    separately).

    Works for any decomposition including 1×1 (periodic wrap becomes a
    self-permute, so single-chip runs use the identical program).
    Ghost slabs are written with dynamic-update-slices.  (Measured on
    v5e: the alternatives — one minor-dim concatenate, or iota-masked
    jnp.where selects — are 10% slower than DUS even though DUS makes
    XLA flip some layouts; see docs/shallow-water.md.)
    """
    token = as_token(token)
    per_y, per_x = periodic
    w = width

    # --- x direction: full-height column slabs (corners ride along) ---
    west_halo, token = _axis_shift(
        arr[:, -2 * w : -w], arr[:, :w], comm, "x", +1, per_x, token
    )
    arr = arr.at[:, :w].set(west_halo)
    east_halo, token = _axis_shift(
        arr[:, w : 2 * w], arr[:, -w:], comm, "x", -1, per_x, token
    )
    arr = arr.at[:, -w:].set(east_halo)

    # --- y direction: full-width row slabs (x halos already current) ---
    south_halo, token = _axis_shift(
        arr[-2 * w : -w, :], arr[:w, :], comm, "y", +1, per_y, token
    )
    arr = arr.at[:w, :].set(south_halo)
    north_halo, token = _axis_shift(
        arr[w : 2 * w, :], arr[-w:, :], comm, "y", -1, per_y, token
    )
    arr = arr.at[-w:, :].set(north_halo)

    return arr, token
