"""Expert parallelism built on the ``alltoall`` building block.

The reference names ``alltoall`` as its expert-dispatch primitive
(SURVEY §2.4 "Ulysses-style sequence parallel / EP dispatch building
block", alltoall.py:35-74 there).  This module composes it into the
standard MoE data path: tokens bucketed by destination expert, one
``alltoall`` to deliver each expert its work, expert computation local,
and the inverse ``alltoall`` + unsort to put results back in token
order.  Differentiable end to end (``alltoall`` transposes to itself
with the inverse layout).

Capacity model: fixed capacity per (source rank, expert) of
``tokens // n_experts`` — the capacity-factor-1.0 regime.  Callers pad
or drop to balanced assignments first (static shapes are what make the
dispatch one fused ICI collective instead of a host gather).
"""

import jax.numpy as jnp

from mpi4jax_tpu.ops._core import as_token
from mpi4jax_tpu.ops.collectives import alltoall

__all__ = ["expert_dispatch", "expert_combine"]


def expert_dispatch(x, expert_idx, comm, *, token=None):
    """Route tokens to experts (expert e = rank e of ``comm``).

    Must be called inside the comm's ``shard_map``.

    Args:
      x: ``(T, d)`` local tokens; ``T`` must be divisible by
        ``comm.size``.
      expert_idx: ``(T,)`` int — destination expert per token. Must be
        **balanced**: exactly ``T // n_experts`` tokens per expert
        (capacity factor 1.0).
      comm: single-axis communicator; one expert per rank.

    Returns:
      ``(expert_input, order, token)`` where ``expert_input`` is
      ``(n_ranks, capacity, d)`` — this rank's expert's tokens, one
      capacity block per source rank — and ``order`` is the local sort
      permutation needed by :func:`expert_combine`.
    """
    token = as_token(token)
    n = comm.size
    t_local, d = x.shape
    if t_local % n:
        raise ValueError(
            f"token count {t_local} not divisible by {n} experts"
        )
    cap = t_local // n
    # stable bucket-by-expert; balancedness makes the reshape exact
    order = jnp.argsort(expert_idx, stable=True)
    buckets = x[order].reshape(n, cap, d)
    expert_input, token = alltoall(buckets, comm=comm, token=token)
    return expert_input, order, token


def expert_combine(expert_output, order, comm, *, token=None):
    """Inverse of :func:`expert_dispatch`: return results to their
    source ranks and original token order.

    ``expert_output``: ``(n_ranks, capacity, d)`` — the local expert's
    results, still grouped by source rank.
    """
    token = as_token(token)
    n, cap, d = expert_output.shape
    back, token = alltoall(expert_output, comm=comm, token=token)
    flat = back.reshape(n * cap, d)
    # O(T) permutation inverse (a second argsort would re-sort)
    inv = jnp.zeros_like(order).at[order].set(jnp.arange(order.shape[0]))
    return flat[inv], token
